"""Native block-collect pass (collect.cc): flag parity with the pure-
Python collect on valid, tampered, and malformed envelopes."""

from __future__ import annotations

import pytest

from orgfix import make_org

from fabric_tpu import native, protoutil
from fabric_tpu.common import configtx_builder as ctx
from fabric_tpu.common.channelconfig import bundle_from_genesis
from fabric_tpu.ledger import LedgerProvider
from fabric_tpu.msp import msp_config_from_ca
from fabric_tpu.peer.endorser import Endorser
from fabric_tpu.peer.txvalidator import TxValidator
from fabric_tpu.protos.common import common_pb2
from fabric_tpu.protos.peer import proposal_pb2, transaction_pb2

V = transaction_pb2

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable"
)


def _cc(sim, args):
    sim.set_state("natcc", args[0].decode(), args[1])
    return 200, "", b""


@pytest.fixture(scope="module")
def world():
    org = make_org("Org1MSP")
    oorg = make_org("OrdererMSP")
    app = ctx.application_group(
        {"Org1": ctx.org_group("Org1MSP", msp_config_from_ca(org.ca, "Org1MSP"))}
    )
    ordg = ctx.orderer_group(
        {"O": ctx.org_group("OrdererMSP", msp_config_from_ca(oorg.ca, "OrdererMSP"))},
        consensus_type="solo",
    )
    genesis = ctx.genesis_block("natch", ctx.channel_group(app, ordg))
    ledger = LedgerProvider(None).create(genesis)
    bundle = bundle_from_genesis(genesis, org.csp)
    endorser = Endorser(
        "natch", ledger, bundle, org.signer("peer0", role_ou="peer"),
        {"natcc": _cc}, org.csp,
    )
    client = org.signer("user1", role_ou="client")
    return org, ledger, bundle, endorser, client


def _tx(endorser, client, key: bytes):
    prop, _ = protoutil.create_chaincode_proposal(
        client.serialize(), "natch", "natcc", [key, b"v"]
    )
    signed = proposal_pb2.SignedProposal(
        proposal_bytes=prop.SerializeToString(),
        signature=client.sign(prop.SerializeToString()),
    )
    resp = endorser.process_proposal(signed)
    return protoutil.create_signed_tx(prop, client, [resp])


def _mutations(make_env, resign):
    """(name, envelope-bytes) variants hitting distinct failure stages.
    Each mutation starts from a FRESH tx (unique txid), so the dup-txid
    stage never masks the stage under test."""
    env = make_env()
    out = [("valid", env.SerializeToString())]

    env = make_env()
    out.append(("empty_payload", common_pb2.Envelope(
        payload=b"", signature=env.signature).SerializeToString()))
    out.append(("garbage", b"\xff\x03garbage-not-an-envelope"))

    def rebuild(p):
        return common_pb2.Envelope(
            payload=p.SerializeToString(), signature=env.signature
        ).SerializeToString()

    env = make_env()
    p = common_pb2.Payload.FromString(env.payload)
    chdr = common_pb2.ChannelHeader.FromString(p.header.channel_header)
    chdr.channel_id = "otherch"
    p.header.channel_header = chdr.SerializeToString()
    out.append(("wrong_channel", rebuild(p)))

    env = make_env()
    p = common_pb2.Payload.FromString(env.payload)
    chdr = common_pb2.ChannelHeader.FromString(p.header.channel_header)
    chdr.epoch = 7
    p.header.channel_header = chdr.SerializeToString()
    out.append(("bad_epoch", rebuild(p)))

    env = make_env()
    p = common_pb2.Payload.FromString(env.payload)
    chdr = common_pb2.ChannelHeader.FromString(p.header.channel_header)
    chdr.tx_id = "f" * 64
    p.header.channel_header = chdr.SerializeToString()
    out.append(("txid_mismatch", rebuild(p)))

    env = make_env()
    p = common_pb2.Payload.FromString(env.payload)
    chdr = common_pb2.ChannelHeader.FromString(p.header.channel_header)
    chdr.type = common_pb2.MESSAGE
    p.header.channel_header = chdr.SerializeToString()
    out.append(("unknown_type", rebuild(p)))

    env = make_env()
    p = common_pb2.Payload.FromString(env.payload)
    shdr = common_pb2.SignatureHeader.FromString(p.header.signature_header)
    shdr.nonce = b""
    p.header.signature_header = shdr.SerializeToString()
    out.append(("no_nonce", rebuild(p)))

    env = make_env()
    p = common_pb2.Payload.FromString(env.payload)
    tx = transaction_pb2.Transaction.FromString(p.data)
    cap = transaction_pb2.ChaincodeActionPayload.FromString(tx.actions[0].payload)
    cap.chaincode_proposal_payload = b"\x0a\x03abc"  # breaks proposal hash
    tx.actions[0].payload = cap.SerializeToString()
    p.data = tx.SerializeToString()
    out.append(("proposal_hash_mismatch", rebuild(p)))

    env = make_env()
    p = common_pb2.Payload.FromString(env.payload)
    tx = transaction_pb2.Transaction.FromString(p.data)
    cap = transaction_pb2.ChaincodeActionPayload.FromString(tx.actions[0].payload)
    del cap.action.endorsements[:]
    tx.actions[0].payload = cap.SerializeToString()
    p.data = tx.SerializeToString()
    out.append(("no_endorsements", rebuild(p)))

    env = make_env()
    p = common_pb2.Payload.FromString(env.payload)
    p.data = transaction_pb2.Transaction().SerializeToString()
    out.append(("no_actions", rebuild(p)))

    # tampered endorsement signature: collects fine (creator signature
    # re-signed over the mutated payload), fails at policy finish
    env = make_env()
    p = common_pb2.Payload.FromString(env.payload)
    tx = transaction_pb2.Transaction.FromString(p.data)
    cap = transaction_pb2.ChaincodeActionPayload.FromString(tx.actions[0].payload)
    sig = bytearray(cap.action.endorsements[0].signature)
    sig[-1] ^= 1
    cap.action.endorsements[0].signature = bytes(sig)
    tx.actions[0].payload = cap.SerializeToString()
    p.data = tx.SerializeToString()
    pb = p.SerializeToString()
    out.append(("bad_endorsement_sig", common_pb2.Envelope(
        payload=pb, signature=resign(pb)).SerializeToString()))

    return out


def _block(envs_bytes) -> common_pb2.Block:
    blk = common_pb2.Block()
    blk.header.number = 5
    blk.data.data.extend(envs_bytes)
    while len(blk.metadata.metadata) < 3:
        blk.metadata.metadata.append(b"")
    return blk


def test_native_collect_flag_parity(world):
    org, ledger, bundle, endorser, client = world
    counter = [0]

    def make_env():
        counter[0] += 1
        return _tx(endorser, client, b"k%d" % counter[0])

    muts = _mutations(make_env, client.sign)
    names = [m[0] for m in muts]
    blk_bytes = [m[1] for m in muts]

    v_native = TxValidator("natch", ledger, bundle, org.csp)
    native_flags = v_native.validate(_block(blk_bytes))

    v_py = TxValidator("natch", ledger, bundle, org.csp)
    v_py._collect_native = lambda *a, **k: False  # force pure-Python path
    py_flags = v_py.validate(_block(blk_bytes))

    assert native_flags == py_flags, list(zip(names, native_flags, py_flags))
    by_name = dict(zip(names, native_flags))
    assert by_name["valid"] == V.VALID
    assert by_name["empty_payload"] == V.NIL_ENVELOPE
    assert by_name["wrong_channel"] == V.BAD_CHANNEL_HEADER
    assert by_name["bad_epoch"] == V.BAD_CHANNEL_HEADER
    assert by_name["txid_mismatch"] == V.BAD_PROPOSAL_TXID
    assert by_name["unknown_type"] == V.UNKNOWN_TX_TYPE
    assert by_name["no_nonce"] == V.BAD_COMMON_HEADER
    assert by_name["proposal_hash_mismatch"] == V.BAD_RESPONSE_PAYLOAD
    assert by_name["no_endorsements"] == V.ENDORSEMENT_POLICY_FAILURE
    assert by_name["no_actions"] == V.NIL_TXACTION
    assert by_name["bad_endorsement_sig"] == V.ENDORSEMENT_POLICY_FAILURE


def test_native_collect_duplicate_txid(world):
    org, ledger, bundle, endorser, client = world
    env = _tx(endorser, client, b"dup")
    raw = env.SerializeToString()
    v = TxValidator("natch", ledger, bundle, org.csp)
    flags = v.validate(_block([raw, raw]))
    assert flags == [V.VALID, V.DUPLICATE_TXID]


def test_native_collect_edge_parity(world):
    """Regression: multi-action envelopes, missing header extension, and
    endorser-less endorsements must flag identically on the native and
    pure-Python paths (validation flags are consensus-relevant)."""
    org, ledger, bundle, endorser, client = world

    def fresh(key):
        return _tx(endorser, client, key)

    variants = []

    # 1. two actions: action[0] valid, action[1] garbage — both paths
    # must validate actions[0] only (tx stays VALID)
    env = fresh(b"ma1")
    p = common_pb2.Payload.FromString(env.payload)
    tx = transaction_pb2.Transaction.FromString(p.data)
    tx.actions.append(transaction_pb2.TransactionAction(
        header=b"x", payload=b"\xff\xff\xff"))
    p.data = tx.SerializeToString()
    pb = p.SerializeToString()
    variants.append(("multi_action", common_pb2.Envelope(
        payload=pb, signature=client.sign(pb)).SerializeToString()))

    # 2. missing channel-header extension -> INVALID_CHAINCODE (python
    # parses empty bytes fine and finds an empty chaincode name).  The
    # proposal hash covers the channel header, so it is recomputed as an
    # extension-less client would have produced it; the INVALID_CHAINCODE
    # flag fires at collect, before any signature checking.
    env = fresh(b"ma2")
    p = common_pb2.Payload.FromString(env.payload)
    chdr = common_pb2.ChannelHeader.FromString(p.header.channel_header)
    chdr.ClearField("extension")
    p.header.channel_header = chdr.SerializeToString()
    tx = transaction_pb2.Transaction.FromString(p.data)
    cap = transaction_pb2.ChaincodeActionPayload.FromString(tx.actions[0].payload)
    from fabric_tpu.protos.peer import proposal_response_pb2
    prp = proposal_response_pb2.ProposalResponsePayload.FromString(
        cap.action.proposal_response_payload)
    prp.proposal_hash = protoutil.proposal_hash(
        p.header.channel_header, p.header.signature_header,
        cap.chaincode_proposal_payload)
    cap.action.proposal_response_payload = prp.SerializeToString()
    tx.actions[0].payload = cap.SerializeToString()
    p.data = tx.SerializeToString()
    pb = p.SerializeToString()
    variants.append(("no_extension", common_pb2.Envelope(
        payload=pb, signature=client.sign(pb)).SerializeToString()))

    # 3. endorsement without an endorser identity -> dummy lane ->
    # ENDORSEMENT_POLICY_FAILURE (not a parse error)
    env = fresh(b"ma3")
    p = common_pb2.Payload.FromString(env.payload)
    tx = transaction_pb2.Transaction.FromString(p.data)
    cap = transaction_pb2.ChaincodeActionPayload.FromString(tx.actions[0].payload)
    del cap.action.endorsements[:]
    cap.action.endorsements.add(signature=b"\x30\x06\x02\x01\x01\x02\x01\x01")
    tx.actions[0].payload = cap.SerializeToString()
    p.data = tx.SerializeToString()
    pb = p.SerializeToString()
    variants.append(("no_endorser", common_pb2.Envelope(
        payload=pb, signature=client.sign(pb)).SerializeToString()))

    names = [v[0] for v in variants]
    blk_bytes = [v[1] for v in variants]
    v_native = TxValidator("natch", ledger, bundle, org.csp)
    nat = v_native.validate(_block(blk_bytes))
    v_py = TxValidator("natch", ledger, bundle, org.csp)
    v_py._collect_native = lambda *a, **k: False
    py = v_py.validate(_block(blk_bytes))
    assert nat == py, list(zip(names, nat, py))
    by = dict(zip(names, nat))
    assert by["multi_action"] == V.VALID
    assert by["no_extension"] == V.INVALID_CHAINCODE
    assert by["no_endorser"] == V.ENDORSEMENT_POLICY_FAILURE
