"""Vectorized multi-precision modular arithmetic for TPU.

The device-side number format backing the batched crypto data plane
(SURVEY.md section 7 step 2).  TPUs have no native wide-integer unit, so
256-bit field elements are decomposed into 16-bit limbs stored in uint32
lanes; every operation below is elementwise/batched over a leading batch
dimension and contains no data-dependent control flow, so the whole pipeline
jits into a single XLA program on the VPU.

Design notes (the "hard part (2)" of SURVEY.md section 7):

* **Limbs.** A field element is ``(..., 17)`` uint32 with each limb < 2**16
  (canonical limbs), value = sum(limb[i] << 16*i).  The 17th limb gives lazy
  headroom: the arithmetic maintains the *invariant* value < 2**257 (top
  limb <= 1) rather than value < m, deferring canonical reduction to a
  single `canon` at the end of a computation chain.
* **Products.** 16-bit limb products fit uint32 exactly
  ((2**16-1)**2 < 2**32).  Column accumulation splits each product into
  lo/hi 16-bit halves so column sums stay < 2**22, then a carry-resolution
  pass (two coarse passes + a Kogge-Stone carry-lookahead, log2(width)
  steps, no serial ripple) restores canonical limbs.
* **Reduction.** Against a fold table R[i] = 2**(256+16i) mod m: the high
  limbs of a product are multiplied into the table and added to the low
  256 bits.  Two folds + a mini-fold bring any 34-limb product back under
  the invariant without a single conditional subtraction; `canon` does the
  final (rare) conditional subtracts.
* **Subtraction** uses a per-modulus relaxed multiple C = c*m whose limbwise
  representation dominates any invariant-bounded operand, so a - b is
  computed as a + (C - b) with no borrow handling.

The same machinery serves P-256 (mod p, mod n) and the BN254/FP256BN
pairing field for idemix batch verification.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

LIMB_BITS = 16
MASK = 0xFFFF
NLIMBS = 16  # canonical 256-bit width
WIDE = 17  # working width under the lazy invariant (value < 2**257)


# ---------------------------------------------------------------------------
# Host <-> limb conversions (numpy, run once per batch on host).
# ---------------------------------------------------------------------------


def int_to_limbs(x: int, width: int = WIDE) -> np.ndarray:
    """Python int -> canonical uint32 limb vector of `width` limbs."""
    if x < 0:
        raise ValueError("negative")
    out = np.zeros((width,), dtype=np.uint32)
    for i in range(width):
        out[i] = x & MASK
        x >>= LIMB_BITS
    if x:
        raise ValueError("does not fit in %d limbs" % width)
    return out


def ints_to_limbs(xs, width: int = WIDE) -> np.ndarray:
    """Batch of python ints -> (len(xs), width) uint32."""
    out = np.zeros((len(xs), width), dtype=np.uint32)
    for j, x in enumerate(xs):
        for i in range(width):
            out[j, i] = x & MASK
            x >>= LIMB_BITS
    return out


def limbs_to_int(a) -> int:
    a = np.asarray(a, dtype=np.uint64)
    x = 0
    for i in range(a.shape[-1] - 1, -1, -1):
        x = (x << LIMB_BITS) + int(a[..., i])  # `+` not `|`: tolerates relaxed limbs
    return x


def limbs_to_ints(a) -> list:
    a = np.asarray(a)
    if a.ndim == 1:
        return [limbs_to_int(a)]
    return [limbs_to_int(row) for row in a]


# ---------------------------------------------------------------------------
# Carry resolution.
# ---------------------------------------------------------------------------


def _shift_up(a, d: int):
    """result[..., i] = a[..., i-d], zero-filled; shifts toward high limbs."""
    if d == 0:
        return a
    pad = [(0, 0)] * (a.ndim - 1) + [(d, 0)]
    return jnp.pad(a[..., :-d] if d < a.shape[-1] else a[..., :0], pad)


def resolve(v, width: int):
    """Full carry resolution: limbs < 2**31 in, canonical 16-bit limbs out.

    Two coarse carry passes bound every limb by 2**16 (+1), then a
    Kogge-Stone carry-lookahead network (log2(width) vector steps — no
    serial ripple, TPU-friendly) resolves the remaining single-bit ripple
    chain exactly.  The caller guarantees value < 2**(16*width).
    """
    if v.shape[-1] < width:
        pad = [(0, 0)] * (v.ndim - 1) + [(0, width - v.shape[-1])]
        v = jnp.pad(v, pad)
    one = jnp.uint32(LIMB_BITS)
    m = jnp.uint32(MASK)
    # coarse pass 1: limbs < 2**31 -> carries < 2**15
    c = v >> one
    v = (v & m) + _shift_up(c, 1)
    # coarse pass 2: limbs < 2**17 -> carries <= 1
    c = v >> one
    v = (v & m) + _shift_up(c, 1)
    # exact ripple: limbs <= 2**16
    g = (v >> one).astype(jnp.uint32)  # generate, in {0, 1}
    lo = v & m
    p = (lo == m).astype(jnp.uint32)  # propagate
    d = 1
    while d < width:
        g = g | (p & _shift_up(g, d))
        p = p & _shift_up(p, d)
        d *= 2
    carry_in = _shift_up(g, 1)
    return (lo + carry_in) & m


# ---------------------------------------------------------------------------
# Full-width multiply (schoolbook, column accumulation).
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _column_matrix(na: int, nb: int) -> np.ndarray:
    """0/1 matrix summing limb products into result columns.

    Row layout matches the flattened (lo | hi) product halves; column k
    collects lo products with i+j == k and hi products with i+j+1 == k.
    Kept in float32 so the contraction runs on the MXU: every operand is an
    integer < 2**16 and every column sum < 2**22 < 2**24, so f32 arithmetic
    is exact.  This is the "limb products as matmul" MXU mapping from
    SURVEY.md §7 — one dot op instead of O(na) slice-adds, which also keeps
    the XLA graph small enough to compile fast.
    """
    s = np.zeros((2 * na * nb, na + nb), np.float32)
    for i in range(na):
        for j in range(nb):
            s[i * nb + j, i + j] = 1.0
            s[na * nb + i * nb + j, i + j + 1] = 1.0
    return s


def mul_wide(a, b):
    """(..., na) x (..., nb) canonical limbs -> (..., na+nb) canonical."""
    na = a.shape[-1]
    nb = b.shape[-1]
    p = a[..., :, None] * b[..., None, :]  # (..., na, nb); exact in uint32
    plo = (p & jnp.uint32(MASK)).astype(jnp.float32)
    phi = (p >> jnp.uint32(LIMB_BITS)).astype(jnp.float32)
    flat = jnp.concatenate(
        [plo.reshape(*a.shape[:-1], na * nb), phi.reshape(*a.shape[:-1], na * nb)],
        axis=-1,
    )
    cols = jnp.matmul(
        flat, _column_matrix(na, nb), precision=jax.lax.Precision.HIGHEST
    )
    return resolve(cols.astype(jnp.uint32), na + nb)


@functools.lru_cache(maxsize=None)
def _column_matrix_low(na: int, nb: int, width: int) -> np.ndarray:
    """Like _column_matrix but keeping only result columns < width —
    product limbs landing at or above `width` are simply dropped, which
    is exact truncation mod 2**(16*width) (no carry out of column
    width-1 can re-enter the kept range)."""
    s = np.zeros((2 * na * nb, width), np.float32)
    for i in range(na):
        for j in range(nb):
            if i + j < width:
                s[i * nb + j, i + j] = 1.0
            if i + j + 1 < width:
                s[na * nb + i * nb + j, i + j + 1] = 1.0
    return s


def mul_low(a, b, width: int):
    """(a * b) mod 2**(16*width) as canonical limbs — the truncated
    low-half multiply Montgomery reduction needs (u = T * m' mod R)."""
    na = a.shape[-1]
    nb = b.shape[-1]
    p = a[..., :, None] * b[..., None, :]
    plo = (p & jnp.uint32(MASK)).astype(jnp.float32)
    phi = (p >> jnp.uint32(LIMB_BITS)).astype(jnp.float32)
    flat = jnp.concatenate(
        [plo.reshape(*a.shape[:-1], na * nb), phi.reshape(*a.shape[:-1], na * nb)],
        axis=-1,
    )
    cols = jnp.matmul(
        flat, _column_matrix_low(na, nb, width),
        precision=jax.lax.Precision.HIGHEST,
    )
    # resolve's carry passes drop carries out of the top limb, which is
    # exactly the mod-2**(16*width) semantics wanted here
    return resolve(cols.astype(jnp.uint32), width)


# ---------------------------------------------------------------------------
# Modulus context.
# ---------------------------------------------------------------------------


class Mod:
    """Precomputed constants for arithmetic mod any 249..256-bit m.

    Reduction depth adapts to the modulus: fold tables converge in one
    pass for a 256-bit m (R[i] is tiny) and in a bound-computed chain of
    passes for smaller moduli like BN254's 254-bit p/r (see _settle);
    canon's conditional-subtract chain is likewise sized from m."""

    def __init__(self, m: int):
        # 249..256-bit moduli: P-256's p and n sit just under 2**256;
        # BN254's p and r are 254-bit.  The lazy invariant (value <
        # ~2**257) and the fold tables work for any modulus in this
        # range; canon uses a binary cond-sub chain sized to the ratio
        # 2**258 / m so smaller moduli still reduce fully.
        if not (1 << 248) < m < (1 << 256):
            raise ValueError("Mod expects a 249..256-bit modulus")
        self.m = m
        self.m_limbs = int_to_limbs(m, WIDE)
        # fold table: R[i] = 2**(256 + 16 i) mod m, canonical 16 limbs.
        self._fold_ints = [
            (1 << (256 + LIMB_BITS * i)) % m for i in range(18)
        ]
        self.fold = np.stack(
            [int_to_limbs(r, NLIMBS) for r in self._fold_ints]
        )
        # relaxed subtraction constant C = c*m with C in [2**259, 2**259+m):
        # limbwise r dominates any invariant-bounded operand (top limb <= 7).
        c = ((1 << 259) + m - 1) // m
        e = int_to_limbs(c * m, WIDE).astype(np.int64)
        r = e.copy()
        r[0] += 1 << LIMB_BITS
        r[1:16] += MASK
        r[16] -= 1
        assert (r >= 0).all() and r[16] >= 7
        self.sub_c = r.astype(np.uint32)
        assert limbs_to_int(self.sub_c) == c * m
        # the representation of 1 in this context's element form —
        # Montgomery subclasses override it with R mod m so shared
        # EC formulas can mint z=1 coordinates without knowing the form
        self._one = int_to_limbs(1, WIDE)

    def one_like(self, x):
        """Limb vector for the field element 1, broadcast to x's shape."""
        return jnp.broadcast_to(jnp.asarray(self._one), x.shape)

    # element-form <-> plain-int boundary, identity for the plain form
    # (MontMod overrides with ·R / ·R⁻¹) — lets callers convert at the
    # host edges without knowing which form the context uses
    def to_mont_int(self, x: int) -> int:
        return x % self.m

    def from_mont_int(self, v: int) -> int:
        return v % self.m

    # -- reduction ---------------------------------------------------------

    def _fold_once(self, v, nrows: int, out_width: int):
        """v (..., 16+nrows) -> (..., out_width): lo + sum hi[i] * R[i]."""
        lo = v[..., :NLIMBS]
        hi = v[..., NLIMBS : NLIMBS + nrows]
        table = jnp.asarray(self.fold[:nrows])  # (nrows, 16)
        p = hi[..., :, None] * table  # (..., nrows, 16)
        plo = p & jnp.uint32(MASK)
        phi = p >> jnp.uint32(LIMB_BITS)
        acc = jnp.zeros(v.shape[:-1] + (out_width,), dtype=jnp.uint32)
        acc = acc.at[..., :NLIMBS].add(lo)
        acc = acc.at[..., :NLIMBS].add(plo.sum(axis=-2))
        acc = acc.at[..., 1 : NLIMBS + 1].add(phi.sum(axis=-2))
        return resolve(acc, out_width)

    def _settle(self, v, bound: int):
        """Fold until the (trace-time, Python-int) value bound drops
        under the 2**257 invariant at width 17.  The number of passes is
        modulus-dependent: a 256-bit m converges in one fold (R[i] is
        tiny), while a 254-bit m like BN254's p has R[i] ~ m and sheds
        only a few bits per pass — the bound arithmetic below sizes the
        chain exactly, at trace time, so the jitted graph is static."""
        while bound >= (1 << 257) or v.shape[-1] != WIDE:
            nrows = v.shape[-1] - NLIMBS
            newb = 1 << 256
            for j in range(nrows):
                hj = min(MASK, bound >> (256 + LIMB_BITS * j))
                if hj:
                    newb += hj * self._fold_ints[j]
            out_w = WIDE if newb < (1 << 271) else NLIMBS + 2
            v = self._fold_once(v, nrows, out_w)
            bound = newb
        return v

    def reduce_product(self, v):
        """34-limb product -> invariant element (< 2**257, 17 limbs)."""
        return self._settle(v, ((1 << 257) - 1) ** 2)

    def _minifold(self, v):
        """17-limb value with small top limb -> invariant element."""
        return self._settle(v, (1 << 272) - 1)

    # -- field ops (all preserve the invariant) ---------------------------

    def add(self, a, b):
        return self._settle(resolve(a + b, WIDE), 1 << 258)

    def sub(self, a, b):
        c = jnp.asarray(self.sub_c)
        return self._settle(resolve(a + (c - b), WIDE), 1 << 261)

    def mul(self, a, b):
        return self.reduce_product(mul_wide(a, b))

    def sqr(self, a):
        return self.mul(a, a)

    def mul_const(self, a, k: int):
        """a * small-constant k (k <= 256: keeps the folded value's top limb
        within the lazy invariant without an extra fold pass)."""
        assert 0 < k <= 256
        p = a * jnp.uint32(k)
        # limbs < 2**32 exact; resolve to 18 then settle.
        v = resolve(p, WIDE + 1)
        return self._settle(v, k << 257)

    # -- canonicalization --------------------------------------------------

    def canon(self, a):
        """Invariant element -> canonical residue < m (17 limbs, top 0).

        Binary cond-sub chain [2**k m, ..., 2m, m]: the minifolded value
        is < 2**258 (the invariant plus one fold's slack for sub-256-bit
        moduli), and v < 2**(j+1) m before step j implies v < 2**j m
        after it, so the chain ends below m."""
        v = self._minifold(a)
        for mult in self._canon_chain():
            v = _cond_sub(v, jnp.asarray(mult))
        return v

    def _canon_chain(self):
        # per-instance memo (NOT lru_cache on the method: a 1-slot cache
        # keyed by self thrashes when several Mod instances alternate —
        # P-256 p/n, BN254 p/r — and pins the last instance alive).
        # numpy (NOT jnp): jax constants minted here could leak out of
        # whatever trace first invoked canon
        chain = getattr(self, "_canon_chain_memo", None)
        if chain is None:
            k = 0
            while (self.m << (k + 1)) < (1 << 258):
                k += 1
            chain = self._canon_chain_memo = tuple(
                int_to_limbs(self.m << j, WIDE) for j in range(k, -1, -1)
            )
        return chain

    def is_zero(self, a):
        return jnp.all(self.canon(a) == 0, axis=-1)

    def eq(self, a, b):
        return jnp.all(self.canon(a) == self.canon(b), axis=-1)


class MontMod(Mod):
    """Mod variant whose elements live in Montgomery form a·R mod m with
    R = 2**272 (one full 17-limb word), and whose mul/sqr use REDC
    instead of the fold-table chains.

    Why: for a 254-bit modulus like BN254's p the fold table entries
    R[i] = 2**(256+16i) mod m are nearly as large as m, so `_settle`
    sheds only a few bits per pass and a single `mul` costs ~6 fold
    passes.  Montgomery reduction replaces the whole chain with two
    fixed multiplies — u = T·m' mod R (low-half) and u·m (full) — and
    one carry resolve: t = (T + u·m)/R, exact division because
    T + u·m ≡ 0 (mod R).  Bounds: inputs < 2**257 (the shared lazy
    invariant) give T < 2**514 < m·R, so t < m + 2**242 < 2m — outputs
    are always tighter than the invariant they consume.

    add, sub, mul_const and the relaxed-subtraction constant are
    inherited: they are value-preserving mod m and therefore agnostic
    to the element form.  is_zero and canon are overridden below with
    cheaper REDC-based versions (eq inherits and picks up the new
    canon).  canon() of a Montgomery element yields the canonical
    *Montgomery* residue; use from_mont_int on the host to leave the
    form.

    Replaces the AMCL big-number arithmetic the reference's idemix
    stack runs per-signature on host Go (idemix/signature.go:290, via
    math/amcl FP256BN) with batched device math.
    """

    def __init__(self, m: int):
        super().__init__(m)
        r = 1 << (LIMB_BITS * WIDE)
        self.r = r
        self.r_inv = pow(r, -1, m)
        self.m_prime = (-pow(m, -1, r)) % r
        self.m_prime_limbs = int_to_limbs(self.m_prime, WIDE)
        self.one_int = r % m
        self._one = int_to_limbs(self.one_int, WIDE)
        self.r2_limbs = int_to_limbs(r * r % m, WIDE)

    # -- host conversions (python ints, used building tables/results) ----

    def to_mont_int(self, x: int) -> int:
        return (x % self.m) * self.r % self.m

    def from_mont_int(self, v: int) -> int:
        return v % self.m * self.r_inv % self.m

    # -- device form conversions ------------------------------------------

    def to_mont(self, a):
        """Plain element -> Montgomery form (a·R): one mont-mul by R²."""
        return self.mul(a, jnp.asarray(self.r2_limbs))

    def from_mont(self, a):
        """Montgomery form -> plain element: REDC(a·1) = a·R⁻¹."""
        return self._redc(a)

    # -- REDC --------------------------------------------------------------

    def _redc(self, t):
        """t (..., <=34 limbs canonical, value < m·R) -> (t·R⁻¹ mod m)
        as a (..., 17)-limb element < 2m."""
        lo = t[..., :WIDE] if t.shape[-1] > WIDE else t
        u = mul_low(lo, jnp.asarray(self.m_prime_limbs), WIDE)
        v = mul_wide(u, jnp.asarray(self.m_limbs))  # (..., 34)
        w = 2 * WIDE + 1

        def pad(x):
            return jnp.pad(
                x, [(0, 0)] * (x.ndim - 1) + [(0, w - x.shape[-1])]
            )

        s = resolve(pad(t) + pad(v), w)
        # low 17 limbs are exactly zero (T + u·m ≡ 0 mod R); the value
        # is < 2m < 2**255 so limbs 34+ are zero too — slice the word
        return s[..., WIDE:2 * WIDE]

    def mul(self, a, b):
        return self._redc(mul_wide(a, b))

    def sqr(self, a):
        return self.mul(a, a)

    # -- cheaper predicates/canonicalization via REDC ----------------------
    #
    # The inherited versions run canon()'s minifold + conditional-subtract
    # chain per call; here one REDC of the 17-limb value lands in [0, m]
    # (bound: (2**257 + R·m)/R < m + 1), so zero-testing is two limb
    # compares and canon is one mont-mul by the form's 1 plus one
    # conditional subtract.

    def is_zero(self, a):
        r = self._redc(a)
        m_l = jnp.asarray(self.m_limbs)
        return jnp.all(r == 0, axis=-1) | jnp.all(r == m_l, axis=-1)

    def canon(self, a):
        v = self.mul(a, jnp.asarray(self._one))  # value preserved, < 2m
        return _cond_sub(v, jnp.asarray(self.m_limbs))


def _cond_sub(a, b_const):
    """a - b if a >= b else a; a, b canonical limbs, same width."""
    width = a.shape[-1]
    notb = jnp.uint32(MASK) - b_const
    t = a + notb
    t = t.at[..., 0].add(1)
    t = resolve(t, width + 1)
    ge = t[..., width] > 0  # carry out => a >= b
    return jnp.where(ge[..., None], t[..., :width], a)


@functools.lru_cache(maxsize=None)
def mod_ctx(m: int) -> Mod:
    return Mod(m)


@functools.lru_cache(maxsize=None)
def mont_ctx(m: int) -> MontMod:
    return MontMod(m)


__all__ = [
    "LIMB_BITS",
    "MASK",
    "NLIMBS",
    "WIDE",
    "Mod",
    "MontMod",
    "mod_ctx",
    "mont_ctx",
    "mul_low",
    "mul_wide",
    "resolve",
    "int_to_limbs",
    "ints_to_limbs",
    "limbs_to_int",
    "limbs_to_ints",
]
