"""Network-world-order integration harness (reference integration/nwo +
integration/raft/cft_test.go): real peer/orderer OS processes on
localhost ports driven through the CLIs, with POSIX-signal fault
injection and restart-recovery assertions."""

import os
import signal
import socket
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# TLS material layout (cryptogen output, relative to the network root)
_ORD_TLS = ("crypto-config/ordererOrganizations/example.com/orderers/"
            "orderer.example.com/tls")
_ORD_TLSCA = ("crypto-config/ordererOrganizations/example.com/tlsca/"
              "tlsca.example.com-cert.pem")
_ORG1_TLSCA = ("crypto-config/peerOrganizations/org1.example.com/tlsca/"
               "tlsca.org1.example.com-cert.pem")
_PEER_TLS = ("crypto-config/peerOrganizations/org1.example.com/peers/"
             "peer0.org1.example.com/tls")
_ADMIN_TLS = ("crypto-config/peerOrganizations/org1.example.com/users/"
              "Admin@org1.example.com/tls")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_listening(port: int, timeout: float = 15.0) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port), timeout=0.5).close()
            return
        except OSError:
            time.sleep(0.1)
    raise TimeoutError(f"nothing listening on {port}")


class Network:
    """Launches cryptogen/configtxgen tooling in-process and the
    peer/orderer daemons as real OS processes (gexec+ifrit role)."""

    def __init__(self, root: str):
        self.root = root
        self.procs: dict[str, subprocess.Popen] = {}
        self.env = dict(os.environ)
        self.env["PYTHONPATH"] = REPO + os.pathsep + root
        self.env.pop("JAX_PLATFORMS", None)
        self.orderer_port = _free_port()
        self.peer_port = _free_port()
        self._generate()

    def _generate(self) -> None:
        from fabric_tpu.cmd import configtxgen, cryptogen

        with open(os.path.join(self.root, "crypto-config.yaml"), "w") as f:
            f.write(
                "OrdererOrgs:\n"
                "  - Name: Orderer\n    Domain: example.com\n"
                "    Specs: [{Hostname: orderer}]\n"
                "PeerOrgs:\n"
                "  - Name: Org1\n    Domain: org1.example.com\n"
                "    Template: {Count: 1}\n    Users: {Count: 1}\n"
            )
        with open(os.path.join(self.root, "configtx.yaml"), "w") as f:
            f.write(
                "Organizations:\n"
                "  - Name: OrdererOrg\n    ID: OrdererMSP\n"
                "    MSPDir: crypto-config/ordererOrganizations/example.com/msp\n"
                "  - Name: Org1\n    ID: Org1MSP\n"
                "    MSPDir: crypto-config/peerOrganizations/org1.example.com/msp\n"
                "Profiles:\n"
                "  OneOrg:\n"
                "    Orderer:\n"
                "      OrdererType: solo\n      BatchTimeout: 250ms\n"
                "      BatchSize: {MaxMessageCount: 10}\n"
                "      Organizations: [OrdererOrg]\n"
                "    Application:\n      Organizations: [Org1]\n"
            )
        with open(os.path.join(self.root, "kvcc.py"), "w") as f:
            f.write(
                "from fabric_tpu.chaincode.shim import Chaincode, success, error\n"
                "class KV(Chaincode):\n"
                "    def invoke(self, stub):\n"
                "        op, params = stub.get_function_and_parameters()\n"
                "        if op == 'put':\n"
                "            stub.put_state(params[0].decode(), params[1])\n"
                "            return success()\n"
                "        if op == 'get':\n"
                "            return success(stub.get_state(params[0].decode()) or b'')\n"
                "        return error('bad op')\n"
            )
        cwd = os.getcwd()
        os.chdir(self.root)
        try:
            cryptogen.main(
                ["generate", "--config", "crypto-config.yaml",
                 "--output", "crypto-config"]
            )
            configtxgen.main(
                ["-profile", "OneOrg", "-channelID", "nwoch",
                 "-outputBlock", "nwoch.block"]
            )
        finally:
            os.chdir(cwd)

    # -- daemon management -------------------------------------------------

    def _spawn(self, name: str, args: list[str]) -> None:
        self.procs[name] = subprocess.Popen(
            [sys.executable, "-m"] + args,
            cwd=self.root,
            env=self.env,
            stdout=open(os.path.join(self.root, f"{name}.log"), "ab"),
            stderr=subprocess.STDOUT,
        )

    def start_orderer(self) -> None:
        self._spawn("orderer", [
            "fabric_tpu.cmd.orderer",
            "--listen", f"127.0.0.1:{self.orderer_port}",
            "--root", "orderer-root",
            "--genesis", "nwoch.block",
            "--mspid", "OrdererMSP",
            "--msp-dir",
            "crypto-config/ordererOrganizations/example.com/orderers/"
            "orderer.example.com/msp",
            "--tls-dir", _ORD_TLS,
            "--tls-root", _ORG1_TLSCA,
        ])
        _wait_listening(self.orderer_port)

    def start_peer(self) -> None:
        self._spawn("peer", [
            "fabric_tpu.cmd.peer", "node", "start",
            "--listen", f"127.0.0.1:{self.peer_port}",
            "--root", "peer-root",
            "--mspid", "Org1MSP",
            "--msp-dir",
            "crypto-config/peerOrganizations/org1.example.com/peers/"
            "peer0.org1.example.com/msp",
            "--orderer", f"127.0.0.1:{self.orderer_port}",
            "--chaincode", "kvcc=kvcc:KV",
            "--tls-dir", _PEER_TLS,
            "--tls-root", _ORD_TLSCA,
        ])
        _wait_listening(self.peer_port)

    def kill(self, name: str, sig=signal.SIGKILL) -> None:
        self.procs[name].send_signal(sig)
        self.procs[name].wait(timeout=10)

    def stop_all(self) -> None:
        for p in self.procs.values():
            if p.poll() is None:
                p.terminate()
        for p in self.procs.values():
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()

    # -- CLI drivers -------------------------------------------------------

    def cli(self, args: list[str]) -> subprocess.CompletedProcess:
        return subprocess.run(
            [sys.executable, "-m"] + args,
            cwd=self.root,
            env=self.env,
            capture_output=True,
            timeout=60,
        )

    @property
    def admin_msp(self) -> str:
        return ("crypto-config/peerOrganizations/org1.example.com/users/"
                "Admin@org1.example.com/msp")

    @property
    def client_tls(self) -> list[str]:
        return ["--tls-dir", _ADMIN_TLS, "--tls-root", _ORD_TLSCA]

    def peer_cli(self, *args: str) -> subprocess.CompletedProcess:
        return self.cli(["fabric_tpu.cmd.peer", *args, *self.client_tls])

    def invoke(self, *cc_args: str) -> subprocess.CompletedProcess:
        argv = ["chaincode", "invoke", "-C", "nwoch", "-n", "kvcc"]
        for a in cc_args:
            argv += ["-a", a]
        argv += [
            "--peer", f"127.0.0.1:{self.peer_port}",
            "--orderer", f"127.0.0.1:{self.orderer_port}",
            "--mspid", "Org1MSP", "--msp-dir", self.admin_msp,
        ]
        return self.peer_cli(*argv)

    def query(self, *cc_args: str) -> bytes:
        argv = ["chaincode", "query", "-C", "nwoch", "-n", "kvcc"]
        for a in cc_args:
            argv += ["-a", a]
        argv += [
            "--peer", f"127.0.0.1:{self.peer_port}",
            "--mspid", "Org1MSP", "--msp-dir", self.admin_msp,
        ]
        out = self.peer_cli(*argv)
        assert out.returncode == 0, out.stderr
        return out.stdout.rstrip(b"\n")

    def height(self) -> int:
        out = self.peer_cli(
            "channel", "getinfo", "-c", "nwoch",
            "--peer", f"127.0.0.1:{self.peer_port}",
        )
        return int(out.stdout.split(b":")[1])

    def wait_height(self, want: int, timeout: float = 20.0) -> None:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self.height() >= want:
                return
            time.sleep(0.3)
        raise TimeoutError(f"height never reached {want}")


@pytest.fixture(scope="module")
def net(tmp_path_factory):
    n = Network(str(tmp_path_factory.mktemp("nwo")))
    n.start_orderer()
    n.start_peer()
    join = n.peer_cli(
        "channel", "join", "--block", "nwoch.block",
        "--peer", f"127.0.0.1:{n.peer_port}",
    )
    assert join.returncode == 0, join.stderr
    yield n
    n.stop_all()


def test_invoke_commit_query(net):
    out = net.invoke("put", "k1", "v1")
    assert out.returncode == 0, out.stderr
    net.wait_height(2)
    assert net.query("get", "k1") == b"v1"


def test_discover_peers_and_endorsers(net):
    import json

    out = net.cli([
        "fabric_tpu.cmd.discover", "peers", "--channel", "nwoch",
        "--peer", f"127.0.0.1:{net.peer_port}",
        "--mspid", "Org1MSP", "--msp-dir", net.admin_msp,
        *net.client_tls,
    ])
    assert out.returncode == 0, out.stderr
    peers = json.loads(out.stdout)
    assert len(peers) == 1 and "kvcc" in peers[0]["chaincodes"]

    out = net.cli([
        "fabric_tpu.cmd.discover", "endorsers", "--channel", "nwoch",
        "--chaincode", "kvcc",
        "--peer", f"127.0.0.1:{net.peer_port}",
        "--mspid", "Org1MSP", "--msp-dir", net.admin_msp,
        *net.client_tls,
    ])
    assert out.returncode == 0, out.stderr
    assert json.loads(out.stdout), "endorser selection empty"


def test_orderer_sigkill_and_recovery(net):
    """CFT: SIGKILL the orderer (integration/raft/cft_test.go:118 style),
    restart it, and verify the peer's deliver client reconnects and new
    transactions commit on top of the recovered chain."""
    base = net.height()
    net.kill("orderer", signal.SIGKILL)
    # endorsement still works while ordering is down; broadcast fails
    out = net.invoke("put", "k2", "v2")
    assert out.returncode != 0
    net.start_orderer()  # recovers chain from its block store
    out = net.invoke("put", "k2", "v2-after-restart")
    assert out.returncode == 0, out.stderr
    net.wait_height(base + 1)
    assert net.query("get", "k2") == b"v2-after-restart"


def test_peer_sigterm_restart_recovers_state(net):
    net.invoke("put", "k3", "v3")
    net.wait_height(net.height())
    deadline = time.time() + 15
    while net.query("get", "k3") != b"v3":
        assert time.time() < deadline
        time.sleep(0.3)
    net.kill("peer", signal.SIGTERM)
    net.start_peer()
    # NO re-join: the peer reopens its joined channels at startup
    # (ledgermgmt recovery), and committed state survives the restart
    deadline = time.time() + 15
    while time.time() < deadline:
        if net.query("get", "k3") == b"v3":
            return
        time.sleep(0.3)
    raise AssertionError("state not recovered after peer restart")


def test_wrong_ca_client_rejected_by_peer(net):
    """The network runs mutual TLS: a client presenting a cert from an
    unrelated CA must be refused by the peer's transport (the
    reference's ClientAuthRequired threat model)."""
    import sys as _sys

    _sys.path.insert(0, REPO)
    from fabric_tpu.comm.rpc import RPCClient, RPCError
    from fabric_tpu.comm.tls import credentials_from_ca
    from fabric_tpu.common.crypto import CA

    rogue_ca = CA("tlsca.rogue.example.com", "rogue")
    creds = credentials_from_ca(rogue_ca, "intruder")
    # trust the peer's real TLS CA so only CLIENT auth can fail
    with open(os.path.join(net.root, _ORG1_TLSCA), "rb") as f:
        creds.ca_pems.append(f.read())
    cli = RPCClient("127.0.0.1", net.peer_port, timeout=5, tls=creds)
    with pytest.raises((RPCError, OSError)):
        cli.call("admin.Channels")


def test_plaintext_client_rejected_by_peer(net):
    import sys as _sys

    _sys.path.insert(0, REPO)
    from fabric_tpu.comm.rpc import RPCClient, RPCError

    cli = RPCClient("127.0.0.1", net.peer_port, timeout=5)
    with pytest.raises((RPCError, OSError)):
        cli.call("admin.Channels")
