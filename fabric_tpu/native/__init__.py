"""Native (C++) host-side components, loaded via ctypes.

`marshal_batch` is the batch signature marshaller feeding the TPU verify
kernel (SURVEY.md §7 native-components policy).  The shared library is
compiled on first use with the system g++ and cached next to the source;
callers fall back to the pure-Python path when no compiler is available.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRCS = [os.path.join(_DIR, "marshal.cc"), os.path.join(_DIR, "collect.cc"),
         os.path.join(_DIR, "bn254.cc"), os.path.join(_DIR, "pairing.cc"),
         os.path.join(_DIR, "ecverify.cc")]
_LIB = os.path.join(_DIR, "libfabricmarshal.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        try:
            if not os.path.exists(_LIB) or any(
                os.path.getmtime(_LIB) < os.path.getmtime(src)
                for src in _SRCS
            ):
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-o", _LIB] + _SRCS,
                    check=True,
                    capture_output=True,
                )
            lib = ctypes.CDLL(_LIB)
            fn = lib.fabric_marshal_batch
            fn.restype = ctypes.c_int
            fn.argtypes = [
                ctypes.c_int,
                ctypes.c_char_p,  # xs
                ctypes.c_char_p,  # ys
                ctypes.c_char_p,  # digests
                ctypes.c_char_p,  # sigs
                np.ctypeslib.ndpointer(np.int32, flags="C"),
                np.ctypeslib.ndpointer(np.uint32, flags="C"),  # qx
                np.ctypeslib.ndpointer(np.uint32, flags="C"),  # qy
                np.ctypeslib.ndpointer(np.uint32, flags="C"),  # d1
                np.ctypeslib.ndpointer(np.uint32, flags="C"),  # d2
                np.ctypeslib.ndpointer(np.uint32, flags="C"),  # c0
                np.ctypeslib.ndpointer(np.uint8, flags="C"),   # c1ok
                np.ctypeslib.ndpointer(np.uint8, flags="C"),   # valid
            ]
            i64p = np.ctypeslib.ndpointer(np.int64, flags="C")
            i32p = np.ctypeslib.ndpointer(np.int32, flags="C")
            u8p = np.ctypeslib.ndpointer(np.uint8, flags="C")
            cb = lib.fabric_collect_block
            cb.restype = ctypes.c_int
            cb.argtypes = (
                [ctypes.c_int, ctypes.c_char_p, i64p, ctypes.c_char_p,
                 ctypes.c_int]
                + [i32p, i32p]                    # status, type
                + [i64p, i32p] * 2 + [u8p]        # creator, sig, payload_digest
                + [i64p, i32p] * 4                # txid, prp, rwset, ccid
                + [i32p, i32p, ctypes.c_int]      # endo_start/count, max
                + [i64p, i32p] * 2 + [u8p]        # endorser, esig, edigest
            )
            msm = lib.bn254_g1_msm
            msm.restype = ctypes.c_int
            msm.argtypes = [ctypes.c_int] + [ctypes.c_char_p] * 3 + [u8p, u8p]
            mm = lib.bn254_g1_mul_many
            mm.restype = ctypes.c_int
            mm.argtypes = [ctypes.c_int] + [ctypes.c_char_p] * 3 + [u8p] * 3
            pc = lib.bn254_pairing_check
            pc.restype = ctypes.c_int
            pc.argtypes = [ctypes.c_int] + [ctypes.c_char_p] * 6
            ev = lib.fabric_ecdsa_verify_host
            ev.restype = ctypes.c_int
            ev.argtypes = [
                ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p,
                ctypes.c_char_p, i32p, i32p, u8p,
            ]
            _lib = lib
        except Exception:
            _lib = None
        return _lib


def available() -> bool:
    return _load() is not None


def marshal_batch(xs: bytes, ys: bytes, digests: bytes, sigs: bytes,
                  sig_off: np.ndarray) -> dict | None:
    """One pass: DER parse + prechecks + batch inversion + packing.
    Inputs: concatenated 32-byte big-endian x/y/digest buffers and
    concatenated DER signatures with (n+1,) int32 offsets.  Returns the
    packed dict fabric_tpu.csp.tpu.pallas_ec.verify_packed consumes, or
    None when the native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    n = len(sig_off) - 1
    qx = np.empty((8, n), np.uint32)
    qy = np.empty((8, n), np.uint32)
    d1 = np.empty((8, n), np.uint32)
    d2 = np.empty((8, n), np.uint32)
    c0 = np.empty((8, n), np.uint32)
    c1ok = np.empty(n, np.uint8)
    valid = np.empty(n, np.uint8)
    lib.fabric_marshal_batch(
        n, xs, ys, digests, sigs, np.ascontiguousarray(sig_off, np.int32),
        qx, qy, d1, d2, c0, c1ok, valid,
    )
    return {
        "qx": qx,
        "qy": qy,
        "d1": d1,
        "d2": d2,
        "cand0": c0,
        # c1 (r+n words) is no longer shipped: the kernel rebuilds cand1
        # on-device from cand0; only the admissibility flag travels.
        "cand1_ok": c1ok.astype(bool),
        "valid": valid.astype(bool),
    }


def ecdsa_verify_host(items) -> list[bool] | None:
    """Batched host ECDSA-P256 verification through libcrypto
    (ecverify.cc): the TPU provider's chip-stall fallback — OpenSSL's
    nistz256 verify is a multiple of the python-wrapped rate, which
    directly bounds the p99 cost of a stalled flush.  Verdicts match
    csp/sw.py _verify_one (strict DER, low-S).  Returns None when the
    native library or libcrypto is unavailable."""
    lib = _load()
    if lib is None:
        return None
    n = len(items)
    if n == 0:
        return []
    qxy = bytearray(64 * n)
    digs = bytearray(32 * n)
    sig_off = np.empty(n, np.int32)
    sig_len = np.empty(n, np.int32)
    sigs = bytearray()
    for i, it in enumerate(items):
        key = it.key
        pub = key.public_key() if hasattr(key, "public_key") else key
        try:
            # the key caches its 32-byte big-endian coordinates exactly
            # for hot-path marshalling — no per-lane int conversion
            qxy[64 * i:64 * i + 32] = pub.x_bytes
            qxy[64 * i + 32:64 * i + 64] = pub.y_bytes
        except (AttributeError, ValueError):
            pass  # zeroed key never validates a real signature
        d = it.digest
        if len(d) == 32:
            digs[32 * i:32 * i + 32] = d
        sig_off[i] = len(sigs)
        sig_len[i] = len(it.signature)
        sigs += it.signature
    out = np.zeros(n, np.uint8)
    rc = lib.fabric_ecdsa_verify_host(
        n, bytes(qxy), bytes(digs), bytes(sigs), sig_off, sig_len, out
    )
    if rc != 0:
        return None  # libcrypto unavailable at runtime
    # a non-32-byte digest is invalid by definition (sw.py returns
    # False); the zeroed placeholder row would also fail, but make it
    # explicit rather than rely on digest(0) never verifying
    mask = out.astype(bool)
    for i, it in enumerate(items):
        if len(it.digest) != 32:
            mask[i] = False
    return mask.tolist()


def collect_block(env_bytes: bytes, env_off: np.ndarray,
                  channel_id: bytes) -> dict | None:
    """Native block-collect pass: walk every envelope's wire format,
    run the syntactic checks, and emit per-tx offsets + SHA-256 digests
    (see collect.cc).  Returns None when the library is unavailable.

    Output dict of numpy arrays; offsets index into env_bytes.  status
    uses collect.cc's codes: 0 endorser-tx ok, 1 config-tx ok, negative
    = error/fallback (mapped to TxValidationCode by the caller)."""
    lib = _load()
    if lib is None:
        return None
    n = len(env_off) - 1
    out = {
        "status": np.empty(n, np.int32),
        "type": np.empty(n, np.int32),
        "creator_off": np.zeros(n, np.int64),
        "creator_len": np.zeros(n, np.int32),
        "sig_off": np.zeros(n, np.int64),
        "sig_len": np.zeros(n, np.int32),
        "payload_digest": np.zeros(32 * n, np.uint8),
        "txid_off": np.zeros(n, np.int64),
        "txid_len": np.zeros(n, np.int32),
        "prp_off": np.zeros(n, np.int64),
        "prp_len": np.zeros(n, np.int32),
        "rwset_off": np.zeros(n, np.int64),
        "rwset_len": np.zeros(n, np.int32),
        "ccid_off": np.zeros(n, np.int64),
        "ccid_len": np.zeros(n, np.int32),
        "endo_start": np.zeros(n, np.int32),
        "endo_count": np.zeros(n, np.int32),
    }
    max_endos = max(64, 8 * n)  # >= 8 endorsements/tx before a retry
    while True:
        endos = {
            "e_endorser_off": np.zeros(max_endos, np.int64),
            "e_endorser_len": np.zeros(max_endos, np.int32),
            "e_sig_off": np.zeros(max_endos, np.int64),
            "e_sig_len": np.zeros(max_endos, np.int32),
            "e_digest": np.zeros(32 * max_endos, np.uint8),
        }
        rc = lib.fabric_collect_block(
            n, env_bytes, np.ascontiguousarray(env_off, np.int64),
            channel_id, len(channel_id),
            out["status"], out["type"],
            out["creator_off"], out["creator_len"],
            out["sig_off"], out["sig_len"], out["payload_digest"],
            out["txid_off"], out["txid_len"],
            out["prp_off"], out["prp_len"],
            out["rwset_off"], out["rwset_len"],
            out["ccid_off"], out["ccid_len"],
            out["endo_start"], out["endo_count"], max_endos,
            endos["e_endorser_off"], endos["e_endorser_len"],
            endos["e_sig_off"], endos["e_sig_len"], endos["e_digest"],
        )
        if rc >= 0:
            out.update(endos)
            out["n_endos"] = rc
            return out
        max_endos *= 4  # undersized endorsement arrays: retry larger


def bn254_msm(points, scalars) -> tuple[int, int] | None:
    """sum_i scalars[i] * points[i] over BN254 G1 (affine int coords;
    None encodes a point at infinity, on input and output).  Raises
    RuntimeError when the native library is unavailable — gate on
    available() (idemix.bn254._native does)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    n = len(points)
    xs = bytearray(32 * n)
    ys = bytearray(32 * n)
    ss = bytearray(32 * n)
    for i, (pt, k) in enumerate(zip(points, scalars)):
        if pt is None:
            continue  # (0,0) = infinity
        xs[32 * i:32 * i + 32] = pt[0].to_bytes(32, "big")
        ys[32 * i:32 * i + 32] = pt[1].to_bytes(32, "big")
        ss[32 * i:32 * i + 32] = (k % _BN254_R).to_bytes(32, "big")
    ox = np.zeros(32, np.uint8)
    oy = np.zeros(32, np.uint8)
    rc = lib.bn254_g1_msm(n, bytes(xs), bytes(ys), bytes(ss), ox, oy)
    if rc:
        return None
    return (
        int.from_bytes(ox.tobytes(), "big"),
        int.from_bytes(oy.tobytes(), "big"),
    )


def bn254_mul_many(points, scalars) -> list[tuple[int, int] | None]:
    """Independent scalars[i] * points[i]; one shared field inversion.
    Raises RuntimeError when the native library is unavailable."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    n = len(points)
    xs = bytearray(32 * n)
    ys = bytearray(32 * n)
    ss = bytearray(32 * n)
    for i, (pt, k) in enumerate(zip(points, scalars)):
        if pt is None:
            continue
        xs[32 * i:32 * i + 32] = pt[0].to_bytes(32, "big")
        ys[32 * i:32 * i + 32] = pt[1].to_bytes(32, "big")
        ss[32 * i:32 * i + 32] = (k % _BN254_R).to_bytes(32, "big")
    ox = np.zeros(32 * n, np.uint8)
    oy = np.zeros(32 * n, np.uint8)
    inf = np.zeros(n, np.uint8)
    lib.bn254_g1_mul_many(n, bytes(xs), bytes(ys), bytes(ss), ox, oy, inf)
    out: list = []
    b_ox, b_oy = ox.tobytes(), oy.tobytes()
    for i in range(n):
        if inf[i]:
            out.append(None)
        else:
            out.append((
                int.from_bytes(b_ox[32 * i:32 * i + 32], "big"),
                int.from_bytes(b_oy[32 * i:32 * i + 32], "big"),
            ))
    return out


_BN254_R = 0x30644e72e131a029b85045b68181585d2833e84879b9709143e1f593f0000001


def bn254_pairing_check(pairs) -> bool:
    """prod e(P_i, Q_i) == 1?  pairs: [(g1_point|None, g2_point|None)]
    with g1 = (x, y) ints and g2 = ((xa, xb), (ya, yb)) Fp2 ints.
    Raises RuntimeError when the native library is unavailable."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    n = len(pairs)
    bufs = [bytearray(32 * n) for _ in range(6)]
    for i, (pg1, qg2) in enumerate(pairs):
        if pg1 is None or qg2 is None:
            continue  # identity factor
        o = 32 * i
        bufs[0][o:o + 32] = pg1[0].to_bytes(32, "big")
        bufs[1][o:o + 32] = pg1[1].to_bytes(32, "big")
        bufs[2][o:o + 32] = qg2[0][0].to_bytes(32, "big")
        bufs[3][o:o + 32] = qg2[0][1].to_bytes(32, "big")
        bufs[4][o:o + 32] = qg2[1][0].to_bytes(32, "big")
        bufs[5][o:o + 32] = qg2[1][1].to_bytes(32, "big")
    return bool(lib.bn254_pairing_check(n, *(bytes(b) for b in bufs)))


__all__ = [
    "available", "marshal_batch", "collect_block", "bn254_msm",
    "bn254_mul_many", "bn254_pairing_check", "ecdsa_verify_host",
]
