"""Gossip core: per-channel block dissemination via push + pull.

Capability parity with the reference's gossip/gossip package
(gossip_impl.go Node; channel/channel.go per-channel message store and
state-info; pull/pullstore.go + algo/pull.go hello/digest/request/
response anti-entropy engine; batcher.go push emitter).  Deterministic
tick-driven core like discovery: `tick()` runs one push round and one
pull round; tests drive it synchronously.

Push: newly added blocks are forwarded to `fanout` random channel peers.
Pull: each round, pick a random peer, send hello; peer answers with the
digests (block seq nums) it holds; we request what we miss; peer responds
with the blocks.  StateInfo messages advertise ledger height so peers
know who is ahead (used by state transfer).
"""

from __future__ import annotations

import random
import threading

from fabric_tpu.devtools.lockwatch import named_lock
from fabric_tpu.protos.gossip import message_pb2 as gpb


class MessageStore:
    """Bounded per-channel store of data messages keyed by seq num
    (reference gossip/gossip/msgstore/msgs.go: messages expire by TTL
    with an expiration callback; a count bound caps burst memory).

    TTL is measured in gossip TICKS (the deterministic clock every other
    gossip subsystem uses): `expire(now)` drops messages added more than
    `ttl_ticks` ago and invokes `on_expire(seq, block_bytes)` for each —
    mirroring the reference's expiredCallback, which the pull mediator
    uses to stop serving a digest while anti-entropy/state transfer
    still serves the block from the ledger.  ttl_ticks=0 disables TTL
    (count bound only)."""

    def __init__(self, capacity: int = 200, ttl_ticks: int = 0,
                 on_expire=None):
        self._cap = capacity
        self._ttl = ttl_ticks
        self._on_expire = on_expire
        self._by_seq: dict[int, bytes] = {}
        self._added: dict[int, int] = {}  # seq -> tick stamp
        self._now = 0
        self._lock = named_lock("gossip.blockcache")

    def add(self, seq: int, block_bytes: bytes) -> bool:
        with self._lock:
            if seq in self._by_seq:
                return False
            self._by_seq[seq] = block_bytes
            self._added[seq] = self._now
            while len(self._by_seq) > self._cap:
                oldest = min(self._by_seq)
                del self._by_seq[oldest]
                self._added.pop(oldest, None)
            return True

    def expire(self, now: int) -> None:
        """Advance the store clock and drop messages older than the TTL,
        reporting each through on_expire OUTSIDE the lock."""
        expired: list[tuple[int, bytes]] = []
        with self._lock:
            self._now = now
            if self._ttl:
                for seq in [
                    s for s, t in self._added.items()
                    if t <= now - self._ttl
                ]:
                    blk = self._by_seq.pop(seq, None)
                    del self._added[seq]
                    if blk is not None:
                        expired.append((seq, blk))
        if self._on_expire is not None:
            for seq, blk in expired:
                self._on_expire(seq, blk)

    def digests(self) -> list[int]:
        with self._lock:
            return sorted(self._by_seq)

    def get(self, seq: int) -> bytes | None:
        with self._lock:
            return self._by_seq.get(seq)


class ChannelGossip:
    def __init__(
        self,
        channel_id: str,
        comm,
        membership,  # callable -> list of alive peer endpoints in channel
        fanout: int = 3,
        store_capacity: int = 200,
        store_ttl_ticks: int = 0,
        on_block=None,
        on_expire=None,
        rng: random.Random | None = None,
    ):
        self.channel_id = channel_id
        self._chan_bytes = channel_id.encode()
        self._comm = comm
        self._membership = membership
        self._fanout = fanout
        self.store = MessageStore(
            store_capacity, ttl_ticks=store_ttl_ticks, on_expire=on_expire
        )
        self._on_block = on_block or (lambda seq, blk: None)
        self._rng = rng or random.Random()
        self._nonce = 0
        self._pending_pulls: dict[int, str] = {}
        # per-digest in-flight filter: digest -> tick stamp.  Concurrent
        # pulls (several hellos per round, reference algo/pull.go) must
        # not re-request a block another in-flight request already
        # covers; entries expire after a couple of ticks so a dropped
        # response never wedges a digest.
        self._inflight: dict[int, int] = {}
        self._tick_no = 0
        self._heights: dict[bytes, int] = {}  # peer pki -> advertised height
        self._height_eps: dict[bytes, str] = {}
        self._lock = named_lock("gossip.channel")
        self.ledger_height = lambda: 0  # wired by the state layer
        comm.subscribe(self._handle)

    # -- outbound ----------------------------------------------------------

    def _targets(self, k: int | None = None) -> list[str]:
        peers = list(self._membership())
        self._rng.shuffle(peers)
        return peers[: (k or self._fanout)]

    def add_block(self, seq: int, block_bytes: bytes, push: bool = True) -> None:
        """Called by the delivery pipeline when a block arrives (from the
        orderer or from a peer). Stores, hands to state layer, pushes."""
        with self._lock:
            self._inflight.pop(seq, None)  # pull satisfied
        if not self.store.add(seq, block_bytes):
            return
        self._on_block(seq, block_bytes)
        if push:
            msg = self._data_msg(seq, block_bytes)
            for ep in self._targets():
                self._comm.send(ep, msg)

    def _data_msg(self, seq: int, block_bytes: bytes) -> gpb.GossipMessage:
        m = gpb.GossipMessage(
            channel=self._chan_bytes, tag=gpb.GossipMessage.CHAN_AND_ORG
        )
        m.data_msg.seq_num = seq
        m.data_msg.block = block_bytes
        return m

    def advertise_state(self) -> None:
        m = gpb.GossipMessage(channel=self._chan_bytes, tag=gpb.GossipMessage.CHAN_ONLY)
        m.state_info.ledger_height = self.ledger_height()
        m.state_info.pki_id = self._comm.pki_id
        for ep in self._targets(len(self._membership())):
            self._comm.send(ep, m)

    def tick(self) -> None:
        """One pull round + state advertisement.  Pulls run CONCURRENTLY
        against several random peers (reference algo/pull.go engages
        defPullPeerNum=3 per round); the per-digest in-flight filter in
        _handle keeps the responses disjoint."""
        with self._lock:
            self._tick_no += 1
            tick_no = self._tick_no
            # expire stale in-flight digests (response lost / peer died)
            dead = [
                d for d, t in self._inflight.items()
                if t < self._tick_no - 2
            ]
            for d in dead:
                del self._inflight[d]
        # TTL sweep: expired blocks leave the pull digests; state
        # transfer still serves them from the ledger
        self.store.expire(tick_no)
        for target in self._targets(min(3, self._fanout)):
            self._nonce += 1
            hello = gpb.GossipMessage(channel=self._chan_bytes)
            hello.hello.nonce = self._nonce
            hello.hello.msg_type = gpb.PULL_BLOCK_MSG
            with self._lock:
                self._pending_pulls[self._nonce] = target
                # bound pending table
                while len(self._pending_pulls) > 32:
                    del self._pending_pulls[min(self._pending_pulls)]
            self._comm.send(target, hello)
        self.advertise_state()

    # -- peers ahead of us (state transfer support) ------------------------

    def best_peer_height(self) -> tuple[str | None, int]:
        with self._lock:
            if not self._heights:
                return None, 0
            pki = max(self._heights, key=lambda k: self._heights[k])
            return self._height_eps.get(pki), self._heights[pki]

    # -- inbound -----------------------------------------------------------

    def _handle(self, rm) -> None:
        msg = rm.msg
        if bytes(msg.channel) != self._chan_bytes:
            return
        kind = msg.WhichOneof("content")
        if kind == "data_msg":
            self.add_block(msg.data_msg.seq_num, bytes(msg.data_msg.block))
        elif kind == "hello":
            resp = gpb.GossipMessage(channel=self._chan_bytes)
            resp.data_dig.nonce = msg.hello.nonce
            resp.data_dig.msg_type = gpb.PULL_BLOCK_MSG
            for seq in self.store.digests():
                resp.data_dig.digests.append(str(seq).encode())
            ep = self._endpoint_for(rm.sender_pki)
            if ep:
                self._comm.send(ep, resp)
        elif kind == "data_dig":
            with self._lock:
                target = self._pending_pulls.pop(msg.data_dig.nonce, None)
            if target is None:
                return
            have = set(self.store.digests())
            with self._lock:
                # per-digest filter: skip blocks another concurrent
                # pull already requested this round
                want = []
                for d in msg.data_dig.digests:
                    seq = int(d)
                    if seq in have or seq in self._inflight:
                        continue
                    self._inflight[seq] = self._tick_no
                    want.append(d)
            if not want:
                return
            req = gpb.GossipMessage(channel=self._chan_bytes)
            req.data_req.nonce = msg.data_dig.nonce
            req.data_req.msg_type = gpb.PULL_BLOCK_MSG
            req.data_req.digests.extend(want)
            self._comm.send(target, req)
        elif kind == "data_req":
            resp = gpb.GossipMessage(channel=self._chan_bytes)
            resp.data_update.nonce = msg.data_req.nonce
            resp.data_update.msg_type = gpb.PULL_BLOCK_MSG
            for d in msg.data_req.digests:
                blk = self.store.get(int(d))
                if blk is not None:
                    inner = self._data_msg(int(d), blk)
                    resp.data_update.data.append(self._comm.wrap(inner))
            ep = self._endpoint_for(rm.sender_pki)
            if ep:
                self._comm.send(ep, resp)
        elif kind == "data_update":
            for signed in msg.data_update.data:
                inner = gpb.GossipMessage.FromString(signed.payload)
                if inner.WhichOneof("content") == "data_msg":
                    self.add_block(
                        inner.data_msg.seq_num, bytes(inner.data_msg.block),
                        push=False,
                    )
        elif kind == "state_info":
            with self._lock:
                self._heights[bytes(msg.state_info.pki_id)] = (
                    msg.state_info.ledger_height
                )
                ep = self._endpoint_for(bytes(msg.state_info.pki_id))
                if ep:
                    self._height_eps[bytes(msg.state_info.pki_id)] = ep

    # endpoint lookup is injected by the node wiring (discovery knows it)
    endpoint_lookup = None

    def _endpoint_for(self, pki_id: bytes) -> str | None:
        if self.endpoint_lookup is not None:
            return self.endpoint_lookup(pki_id)
        return None


__all__ = ["ChannelGossip", "MessageStore"]
