"""Configuration loading: YAML + environment overrides + decode hooks.

The reference reads core.yaml / orderer.yaml through viper with an
enhanced unmarshal (common/viperutil/config_util.go:34-240): nested env
overrides (`CORE_PEER_LISTENADDRESS`), byte-size strings ("100 MB"),
duration strings ("5s"), and `file:` indirection for PEM blobs; config
files resolve via FABRIC_CFG_PATH (core/config/config.go).  This module
is the TPU build's equivalent, used by the peer and orderer CLIs.

Resolution order (viper semantics): explicit flag > environment
variable > config file value > default.
"""

from __future__ import annotations

import os
import re
from typing import Any

_CFG_ENV = "FABRIC_CFG_PATH"

_SIZE_RE = re.compile(r"^\s*(\d+(?:\.\d+)?)\s*([kmg]?)b?\s*$", re.I)
_DUR_RE = re.compile(r"^\s*(\d+(?:\.\d+)?)\s*(ns|us|ms|s|m|h)\s*$", re.I)
_DUR_SCALE = {"ns": 1e-9, "us": 1e-6, "ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0}
_SIZE_SCALE = {"": 1, "k": 1 << 10, "m": 1 << 20, "g": 1 << 30}


def cfg_path() -> str:
    """Directory config files resolve against (FABRIC_CFG_PATH, else cwd)."""
    return os.environ.get(_CFG_ENV, ".")


def parse_bytesize(v) -> int:
    """'100 MB' / '16k' / 1024 -> bytes (viperutil byte-size hook)."""
    if isinstance(v, (int, float)):
        return int(v)
    m = _SIZE_RE.match(str(v))
    if not m:
        raise ValueError(f"not a byte size: {v!r}")
    return int(float(m.group(1)) * _SIZE_SCALE[m.group(2).lower()])


def parse_duration(v) -> float:
    """'250ms' / '5s' / '2m' / 1.5 -> seconds (time.Duration strings)."""
    if isinstance(v, (int, float)):
        return float(v)
    m = _DUR_RE.match(str(v))
    if not m:
        raise ValueError(f"not a duration: {v!r}")
    return float(m.group(1)) * _DUR_SCALE[m.group(2).lower()]


def resolve_file_ref(v, base_dir: str | None = None):
    """`file:relative/or/abs.pem` -> file contents (viperutil file: hook)."""
    if isinstance(v, str) and v.startswith("file:"):
        path = v[5:]
        if not os.path.isabs(path):
            path = os.path.join(base_dir or cfg_path(), path)
        with open(path, "rb") as f:
            return f.read()
    return v


def load_yaml(name: str, path: str | None = None) -> dict:
    """Load `<FABRIC_CFG_PATH>/<name>.yaml` (missing file -> {})."""
    import yaml

    p = path or os.path.join(cfg_path(), name + ".yaml")
    if not os.path.exists(p):
        return {}
    with open(p) as f:
        return yaml.safe_load(f) or {}


def _env_overrides(prefix: str) -> dict[tuple[str, ...], str]:
    """CORE_PEER_LISTENADDRESS=... -> {("peer","listenaddress"): ...}."""
    out = {}
    pre = prefix.upper() + "_"
    for k, v in os.environ.items():
        if k.startswith(pre):
            out[tuple(k[len(pre):].lower().split("_"))] = v
    return out


class Config:
    """Nested config with case-insensitive dotted lookup and env
    overrides, mirroring viper's `GetString("peer.listenAddress")` +
    `CORE_PEER_LISTENADDRESS` behavior."""

    def __init__(self, data: dict | None = None, env_prefix: str = "CORE"):
        self._data = data or {}
        self._env = _env_overrides(env_prefix)

    @classmethod
    def load(cls, name: str, env_prefix: str, path: str | None = None) -> "Config":
        return cls(load_yaml(name, path), env_prefix)

    def get(self, dotted: str, default: Any = None) -> Any:
        keys = tuple(k.lower() for k in dotted.split("."))
        if keys in self._env:
            return self._env[keys]
        node: Any = self._data
        for k in keys:
            if not isinstance(node, dict):
                return default
            hit = None
            for kk, vv in node.items():
                if str(kk).lower() == k:
                    hit = vv
                    break
            else:
                return default
            node = hit
        return node

    def get_bool(self, dotted: str, default: bool = False) -> bool:
        v = self.get(dotted, default)
        if isinstance(v, str):
            return v.strip().lower() in ("1", "true", "yes", "on")
        return bool(v)

    def get_int(self, dotted: str, default: int = 0) -> int:
        v = self.get(dotted, default)
        return int(v)

    def get_duration(self, dotted: str, default: float = 0.0) -> float:
        v = self.get(dotted, None)
        return default if v is None else parse_duration(v)

    def get_bytesize(self, dotted: str, default: int = 0) -> int:
        v = self.get(dotted, None)
        return default if v is None else parse_bytesize(v)

    def get_file(self, dotted: str, default: bytes | None = None) -> bytes | None:
        v = self.get(dotted, None)
        if v is None:
            return default
        return resolve_file_ref(v)


__all__ = [
    "Config",
    "cfg_path",
    "load_yaml",
    "parse_bytesize",
    "parse_duration",
    "resolve_file_ref",
]
