"""Weighted semaphore (reference common/semaphore/semaphore.go:19 — a
channel-based counting semaphore used for validator concurrency and gRPC
limiters; here it caps RPC handler and chaincode-execution concurrency)."""

from __future__ import annotations

import threading


class Semaphore:
    """Counting semaphore with try-acquire and context-manager use."""

    def __init__(self, permits: int):
        if permits <= 0:
            raise ValueError("permits must be positive")
        self._sem = threading.Semaphore(permits)
        self.permits = permits

    def acquire(self, timeout: float | None = None) -> bool:
        return self._sem.acquire(timeout=timeout)

    def try_acquire(self) -> bool:
        return self._sem.acquire(blocking=False)

    def release(self) -> None:
        self._sem.release()

    def __enter__(self):
        self._sem.acquire()
        return self

    def __exit__(self, *exc):
        self._sem.release()
        return False


__all__ = ["Semaphore"]
