"""Deliver client: pull blocks from the ordering service into the peer.

Capability parity with the reference's deliver service
(core/deliverservice/deliveryclient.go:108 + internal/pkg/peer/
blocksprovider/blocksprovider.go:113 DeliverBlocks): a loop that connects
to an orderer endpoint (shuffled, with exponential backoff on failure),
sends a signed SeekInfo from the peer's current height, verifies each
received block's orderer signature against the channel's block-validation
policy, and hands it to the provided sink (gossip state provider on the
leader peer).  `endpoints` are callables yielding deliver iterators so the
same client drives in-process orderers (tests) and socket transports.
"""

from __future__ import annotations

import collections
import random
import threading

from fabric_tpu.common import tracing
from fabric_tpu.devtools import clockskew, faultline, netsplit
from fabric_tpu.devtools.lockwatch import spawn_thread

from fabric_tpu.orderer.blockwriter import verify_block_signature
from fabric_tpu.protos.common import common_pb2


class DeliverClient:
    def __init__(
        self,
        channel_id: str,
        endpoints,   # list of callables: start_num -> iterator of Block
        height_fn,   # () -> int, current committed height
        sink,        # callable(seq, block_bytes) — e.g. StateProvider.add_payload
        bundle=None,  # channel config for block signature verification
        csp=None,
        max_backoff_s: float = 10.0,
        metrics=None,  # common.metrics.DeliverMetrics | None
        endpoint_addrs=None,  # optional "host:port"/node-id labels
        # parallel to `endpoints`, routing each rotation attempt
        # through the netsplit seam before the opaque connect callable
    ):
        self.channel_id = channel_id
        self._metrics = metrics
        self._endpoints = list(endpoints)
        self._endpoint_addrs = (
            list(endpoint_addrs) if endpoint_addrs is not None else None
        )
        self._height = height_fn
        self._sink = sink
        self._bundle = bundle
        self._csp = csp
        self._max_backoff = max_backoff_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        # recent backoff values actually waited, in order — the rotation
        # loop's observable contract (tests assert the 0.1s reset after
        # a delivered block and the max_backoff_s cap against this);
        # bounded so a long-lived client against a flaky orderer never
        # grows it without limit
        self.backoff_log: collections.deque = collections.deque(maxlen=64)
        # endpoint indices (into the caller's `endpoints` list) this
        # client actually attempted, in order — the failover contract's
        # observable: a SIGKILLed orderer must show up as a rotation to
        # a DIFFERENT index, not a wedge on the dead one
        self.endpoint_log: collections.deque = collections.deque(maxlen=64)
        # blocks delivered through the sink since start() — the
        # liveness probe the failover tests poll
        self.delivered = 0

    def set_metrics(self, metrics) -> None:
        """Bind a common.metrics.DeliverMetrics bundle (blocks pulled,
        reconnect episodes, cumulative backoff) for /metrics."""
        self._metrics = metrics

    def start(self) -> None:
        """Idempotent while running; safe to call while a PREVIOUS
        stop() is still draining.  Leadership can flap (relinquish then
        regain within seconds, netharness churn): the old runner may
        still be blocked in a stream read when start() is called again,
        and the old re-used stop flag turned that into a permanent
        wedge — start() saw a live thread and returned, the live thread
        saw the stop flag and exited, and nobody ever pulled again.
        Each start() therefore gets its OWN stop event/generation; a
        draining runner exits on its own event whenever it unblocks."""
        with self._lock:
            if (
                self._thread is not None
                and self._thread.is_alive()
                and not self._stop.is_set()
            ):
                return  # current generation is live
            self._stop = stop = threading.Event()
            self._thread = spawn_thread(
                target=self._run, args=(stop,),
                name="deliver-client", kind="service",
            )
            self._thread.start()

    def stop(self) -> None:
        with self._lock:
            self._stop.set()
            t = self._thread
        if t is not None:
            t.join(timeout=3)

    def _verify(self, blk: common_pb2.Block) -> bool:
        if self._bundle is None:
            return True
        policy = self._bundle.policy_manager.get_policy(
            "/Channel/Orderer/BlockValidation"
        )
        if policy is None:
            return True
        return verify_block_signature(blk, policy, self._csp)

    def _run(self, stop: threading.Event) -> None:
        backoff = 0.1
        # shuffle the ROTATION ORDER, not the endpoint objects, so the
        # endpoint_log indices stay meaningful to the caller
        order = list(range(len(self._endpoints)))
        random.shuffle(order)
        idx = 0
        while not stop.is_set():
            pos = order[idx % len(order)]
            connect = self._endpoints[pos]
            idx += 1
            self.endpoint_log.append(pos)
            try:
                faultline.point("deliver.connect", endpoint=pos)
                if self._endpoint_addrs is not None:
                    # denied endpoints rotate immediately (NetsplitDenied
                    # is an OSError caught by the reconnect handler
                    # below) — no stream setup, no connect stall
                    netsplit.connect(addr=self._endpoint_addrs[pos])
                for blk in connect(self._height()):
                    if stop.is_set():
                        return
                    faultline.point("deliver.read", block=blk.header.number)
                    # one span per delivered block: verify + sink hand-
                    # off (gossip add_payload / direct commit) — the
                    # deliver leg of the block's journey into the ledger
                    with tracing.span(
                        "deliver.block", block=blk.header.number,
                        channel=self.channel_id,
                    ):
                        if not self._verify(blk):
                            break  # bad orderer: switch endpoints
                        self._sink(
                            blk.header.number, blk.SerializeToString()
                        )
                        self.delivered += 1
                        if self._metrics is not None:
                            self._metrics.blocks.With(
                                "channel", self.channel_id
                            ).add()
                    backoff = 0.1
            except Exception:
                # fabriclint: allow[exception-discipline] reconnect loop: ANY
                # endpoint failure routes to backoff + the next endpoint
                # (the faultline seam is transparent to the rule; use
                # action=delay rules here to count reconnects)
                faultline.point("deliver.reconnect")
            if self._metrics is not None:
                # every loop iteration that reaches here is a rotation
                # episode: the stream ended, failed, or never connected
                self._metrics.reconnects.With(
                    "channel", self.channel_id
                ).add()
                self._metrics.backoff_seconds.With(
                    "channel", self.channel_id
                ).add(backoff)
            self.backoff_log.append(backoff)
            # through the clockskew seam: a virtual clock turns this
            # reconnect wait into a deterministic clock advance, so the
            # whole rotation/backoff cycle runs with no real sleeps
            if clockskew.wait(stop, backoff):
                return
            backoff = min(backoff * 2, self._max_backoff)


__all__ = ["DeliverClient"]
