"""Key-value store SPI + implementations.

Equivalent of the reference's common/ledger/util/leveldbhelper (a shared
goleveldb wrapper with db-name prefixing, batches and range iterators).
goleveldb has no Python counterpart in this image, so the durable backend
is sqlite (WAL mode, ordered BLOB keys give the same range-scan
contract); an in-memory impl serves tests and ephemeral ledgers.
"""

from __future__ import annotations

import bisect
import heapq
import os
import sqlite3
import struct
import threading
import time
import zlib
from typing import Iterator

from fabric_tpu.devtools import faultline, knob_registry
from fabric_tpu.devtools.lockwatch import guarded, named_lock, named_rlock


class KVStore:
    """Ordered byte-key store. Iteration is over a half-open [start, end)
    range in lexicographic key order, like leveldb iterators."""

    def get(self, key: bytes) -> bytes | None:
        raise NotImplementedError

    def get_many(self, keys) -> dict[bytes, bytes]:
        """Present keys -> values (absent keys omitted).  Backends
        override with one round-trip; the default loops."""
        out = {}
        for k in keys:
            v = self.get(k)
            if v is not None:
                out[k] = v
        return out

    def write_batch(self, puts: dict[bytes, bytes], deletes=()) -> None:
        raise NotImplementedError

    def write_batch_if_absent(self, puts: dict[bytes, bytes]) -> None:
        """Insert keys that do not exist yet; existing keys keep their
        value (leveldb has no native merge operator either — the
        reference reads before writing for first-wins indexes; backends
        here do it in one INSERT OR IGNORE round-trip)."""
        existing = self.get_many(list(puts))
        self.write_batch({k: v for k, v in puts.items() if k not in existing})

    def put(self, key: bytes, value: bytes) -> None:
        self.write_batch({key: value})

    def delete(self, key: bytes) -> None:
        self.write_batch({}, [key])

    def iterate(self, start: bytes = b"", end: bytes | None = None) -> Iterator[tuple[bytes, bytes]]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class MemKVStore(KVStore):
    def __init__(self) -> None:
        self._data: dict[bytes, bytes] = {}
        self._keys: list[bytes] = []
        self._lock = threading.RLock()

    def get(self, key: bytes) -> bytes | None:
        with self._lock:
            return self._data.get(key)

    def write_batch(self, puts, deletes=()) -> None:
        with self._lock:
            for k, v in puts.items():
                if k not in self._data:
                    bisect.insort(self._keys, k)
                self._data[k] = v
            for k in deletes:
                if k in self._data:
                    del self._data[k]
                    i = bisect.bisect_left(self._keys, k)
                    if i < len(self._keys) and self._keys[i] == k:
                        self._keys.pop(i)

    def iterate(self, start: bytes = b"", end: bytes | None = None):
        with self._lock:
            i = bisect.bisect_left(self._keys, start)
            keys = []
            while i < len(self._keys):
                k = self._keys[i]
                if end is not None and k >= end:
                    break
                keys.append(k)
                i += 1
            snapshot = [(k, self._data[k]) for k in keys]
        yield from snapshot


_SQLITE_SYNC_LEVELS = ("OFF", "NORMAL", "FULL", "EXTRA")


def _sqlite_sync_level(override: str | None) -> str:
    """PRAGMA synchronous level: ctor override, else
    FABRIC_TPU_SQLITE_SYNC, else NORMAL — the default the chaos-commit
    crash matrix and faultfuzz campaigns run against (in WAL mode,
    NORMAL can lose the last transactions on POWER loss but never
    corrupts, and the block-file-first invariant makes lost KV txns
    replayable from the file scan; FULL/EXTRA trade throughput for
    power-loss durability, OFF is bench-sweep-only)."""
    raw = (
        override
        if override is not None
        else knob_registry.raw("FABRIC_TPU_SQLITE_SYNC")
    ).strip().upper()
    if not raw:
        return "NORMAL"
    if raw not in _SQLITE_SYNC_LEVELS:
        raise ValueError(
            f"FABRIC_TPU_SQLITE_SYNC={raw!r}: expected one of "
            f"{'/'.join(_SQLITE_SYNC_LEVELS)}"
        )
    return raw


def _sqlite_wal_checkpoint(override: int | None) -> int:
    """wal_autocheckpoint page threshold: ctor override, else
    FABRIC_TPU_WAL_CHECKPOINT, else sqlite's stock 1000.  Larger values
    move checkpoint I/O off the commit path at the cost of a longer WAL
    (recovery still replays it fully); 0 disables auto-checkpointing
    entirely (operator-driven checkpoints only)."""
    if override is not None:
        return max(0, int(override))
    raw = knob_registry.raw("FABRIC_TPU_WAL_CHECKPOINT").strip()
    if not raw:
        return 1000
    try:
        return max(0, int(raw))
    except ValueError:
        raise ValueError(
            f"FABRIC_TPU_WAL_CHECKPOINT={raw!r} is not an integer page "
            "count (0 disables auto-checkpointing)"
        ) from None


class SqliteKVStore(KVStore):
    """Durable backend. One table of BLOB key/value; WAL journaling gives
    atomic batch commits (the recovery property blkstorage/kvledger rely
    on, reference blockfile checkpoints + leveldb atomicity).

    Durability knobs (`python bench.py --sweep-sqlite` measures the
    combos; the chaos crash matrix pins the default's safety):
    `synchronous`/`FABRIC_TPU_SQLITE_SYNC` and
    `wal_autocheckpoint`/`FABRIC_TPU_WAL_CHECKPOINT` — see
    _sqlite_sync_level/_sqlite_wal_checkpoint."""

    def __init__(self, path: str, synchronous: str | None = None,
                 wal_autocheckpoint: int | None = None):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self.sync_level = _sqlite_sync_level(synchronous)
        self._conn.execute(f"PRAGMA synchronous={self.sync_level}")
        self.wal_autocheckpoint = _sqlite_wal_checkpoint(wal_autocheckpoint)
        self._conn.execute(
            f"PRAGMA wal_autocheckpoint={self.wal_autocheckpoint:d}"
        )
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS kv (k BLOB PRIMARY KEY, v BLOB NOT NULL)"
        )
        self._conn.commit()
        self._lock = threading.RLock()

    def get(self, key: bytes) -> bytes | None:
        with self._lock:
            row = self._conn.execute("SELECT v FROM kv WHERE k = ?", (key,)).fetchone()
        return None if row is None else row[0]

    def get_many(self, keys) -> dict[bytes, bytes]:
        keys = list(keys)
        out: dict[bytes, bytes] = {}
        with self._lock:
            for off in range(0, len(keys), 500):  # sqlite variable limit
                chunk = keys[off:off + 500]
                rows = self._conn.execute(
                    "SELECT k, v FROM kv WHERE k IN (%s)"
                    % ",".join("?" * len(chunk)),
                    chunk,
                ).fetchall()
                out.update(rows)
        return out

    def write_batch(self, puts, deletes=()) -> None:
        # fault point BEFORE the transaction: an injected crash here
        # models process death between the block-file fsync and the KV
        # txn (sqlite's own atomicity covers mid-txn death)
        faultline.point("kvstore.txn", puts=len(puts))
        with self._lock:
            with self._conn:
                self._conn.executemany(
                    "INSERT INTO kv(k, v) VALUES(?, ?) "
                    "ON CONFLICT(k) DO UPDATE SET v = excluded.v",
                    [(k, v) for k, v in puts.items()],
                )
                self._conn.executemany(
                    "DELETE FROM kv WHERE k = ?", [(k,) for k in deletes]
                )

    def write_batch_if_absent(self, puts) -> None:
        # first occurrence wins WITHIN the batch too: sqlite executes
        # the rows in order and ignores every later conflicting insert
        with self._lock:
            with self._conn:
                self._conn.executemany(
                    "INSERT OR IGNORE INTO kv(k, v) VALUES(?, ?)",
                    list(puts.items()),
                )

    def iterate(self, start: bytes = b"", end: bytes | None = None):
        with self._lock:
            if end is None:
                rows = self._conn.execute(
                    "SELECT k, v FROM kv WHERE k >= ? ORDER BY k", (start,)
                ).fetchall()
            else:
                rows = self._conn.execute(
                    "SELECT k, v FROM kv WHERE k >= ? AND k < ? ORDER BY k",
                    (start, end),
                ).fetchall()
        yield from rows

    def close(self) -> None:
        self._conn.close()


class WriteBatchCollector(KVStore):
    """Buffers every mutation destined for `base` so one whole commit —
    state + history + pvt store + block index + savepoints — lands in a
    SINGLE base write_batch: on the sqlite backend that is exactly one
    transaction (the group-commit seam; the reference accumulates a
    leveldbhelper UpdateBatch per store but still pays one WriteBatch
    per store per block).  Reads are overlay-aware (read-your-writes),
    so MVCC validation of block k+1 in a group sees block k's buffered
    writes; flush() is all-or-nothing."""

    def __init__(self, base: KVStore):
        self._base = base
        self._puts: dict[bytes, bytes] = {}
        self._dels: set[bytes] = set()

    def get(self, key: bytes) -> bytes | None:
        if key in self._puts:
            return self._puts[key]
        if key in self._dels:
            return None
        return self._base.get(key)

    def get_many(self, keys) -> dict[bytes, bytes]:
        out: dict[bytes, bytes] = {}
        missing: list[bytes] = []
        for k in keys:
            if k in self._puts:
                out[k] = self._puts[k]
            elif k not in self._dels:
                missing.append(k)
        if missing:
            out.update(self._base.get_many(missing))
        return out

    def write_batch(self, puts, deletes=()) -> None:
        for k, v in puts.items():
            self._dels.discard(k)
            self._puts[k] = v
        for k in deletes:
            self._puts.pop(k, None)
            self._dels.add(k)

    # write_batch_if_absent: the KVStore default (get_many + filtered
    # write_batch) is already correct here because get_many sees the
    # overlay — first-wins holds across the buffered blocks of a group
    # as well as against committed state.

    def iterate(self, start: bytes = b"", end: bytes | None = None):
        """Merge the overlay into the base's ordered scan (the pvt
        store's expiry purge range-reads mid-commit)."""
        ov = iter(sorted(
            k for k in self._puts
            if k >= start and (end is None or k < end)
        ))
        ok = next(ov, None)
        for k, v in self._base.iterate(start, end):
            while ok is not None and ok < k:
                yield ok, self._puts[ok]
                ok = next(ov, None)
            if ok == k:
                yield k, self._puts[k]
                ok = next(ov, None)
                continue
            if k in self._dels:
                continue
            yield k, v
        while ok is not None:
            yield ok, self._puts[ok]
            ok = next(ov, None)

    @property
    def pending(self) -> int:
        return len(self._puts) + len(self._dels)

    def flush(self) -> None:
        """Commit everything buffered to the base store in one
        write_batch (one sqlite transaction), then reset."""
        if self._puts or self._dels:
            self._base.write_batch(self._puts, sorted(self._dels))
        self._puts = {}
        self._dels = set()

    def discard(self) -> None:
        """Drop everything buffered without touching the base store —
        the group-commit failure rollback."""
        self._puts = {}
        self._dels = set()


class NamedDB(KVStore):
    """A prefixed view over a shared store — the reference's
    leveldbhelper.Provider GetDBHandle(dbName) pattern."""

    _SEP = b"\x00\xff"

    def __init__(self, base: KVStore, name: str):
        self._base = base
        self._prefix = name.encode() + self._SEP

    def rebase(self, base: KVStore) -> "NamedDB":
        """The same namespace view over a different base — how commit
        hands each store a WriteBatchCollector without re-deriving the
        prefix from a name."""
        c = NamedDB.__new__(NamedDB)
        c._base = base
        c._prefix = self._prefix
        return c

    def _k(self, key: bytes) -> bytes:
        return self._prefix + key

    def get(self, key: bytes) -> bytes | None:
        return self._base.get(self._k(key))

    def get_many(self, keys) -> dict[bytes, bytes]:
        plen = len(self._prefix)
        got = self._base.get_many([self._k(k) for k in keys])
        return {k[plen:]: v for k, v in got.items()}

    def write_batch(self, puts, deletes=()) -> None:
        self._base.write_batch(
            {self._k(k): v for k, v in puts.items()}, [self._k(k) for k in deletes]
        )

    def write_batch_if_absent(self, puts) -> None:
        self._base.write_batch_if_absent(
            {self._k(k): v for k, v in puts.items()}
        )

    def iterate(self, start: bytes = b"", end: bytes | None = None):
        pend = self._prefix + end if end is not None else _prefix_end(self._prefix)
        for k, v in self._base.iterate(self._prefix + start, pend):
            yield k[len(self._prefix):], v


def _prefix_end(prefix: bytes) -> bytes | None:
    """Smallest key greater than every key with this prefix."""
    p = bytearray(prefix)
    while p:
        if p[-1] != 0xFF:
            p[-1] += 1
            return bytes(p)
        p.pop()
    return None


def wipe_prefix(store: KVStore, prefix: bytes) -> int:
    """Delete every key under `prefix` in one batch; returns the count.
    THE range-delete helper — ledger admin repair ops and the crashed-
    import discard both sweep namespaces through it, so the 0xFF-carry
    end-key logic lives in exactly one place."""
    keys = [k for k, _ in store.iterate(prefix, _prefix_end(prefix))]
    if keys:
        store.write_batch({}, deletes=keys)
    return len(keys)


def open_kvstore(path: str | None) -> KVStore:
    """None/':memory:' -> MemKVStore, else sqlite at path."""
    if path in (None, ":memory:"):
        return MemKVStore()
    return SqliteKVStore(path)


# -- storage engine v2: namespace-sharded store, two-phase group flush -------
#
# One sqlite file means one WAL and one fsync stream for every namespace a
# peer commits to.  The sharded store splits the STATE portion of the key
# space (``statedb/<lid>`` ``\x02`` entries — the bulk of every commit's
# bytes) across N shard files routed by top-level chaincode namespace,
# while everything whose atomicity defines the crash contract (state
# savepoints, block index + checkpoint, history, pvt store, metadata
# namespaces) stays in the coordinator file.  A group flush becomes two
# phases: every touched shard STAGES its mutations in a local
# pending-table transaction tagged with the flush epoch, then ONE
# coordinator transaction (carrying the savepoint/index/history writes
# plus the epoch record) commits the whole flush — reopen rolls prepared-
# but-uncommitted shards back and committed-but-unapplied shards forward,
# so the one-atomic-txn-per-block contract survives sharding.

_STATEDB_RAW_PREFIX = b"statedb/"
# coordinator-file metadata; \x00-leading raw keys sort below every
# NamedDB namespace so no prefixed view or wipe sweep can reach them
_SHARD_COUNT_KEY = b"\x00storev2\x00shards"
_EPOCH_KEY = b"\x00storev2\x00epoch"

_MAX_SHARDS = 64


def store_shards(override: int | None = None) -> int:
    """FABRIC_TPU_STORE_SHARDS: statedb shard-file count.  Default 1
    keeps the single-file seed layout (plain SqliteKVStore, no epoch
    machinery); values > 1 enable the namespace-sharded two-phase-flush
    engine.  The count is pinned into the coordinator file at creation —
    a reopen under a different knob value keeps the persisted width, so
    key routing can never drift across restarts."""
    if override is not None:
        return max(1, min(int(override), _MAX_SHARDS))
    raw = knob_registry.raw("FABRIC_TPU_STORE_SHARDS").strip()
    if not raw:
        return 1
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"FABRIC_TPU_STORE_SHARDS={raw!r} is not an integer shard "
            "count (1 = single-file layout)"
        ) from None
    return max(1, min(n, _MAX_SHARDS))


def shard_of_namespace(ns: str, n: int) -> int:
    """Shard index a namespace's state entries route to.  Derived
    namespaces (``cc\\x00pvt\\x00coll`` / ``cc\\x00hash\\x00coll``, see
    txmgmt.pvt_ns/hash_ns) ride with their parent chaincode so one
    chaincode's public + private state shares a shard/WAL."""
    top = ns.split("\x00", 1)[0]
    return zlib.crc32(top.encode()) % n


def state_shard(key: bytes, n: int) -> int | None:
    """Shard index for a raw store key, or None for coordinator keys.
    Only ``statedb/<lid>`` ``\x02`` state entries shard; savepoints
    (``\x01``), indexes (``\x03``/``\x04``), metadata (``\x05``) and
    every non-statedb namespace stay coordinated — they are the
    atomicity anchors of the commit."""
    if n <= 1 or not key.startswith(_STATEDB_RAW_PREFIX):
        return None
    sep = key.find(NamedDB._SEP, len(_STATEDB_RAW_PREFIX))
    if sep < 0:
        return None
    inner = key[sep + len(NamedDB._SEP):]
    if not inner.startswith(b"\x02"):
        return None
    nul = inner.find(b"\x00", 1)
    ns = inner[1:nul] if nul > 0 else inner[1:]
    return zlib.crc32(ns) % n


class _ShardStore(SqliteKVStore):
    """One statedb shard: the plain sqlite kv table plus a PENDING
    staging table and the shard-local epoch mark the two-phase flush
    stages into.  Pending rows are invisible to every read until
    apply_pending() folds them into kv (NULL value = delete marker)."""

    def __init__(self, path: str, synchronous: str | None = None,
                 wal_autocheckpoint: int | None = None):
        super().__init__(path, synchronous, wal_autocheckpoint)
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS pending (k BLOB PRIMARY KEY, v BLOB)"
        )
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS shardmeta "
            "(mk TEXT PRIMARY KEY, mv INTEGER NOT NULL)"
        )
        self._conn.commit()
        # lockwatch role: every shard file's connection lock shares one
        # role (no two shard locks ever nest on a thread — the fan-out
        # holds at most one per worker), ordered under the flush lock
        self._lock = named_rlock("kvstore.shard")

    def stage_pending(self, puts, deletes, epoch: int) -> None:
        """Phase-1 prepare: replace the pending table with this flush's
        mutations and mark the shard's epoch, in one local txn.  The
        leading DELETE makes prepare idempotent AND sweeps any stale
        pending left by a crashed-then-rolled-back earlier flush."""
        with self._lock:
            with self._conn:
                self._conn.execute("DELETE FROM pending")
                self._conn.executemany(
                    "INSERT INTO pending(k, v) VALUES(?, ?)",
                    list(puts.items()),
                )
                # deletes win over same-key puts, matching write_batch
                self._conn.executemany(
                    "INSERT OR REPLACE INTO pending(k, v) VALUES(?, NULL)",
                    [(k,) for k in deletes],
                )
                self._conn.execute(
                    "INSERT INTO shardmeta(mk, mv) "
                    "VALUES('pending_epoch', ?) "
                    "ON CONFLICT(mk) DO UPDATE SET mv = excluded.mv",
                    (epoch,),
                )

    def pending_epoch(self) -> int | None:
        """Epoch of the staged-but-unapplied flush, None when clean."""
        with self._lock:
            row = self._conn.execute(
                "SELECT mv FROM shardmeta WHERE mk = 'pending_epoch'"
            ).fetchone()
        return None if row is None else row[0]

    def apply_pending(self) -> None:
        """Phase-3 apply (also reopen roll-forward): fold pending into
        kv and clear the stage, in one local txn — atomic, so a crash
        mid-apply re-applies idempotently on the next open."""
        with self._lock:
            with self._conn:
                self._conn.execute(
                    "INSERT INTO kv(k, v) "
                    "SELECT k, v FROM pending WHERE v IS NOT NULL "
                    "ON CONFLICT(k) DO UPDATE SET v = excluded.v"
                )
                self._conn.execute(
                    "DELETE FROM kv WHERE k IN "
                    "(SELECT k FROM pending WHERE v IS NULL)"
                )
                self._conn.execute("DELETE FROM pending")
                self._conn.execute(
                    "DELETE FROM shardmeta WHERE mk = 'pending_epoch'"
                )

    def drop_pending(self) -> None:
        """Reopen roll-back: discard a prepared-but-never-committed
        stage (the coordinator's epoch record never landed)."""
        with self._lock:
            with self._conn:
                self._conn.execute("DELETE FROM pending")
                self._conn.execute(
                    "DELETE FROM shardmeta WHERE mk = 'pending_epoch'"
                )


class ShardedKVStore(KVStore):
    """The KVStore SPI over one coordinator file + N statedb shard
    files.  Reads route per key; iteration heap-merges the per-file
    ordered scans (routing is deterministic and disjoint, so the merge
    is exactly the single-file key order — snapshot export, state
    digests and range reads are byte-identical at every shard width).
    write_batch with shard-routed mutations runs the two-phase group
    flush; batches that touch no shard (index-only writes, recovery
    bookkeeping) commit straight to the coordinator exactly like the
    single-file engine."""

    def __init__(self, root_dir: str, shards: int | None = None,
                 synchronous: str | None = None,
                 wal_autocheckpoint: int | None = None):
        self._coord = SqliteKVStore(
            os.path.join(root_dir, "index.sqlite"),
            synchronous, wal_autocheckpoint,
        )
        raw = self._coord.get(_SHARD_COUNT_KEY)
        if raw is not None:
            # the persisted width wins: routing must never drift
            n = struct.unpack(">I", raw)[0]
        else:
            n = max(2, store_shards(shards))
            self._coord.put(_SHARD_COUNT_KEY, struct.pack(">I", n))
        self.shards = n
        self._stores = [
            _ShardStore(
                os.path.join(root_dir, f"state_{i:02d}.sqlite"),
                synchronous, wal_autocheckpoint,
            )
            for i in range(n)
        ]
        # serializes two-phase flushes and guards the epoch counter
        self._lock = named_lock("kvstore.shard_flush")
        # per-phase wall splits of the LAST two-phase flush; kvledger
        # folds them into commit_stage_seconds after each group flush
        self.last_stage_seconds: dict[str, float] = {}
        self._epoch = 0
        with self._lock:
            raw = self._coord.get(_EPOCH_KEY)
            self._epoch = 0 if raw is None else struct.unpack(">Q", raw)[0]
            self._recover_pending()

    # -- reopen recovery ---------------------------------------------------

    def _recover_pending(self) -> None:
        """Resolve staged flushes left by a crash: a shard whose pending
        epoch matches the coordinator's committed epoch lost only its
        apply phase — roll FORWARD (the flush was acknowledged by the
        coordinator txn).  Any other pending epoch was prepared but
        never committed — roll back.  Both arms are idempotent, so a
        crash during recovery just re-runs it."""
        for i, s in enumerate(self._stores):
            pe = s.pending_epoch()
            if pe is None:
                continue
            if pe == self._epoch:
                # guard-style fault point: a faultfuzz "skip" rule
                # deletes this roll-forward, leaving writes the
                # coordinator's savepoint already acknowledges missing
                # from the shard — the lost-committed-state corruption
                # the invariants oracle must catch (the storage-v2
                # seeded-violation acceptance case)
                if faultline.guard(
                    "store.shard_recover", shard=i, epoch=pe,
                ):
                    s.apply_pending()
            else:
                s.drop_pending()

    # -- reads -------------------------------------------------------------

    def _store_for(self, key: bytes) -> KVStore:
        i = state_shard(key, self.shards)
        return self._coord if i is None else self._stores[i]

    def get(self, key: bytes) -> bytes | None:
        return self._store_for(key).get(key)

    def get_many(self, keys) -> dict[bytes, bytes]:
        groups: dict[int | None, list[bytes]] = {}
        for k in keys:
            groups.setdefault(state_shard(k, self.shards), []).append(k)
        out: dict[bytes, bytes] = {}
        for i, ks in groups.items():
            store = self._coord if i is None else self._stores[i]
            out.update(store.get_many(ks))
        return out

    def iterate(self, start: bytes = b"", end: bytes | None = None):
        # routing is disjoint: the heap-merge of per-file ordered scans
        # IS the global key order (each file scan releases its lock
        # before yielding, so the lazy merge never nests shard locks)
        return heapq.merge(
            self._coord.iterate(start, end),
            *(s.iterate(start, end) for s in self._stores),
        )

    # -- writes ------------------------------------------------------------

    def _partition(self, puts, deletes):
        shard_puts: dict[int, dict[bytes, bytes]] = {}
        shard_dels: dict[int, list[bytes]] = {}
        coord_puts: dict[bytes, bytes] = {}
        coord_dels: list[bytes] = []
        for k, v in puts.items():
            i = state_shard(k, self.shards)
            if i is None:
                coord_puts[k] = v
            else:
                shard_puts.setdefault(i, {})[k] = v
        for k in deletes:
            i = state_shard(k, self.shards)
            if i is None:
                coord_dels.append(k)
            else:
                shard_dels.setdefault(i, []).append(k)
        return shard_puts, shard_dels, coord_puts, coord_dels

    @staticmethod
    def _fanout_width(n_shards: int) -> int:
        """Chunk fan-out for the prepare/apply phases on the shared
        workpool (FABRIC_TPU_STORE_POOL, default auto, 0 = serial).
        Width never changes RESULTS — partitioning is deterministic and
        shard key sets are disjoint — only wall time."""
        from fabric_tpu.common import workpool

        return min(workpool.stage_width("FABRIC_TPU_STORE_POOL"), n_shards)

    def write_batch(self, puts, deletes=()) -> None:
        shard_puts, shard_dels, coord_puts, coord_dels = self._partition(
            puts, deletes
        )
        if not shard_puts and not shard_dels:
            # coordinator-only batch: no two-phase machinery, and no
            # stale phase splits left for the caller to re-observe
            self.last_stage_seconds = {}
            self._coord.write_batch(coord_puts, coord_dels)
            return
        from fabric_tpu.common import workpool

        t = time.perf_counter
        with self._lock:
            guarded(self, "_epoch", by="kvstore.shard_flush")
            epoch = self._epoch + 1
            touched = sorted(set(shard_puts) | set(shard_dels))
            wall: dict[str, float] = {}

            def _prep(off, items):
                out = []
                for i in items:
                    t0 = t()
                    p = shard_puts.get(i, {})
                    faultline.point(
                        "store.shard_flush", stage="prepare", shard=i,
                        epoch=epoch, puts=len(p),
                    )
                    self._stores[i].stage_pending(
                        p, shard_dels.get(i, ()), epoch
                    )
                    out.append((i, t() - t0))
                return out

            def _apply(off, items):
                out = []
                for i in items:
                    t0 = t()
                    faultline.point(
                        "store.shard_flush", stage="apply", shard=i,
                        epoch=epoch,
                    )
                    self._stores[i].apply_pending()
                    out.append((i, t() - t0))
                return out

            width = self._fanout_width(len(touched))
            pool = workpool.default_pool() if width > 1 else None
            t0 = t()
            # phase 1: stage every touched shard (parallel fan-out)
            for i, dt in workpool.run_chunked(
                pool, _prep, touched, max(width, 1)
            ):
                wall[f"shard{i}"] = wall.get(f"shard{i}", 0.0) + dt
            t1 = t()
            # phase 2: THE commit point — coordinator mutations (index,
            # savepoint, history, pvt) plus the epoch record in ONE
            # sqlite txn; a crash on either side of it resolves cleanly
            # at reopen (_recover_pending)
            faultline.point(
                "store.shard_flush", stage="commit", epoch=epoch,
                shards=len(touched),
            )
            coord_puts[_EPOCH_KEY] = struct.pack(">Q", epoch)
            self._coord.write_batch(coord_puts, coord_dels)
            self._epoch = epoch
            t2 = t()
            # phase 3: fold each shard's stage into its kv table
            for i, dt in workpool.run_chunked(
                pool, _apply, touched, max(width, 1)
            ):
                wall[f"shard{i}"] = wall.get(f"shard{i}", 0.0) + dt
            t3 = t()
            wall["prepare"] = t1 - t0
            wall["commit"] = t2 - t1
            wall["apply"] = t3 - t2
            self.last_stage_seconds = wall

    def write_batch_if_absent(self, puts) -> None:
        shard_puts, _, coord_puts, _ = self._partition(puts, ())
        if coord_puts:
            self._coord.write_batch_if_absent(coord_puts)
        for i in sorted(shard_puts):
            self._stores[i].write_batch_if_absent(shard_puts[i])

    def close(self) -> None:
        self._coord.close()
        for s in self._stores:
            s.close()


def open_store_root(root_dir: str | None,
                    shards: int | None = None) -> KVStore:
    """The provider's root store.  None -> MemKVStore; the single
    sqlite file (seed layout) unless FABRIC_TPU_STORE_SHARDS asks for
    more or shard files already exist on disk — an existing sharded
    layout always reopens sharded, whatever the knob says now."""
    if root_dir is None:
        return MemKVStore()
    n = store_shards(shards)
    if n <= 1 and not os.path.exists(
        os.path.join(root_dir, "state_00.sqlite")
    ):
        return SqliteKVStore(os.path.join(root_dir, "index.sqlite"))
    return ShardedKVStore(root_dir, shards=n)


__all__ = [
    "KVStore",
    "MemKVStore",
    "SqliteKVStore",
    "ShardedKVStore",
    "NamedDB",
    "WriteBatchCollector",
    "open_kvstore",
    "open_store_root",
    "store_shards",
    "shard_of_namespace",
    "state_shard",
    "wipe_prefix",
]
