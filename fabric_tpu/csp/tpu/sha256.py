"""Batched SHA-256 on TPU.

Replaces per-message `hashlib.sha256` host hashing on the validation hot
path (reference: msp/identities.go:169-196 hashes each message before
`bccsp.Verify`; bccsp/sw hash dispatch in bccsp/sw/impl.go) with one
vectorized compression over all messages of a block.

TPU-first shape: every message is padded (standard SHA-256 Merkle–Damgård
padding, done host-side in numpy) to the same static number of 64-byte
blocks for its bucket, and the kernel runs the 64-round compression as a
`lax.fori_loop` over rounds with the whole batch in lockstep — uint32
VPU arithmetic, no data-dependent control flow, one jit per
(batch, n_blocks) bucket.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

_K = np.array(
    [
        0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5, 0x3956C25B, 0x59F111F1,
        0x923F82A4, 0xAB1C5ED5, 0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
        0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174, 0xE49B69C1, 0xEFBE4786,
        0x0FC19DC6, 0x240CA1CC, 0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
        0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7, 0xC6E00BF3, 0xD5A79147,
        0x06CA6351, 0x14292967, 0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
        0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85, 0xA2BFE8A1, 0xA81A664B,
        0xC24B8B70, 0xC76C51A3, 0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
        0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5, 0x391C0CB3, 0x4ED8AA4A,
        0x5B9CCA4F, 0x682E6FF3, 0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
        0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
    ],
    dtype=np.uint32,
)

_H0 = np.array(
    [0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
     0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19],
    dtype=np.uint32,
)


def _rotr(x, n: int):
    return (x >> jnp.uint32(n)) | (x << jnp.uint32(32 - n))


def _compress_block(h, w_block):
    """One 64-round compression; h (..., 8), w_block (..., 16) uint32."""
    k = jnp.asarray(_K)

    def round_fn(i, state):
        a, b, c, d, e, f, g, hh, w = state
        wi = w[..., 0]
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = hh + s1 + ch + k[i] + wi
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        # message schedule computed in-place on a rolling 16-word window
        w15 = w[..., 1]
        w2 = w[..., 14]
        sig0 = _rotr(w15, 7) ^ _rotr(w15, 18) ^ (w15 >> jnp.uint32(3))
        sig1 = _rotr(w2, 17) ^ _rotr(w2, 19) ^ (w2 >> jnp.uint32(10))
        w_next = wi + sig0 + w[..., 9] + sig1
        w = jnp.concatenate([w[..., 1:], w_next[..., None]], axis=-1)
        return (t1 + t2, a, b, c, d + t1, e, f, g, w)

    a, b, c, d, e, f, g, hh = [h[..., i] for i in range(8)]
    a, b, c, d, e, f, g, hh, _ = jax.lax.fori_loop(
        0, 64, round_fn, (a, b, c, d, e, f, g, hh, w_block)
    )
    return h + jnp.stack([a, b, c, d, e, f, g, hh], axis=-1)


def sha256_kernel(words, nblk):
    """words: (B, n_blocks, 16) uint32 big-endian padded message words;
    nblk: (B,) int32 — how many blocks each lane actually occupies (its own
    Merkle–Damgård padding sits inside those blocks).  Lanes freeze once
    their block count is reached, so one jitted program serves mixed-length
    batches padded to a common static width.  Returns (B, 8) digest words."""
    n_blocks = words.shape[-2]
    h = jnp.broadcast_to(jnp.asarray(_H0), words.shape[:-2] + (8,))

    def body(i, h):
        blk = jax.lax.dynamic_index_in_dim(words, i, axis=-2, keepdims=False)
        h_new = _compress_block(h, blk)
        live = (i < nblk)[..., None]
        return jnp.where(live, h_new, h)

    return jax.lax.fori_loop(0, n_blocks, body, h)


@functools.lru_cache(maxsize=None)
def _jit_sha():
    return jax.jit(sha256_kernel)


def pad_messages(msgs, n_blocks: int | None = None) -> tuple[np.ndarray, np.ndarray]:
    """Standard SHA-256 padding, each message inside its OWN final block.

    Returns (words (B, n_blocks, 16) uint32, nblk (B,) int32): batches mix
    lengths freely; `n_blocks` only sets the static width (bucketing)."""
    blocks = [(len(m) + 9 + 63) // 64 for m in msgs]
    need = max(blocks) if blocks else 1
    if n_blocks is None:
        n_blocks = need
    if need > n_blocks:
        raise ValueError("messages need %d blocks > %d" % (need, n_blocks))
    out = np.zeros((len(msgs), n_blocks * 64), dtype=np.uint8)
    for i, m in enumerate(msgs):
        out[i, : len(m)] = np.frombuffer(m, dtype=np.uint8)
        out[i, len(m)] = 0x80
        bitlen = (8 * len(m)).to_bytes(8, "big")
        out[i, blocks[i] * 64 - 8 : blocks[i] * 64] = np.frombuffer(bitlen, dtype=np.uint8)
    words = out.reshape(len(msgs), n_blocks, 16, 4)
    packed = (
        (words[..., 0].astype(np.uint32) << 24)
        | (words[..., 1].astype(np.uint32) << 16)
        | (words[..., 2].astype(np.uint32) << 8)
        | words[..., 3].astype(np.uint32)
    )
    return packed, np.asarray(blocks, dtype=np.int32)


def digest_to_bytes(dig: np.ndarray) -> list[bytes]:
    """(B, 8) uint32 words -> list of 32-byte digests."""
    dig = np.asarray(dig)
    b = np.zeros((dig.shape[0], 32), dtype=np.uint8)
    for i in range(8):
        b[:, 4 * i] = (dig[:, i] >> 24) & 0xFF
        b[:, 4 * i + 1] = (dig[:, i] >> 16) & 0xFF
        b[:, 4 * i + 2] = (dig[:, i] >> 8) & 0xFF
        b[:, 4 * i + 3] = dig[:, i] & 0xFF
    return [row.tobytes() for row in b]


def sha256_batch(msgs, n_blocks: int | None = None) -> list[bytes]:
    """Hash a batch of messages on device (one jit per block-count bucket)."""
    if not msgs:
        return []
    words, nblk = pad_messages(msgs, n_blocks)
    return digest_to_bytes(np.asarray(_jit_sha()(words, nblk)))


__all__ = ["sha256_kernel", "sha256_batch", "pad_messages", "digest_to_bytes"]
