"""Idemix tests: pairing math, credential lifecycle, signatures.

Mirrors the reference's idemix test coverage (idemix/idemix_test.go):
issuer key check, cred request check, credential ver, signature
sign/verify with selective disclosure, nym signatures, weak-BB, CRI.
"""

import random

import pytest

from fabric_tpu.idemix import bn254 as bn
from fabric_tpu.idemix import nymsignature, revocation, signature, weakbb
from fabric_tpu.idemix.credential import (
    attribute_to_scalar,
    new_cred_request,
    new_credential,
)
from fabric_tpu.idemix.issuer import IssuerKey

RNG = random.Random(42)

ATTRS = ["OU", "Role", "EnrollmentID", "RevocationHandle"]


@pytest.fixture(scope="module")
def issuer():
    return IssuerKey.generate(ATTRS, rng=RNG)


@pytest.fixture(scope="module")
def user(issuer):
    sk = bn.rand_zr(RNG)
    req = new_cred_request(sk, b"nonce-1", issuer.ipk, rng=RNG)
    attrs = [
        attribute_to_scalar("org1"),
        attribute_to_scalar(2),
        attribute_to_scalar("alice"),
        attribute_to_scalar(100),
    ]
    cred = new_credential(issuer, req, attrs, rng=RNG)
    cred.ver(sk, issuer.ipk)
    return sk, cred


class TestPairing:
    def test_bilinearity(self):
        a, b = 1234567, 987654321
        e = bn.pairing(bn.G1_GEN, bn.G2_GEN)
        assert e != bn.FP12_ONE
        lhs = bn.pairing(bn.g1_mul(bn.G1_GEN, a), bn.g2_mul(bn.G2_GEN, b))
        assert lhs == bn.fp12_pow(e, a * b % bn.R)

    def test_gt_order(self):
        e = bn.pairing(bn.G1_GEN, bn.G2_GEN)
        assert bn.fp12_pow(e, bn.R) == bn.FP12_ONE

    def test_multi_pairing_cancellation(self):
        mp = bn.multi_pairing(
            [(bn.G1_GEN, bn.G2_GEN), (bn.g1_neg(bn.G1_GEN), bn.G2_GEN)]
        )
        assert mp == bn.FP12_ONE

    def test_serialization_roundtrip(self):
        p = bn.g1_mul(bn.G1_GEN, 77)
        q = bn.g2_mul(bn.G2_GEN, 99)
        assert bn.g1_from_bytes(bn.g1_to_bytes(p)) == p
        assert bn.g2_from_bytes(bn.g2_to_bytes(q)) == q
        with pytest.raises(ValueError):
            bn.g1_from_bytes(b"\x01" * 64)  # not on curve


class TestIssuerAndCredential:
    def test_issuer_key_check(self, issuer):
        issuer.ipk.check()

    def test_issuer_key_tamper(self, issuer):
        import copy

        bad = copy.deepcopy(issuer.ipk)
        bad.w = bn.g2_mul(bn.G2_GEN, 123)
        with pytest.raises(ValueError):
            bad.check()

    def test_cred_request_bad_proof(self, issuer):
        sk = bn.rand_zr(RNG)
        req = new_cred_request(sk, b"n", issuer.ipk, rng=RNG)
        req.proof_s = (req.proof_s + 1) % bn.R
        with pytest.raises(ValueError):
            req.check(issuer.ipk)

    def test_credential_wrong_sk(self, issuer, user):
        _, cred = user
        with pytest.raises(ValueError):
            cred.ver(bn.rand_zr(RNG), issuer.ipk)

    def test_credential_attr_mismatch(self, issuer, user):
        sk, cred = user
        import copy

        bad = copy.deepcopy(cred)
        bad.attrs[0] = attribute_to_scalar("org2")
        with pytest.raises(ValueError):
            bad.ver(sk, issuer.ipk)


class TestSignature:
    def test_sign_verify_no_disclosure(self, issuer, user):
        sk, cred = user
        sig = signature.new_signature(
            cred, sk, issuer.ipk, b"msg", rng=RNG
        )
        assert signature.verify(sig, issuer.ipk, b"msg")
        assert not signature.verify(sig, issuer.ipk, b"other msg")

    def test_sign_verify_selective_disclosure(self, issuer, user):
        sk, cred = user
        disclosure = [True, True, False, False]
        sig = signature.new_signature(
            cred, sk, issuer.ipk, b"msg", disclosure=disclosure, rng=RNG
        )
        assert sig.disclosed_attrs == {
            0: cred.attrs[0], 1: cred.attrs[1]
        }
        assert signature.verify(sig, issuer.ipk, b"msg")
        # Lying about a disclosed attribute fails.
        sig.disclosed_attrs[0] = attribute_to_scalar("org2")
        assert not signature.verify(sig, issuer.ipk, b"msg")

    def test_tampered_pairing_component(self, issuer, user):
        sk, cred = user
        sig = signature.new_signature(cred, sk, issuer.ipk, b"m", rng=RNG)
        # Replacing ABar with a consistent-looking but wrong point must
        # fail the pairing check even if we can't fake the Schnorr part.
        sig.a_bar = bn.g1_mul(bn.G1_GEN, 5)
        assert not signature.verify(sig, issuer.ipk, b"m")

    def test_batch_verify(self, issuer, user):
        sk, cred = user
        msgs = [b"m%d" % i for i in range(4)]
        sigs = [
            signature.new_signature(cred, sk, issuer.ipk, m, rng=RNG)
            for m in msgs
        ]
        assert signature.verify_batch(sigs, issuer.ipk, msgs, rng=RNG) == [
            True
        ] * 4
        # Corrupt one: batch falls back and isolates it.
        sigs[2].a_bar = bn.g1_mul(bn.G1_GEN, 9)
        assert signature.verify_batch(sigs, issuer.ipk, msgs, rng=RNG) == [
            True, True, False, True,
        ]
        # Corrupt another at the Schnorr level.
        sigs[0].challenge = (sigs[0].challenge + 1) % bn.R
        assert signature.verify_batch(sigs, issuer.ipk, msgs, rng=RNG) == [
            False, True, False, True,
        ]


class TestIdemixCSPDeviceSelect:
    """The provider auto-selects the device Schnorr path at or above
    the measured crossover (VERDICT r4 #6): callers never need to know
    the constant, and small batches never pay a kernel compile."""

    def _record_dispatch(self, monkeypatch):
        calls = []

        def host(sigs, ipk, msgs, rng=None):
            calls.append("host")
            return [True] * len(sigs)

        def device(sigs, ipk, msgs, rng=None):
            calls.append("device")
            return [True] * len(sigs)

        from fabric_tpu.csp import idemix_provider as ip

        monkeypatch.setattr(ip.signature, "verify_batch", host)
        monkeypatch.setattr(ip.signature, "verify_batch_device", device)
        # the suite runs on CPU; pretend a TPU backend is present so
        # the auto path's size threshold is what's under test
        monkeypatch.setattr(ip, "_on_tpu", lambda: True)
        return calls

    def test_auto_select_by_batch_size(self, issuer, monkeypatch):
        from fabric_tpu.csp import IdemixCSP, IdemixVerifyItem

        calls = self._record_dispatch(monkeypatch)
        csp = IdemixCSP(rng=RNG)
        small = [IdemixVerifyItem(None, b"m")] * (csp.DEVICE_CROSSOVER - 1)
        large = [IdemixVerifyItem(None, b"m")] * csp.DEVICE_CROSSOVER
        csp.verify_batch(small, issuer.ipk)
        csp.verify_batch(large, issuer.ipk)
        assert calls == ["host", "device"]

    def test_forced_and_overridden(self, issuer, monkeypatch):
        from fabric_tpu.csp import IdemixCSP, IdemixVerifyItem

        calls = self._record_dispatch(monkeypatch)
        items = [IdemixVerifyItem(None, b"m")] * 8
        IdemixCSP(rng=RNG, device=True).verify_batch(items, issuer.ipk)
        IdemixCSP(rng=RNG, device=False).verify_batch(
            items * 40, issuer.ipk
        )
        IdemixCSP(rng=RNG, device_crossover=8).verify_batch(
            items, issuer.ipk
        )
        assert calls == ["device", "host", "device"]

    def test_auto_device_path_is_correct(self, issuer, user, monkeypatch):
        """Real (un-mocked) dispatch above the crossover must produce
        the same mask as the host path — parity at the provider level.
        Uses a lowered crossover so the suite stays fast; _on_tpu is
        forced True (the suite runs on CPU) so the REAL
        verify_batch_device call executes via its XLA fallback."""
        from fabric_tpu.csp import IdemixCSP, IdemixVerifyItem
        from fabric_tpu.csp import idemix_provider as ip

        monkeypatch.setattr(ip, "_on_tpu", lambda: True)
        sk, cred = user
        msgs = [b"b%d" % i for i in range(6)]
        sigs = [
            signature.new_signature(cred, sk, issuer.ipk, m, rng=RNG)
            for m in msgs
        ]
        sigs[3].a_bar = bn.g1_mul(bn.G1_GEN, 7)
        items = [IdemixVerifyItem(s, m) for s, m in zip(sigs, msgs)]
        csp = IdemixCSP(rng=RNG, device_crossover=4)
        want = [True, True, True, False, True, True]
        assert csp.verify_batch(items, issuer.ipk) == want


class TestNymSignature:
    def test_roundtrip(self, issuer):
        sk = bn.rand_zr(RNG)
        r_nym = bn.rand_zr(RNG)
        nym = bn.g1_add(
            bn.g1_mul(issuer.ipk.h_sk, sk),
            bn.g1_mul(issuer.ipk.h_rand, r_nym),
        )
        sig = nymsignature.new_nym_signature(
            sk, nym, r_nym, issuer.ipk, b"hello", rng=RNG
        )
        assert nymsignature.verify_nym(sig, nym, issuer.ipk, b"hello")
        assert not nymsignature.verify_nym(sig, nym, issuer.ipk, b"bye")
        sig.z_sk = (sig.z_sk + 1) % bn.R
        assert not nymsignature.verify_nym(sig, nym, issuer.ipk, b"hello")


class TestWeakBB:
    def test_roundtrip(self):
        sk, pk = weakbb.wbb_key_gen(rng=RNG)
        m = bn.rand_zr(RNG)
        sig = weakbb.wbb_sign(sk, m)
        assert weakbb.wbb_verify(pk, sig, m)
        assert not weakbb.wbb_verify(pk, sig, (m + 1) % bn.R)


class TestRevocation:
    def test_cri(self):
        ra = revocation.generate_long_term_revocation_key()
        cri = revocation.create_cri(ra, epoch=7, rng=RNG)
        raw = cri.to_bytes()
        back = revocation.CredentialRevocationInformation.from_bytes(raw)
        assert revocation.verify_epoch_pk(ra.public_key(), back)
        back.epoch = 8
        assert not revocation.verify_epoch_pk(ra.public_key(), back)
