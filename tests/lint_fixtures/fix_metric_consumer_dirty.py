"""Seeded violation (metrics-conformance): the rollup consumes series
``fix_missing_total`` but no producer or derived series carries that
name — the threshold can only ever see an absent series.  The module's
actual producer (``fix_events_total``) is registered and consumed, so
the only violation is the orphan consumer."""

from fabric_tpu.common.metrics import CounterOpts


def wire(provider):
    return provider.new_counter(
        CounterOpts(namespace="fix", name="events_total")
    )


def watch(scope, node):
    good = scope.series(node, "fix_events_total")
    bad = scope.series(node, "fix_missing_total")  # <- orphan consumer
    return good, bad


def boot(provider, scope, node):
    wire(provider)
    return watch(scope, node)
