"""Operations endpoint, metrics SPI, and logging tests (reference
core/operations/system_test.go, common/metrics, common/flogging)."""

import json
import logging
import urllib.request

import pytest

from fabric_tpu.common import flogging
from fabric_tpu.common.metrics import (
    CounterOpts,
    GaugeOpts,
    HistogramOpts,
    PrometheusProvider,
    StatsdProvider,
)
from fabric_tpu.common.operations import System


def _get(url):
    with urllib.request.urlopen(url, timeout=3) as r:
        return r.status, r.read()


class TestMetrics:
    def test_prometheus_counter_gauge_histogram(self):
        p = PrometheusProvider()
        c = p.new_counter(
            CounterOpts(namespace="ledger", name="commits",
                        help="Total commits.")
        )
        c.with_labels("channel", "ch1").add()
        c.with_labels("channel", "ch1").add(2)
        c.with_labels("channel", "ch2").add()
        g = p.new_gauge(GaugeOpts(namespace="gossip", name="peers"))
        g.set(5)
        h = p.new_histogram(
            HistogramOpts(namespace="ledger", name="commit_seconds",
                          buckets=(0.1, 1.0))
        )
        h.with_labels("channel", "ch1").observe(0.05)
        h.with_labels("channel", "ch1").observe(0.5)
        text = p.registry.expose()
        assert 'ledger_commits{channel="ch1"} 3' in text
        assert 'ledger_commits{channel="ch2"} 1' in text
        assert "gossip_peers 5" in text
        assert (
            'ledger_commit_seconds_bucket{channel="ch1",le="0.1"} 1' in text
        )
        assert 'ledger_commit_seconds_count{channel="ch1"} 2' in text
        assert "# TYPE ledger_commits counter" in text

    def test_statsd_lines(self):
        lines = []
        p = StatsdProvider(lines.append, prefix="peer")
        p.new_counter(CounterOpts(name="tx_count")).add()
        p.new_gauge(GaugeOpts(name="height")).set(7)
        p.new_histogram(HistogramOpts(name="lat")).observe(12.5)
        assert lines == [
            "peer.tx.count:1|c", "peer.height:7|g", "peer.lat:12.5|ms"
        ]


class TestFlogging:
    def test_spec_parsing_and_prefix_match(self):
        default, overrides = flogging.parse_spec(
            "gossip=debug:ledger,orderer=error:warning"
        )
        assert default == logging.WARNING
        assert overrides == {
            "gossip": logging.DEBUG,
            "ledger": logging.ERROR,
            "orderer": logging.ERROR,
        }
        lv = flogging.LoggerLevels()
        lv.activate_spec("gossip=debug:gossip.comm=error:info")
        assert lv.level_for("gossip.pull") == logging.DEBUG
        assert lv.level_for("gossip.comm") == logging.ERROR
        assert lv.level_for("ledger") == logging.INFO

    def test_invalid_spec(self):
        with pytest.raises(flogging.LogSpecError):
            flogging.parse_spec("gossip=nope")

    def test_observer_counts(self):
        p = PrometheusProvider()
        counter = p.new_counter(
            CounterOpts(namespace="logging", name="entries_checked")
        )
        reg = flogging.global_registry()
        reg.set_observer_counter(counter)
        try:
            flogging.activate_spec("info")
            log = flogging.must_get_logger("testobs")
            log.info("hello")
            log.debug("filtered out — also not counted")
            text = p.registry.expose()
            assert 'logging_entries_checked{level="info"} 1' in text
        finally:
            reg.observer = None


class TestOperationsServer:
    @pytest.fixture()
    def system(self):
        s = System(("127.0.0.1", 0))
        s.start()
        yield s
        s.stop()

    def test_endpoints(self, system):
        host, port = system.addr
        base = f"http://{host}:{port}"
        system.metrics_provider.new_counter(
            CounterOpts(name="ops_test_total")
        ).add(4)
        status, body = _get(base + "/metrics")
        assert status == 200 and b"ops_test_total 4" in body
        status, body = _get(base + "/version")
        assert status == 200 and json.loads(body)["Version"]
        status, body = _get(base + "/healthz")
        assert status == 200 and json.loads(body)["status"] == "OK"

        # failing checker flips /healthz to 503
        system.register_checker("statedb", lambda: False)
        req = urllib.request.Request(base + "/healthz")
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=3)
        assert exc.value.code == 503
        assert "statedb" in json.loads(exc.value.read())["failed_checks"]

    def test_healthz_detail_mode(self, system):
        """ISSUE 12 satellite: ?detail=1 lists every checker with its
        name, pass/fail status, and a persistent last_error — the
        netscope health timeline's per-checker input."""
        host, port = system.addr
        base = f"http://{host}:{port}"
        flaky = {"fail": True}

        def flaky_check():
            if flaky["fail"]:
                raise RuntimeError("db unreachable")
            return True

        system.register_checker("statedb", flaky_check)
        system.register_checker("always", lambda: True)

        req = urllib.request.Request(base + "/healthz?detail=1")
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=3)
        assert exc.value.code == 503
        body = json.loads(exc.value.read())
        assert body["status"] == "Service Unavailable"
        assert body["failed_checks"] == ["statedb: db unreachable"]
        checks = {c["component"]: c for c in body["checks"]}
        assert checks["statedb"]["status"] == "failed"
        assert checks["statedb"]["last_error"] == "db unreachable"
        assert checks["always"] == {
            "component": "always", "status": "OK", "last_error": None,
        }

        # recovery: healthy again, but last_error persists in detail
        flaky["fail"] = False
        status, raw = _get(base + "/healthz?detail=1")
        assert status == 200
        body = json.loads(raw)
        assert body["status"] == "OK"
        checks = {c["component"]: c for c in body["checks"]}
        assert checks["statedb"]["status"] == "OK"
        assert checks["statedb"]["last_error"] == "db unreachable"
        # plain mode keeps the reference body shape (no checks key)
        status, raw = _get(base + "/healthz")
        assert status == 200 and "checks" not in json.loads(raw)

    def test_workpool_saturation_checker(self, monkeypatch):
        from fabric_tpu.common import workpool

        check = workpool.health_checker()
        # no pool ever created: healthy, and the probe must not spin
        # one up
        assert check() is True

        class _FakeQueue:
            def __init__(self, n):
                self._n = n

            def qsize(self):
                return self._n

        class _FakePool:
            _max_workers = 2
            _work_queue = _FakeQueue(3)

        monkeypatch.setattr(workpool, "_pool", _FakePool())
        monkeypatch.setitem(workpool._stats, "in_flight", 5)
        with pytest.raises(RuntimeError, match="saturated"):
            check()
        # full utilization with an empty queue is NOT unhealthy
        _FakePool._work_queue = _FakeQueue(0)
        monkeypatch.setitem(workpool._stats, "in_flight", 2)
        assert check() is True

    def test_tpu_breaker_checker(self):
        from fabric_tpu.csp.tpu import provider as tpuprov

        class _Stub:
            class _breaker:
                open = False
                trips = 0

        check = tpuprov.TPUCSP.health_checker(_Stub())
        assert check() is True
        _Stub._breaker.open = True
        _Stub._breaker.trips = 2
        with pytest.raises(RuntimeError, match="breaker open"):
            check()

    def test_logspec_roundtrip(self, system):
        host, port = system.addr
        base = f"http://{host}:{port}"
        req = urllib.request.Request(
            base + "/logspec",
            data=json.dumps({"spec": "gossip=debug:info"}).encode(),
            method="PUT",
        )
        with urllib.request.urlopen(req, timeout=3) as r:
            assert r.status == 204
        status, body = _get(base + "/logspec")
        assert json.loads(body)["spec"] == "gossip=debug:info"
        # invalid spec -> 400
        req = urllib.request.Request(
            base + "/logspec",
            data=json.dumps({"spec": "x=bogus"}).encode(),
            method="PUT",
        )
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=3)
        assert exc.value.code == 400
