"""Test configuration.

Tests run on CPU with a virtual 8-device mesh so multi-chip sharding
(shard_map over jax.sharding.Mesh) is exercised without TPU hardware, per
the reference test strategy of simulating multi-node on one host
(integration/nwo).  Must run before jax initializes a backend.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
