"""In-memory certificate authority + cert utilities.

Equivalent of the reference's common/crypto/tlsgen (test CAs, chaincode TLS)
and the CA core of the cryptogen tool (internal/cryptogen/ca).  ECDSA-P256
throughout, matching the fabric default.  Also hosts the cert-expiration
warning helper (reference common/crypto/expiration.go).
"""

from __future__ import annotations

import datetime
import hashlib
import ipaddress

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import ExtendedKeyUsageOID, NameOID


# The host-side CSP hash seam lives in common/hashing.py (stdlib-only,
# so protoutil/ledger/chaincode can import it on hosts without the
# `cryptography` package); re-exported here for cert-side callers.
from fabric_tpu.common.hashing import (  # noqa: F401
    set_hash_backend,
    sha256,
    sha256_many,
)


def _name(common_name: str, org: str | None = None, ou: str | None = None) -> x509.Name:
    attrs = [x509.NameAttribute(NameOID.COMMON_NAME, common_name)]
    if org:
        attrs.append(x509.NameAttribute(NameOID.ORGANIZATION_NAME, org))
    if ou:
        attrs.append(x509.NameAttribute(NameOID.ORGANIZATIONAL_UNIT_NAME, ou))
    return x509.Name(attrs)


def _ski(pub) -> bytes:
    raw = pub.public_bytes(
        serialization.Encoding.X962, serialization.PublicFormat.UncompressedPoint
    )
    return hashlib.sha256(raw).digest()


class CertKeyPair:
    def __init__(self, cert: x509.Certificate, key: ec.EllipticCurvePrivateKey | None):
        self.cert = cert
        self.key = key

    @property
    def cert_pem(self) -> bytes:
        return self.cert.public_bytes(serialization.Encoding.PEM)

    @property
    def key_pem(self) -> bytes:
        assert self.key is not None
        return self.key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption(),
        )


class CA:
    """Issuing CA. `new_intermediate()` chains; `issue()` creates leaf certs
    with optional OUs (the hooks NodeOUs classification keys off)."""

    def __init__(
        self,
        common_name: str = "ca.example.com",
        org: str = "example.com",
        parent: "CA | None" = None,
        validity_days: int = 3650,
    ):
        self.key = ec.generate_private_key(ec.SECP256R1())
        self.org = org
        subject = _name(common_name, org)
        issuer = subject if parent is None else parent.cert.subject
        sign_key = self.key if parent is None else parent.key
        now = datetime.datetime.now(datetime.timezone.utc)
        builder = (
            x509.CertificateBuilder()
            .subject_name(subject)
            .issuer_name(issuer)
            .public_key(self.key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=validity_days))
            .add_extension(x509.BasicConstraints(ca=True, path_length=None), critical=True)
            .add_extension(
                x509.KeyUsage(
                    digital_signature=True, key_cert_sign=True, crl_sign=True,
                    content_commitment=False, key_encipherment=False,
                    data_encipherment=False, key_agreement=False,
                    encipher_only=False, decipher_only=False,
                ),
                critical=True,
            )
            .add_extension(x509.SubjectKeyIdentifier(_ski(self.key.public_key())), critical=False)
        )
        self.cert = builder.sign(sign_key, hashes.SHA256())
        self.parent = parent
        self._revoked: list[x509.Certificate] = []

    @property
    def cert_pem(self) -> bytes:
        return self.cert.public_bytes(serialization.Encoding.PEM)

    def new_intermediate(self, common_name: str = "ica.example.com") -> "CA":
        return CA(common_name, self.org, parent=self)

    def issue(
        self,
        common_name: str,
        ous: list[str] | None = None,
        sans: list[str] | None = None,
        client: bool = True,
        server: bool = False,
        validity_days: int = 3650,
        not_after: datetime.datetime | None = None,
    ) -> CertKeyPair:
        key = ec.generate_private_key(ec.SECP256R1())
        cert = self.issue_for_public_key(
            common_name, key.public_key(), ous=ous, sans=sans,
            client=client, server=server, validity_days=validity_days,
            not_after=not_after,
        )
        return CertKeyPair(cert, key)

    def issue_for_public_key(
        self,
        common_name: str,
        public_key,
        ous: list[str] | None = None,
        sans: list[str] | None = None,
        client: bool = True,
        server: bool = False,
        validity_days: int = 3650,
        not_after: datetime.datetime | None = None,
    ) -> "x509.Certificate":
        """Certify an EXTERNALLY-HELD key (CSR-style): the subject's
        private key never touches the CA — the enrollment path for
        custody/HSM-held keys (csp/custody.py), where key generation
        happens inside the custody boundary and only the public half
        comes out for certification."""
        now = datetime.datetime.now(datetime.timezone.utc)
        na = not_after or (now + datetime.timedelta(days=validity_days))
        nb = min(now - datetime.timedelta(minutes=5), na - datetime.timedelta(minutes=10))
        attrs = [x509.NameAttribute(NameOID.COMMON_NAME, common_name)]
        for ou in ous or []:
            attrs.append(x509.NameAttribute(NameOID.ORGANIZATIONAL_UNIT_NAME, ou))
        eku = []
        if client:
            eku.append(ExtendedKeyUsageOID.CLIENT_AUTH)
        if server:
            eku.append(ExtendedKeyUsageOID.SERVER_AUTH)
        builder = (
            x509.CertificateBuilder()
            .subject_name(x509.Name(attrs))
            .issuer_name(self.cert.subject)
            .public_key(public_key)
            .serial_number(x509.random_serial_number())
            .not_valid_before(nb)
            .not_valid_after(na)
            .add_extension(x509.BasicConstraints(ca=False, path_length=None), critical=True)
            .add_extension(
                x509.KeyUsage(
                    digital_signature=True, key_cert_sign=False, crl_sign=False,
                    content_commitment=False, key_encipherment=False,
                    data_encipherment=False, key_agreement=False,
                    encipher_only=False, decipher_only=False,
                ),
                critical=True,
            )
            .add_extension(x509.SubjectKeyIdentifier(_ski(public_key)), critical=False)
            .add_extension(
                # keyid must equal the issuer's (sha256-based) SKI —
                # OpenSSL rejects chain candidates on keyid mismatch,
                # so derive it from the CA cert's actual extension
                # rather than from_issuer_public_key's sha1 form
                x509.AuthorityKeyIdentifier.from_issuer_subject_key_identifier(
                    self.cert.extensions.get_extension_for_class(
                        x509.SubjectKeyIdentifier
                    ).value
                ),
                critical=False,
            )
        )
        if sans:
            names: list[x509.GeneralName] = []
            for s in sans:
                try:
                    names.append(x509.IPAddress(ipaddress.ip_address(s)))
                except ValueError:
                    names.append(x509.DNSName(s))
            builder = builder.add_extension(
                x509.SubjectAlternativeName(names), critical=False
            )
        if eku:
            builder = builder.add_extension(x509.ExtendedKeyUsage(eku), critical=False)
        return builder.sign(self.key, hashes.SHA256())

    # -- revocation --------------------------------------------------------

    def revoke(self, cert: x509.Certificate) -> None:
        self._revoked.append(cert)

    def gen_crl(self) -> bytes:
        """PEM CRL over everything revoked so far (reference MSPs carry PEM
        CRLs in FabricMSPConfig.revocation_list)."""
        now = datetime.datetime.now(datetime.timezone.utc)
        builder = (
            x509.CertificateRevocationListBuilder()
            .issuer_name(self.cert.subject)
            .last_update(now - datetime.timedelta(minutes=5))
            .next_update(now + datetime.timedelta(days=365))
        )
        for cert in self._revoked:
            builder = builder.add_revoked_certificate(
                x509.RevokedCertificateBuilder()
                .serial_number(cert.serial_number)
                .revocation_date(now)
                .build()
            )
        return builder.sign(self.key, hashes.SHA256()).public_bytes(
            serialization.Encoding.PEM
        )


def cert_expiration(pem: bytes) -> datetime.datetime:
    """Earliest not-after among certs in a PEM bundle (reference
    common/crypto/expiration.go warns ahead of expiry)."""
    certs = x509.load_pem_x509_certificates(pem)
    return min(c.not_valid_after_utc for c in certs)


def expiration_warning(
    pem: bytes, label: str, now: datetime.datetime | None = None,
    warn_within: datetime.timedelta = datetime.timedelta(days=7),
) -> str | None:
    """Warning text when `pem`'s earliest cert expires within
    `warn_within` (or has expired); None otherwise.  Reference
    common/crypto/expiration.go TrackExpiration, wired at node start
    (internal/peer/node/start.go:310) so operators hear about dying
    enrollment/TLS certs a week ahead instead of at outage time."""
    try:
        exp = cert_expiration(pem)
    except Exception:
        return None
    return _expiry_text(exp, label, now, warn_within)


def _expiry_text(exp, label, now=None, warn_within=datetime.timedelta(days=7)):
    now = now or datetime.datetime.now(datetime.timezone.utc)
    if exp <= now:
        return f"{label} certificate EXPIRED at {exp.isoformat()}"
    if exp - now <= warn_within:
        days = -((now - exp) // datetime.timedelta(days=1))  # ceil
        return (
            f"{label} certificate expires within "
            f"{days} day(s), at {exp.isoformat()}"
        )
    return None


def track_expiration(entries, warn) -> None:
    """Run expiration_warning over [(label, pem)] pairs, calling
    `warn(text)` for each finding — the node-start expiration sweep."""
    for label, pem in entries:
        if not pem:
            continue
        text = expiration_warning(pem, label)
        if text:
            warn(text)


def warn_node_cert_expirations(signer, tls, signer_label: str, warn) -> None:
    """The shared peer/orderer start-time sweep: week-ahead warnings for
    the node's signing identity (via its already-parsed expiry) and its
    TLS certificate (reference TrackExpiration at node start)."""
    if signer is not None and hasattr(signer, "expires_at"):
        try:
            text = _expiry_text(signer.expires_at(), signer_label)
        except Exception:
            text = None
        if text:
            warn(text)
    if tls is not None:
        track_expiration([("server TLS", tls.cert_pem)], warn)


__all__ = [
    "set_hash_backend",
    "sha256",
    "sha256_many",
    "CA",
    "CertKeyPair",
    "cert_expiration",
    "expiration_warning",
    "track_expiration",
    "warn_node_cert_expirations",
]
