"""Block validation with whole-block batched signature verification.

This is the north-star rework (BASELINE.json): the reference's
txvalidator v20 (core/committer/txvalidator/v20/validator.go:180-265)
validates each tx in its own goroutine, and every tx serially verifies
1 creator signature + K endorsement signatures through per-identity
`msp.Identity.Verify` calls.  Here validation is three phases:

  1. **Collect** (host): per-tx syntactic checks (envelope/header shape,
     channel id, tx-id binding, duplicate tx ids, proposal-hash binding —
     reference core/common/validation/msgvalidation.go:26-330), identity
     deserialization/validation, and endorsement-policy *preparation*
     (fabric_tpu.policies two-phase protocol).  No crypto.
  2. **Verify** (device): ONE `CSP.verify_batch` over every creator and
     endorsement signature of the whole block.
  3. **Finish** (host): creator mask -> BAD_CREATOR_SIGNATURE; policy
     closures over the mask -> ENDORSEMENT_POLICY_FAILURE; MVCC runs later
     in the ledger commit (kvledger).

The endorsement-policy check is dispatched through a pluggable map like
the reference's validation-plugin framework (core/handlers/validation);
the builtin plugin evaluates the channel/chaincode endorsement policy.
"""

from __future__ import annotations

import dataclasses

from fabric_tpu.protos.common import common_pb2
from fabric_tpu.protos.peer import (
    proposal_pb2,
    proposal_response_pb2,
    transaction_pb2,
)
from fabric_tpu import protoutil
from fabric_tpu.protoutil import SignedData

V = transaction_pb2


@dataclasses.dataclass
class _TxWork:
    """Per-tx deferred crypto: creator item index + policy pendings."""

    creator_item: int | None = None
    pendings: list = dataclasses.field(default_factory=list)  # (PendingEvaluation, slice)


class TxValidator:
    """Reference TxValidator.Validate equivalent; `Validate` mutates the
    block's TRANSACTIONS_FILTER metadata like the reference does."""

    def __init__(self, channel_id: str, ledger, bundle, csp, endorsement_policy=None):
        """endorsement_policy: callable(chaincode_name) -> policy object
        (two-phase protocol).  Defaults to the channel's
        /Channel/Application/Endorsement policy — the v2.0 default when a
        chaincode defines none (reference builtin v20 + lifecycle)."""
        self.channel_id = channel_id
        self._ledger = ledger
        self._bundle = bundle
        self._csp = csp
        if endorsement_policy is None:
            default_pol = bundle.policy_manager.get_policy("/Channel/Application/Endorsement")
            endorsement_policy = lambda cc: default_pol  # noqa: E731
        self._endorsement_policy = endorsement_policy

    # -- phase 1: per-tx syntactic validation + collection ----------------

    def _collect_tx(self, env_bytes: bytes, seen_txids: set, items: list, work: _TxWork) -> int:
        try:
            env = common_pb2.Envelope.FromString(env_bytes)
            if not env.payload:
                return V.NIL_ENVELOPE
            payload = common_pb2.Payload.FromString(env.payload)
            chdr = common_pb2.ChannelHeader.FromString(payload.header.channel_header)
            shdr = common_pb2.SignatureHeader.FromString(payload.header.signature_header)
        except Exception:
            return V.BAD_PAYLOAD
        if not shdr.creator or not shdr.nonce:
            return V.BAD_COMMON_HEADER
        if chdr.channel_id != self.channel_id:
            return V.BAD_CHANNEL_HEADER
        if chdr.epoch != 0:
            return V.BAD_CHANNEL_HEADER

        # creator must deserialize and be valid under a channel MSP
        try:
            creator = self._bundle.msp_manager.deserialize_identity(shdr.creator)
            self._bundle.msp_manager.validate(creator)
        except Exception:
            return V.BAD_CREATOR_SIGNATURE
        # creator signature over the payload bytes (checkSignatureFromCreator)
        work.creator_item = len(items)
        items.append(creator.verification_item(env.payload, env.signature))

        if chdr.type == common_pb2.CONFIG:
            # config txs are validated/applied by the channel config engine
            return V.VALID
        if chdr.type != common_pb2.ENDORSER_TRANSACTION:
            return V.UNKNOWN_TX_TYPE

        # tx-id binding + duplicate detection (CheckTxID + checkTxIdDupsLedger)
        if not chdr.tx_id or not protoutil.check_tx_id(chdr.tx_id, shdr.nonce, shdr.creator):
            return V.BAD_PROPOSAL_TXID
        if chdr.tx_id in seen_txids or self._ledger.tx_id_exists(chdr.tx_id):
            return V.DUPLICATE_TXID
        seen_txids.add(chdr.tx_id)

        try:
            tx = transaction_pb2.Transaction.FromString(payload.data)
            if not tx.actions:
                return V.NIL_TXACTION
            cap = transaction_pb2.ChaincodeActionPayload.FromString(tx.actions[0].payload)
            prp_bytes = cap.action.proposal_response_payload
            prp = proposal_response_pb2.ProposalResponsePayload.FromString(prp_bytes)
            action = proposal_pb2.ChaincodeAction.FromString(prp.extension)
        except Exception:
            return V.BAD_PAYLOAD
        # proposal-hash binding: endorsers signed over this exact proposal
        want = protoutil.proposal_hash(
            payload.header.channel_header,
            payload.header.signature_header,
            cap.chaincode_proposal_payload,
        )
        if prp.proposal_hash != want:
            return V.BAD_RESPONSE_PAYLOAD
        if not cap.action.endorsements:
            return V.ENDORSEMENT_POLICY_FAILURE

        # endorsement policy: each endorsement signs prp_bytes || endorser
        signed = [
            SignedData(prp_bytes + e.endorser, e.endorser, e.signature)
            for e in cap.action.endorsements
        ]
        policy = self._endorsement_policy(action.chaincode_id.name)
        pending = policy.prepare(signed)
        start = len(items)
        items.extend(pending.items)
        work.pendings.append((pending, (start, len(items))))
        return V.VALID

    # -- the three-phase validate -----------------------------------------

    def validate(self, block: common_pb2.Block) -> list[int]:
        n = len(block.data.data)
        flags = [V.NOT_VALIDATED] * n
        works = [_TxWork() for _ in range(n)]
        items: list = []
        seen_txids: set[str] = set()

        for i in range(n):
            flags[i] = self._collect_tx(block.data.data[i], seen_txids, items, works[i])

        # phase 2: one device call for the whole block
        mask = self._csp.verify_batch(items) if items else []

        # phase 3: apply per-tx results
        for i in range(n):
            if flags[i] != V.VALID:
                continue
            w = works[i]
            if w.creator_item is not None and not mask[w.creator_item]:
                flags[i] = V.BAD_CREATOR_SIGNATURE
                continue
            for pending, (start, end) in w.pendings:
                if not pending.finish(mask[start:end]):
                    flags[i] = V.ENDORSEMENT_POLICY_FAILURE
                    break
        protoutil.set_tx_filter(block, bytes(flags))
        return flags


__all__ = ["TxValidator"]
