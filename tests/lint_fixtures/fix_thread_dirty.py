"""SEEDED VIOLATION (thread-hygiene): a daemonized thread created
outside the threadwatch seam — undrainable at interpreter exit."""

import threading


def start_worker(job):
    t = threading.Thread(target=job, daemon=True)  # <- fires HERE
    t.start()
    return t
