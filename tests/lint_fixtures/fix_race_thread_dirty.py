"""SEEDED VIOLATION (racecheck): a spawned worker thread writes a
map whose other access sites all hold the owning lock — the majority
infers the guard, the thread path misses it."""

from fabric_tpu.devtools.lockwatch import named_lock, spawn_thread


class OffersCache:
    def __init__(self):
        self._lock = named_lock("fixture.offers")
        self._offers = {}

    def start(self):
        t = spawn_thread(
            target=self._refresh, name="fixture-refresh", kind="worker"
        )
        t.start()
        return t

    def _refresh(self):
        self._offers["latest"] = 1  # <- racecheck fires HERE

    def get(self, key):
        with self._lock:
            return self._offers.get(key)

    def size(self):
        with self._lock:
            return len(self._offers)
