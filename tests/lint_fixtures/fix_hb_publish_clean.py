"""Clean twin of fix_hb_publish_dirty: the lock-free worker reads are
credited by publication edges — Event set()->wait() for Feed, queue
put()->get() for Line — so neither fires even though both fields carry
an inferred lock guard the workers do not hold.  This is the v4
acceptance shape: a site v3 could only handle with a guards.py entry
or an UNKNOWN hole is now PROVEN safe."""

import queue

import threading

from fabric_tpu.devtools.lockwatch import named_lock, spawn_thread


def use(x):
    return x


class Feed:
    def __init__(self):
        self._lock = named_lock("fixture.feed")
        self._ready = threading.Event()
        self._snapshot = None
        self._thread = spawn_thread(
            target=self._consume, name="feed", kind="worker"
        )

    def start(self):
        self._thread.start()

    def refresh(self, rows):
        with self._lock:
            self._snapshot = rows
        self._ready.set()

    def peek(self):
        with self._lock:
            return self._snapshot

    def _consume(self):
        self._ready.wait()
        use(self._snapshot)  # lock-free, credited by set()->wait()


class Line:
    def __init__(self):
        self._lock = named_lock("fixture.line")
        self._q = queue.Queue()
        self._wm = 0
        self._thread = spawn_thread(
            target=self._drain, name="line", kind="worker"
        )

    def start(self):
        self._thread.start()

    def push(self, n):
        with self._lock:
            self._wm = n
        self._q.put(n)

    def watermark(self):
        with self._lock:
            return self._wm

    def _drain(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            use(self._wm)  # lock-free, credited by put()->get()
