"""Caching MSP wrapper (reference msp/cache: memoizes
DeserializeIdentity, Validate, and SatisfiesPrincipal — the second-order
perf lever under signature-heavy validation).

Wraps any object with the MSP/MSPManager surface; safe because
identities and principals are immutable once parsed and the underlying
MSP config is fixed for a Bundle's lifetime (a config update builds a
NEW bundle with fresh MSPs, so caches never go stale).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict

_DESERIALIZE_CACHE = 100
_VALIDATE_CACHE = 100
_PRINCIPAL_CACHE = 100
# validate() compares wall clock against the cert validity window, so its
# cache entries expire instead of living for the bundle's lifetime
_VALIDATE_TTL_S = 60.0


class _LRU:
    def __init__(self, cap: int):
        self._cap = cap
        self._d: OrderedDict = OrderedDict()
        self._lock = threading.Lock()

    def get(self, key):
        with self._lock:
            if key not in self._d:
                return None, False
            self._d.move_to_end(key)
            return self._d[key], True

    def put(self, key, value) -> None:
        with self._lock:
            self._d[key] = value
            self._d.move_to_end(key)
            while len(self._d) > self._cap:
                self._d.popitem(last=False)


class CachedMSP:
    """Memoizing facade over an MSP or MSPManager."""

    def __init__(
        self,
        inner,
        deserialize_cap: int = _DESERIALIZE_CACHE,
        validate_cap: int = _VALIDATE_CACHE,
        principal_cap: int = _PRINCIPAL_CACHE,
    ):
        self._inner = inner
        self._deserialize = _LRU(deserialize_cap)
        self._validate = _LRU(validate_cap)
        self._principal = _LRU(principal_cap)

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def deserialize_identity(self, serialized: bytes):
        ident, hit = self._deserialize.get(serialized)
        if hit:
            return ident
        ident = self._inner.deserialize_identity(serialized)
        self._deserialize.put(bytes(serialized), ident)
        return ident

    def validate(self, identity) -> None:
        key = identity.serialize()
        res, hit = self._validate.get(key)
        if hit:
            stamp, outcome = res
            if time.monotonic() - stamp < _VALIDATE_TTL_S:
                if isinstance(outcome, Exception):
                    raise outcome
                return
        try:
            self._inner.validate(identity)
        except Exception as exc:
            self._validate.put(key, (time.monotonic(), exc))
            raise
        self._validate.put(key, (time.monotonic(), None))

    def satisfies_principal(self, identity, principal) -> None:
        key = (identity.serialize(), principal.SerializeToString())
        res, hit = self._principal.get(key)
        if hit:
            if isinstance(res, Exception):
                raise res
            return
        try:
            self._inner.satisfies_principal(identity, principal)
        except Exception as exc:
            self._principal.put(key, exc)
            raise
        self._principal.put(key, None)


__all__ = ["CachedMSP"]
