"""Fused Pallas TPU kernel for the batched idemix Schnorr MSMs (BN254 G1).

The XLA ladder in `bn254_batch.py` is HBM-bound the same way `ec.py`'s
was: every field multiplication round-trips (B, ~600)-wide limb-product
intermediates through HBM, so the whole 64-window ladder runs ~100x
slower than its arithmetic (scripts/bench_fieldops.py measures a
point-add at ~25 us/1024 lanes; the ladder pays ~1.4 s).  This kernel is
the `pallas_ec.py` treatment for BN254: the entire joint T1/T2/T3 ladder
stays resident in VMEM — inputs stream in once, nine coordinates stream
out.

What differs from the P-256 kernel:

* **Montgomery REDC instead of Solinas.**  BN254's p is not a Solinas
  prime, so products reduce on the R = 2^272 word boundary (the same
  form as limbs.MontMod; coordinates arrive from the host already in
  Montgomery form x·R mod p): t = (T + ((T·m' mod R)·m)) / R — two
  extra schoolbook multiplies and one carry resolve, no fold chains.
  add/sub/mul_const keep the < 2^257 invariant with a SINGLE top-limb
  fold (2^256 mod p ~ 2^251.8 is small, unlike the near-m fold rows
  that make limbs.Mod's generic product chains slow); bound calculus in
  FpBN254.
* **One unified Jacobian table stack, rolled term loop.**  All bases —
  the issuer-key shared points (broadcast over lanes with z = R mod p)
  and the four per-lane points (a', a_bar, b', nym; one 14-step
  mixed-add chain builds all four tables at once) — live in one
  (n_tables*16, 17, BLK) VMEM scratch.  The per-window term loop is a
  lax.fori_loop whose body is ONE full Jacobian add with pl.ds table
  and accumulator indexing: graph size stays ~one-point-add regardless
  of attribute count (an unrolled-terms variant exceeded 10^5 HLO ops
  and did not compile in useful time), while VMEM residency keeps the
  runtime compute-bound.
* **a = 0 curve formulas** (y² = x³ + 3, dbl-2009-l), limb axis at -2
  so the table chain (4, 17, BLK) and the three ladder accumulators
  (3, 17, BLK) vectorize over a leading batch axis.

Parity: tests/test_pallas_bn254.py checks bit-for-bit agreement with the
host path (idemix/schnorr.py) through schnorr_commitments_batch.
Reference baseline being replaced: the per-signature AMCL G1 scalar
multiplications of idemix Ver (/root/reference/idemix/signature.go:243,
290-291 via math/amcl FP256BN).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from fabric_tpu.csp.tpu import limbs
from fabric_tpu.csp.tpu import bn254_batch as _xla_engine
from fabric_tpu.csp.tpu.limbs import LIMB_BITS, MASK, WIDE, int_to_limbs
from fabric_tpu.idemix import bn254 as bn

BLK = 128  # lanes (signatures) per grid block
# window geometry and lane-base order are the XLA engine's — the two
# engines must agree bit-for-bit on the digit recoding and term layout
NWINDOWS = _xla_engine.NWINDOWS
TABLE = _xla_engine.TABLE
N_LANE_BASES = len(_xla_engine.LANE_BASES)  # a', a_bar, b', nym


@functools.lru_cache(maxsize=None)
def _consts():
    ctx = limbs.mont_ctx(bn.P)
    return dict(
        m=int_to_limbs(bn.P, WIDE).astype(np.uint32)[:, None],
        mp=ctx.m_prime_limbs.astype(np.uint32)[:, None],
        one=ctx._one.astype(np.uint32)[:, None],  # R mod p
        sub_c=ctx.sub_c.astype(np.uint32)[:, None],
        # 2^256 mod p ~ 2^251.8 (2^256 - 5p): small enough that ONE
        # top-limb fold restores the < 2^257 invariant after add/sub
        r256=int_to_limbs((1 << 256) % bn.P, WIDE - 1).astype(
            np.uint32
        )[:, None],
    )


# ---------------------------------------------------------------------------
# Carry machinery on (..., 17, LANES) uint32 — limb axis at -2 (the
# pallas_ec helpers pin it to axis 0; here a leading batch axis carries
# the stacked bases/accumulators).
# ---------------------------------------------------------------------------


def _shift_up(a, d: int):
    """result[..., i, :] = a[..., i-d, :], zero filled."""
    if d == 0:
        return a
    pad = [(0, 0)] * (a.ndim - 2) + [(d, 0), (0, 0)]
    keep = a[..., : a.shape[-2] - d, :] if d < a.shape[-2] else a[..., :0, :]
    return jnp.pad(keep, pad)


def _grow(v, width: int):
    if v.shape[-2] < width:
        pad = [(0, 0)] * (v.ndim - 2) + [(0, width - v.shape[-2]), (0, 0)]
        v = jnp.pad(v, pad)
    return v


def _coarse(v, width: int):
    """One carry pass: limbs < 2^31 in, limbs <= 2^16 + small out.
    Value-preserving except for the (dropped) carry out of the top limb."""
    v = _grow(v, width)
    one = jnp.uint32(LIMB_BITS)
    m = jnp.uint32(MASK)
    return (v & m) + _shift_up(v >> one, 1)


def _resolve(v, width: int):
    """Exact carry resolution to canonical 16-bit limbs (Kogge-Stone,
    see limbs.resolve); caller guarantees value < 2^(16*width)."""
    v = _grow(v, width)
    one = jnp.uint32(LIMB_BITS)
    m = jnp.uint32(MASK)
    c = v >> one
    v = (v & m) + _shift_up(c, 1)
    c = v >> one
    v = (v & m) + _shift_up(c, 1)
    g = (v >> one).astype(jnp.uint32)
    lo = v & m
    pprop = (lo == m).astype(jnp.uint32)
    d = 1
    while d < width:
        g = g | (pprop & _shift_up(g, d))
        pprop = pprop & _shift_up(pprop, d)
        d *= 2
    return (lo + _shift_up(g, 1)) & m


# ---------------------------------------------------------------------------
# Montgomery field ops; elements are (..., 17, LANES) uint32.
# ---------------------------------------------------------------------------


def _mul_cols(a, b, width: int):
    """Schoolbook product columns 0..width-1 of a x b, coarse limbs out
    (<= 2^16 + 2^6).  Limb bounds: every product must stay below 2^32 —
    canonical x canonical, or double-coarse (<= 2^16 + 1) x canonical
    ((2^16+1)(2^16-1) = 2^32 - 1).  Dropping columns >= width is exact
    truncation mod 2^(16*width)."""
    na = a.shape[-2]
    nb = b.shape[-2]
    prod = a[..., :, None, :] * b[..., None, :, :]  # (..., na, nb, L)
    plo = prod & jnp.uint32(MASK)
    phi = prod >> jnp.uint32(LIMB_BITS)
    zrow = jnp.zeros(plo.shape[:-3] + (1,) + plo.shape[-1:], jnp.uint32)
    parts = []
    for i in range(na):
        # row i contributes at columns i..i+nb (lo at +0, hi at +1)
        row = jnp.concatenate([plo[..., i, :, :], zrow], axis=-2)
        row = row + jnp.concatenate([zrow, phi[..., i, :, :]], axis=-2)
        lo_col, hi_col = i, min(i + nb + 1, width)
        if lo_col >= width:
            continue
        row = row[..., : hi_col - lo_col, :]
        parts.append(jnp.pad(
            row,
            [(0, 0)] * (row.ndim - 2)
            + [(lo_col, width - hi_col), (0, 0)],
        ))
    while len(parts) > 1:
        parts = [
            parts[k] + parts[k + 1] if k + 1 < len(parts) else parts[k]
            for k in range(0, len(parts), 2)
        ]
    return _coarse(parts[0], width)


class FpBN254:
    """Montgomery field ops mod BN254 p on (..., 17, LANES) uint32, all
    preserving the shared lazy invariant value < 2^257.

    Bound calculus: mul/sqr outputs are < 1.01m + 2^242 < 2m (REDC of a
    T < 2^514 product — inputs < 2^257 keep T far under the m*R ~
    2^525.6 precondition).  add/sub/mul_const resolve limbs, then fold
    the top limb once through r256 = 2^256 mod p: r256 ~ 2^251.8 is
    small (2^256 - 5p), so a single fold of any value < 2^261 lands
    under 2^256 + 32*2^251.8 < 2^257.  The invariant in turn keeps the
    relaxed-subtraction constant limbwise dominant (its top limb is 7;
    invariant operands have top limb <= 1) — an earlier no-reduction
    variant let sub's subtrahend reach top limb ~2^6 and underflowed
    exactly there.  Limb bounds: every op output is canonical; REDC's
    internal T_lo and u take one extra coarse pass to <= 2^16 + 1
    before multiplying a canonical constant (products <= 2^32 - 1,
    exact in u32); the top-limb fold multiplies a coarse top limb
    (<= 32 for every caller) into canonical r256 limbs (< 2^21)."""

    def __init__(self, m, mp, one, sub_c, r256):
        self.m_limbs = m          # (17, 1) canonical p
        self.mp_limbs = mp        # (17, 1) -p^-1 mod 2^272
        self.one_limbs = one      # (17, 1) R mod p (Montgomery 1)
        self.sub_c = sub_c        # (17, 1) relaxed multiple of p
        self.r256 = r256          # (16, 1) 2^256 mod p

    def one(self, shape_like):
        return jnp.broadcast_to(self.one_limbs, shape_like.shape)

    def _redc(self, t_cols):
        """Coarse product columns (value < m*R) -> element < 1.1m with
        canonical limbs: t = (T + (T*m' mod R)*m) / R.  The division is
        exact — after full carry resolution the low 17 limbs of the sum
        are identically zero — so it is a slice."""
        t_lo = _coarse(t_cols[..., :WIDE, :], WIDE)  # limbs <= 2^16+1
        u = _coarse(_mul_cols(t_lo, self.mp_limbs, WIDE), WIDE)
        v = _mul_cols(u, self.m_limbs, 2 * WIDE)
        w = 2 * WIDE + 1
        s = _resolve(_grow(t_cols, w) + _grow(v, w), w)
        return s[..., WIDE:2 * WIDE, :]

    def mul(self, a, b):
        return self._redc(_mul_cols(a, b, 2 * WIDE))

    def sqr(self, a):
        return self.mul(a, a)

    def _fold_resolve(self, s):
        """Coarse 17-row value (top limb <= 32) -> canonical invariant
        element: fold the top limb through r256, resolve carries."""
        t = s[..., :WIDE - 1, :] + s[..., WIDE - 1:WIDE, :] * self.r256
        return _resolve(t, WIDE)

    def add(self, a, b):
        # a + b < 2^258: coarse top limb <= 3
        return self._fold_resolve(_coarse(a + b, WIDE))

    def sub(self, a, b):
        # a + (C - b), C a relaxed multiple of p (~2^259) limbwise
        # dominating any invariant b; coarse top limb <= 10
        return self._fold_resolve(_coarse(a + (self.sub_c - b), WIDE))

    def mul_const(self, a, k: int):
        # a*k < 2^260 for k <= 8: coarse top limb <= 17
        assert 0 < k <= 8
        return self._fold_resolve(_coarse(a * jnp.uint32(k), WIDE))

    def is_zero(self, a):
        # REDC(a) lands in [0, 1.1m) and is ≡ a*R^-1 (mod p): a ≡ 0 iff
        # the residue is exactly 0 or exactly p — two limbwise compares.
        # int32 0/1 flags (Mosaic handles i1 vectors poorly).
        r = self._redc(_grow(a, 2 * WIDE))

        def mism(c):
            return jnp.sum(
                (r != c).astype(jnp.int32), axis=-2, keepdims=True
            )

        n = mism(jnp.zeros_like(r)) * mism(self.m_limbs)
        return (n == 0).astype(jnp.int32)

    def canon(self, a):
        # one mont-mul by the form's 1 preserves value and lands < 1.1m;
        # a single conditional subtract of p finishes
        v = self.mul(a, jnp.broadcast_to(self.one_limbs, a.shape))
        return self._cond_sub_m(v)

    def _cond_sub_m(self, a):
        notb = jnp.uint32(MASK) - self.m_limbs
        one_row = jnp.concatenate(
            [jnp.ones_like(a[..., :1, :]), jnp.zeros_like(a[..., 1:, :])],
            axis=-2,
        )
        t = _resolve(a + notb + one_row, WIDE + 1)
        ge = (t[..., WIDE:WIDE + 1, :] > 0).astype(jnp.int32)
        return _sel(ge, t[..., :WIDE, :], a)


# ---------------------------------------------------------------------------
# Selection + a = 0 point formulas; int32 0/1 flags shaped (..., 1, L).
# ---------------------------------------------------------------------------


def _sel(c, a, b):
    mask = (-c).astype(jnp.uint32)  # 0 or 0xffffffff, broadcasts on -2
    return b ^ ((a ^ b) & mask)


def _fsel(c, a, b):
    return b + (a - b) * c


def _pt_sel(c, p1, p2):
    return (
        _sel(c, p1[0], p2[0]),
        _sel(c, p1[1], p2[1]),
        _sel(c, p1[2], p2[2]),
        _fsel(c, p1[3], p2[3]),
    )


def _dbl_a0(fp, p):
    """dbl-2009-l for a = 0 (BN254: y² = x³ + 3)."""
    x, y, z, inf = p
    a = fp.sqr(x)
    b = fp.sqr(y)
    c = fp.sqr(b)
    d_inner = fp.sqr(fp.add(x, b))
    d = fp.mul_const(fp.sub(fp.sub(d_inner, a), c), 2)
    e = fp.mul_const(a, 3)
    f = fp.sqr(e)
    x3 = fp.sub(f, fp.add(d, d))
    y3 = fp.sub(fp.mul(e, fp.sub(d, x3)), fp.mul_const(c, 8))
    z3 = fp.mul_const(fp.mul(y, z), 2)
    return (x3, y3, z3, inf)


def _add_full(fp, p1, p2):
    """add-2007-bl with degenerate handling; equal points fall back to
    the a=0 doubling, opposites to infinity, identities pass through."""
    x1, y1, z1, inf1 = p1
    x2, y2, z2, inf2 = p2
    z1z1 = fp.sqr(z1)
    z2z2 = fp.sqr(z2)
    u1 = fp.mul(x1, z2z2)
    u2 = fp.mul(x2, z1z1)
    s1 = fp.mul(fp.mul(y1, z2), z2z2)
    s2 = fp.mul(fp.mul(y2, z1), z1z1)
    h = fp.sub(u2, u1)
    rr = fp.sub(s2, s1)
    h_zero = fp.is_zero(h)
    r_zero = fp.is_zero(rr)
    i = fp.sqr(fp.add(h, h))
    j = fp.mul(h, i)
    rr2 = fp.add(rr, rr)
    v = fp.mul(u1, i)
    x3 = fp.sub(fp.sub(fp.sqr(rr2), j), fp.add(v, v))
    t = fp.mul(s1, j)
    y3 = fp.sub(fp.mul(rr2, fp.sub(v, x3)), fp.add(t, t))
    z3 = fp.mul(fp.sub(fp.sub(fp.sqr(fp.add(z1, z2)), z1z1), z2z2), h)
    fin = jnp.zeros_like(inf1)
    out = (x3, y3, z3, fin)
    out = _pt_sel(h_zero * r_zero, _dbl_a0(fp, p1), out)
    out = (out[0], out[1], out[2],
           jnp.maximum(out[3], h_zero * (1 - r_zero)))
    out = _pt_sel(inf2, p1, out)
    out = _pt_sel(inf1, p2, out)
    return out


def _add_mixed(fp, p1, a2):
    """madd-2007-bl, second operand affine with z = one (Montgomery 1);
    used only for the per-lane window-table build chain."""
    x1, y1, z1, inf1 = p1
    ax, ay, ainf = a2
    z1z1 = fp.sqr(z1)
    u2 = fp.mul(ax, z1z1)
    s2 = fp.mul(fp.mul(ay, z1), z1z1)
    h = fp.sub(u2, x1)
    rr = fp.sub(s2, y1)
    h_zero = fp.is_zero(h)
    r_zero = fp.is_zero(rr)
    hh = fp.sqr(h)
    i = fp.mul_const(hh, 4)
    j = fp.mul(h, i)
    rr2 = fp.add(rr, rr)
    v = fp.mul(x1, i)
    x3 = fp.sub(fp.sub(fp.sqr(rr2), j), fp.add(v, v))
    t = fp.mul(y1, j)
    y3 = fp.sub(fp.mul(rr2, fp.sub(v, x3)), fp.add(t, t))
    z3 = fp.sub(fp.sub(fp.sqr(fp.add(z1, h)), z1z1), hh)
    fin = jnp.zeros_like(inf1)
    out = (x3, y3, z3, fin)
    out = _pt_sel(h_zero * r_zero, _dbl_a0(fp, p1), out)
    out = (out[0], out[1], out[2],
           jnp.maximum(out[3], h_zero * (1 - r_zero)))
    a2j = (ax, ay, fp.one(ax), ainf)
    out = _pt_sel(ainf, p1, out)
    out = _pt_sel(inf1, a2j, out)
    return out


# ---------------------------------------------------------------------------
# The kernel.
# ---------------------------------------------------------------------------


def _unpack_rows(w):
    """(..., 8, X) uint32 32-bit words -> (..., 17, X) canonical limbs
    (inputs are canonical field elements < 2^256: top limb 0)."""
    rows = []
    for i in range(8):
        rows.append(w[..., i:i + 1, :] & jnp.uint32(MASK))
        rows.append(w[..., i:i + 1, :] >> jnp.uint32(LIMB_BITS))
    rows.append(jnp.zeros_like(rows[0]))
    return jnp.concatenate(rows, axis=-2)


def _onehot(digit, blk):
    """digit (1, BLK) int32 -> (16, BLK) int32 one-hot."""
    t = jax.lax.broadcasted_iota(jnp.int32, (TABLE, blk), 0)
    return (t == digit).astype(jnp.int32)


def _isum(mask_i32, tab_u32):
    """One-hot select over the table-entry axis (-3), int32-exact
    (limbs < 2^16)."""
    return jnp.sum(
        mask_i32 * tab_u32.astype(jnp.int32), axis=-3
    ).astype(jnp.uint32)


def _make_kernel(n_terms: int, n_tables: int):
    def kernel(lanes_ref, laneinf_ref, digits_ref, termmeta_ref,
               sx_ref, sy_ref, sz_ref, sinf_ref,
               m_ref, mp_ref, one_ref, subc_ref, r256_ref, out_ref,
               tabx, taby, tabz, tabinf,
               accx, accy, accz, accinf):
        fp = FpBN254(
            m_ref[:], mp_ref[:], one_ref[:], subc_ref[:], r256_ref[:]
        )
        blk = laneinf_ref.shape[-1]
        n_shared = n_tables - N_LANE_BASES

        # -- shared-base tables: broadcast over lanes into the unified
        # scratch (z = Montgomery 1 everywhere; entry 0 carries inf=1
        # via sinf and is never read through z) --
        tabx[: n_shared * TABLE] = jnp.broadcast_to(
            sx_ref[:][:, :, None], (n_shared * TABLE, WIDE, blk)
        )
        taby[: n_shared * TABLE] = jnp.broadcast_to(
            sy_ref[:][:, :, None], (n_shared * TABLE, WIDE, blk)
        )
        tabz[: n_shared * TABLE] = jnp.broadcast_to(
            sz_ref[:][:, :, None], (n_shared * TABLE, WIDE, blk)
        )
        tabinf[: n_shared * TABLE] = jnp.broadcast_to(
            sinf_ref[:], (n_shared * TABLE, blk)
        )

        # -- per-lane points: 4 bases stacked on a leading axis
        # (static row slices, base-major x-then-y word planes) --
        px = jnp.stack([
            _unpack_rows(lanes_ref[2 * b4 * 8:(2 * b4 + 1) * 8])
            for b4 in range(N_LANE_BASES)
        ])
        py = jnp.stack([
            _unpack_rows(lanes_ref[(2 * b4 + 1) * 8:(2 * b4 + 2) * 8])
            for b4 in range(N_LANE_BASES)
        ])
        pinf = laneinf_ref[:][:, None, :].astype(jnp.int32)  # (4, 1, BLK)

        # -- per-lane Jacobian tables: one 14-step mixed-add chain
        # builds all four bases' windows at once --
        base0 = n_shared * TABLE
        zero4 = jnp.zeros((N_LANE_BASES, WIDE, blk), jnp.uint32)
        one4 = jnp.broadcast_to(one_ref[:], (N_LANE_BASES, WIDE, blk))

        def write_entry(i, pt):
            for b4 in range(N_LANE_BASES):
                r = pl.ds(base0 + b4 * TABLE + i, 1)
                tabx[r] = pt[0][b4][None]
                taby[r] = pt[1][b4][None]
                tabz[r] = pt[2][b4][None]
                tabinf[r] = pt[3][b4].astype(jnp.uint32)

        write_entry(0, (zero4, zero4, zero4, jnp.ones_like(pinf)))
        write_entry(1, (px, py, one4, pinf))
        q_aff = (px, py, pinf)

        def build(i, carry):
            nxt = _add_mixed(fp, carry, q_aff)
            write_entry(i, nxt)
            return nxt

        jax.lax.fori_loop(2, TABLE, build, (px, py, one4, pinf))

        # -- accumulators in scratch: (3, 17, BLK) + (3, BLK) inf --
        accx[:] = jnp.zeros((4, WIDE, blk), jnp.uint32)
        accy[:] = jnp.zeros((4, WIDE, blk), jnp.uint32)
        accz[:] = jnp.zeros((4, WIDE, blk), jnp.uint32)
        accinf[:] = jnp.ones((4, blk), jnp.uint32)

        # -- 64-window joint ladder, MSB first ------------------------
        def term_step(t, w):
            meta = termmeta_ref[pl.ds(t, 1)]  # (1, 2): [table, acc]
            ti = meta[0, 0]
            ai = meta[0, 1]
            word = digits_ref[pl.ds(t * 8 + w // 8, 1)]
            shift = jnp.uint32(4) * (w % 8).astype(jnp.uint32)
            dig = ((word >> shift) & jnp.uint32(0xF)).astype(jnp.int32)
            oh = _onehot(dig, blk)[:, None, :]  # (16, 1, BLK)
            ts = pl.ds(ti * TABLE, TABLE)
            q = (
                _isum(oh, tabx[ts]),
                _isum(oh, taby[ts]),
                _isum(oh, tabz[ts]),
                jnp.sum(
                    oh[:, 0, :] * tabinf[ts].astype(jnp.int32),
                    axis=0, keepdims=True,
                ),
            )
            ar = pl.ds(ai, 1)
            cur = (
                accx[ar][0], accy[ar][0], accz[ar][0],
                accinf[ar].astype(jnp.int32),
            )
            new = _add_full(fp, cur, q)
            accx[ar] = new[0][None]
            accy[ar] = new[1][None]
            accz[ar] = new[2][None]
            accinf[ar] = new[3].astype(jnp.uint32)
            return w

        def window(w, _):
            st = (
                accx[0:3], accy[0:3], accz[0:3],
                accinf[0:3][:, None, :].astype(jnp.int32),
            )
            for _i in range(4):
                st = _dbl_a0(fp, st)
            accx[0:3] = st[0]
            accy[0:3] = st[1]
            accz[0:3] = st[2]
            accinf[0:3] = st[3][:, 0, :].astype(jnp.uint32)
            jax.lax.fori_loop(0, n_terms, term_step, w)
            return 0

        jax.lax.fori_loop(0, NWINDOWS, window, 0)

        # canonical Montgomery residues: one canon over all 9 coords
        coords = jnp.concatenate(
            [accx[0:3], accy[0:3], accz[0:3]], axis=0
        )  # (9, 17, BLK): rows 0-2 x, 3-5 y, 6-8 z of T1..T3
        can = fp.canon(coords)
        infrow = jnp.concatenate(
            [accinf[0:3], jnp.zeros((WIDE - 3, blk), jnp.uint32)], axis=0
        )[None]  # (1, 17, BLK), acc t's flag in limb row t
        out_ref[:] = jnp.concatenate([can, infrow], axis=0)[None]

    return kernel


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


@functools.lru_cache(maxsize=None)
def _build_call(nblocks: int, blk: int, n_terms: int, n_tables: int,
                interpret: bool):
    lane_spec = lambda rows: pl.BlockSpec(  # noqa: E731
        (rows, blk), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    const_spec = lambda shape: pl.BlockSpec(  # noqa: E731
        shape, lambda i: tuple(0 for _ in shape), memory_space=pltpu.VMEM
    )
    n_shared = n_tables - N_LANE_BASES
    fn = pl.pallas_call(
        _make_kernel(n_terms, n_tables),
        in_specs=[
            lane_spec(2 * N_LANE_BASES * 8),       # packed lane coords
            lane_spec(N_LANE_BASES),               # lane inf flags
            lane_spec(n_terms * 8),                # packed digits
            const_spec((n_terms, 2)),              # (table, acc) per term
            const_spec((n_shared * TABLE, WIDE)),  # shared x limbs
            const_spec((n_shared * TABLE, WIDE)),  # shared y limbs
            const_spec((n_shared * TABLE, WIDE)),  # shared z limbs
            const_spec((n_shared * TABLE, 1)),     # shared inf
            const_spec((WIDE, 1)),                 # p
            const_spec((WIDE, 1)),                 # m' = -p^-1 mod R
            const_spec((WIDE, 1)),                 # R mod p
            const_spec((WIDE, 1)),                 # sub_c
            const_spec((WIDE - 1, 1)),             # 2^256 mod p
        ],
        grid=(nblocks,),
        out_specs=pl.BlockSpec(
            (1, 10, WIDE, blk), lambda i: (i, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((nblocks, 10, WIDE, blk), jnp.uint32),
        scratch_shapes=[
            pltpu.VMEM((n_tables * TABLE, WIDE, blk), jnp.uint32),  # tabx
            pltpu.VMEM((n_tables * TABLE, WIDE, blk), jnp.uint32),  # taby
            pltpu.VMEM((n_tables * TABLE, WIDE, blk), jnp.uint32),  # tabz
            pltpu.VMEM((n_tables * TABLE, blk), jnp.uint32),        # tabinf
            pltpu.VMEM((4, WIDE, blk), jnp.uint32),                 # accx
            pltpu.VMEM((4, WIDE, blk), jnp.uint32),                 # accy
            pltpu.VMEM((4, WIDE, blk), jnp.uint32),                 # accz
            pltpu.VMEM((4, blk), jnp.uint32),                       # accinf
        ],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
        interpret=interpret,
    )
    return jax.jit(fn)


# ---------------------------------------------------------------------------
# Host packing.
# ---------------------------------------------------------------------------


def _words_from_ints(vals: list[int]) -> np.ndarray:
    """Canonical ints < 2^256 -> (8, B) uint32 little-endian words."""
    buf = bytearray(32 * len(vals))
    for i, v in enumerate(vals):
        buf[32 * i:32 * i + 32] = v.to_bytes(32, "little")
    return np.ascontiguousarray(
        np.frombuffer(bytes(buf), np.uint32).reshape(len(vals), 8).T
    )


def _digits_from_ints(vals: list[int]) -> np.ndarray:
    """Scalars < 2^256 -> (8, B) uint32: 64 MSB-first 4-bit window
    digits, 8 per word (digit k in bits 4*(k%8) of word k//8) — the
    same recoding as bn254_batch._recode, packed."""
    n = len(vals)
    buf = bytearray(32 * n)
    for i, v in enumerate(vals):
        buf[32 * i:32 * i + 32] = v.to_bytes(32, "little")
    u8 = np.frombuffer(bytes(buf), np.uint8).reshape(n, 32)
    nibbles = np.empty((n, 64), np.uint32)
    nibbles[:, 0::2] = u8 & 0xF
    nibbles[:, 1::2] = u8 >> 4
    d = nibbles[:, ::-1]  # digit k = nibble 63-k (MSB first)
    shifts = (np.uint32(4) * np.arange(8, dtype=np.uint32))[None, None]
    return np.ascontiguousarray(
        (d.reshape(n, 8, 8) << shifts).sum(axis=2, dtype=np.uint32).T
    )


@functools.lru_cache(maxsize=8)
def _shared_limbs(ipk_key: tuple) -> tuple:
    """Shared-base affine window tables in Montgomery form:
    (x, y, z (n_shared*16, 17), inf (n_shared*16, 1)); z is the
    Montgomery 1 on finite entries.  The raw small multiples come from
    the engine-shared cache (bn254_batch.shared_multiples) so the host
    scalar multiplications are done once per issuer key, not once per
    engine."""
    from fabric_tpu.csp.tpu.bn254_batch import shared_multiples

    ctx = limbs.mont_ctx(bn.P)
    one = int_to_limbs(ctx.one_int, WIDE)
    zero = int_to_limbs(0, WIDE)
    xs, ys, zs, infs = [], [], [], []
    for row in shared_multiples(ipk_key):
        for q in row:
            if q is None:
                xs.append(zero)
                ys.append(zero)
                zs.append(zero)
                infs.append(1)
            else:
                xs.append(int_to_limbs(ctx.to_mont_int(q[0]), WIDE))
                ys.append(int_to_limbs(ctx.to_mont_int(q[1]), WIDE))
                zs.append(one)
                infs.append(0)
    return (
        np.stack(xs).astype(np.uint32),
        np.stack(ys).astype(np.uint32),
        np.stack(zs).astype(np.uint32),
        np.asarray(infs, np.uint32)[:, None],
    )


def commitments(lane_pts, scalars, ok, term_table, term_acc, shared_pts,
                blk: int = BLK, interpret: bool | None = None):
    """Run the ladder for a prepared batch.

    lane_pts: per-sig tuple of 4 affine int points (or None); scalars:
    per-sig list of n_terms ints (None when not ok); ok: per-sig
    validity (bad lanes run with zero scalars and infinity bases).
    Returns per-sig [(x, y, z, inf)] * 3 Jacobian ints (plain form)."""
    if interpret is None:
        interpret = _use_interpret()
    n = len(lane_pts)
    n_terms = len(term_table)
    n_shared = len(shared_pts)
    n_tables = n_shared + N_LANE_BASES
    nb = -(-n // blk)
    while nb & (nb - 1):  # power-of-two blocks: one compile per
        nb += 1           # (nblocks, n_attrs) pair
    padded = nb * blk
    ctx = limbs.mont_ctx(bn.P)

    coords: list[list[int]] = [[] for _ in range(2 * N_LANE_BASES)]
    laneinf = np.ones((N_LANE_BASES, padded), np.uint32)
    digit_ints: list[list[int]] = [[] for _ in range(n_terms)]
    for j in range(padded):
        good = j < n and ok[j]
        pts = lane_pts[j] if good else (None,) * N_LANE_BASES
        sc = scalars[j] if good else [0] * n_terms
        for b4 in range(N_LANE_BASES):
            p = pts[b4]
            if p is None:
                coords[2 * b4].append(0)
                coords[2 * b4 + 1].append(0)
            else:
                coords[2 * b4].append(ctx.to_mont_int(p[0]))
                coords[2 * b4 + 1].append(ctx.to_mont_int(p[1]))
                laneinf[b4, j] = 0
        for t in range(n_terms):
            digit_ints[t].append(sc[t])

    # lane coord plane order matches the kernel's reshape: base-major,
    # x words then y words
    lanes = np.concatenate(
        [_words_from_ints(coords[c]) for c in range(2 * N_LANE_BASES)],
        axis=0,
    )  # (64, padded)
    digits = np.concatenate(
        [_digits_from_ints(d) for d in digit_ints], axis=0
    )  # (n_terms*8, padded)
    termmeta = np.stack(
        [
            np.asarray(term_table, np.int32),
            np.asarray(term_acc, np.int32),
        ],
        axis=1,
    )  # (n_terms, 2)
    sxl, syl, szl, sinf = _shared_limbs(tuple(shared_pts))
    c = _consts()
    call = _build_call(nb, blk, n_terms, n_tables, bool(interpret))
    out = np.asarray(call(
        lanes, laneinf, digits, termmeta, sxl, syl, szl, sinf,
        c["m"], c["mp"], c["one"], c["sub_c"], c["r256"],
    ))  # (nb, 10, 17, blk)

    results = []
    for j in range(n):
        b_i, l_i = divmod(j, blk)
        tri = []
        for t in range(3):
            x = ctx.from_mont_int(limbs.limbs_to_int(out[b_i, t, :, l_i]))
            y = ctx.from_mont_int(
                limbs.limbs_to_int(out[b_i, 3 + t, :, l_i])
            )
            z = ctx.from_mont_int(
                limbs.limbs_to_int(out[b_i, 6 + t, :, l_i])
            )
            inf = bool(out[b_i, 9, t, l_i])
            tri.append((x, y, z, inf))
        results.append(tri)
    return results


__all__ = ["commitments", "FpBN254", "BLK"]
