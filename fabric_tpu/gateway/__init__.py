"""Gateway: the pipelined submission front-end for the ordering
service (reference gateway/gateway.go + gateway/api — the Fabric
Gateway service that fronts broadcast/deliver for SDK clients).

Many concurrent clients multiplex onto a small number of pipelined
broadcast streams to the orderer cluster; the gateway dedups txids,
applies bounded admission with backpressure, fails over between
orderers deterministically (resubmitting in-flight envelopes), and
tails blocks through the deliver client to resolve every accepted tx
to a definitive VALID/INVALID/TIMEOUT status (`submit_and_wait`, the
reference's SubmitTransaction+CommitStatus in one call)."""

from fabric_tpu.gateway.core import (  # noqa: F401
    Gateway,
    SubmitResult,
    STATUS_PENDING,
    STATUS_VALID,
    STATUS_INVALID,
    STATUS_TIMEOUT,
)
