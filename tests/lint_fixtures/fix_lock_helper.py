"""Helper half of the cross-module lock fixture: performs blocking I/O.
Blocking is fine on its own — the violation is REACHING it under the
commit lock."""

import os


def persist(fd: int) -> None:
    os.fsync(fd)
