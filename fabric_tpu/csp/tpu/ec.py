"""Batched ECDSA-P256 verification on TPU.

Replaces the reference's per-signature `ecdsa.Verify` hot loop
(bccsp/sw/ecdsa.go:41-57, fanned out per tx/endorsement by
core/committer/txvalidator/v20/validator.go goroutines) with one jitted XLA
program that verifies an entire block's signatures at once — the
"embarrassingly batchable" rework called out in SURVEY.md §3.4.

TPU-first design:

* All signatures in the batch advance in lockstep through a fixed
  64-window (4-bit) joint Shamir ladder ``R = u1*G + u2*Q``: a
  `lax.scan` over windows, `lax.fori_loop` over the 4 doublings —
  static shapes, no data-dependent branching, pure VPU work on the
  limb representation from `limbs.py`.
* Exception/degenerate cases (point at infinity, equal/opposite addends)
  are handled with per-lane boolean flags + `jnp.where` selects, never
  host branches, so one adversarial signature cannot desynchronize the
  batch (SURVEY.md §7 hard part (4): per-signature failure semantics).
* The final affine check avoids modular inversion entirely: instead of
  x(R) = X/Z^2 mod p == r mod n, it checks X == c*Z^2 (mod p) for each
  admissible candidate c in {r, r+n} (r+n only when < p).
* Host does only O(1)-per-item scalar work: DER parse, range/low-S
  checks, u1/u2 = e*s^-1, r*s^-1 mod n, and window-digit recoding.

Parity oracle: fabric_tpu.csp.sw (OpenSSL), tested on NIST/Wycheproof-style
edge cases in tests/test_ec.py / tests/test_csp_tpu.py.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

from fabric_tpu.csp.api import P256_GX, P256_GY, P256_N, P256_P
from fabric_tpu.csp.tpu import limbs
from fabric_tpu.csp.tpu.limbs import WIDE, ints_to_limbs, mod_ctx

WINDOW_BITS = 4
NWINDOWS = 64  # 256 / 4
TABLE = 1 << WINDOW_BITS


# ---------------------------------------------------------------------------
# Host-side affine P-256 (python ints) — used only to precompute the fixed
# G window table and in tests as a reference; never on the hot path.
# ---------------------------------------------------------------------------


def affine_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P256_P == 0:
            return None
        lam = (3 * x1 * x1 - 3) * pow(2 * y1, -1, P256_P) % P256_P
    else:
        lam = (y2 - y1) * pow(x2 - x1, -1, P256_P) % P256_P
    x3 = (lam * lam - x1 - x2) % P256_P
    y3 = (lam * (x1 - x3) - y1) % P256_P
    return (x3, y3)


def affine_mul(k: int, p):
    acc = None
    while k:
        if k & 1:
            acc = affine_add(acc, p)
        p = affine_add(p, p)
        k >>= 1
    return acc


@functools.lru_cache(maxsize=None)
def g_table() -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Affine multiples 0..15 of the base point; index 0 is infinity."""
    xs, ys, inf = [], [], []
    for i in range(TABLE):
        pt = affine_mul(i, (P256_GX, P256_GY))
        if pt is None:
            xs.append(0)
            ys.append(0)
            inf.append(True)
        else:
            xs.append(pt[0])
            ys.append(pt[1])
            inf.append(False)
    return (
        np.asarray(ints_to_limbs(xs)),
        np.asarray(ints_to_limbs(ys)),
        np.asarray(inf),
    )


# ---------------------------------------------------------------------------
# Jacobian point ops (batched, flag-carried infinity).
# ---------------------------------------------------------------------------


class Jac(NamedTuple):
    """Batched Jacobian point: limb arrays (..., 17) + infinity flag (...)."""

    x: jnp.ndarray
    y: jnp.ndarray
    z: jnp.ndarray
    inf: jnp.ndarray


class Aff(NamedTuple):
    """Batched affine point (for table entries); inf marks identity."""

    x: jnp.ndarray
    y: jnp.ndarray
    inf: jnp.ndarray


def _sel(c, a, b):
    """Lane select: c (...,) bool picks a (...,K) else b."""
    return jnp.where(c[..., None], a, b)


def _sel_pt(c, a: Jac, b: Jac) -> Jac:
    return Jac(
        _sel(c, a.x, b.x), _sel(c, a.y, b.y), _sel(c, a.z, b.z), jnp.where(c, a.inf, b.inf)
    )


def point_dbl(fp: limbs.Mod, p: Jac) -> Jac:
    """dbl-2001-b for a = -3 (3M + 5S).  Doubling infinity stays infinity via
    the flag; P-256 has odd order so no finite point doubles to infinity."""
    delta = fp.sqr(p.z)
    gamma = fp.sqr(p.y)
    beta = fp.mul(p.x, gamma)
    alpha = fp.mul_const(fp.mul(fp.sub(p.x, delta), fp.add(p.x, delta)), 3)
    x3 = fp.sub(fp.sqr(alpha), fp.mul_const(beta, 8))
    z3 = fp.sub(fp.sub(fp.sqr(fp.add(p.y, p.z)), gamma), delta)
    y3 = fp.sub(
        fp.mul(alpha, fp.sub(fp.mul_const(beta, 4), x3)),
        fp.mul_const(fp.sqr(gamma), 8),
    )
    return Jac(x3, y3, z3, p.inf)


def point_add(fp: limbs.Mod, p1: Jac, p2: Jac, dbl=None) -> Jac:
    """add-2007-bl (11M + 5S) with full degenerate handling: equal inputs
    fall back to doubling, opposite inputs yield infinity, identity inputs
    pass the other operand through."""
    z1z1 = fp.sqr(p1.z)
    z2z2 = fp.sqr(p2.z)
    u1 = fp.mul(p1.x, z2z2)
    u2 = fp.mul(p2.x, z1z1)
    s1 = fp.mul(fp.mul(p1.y, p2.z), z2z2)
    s2 = fp.mul(fp.mul(p2.y, p1.z), z1z1)
    h = fp.sub(u2, u1)
    rr = fp.sub(s2, s1)
    h_zero = fp.is_zero(h)
    r_zero = fp.is_zero(rr)
    i = fp.sqr(fp.add(h, h))
    j = fp.mul(h, i)
    rr2 = fp.add(rr, rr)
    v = fp.mul(u1, i)
    x3 = fp.sub(fp.sub(fp.sqr(rr2), j), fp.add(v, v))
    t = fp.mul(s1, j)
    y3 = fp.sub(fp.mul(rr2, fp.sub(v, x3)), fp.add(t, t))
    z3 = fp.mul(fp.sub(fp.sub(fp.sqr(fp.add(p1.z, p2.z)), z1z1), z2z2), h)
    out = Jac(x3, y3, z3, jnp.zeros_like(p1.inf))
    out = _sel_pt(h_zero & r_zero, (dbl or point_dbl)(fp, p1), out)  # P1 == P2
    out = Jac(out.x, out.y, out.z, out.inf | (h_zero & ~r_zero))  # P1 == -P2
    out = _sel_pt(p2.inf, p1, out)
    out = _sel_pt(p1.inf, p2, out)
    return out


def point_add_mixed(fp: limbs.Mod, p1: Jac, a2: Aff, dbl=None) -> Jac:
    """madd-2007-bl (7M + 4S), second operand affine (Z2 = 1)."""
    z1z1 = fp.sqr(p1.z)
    u2 = fp.mul(a2.x, z1z1)
    s2 = fp.mul(fp.mul(a2.y, p1.z), z1z1)
    h = fp.sub(u2, p1.x)
    rr = fp.sub(s2, p1.y)
    h_zero = fp.is_zero(h)
    r_zero = fp.is_zero(rr)
    hh = fp.sqr(h)
    i = fp.mul_const(hh, 4)
    j = fp.mul(h, i)
    rr2 = fp.add(rr, rr)
    v = fp.mul(p1.x, i)
    x3 = fp.sub(fp.sub(fp.sqr(rr2), j), fp.add(v, v))
    t = fp.mul(p1.y, j)
    y3 = fp.sub(fp.mul(rr2, fp.sub(v, x3)), fp.add(t, t))
    z3 = fp.sub(fp.sub(fp.sqr(fp.add(p1.z, h)), z1z1), hh)
    out = Jac(x3, y3, z3, jnp.zeros_like(p1.inf))
    out = _sel_pt(h_zero & r_zero, (dbl or point_dbl)(fp, p1), out)
    out = Jac(out.x, out.y, out.z, out.inf | (h_zero & ~r_zero))
    a2j = Jac(a2.x, a2.y, fp.one_like(a2.x), a2.inf)
    out = _sel_pt(a2.inf, p1, out)
    out = _sel_pt(p1.inf, a2j, out)
    return out


# ---------------------------------------------------------------------------
# The batched verify kernel.
# ---------------------------------------------------------------------------


def _q_window_table(fp: limbs.Mod, qx, qy):
    """Jacobian multiples 0..15 of each Q: (B, 16, 17) coordinate stacks.
    Built with 14 mixed adds; index 0 is infinity."""
    b = qx.shape[:-1]
    zero = jnp.zeros(b + (WIDE,), jnp.uint32)
    inf_t = jnp.ones(b, bool)
    fin = jnp.zeros(b, bool)
    q_aff = Aff(qx, qy, fin)
    q1 = Jac(qx, qy, fp.one_like(qx), fin)

    def step(p: Jac, _):
        nxt = point_add_mixed(fp, p, q_aff)
        return nxt, nxt

    # scan the add chain (2Q .. 15Q) so the graph holds ONE mixed add
    _, rest = jax.lax.scan(step, q1, None, length=TABLE - 2)
    # rest leaves: (TABLE-2, B, ...) -> move table axis next to batch
    cat = lambda z, o, r: jnp.concatenate(  # noqa: E731
        [z[..., None, :], o[..., None, :], jnp.moveaxis(r, 0, -2)], axis=-2
    )
    tinf = jnp.concatenate(
        [inf_t[..., None], fin[..., None], jnp.moveaxis(rest.inf, 0, -1)], axis=-1
    )
    return (
        cat(zero, q1.x, rest.x),
        cat(zero, q1.y, rest.y),
        cat(zero, q1.z, rest.z),
        tinf,
    )


def _gather_pt(tx, ty, tz, tinf, idx) -> Jac:
    """Select per-lane table entry idx (B,) from (B, 16, 17) stacks."""
    ii = idx[..., None, None]
    g = lambda t: jnp.take_along_axis(t, ii, axis=-2)[..., 0, :]  # noqa: E731
    inf = jnp.take_along_axis(tinf, idx[..., None], axis=-1)[..., 0]
    return Jac(g(tx), g(ty), g(tz), inf)


def verify_kernel(qx, qy, d1, d2, cand0, cand1, cand1_ok, valid):
    """Batched ECDSA-P256 verify core.

    Args (B = batch):
      qx, qy:    (B, 17) uint32 — public key affine coords (canonical limbs)
      d1, d2:    (B, 64) int32 — 4-bit MSB-first window digits of u1, u2
      cand0:     (B, 17) uint32 — r (mod p)
      cand1:     (B, 17) uint32 — r + n when < p (else ignored)
      cand1_ok:  (B,) bool — whether cand1 is admissible
      valid:     (B,) bool — host precheck passed (DER, range, low-S)
    Returns: (B,) bool — signature valid.
    """
    fp = mod_ctx(P256_P)
    gx, gy, ginf = (jnp.asarray(t) for t in g_table())
    tqx, tqy, tqz, tqinf = _q_window_table(fp, qx, qy)

    b = qx.shape[:-1]
    zero = jnp.zeros(b + (WIDE,), jnp.uint32)
    r0 = Jac(zero, zero, zero, jnp.ones(b, bool))

    def window(r: Jac, digs):
        w1, w2 = digs
        r = jax.lax.fori_loop(0, WINDOW_BITS, lambda _, p: point_dbl(fp, p), r)
        ga = Aff(gx[w1], gy[w1], ginf[w1])
        r = point_add_mixed(fp, r, ga)
        qj = _gather_pt(tqx, tqy, tqz, tqinf, w2)
        r = point_add(fp, r, qj)
        return r, None

    # scan over the 64 windows, MSB first; digits transposed to (64, B)
    r_final, _ = jax.lax.scan(window, r0, (d1.T, d2.T))

    z2 = fp.sqr(r_final.z)
    x_can = fp.canon(r_final.x)
    m0 = jnp.all(x_can == fp.canon(fp.mul(cand0, z2)), axis=-1)
    m1 = jnp.all(x_can == fp.canon(fp.mul(cand1, z2)), axis=-1) & cand1_ok
    return (m0 | m1) & ~r_final.inf & valid


@functools.lru_cache(maxsize=None)
def _jit_verify():
    return jax.jit(verify_kernel)


def verify_prepared(qx, qy, d1, d2, cand0, cand1, cand1_ok, valid):
    """Jitted entry; compiles once per batch shape (callers bucket batches)."""
    return _jit_verify()(qx, qy, d1, d2, cand0, cand1, cand1_ok, valid)


# ---------------------------------------------------------------------------
# Host-side preparation: scalar math per item, numpy packing.
# ---------------------------------------------------------------------------

_HALF_N = P256_N >> 1


def recode_windows(u: int) -> np.ndarray:
    """256-bit scalar -> 64 MSB-first 4-bit window digits."""
    return np.asarray(
        [(u >> (WINDOW_BITS * (NWINDOWS - 1 - k))) & (TABLE - 1) for k in range(NWINDOWS)],
        dtype=np.int32,
    )


def prepare_batch(items) -> dict:
    """Host preprocessing for a batch of (x, y, digest32, r, s) tuples.

    Performs the reference's host-side checks (bccsp/sw/ecdsa.go:41-57 —
    malformed encoding, zero/negative or out-of-range r/s, high-S rejection)
    and the cheap modular scalar math; returns numpy arrays for the kernel.
    Items that fail prechecks stay in the batch with `valid=False` and dummy
    values so shapes remain static.
    """
    n = len(items)
    xs, ys, u1s, u2s = [], [], [], []
    c0, c1 = [], []
    c1_ok = np.zeros(n, bool)
    valid = np.zeros(n, bool)
    for i, (x, y, digest, r, s) in enumerate(items):
        ok = (
            isinstance(r, int)
            and isinstance(s, int)
            and 0 < r < P256_N
            and 0 < s <= _HALF_N  # low-S enforced, as the reference does
            and len(digest) == 32
        )
        if not ok:
            xs.append(P256_GX)
            ys.append(P256_GY)
            u1s.append(1)
            u2s.append(1)
            c0.append(1)
            c1.append(1)
            continue
        valid[i] = True
        e = int.from_bytes(digest, "big") % P256_N
        w = pow(s, -1, P256_N)
        u1s.append(e * w % P256_N)
        u2s.append(r * w % P256_N)
        xs.append(x)
        ys.append(y)
        c0.append(r)
        rpn = r + P256_N
        if rpn < P256_P:
            c1.append(rpn)
            c1_ok[i] = True
        else:
            c1.append(1)
    return dict(
        qx=np.asarray(ints_to_limbs(xs)),
        qy=np.asarray(ints_to_limbs(ys)),
        d1=np.stack([recode_windows(u) for u in u1s]),
        d2=np.stack([recode_windows(u) for u in u2s]),
        cand0=np.asarray(ints_to_limbs(c0)),
        cand1=np.asarray(ints_to_limbs(c1)),
        cand1_ok=c1_ok,
        valid=valid,
    )


__all__ = [
    "Jac",
    "Aff",
    "affine_add",
    "affine_mul",
    "g_table",
    "point_dbl",
    "point_add",
    "point_add_mixed",
    "verify_kernel",
    "verify_prepared",
    "prepare_batch",
    "recode_windows",
]
