"""Interpreter-teardown regression tests (ISSUE 4: retire os._exit).

Round 5's MULTICHIP artifact regressed to rc=134: dryrun_multichip(8)
passed every assertion, printed success, then ABORTED at interpreter
teardown — a `tpu-flush-waiter` daemon thread was still inside an XLA
kernel when Python exited, the runtime pthread-killed it, the forced
unwind crossed XLA's catch(...), and glibc raised "FATAL: exception not
rethrown".  bench.py papered the same abort over with os._exit(0).

The fix is a lifecycle, not a bigger hammer: TPUCSP.drain() joins every
in-flight flush waiter (cancelling their EWMA feedback), bench.py and
the dryrun call it on the way out, and threadwatch asserts the worker
ledger is empty.  These tests pin the property: the dryrun subprocess
must exit rc=0 through NORMAL teardown, with no os._exit anywhere on
the entry paths and nothing left in the threadwatch ledger."""

from __future__ import annotations

import os
import subprocess
import sys
import textwrap

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_no_os_exit_in_entry_points():
    # the workaround must stay dead: a reintroduced os._exit would mask
    # the next lifecycle regression instead of failing loudly
    import ast

    for rel in ("bench.py", "__graft_entry__.py"):
        with open(os.path.join(ROOT, rel), "r", encoding="utf-8") as f:
            tree = ast.parse(f.read())
        calls = [
            node.lineno
            for node in ast.walk(tree)
            if isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "_exit"
        ]
        assert not calls, (
            f"{rel}:{calls} reintroduced os._exit — drain the provider "
            "instead (TPUCSP.drain joins the flush waiters)"
        )


def _run_dryrun(n_devices: int, timeout: float) -> None:
    code = textwrap.dedent(f"""
        import __graft_entry__

        __graft_entry__.dryrun_multichip({n_devices})

        from fabric_tpu.devtools import lockwatch

        assert not lockwatch.thread_violations, (
            "threadwatch ledger not empty: "
            + repr(lockwatch.thread_violations)
        )
        stragglers = lockwatch.drain_threads(timeout=30.0)
        assert not stragglers, (
            "worker threads alive after dryrun: " + repr(stragglers)
        )
        print("TEARDOWN-OK", flush=True)
    """)
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": (
            f"--xla_force_host_platform_device_count={n_devices}"
        ),
        "FABRIC_TPU_LOCKWATCH": "1",
        "FABRIC_TPU_THREADWATCH": "1",
    })
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=ROOT, env=env, capture_output=True, text=True,
        timeout=timeout,
    )
    # rc=0 through NORMAL teardown is the whole point: -6/134 here is
    # the "FATAL: exception not rethrown" abort this PR fixes
    assert proc.returncode == 0, (
        f"dryrun_multichip({n_devices}) exited rc={proc.returncode}\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr[-4000:]}"
    )
    assert "TEARDOWN-OK" in proc.stdout


def test_dryrun_multichip_teardown_rc0_two_devices():
    """Tier-1 variant (2 virtual devices): the full dryrun — including
    the injected slow flush whose waiter is the historical orphan —
    must drain and exit rc=0 with an empty threadwatch ledger."""
    pytest.importorskip(
        "cryptography", reason="dryrun builds a 5-org world"
    )
    _run_dryrun(2, timeout=840.0)


@pytest.mark.slow
def test_dryrun_multichip_teardown_rc0_driver_shape():
    """Driver-shape variant (8 virtual devices) — the exact MULTICHIP
    artifact configuration that regressed in round 5."""
    pytest.importorskip(
        "cryptography", reason="dryrun builds a 5-org world"
    )
    _run_dryrun(8, timeout=1800.0)


# -- TPUCSP.drain unit coverage (satellite: cancelled flushes feed no
# EWMA) ----------------------------------------------------------------------


def test_drain_joins_waiters_and_skips_cancelled_ewma():
    pytest.importorskip("cryptography", reason="provider imports SWCSP")
    import threading
    import time as _time

    from fabric_tpu.csp.tpu.provider import _FlushResult

    fed: list = []

    def make(cancelled: bool) -> _FlushResult:
        release = threading.Event()

        def collect():
            release.wait(5)
            return [True]

        res = _FlushResult(
            [(collect, 1)], 1, device_items=[object()],
            on_device_wall=lambda lanes, wall: fed.append((lanes, wall)),
        )
        res.cancelled = cancelled
        res.start_background()
        _time.sleep(0.02)
        release.set()
        return res

    # a live (uncancelled) flush feeds the lane-wall EWMA...
    res = make(cancelled=False)
    assert res.collect() == [True]
    res._waiter.join(5)
    assert len(fed) == 1

    # ...a flush cancelled during drain never does: its wall measures
    # teardown contention, not chip speed
    fed.clear()
    res = make(cancelled=True)
    assert res.collect() == [True]
    res._waiter.join(5)
    assert fed == []


def test_drain_flushes_pending_and_returns_true():
    pytest.importorskip("cryptography")
    import hashlib

    from fabric_tpu.csp import SWCSP
    from fabric_tpu.csp.api import VerifyBatchItem
    from fabric_tpu.csp.tpu.provider import TPUCSP

    sw = SWCSP()
    key = sw.key_gen()
    d = hashlib.sha256(b"drain").digest()
    items = [
        VerifyBatchItem(key.public_key(), d, sw.sign(key, d))
        for _ in range(24)
    ]
    # coalesce_lanes high: the batch stays BUFFERED (no flush yet);
    # drain must flush it so no collector can dangle, then join
    csp = TPUCSP(min_device_batch=1, coalesce_lanes=10_000)
    collector = csp.verify_batch_async(items)
    assert csp.drain(timeout=60.0) is True
    assert csp._inflight == []
    assert collector() == [True] * 24
    # idempotent on a quiesced provider
    assert csp.drain() is True
    csp.close()
