"""Clean twin of fix_closure_sibling_dirty: the sibling closure takes
the lock around its write, so the resolved call chain carries a
correct lockset and nothing fires."""

from fabric_tpu.devtools.lockwatch import named_lock, spawn_thread


class Roller:
    def __init__(self):
        self._lock = named_lock("fixture.roller")
        self._height = 0

    def launch(self):
        def bump():
            with self._lock:
                self._height += 1

        def pump_loop():
            for _ in range(4):
                bump()

        t = spawn_thread(target=pump_loop, name="roller", kind="worker")
        t.start()
        return t

    def read(self):
        with self._lock:
            return self._height

    def write(self, h):
        with self._lock:
            self._height = h
