"""Reviewed cross-file fabriclint suppressions.

Every entry must carry a reason; unused entries are themselves lint
violations (lint.py reports them), so this file can only shrink as code
is fixed — it never accumulates dead grants.  Prefer inline pragmas for
single-line local exemptions; entries here are for cases where the
suppression is a reviewed DESIGN decision rather than a line-local one.
"""

from __future__ import annotations

from fabric_tpu.devtools.lint import AllowEntry

ALLOWLIST: list[AllowEntry] = [
    AllowEntry(
        rule="determinism",
        path="fabric_tpu/peer/deliverclient.py",
        match="random.shuffle(order)",
        reason="endpoint shuffle is deliberately randomized per peer "
               "for orderer load-spreading; connection order never "
               "enters consensus state",
    ),
    AllowEntry(
        rule="lock-discipline",
        path="fabric_tpu/ledger/kvledger.py",
        match="self._flush_group(g)",
        reason="the approved group-commit seam: KVLedger.commit flushes "
               "one fsync + one atomic KV txn per group boundary under "
               "the commit lock BY DESIGN (PR 2 pipeline invariant)",
    ),
    AllowEntry(
        rule="lock-discipline",
        path="fabric_tpu/ledger/kvledger.py",
        match="self._flush_group(group)",
        reason="the approved group-commit seam: commit_group_flush is "
               "the explicit group boundary — the single coalesced "
               "fsync + KV txn must be atomic w.r.t. concurrent "
               "snapshot exports, so it runs under the commit lock",
    ),
]

__all__ = ["ALLOWLIST"]
