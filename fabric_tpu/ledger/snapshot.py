"""Channel snapshots & join-by-snapshot: TPU-hashed ledger checkpoints.

Reference: core/ledger/kvledger/snapshot.go + snapshot_mgmt.go (generate
at commit, request bookkeeping), core/ledger/kvledger/kv_ledger_provider.go
CreateFromSnapshot, internal/peer/snapshot (CLI surface).  A snapshot is a
directory of deterministic, ordered export files

    public_state.data          raw (key, value) records of the public
                               state namespaces, in state-key order
    private_state_hashes.data  the derived hashed-collection namespaces
                               (key hashes + value hashes; cleartext
                               private data is NEVER exported — a
                               restored peer reconciles it later)
    txids.data                 every committed txid (duplicate-tx guard)
    confighistory.data         collection-config history entries
    channel_config.block       the channel's config block (lets a peer
                               with no blocks build its channel bundle)
    _snapshot_signable_metadata.json
                               channel id, last block number/hash, and
                               per-file SHA-256 digests

The per-file digests are computed through the CSP `hash_batch` seam
(fabric_tpu/csp/api.py) — one batched call for all files — so snapshot
integrity hashing rides the same TPU-batched path as block validation,
with the sw provider as the host fallback.  `verify_snapshot` recomputes
the digests on import and refuses a tampered directory.

Request lifecycle (reference snapshot_mgmt.go): requests are persisted
under the ledger's bookkeeping/snapshot-request namespace (submit /
cancel / list-pending) and the ledger triggers generation automatically
when it commits the requested block number.  Generated snapshots land in

    <snapshots_root>/completed/<ledger_id>/<last_block_number>/

written via an in_progress staging directory + atomic rename so a crash
never leaves a half-written "completed" snapshot.
"""

from __future__ import annotations

import json
import os
import shutil
import struct
import threading
import time

from fabric_tpu.common import tracing
from fabric_tpu.devtools import faultline
from fabric_tpu.devtools.lockwatch import (
    guarded,
    named_condition,
    named_lock,
    spawn_thread,
)
from fabric_tpu.ledger.bookkeeping import (
    SNAPSHOT_REQUEST,
    BookkeepingProvider,
)
from fabric_tpu.ledger.confighistory import ConfigHistoryMgr
from fabric_tpu.ledger.kvstore import KVStore, NamedDB
from fabric_tpu.ledger.pvtdatastorage import PvtDataStore
from fabric_tpu.ledger.txmgmt import key_hash
from fabric_tpu.ledger.statedb import Height, VersionedDB

SNAPSHOT_FORMAT_VERSION = 1

METADATA_FILE = "_snapshot_signable_metadata.json"
PUBLIC_STATE_FILE = "public_state.data"
PVT_HASHES_FILE = "private_state_hashes.data"
TXIDS_FILE = "txids.data"
CONFIG_HISTORY_FILE = "confighistory.data"
CONFIG_BLOCK_FILE = "channel_config.block"

# the data files whose digests enter the signable metadata, in the fixed
# order they are hashed (sorted, so the metadata is deterministic)
DATA_FILES = (
    CONFIG_BLOCK_FILE,
    CONFIG_HISTORY_FILE,
    PVT_HASHES_FILE,
    PUBLIC_STATE_FILE,
    TXIDS_FILE,
)

_LEN = struct.Struct(">I")


class SnapshotError(Exception):
    pass


class SnapshotExistsError(SnapshotError):
    """A snapshot for this (channel, height) already exists on disk —
    benign for the background auto-trigger: two requests satisfied by
    the same commit group both export at the same durable height, and
    the loser's request is answered by the winner's snapshot."""


# -- record files ------------------------------------------------------------
#
# All .data files share one trivially deterministic format: a sequence of
# length-prefixed (key, value) byte-string pairs in the order the source
# store iterates them (lexicographic key order everywhere).


def _write_record(f, k: bytes, v: bytes) -> None:
    f.write(_LEN.pack(len(k)))
    f.write(k)
    f.write(_LEN.pack(len(v)))
    f.write(v)


def write_records(path: str, records) -> tuple[int, int]:
    """Write (key, value) pairs; returns (record_count, byte_count)."""
    count = size = 0
    with open(path, "wb") as f:
        for k, v in records:
            _write_record(f, k, v)
            count += 1
            size += 8 + len(k) + len(v)
    return count, size


def read_records(path: str):
    """Yield the (key, value) pairs of a record file; raises
    SnapshotError on a truncated or malformed file."""
    with open(path, "rb") as f:
        while True:
            hdr = f.read(_LEN.size)
            if not hdr:
                return
            if len(hdr) < _LEN.size:
                raise SnapshotError(f"truncated record file {path!r}")
            (klen,) = _LEN.unpack(hdr)
            k = f.read(klen)
            vhdr = f.read(_LEN.size)
            if len(k) < klen or len(vhdr) < _LEN.size:
                raise SnapshotError(f"truncated record file {path!r}")
            (vlen,) = _LEN.unpack(vhdr)
            v = f.read(vlen)
            if len(v) < vlen:
                raise SnapshotError(f"truncated record file {path!r}")
            yield k, v


# -- request bookkeeping -----------------------------------------------------


class SnapshotRequestBookkeeper:
    """Durable pending snapshot requests (reference snapshot_mgmt.go
    snapshotRequestBookkeeper): one key per requested block number under
    the ledger's bookkeeping/<ledger>/snapshot-request namespace, so
    pending requests survive a peer restart."""

    def __init__(self, db):
        self._db = db

    @staticmethod
    def _key(block_number: int) -> bytes:
        return b"%016x" % block_number

    def submit(self, block_number: int) -> None:
        if self.has(block_number):
            raise SnapshotError(
                f"snapshot request for block {block_number} already pending"
            )
        self._db.put(self._key(block_number), b"")

    def cancel(self, block_number: int) -> None:
        if not self.has(block_number):
            raise SnapshotError(
                f"no pending snapshot request for block {block_number}"
            )
        self._db.delete(self._key(block_number))

    def has(self, block_number: int) -> bool:
        return self._db.get(self._key(block_number)) is not None

    def list_pending(self) -> list[int]:
        return [int(k, 16) for k, _ in self._db.iterate(b"", None)]


# -- generation --------------------------------------------------------------


def _metadata_path(snapshot_dir: str) -> str:
    return os.path.join(snapshot_dir, METADATA_FILE)


def load_metadata(snapshot_dir: str) -> dict:
    path = _metadata_path(snapshot_dir)
    if not os.path.isfile(path):
        raise SnapshotError(f"no snapshot metadata at {path!r}")
    with open(path, "rb") as f:
        return json.loads(f.read().decode("utf-8"))


def _hash_files(snapshot_dir: str, names, csp=None, metrics=None,
                channel: str = ""):
    """Per-file SHA-256 digests through the CSP hash_batch seam — ONE
    batched call covers every file, so on the TPU provider the whole
    snapshot is digested device-side; sw is the host fallback.  When the
    csp package itself is unavailable (hosts without `cryptography`),
    the common.hashing seam produces the identical digests."""
    if csp is None:
        try:
            from fabric_tpu.csp.factory import get_default

            csp = get_default()
        except ImportError:
            csp = None
    blobs = []
    for name in names:
        path = os.path.join(snapshot_dir, name)
        if not os.path.isfile(path):
            raise SnapshotError(f"snapshot file {name!r} is missing")
        with open(path, "rb") as f:
            blobs.append(f.read())
    t0 = time.perf_counter()
    if csp is not None:
        digests = csp.hash_batch(blobs)
    else:
        from fabric_tpu.common.hashing import sha256_many

        digests = sha256_many(blobs)
    dt = time.perf_counter() - t0
    total = sum(len(b) for b in blobs)
    if metrics is not None:
        metrics.bytes_hashed.With("channel", channel).add(total)
        if dt > 0:
            metrics.hash_mb_per_s.With("channel", channel).set(
                total / dt / 1e6
            )
    return {name: d.hex() for name, d in zip(names, digests)}


def generate_snapshot(
    ledger, snapshots_root: str, csp=None, metrics=None
) -> str:
    """Export the ledger into <snapshots_root>/completed/<id>/<height-1>
    and return the snapshot directory.  Deterministic: same ledger state
    -> byte-identical files -> identical signable metadata.  The whole
    export runs under one trace span (per-stage progress lands as
    instant marks at the faultline stage points), so a trace shows
    whether an export overlapped or serialized behind the next commit."""
    with tracing.span(
        "snapshot.export", cat="stage",
        channel=getattr(ledger, "ledger_id", ""),
        block=max(0, getattr(ledger, "durable_height", ledger.height) - 1),
    ):
        return _generate_snapshot(ledger, snapshots_root, csp, metrics)


def _generate_snapshot(
    ledger, snapshots_root: str, csp=None, metrics=None
) -> str:
    if not snapshots_root:
        raise SnapshotError("ledger provider has no snapshots directory")
    # export the DURABLE height: under group commit the in-memory
    # height can run ahead of the last flushed fsync+txn boundary, and
    # only the flushed prefix is readable (and crash-safe) here
    height = getattr(ledger, "durable_height", ledger.height)
    if height == 0:
        raise SnapshotError("cannot snapshot an empty ledger")
    t0 = time.perf_counter()
    lid = ledger.ledger_id
    last_num = height - 1
    final_dir = os.path.join(snapshots_root, "completed", lid, str(last_num))
    if os.path.exists(final_dir):
        raise SnapshotExistsError(
            f"snapshot for {lid!r} at block {last_num} already exists"
        )
    work = os.path.join(snapshots_root, "in_progress", f"{lid}-{last_num}")
    if os.path.isdir(work):
        shutil.rmtree(work)  # a crashed previous attempt
    os.makedirs(work)

    store = ledger.block_store
    state: VersionedDB = ledger.state_db

    # state: ONE ordered pass routing each record to the public or
    # hashed-collection file; cleartext private namespaces are skipped
    # (the reference never exports them either — a restored peer
    # reconciles cleartext from collection peers).  The ns/key split is
    # heuristic (a public KEY may itself embed '\x00pvt\x00'-shaped
    # bytes — the statedb key encoding cannot distinguish that from a
    # collection namespace), so a pvt-classified record is only DROPPED
    # when its hashed counterpart exists: every genuinely-private
    # committed write also committed a hash-namespace entry
    # (txmgmt validate_and_prepare), while a look-alike public key has
    # none and must ride the public file.  Misrouting between the two
    # EXPORTED files is harmless — import re-writes raw records
    # verbatim from both.
    with open(os.path.join(work, PUBLIC_STATE_FILE), "wb") as pub_f, \
            open(os.path.join(work, PVT_HASHES_FILE), "wb") as hash_f:
        for raw_key, raw_val in state.export_records():
            ns, key = VersionedDB.split_state_key(raw_key)
            parts = ns.split("\x00")
            if len(parts) == 3 and parts[1] == "pvt":
                hashed_ns = f"{parts[0]}\x00hash\x00{parts[2]}"
                khash = key_hash(key).hex()
                if state.get_state(hashed_ns, khash) is not None:
                    continue  # confirmed cleartext private: never export
            out = hash_f if len(parts) == 3 and parts[1] == "hash" else pub_f
            _write_record(out, raw_key, raw_val)
    # export stage fault points (ROADMAP faultline gap): a crash at any
    # of these leaves only the in_progress/ staging directory — the
    # atomic-rename contract says completed/ never holds a partial
    # snapshot, which the faultfuzz oracle verifies
    faultline.point("snapshot.export.stage", stage="state", channel=lid)
    tracing.instant("snapshot.stage", stage="state", channel=lid)
    write_records(
        os.path.join(work, TXIDS_FILE),
        ((t.encode(), b"") for t in store.export_txids()),
    )
    faultline.point("snapshot.export.stage", stage="txids", channel=lid)
    tracing.instant("snapshot.stage", stage="txids", channel=lid)
    write_records(
        os.path.join(work, CONFIG_HISTORY_FILE),
        ledger.config_history.export_entries(),
    )
    faultline.point(
        "snapshot.export.stage", stage="confighistory", channel=lid
    )
    tracing.instant("snapshot.stage", stage="confighistory", channel=lid)
    cfg_raw = store.config_block_bytes()
    if cfg_raw is None:
        blk0 = store.get_block_by_number(0)
        if blk0 is None:
            raise SnapshotError(
                f"ledger {lid!r} has neither a config block nor block 0"
            )
        cfg_raw = blk0.SerializeToString()
    with open(os.path.join(work, CONFIG_BLOCK_FILE), "wb") as f:
        f.write(cfg_raw)
    faultline.point(
        "snapshot.export.stage", stage="config_block", channel=lid
    )
    tracing.instant("snapshot.stage", stage="config_block", channel=lid)

    files = _hash_files(work, DATA_FILES, csp, metrics, channel=lid)
    faultline.point("snapshot.export.stage", stage="hash", channel=lid)
    tracing.instant("snapshot.stage", stage="hash", channel=lid)
    last_blk = store.get_block_by_number(last_num)
    sp = state.savepoint()
    last_hash = getattr(ledger, "durable_block_hash", None)
    if last_hash is None:
        last_hash = store.last_block_hash
    meta = {
        "version": SNAPSHOT_FORMAT_VERSION,
        "channel_id": lid,
        "last_block_number": last_num,
        "last_block_hash": last_hash.hex(),
        # informational for external auditors signing/checking the
        # metadata against the source chain (the reference's signable
        # metadata carries it too); import does not consume it
        "previous_block_hash": (
            last_blk.header.previous_hash.hex() if last_blk is not None
            else ""
        ),
        "state_savepoint": [sp.block_num, sp.tx_num] if sp else None,
        "index_defs": {
            ns: sorted(state.indexes_for(ns))
            for ns in sorted(state.indexed_namespaces())
        },
        "files": files,
    }
    with open(_metadata_path(work), "wb") as f:
        # torn-manifest seam: a "torn" rule writes a strict prefix of
        # the signable metadata and crashes — verify_snapshot must then
        # refuse the staging directory (truncated JSON, missing digests)
        faultline.write(
            "snapshot.manifest", f,
            json.dumps(meta, sort_keys=True, indent=2).encode(),
            channel=lid,
        )

    faultline.point("snapshot.export.stage", stage="rename", channel=lid)
    tracing.instant("snapshot.stage", stage="rename", channel=lid)
    os.makedirs(os.path.dirname(final_dir), exist_ok=True)
    os.replace(work, final_dir)
    if metrics is not None:
        metrics.generation_duration.With("channel", lid).observe(
            time.perf_counter() - t0
        )
    return final_dir


# -- verification + import ---------------------------------------------------


def verify_snapshot(snapshot_dir: str, csp=None) -> dict:
    """Recompute every data file's digest (through hash_batch) and check
    it against the signable metadata; returns the metadata.  Raises
    SnapshotError on any mismatch or missing file."""
    meta = load_metadata(snapshot_dir)
    if meta.get("version") != SNAPSHOT_FORMAT_VERSION:
        raise SnapshotError(
            f"unsupported snapshot format version {meta.get('version')!r}"
        )
    expected = meta.get("files") or {}
    # a digest for EVERY data file must be present — otherwise editing
    # the metadata to drop an entry would exempt that file from the
    # tamper check entirely
    missing = [n for n in DATA_FILES if n not in expected]
    if missing:
        raise SnapshotError(
            "snapshot metadata lists no digest for: " + ", ".join(missing)
        )
    names = sorted(expected)
    actual = _hash_files(snapshot_dir, names, csp)
    bad = [n for n in names if actual[n] != expected[n]]
    if bad:
        raise SnapshotError(
            "snapshot file hash mismatch (tampered or corrupt): "
            + ", ".join(bad)
        )
    return meta


IMPORT_IN_PROGRESS = b"in_progress"
IMPORT_DONE = b"done"


def import_marker(kv: KVStore, ledger_id: str) -> bytes | None:
    """The channel's snapshot-import completion marker: None (never
    imported), IMPORT_IN_PROGRESS (a crashed half-import — the stores
    hold an arbitrary prefix of the snapshot and must NOT be served),
    or IMPORT_DONE."""
    return NamedDB(kv, f"snapimport/{ledger_id}").get(b"state")


def import_snapshot(
    meta: dict, snapshot_dir: str, store, kv: KVStore, ledger_id: str
) -> None:
    """Populate an EMPTY channel's stores from a verified snapshot:
    block-store bootstrap info + txid index, state DB (public + hashed,
    savepoint at the snapshot height so recovery replays nothing),
    config history, and the pvt store's bootstrap marker.  The caller
    then constructs the KVLedger over the same stores.

    Crash safety: an IMPORT_IN_PROGRESS marker lands FIRST and flips to
    IMPORT_DONE only after every store is populated — a crash anywhere
    between (the faultline stage points below inject exactly those)
    leaves the marker mid-flight, and LedgerProvider.open refuses to
    serve the half-imported channel instead of silently opening partial
    state."""
    marker = NamedDB(kv, f"snapimport/{ledger_id}")
    marker.put(b"state", IMPORT_IN_PROGRESS)
    last_num = int(meta["last_block_number"])
    with open(os.path.join(snapshot_dir, CONFIG_BLOCK_FILE), "rb") as f:
        cfg_raw = f.read()
    store.bootstrap(
        last_num, bytes.fromhex(meta["last_block_hash"]), config_block=cfg_raw
    )
    faultline.point(
        "snapshot.import.stage", stage="bootstrap", channel=ledger_id
    )
    store.import_snapshot_txids(
        k.decode() for k, _ in read_records(
            os.path.join(snapshot_dir, TXIDS_FILE)
        )
    )
    faultline.point(
        "snapshot.import.stage", stage="txids", channel=ledger_id
    )

    def state_records():
        yield from read_records(os.path.join(snapshot_dir, PUBLIC_STATE_FILE))
        yield from read_records(os.path.join(snapshot_dir, PVT_HASHES_FILE))

    sp = meta.get("state_savepoint")
    savepoint = Height(sp[0], sp[1]) if sp else Height(last_num, 0)
    state = VersionedDB(kv, f"statedb/{ledger_id}")
    state.import_records(state_records(), savepoint)
    faultline.point(
        "snapshot.import.stage", stage="state", channel=ledger_id
    )
    for ns, specs in (meta.get("index_defs") or {}).items():
        for spec in specs:
            state.define_index(ns, spec)
    ConfigHistoryMgr(kv, ledger_id).import_entries(
        read_records(os.path.join(snapshot_dir, CONFIG_HISTORY_FILE))
    )
    faultline.point(
        "snapshot.import.stage", stage="confighistory", channel=ledger_id
    )
    PvtDataStore(kv, ledger_id).init_bootstrap_height(last_num + 1)
    marker.put(b"state", IMPORT_DONE)


# -- manager -----------------------------------------------------------------


class SnapshotManager:
    """Per-ledger snapshot front end: request bookkeeping + commit-time
    auto-trigger + on-demand generation (reference snapshot_mgmt.go's
    snapshotMgr, owned by the kvledger)."""

    def __init__(self, ledger, snapshots_root: str | None, kv: KVStore,
                 csp=None, metrics=None):
        self._ledger = ledger
        self._root = snapshots_root
        self._csp = csp
        self.metrics = metrics
        self._requests = SnapshotRequestBookkeeper(
            BookkeepingProvider(kv).get_kv(ledger.ledger_id, SNAPSHOT_REQUEST)
        )
        # watched under FABRIC_TPU_LOCKWATCH: canonical order is
        # ledger.commit_lock FIRST, then this manager lock
        self._lock = named_lock("snapshot.manager")
        # background auto-trigger generations in flight (wait_idle),
        # plus a spawn/ack handshake: _spawn_seq counts generations
        # handed to background threads, _ack_seq counts those that have
        # ACQUIRED the ledger commit lock — commits wait for the two to
        # match so a pinned export runs before state advances past its
        # height (the reference blocks commits during generation too)
        self._idle = named_condition("snapshot.idle")
        self._inflight = 0
        self._spawn_seq = 0
        self._ack_seq = 0
        # in-memory mirror of the durable pending-request set: the
        # per-block boundary-hint probe on the commit hot path must not
        # pay a KV get
        self._pending = set(self._requests.list_pending())
        self._update_gauge()

    # -- requests ----------------------------------------------------------

    def _update_gauge(self) -> None:
        if self.metrics is not None:
            self.metrics.pending_requests.With(
                "channel", self._ledger.ledger_id
            ).set(len(self._requests.list_pending()))

    def submit_request(self, block_number: int = 0) -> dict:
        """Request a snapshot at `block_number` (0 = the last committed
        block, generated immediately).  A request at the last committed
        block also generates immediately; a future block is recorded and
        auto-triggers when the ledger commits it (reference
        SubmitSnapshotRequest semantics).

        Lock order everywhere is ledger.commit_lock -> manager lock (the
        commit-time trigger enters with commit_lock already held), so an
        RPC-thread generate can never deadlock against a commit — and
        the export always sees a fully committed block, never a torn
        one."""
        with self._ledger.commit_lock:
            with self._lock:
                # anchor on the DURABLE height: an open commit group's
                # buffered tail is neither readable nor crash-safe, so
                # "the last committed block" means the watermark
                last = getattr(
                    self._ledger, "durable_height", self._ledger.height
                ) - 1
                if block_number == 0:
                    if last < 0:
                        raise SnapshotError("ledger has no committed blocks")
                    block_number = last
                if block_number < last:
                    raise SnapshotError(
                        f"requested block {block_number} is already "
                        f"committed (last committed block is {last})"
                    )
                if block_number == last:
                    path = self._generate()
                    return {
                        "block_number": block_number, "snapshot_dir": path
                    }
                if block_number < self._ledger.height:
                    # already buffered in an OPEN commit group: the
                    # stream's flush-at-requested-height hint for this
                    # block has passed, so the export could only run at
                    # the group's (later) flush height — silently
                    # exporting at the wrong height would break the
                    # deterministic-height guarantee, so refuse instead
                    raise SnapshotError(
                        f"requested block {block_number} is already "
                        f"buffered in an open commit group (last durable "
                        f"block is {last}); request block 0 for the last "
                        f"durable block, or a block >= "
                        f"{self._ledger.height}"
                    )
                self._requests.submit(block_number)
                self._pending.add(block_number)
                self._update_gauge()
                return {"block_number": block_number, "snapshot_dir": None}

    def cancel_request(self, block_number: int) -> None:
        with self._lock:
            self._requests.cancel(block_number)
            self._pending.discard(block_number)
            self._update_gauge()

    def has_pending_request(self, block_number: int) -> bool:
        """O(1) in-memory probe — the commit path's per-block
        boundary-hint check."""
        return block_number in self._pending

    def list_pending(self) -> list[int]:
        return self._requests.list_pending()

    # -- generation --------------------------------------------------------

    def on_block_committed(self, block_number: int) -> None:
        """KVLedger's group flush calls this for each block made durable
        (commit_lock held); a matching pending request hands generation
        to a BACKGROUND thread — the commit thread only dequeues the
        request, so the export no longer runs inline on the committer
        (the reference generates in a background goroutine the same
        way).  Height determinism is preserved by three pieces: the
        streaming committer flushes AT a requested block (CommitGroup.
        boundary_hint), submit_request refuses heights already buffered
        in an open group (whose hint has passed), and
        wait_generation_turn makes the next commit wait until the
        export thread holds the commit lock — so the snapshot is taken
        at exactly the requested height, as the synchronous path
        guaranteed (and peers generating from the same request agree
        byte-for-byte).  A generation failure is logged
        and the request dropped — the commit itself must never fail
        because a snapshot could not be written (reference logs and
        continues the same way).  Tests and operators can wait_idle()
        for the export to finish."""
        with self._lock:
            guarded(self, "_pending", by="snapshot.manager")
            if not self._requests.has(block_number):
                return
            self._requests.cancel(block_number)
            self._pending.discard(block_number)
            self._update_gauge()
        with self._idle:
            guarded(self, "_spawn_seq", by="snapshot.idle")
            self._inflight += 1
            self._spawn_seq += 1
        spawn_thread(
            target=self._bg_generate, args=(block_number,),
            name=f"snapshot-gen-{self._ledger.ledger_id}", kind="worker",
        ).start()

    def wait_generation_turn(self, timeout: float = 30.0) -> None:
        """Block until every spawned background generation has acquired
        the ledger commit lock.  KVLedger calls this at each commit/
        flush entry (BEFORE taking the commit lock itself), so an export
        pinned to the triggering flush's height always runs before state
        can advance past it — the export height is deterministic, not a
        race.  Times out rather than wedging commits if a generation
        thread dies before acquiring."""
        deadline = time.monotonic() + timeout
        with self._idle:
            while self._ack_seq < self._spawn_seq:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return
                self._idle.wait(remaining)

    def _bg_generate(self, block_number: int) -> None:
        try:
            with self._ledger.commit_lock:
                with self._idle:
                    self._ack_seq += 1
                    self._idle.notify_all()
                with self._lock:
                    self._generate()
        except SnapshotExistsError:
            # several requests satisfied by one commit group race to
            # export the same durable height: the winner's snapshot
            # answers every one of them
            pass
        except Exception as exc:
            from fabric_tpu.common.flogging import must_get_logger

            must_get_logger("ledger.snapshot").warning(
                "snapshot generation at block %d failed for %r: %s",
                block_number, self._ledger.ledger_id, exc,
            )
        finally:
            with self._idle:
                self._inflight -= 1
                self._idle.notify_all()

    def wait_idle(self, timeout: float = 30.0) -> bool:
        """Block until no background auto-trigger generation is in
        flight; False on timeout."""
        deadline = time.monotonic() + timeout
        with self._idle:
            while self._inflight:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._idle.wait(remaining)
        return True

    def generate(self) -> str:
        """Generate a snapshot at the current committed height."""
        with self._ledger.commit_lock:
            with self._lock:
                return self._generate()

    def _generate(self) -> str:
        return generate_snapshot(
            self._ledger, self._root, csp=self._csp, metrics=self.metrics
        )


# -- snapshot serving (remote fetch) ------------------------------------------
#
# join_by_snapshot used to require the snapshot directory on SHARED disk.
# These helpers stream a COMPLETED snapshot directory over any frame
# transport (the peer's admin.SnapshotFetch RPC): each frame is a JSON
# header line (file name + eof marker) followed by a raw chunk.  The
# receiver rebuilds the directory; integrity needs no transport trust —
# verify-on-import recomputes every file digest, so a torn or tampered
# stream is refused at join time (pinned by the torn-stream test via the
# snapshot.fetch.chunk faultline seam).

FETCH_CHUNK = 1 << 20


def completed_snapshot_dir(snapshots_root: str, ledger_id: str,
                           block_number: int) -> str:
    """The canonical completed/<lid>/<height> path; raises when absent."""
    path = os.path.join(
        snapshots_root, "completed", ledger_id, str(int(block_number))
    )
    if not os.path.isdir(path):
        raise SnapshotError(
            f"no completed snapshot for {ledger_id!r} at height "
            f"{block_number}"
        )
    return path


def list_completed(snapshots_root: str, ledger_id: str) -> list[int]:
    """Completed snapshot heights for a channel, ascending."""
    ldir = os.path.join(snapshots_root, "completed", ledger_id)
    if not os.path.isdir(ldir):
        return []
    return sorted(int(h) for h in os.listdir(ldir) if h.isdigit())


def stream_snapshot_dir(snapshot_dir: str):
    """Yield the frames of a completed snapshot directory: per chunk, a
    JSON header line + raw bytes.  The first frame is the manifest."""
    names = sorted(
        n for n in os.listdir(snapshot_dir)
        if os.path.isfile(os.path.join(snapshot_dir, n))
    )
    yield json.dumps(
        {"manifest": names, "snapshot": os.path.basename(snapshot_dir)},
        sort_keys=True,
    ).encode() + b"\n"
    for name in names:
        path = os.path.join(snapshot_dir, name)
        index = 0
        with open(path, "rb") as f:
            while True:
                chunk = f.read(FETCH_CHUNK)
                eof = len(chunk) < FETCH_CHUNK
                # torn-stream seam: an armed plan raising here cuts the
                # transfer mid-file; the receiver is left with a partial
                # directory that verify-on-import must refuse
                faultline.point(
                    "snapshot.fetch.chunk", file=name, index=index
                )
                header = json.dumps(
                    {"name": name, "eof": eof}, sort_keys=True
                ).encode() + b"\n"
                yield header + chunk
                index += 1
                if eof:
                    break


def receive_snapshot_stream(frames, dest_dir: str) -> str:
    """Rebuild a streamed snapshot directory under ``dest_dir``; returns
    the directory holding the received files.  Verification is the
    CALLER's job (create_from_snapshot / verify_snapshot) — a transport
    error mid-stream leaves a partial directory those refuse."""
    os.makedirs(dest_dir, exist_ok=True)
    open_files: dict[str, object] = {}
    try:
        it = iter(frames)
        first = next(it, None)
        if first is None:
            raise SnapshotError("empty snapshot stream")
        manifest = json.loads(first.split(b"\n", 1)[0].decode("utf-8"))
        if "manifest" not in manifest:
            raise SnapshotError("snapshot stream missing its manifest")
        for frame in it:
            header_line, chunk = frame.split(b"\n", 1)
            header = json.loads(header_line.decode("utf-8"))
            name = os.path.basename(header["name"])  # no path escapes
            f = open_files.get(name)
            if f is None:
                f = open_files[name] = open(
                    os.path.join(dest_dir, name), "wb"
                )
            f.write(chunk)
            if header.get("eof"):
                open_files.pop(name).close()
    finally:
        for f in open_files.values():
            f.close()
    return dest_dir


def fetch_snapshot(client, channel_id: str, block_number: int,
                   dest_dir: str) -> str:
    """Client half of ``admin.SnapshotFetch``: stream a remote peer's
    completed snapshot into ``dest_dir`` (``client`` is an RPCClient —
    or anything with .stream(method, body))."""
    body = json.dumps(
        {"channel": channel_id, "block_number": int(block_number)},
        sort_keys=True,
    ).encode()
    return receive_snapshot_stream(
        client.stream("admin.SnapshotFetch", body), dest_dir
    )


__all__ = [
    "SnapshotError",
    "SnapshotExistsError",
    "SnapshotManager",
    "SnapshotRequestBookkeeper",
    "generate_snapshot",
    "verify_snapshot",
    "import_snapshot",
    "import_marker",
    "IMPORT_IN_PROGRESS",
    "IMPORT_DONE",
    "load_metadata",
    "read_records",
    "write_records",
    "METADATA_FILE",
    "PUBLIC_STATE_FILE",
    "PVT_HASHES_FILE",
    "TXIDS_FILE",
    "CONFIG_HISTORY_FILE",
    "CONFIG_BLOCK_FILE",
    "DATA_FILES",
    "SNAPSHOT_FORMAT_VERSION",
    "completed_snapshot_dir",
    "list_completed",
    "stream_snapshot_dir",
    "receive_snapshot_stream",
    "fetch_snapshot",
    "FETCH_CHUNK",
]
