"""lscc (legacy lifecycle SCC) tests — install/deploy/upgrade/query
surface parity with reference core/scc/lscc/lscc.go."""

import hashlib

import pytest

from fabric_tpu.chaincode import ChaincodeSupport, InProcStream
from fabric_tpu.chaincode.lifecycle import PackageStore
from fabric_tpu.chaincode.lscc import LSCC, LegacyDefinitionProvider, NAMESPACE
from fabric_tpu.ledger.kvstore import MemKVStore
from fabric_tpu.ledger.statedb import Height, VersionedDB, VersionedValue
from fabric_tpu.ledger.txmgmt import TxSimulator
from fabric_tpu.protos.peer import chaincode_pb2, query_pb2
from fabric_tpu.protos.ledger.rwset import rwset_pb2
from fabric_tpu.protos.ledger.rwset.kvrwset import kv_rwset_pb2


def make_cds(name: str, version: str) -> bytes:
    return chaincode_pb2.ChaincodeDeploymentSpec(
        chaincode_spec=chaincode_pb2.ChaincodeSpec(
            chaincode_id=chaincode_pb2.ChaincodeID(name=name, version=version),
        ),
        code_package=b"legacy-code",
    ).SerializeToString()


@pytest.fixture
def world(tmp_path):
    support = ChaincodeSupport(invoke_timeout_s=5.0)
    store = PackageStore(str(tmp_path / "packages"))
    scc = LSCC(store)
    stream = InProcStream(support, scc, NAMESPACE)
    stream.start()
    stream.wait_registered(support, NAMESPACE)
    db = VersionedDB(MemKVStore())
    return support, db


def call(support, db, args, txid="tx"):
    sim = TxSimulator(db)
    resp, _ = support.execute(
        NAMESPACE, "ch", f"{txid}-{args[0].decode()}", sim, args
    )
    txrw = rwset_pb2.TxReadWriteSet.FromString(sim.get_tx_simulation_results())
    batch = {}
    for ns in txrw.ns_rwset:
        kv = kv_rwset_pb2.KVRWSet.FromString(ns.rwset)
        for w in kv.writes:
            batch.setdefault(ns.namespace, {})[w.key] = (
                None if w.is_delete else VersionedValue(w.value, Height(1, 1), b"")
            )
    if batch:
        db.apply_updates(batch, Height(1, 1))
    return resp


def test_install_deploy_query(world):
    support, db = world
    cds = make_cds("legcc", "1.0")
    assert call(support, db, [b"install", cds]).status == 200

    resp = call(support, db, [b"getinstalledchaincodes"])
    installed = query_pb2.ChaincodeQueryResponse.FromString(resp.payload)
    assert [(c.name, c.version) for c in installed.chaincodes] == [("legcc", "1.0")]

    resp = call(support, db, [b"deploy", b"ch", cds, b"policy-bytes"])
    assert resp.status == 200
    data = query_pb2.ChaincodeData.FromString(resp.payload)
    assert (data.name, data.version, data.escc, data.vscc) == (
        "legcc", "1.0", "escc", "vscc"
    )
    assert data.id == hashlib.sha256(cds).digest()

    # duplicate deploy refused; upgrade of a missing chaincode refused
    assert call(support, db, [b"deploy", b"ch", cds, b""]).status != 200
    other = make_cds("nope", "1.0")
    assert call(support, db, [b"upgrade", b"ch", other, b""]).status != 200

    # upgrade bumps version
    cds2 = make_cds("legcc", "2.0")
    resp = call(support, db, [b"upgrade", b"ch", cds2, b"p2"])
    assert resp.status == 200

    resp = call(support, db, [b"getccdata", b"ch", b"legcc"])
    data = query_pb2.ChaincodeData.FromString(resp.payload)
    assert data.version == "2.0" and data.policy == b"p2"

    resp = call(support, db, [b"getid", b"ch", b"legcc"])
    assert resp.payload == hashlib.sha256(cds2).digest()

    resp = call(support, db, [b"getchaincodes"])
    allcc = query_pb2.ChaincodeQueryResponse.FromString(resp.payload)
    assert [(c.name, c.version) for c in allcc.chaincodes] == [("legcc", "2.0")]

    # getdepspec needs the (installed) package for the committed version
    assert call(support, db, [b"install", cds2]).status == 200
    resp = call(support, db, [b"getdepspec", b"ch", b"legcc"])
    assert resp.status == 200 and resp.payload == cds2


def test_name_version_rules(world):
    support, db = world
    bad = make_cds("9bad", "1.0")
    assert call(support, db, [b"install", bad]).status != 200
    bad2 = make_cds("okname", "sp ace")
    assert call(support, db, [b"deploy", b"ch", bad2, b""]).status != 200


def test_legacy_definition_provider(world):
    support, db = world
    cds = make_cds("provcc", "1.0")
    call(support, db, [b"deploy", b"ch", cds, b"the-policy"])

    class _Ledger:
        def new_query_executor(self):
            class _QE:
                def get_state(self, ns, key):
                    vv = db.get_state(ns, key)
                    return vv.value if vv else None
            return _QE()

    prov = LegacyDefinitionProvider(_Ledger())
    assert prov.validation_info("provcc") == ("vscc", b"the-policy")
    assert prov.validation_info("missing") is None
