"""fabric-tpu benchmark entry point.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

North-star metric (BASELINE.json / BASELINE.md): **committed tx/s** for
1000-tx blocks under a 3-of-5 (MAJORITY over 5 orgs) endorsement policy
— and this round the timed loop really commits: every measured run
drives `Committer.store_stream`, so MVCC validation, block-file append,
state-DB apply, and history indexing are all inside the measurement
(reference kvledger CommitLegacy, core/ledger/kvledger/kv_ledger.go:447-530,
downstream of txvalidator v20, validator.go:180-265).  The ledger is
on-disk (block files + sqlite WAL), matching the reference's
blockfile+leveldb persistence.

Baseline is the *faithful* reference-shaped host path: sequential
per-signature `ecdsa.Verify` with every sub-policy re-verifying its
signatures per tx, no verify-item interning / plan caching / creator
memo (bccsp/sw/ecdsa.go:41 + common/policies/policy.go:365-402
semantics), committing each block serially after validation the way
coordinator.StoreBlock does (gossip/privdata/coordinator.go:149).

Fairness: BOTH sides take best-of-N with the SAME N (4) over fresh
on-disk ledgers, after one warmup each — on a time-shared chip/host an
asymmetric N would score scheduling luck, not the pipeline
(round-4 verdict, weak #5).

Also reported: p99 block-validate latency (the second north-star
metric) over every per-block validate duration observed on the
measured path.

Two storage-focused modes ride along (PR 17 storage engine v2), both
on the ``devtools/netident`` fake-identity plane so they run in
minimal containers without the ``cryptography`` package — the real
TxValidator, Committer.store_stream, MVCC, and the full on-disk ledger
stack are all inside the measurement; only signature math is faked:

* ``--sweep-storage`` — one JSON line per shards x sqlite-sync x
  segment-size combo over a best-of-2 commit stream, echoing the
  storage config in the line (mirrors ``--sweep-sqlite``);
* ``--scenario smallbank`` — hot-key read-modify-write payments over
  checking/savings accounts, each block endorsed one block behind its
  commit so hot keys storm into intra-block MVCC conflicts; reports
  committed vs conflicted and the same trace/profile artifacts.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

_ROOT = os.path.dirname(os.path.abspath(__file__))


def _setup_path() -> None:
    for p in (_ROOT, os.path.join(_ROOT, "scripts"), os.path.join(_ROOT, "tests")):
        if p not in sys.path:
            sys.path.insert(0, p)


# -- storage-v2 modes (netident plane: no `cryptography` needed) -------------


def _fake_env(channel: str, cc: str, rwset: bytes, tag: str) -> bytes:
    """A policy-satisfying endorser envelope over a caller-simulated
    rwset (netident.make_tx fixes its own write-only rwset; the
    smallbank scenario needs read-modify-write sets simulated against
    the live build ledger)."""
    from fabric_tpu import protoutil
    from fabric_tpu.common.hashing import sha256
    from fabric_tpu.devtools import netident
    from fabric_tpu.protos.common import common_pb2
    from fabric_tpu.protos.peer import (
        proposal_pb2,
        proposal_response_pb2,
        transaction_pb2,
    )

    creator = b"cre:bench-client"
    nonce = sha256(b"nonce:%s:%s" % (channel.encode(), tag.encode()))
    txid = protoutil.compute_tx_id(nonce, creator)
    ext = proposal_pb2.ChaincodeHeaderExtension()
    ext.chaincode_id.name = cc
    chdr = protoutil.make_channel_header(
        common_pb2.ENDORSER_TRANSACTION, channel, tx_id=txid,
        extension=ext.SerializeToString(), timestamp=0,
    )
    shdr = protoutil.make_signature_header(creator, nonce)
    chdr_b = chdr.SerializeToString()
    shdr_b = shdr.SerializeToString()
    ccpp_b = proposal_pb2.ChaincodeProposalPayload(
        input=b"input:" + tag.encode()
    ).SerializeToString()
    action = proposal_pb2.ChaincodeAction(results=rwset)
    action.chaincode_id.name = cc
    prp = proposal_response_pb2.ProposalResponsePayload(
        proposal_hash=protoutil.proposal_hash2(chdr_b, shdr_b, ccpp_b),
        extension=action.SerializeToString(),
    )
    prp_b = prp.SerializeToString()
    endos = [
        proposal_response_pb2.Endorsement(
            endorser=eb,
            signature=netident.sign_as(eb, sha256(prp_b + eb)),
        )
        for eb in netident.org_endorsers(3)
    ]
    cap = transaction_pb2.ChaincodeActionPayload(
        chaincode_proposal_payload=ccpp_b,
        action=transaction_pb2.ChaincodeEndorsedAction(
            proposal_response_payload=prp_b, endorsements=endos
        ),
    )
    tx = transaction_pb2.Transaction(actions=[
        transaction_pb2.TransactionAction(payload=cap.SerializeToString())
    ])
    payload_b = common_pb2.Payload(
        header=common_pb2.Header(
            channel_header=chdr_b, signature_header=shdr_b
        ),
        data=tx.SerializeToString(),
    ).SerializeToString()
    return common_pb2.Envelope(
        payload=payload_b,
        signature=netident.sign_as(creator, sha256(payload_b)),
    ).SerializeToString()


def _seal_block(blk, prev_hash: bytes):
    from fabric_tpu import protoutil

    blk.header.previous_hash = prev_hash
    blk.header.data_hash = protoutil.block_data_hash(blk.data)
    protoutil.init_block_metadata(blk)
    protoutil.set_tx_filter(blk, bytearray(len(blk.data.data)))
    return blk


def _storage_stream_world(channel: str, n_txs: int, n_blocks: int):
    """Pre-built uniform commit stream for the storage sweep: write-only
    txs (always MVCC-valid) across 8 chaincode namespaces, so every
    shard width has real fan-out.  Returns (genesis, bundle, csp,
    blocks) — blocks chained from genesis, numbers 1..n_blocks."""
    from fabric_tpu import protoutil
    from fabric_tpu.devtools import netident
    from fabric_tpu.ledger import LedgerProvider
    from fabric_tpu.protos.common import common_pb2

    genesis = netident.make_genesis(channel)
    provider = LedgerProvider(None)
    ledger = provider.create(genesis)
    blocks = []
    prev = protoutil.block_header_hash(genesis.header)
    for bno in range(n_blocks):
        blk = common_pb2.Block()
        blk.header.number = 1 + bno
        for i in range(n_txs):
            sim = ledger.new_tx_simulator()
            cc = f"cc{i % 8}"
            sim.set_state(cc, f"k{bno}-{i}", b"v" * 128)
            blocks_tag = f"b{bno}t{i}"
            blk.data.data.append(_fake_env(
                channel, cc, sim.get_tx_simulation_results(), blocks_tag
            ))
        _seal_block(blk, prev)
        prev = protoutil.block_header_hash(blk.header)
        blocks.append(blk)
    provider.close()
    return genesis, netident.FakeBundle(), netident.FakeCSP(), blocks


def _run_fake_stream(genesis, bundle, csp, blocks, root: str,
                     passes: int = 2, depth: int = 6):
    """Best-of-N Committer.store_stream over fresh on-disk ledgers;
    returns (best_seconds, commit_stages, flags_of_best)."""
    import copy as _copy

    from fabric_tpu.ledger import LedgerProvider
    from fabric_tpu.peer.committer import Committer
    from fabric_tpu.peer.txvalidator import TxValidator

    best = float("inf")
    stages: dict = {}
    best_flags: list[list[int]] = []
    for p in range(passes):
        provider = LedgerProvider(os.path.join(root, f"p{p}"))
        led = provider.create(genesis)
        committer = Committer(
            TxValidator("benchch", led, bundle, csp), led
        )
        bs = [_copy.deepcopy(b) for b in blocks]
        flags: list[list[int]] = []
        t0 = time.perf_counter()
        for f in committer.store_stream(iter(bs), depth=depth):
            flags.append(list(f))
        dt = time.perf_counter() - t0
        assert led.height == 1 + len(blocks)
        if dt < best:
            best = dt
            stages = dict(led.commit_stage_seconds)
            best_flags = flags
        provider.close()
    return best, stages, best_flags


def _sweep_storage() -> None:
    """One JSON line per shards x sqlite-sync x segment combo, each over
    a best-of-2 uniform commit stream — the storage-v2 A/B scoreboard
    (shards=1 + 16m is the pre-v2 single-file shape)."""
    # same WAL-checkpoint shape as the main bench path (main() sets it
    # after this mode has already dispatched)
    os.environ.setdefault("FABRIC_TPU_WAL_CHECKPOINT", "4000")
    n_txs, n_blocks = 400, 8
    genesis, bundle, csp, blocks = _storage_stream_world(
        "benchch", n_txs, n_blocks
    )
    tmp = tempfile.TemporaryDirectory(prefix="fabric-bench-storage-")
    combo = 0
    for shards in (1, 2, 4):
        for sync in ("NORMAL", "FULL"):
            for seg in ("1m", "16m"):
                combo += 1
                os.environ["FABRIC_TPU_STORE_SHARDS"] = str(shards)
                os.environ["FABRIC_TPU_SQLITE_SYNC"] = sync
                os.environ["FABRIC_TPU_STORE_SEGMENT"] = seg
                best, stages, flags = _run_fake_stream(
                    genesis, bundle, csp, blocks,
                    os.path.join(tmp.name, f"c{combo}"),
                )
                assert all(
                    f == 0 for blk in flags for f in blk
                ), "uniform stream must commit clean"
                line = {
                    "metric": "storage_sweep_tx_per_s",
                    "shards": shards,
                    "synchronous": sync,
                    "segment": seg,
                    "value": round(n_blocks * n_txs / best, 2),
                    "unit": "tx/s",
                    "fsync_ms": round(
                        stages.get("fsync", 0.0) * 1e3, 2
                    ),
                    "kv_txn_ms": round(
                        stages.get("kv_txn", 0.0) * 1e3, 2
                    ),
                }
                for k in sorted(stages):
                    if k.startswith("kv_") and k != "kv_txn":
                        line[f"{k}_ms"] = round(stages[k] * 1e3, 2)
                print(json.dumps(line))
    for k in ("FABRIC_TPU_STORE_SHARDS", "FABRIC_TPU_SQLITE_SYNC",
              "FABRIC_TPU_STORE_SEGMENT"):
        del os.environ[k]
    sys.stdout.flush()
    from fabric_tpu.common import workpool

    workpool.shutdown()
    tmp.cleanup()


def _scenario_smallbank(trace_out: str | None,
                        profile_out: str | None) -> None:
    """Hot-key contention scoreboard (workload-zoo seed): payment txs
    read-modify-write checking balances with a quarter of the endpoints
    drawn from 10 hot accounts, each block endorsed one block behind its
    commit (the endorse->order->commit staleness), so every block
    storms into intra-block MVCC read conflicts on the hot keys — the
    conflict-heavy counterpart to the uniform canned stream.  Reports
    committed vs conflicted (deterministic across passes) plus the
    usual stage splits and artifacts."""
    import random

    from fabric_tpu import protoutil
    from fabric_tpu.common import profile, tracing
    from fabric_tpu.devtools import netident
    from fabric_tpu.ledger import LedgerProvider
    from fabric_tpu.peer.committer import Committer
    from fabric_tpu.peer.txvalidator import TxValidator
    from fabric_tpu.protos.common import common_pb2

    os.environ.setdefault("FABRIC_TPU_WAL_CHECKPOINT", "4000")
    channel = "benchch"
    n_accounts, n_hot, hot_prob = 1000, 10, 0.25
    n_txs, n_blocks = 400, 6
    rng = random.Random(11)
    accounts = [f"acct{a:04d}" for a in range(n_accounts)]

    genesis = netident.make_genesis(channel)
    provider = LedgerProvider(None)
    ledger = provider.create(genesis)

    # block 1 seeds every checking/savings balance in one tx
    sim = ledger.new_tx_simulator()
    for a in accounts:
        sim.set_state("checking", a, b"1000")
        sim.set_state("savings", a, b"1000")
    seed_blk = common_pb2.Block()
    seed_blk.header.number = 1
    seed_blk.data.data.append(_fake_env(
        channel, "checking", sim.get_tx_simulation_results(), "seed"
    ))
    _seal_block(seed_blk, protoutil.block_header_hash(genesis.header))
    ledger.commit(seed_blk)  # endorsements below read the seeded state

    def pick() -> str:
        if rng.random() < hot_prob:
            return accounts[rng.randrange(n_hot)]
        return accounts[rng.randrange(n_accounts)]

    blocks = []
    prev = protoutil.block_header_hash(seed_blk.header)
    for bno in range(n_blocks):
        blk = common_pb2.Block()
        blk.header.number = 2 + bno
        for i in range(n_txs):
            src = pick()
            dst = pick()
            while dst == src:
                dst = accounts[rng.randrange(n_accounts)]
            s = ledger.new_tx_simulator()
            a = int(s.get_state("checking", src) or b"0")
            b = int(s.get_state("checking", dst) or b"0")
            s.get_state("savings", src)  # overdraft check reads savings
            s.set_state("checking", src, b"%d" % (a - 1))
            s.set_state("checking", dst, b"%d" % (b + 1))
            blk.data.data.append(_fake_env(
                channel, "checking", s.get_tx_simulation_results(),
                f"pay-b{bno}t{i}",
            ))
        _seal_block(blk, prev)
        prev = protoutil.block_header_hash(blk.header)
        blocks.append(blk)
        # advance the build ledger one block behind endorsement (the
        # realistic endorse->order->commit staleness): block k+1's
        # reads see block k's WINNERS, so conflicts come from hot-key
        # contention inside each block, not from a saturating cascade
        import copy as _copy

        ledger.commit(_copy.deepcopy(blk))
    provider.close()

    if (trace_out or profile_out) and not tracing.enabled():
        tracing.arm()
    if profile_out and not profile.enabled():
        profile.arm()

    import copy as _copy

    bundle, csp = netident.FakeBundle(), netident.FakeCSP()
    tmp = tempfile.TemporaryDirectory(prefix="fabric-bench-smallbank-")
    best = float("inf")
    stages: dict = {}
    best_flags: list[int] = []
    trace = prof = None
    per_pass_flags = []
    for p in range(2):
        if tracing.enabled():
            tracing.reset()
        if profile.enabled():
            profile.reset()
        prov = LedgerProvider(os.path.join(tmp.name, f"p{p}"))
        led = prov.create(genesis)
        committer = Committer(
            TxValidator(channel, led, bundle, csp), led
        )
        sf = committer.store_block(_copy.deepcopy(seed_blk))
        assert all(f == 0 for f in sf), "the seed block must be clean"
        bs = [_copy.deepcopy(b) for b in blocks]
        flags: list[int] = []
        t0 = time.perf_counter()
        for f in committer.store_stream(iter(bs), depth=6):
            flags.extend(f)
        dt = time.perf_counter() - t0
        assert led.height == 2 + n_blocks
        per_pass_flags.append(flags)
        if dt < best:
            best = dt
            stages = dict(led.commit_stage_seconds)
            best_flags = flags
            if tracing.enabled():
                trace = tracing.export()
            if profile.enabled():
                prof = profile.export("bench.smallbank")
        prov.close()
    # the conflict outcome is part of the scoreboard's contract: same
    # blocks, same order -> byte-identical flags on every pass
    assert per_pass_flags[0] == per_pass_flags[1], \
        "smallbank flags must be deterministic"

    committed = sum(1 for f in best_flags if f == 0)
    conflicted = len(best_flags) - committed
    by_code: dict = {}
    for f in best_flags:
        if f:
            by_code[str(f)] = by_code.get(str(f), 0) + 1
    from fabric_tpu.ledger.blkstorage import segment_size
    from fabric_tpu.ledger.kvstore import store_shards
    from fabric_tpu.ledger.kvstore import _sqlite_sync_level as _sync

    line = {
        "metric": "smallbank_committed_tx_per_s",
        "scenario": "smallbank",
        "value": round(committed / best, 2),
        "unit": "tx/s",
        "attempted_tx_per_s": round(len(best_flags) / best, 2),
        "attempted": len(best_flags),
        "committed": committed,
        "conflicted": conflicted,
        "conflict_rate": round(conflicted / len(best_flags), 4),
        "invalid_by_code": by_code,
        "accounts": n_accounts,
        "hot_accounts": n_hot,
        "hot_prob": hot_prob,
        "commit_stage_ms": {
            k: round(v * 1e3, 2) for k, v in sorted(stages.items())
        },
        "storage": {
            "shards": store_shards(),
            "segment": segment_size(None),
            "synchronous": _sync(None),
        },
    }
    if trace_out and trace is not None:
        with open(trace_out, "w", encoding="utf-8") as f:
            json.dump(trace, f, indent=1, sort_keys=True)
            f.write("\n")
        line["trace_out"] = trace_out
    if profile_out and prof is not None:
        from fabric_tpu.common import profile as _profile

        _profile.dump_to(profile_out, prof)
        line["self_cpu_ms"] = prof["otherData"]["self_cpu_ms"]
        line["profile_out"] = profile_out
        _profile.disarm()
    print(json.dumps(line))
    sys.stdout.flush()
    from fabric_tpu.common import workpool

    workpool.shutdown()
    tmp.cleanup()


def main() -> None:
    _setup_path()

    scenario = None
    if "--scenario" in sys.argv:
        i = sys.argv.index("--scenario")
        if i + 1 >= len(sys.argv) or sys.argv[i + 1].startswith("--"):
            sys.exit("bench.py: --scenario requires a NAME argument")
        scenario = sys.argv[i + 1]
        if scenario != "smallbank":
            sys.exit(f"bench.py: unknown scenario {scenario!r}")
    early_trace = None
    if "--trace-out" in sys.argv:
        i = sys.argv.index("--trace-out")
        if i + 1 >= len(sys.argv) or sys.argv[i + 1].startswith("--"):
            sys.exit("bench.py: --trace-out requires a PATH argument")
        early_trace = sys.argv[i + 1]
    early_profile = None
    if "--profile-out" in sys.argv:
        i = sys.argv.index("--profile-out")
        if i + 1 >= len(sys.argv) or sys.argv[i + 1].startswith("--"):
            sys.exit("bench.py: --profile-out requires a PATH argument")
        early_profile = sys.argv[i + 1]
    if "--sweep-storage" in sys.argv:
        _sweep_storage()
        return
    if scenario == "smallbank":
        _scenario_smallbank(early_trace, early_profile)
        return

    from bench_pipeline import _build_world, _make_blocks

    from fabric_tpu.csp import SWCSP
    from fabric_tpu.ledger import LedgerProvider
    from fabric_tpu.ledger.kvstore import (
        _sqlite_sync_level as _sync_level,
        _sqlite_wal_checkpoint as _wal_ckpt,
    )
    from fabric_tpu.peer.committer import Committer
    from fabric_tpu.peer.txvalidator import TxValidator
    from fabric_tpu.protos.common import common_pb2

    sweep_sqlite = "--sweep-sqlite" in sys.argv
    trace_out = early_trace
    profile_out = early_profile

    # sqlite tuning applied to BOTH sides (baseline and measured): a
    # larger WAL autocheckpoint keeps checkpoint I/O out of the timed
    # window — durability-neutral, checkpoint timing never affects
    # crash safety (the WAL replays either way).  `synchronous` stays
    # at the safe NORMAL default the chaos matrix proves;
    # `--sweep-sqlite` measures the full knob matrix.
    os.environ.setdefault("FABRIC_TPU_WAL_CHECKPOINT", "4000")

    n_txs, n_blocks = 1000, 8
    sw = SWCSP()
    orgs, genesis = _build_world(5)
    _, bundle, blocks = _make_blocks(orgs, genesis, sw, n_txs, 3, n_blocks)

    def copies(k):
        out = []
        for j in range(k):
            b = common_pb2.Block()
            b.CopyFrom(blocks[j % n_blocks])
            out.append(b)
        return out

    tmp = tempfile.TemporaryDirectory(prefix="fabric-bench-")
    fresh_n = [0]

    def fresh_ledger():
        """A brand-new on-disk ledger (block files + sqlite WAL) holding
        only the genesis block — every timed run commits 1..n_blocks."""
        fresh_n[0] += 1
        provider = LedgerProvider(os.path.join(tmp.name, f"run{fresh_n[0]}"))
        return provider.create(genesis)

    # -- baseline: faithful host path, serial validate -> commit ----------
    warm = Committer(
        TxValidator("benchch", (wl := fresh_ledger()), bundle, sw, faithful=True),
        wl,
    )
    warm.store_block(copies(1)[0])  # EC backend init, native lib, protos
    baseline = None
    if not sweep_sqlite:  # the sweep compares combos, not vs-host
        base_best = float("inf")
        for _ in range(4):
            led = fresh_ledger()
            committer = Committer(
                TxValidator("benchch", led, bundle, sw, faithful=True), led
            )
            bs = copies(n_blocks)
            t0 = time.perf_counter()
            for b in bs:
                flags = committer.store_block(b)
                assert all(f == 0 for f in flags)
            base_best = min(base_best, time.perf_counter() - t0)
            assert led.height == 1 + n_blocks
        baseline = n_blocks * n_txs / base_best

    # -- measured: pipelined validate+commit stream, TPU batch verify -----
    try:
        from fabric_tpu.csp.tpu.provider import TPUCSP

        # flush/depth point measured on the real chip (round-5 sweep):
        # ~1-block flushes at depth 6 beat the old 2-block flushes at
        # depth 4 — the fixed dispatch cost amortizes worse than the
        # lost overlap from waiting for a second block's lanes
        csp = TPUCSP(min_device_batch=1, coalesce_lanes=4096)
        wl2 = fresh_ledger()
        Committer(
            TxValidator("benchch", wl2, bundle, csp), wl2
        ).store_block(copies(1)[0])  # compile + first transfer
    except Exception:
        csp = sw

    def run_stream(passes: int = 4):
        """Best-of-N pipelined validate+commit stream; returns
        (best_seconds, commit_stages, validate_stages, trace, prof) of
        the winning pass.  The provider is drained before every pass
        for the same reason the p99 loop drains: a prior pass's
        host-raced flush can leave the device leg still crunching, and
        that tail must not become the next pass's head.  Under
        --trace-out the flight recorder resets per pass and the WINNING
        pass's export is kept — the artifact matches the measured
        number; --profile-out holds profscope's aggregate to the same
        contract."""
        from fabric_tpu.common import profile, tracing

        best = float("inf")
        commit_stages: dict = {}
        validate_stages: dict = {}
        trace: dict | None = None
        prof: dict | None = None
        stream_drain = getattr(csp, "drain", None)
        for _ in range(passes):
            if stream_drain is not None:
                stream_drain()
            if tracing.enabled():
                tracing.reset()
            if profile.enabled():
                profile.reset()
            led = fresh_ledger()
            validator = TxValidator("benchch", led, bundle, csp)
            committer = Committer(validator, led)
            bs = copies(n_blocks)
            t0 = time.perf_counter()
            for flags in committer.store_stream(iter(bs), depth=6):
                assert all(f == 0 for f in flags)
            dt = time.perf_counter() - t0
            if dt < best:
                best = dt
                # per-stage breakdowns of the winning run (the same
                # numbers the operations /metrics endpoint exposes as
                # ledger_commit_stage_duration /
                # validator_block_stage_duration histograms)
                commit_stages = dict(led.commit_stage_seconds)
                validate_stages = dict(validator.validate_stage_seconds)
                if tracing.enabled():
                    trace = tracing.export()
                if profile.enabled():
                    prof = profile.export("bench.stream")
            assert led.height == 1 + n_blocks
        return best, commit_stages, validate_stages, trace, prof

    if sweep_sqlite:
        # durability sweep: one JSON line per synchronous/checkpoint
        # combo, each over a shortened best-of-2 measured stream with
        # the env knobs set before the combo's fresh on-disk ledgers
        # are created (SqliteKVStore reads them at open)
        for sync in ("OFF", "NORMAL", "FULL"):
            for ckpt in (250, 1000, 4000):
                os.environ["FABRIC_TPU_SQLITE_SYNC"] = sync
                os.environ["FABRIC_TPU_WAL_CHECKPOINT"] = str(ckpt)
                best, stages, _vstages, _trace, _prof = run_stream(
                    passes=2
                )
                print(json.dumps({
                    "metric": "sqlite_sweep_tx_per_s",
                    "synchronous": sync,
                    "wal_autocheckpoint": ckpt,
                    "value": round(n_blocks * n_txs / best, 2),
                    "unit": "tx/s",
                    "fsync_ms": round(
                        stages.get("fsync", 0.0) * 1e3, 2
                    ),
                    "kv_txn_ms": round(
                        stages.get("kv_txn", 0.0) * 1e3, 2
                    ),
                }))
        del os.environ["FABRIC_TPU_SQLITE_SYNC"]
        del os.environ["FABRIC_TPU_WAL_CHECKPOINT"]
        sys.stdout.flush()
        _quiesce(csp)
        tmp.cleanup()
        return

    # tracing/profiling arm AFTER the baseline measurement so the
    # (already near-zero) armed-path overhead cannot skew the
    # vs-baseline ratio; the measured side carries it inside the
    # traced/profiled passes by design
    if trace_out or profile_out:
        from fabric_tpu.common import tracing

        if not tracing.enabled():
            # FABRIC_TPU_TRACE=N may have armed a user-sized ring at
            # import; only arm the default when nothing is armed yet.
            # --profile-out arms it too: the sampler attributes CPU to
            # live tracelens spans (self_cpu_ms), which needs spans
            tracing.arm()
        from fabric_tpu.common import workpool as _workpool

        _workpool.reset_stats()
    if profile_out:
        from fabric_tpu.common import profile

        if not profile.enabled():
            # FABRIC_TPU_PROFILE may have armed a tuned cadence
            profile.arm()

    best, commit_stages, validate_stages, trace, prof = run_stream()
    value = n_blocks * n_txs / best

    # -- p99 block-validate latency on the measured path ------------------
    # (the reference logs per-block validate duration, validator.go:261;
    # here every serial validate() wall time over 3 fresh-ledger passes).
    # The provider is DRAINED between passes: pass N's last async verify
    # otherwise still holds device lanes when pass N+1's first block
    # dispatches, inflating that block's wall time — the tail of one
    # pass must not become the head of the next.
    lat = []
    drain = getattr(csp, "drain", None)
    for _ in range(3):
        if drain is not None:
            drain()
        led = fresh_ledger()
        v = TxValidator("benchch", led, bundle, csp)
        for b in copies(n_blocks):
            t0 = time.perf_counter()
            flags = v.validate(b)
            lat.append(time.perf_counter() - t0)
            assert all(f == 0 for f in flags)
            led.commit(b)
    lat.sort()
    p99 = lat[min(len(lat) - 1, int(0.99 * len(lat)))]

    line = {
        "metric": "committed_tx_per_s_1000tx_3of5_stream",
        "value": round(value, 2),
        "unit": "tx/s",
        "vs_baseline": round(value / baseline, 3),
        "baseline_tx_per_s": round(baseline, 2),
        "p99_block_validate_ms": round(p99 * 1e3, 2),
        "commit_stage_ms": {
            k: round(v * 1e3, 2)
            for k, v in sorted(commit_stages.items())
        },
        "validate_stage_ms": {
            k: round(v * 1e3, 2)
            for k, v in sorted(validate_stages.items())
        },
        "sqlite": {
            "synchronous": _sync_level(None),
            "wal_autocheckpoint": _wal_ckpt(None),
        },
    }
    if trace_out and trace is not None:
        from fabric_tpu.common import tracing
        from fabric_tpu.common import workpool as _workpool

        with open(trace_out, "w", encoding="utf-8") as f:
            json.dump(trace, f, indent=1, sort_keys=True)
            f.write("\n")
        # per-block critical path over the winning pass's stage spans:
        # which stages actually gated the wall clock (summed ms across
        # blocks), vs the plain busy-time sums above
        line["critical_path_ms"] = {
            k: round(v, 2)
            for k, v in sorted(tracing.critical_path_ms(
                trace["traceEvents"]
            ).items())
        }
        line["trace_out"] = trace_out
        line["workpool"] = _workpool.stats()
    if profile_out and prof is not None:
        from fabric_tpu.common import profile

        profile.dump_to(profile_out, prof)
        # per-stage CPU attribution of the winning pass (sampler time
        # inside each live span) — read next to critical_path_ms:
        # busy-CPU vs wall-gating per stage
        line["self_cpu_ms"] = prof["otherData"]["self_cpu_ms"]
        line["profile_out"] = profile_out
        # stop the sampler service thread before teardown (same
        # reasoning as _quiesce joining the flush waiters)
        profile.disarm()
    print(json.dumps(line))
    sys.stdout.flush()
    # quiesce the device provider AFTER the one JSON line is out (a
    # wedged chip must not discard completed measurements) but BEFORE
    # interpreter exit: joining the flush waiters is what lets teardown
    # run cleanly — a tpu-flush-waiter still inside an XLA kernel at
    # exit is killed mid-unwind and glibc aborts with "FATAL: exception
    # not rethrown" (the old os._exit(0) workaround this close
    # replaces).  close() is the indefinite join: exiting under a live
    # waiter would reproduce the abort, while a genuinely wedged chip
    # is the harness timeout's problem.
    _quiesce(csp)
    tmp.cleanup()


def _quiesce(csp) -> None:
    """Join every worker this process spun up: the CSP's flush waiters
    AND the shared host work pool behind the parallel collect/prepare
    stages — a pool worker alive at interpreter exit is the same
    teardown hazard as a flush waiter."""
    close = getattr(csp, "close", None)
    if close is not None:
        close()
    from fabric_tpu.common import workpool

    workpool.shutdown()


if __name__ == "__main__":
    main()
    sys.stdout.flush()
