"""Wycheproof-style ECDSA-P256 vector corpus, run against all three
verification implementations:

  1. host `sw` (OpenSSL via cryptography; reference bccsp/sw/ecdsa.go:41-57)
  2. the XLA batch kernel (csp/tpu/ec.py prepare_batch/verify_prepared)
  3. the native DER parser + packed Pallas kernel
     (native/marshal.cc fabric_marshal_batch -> pallas_ec.verify_packed,
      interpret mode)

Every vector carries an expected accept/reject verdict; all paths must
agree bit-for-bit.  Covers: malleable/non-canonical DER (long-form
lengths, non-minimal integers, trailing bytes, truncation, BER
indefinite length, wrong tags), boundary scalars r,s ∈ {0, 1, n-1, n,
n+...}, high-S rejection, legitimate leading-zero encodings, wrong
digests, and (separately) off-curve / point-at-infinity public keys,
which the key-load layer must refuse to construct (the reference parses
keys through crypto/x509, which enforces on-curve).
"""

from __future__ import annotations

import hashlib

import numpy as np
import pytest

from fabric_tpu.csp import SWCSP
from fabric_tpu.csp import api
from fabric_tpu.csp.api import P256_N, VerifyBatchItem

HALF_N = P256_N >> 1


def _der_int(value_bytes: bytes) -> bytes:
    return b"\x02" + bytes([len(value_bytes)]) + value_bytes


def _minimal(i: int) -> bytes:
    raw = i.to_bytes((i.bit_length() + 7) // 8 or 1, "big")
    if raw[0] & 0x80:
        raw = b"\x00" + raw
    return raw


def _der_sig(r: int, s: int, r_bytes: bytes | None = None,
             s_bytes: bytes | None = None, seq_tag: int = 0x30,
             long_len: bool = False, trailer: bytes = b"") -> bytes:
    rb = _der_int(r_bytes if r_bytes is not None else _minimal(r))
    sb = _der_int(s_bytes if s_bytes is not None else _minimal(s))
    body = rb + sb
    if long_len:
        hdr = bytes([seq_tag, 0x81, len(body)])
    else:
        hdr = bytes([seq_tag, len(body)])
    return hdr + body + trailer


@pytest.fixture(scope="module")
def world():
    sw = SWCSP()
    key = sw.key_gen()
    digest = hashlib.sha256(b"wycheproof").digest()
    sig = sw.sign(key, digest)
    r, s = api.unmarshal_ecdsa_signature(sig)
    assert s <= HALF_N  # sw signs low-S
    return sw, key, digest, r, s


def _vectors(r: int, s: int, digest: bytes):
    """(name, sig_bytes, digest, expect_ok) — DER/scalar-level corpus."""
    good = _der_sig(r, s)
    other_digest = hashlib.sha256(b"other").digest()
    return [
        ("valid", good, digest, True),
        ("valid_roundtrip_matches_marshal",
         api.marshal_ecdsa_signature(r, s), digest, True),
        ("wrong_digest", good, other_digest, False),
        ("short_digest", good, digest[:31], False),
        ("long_digest", good, digest + b"\x00", False),
        # -- boundary scalars ------------------------------------------
        ("r_zero", _der_sig(0, s), digest, False),
        ("s_zero", _der_sig(r, 0), digest, False),
        ("r_eq_n", _der_sig(P256_N, s), digest, False),
        ("s_eq_n", _der_sig(r, P256_N), digest, False),
        ("r_eq_n_minus_1", _der_sig(P256_N - 1, s), digest, False),
        ("s_eq_n_minus_1", _der_sig(r, P256_N - 1), digest, False),
        ("r_one", _der_sig(1, s), digest, False),
        ("s_one", _der_sig(r, 1), digest, False),
        # high-S: the complement verifies mathematically but MUST be
        # rejected by the low-S rule (bccsp/utils/ecdsa.go IsLowS)
        ("high_s_complement", _der_sig(r, P256_N - s), digest, False),
        ("r_plus_n", _der_sig(r + P256_N, s), digest, False),
        # -- DER malleability ------------------------------------------
        ("neg_r_encoding", _der_sig(r, s, r_bytes=_minimal(r)[1:]
                                    if _minimal(r)[0] == 0 else
                                    b"\xff" + _minimal(r)), digest, False),
        ("nonminimal_r_leading_zero",
         _der_sig(r, s, r_bytes=b"\x00" + _minimal(r)), digest, False),
        ("nonminimal_s_leading_zero",
         _der_sig(r, s, s_bytes=b"\x00" + _minimal(s)), digest, False),
        ("long_form_length", _der_sig(r, s, long_len=True), digest, False),
        ("trailing_garbage", _der_sig(r, s, trailer=b"\x00"), digest, False),
        ("truncated", good[:-1], digest, False),
        ("truncated_header", good[:1], digest, False),
        ("empty_sig", b"", digest, False),
        ("wrong_seq_tag", _der_sig(r, s, seq_tag=0x31), digest, False),
        ("ber_indefinite_length",
         b"\x30\x80" + _der_int(_minimal(r)) + _der_int(_minimal(s))
         + b"\x00\x00", digest, False),
        ("int_tag_wrong",
         b"\x30" + bytes([len(_minimal(r)) + len(_minimal(s)) + 4])
         + b"\x03" + bytes([len(_minimal(r))]) + _minimal(r)
         + _der_int(_minimal(s)), digest, False),
    ]


def _expected_and_names(world):
    sw, key, digest, r, s = world
    vecs = _vectors(r, s, digest)
    names = [v[0] for v in vecs]
    expect = [v[3] for v in vecs]
    items = [VerifyBatchItem(key.public_key(), v[2], v[1]) for v in vecs]
    return names, expect, items


def test_sw_path(world):
    sw, *_ = world
    names, expect, items = _expected_and_names(world)
    got = sw.verify_batch(items)
    for n, e, g in zip(names, expect, got):
        assert g == e, f"sw disagrees on {n}: got {g}, want {e}"


def test_native_host_verify_path(world):
    """The libcrypto batch verifier (native/ecverify.cc — the TPU
    provider's chip-stall fallback) must agree with the sw oracle on
    the full DER/scalar corpus: a laxer native parse would let a
    stalled-chip window change which signatures a block accepts."""
    import pytest

    from fabric_tpu import native

    if not native.available():
        pytest.skip("native unavailable")
    names, expect, items = _expected_and_names(world)
    got = native.ecdsa_verify_host(items)
    if got is None:
        pytest.skip("libcrypto unavailable")
    for n, e, g in zip(names, expect, got):
        assert g == e, f"native host verify disagrees on {n}: got {g}, want {e}"


def test_xla_kernel_path(world):
    from fabric_tpu.csp.tpu import ec

    names, expect, items = _expected_and_names(world)
    tuples = []
    for it in items:
        try:
            r, s = api.unmarshal_ecdsa_signature(it.signature)
        except ValueError:
            r, s = -1, -1
        tuples.append((it.key.x, it.key.y, it.digest, r, s))
    mask = np.asarray(ec.verify_prepared(**ec.prepare_batch(tuples)))
    for n, e, g in zip(names, expect, mask):
        assert bool(g) == e, f"xla kernel disagrees on {n}: got {g}, want {e}"


def test_native_der_and_pallas_kernel_path(world):
    from fabric_tpu import native
    from fabric_tpu.csp.tpu import pallas_ec

    if not native.available():
        pytest.skip("native marshaller unavailable (no g++)")
    sw, key, digest, r, s = world
    names, expect, items = _expected_and_names(world)
    pub = key.public_key()
    xs = b"".join(pub.x_bytes for _ in items)
    ys = b"".join(pub.y_bytes for _ in items)
    digs, offs, sigs = [], [0], []
    bad_digest = []
    for i, it in enumerate(items):
        digs.append(it.digest if len(it.digest) == 32 else b"\x00" * 32)
        if len(it.digest) != 32:
            bad_digest.append(i)
        sigs.append(it.signature)
        offs.append(offs[-1] + len(it.signature))
    packed = native.marshal_batch(
        xs, ys, b"".join(digs), b"".join(sigs),
        np.asarray(offs, np.int32),
    )
    packed["valid"][bad_digest] = False
    mask = pallas_ec.verify_packed(
        pallas_ec.dedup_keys(packed), interpret=True
    )()
    for n, e, g in zip(names, expect, mask):
        assert bool(g) == e, (
            f"marshal.cc+pallas disagrees on {n}: got {g}, want {e}"
        )


def test_provider_agrees_with_sw(world):
    """TPUCSP end-to-end over the corpus must match sw bit-for-bit on
    whatever backend is active."""
    from fabric_tpu.csp.tpu.provider import TPUCSP

    sw, *_ = world
    names, expect, items = _expected_and_names(world)
    got = TPUCSP(min_device_batch=1).verify_batch(items)
    for n, e, g in zip(names, expect, got):
        assert g == e, f"TPUCSP disagrees on {n}: got {g}, want {e}"


def test_offcurve_and_infinity_keys_rejected_at_load(world):
    """The reference parses keys via crypto/x509, which enforces
    on-curve; our key-load layer must equally refuse to construct
    off-curve or identity points (the kernels' z==0 guard is defense in
    depth, not the primary check)."""
    # y tweaked off the curve
    sw, key, digest, r, s = world
    pub = key.public_key()
    with pytest.raises(Exception):
        api.ECDSAP256PublicKey.from_point(pub.x, pub.y + 1)
    with pytest.raises(Exception):
        api.ECDSAP256PublicKey.from_point(0, 0)


def test_kernel_rejects_identity_point_lane(world):
    """Defense in depth: a (0, 0) 'key' forced into the packed layout
    must come back invalid from the kernel (z==0 guard), never accepted."""
    from fabric_tpu import native
    from fabric_tpu.csp.tpu import pallas_ec

    if not native.available():
        pytest.skip("native marshaller unavailable (no g++)")
    sw, key, digest, r, s = world
    sig = api.marshal_ecdsa_signature(r, s)
    zero32 = b"\x00" * 32
    packed = native.marshal_batch(
        zero32, zero32, digest, sig,
        np.asarray([0, len(sig)], np.int32),
    )
    mask = pallas_ec.verify_packed(packed, interpret=True)()
    assert not mask[0]
