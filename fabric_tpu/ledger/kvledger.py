"""The peer ledger: block store + state DB + history DB orchestration.

Reference: core/ledger/kvledger/kv_ledger.go:447-530 CommitLegacy
(ValidateAndPrepare -> block store -> state DB -> history DB), provider in
kv_ledger_provider.go, recovery-on-open (state/history DBs replay blocks
newer than their savepoints), ledgermgmt/ledger_mgmt.go lifecycle.
"""

from __future__ import annotations

import os
import threading
import time

from fabric_tpu.common import tracing
from fabric_tpu.devtools import faultline, knob_registry
from fabric_tpu.devtools.lockwatch import guarded, named_rlock
from fabric_tpu.ledger.blkstorage import BlockStore, BlockStoreError
from fabric_tpu.ledger.history import HistoryDB
from fabric_tpu.ledger.kvstore import (
    KVStore,
    WriteBatchCollector,
    open_store_root,
)
from fabric_tpu.ledger.statedb import Height, VersionedDB
from fabric_tpu.ledger.txmgmt import (
    MVCCValidator,
    TxSimulator,
    VALID,
    hash_ns,
    key_hash,
    pvt_ns,
)
from fabric_tpu.protos.common import common_pb2
from fabric_tpu.protos.ledger.rwset import rwset_pb2
from fabric_tpu.protos.ledger.rwset.kvrwset import kv_rwset_pb2
from fabric_tpu import protoutil


import dataclasses


@dataclasses.dataclass
class CommitAssist:
    """Everything the validator already learned about a block that the
    commit path would otherwise re-derive: per-tx marshaled rwsets (no
    envelope re-walk), per-tx decoded RwsetFootprints (no rwset
    re-unmarshal in MVCC/history), per-tx txids (no envelope parse in the
    block-store index), and the materialized envelope byte list (the
    store splice-serializes the block from these instead of re-encoding
    the whole message).  The reference re-unmarshals at every one of
    those stages (validator.go, validateAndPrepareBatch, blockindex.go)."""

    rwsets: list  # per-tx marshaled TxReadWriteSet | None
    footprints: list  # per-tx RwsetFootprint | None
    txids: list  # per-tx txid str | None
    env_bytes: list | None = None  # the block's envelope byte strings
    # the validator's per-block trace root (tracing.SpanContext | None):
    # the committer thread attaches it so commit-stage spans join the
    # block's trace across the pipeline hop
    trace_ctx: object | None = None


@dataclasses.dataclass
class CommitGroup:
    """In-flight group-commit state: a WriteBatchCollector buffering
    every KV mutation (block index + pvt + state + history + savepoints)
    destined for ONE atomic base transaction, an overlay-aware state
    view so MVCC of block k+1 sees block k's buffered writes, and the
    bookkeeping the flush boundary needs (which block files to fsync,
    which committed heights to hand the snapshot auto-trigger).  Created
    by KVLedger.begin_commit_group, reusable across flushes."""

    collector: WriteBatchCollector
    state: VersionedDB  # rebased view over the collector
    mvcc: MVCCValidator
    blocks: int = 0
    dirty_files: set = dataclasses.field(default_factory=set)
    snap_notify: list = dataclasses.field(default_factory=list)
    # set when a buffered block has a pending snapshot request: the
    # streaming committer flushes at this block so the export height is
    # exactly the requested height (deterministic across peers)
    boundary_hint: bool = False


def extract_rwsets(block: common_pb2.Block) -> list[bytes | None]:
    """Per-tx marshaled TxReadWriteSet for endorser txs (None otherwise)."""
    out: list[bytes | None] = []
    for i in range(len(block.data.data)):
        raw = None
        try:
            env = protoutil.extract_envelope(block, i)
            payload = common_pb2.Payload.FromString(env.payload)
            chdr = common_pb2.ChannelHeader.FromString(payload.header.channel_header)
            if chdr.type == common_pb2.ENDORSER_TRANSACTION:
                _, action = protoutil.get_action_from_envelope(env)
                raw = action.results
        except Exception:
            raw = None
        out.append(raw)
    return out


def _history_writes(
    rwsets: list[bytes | None],
    flags: list[int],
    footprints: list | None = None,
):
    """Per-tx (ns, key) write lists for the history index (valid txs
    only).  When the validator's decoded footprints ride along, the
    public write keys are read straight off them — no re-unmarshal."""
    writes_per_tx: list[list[tuple[str, str]]] = [[] for _ in flags]
    for tx_num, raw in enumerate(rwsets):
        if flags[tx_num] != VALID or raw is None:
            continue
        fp = footprints[tx_num] if footprints is not None else None
        if fp is not None:
            out = writes_per_tx[tx_num]
            for ns, kvrw, _colls in fp.parsed:
                out.extend((ns, w.key) for w in kvrw.writes)
            continue
        try:
            txrw = rwset_pb2.TxReadWriteSet.FromString(raw)
            for nsrw in txrw.ns_rwset:
                kvrw = kv_rwset_pb2.KVRWSet.FromString(nsrw.rwset)
                writes_per_tx[tx_num].extend(
                    (nsrw.namespace, w.key) for w in kvrw.writes
                )
        except Exception:
            # fabriclint: allow[exception-discipline] a malformed rwset
            # contributes no history writes; MVCC already flagged the tx
            continue
    return writes_per_tx


class KVLedger:
    """One channel's ledger (reference ledger.PeerLedger,
    core/ledger/ledger_interface.go:142).  Owns the block store, state DB,
    history DB, and the private-data store — the reference's kvledger also
    commits block + pvtdata together (kv_ledger.go commitToPvtAndBlockStore)
    so that restart recovery can replay cleartext private writes."""

    def __init__(
        self,
        ledger_id: str,
        block_store: BlockStore,
        kv: KVStore,
        btl_policy=None,
        metrics=None,
        ledger_metrics=None,
    ):
        from fabric_tpu.ledger.confighistory import ConfigHistoryMgr
        from fabric_tpu.ledger.pvtdatastorage import PvtDataStore

        self.ledger_id = ledger_id
        self._kv = kv
        self._blocks = block_store
        self._state = VersionedDB(kv, f"statedb/{ledger_id}")
        self._history = HistoryDB(kv, f"historydb/{ledger_id}")
        self._mvcc = MVCCValidator(self._state)
        self.pvt_store = PvtDataStore(kv, ledger_id, btl_policy=btl_policy)
        self.config_history = ConfigHistoryMgr(kv, ledger_id)
        # SnapshotManager wired by the provider after construction (it
        # needs the ledger); commit() notifies it per committed block
        self.snapshots = None
        # Per-stage commit timing: cumulative wall seconds per pipeline
        # stage (CommitMetrics.STAGES keys), always maintained (bench.py
        # reads them); `metrics` (a common.metrics.CommitMetrics) also
        # gets per-observation histograms for /metrics.
        self._metrics = metrics
        # `ledger_metrics` (common.metrics.LedgerMetrics): the
        # per-channel height / durable_height gauges + block/tx
        # counters the netscope telemetry plane derives cross-peer
        # commit lag and sustained throughput from
        self._lmetrics = ledger_metrics
        self.commit_stage_seconds: dict[str, float] = {}
        # Serializes state mutation against snapshot export: commits are
        # already single-threaded per ledger (one committer), but an
        # admin RPC can request an on-demand snapshot concurrently — the
        # export takes this lock so it never reads a half-committed
        # block.  RLock because the commit-time auto-trigger generates
        # while the committing thread already holds it.  Created through
        # the lockwatch seam: under FABRIC_TPU_LOCKWATCH (tier-1) every
        # acquisition feeds the runtime lock-order watchdog.
        self.commit_lock = named_rlock("kvledger.commit_lock")
        # the CommitGroup currently holding buffered (unflushed) blocks,
        # if any — commits through any OTHER group are rejected while it
        # is open (their collectors would disagree about the checkpoint)
        self._active_group: CommitGroup | None = None
        self._recover()
        # Durability watermark: the height (and last block hash) as of
        # the last group boundary — everything at or below it has its
        # block file fsynced AND its KV transaction committed.  During
        # an open group, self.height runs ahead of this; snapshot
        # exports and the auto-trigger only ever observe the watermark.
        self._durable_height = self._blocks.height
        self._durable_hash = self._blocks.last_block_hash
        self._publish_heights()

    def _publish_heights(self) -> None:
        lm = self._lmetrics
        if lm is not None:
            lm.height.With("channel", self.ledger_id).set(
                self._blocks.height
            )
            lm.durable_height.With("channel", self.ledger_id).set(
                self._durable_height
            )

    def set_btl_policy(self, btl_policy) -> None:
        self.pvt_store._btl = btl_policy or (lambda ns, coll: 0)

    # -- recovery (reference recoverDBs / syncStateAndHistoryDBWithBlockstore)

    @staticmethod
    def _recovery_group_size() -> int:
        """Blocks replayed per recovery KV transaction
        (FABRIC_TPU_RECOVERY_GROUP, default 32; values below 1 restore
        the old per-block-txn behavior)."""
        raw = knob_registry.raw("FABRIC_TPU_RECOVERY_GROUP").strip()
        if not raw:
            return 32
        try:
            return max(1, int(raw))
        except ValueError:
            raise ValueError(
                f"FABRIC_TPU_RECOVERY_GROUP={raw!r} is not an integer "
                "group size"
            ) from None

    def _recover(self) -> None:
        """Replay blocks newer than the state savepoint THROUGH the same
        WriteBatchCollector group-commit seam live commits use: one KV
        transaction per replayed group instead of four-plus per block
        (the pre-batched path), with the rebased overlay making each
        block's MVCC re-application see its predecessors' buffered
        writes.  Crash-safe at every boundary: the savepoint rides each
        group's atomic flush, so a crash mid-recovery resumes from the
        last flushed group and the replay is idempotent."""
        height = self._blocks.height
        sp = self._state.savepoint()
        first = 0 if sp is None else sp.block_num + 1
        if first >= height:
            return
        group_size = self._recovery_group_size()
        collector = WriteBatchCollector(self._kv)
        state = self._state.rebased(collector)
        mvcc = MVCCValidator(state)
        buffered = 0
        for num in range(first, height):
            block = self._blocks.get_block_by_number(num)
            self._apply_state_updates(
                block, self.pvt_store.get_pvt_data_by_block(num),
                mvcc=mvcc, state=state, into=collector,
            )
            buffered += 1
            if buffered >= group_size:
                collector.flush()
                state.invalidate_caches()
                buffered = 0
        if buffered:
            collector.flush()
        # the base store changed underneath the main view's caches
        self._state.invalidate_caches()

    def _apply_state_updates(
        self, block: common_pb2.Block,
        pvt_data: dict[int, bytes] | None = None,
        *, mvcc=None, state=None, into=None,
    ) -> None:
        """Replay one block's state/history effects.  `mvcc`/`state`
        default to the live DBs (per-block commit); recovery passes a
        collector-rebased pair plus `into` so a whole replay group lands
        in one KV transaction."""
        mvcc = mvcc if mvcc is not None else self._mvcc
        state = state if state is not None else self._state
        flags = list(protoutil.tx_filter(block))
        rwsets = extract_rwsets(block)
        # replay trusts the recorded validation flags; MVCC re-application
        # is deterministic because only VALID txs contribute writes
        batch = mvcc.validate_and_prepare(
            block.header.number, rwsets, flags, pvt_data
        )
        # a replayed block whose group KV txn died with a crash lost its
        # cleartext pvt writes (pvt store + state are one atomic txn):
        # record every endorsed-cleartext collection with no stored data
        # as MISSING so the reconciler re-fetches instead of the loss
        # staying silent (may over-report collections this peer was
        # never eligible for; reconciliation of those is a no-op)
        missing = self._lost_pvt(rwsets, flags, pvt_data or {})
        if missing:
            self.pvt_store.commit(
                block.header.number, {}, missing, into=into
            )
        state.apply_updates(batch, Height(block.header.number, len(flags)))
        self._history.commit(
            block.header.number, _history_writes(rwsets, flags), into=into
        )

    @staticmethod
    def _lost_pvt(rwsets, flags, pvt_data) -> list[tuple[int, str, str]]:
        """[(tx, ns, coll)] where the rwset endorsed a cleartext private
        rwset (non-empty pvt_rwset_hash) but no cleartext survives."""
        out: list[tuple[int, str, str]] = []
        for tx_num, raw in enumerate(rwsets):
            if flags[tx_num] != VALID or raw is None or pvt_data.get(tx_num):
                continue
            try:
                txrw = rwset_pb2.TxReadWriteSet.FromString(raw)
            except Exception:
                # fabriclint: allow[exception-discipline] unparsable rwset ->
                # no endorsed collections -> nothing can be missing
                continue
            for nsrw in txrw.ns_rwset:
                for ch in nsrw.collection_hashed_rwset:
                    if ch.pvt_rwset_hash:
                        out.append(
                            (tx_num, nsrw.namespace, ch.collection_name)
                        )
        return out

    # -- commit path (reference kv_ledger.go:447 CommitLegacy) -------------

    def begin_commit_group(self) -> CommitGroup:
        """Start a group commit: blocks committed with this group buffer
        every KV mutation in one shared collector (and skip per-block
        fsyncs); commit_group_flush lands the whole group with one
        block-file fsync + one all-or-nothing KV transaction.  Reusable
        after each flush."""
        collector = WriteBatchCollector(self._kv)
        view = self._state.rebased(collector)
        return CommitGroup(
            collector=collector, state=view, mvcc=MVCCValidator(view)
        )

    def commit(
        self,
        block: common_pb2.Block,
        pvt_data: dict[int, bytes] | None = None,
        missing_pvt: list[tuple[int, str, str]] | None = None,
        rwsets: list[bytes | None] | None = None,
        assist: CommitAssist | None = None,
        group: CommitGroup | None = None,
    ) -> None:
        """MVCC-validate (updating the tx filter), persist block + private
        data, apply state + history.  Signature/policy flags must already
        be set by the txvalidator; this adds the MVCC codes.  pvt_data maps
        tx index -> marshaled TxPvtReadWriteSet (cleartext private writes
        this peer is eligible for); missing_pvt records eligible-but-absent
        collections for the reconciler.  `rwsets` may carry the per-tx
        marshaled TxReadWriteSets the validator already extracted
        (Committer.store_stream) — the commit then skips re-walking
        every envelope; a full `assist` additionally skips the rwset
        re-unmarshal (MVCC + history read the decoded footprints), the
        txid envelope parse in the block index, and the whole-block
        re-serialization (splice from the envelope bytes).

        Without `group`, the block is flushed immediately — still as ONE
        block-file fsync + ONE atomic KV transaction carrying the block
        index, pvt store, state (with savepoint) and history together
        (the pre-group code paid one fsync plus four-plus independent
        KV transactions here).  With `group`, the block lands in the
        group's buffers and only becomes durable/visible at the next
        commit_group_flush."""
        if self.snapshots is not None:
            # a background snapshot export pinned to the last flush
            # height must win the commit lock before state advances
            self.snapshots.wait_generation_turn()
        with self.commit_lock:
            g = group if group is not None else self.begin_commit_group()
            if self._active_group is not None and g is not self._active_group:
                # a DIFFERENT group holds buffered blocks: its index/
                # checkpoint advance lives only in its collector, so a
                # fresh collector would read the stale base checkpoint
                # and index this block at already-occupied offsets
                raise BlockStoreError(
                    "another commit group holds unflushed blocks for "
                    f"ledger {self.ledger_id!r}"
                )
            try:
                # fabriclint: allow[lock-discipline] the faultline stage
                # points inside may inject delays under the commit lock BY
                # DESIGN (chaos latency testing); with no plan armed they
                # are zero-overhead no-ops
                self._commit_into(
                    block, pvt_data, missing_pvt, rwsets, assist, g
                )
            except BaseException as exc:
                # a failure after add_block would otherwise leave the
                # live block store advanced (file appended, height
                # bumped) with its index writes stranded in the
                # abandoned collector — unwind the WHOLE group (its
                # blocks were never acknowledged).  An injected
                # FaultCrash models PROCESS DEATH: no unwind runs, so
                # the chaos tests' reopen exercises the real recovery
                # path, not the graceful rollback.
                if not faultline.is_crash(exc):
                    self._rollback_group(g)
                raise
            if group is None:
                self._flush_group(g)

    def commit_group_flush(self, group: CommitGroup) -> None:
        """Land an open group: fsync the touched block files FIRST, then
        commit the group's single KV transaction (index + pvt + state +
        history + savepoints) — the same block-file-first recovery
        invariant as per-block commits, paid once per group.  Finally
        fire the deferred snapshot auto-triggers; the durability
        watermark advances so exports only see fully-synced heights."""
        if self.snapshots is not None:
            self.snapshots.wait_generation_turn()
        with self.commit_lock:
            self._flush_group(group)

    def _commit_into(
        self, block, pvt_data, missing_pvt, rwsets, assist,
        group: CommitGroup,
    ) -> None:
        t = time.perf_counter
        flags = list(protoutil.tx_filter(block))
        footprints = txids = env_bytes = None
        if assist is not None and len(assist.rwsets) == len(flags):
            rwsets = assist.rwsets
            footprints = assist.footprints
            txids = assist.txids
            env_bytes = assist.env_bytes
        if rwsets is None or len(rwsets) != len(flags):
            rwsets = extract_rwsets(block)
        num = block.header.number
        t0 = t()
        # group.mvcc reads through the collector overlay, so a block
        # sees the buffered writes of earlier blocks in its group.
        # Stage spans join the validator's per-block trace when the
        # committer thread attached the CommitAssist context; the
        # stage-boundary fault points stay INSIDE each span so injected
        # trips annotate the stage they landed in.
        with tracing.span("mvcc", cat="stage", block=num):
            batch = group.mvcc.validate_and_prepare(
                num, rwsets, flags, pvt_data,
                footprints=footprints,
            )
            protoutil.set_tx_filter(block, flags)
            # stage-boundary fault points: an injected crash lands AFTER
            # the named stage's work (the any-stage crash matrix in
            # tests/test_chaos_commit.py drives every one of these)
            faultline.point("commit.stage", stage="mvcc", block=num)
        t1 = t()
        with tracing.span("block_append", cat="stage", block=num):
            file_idx = self._blocks.add_block(
                block, txids=txids, env_bytes=env_bytes,
                into=group.collector, sync=False,
            )
            if file_idx is not None:
                group.dirty_files.add(file_idx)
            faultline.point(
                "commit.stage", stage="block_append", block=num
            )
        t2 = t()
        # Pvt store and state ride the SAME atomic KV transaction (with
        # the savepoint), so recovery never sees state ahead of the pvt
        # store; a crash losing the whole txn loses both together, and
        # _recover's replay records reconciler missing-data entries for
        # cleartext that went down with an unflushed group.
        with tracing.span("pvt", cat="stage", block=num):
            self.pvt_store.commit(
                num, pvt_data or {}, missing_pvt,
                into=group.collector,
            )
            faultline.point("commit.stage", stage="pvt", block=num)
        t3 = t()
        with tracing.span("state", cat="stage", block=num):
            group.state.apply_updates(batch, Height(num, len(flags)))
            faultline.point("commit.stage", stage="state", block=num)
        t4 = t()
        with tracing.span("history", cat="stage", block=num):
            self._history.commit(
                num, _history_writes(rwsets, flags, footprints),
                into=group.collector,
            )
            faultline.point("commit.stage", stage="history", block=num)
        t5 = t()
        group.blocks += 1
        group.snap_notify.append(block.header.number)
        self._active_group = group
        lm = self._lmetrics
        if lm is not None:
            lm.height.With("channel", self.ledger_id).set(
                self._blocks.height
            )
            lm.blocks_committed.With("channel", self.ledger_id).add()
            lm.transactions.With("channel", self.ledger_id).add(
                sum(1 for f in flags if f == 0)  # VALID
            )
        if self.snapshots is not None and self.snapshots.has_pending_request(
            block.header.number
        ):
            group.boundary_hint = True
        sub = getattr(group.mvcc, "last_stage_seconds", None) or {}
        self._observe_stages(
            mvcc=t1 - t0, block_append=t2 - t1, pvt=t3 - t2,
            state=t4 - t3, history=t5 - t4,
            # the mvcc stage's own split (preload / serial check /
            # write-set prepare) so the next optimisation round can see
            # where the remaining commit-path host time lives
            mvcc_preload=sub.get("preload", 0.0),
            mvcc_check=sub.get("check", 0.0),
            mvcc_prepare=sub.get("prepare", 0.0),
        )

    def _flush_group(self, group: CommitGroup) -> None:
        # static guard (devtools/guards.py) cross-checked at runtime:
        # the open group and durability watermark move only under the
        # commit lock
        guarded(self, "_active_group", by="kvledger.commit_lock")
        if group.blocks:
            # flush spans are attributed to the group's boundary block
            # so the bench critical-path summary can charge the fsync/
            # kv_txn wall time to the block whose flush paid it
            boundary = (
                group.snap_notify[-1] if group.snap_notify else None
            )
            t0 = time.perf_counter()
            try:
                with tracing.span(
                    "fsync", cat="stage", block=boundary,
                    blocks=group.blocks,
                ):
                    self._blocks.sync_files(group.dirty_files)
                    faultline.point("commit.stage", stage="fsync")
                t1 = time.perf_counter()
                with tracing.span(
                    "kv_txn", cat="stage", block=boundary,
                    blocks=group.blocks,
                ):
                    group.collector.flush()
                    faultline.point("commit.stage", stage="kv_txn")
            except BaseException as exc:
                # roll the WHOLE group back so the live ledger stays
                # consistent with committed storage: the buffered index
                # data is gone, so the unindexed file appends go with it
                # and height/hash return to the durable watermark.  The
                # group's blocks were never acknowledged; callers may
                # re-commit them into a fresh (or this, now-empty) group.
                # An injected FaultCrash (simulated process death) skips
                # the unwind — reopen must run real recovery instead.
                if not faultline.is_crash(exc):
                    self._rollback_group(group)
                raise
            t2 = time.perf_counter()
            self._observe_stages(fsync=t1 - t0, kv_txn=t2 - t1)
            # sharded-store engine: fold the two-phase flush's per-phase
            # and per-shard wall splits into the same accounting the
            # bench sweeps read (kv_txn already covers their sum; the
            # splits say WHERE inside the txn the time went)
            sub = getattr(self._kv, "last_stage_seconds", None)
            if sub:
                self._observe_stages(
                    **{f"kv_{k}": v for k, v in sub.items()}
                )
            if self._metrics is not None:
                self._metrics.blocks_per_sync.With(
                    "channel", self.ledger_id
                ).observe(group.blocks)
            # the base store changed under the main view's caches
            self._state.invalidate_caches()
            self._durable_height = self._blocks.height
            self._durable_hash = self._blocks.last_block_hash
            self._publish_heights()
        notify, group.snap_notify = group.snap_notify, []
        group.blocks = 0
        group.dirty_files.clear()
        group.boundary_hint = False
        if self._active_group is group:
            self._active_group = None
        if self.snapshots is not None:
            for num in notify:
                self.snapshots.on_block_committed(num)

    def _rollback_group(self, group: CommitGroup) -> None:
        """Discard a group's buffered KV writes, truncate its unindexed
        file appends, and restore block-store height/hash to committed
        state — the all-or-nothing unwind for any group failure."""
        group.collector.discard()
        self._blocks.truncate_to_checkpoint()
        group.blocks = 0
        group.dirty_files.clear()
        group.snap_notify.clear()
        group.boundary_hint = False
        group.state.invalidate_caches()
        if self._active_group is group:
            self._active_group = None
        self._publish_heights()

    def _observe_stages(self, **stages: float) -> None:
        acc = self.commit_stage_seconds
        for name, dt in stages.items():
            acc[name] = acc.get(name, 0.0) + dt
            if self._metrics is not None:
                self._metrics.stage_duration.With(
                    "channel", self.ledger_id, "stage", name
                ).observe(dt)

    @property
    def durable_height(self) -> int:
        """Height as of the last flushed group boundary — block files
        fsynced and the KV transaction committed up to here."""
        return self._durable_height

    @property
    def durable_block_hash(self) -> bytes:
        return self._durable_hash

    def commit_old_pvt_data(
        self, block_num: int, tx_num: int, pvt_bytes: bytes
    ) -> None:
        """Apply reconciled private data from an old block (reference
        CommitPvtDataOfOldBlocks): persist in the pvt store and update the
        private state for keys whose hashed version still points at
        (block_num, tx_num) — anything newer means the value is stale and
        only the store copy is kept."""
        from fabric_tpu.ledger.txmgmt import key_hash as _kh
        from fabric_tpu.protos.ledger.rwset import rwset_pb2 as _rw
        from fabric_tpu.protos.ledger.rwset.kvrwset import (
            kv_rwset_pb2 as _kvrw,
        )

        self.pvt_store.resolve_missing(block_num, tx_num, pvt_bytes)
        h = Height(block_num, tx_num)
        batch: dict[str, dict] = {}
        txpvt = _rw.TxPvtReadWriteSet.FromString(pvt_bytes)
        for nsp in txpvt.ns_pvt_rwset:
            for cp in nsp.collection_pvt_rwset:
                hns = hash_ns(nsp.namespace, cp.collection_name)
                pns = pvt_ns(nsp.namespace, cp.collection_name)
                kvrw = _kvrw.KVRWSet.FromString(cp.rwset)
                for w in kvrw.writes:
                    hv = self._state.get_version(
                        hns, _kh(w.key).hex()
                    )
                    if hv != h:
                        continue  # stale: overwritten since
                    from fabric_tpu.ledger.statedb import VersionedValue

                    batch.setdefault(pns, {})[w.key] = (
                        None if w.is_delete else VersionedValue(w.value, h)
                    )
        if batch:
            self._state.apply_updates(batch, None)

    # -- queries -----------------------------------------------------------

    @property
    def block_store(self):
        """Read access to the underlying block store (qscc's query
        surface — GetBlockByHash/GetTransactionByID/GetBlockByTxID ride
        the store's indexes directly, reference core/scc/qscc/query.go)."""
        return self._blocks

    @property
    def state_db(self):
        """Read access to the versioned state DB (the snapshot exporter
        streams its raw records; everything else should go through the
        query executor / simulator)."""
        return self._state

    @property
    def height(self) -> int:
        return self._blocks.height

    def get_blockchain_info(self):
        return self._blocks.info()

    def get_block_by_number(self, num: int):
        return self._blocks.get_block_by_number(num)

    def get_block_by_hash(self, h: bytes):
        return self._blocks.get_block_by_hash(h)

    def get_tx_by_id(self, txid: str):
        return self._blocks.get_tx_by_id(txid)

    def get_tx_validation_code(self, txid: str):
        return self._blocks.get_tx_validation_code(txid)

    def tx_id_exists(self, txid: str) -> bool:
        # presence probe, not a location lookup: txids imported from a
        # snapshot have no block location but still count as duplicates
        return bool(self._blocks.tx_ids_exist([txid]))

    def tx_ids_exist(self, txids) -> set[str]:
        """Bulk duplicate-txid probe (one index round-trip)."""
        return self._blocks.tx_ids_exist(txids)

    def may_have_state_metadata(self, ns: str) -> bool:
        """False guarantees no key in `ns` (public or derived hashed
        namespace) carries state metadata — the validator's key-level
        endorsement fast path."""
        return self._state.may_have_metadata(ns)

    def define_index(self, ns: str, field: str) -> None:
        """Create (and backfill) a rich-query index on a dotted JSON
        field of a namespace — the statecouchdb index-definition
        equivalent (statecouchdb.go:53); chaincode deployments feed
        this from META-INF/statedb/indexes/*.json."""
        self._state.define_index(ns, field)

    def new_tx_simulator(self) -> TxSimulator:
        return TxSimulator(self._state)

    def new_query_executor(self) -> "QueryExecutor":
        """Read-only executor (reference ledger.QueryExecutor,
        core/ledger/ledger_interface.go:214)."""
        return QueryExecutor(self._state)

    def get_state(self, ns: str, key: str) -> bytes | None:
        return self.new_query_executor().get_state(ns, key)

    def get_state_range(self, ns: str, start: str, end: str):
        return self.new_query_executor().get_state_range(ns, start, end)

    def get_private_data(self, ns: str, coll: str, key: str) -> bytes | None:
        return self.new_query_executor().get_private_data(ns, coll, key)

    def get_private_data_hash(self, ns: str, coll: str, key: str):
        return self.new_query_executor().get_private_data_hash(ns, coll, key)

    def get_state_metadata(self, ns: str, key: str) -> dict[str, bytes]:
        return self.new_query_executor().get_state_metadata(ns, key)

    def get_history_for_key(self, ns: str, key: str):
        return self._history.get_history_for_key(ns, key)


class QueryExecutor:
    """Read-only state access handed to SCCs/endorser queries (reference
    QueryExecutor ledger_interface.go:214: GetState/GetStateRange/
    GetPrivateData*).  No read recording — never part of a transaction."""

    def __init__(self, state: VersionedDB):
        self._state = state

    def get_state(self, ns: str, key: str) -> bytes | None:
        vv = self._state.get_state(ns, key)
        return vv.value if vv else None

    def get_state_multiple(self, ns: str, keys) -> list[bytes | None]:
        return [
            vv.value if vv else None
            for vv in self._state.get_state_multiple(ns, keys)
        ]

    def get_state_range(self, ns: str, start: str, end: str):
        for key, vv in self._state.get_state_range(ns, start, end):
            yield key, vv.value

    def get_private_data(self, ns: str, coll: str, key: str) -> bytes | None:
        vv = self._state.get_state(pvt_ns(ns, coll), key)
        return vv.value if vv else None

    def get_private_data_hash(self, ns: str, coll: str, key: str):
        vv = self._state.get_state(hash_ns(ns, coll), key_hash(key).hex())
        return vv.value if vv else None

    def get_state_metadata(self, ns: str, key: str) -> dict[str, bytes]:
        """Decoded metadata entries of a key, matching the simulator's
        get_state_metadata; `ns` may be a derived hashed namespace."""
        from fabric_tpu.ledger.txmgmt import decode_metadata

        if not self._state.may_have_metadata(ns):
            return {}  # namespace never stored metadata: skip the store
        vv = self._state.get_state(ns, key)
        return decode_metadata(vv.metadata) if vv else {}

    def done(self) -> None:
        pass


class LedgerProvider:
    """Opens/creates per-channel ledgers under one root (reference
    kv_ledger_provider.go + ledgermgmt).  `csp`/`metrics` feed the
    snapshot subsystem: per-file digests of generated snapshots go
    through csp.hash_batch (TPU-batched when the node runs the tpu
    provider, sw fallback otherwise); `snapshots_dir` defaults to
    <root>/snapshots."""

    def __init__(self, root_dir: str | None = None, csp=None, metrics=None,
                 snapshots_dir: str | None = None, commit_metrics=None,
                 ledger_metrics=None):
        self._root = root_dir
        self._csp = csp
        self._metrics = metrics
        self._commit_metrics = commit_metrics
        self._ledger_metrics = ledger_metrics
        if snapshots_dir is None and root_dir is not None:
            snapshots_dir = os.path.join(root_dir, "snapshots")
        self._snapshots_dir = snapshots_dir
        if root_dir is not None:
            os.makedirs(root_dir, exist_ok=True)
        # single sqlite file by default; FABRIC_TPU_STORE_SHARDS > 1 (or
        # an existing sharded layout on disk) mounts the namespace-
        # sharded two-phase-flush store behind the same KVStore SPI
        self._kv = open_store_root(root_dir)
        self._ledgers: dict[str, KVLedger] = {}

    def create(self, genesis_block: common_pb2.Block) -> KVLedger:
        """Create from a genesis block (ledger id = channel id inside)."""
        env = protoutil.extract_envelope(genesis_block, 0)
        payload = common_pb2.Payload.FromString(env.payload)
        chdr = common_pb2.ChannelHeader.FromString(payload.header.channel_header)
        ledger = self.open(chdr.channel_id)
        if ledger.height == 0:
            ledger.commit(genesis_block)
        return ledger

    def open(self, ledger_id: str) -> KVLedger:
        if ledger_id in self._ledgers:
            return self._ledgers[ledger_id]
        from fabric_tpu.ledger import snapshot as snap

        # a crashed join-by-snapshot leaves the stores holding an
        # arbitrary prefix of the snapshot (bootstrap info without
        # state, or state without config history) — refuse LOUDLY
        # instead of opening a channel whose reads would silently
        # disagree with the chain it claims to be at
        if snap.import_marker(self._kv, ledger_id) == \
                snap.IMPORT_IN_PROGRESS:
            raise snap.SnapshotError(
                f"channel {ledger_id!r} has a half-finished snapshot "
                "import (the importing process crashed); run "
                "discard_failed_import() and re-join from the snapshot"
            )
        block_dir = (
            None if self._root is None else os.path.join(self._root, ledger_id, "chains")
        )
        store = BlockStore(block_dir, self._kv, name=ledger_id)
        ledger = KVLedger(
            ledger_id, store, self._kv, metrics=self._commit_metrics,
            ledger_metrics=self._ledger_metrics,
        )
        self._wire_snapshots(ledger)
        self._ledgers[ledger_id] = ledger
        return ledger

    def _wire_snapshots(self, ledger: KVLedger) -> None:
        from fabric_tpu.ledger.snapshot import SnapshotManager

        ledger.snapshots = SnapshotManager(
            ledger, self._snapshots_dir, self._kv,
            csp=self._csp, metrics=self._metrics,
        )

    def create_from_snapshot(self, snapshot_dir: str) -> KVLedger:
        """Bootstrap a BLOCKLESS channel ledger from a verified snapshot
        (reference kv_ledger_provider.go CreateFromSnapshot): the block
        store records the bootstrap height + last block hash so commit
        resumes at the snapshot height, the state DB is bulk-loaded with
        its savepoint at the snapshot, and deliver-based catch-up
        (height_fn) naturally starts there.  Verification recomputes
        every file digest through csp.hash_batch and refuses tampered
        snapshots."""
        from fabric_tpu.ledger import snapshot as snap

        meta = snap.verify_snapshot(snapshot_dir, csp=self._csp)
        ledger_id = meta["channel_id"]
        if ledger_id in self._ledgers:
            raise snap.SnapshotError(
                f"ledger {ledger_id!r} already exists"
            )
        if snap.import_marker(self._kv, ledger_id) == \
                snap.IMPORT_IN_PROGRESS:
            raise snap.SnapshotError(
                f"channel {ledger_id!r} has a half-finished snapshot "
                "import; run discard_failed_import() before re-joining"
            )
        block_dir = (
            None if self._root is None
            else os.path.join(self._root, ledger_id, "chains")
        )
        store = BlockStore(block_dir, self._kv, name=ledger_id)
        if store.height:
            raise snap.SnapshotError(
                f"channel {ledger_id!r} already has {store.height} blocks"
            )
        snap.import_snapshot(meta, snapshot_dir, store, self._kv, ledger_id)
        ledger = KVLedger(
            ledger_id, store, self._kv, metrics=self._commit_metrics,
            ledger_metrics=self._ledger_metrics,
        )
        self._wire_snapshots(ledger)
        self._ledgers[ledger_id] = ledger
        return ledger

    # every per-channel namespace mounted on the shared KV store — the
    # discard sweep below must cover ALL of them, or a retried import
    # would land on residue (bookkeeping is a two-level namespace:
    # bookkeeping/<lid>/<category>)
    _CHANNEL_NAMESPACES = (
        "blkindex/{lid}", "statedb/{lid}", "historydb/{lid}",
        "pvtdata/{lid}", "confighistory/{lid}", "transient/{lid}",
        "bookkeeping/{lid}/", "snapimport/{lid}",
    )

    def discard_failed_import(self, ledger_id: str) -> int:
        """Clear the debris of a CRASHED snapshot import so the channel
        can re-join (the recovery path the half-import refusal points
        operators at).  Deliberately narrow: refuses unless the
        channel's import marker is IMPORT_IN_PROGRESS — this is a
        crashed-import cleanup, not a general channel-delete.  Sweeps
        every per-channel namespace off the shared KV store (the marker
        goes LAST, so a crash mid-discard leaves the channel still
        refused, and the discard itself is re-runnable) and removes the
        channel's block-file directory.  Returns the number of KV keys
        deleted."""
        from fabric_tpu.ledger import snapshot as snap
        from fabric_tpu.ledger.kvstore import NamedDB, wipe_prefix

        if snap.import_marker(self._kv, ledger_id) != \
                snap.IMPORT_IN_PROGRESS:
            raise snap.SnapshotError(
                f"channel {ledger_id!r} has no half-finished snapshot "
                "import to discard"
            )
        deleted = 0
        marker_prefix = (
            f"snapimport/{ledger_id}".encode() + NamedDB._SEP
        )
        for ns in self._CHANNEL_NAMESPACES:
            name = ns.format(lid=ledger_id)
            # bookkeeping/<lid>/ spans its categories' namespaces, so
            # the raw name (sans separator) is the scan prefix there
            prefix = name.encode() if name.endswith("/") else (
                name.encode() + NamedDB._SEP
            )
            if prefix == marker_prefix:
                continue  # the marker falls last, below
            deleted += wipe_prefix(self._kv, prefix)
        if self._root is not None:
            chain_dir = os.path.join(self._root, ledger_id)
            if os.path.isdir(chain_dir):
                import shutil

                shutil.rmtree(chain_dir)
        NamedDB(self._kv, f"snapimport/{ledger_id}").delete(b"state")
        return deleted

    @property
    def kv(self):
        """The provider's shared index KVStore — side stores that live
        next to the ledgers (transient store) mount namespaces on it."""
        return self._kv

    @property
    def snapshots_root(self) -> str | None:
        """The completed/in_progress snapshot tree this provider's
        ledgers export into — the directory admin.SnapshotFetch serves
        remote join-by-snapshot from."""
        return self._snapshots_dir

    def list(self) -> list[str]:
        return sorted(self._ledgers)

    def close(self) -> None:
        for led in self._ledgers.values():
            led._blocks.close()
        self._kv.close()


__all__ = [
    "KVLedger",
    "LedgerProvider",
    "QueryExecutor",
    "CommitGroup",
    "extract_rwsets",
]
