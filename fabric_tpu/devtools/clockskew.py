"""clockskew — one patchable time provider for the timeout-bearing layers.

Faultline (PR 6) made failures injectable; this module makes TIME
injectable.  Every reconnect gate, keepalive deadline, and idle-timeout
window in the comm stack reads its clock through these functions instead
of `time.*` directly, so a test (or a faultline ``skew`` rule) can jump
the clock deterministically and watch a 30-second idle reap or a
capped-out dial backoff play out in milliseconds of real time — no
monkeypatching, no real sleeps.

Default behavior is the system clock: :func:`monotonic`/:func:`wall`
are one module-global load away from ``time.monotonic()``/
``time.time()``, :func:`sleep`/:func:`wait` really sleep, and
:func:`io_timeout` returns its argument unchanged.  Installing a
:class:`VirtualClock` (``with clockskew.use_virtual() as clk``) flips
all of them to the virtual time base:

- ``monotonic()``/``wall()`` read the manual clock (monotonic never
  goes backwards; wall may jump either way — that is what a skewed NTP
  step looks like to the process),
- ``sleep(s)``/``wait(event, s)`` ADVANCE the clock instead of
  sleeping (``wait`` still yields the GIL so the signalling thread
  runs), and every virtual sleep is recorded on ``clk.sleeps`` for
  tests to assert the exact wait sequence a loop produced,
- ``io_timeout(s)`` scales socket/queue deadlines by
  ``clk.timeout_scale`` (floored at 10ms) so code that must hand a
  REAL deadline to the kernel (``sock.settimeout``, ``queue.get``)
  can still be compressed: a 30s idle window under ``timeout_scale=
  0.005`` reaps in 150ms of wall time.

Faultline integration: a plan rule with ``action: "skew"`` calls
:func:`advance` at its fault point — a deterministic clock jump in the
middle of whatever the point instruments.  On the system clock (no
virtual clock installed) the jump is recorded as a trip but moves
nothing: real time cannot be skewed, so skew plans are exercised under
``use_virtual`` (see tests/test_clockskew.py).

Consumers today: ``comm/backoff.py`` (BackoffGate), ``comm/rpc.py``
(idle timeout, keepalive ping interval, client stream deadline),
``orderer/raft/transport.py`` (dial gate), ``peer/deliverclient.py``
(reconnect wait).
"""

from __future__ import annotations

import contextlib
import threading
import time as _time

# minimum REAL deadline io_timeout may hand to the kernel — a scaled-to-
# zero timeout would turn poll loops into busy spins
_IO_FLOOR = 0.01


class VirtualClock:
    """A deterministic, manually advanced clock.

    ``start``/``wall`` seed the monotonic and wall bases; ``auto_step``
    adds that many seconds on every ``monotonic()`` READ, which drives
    deadline-polling loops forward without any explicit advance calls;
    ``timeout_scale`` compresses :func:`io_timeout` deadlines."""

    def __init__(self, start: float = 1000.0, wall: float = 1.7e9,
                 timeout_scale: float = 1.0, auto_step: float = 0.0):
        self._lock = threading.Lock()
        self._mono = float(start)
        self._wall = float(wall)
        self.timeout_scale = float(timeout_scale)
        self._auto = float(auto_step)
        # every virtual sleep/wait duration, in order — the observable
        # timeline tests assert against
        self.sleeps: list[float] = []

    def monotonic(self) -> float:
        with self._lock:
            self._mono += self._auto
            return self._mono

    def wall(self) -> float:
        with self._lock:
            return self._wall

    def advance(self, dt: float, wall_dt: float | None = None) -> None:
        """Jump the clock: monotonic moves forward by max(dt, 0) — a
        monotonic source never runs backwards — while wall moves by
        ``wall_dt`` (defaults to ``dt``) in EITHER direction, modeling
        an NTP step."""
        with self._lock:
            if dt > 0:
                self._mono += dt
            self._wall += dt if wall_dt is None else wall_dt

    def sleep(self, seconds: float) -> None:
        if seconds > 0:
            self.sleeps.append(seconds)
            self.advance(seconds)

    def wait(self, event: threading.Event, timeout: float | None) -> bool:
        if event.is_set():
            return True
        if timeout is None:
            # the blocking-forever form has no virtual meaning — only a
            # real signal can end it, so block for real (a zero-length
            # poll here would turn `while not wait(stop, None)` loops
            # into hot spins)
            return event.wait()
        if timeout > 0:
            self.sleeps.append(timeout)
            self.advance(timeout)
        # zero-length REAL wait: yields the GIL so the setter thread can
        # run, without consuming real time proportional to `timeout`
        return event.wait(0.0)

    def io_timeout(self, seconds: float | None) -> float | None:
        if seconds is None:
            return None
        return max(seconds * self.timeout_scale, _IO_FLOOR)


# the installed provider; None = system time.  Every accessor below is
# a single global load + branch, cheap enough for reconnect loops (none
# of these sit on the ledger commit hot path).
_clock: VirtualClock | None = None


def installed() -> VirtualClock | None:
    return _clock


def install(clock: VirtualClock | None) -> None:
    global _clock
    _clock = clock


@contextlib.contextmanager
def use_virtual(clock: VirtualClock | None = None):
    """Install a virtual clock for a scope (restores the previous
    provider on exit, so nested scopes compose)."""
    c = clock if clock is not None else VirtualClock()
    prev = _clock
    install(c)
    try:
        yield c
    finally:
        install(prev)


def monotonic() -> float:
    c = _clock
    return _time.monotonic() if c is None else c.monotonic()


def wall() -> float:
    c = _clock
    return _time.time() if c is None else c.wall()


def sleep(seconds: float) -> None:
    c = _clock
    if c is None:
        if seconds > 0:
            _time.sleep(seconds)
    else:
        c.sleep(seconds)


def wait(event: threading.Event, timeout: float | None) -> bool:
    """``event.wait(timeout)`` through the provider: virtual clocks
    advance instead of blocking.  Returns the event state."""
    c = _clock
    return event.wait(timeout) if c is None else c.wait(event, timeout)


def io_timeout(seconds: float | None) -> float | None:
    """A deadline handed to the kernel/queue layer (``sock.settimeout``,
    ``queue.get``): real seconds on the system clock, scaled by the
    virtual clock's ``timeout_scale`` otherwise."""
    c = _clock
    return seconds if c is None else c.io_timeout(seconds)


def advance(dt: float, wall_dt: float | None = None) -> None:
    """Skew injection (faultline ``skew`` rules land here): jump the
    virtual clock; a no-op on the system clock — real time cannot be
    skewed, the trip is still recorded by faultline."""
    c = _clock
    if c is not None:
        c.advance(dt, wall_dt)


__all__ = [
    "VirtualClock",
    "install",
    "installed",
    "use_virtual",
    "monotonic",
    "wall",
    "sleep",
    "wait",
    "io_timeout",
    "advance",
]
