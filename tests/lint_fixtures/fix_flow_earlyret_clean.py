"""Clean twin of fix_flow_earlyret_dirty: every access — the empty
check, the snapshot, the reset — happens before the release on its
path (try/finally), so the flow-sensitive lockset proves the whole
function and stays quiet."""

import threading

from fabric_tpu.devtools.lockwatch import spawn_thread


class Spool:
    def __init__(self):
        self._lock = threading.Lock()
        self._buf = []
        self._stop = threading.Event()

    def serve(self):
        t = spawn_thread(
            target=self._run, name="spool", kind="service"
        )
        t.start()
        return t

    def stop(self):
        self._stop.set()

    def _run(self):
        while not self._stop.is_set():
            self.drain()

    def drain(self):
        self._lock.acquire()
        try:
            if not self._buf:
                return []
            items = list(self._buf)
            self._buf = []
            return items
        finally:
            self._lock.release()

    def push(self, item):
        with self._lock:
            self._buf.append(item)

    def peek(self):
        with self._lock:
            return list(self._buf)
