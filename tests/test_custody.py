"""Process-isolated key custody (csp/custody.py) — the pkcs11/HSM seam
(reference bccsp/pkcs11/impl.go): keygen/sign happen behind a process
boundary, private keys never enter the client, hash/verify stay local,
and keys survive daemon restarts via the file keystore."""

from __future__ import annotations

import hashlib
import os

import pytest

from fabric_tpu.csp import SWCSP
from fabric_tpu.csp.api import VerifyBatchItem
from fabric_tpu.csp.custody import (
    CustodyCSP,
    CustodyError,
    CustodyKeyHandle,
    KeyCustodyServer,
    load_token,
)

TOKEN = b"custody-pin-0001"


@pytest.fixture()
def daemon(tmp_path):
    srv = KeyCustodyServer(str(tmp_path / "keys"), TOKEN)
    srv.start()
    yield srv, str(tmp_path / "keys")
    srv.stop()


def test_keygen_sign_verify_roundtrip(daemon):
    srv, _ = daemon
    csp = CustodyCSP(srv.addr, TOKEN)
    handle = csp.key_gen()
    assert isinstance(handle, CustodyKeyHandle)
    digest = csp.hash(b"custody-msg")
    sig = csp.sign(handle, digest)
    # the signature verifies under the PUBLIC key through an
    # independent local provider — the daemon really signed with the
    # matching private key
    assert SWCSP().verify(handle.public_key(), sig, digest)
    assert csp.verify(handle, sig, digest)
    assert not csp.verify(handle, sig, csp.hash(b"other"))
    # batch path publicizes handles before delegating
    items = [VerifyBatchItem(handle, digest, sig)]
    assert csp.verify_batch(items) == [True]


def test_wrong_token_rejected(daemon):
    srv, _ = daemon
    bad = CustodyCSP(srv.addr, b"wrong-token-....")
    with pytest.raises(Exception, match="bad token"):
        bad.key_gen()
    good = CustodyCSP(srv.addr, TOKEN)
    h = good.key_gen()
    with pytest.raises(Exception, match="bad token"):
        bad.sign(h, hashlib.sha256(b"x").digest())


def test_no_private_material_crosses_the_boundary(daemon):
    srv, _ = daemon
    csp = CustodyCSP(srv.addr, TOKEN)
    h = csp.key_gen()
    # the handle is NON-EXTRACTABLE: raw() refuses (the Key contract
    # says private raw() is PKCS8 DER, which custody cannot and must
    # not produce); the public half is available explicitly
    with pytest.raises(CustodyError, match="not extractable"):
        h.raw()
    pub = h.public_key().raw()
    assert pub[:1] == b"\x04" and len(pub) == 65
    # private import is refused outright
    with pytest.raises(CustodyError, match="cannot import private"):
        csp.key_import(b"\x30\x00", private=True)
    # signing with a non-custody key is refused (no secret ever rides
    # the client provider)
    local = SWCSP().key_gen()
    with pytest.raises(CustodyError, match="custody-held"):
        csp.sign(local, hashlib.sha256(b"d").digest())


def test_unknown_ski_sentinel_vs_transport_failure(daemon):
    """The local-keystore fallback keys off the daemon's STRUCTURED
    ERR_UNKNOWN_SKI sentinel, not prose: an unknown SKI falls through to
    the local keystore, while a transport-ish error whose message merely
    mentions missing keys PROPAGATES (a daemon outage must never
    silently demote a signable key to a public one)."""
    from fabric_tpu.comm.rpc import RPCError
    from fabric_tpu.csp.custody import ERR_UNKNOWN_SKI

    srv, _ = daemon
    local = SWCSP()
    local_key = local.key_gen()
    csp = CustodyCSP(srv.addr, TOKEN, verify_csp=local)
    # daemon answers the sentinel for a SKI it does not hold -> the
    # locally-held key is served
    assert csp.get_key(local_key.ski()).ski() == local_key.ski()
    # a reworded/unstructured error must NOT be mistaken for unknown-SKI
    csp2 = CustodyCSP(srv.addr, TOKEN, verify_csp=local)
    def _flaky(method, body):
        raise RPCError("connection reset: daemon has no key material yet")
    csp2._call = _flaky
    with pytest.raises(RPCError, match="connection reset"):
        csp2.get_key(local_key.ski())
    # a totally unknown SKI surfaces the sentinel code end to end
    with pytest.raises(KeyError):
        csp.get_key(b"\x00" * 32)
    with pytest.raises(RPCError, match=ERR_UNKNOWN_SKI):
        CustodyCSP(srv.addr, TOKEN)._call("custody.GetKey", b"\x01" * 32)


def test_keys_survive_daemon_restart(daemon, tmp_path):
    srv, ksdir = daemon
    csp = CustodyCSP(srv.addr, TOKEN)
    h = csp.key_gen()
    digest = hashlib.sha256(b"persist").digest()
    sig1 = csp.sign(h, digest)
    srv.stop()
    # a FRESH daemon over the same keystore dir serves the same key
    srv2 = KeyCustodyServer(ksdir, TOKEN)
    srv2.start()
    try:
        csp2 = CustodyCSP(srv2.addr, TOKEN)
        h2 = csp2.get_key(h.ski())
        assert h2.public_key().raw() == h.public_key().raw()
        sig2 = csp2.sign(h2, digest)
        assert SWCSP().verify(h.public_key(), sig2, digest)
        assert SWCSP().verify(h.public_key(), sig1, digest)
    finally:
        srv2.stop()


def test_custody_over_mutual_tls(tmp_path):
    """The token must be protectable in transit: daemon and provider
    talk mutual TLS, and a plaintext client cannot reach the daemon."""
    from fabric_tpu.common.crypto import CA
    from fabric_tpu.comm.tls import credentials_from_ca

    ca = CA("custody-tls-ca", "org1")
    srv = KeyCustodyServer(
        str(tmp_path / "keys"), TOKEN,
        tls=credentials_from_ca(ca, "custody-daemon"),
    )
    srv.start()
    try:
        csp = CustodyCSP(
            srv.addr, TOKEN, tls=credentials_from_ca(ca, "peer-client")
        )
        h = csp.key_gen()
        d = csp.hash(b"tls-sign")
        assert SWCSP().verify(h.public_key(), csp.sign(h, d), d)
        # plaintext client: the handshake fails, the token never flows
        with pytest.raises(Exception):
            CustodyCSP(srv.addr, TOKEN).key_gen()
    finally:
        srv.stop()


def test_token_file_loader(tmp_path):
    p = tmp_path / "tok"
    p.write_bytes(b"secret-token\n")
    assert load_token(str(p)) == b"secret-token"
    (tmp_path / "empty").write_bytes(b"\n")
    with pytest.raises(CustodyError, match="empty"):
        load_token(str(tmp_path / "empty"))


def test_factory_builds_custody_from_config(daemon, tmp_path):
    srv, _ = daemon
    tok = tmp_path / "tok"
    tok.write_bytes(TOKEN)

    class Cfg:
        def __init__(self, d):
            self._d = d

        def get(self, k, default=None):
            return self._d.get(k, default)

    from fabric_tpu.csp.factory import csp_from_config

    cfg = Cfg({
        "bccsp.default": "CUSTODY",
        "bccsp.custody.endpoint": "%s:%d" % srv.addr,
        "bccsp.custody.tokenFile": str(tok),
    })
    csp = csp_from_config(cfg)
    assert isinstance(csp, CustodyCSP)
    h = csp.key_gen()
    d = csp.hash(b"cfg")
    assert csp.verify(h, csp.sign(h, d), d)


def test_custody_signed_endorsement_validates_e2e(daemon):
    """The full MSP path with a custody-held peer key: the custody
    daemon generates the endorser's key, the org CA certifies the
    PUBLIC half (CSR-style issue_for_public_key — the private key never
    leaves the daemon), and an endorsement signed through the custody
    provider orders and validates in a dev network like any other."""
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from orgfix import make_org

    from fabric_tpu.common import configtx_builder as ctx
    from fabric_tpu.msp import msp_config_from_ca
    from fabric_tpu.msp.identity import SigningIdentity
    from fabric_tpu.node.devnode import DevNode
    from fabric_tpu.protos.peer import proposal_pb2, transaction_pb2
    from fabric_tpu import protoutil

    srv, _ = daemon
    org = make_org("Org1MSP")
    oorg = make_org("OrdererMSP")
    app = ctx.application_group(
        {"Org1": ctx.org_group("Org1MSP", msp_config_from_ca(org.ca, "Org1MSP"))}
    )
    ordg = ctx.orderer_group(
        {"O": ctx.org_group("OrdererMSP", msp_config_from_ca(oorg.ca, "OrdererMSP"))},
        consensus_type="solo",
    )
    genesis = ctx.genesis_block("cch", ctx.channel_group(app, ordg))

    custody = CustodyCSP(srv.addr, TOKEN)
    handle = custody.key_gen()
    cert = org.ca.issue_for_public_key(
        "peer0.custody", handle.public_key().crypto_key, ous=["peer"]
    )
    peer_signer = SigningIdentity("Org1MSP", cert, handle, custody)

    def kvcc(sim, args):
        sim.set_state("kvcc", args[1].decode(), args[2])
        return 200, "", b""

    node = DevNode(
        genesis, csp=org.csp, peer_signer=peer_signer,
        chaincodes={"kvcc": kvcc}, batch_timeout_s=0.2,
    )
    try:
        client = org.signer("alice", role_ou="client")
        prop, _ = protoutil.create_chaincode_proposal(
            client.serialize(), "cch", "kvcc", [b"put", b"k", b"v"]
        )
        sp = proposal_pb2.SignedProposal(
            proposal_bytes=prop.SerializeToString(),
            signature=client.sign(prop.SerializeToString()),
        )
        resp = node.endorser.process_proposal(sp)
        assert resp.response.status == 200
        env = protoutil.create_signed_tx(prop, client, [resp])
        node.broadcast(env)
        _, flags = node.wait_commit()
        assert list(flags) == [transaction_pb2.VALID]
        assert node.ledger.get_state("kvcc", "k") == b"v"
    finally:
        node.shutdown()
