"""SEEDED VIOLATION (taint, cross-function): the wall-clock value never
touches a sink in THIS module — it crosses into fix_taint_helper, whose
param-to-sink summary carries the flow back to this call site."""

import time

from fabric_tpu.orderer.fix_taint_helper import marshal_at


def author_header():
    now = time.time()
    return marshal_at(now)  # <- taint must fire HERE (param 0 sinks)
