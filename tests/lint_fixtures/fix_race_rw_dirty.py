"""SEEDED VIOLATION (racecheck): the worker holds the guard for its
READ but drops it before the WRITE — mixed discipline on one field."""

from fabric_tpu.devtools.lockwatch import named_lock, spawn_thread


class TickerBoard:
    def __init__(self):
        self._lock = named_lock("fixture.ticker")
        self._quotes = {}

    def start(self):
        t = spawn_thread(
            target=self._pump, name="fixture-pump", kind="worker"
        )
        t.start()
        return t

    def _pump(self):
        with self._lock:
            n = len(self._quotes)  # read under the guard...
        self._quotes["seq"] = n + 1  # <- ...write without it: fires HERE

    def snapshot(self):
        with self._lock:
            return dict(self._quotes)
