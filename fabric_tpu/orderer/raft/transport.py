"""Cluster communication: the orderer-to-orderer Step fabric.

Capability parity with the reference's cluster comm
(orderer/common/cluster/comm.go Step RPC over mutually-authenticated gRPC;
rpc.go wraps consensus sends + submit forwarding).  Two transports behind
one interface:

  InProcTransport  — registry of nodes in one process, with per-link
                     partition/drop controls for fault-injection tests
                     (the role the reference's in-test network shims play).
  TCPTransport     — length-prefixed StepRequest frames over localhost TCP
                     for real multi-process deployments; per-peer sender
                     threads (OutboundConn) with bounded queues
                     (drop-on-overflow — raft tolerates loss, but drops
                     are LOUD: logged once per episode and counted as
                     raft_send_dropped_total) and automatic reconnect
                     under deterministic decorrelated-jitter backoff.

TLS: pass a comm.tls.TLSCredentials with `pinned_certs` set to the
consenter set's TLS leaf DERs — every link is then mutual TLS and BOTH
sides require the counterparty's exact certificate to be in the
allowlist, the reference's pinned-cert cluster scheme
(orderer/common/cluster/comm.go:116 VerifyConnection); update the
allowlist on config changes via set_pinned().
"""

from __future__ import annotations

import queue
import socket
import struct
import threading

from fabric_tpu.comm.backoff import BackoffGate
from fabric_tpu.common import tracing
from fabric_tpu.common.flogging import must_get_logger
from fabric_tpu.devtools import faultline, netsplit
from fabric_tpu.devtools.lockwatch import spawn_thread

from fabric_tpu.protos.orderer import raft_pb2 as rpb

_LEN = struct.Struct(">I")

_logger = must_get_logger("orderer.consensus.transport")


class InProcTransport:
    """Shared by all in-process nodes: register(id, handler) then send."""

    def __init__(self):
        self._nodes: dict[int, callable] = {}
        self._cut: set[tuple[int, int]] = set()
        self._lock = threading.Lock()

    def register(self, node_id: int, handler) -> None:
        with self._lock:
            self._nodes[node_id] = handler

    def unregister(self, node_id: int) -> None:
        with self._lock:
            self._nodes.pop(node_id, None)

    def partition(self, a: int, b: int) -> None:
        with self._lock:
            self._cut.add((a, b))
            self._cut.add((b, a))

    def heal(self, a: int | None = None, b: int | None = None) -> None:
        with self._lock:
            if a is None:
                self._cut.clear()
            else:
                self._cut.discard((a, b))
                self._cut.discard((b, a))

    def send(self, frm: int, to: int, req: rpb.StepRequest) -> None:
        with self._lock:
            if (frm, to) in self._cut:
                return
            handler = self._nodes.get(to)
        if handler is not None:
            handler(req)


class OutboundConn:
    """Per-peer sender thread: bounded queue, automatic reconnect with
    deterministic decorrelated-jitter backoff (a down peer is not
    hammered at message rate, and chaos runs replay the exact dial
    cadence), and LOUD overflow drops — queue-full discards used to be
    fully silent, so a wedged link looked identical to a healthy quiet
    one; now the first drop of each episode logs and every drop counts
    toward ``raft_send_dropped_total``."""

    def __init__(self, addr: tuple[str, int], tls=None, ssl_ctx=None,
                 peer_id: int | None = None, metrics=None,
                 queue_size: int = 4096, local_key: str = ""):
        self.addr = addr
        self._tls = tls
        self._ssl_ctx = ssl_ctx
        self.peer_id = peer_id
        self._metrics = metrics
        # labeled gauge child cached once: send() runs per raft
        # message, and With() re-sorts/allocates per call
        self._queue_gauge = (
            metrics.queue_depth.With("dest", self._dest())
            if metrics is not None else None
        )
        self.q: queue.Queue = queue.Queue(maxsize=queue_size)
        self._sock: socket.socket | None = None
        self._ns_tok: int | None = None  # netsplit cut-registry handle
        self._stop = threading.Event()
        self.dropped = 0
        self._drop_episode = False   # contiguous queue-full drops
        self._down_episode = False   # contiguous link-down drops (_run)
        # seeded from stable local+peer identity, never wall-clock:
        # deterministic per process, decorrelated ACROSS the peers of a
        # downed node (see DecorrelatedBackoff.for_key); the gate reads
        # its clock through devtools.clockskew, so a virtual clock (or
        # an injected skew) moves the dial windows deterministically
        self._gate = BackoffGate.for_key(f"{local_key}->{addr!r}")
        self._thread = spawn_thread(
            target=self._run, name="raft-dial", kind="service"
        )
        self._thread.start()

    def _dest(self) -> str:
        return str(self.peer_id) if self.peer_id is not None else repr(
            self.addr
        )

    def send(self, data: bytes) -> None:
        try:
            # the enqueuer's span context rides the queue item so the
            # sender thread's raft.send span joins the caller's trace
            # (None on the untraced path — one tuple either way)
            self.q.put_nowait((data, tracing.current()))
            self._drop_episode = False
            if self._queue_gauge is not None:
                # approximate by design (qsize races the drainer); the
                # gauge's job is trend, not an exact census
                self._queue_gauge.set(self.q.qsize())
        except queue.Full:
            # raft retransmits, so dropping beats blocking consensus —
            # but never silently: log once per contiguous episode and
            # count every drop
            self.dropped += 1
            if self._metrics is not None:
                self._metrics.send_dropped.With("dest", self._dest()).add()
            if not self._drop_episode:
                self._drop_episode = True
                _logger.warning(
                    "raft outbound queue to node %s full; dropping "
                    "messages (one log per episode; see "
                    "raft_send_dropped_total)", self._dest(),
                )

    def _connect(self) -> socket.socket | None:
        if self._metrics is not None:
            self._metrics.dials.With("dest", self._dest()).add()
        try:
            faultline.point("raft.connect", peer=self.peer_id)
            # a netsplit-denied link fails HERE (NetsplitDenied is an
            # OSError), before the connect timeout can stall the dial,
            # and rides the same gate-arm drop path as a down peer
            netsplit.connect(addr=self.addr)
            s = socket.create_connection(self.addr, timeout=2.0)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            if self._ssl_ctx is not None:
                s = self._ssl_ctx.wrap_socket(
                    s, server_hostname=self.addr[0]
                )
                if not self._tls.check_pinned(
                    s.getpeercert(binary_form=True)
                ):
                    s.close()
                    return None  # counterparty not in the consenter set
            s = faultline.io(s, "raft.conn")
            self._ns_tok = netsplit.track(s, addr=self.addr)
            return s
        except OSError:
            return None

    def _drop_down(self) -> None:
        """One message discarded because the link is down (dial gate
        open or connect failed) — same LOUD accounting as queue-full
        drops: counted, on /metrics, logged once per episode."""
        self.dropped += 1
        if self._metrics is not None:
            self._metrics.send_dropped.With("dest", self._dest()).add()
        if not self._down_episode:
            self._down_episode = True
            _logger.warning(
                "raft outbound link to node %s down; dropping queued "
                "messages during reconnect backoff (one log per "
                "episode; see raft_send_dropped_total)", self._dest(),
            )

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                data, trace_ctx = self.q.get(timeout=0.5)
            except queue.Empty:
                continue
            if self._sock is None:
                if not self._gate.ready():
                    self._drop_down()  # backoff window open: peer down
                    continue
                self._sock = self._connect()
                if self._sock is None:
                    # arm the next dial window; messages arriving
                    # before it drop fast instead of re-dialing
                    self._gate.arm()
                    self._drop_down()
                    continue
                self._gate.clear()
            try:
                with tracing.attached(trace_ctx), tracing.span(
                    "raft.send", peer=self.peer_id, n=len(data),
                ):
                    self._sock.sendall(_LEN.pack(len(data)) + data)
                # only a COMPLETED send proves the link: resetting on
                # connect alone would let an accept-then-reset peer
                # restart the backoff sequence every flap
                self._gate.reset()
                self._down_episode = False
            except OSError:
                if self._ns_tok is not None:
                    netsplit.untrack(self._ns_tok)
                    self._ns_tok = None
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
                # the dequeued message is lost — count it like every
                # other drop, and arm the dial gate so an accept-then-
                # reset peer is not redialed at message rate (connect
                # success reset the backoff, but the link was NOT
                # proven: only a completed send is)
                self._drop_down()
                self._gate.arm()

    def close(self) -> None:
        self._stop.set()
        if self._ns_tok is not None:
            netsplit.untrack(self._ns_tok)
            self._ns_tok = None
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass


class TCPTransport:
    """One listener per ordering node; senders keyed by node id."""

    def __init__(self, node_id: int, listen_addr: tuple[str, int], tls=None,
                 metrics=None):
        self.node_id = node_id
        self._handler = None
        self._tls = tls
        self._metrics = metrics  # common.metrics.RaftMetrics | None
        self._server_ctx = tls.server_context() if tls is not None else None
        if tls is not None:
            self._client_ctx = tls.client_context()
            if tls.pinned_certs is not None:
                # the cluster authenticates by byte-exact pinned leaves
                # (reference cluster/comm.go:116) — strictly stronger
                # than SAN matching, and consenter endpoints are often
                # dialed by addresses absent from their cert SANs
                self._client_ctx.check_hostname = False
        else:
            self._client_ctx = None
        self._peers: dict[int, OutboundConn] = {}
        self._lock = threading.Lock()
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind(listen_addr)
        self._server.listen(32)
        self.addr = self._server.getsockname()
        self._stop = threading.Event()
        self._accept_thread = spawn_thread(
            target=self._accept, name="raft-accept", kind="service"
        )
        self._accept_thread.start()

    def set_handler(self, handler) -> None:
        self._handler = handler

    def set_metrics(self, metrics) -> None:
        """Bind a common.metrics.RaftMetrics after construction —
        existing senders keep counting into it from their next call;
        senders created before the bind keep their old bundle (None)."""
        self._metrics = metrics
        with self._lock:
            for conn in self._peers.values():
                conn._metrics = metrics
                conn._queue_gauge = (
                    metrics.queue_depth.With("dest", conn._dest())
                    if metrics is not None else None
                )

    def set_peer(self, node_id: int, addr: tuple[str, int]) -> None:
        with self._lock:
            old = self._peers.get(node_id)
            if old is not None and old.addr == tuple(addr):
                return
            if old is not None:
                old.close()
            self._peers[node_id] = OutboundConn(
                tuple(addr), self._tls, self._client_ctx,
                peer_id=node_id, metrics=self._metrics,
                local_key=str(self.node_id),
            )

    def remove_peer(self, node_id: int) -> None:
        with self._lock:
            s = self._peers.pop(node_id, None)
        if s is not None:
            s.close()

    def send(self, frm: int, to: int, req: rpb.StepRequest) -> None:
        with self._lock:
            sender = self._peers.get(to)
        if sender is not None:
            sender.send(req.SerializeToString())

    def _accept(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            spawn_thread(
                target=self._serve_conn, args=(conn,),
                name="raft-serve", kind="service",
            ).start()

    def set_pinned(self, certs: list) -> None:
        """Replace the pinned-cert allowlist (DER leaves) — called when a
        config block changes the consenter set.  Once pinning is active
        the client context drops SAN matching, same as construction-time
        pinning (byte-exact leaves are the cluster's authentication)."""
        if self._tls is not None:
            self._tls.pinned_certs = list(certs)
            if self._client_ctx is not None:
                self._client_ctx.check_hostname = False

    def _serve_conn(self, conn: socket.socket) -> None:
        buf = b""
        conn.settimeout(30.0)
        try:
            # accept half of the netsplit seam (plain-TCP accept only
            # knows the remote's ephemeral address; outbound checks in
            # OutboundConn._connect carry the enforcement)
            netsplit.accept(addr=conn.getpeername())
        except OSError:
            try:
                conn.close()
            except OSError:
                pass
            return
        if self._server_ctx is not None:
            try:
                conn = self._server_ctx.wrap_socket(conn, server_side=True)
            except OSError:
                return
            if not self._tls.check_pinned(conn.getpeercert(binary_form=True)):
                try:
                    conn.close()
                except OSError:
                    pass
                return
        try:
            while not self._stop.is_set():
                while len(buf) < _LEN.size:
                    chunk = conn.recv(65536)
                    if not chunk:
                        return
                    buf += chunk
                (ln,) = _LEN.unpack_from(buf)
                while len(buf) < _LEN.size + ln:
                    chunk = conn.recv(65536)
                    if not chunk:
                        return
                    buf += chunk
                frame, buf = buf[_LEN.size : _LEN.size + ln], buf[_LEN.size + ln :]
                if self._handler is not None:
                    self._handler(rpb.StepRequest.FromString(frame))
        except OSError:
            return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        self._stop.set()
        try:
            self._server.close()
        except OSError:
            pass
        with self._lock:
            for s in self._peers.values():
                s.close()
            self._peers.clear()


__all__ = ["InProcTransport", "OutboundConn", "TCPTransport"]
