"""Peer-side chaincode runtime.

Capability parity with the reference's core/chaincode
(chaincode_support.go:79 Launch / :129 Register / :154 Execute;
handler.go:355 ProcessStream, :147 handleMessage state machine, :594+
HandleGetState/HandlePutState/...; transaction_context.go registry):

- `ChaincodeSupport.register_stream` serves one chaincode connection:
  REGISTER -> REGISTERED -> READY handshake, then routes ledger callbacks
  against the per-tx TxSimulator and replies RESPONSE/ERROR.
- `execute` dispatches a TRANSACTION to a registered chaincode and waits
  for COMPLETED/ERROR with a timeout.
- `InProcStream` runs a shim-side handler in-process over queue pipes
  (reference core/scc/inprocstream.go, the system-chaincode path).
- `TCPChaincodeListener` accepts external chaincode processes (reference
  externalbuilder run mode — docker-free, like our TPU hosts).

Range queries paginate through the tx context's open iterators
(QUERY_STATE_NEXT/CLOSE), matching handler.go's queryResponseGenerator.
"""

from __future__ import annotations

import queue
import socket
import struct
import threading

from fabric_tpu.devtools.lockwatch import spawn_thread
from fabric_tpu.protos.peer import chaincode_pb2, chaincode_shim_pb2 as shim_pb
from fabric_tpu.protos.peer import proposal_pb2

_LEN = struct.Struct(">I")
M = shim_pb.ChaincodeMessage
_RANGE_PAGE = 100


class ChaincodeExecuteError(Exception):
    pass


class TxContext:
    def __init__(self, simulator, channel_id: str, txid: str):
        self.simulator = simulator
        self.channel_id = channel_id
        self.txid = txid
        self.iterators: dict[str, object] = {}
        self._iter_seq = 0
        self.event: bytes = b""
        self.response_q: queue.Queue = queue.Queue(maxsize=1)

    def new_iterator_id(self) -> str:
        self._iter_seq += 1
        return f"it{self._iter_seq}"


class _CCHandle:
    """One registered chaincode stream."""

    def __init__(self, name: str, send):
        self.name = name
        self.send = send


class ChaincodeSupport:
    def __init__(self, invoke_timeout_s: float = 30.0):
        self._ccs: dict[str, _CCHandle] = {}
        self._contexts: dict[tuple[str, str], TxContext] = {}
        self._namespaces: dict[tuple[str, str], str] = {}
        self._lock = threading.Lock()
        self._timeout = invoke_timeout_s
        self.cc2cc_allowed = True
        self._launch_tokens: dict[str, str] = {}

    # -- launch credentials (reference core/chaincode/accesscontrol:
    # the peer issues each chaincode a client TLS cert at launch and the
    # Register handler rejects a stream whose cert hash it did not
    # issue.  Here the launch credential is a random token the peer
    # writes into the process's chaincode.json; the TCP listener demands
    # it in a handshake frame before any protocol message, so a rogue
    # local process can neither register at all nor claim another
    # chaincode's name.  In-process streams are peer-owned and trusted.)

    def issue_launch_token(self, name: str) -> str:
        """Mint (and remember) the launch credential for one chaincode
        process; re-issuing invalidates the previous token."""
        import secrets

        token = secrets.token_hex(32)
        with self._lock:
            self._launch_tokens[name] = token
        return token

    def check_launch_token(self, name: str, token: str) -> bool:
        import hmac

        with self._lock:
            want = self._launch_tokens.get(name)
        return want is not None and hmac.compare_digest(want, token)

    # -- registration (one per stream) -------------------------------------

    def register_stream(self, send, recv, authorized_name: str | None = None) -> None:
        """Serve one chaincode connection until EOF.  `send(bytes)`,
        `recv() -> bytes | None`.  Replies to ledger callbacks go back on
        this same stream (handler.go serialSendAsync).  When
        `authorized_name` is set (authenticated TCP streams), REGISTER
        for any other name is rejected — the reference makes the same
        cert-to-name binding check in handleRegister via accesscontrol."""
        name: str | None = None
        handle: _CCHandle | None = None
        try:
            while True:
                raw = recv()
                if raw is None:
                    return
                msg = M.FromString(raw)
                if msg.type == M.REGISTER:
                    cid = chaincode_pb2.ChaincodeID.FromString(msg.payload)
                    if (
                        authorized_name is not None
                        and cid.name != authorized_name
                    ):
                        send(
                            M(
                                type=M.ERROR,
                                payload=b"chaincode name does not match "
                                b"launch credential",
                            ).SerializeToString()
                        )
                        return
                    with self._lock:
                        if cid.name in self._ccs:
                            # Duplicate registration is rejected, matching
                            # the reference (handler.go handleRegister).
                            dup = True
                        else:
                            dup = False
                            name = cid.name
                            handle = _CCHandle(
                                name, lambda m: send(m.SerializeToString())
                            )
                            self._ccs[name] = handle
                    if dup:
                        send(
                            M(
                                type=M.ERROR,
                                payload=b"duplicate registered name "
                                + cid.name.encode(),
                            ).SerializeToString()
                        )
                        return
                    send(M(type=M.REGISTERED).SerializeToString())
                    send(M(type=M.READY).SerializeToString())
                    continue
                if msg.type in (M.COMPLETED, M.ERROR):
                    # Tx completion: deliver inline (non-blocking).
                    ctx = self._ctx(msg)
                    if ctx is not None:
                        self._dispatch(msg, ctx)
                    continue
                # Ledger callbacks run off the read loop so a blocking
                # cc2cc (INVOKE_CHAINCODE -> execute) can't deadlock the
                # stream that must also deliver its COMPLETED (the
                # reference runs handleMessage in per-tx goroutines,
                # handler.go:355).
                spawn_thread(
                    target=self._dispatch_async, args=(msg, send),
                    name="cc-dispatch", kind="worker",
                ).start()
        finally:
            if name is not None:
                with self._lock:
                    # Only deregister if this stream's handle is current.
                    if self._ccs.get(name) is handle:
                        self._ccs.pop(name, None)

    def _dispatch_async(self, msg: M, send) -> None:
        ctx = self._ctx(msg)
        if ctx is None:
            return  # unknown tx: drop (reference logs + ERROR)
        try:
            out = self._dispatch(msg, ctx)
        except Exception as exc:
            out = self._error(msg, str(exc))
        if out is not None:
            send(out.SerializeToString())

    def registered(self, name: str) -> bool:
        with self._lock:
            return name in self._ccs

    # -- execution (peer -> chaincode) -------------------------------------

    def execute(
        self,
        name: str,
        channel_id: str,
        txid: str,
        simulator,
        args: list[bytes],
        is_init: bool = False,
        signed_proposal_bytes: bytes = b"",
        namespace: str | None = None,
    ) -> tuple[proposal_pb2.Response, bytes]:
        """Returns (Response, chaincode_event_bytes).  State access inside
        the tx is namespaced to the chaincode name (handler.go uses the
        chaincode name as the rwset namespace)."""
        with self._lock:
            cc = self._ccs.get(name)
        if cc is None:
            raise ChaincodeExecuteError(f"chaincode {name!r} not registered")
        ctx = TxContext(simulator, channel_id, txid)
        key = (channel_id, txid)
        with self._lock:
            if key in self._contexts:
                raise ChaincodeExecuteError(f"duplicate tx context {key}")
            self._contexts[key] = ctx
            self._namespaces[key] = namespace if namespace is not None else name
        try:
            inp = chaincode_pb2.ChaincodeInput(args=args)
            cc.send(
                M(
                    type=M.INIT if is_init else M.TRANSACTION,
                    payload=inp.SerializeToString(),
                    txid=txid,
                    channel_id=channel_id,
                    proposal=signed_proposal_bytes,
                )
            )
            try:
                msg = ctx.response_q.get(timeout=self._timeout)
            except queue.Empty:
                raise ChaincodeExecuteError(
                    f"chaincode {name!r} timed out after {self._timeout}s"
                ) from None
            if msg.type == M.ERROR:
                raise ChaincodeExecuteError(msg.payload.decode("utf-8", "replace"))
            resp = proposal_pb2.Response.FromString(msg.payload)
            return resp, bytes(msg.chaincode_event)
        finally:
            with self._lock:
                self._contexts.pop(key, None)
                self._namespaces.pop(key, None)

    # -- ledger callbacks (chaincode -> peer) ------------------------------

    def _ctx(self, msg: M) -> TxContext | None:
        with self._lock:
            return self._contexts.get((msg.channel_id, msg.txid))

    def _reply(self, msg: M, payload: bytes = b"") -> M:
        return M(
            type=M.RESPONSE, payload=payload, txid=msg.txid, channel_id=msg.channel_id
        )

    def _error(self, msg: M, text: str) -> M:
        return M(
            type=M.ERROR, payload=text.encode(), txid=msg.txid,
            channel_id=msg.channel_id,
        )

    def _dispatch(self, msg: M, ctx: TxContext) -> M:
        sim = ctx.simulator
        ns = self._tx_namespace(ctx)
        if msg.type == M.GET_STATE:
            g = shim_pb.GetState.FromString(msg.payload)
            if g.collection:
                val = sim.get_private_data(ns, g.collection, g.key)
            else:
                val = sim.get_state(ns, g.key)
            return self._reply(msg, val or b"")
        if msg.type == M.PUT_STATE:
            p = shim_pb.PutState.FromString(msg.payload)
            if p.collection:
                sim.set_private_data(ns, p.collection, p.key, p.value)
            else:
                sim.set_state(ns, p.key, p.value)
            return self._reply(msg)
        if msg.type == M.DEL_STATE:
            d = shim_pb.DelState.FromString(msg.payload)
            if d.collection:
                sim.delete_private_data(ns, d.collection, d.key)
            else:
                sim.delete_state(ns, d.key)
            return self._reply(msg)
        if msg.type == M.GET_STATE_METADATA:
            from fabric_tpu.ledger.txmgmt import encode_metadata

            g = shim_pb.GetStateMetadata.FromString(msg.payload)
            if g.collection:
                entries = sim.get_private_data_metadata(ns, g.collection, g.key)
            else:
                entries = sim.get_state_metadata(ns, g.key)
            return self._reply(msg, encode_metadata(entries))
        if msg.type == M.PUT_STATE_METADATA:
            p = shim_pb.PutStateMetadata.FromString(msg.payload)
            entry = {p.metadata.metakey: bytes(p.metadata.value)}
            if p.collection:
                sim.set_private_data_metadata(ns, p.collection, p.key, entry)
            else:
                sim.set_state_metadata(ns, p.key, entry)
            return self._reply(msg)
        if msg.type == M.GET_PRIVATE_DATA_HASH:
            g = shim_pb.GetState.FromString(msg.payload)
            val = sim.get_private_data_hash(ns, g.collection, g.key)
            return self._reply(msg, val or b"")
        if msg.type == M.GET_STATE_BY_RANGE:
            g = shim_pb.GetStateByRange.FromString(msg.payload)
            if g.collection:
                it = iter(
                    sim.get_private_data_range(
                        ns, g.collection, g.start_key, g.end_key
                    )
                )
            else:
                it = iter(sim.get_state_range(ns, g.start_key, g.end_key))
            iid = ctx.new_iterator_id()
            ctx.iterators[iid] = it
            return self._reply(msg, self._page(ctx, iid).SerializeToString())
        if msg.type == M.GET_QUERY_RESULT:
            g = shim_pb.GetQueryResult.FromString(msg.payload)
            if g.collection:
                rows = sim.get_private_data_query_result(
                    ns, g.collection, g.query
                )
            else:
                rows = sim.get_query_result(ns, g.query)
            iid = ctx.new_iterator_id()
            ctx.iterators[iid] = iter(rows)
            return self._reply(msg, self._page(ctx, iid).SerializeToString())
        if msg.type == M.QUERY_STATE_NEXT:
            qn = shim_pb.QueryStateNext.FromString(msg.payload)
            if qn.id not in ctx.iterators:
                return self._error(msg, f"unknown iterator {qn.id}")
            return self._reply(msg, self._page(ctx, qn.id).SerializeToString())
        if msg.type == M.QUERY_STATE_CLOSE:
            qc = shim_pb.QueryStateClose.FromString(msg.payload)
            ctx.iterators.pop(qc.id, None)
            return self._reply(msg)
        if msg.type == M.INVOKE_CHAINCODE:
            return self._handle_cc2cc(msg, ctx)
        if msg.type in (M.COMPLETED, M.ERROR):
            ctx.event = bytes(msg.chaincode_event)
            ctx.response_q.put(msg)
            return None  # no reply
        return self._error(msg, f"unexpected message type {msg.type}")

    def _tx_namespace(self, ctx: TxContext) -> str:
        return self._namespaces.get((ctx.channel_id, ctx.txid), "")

    def set_tx_namespace(self, channel_id: str, txid: str, ns: str) -> None:
        self._namespaces[(channel_id, txid)] = ns

    def _page(self, ctx: TxContext, iid: str) -> shim_pb.QueryResponse:
        it = ctx.iterators[iid]
        qr = shim_pb.QueryResponse(id=iid)
        for _ in range(_RANGE_PAGE):
            try:
                key, value = next(it)
            except StopIteration:
                ctx.iterators.pop(iid, None)
                qr.has_more = False
                return qr
            kv = shim_pb.KV(key=key, value=value)
            qr.results.add().result_bytes = kv.SerializeToString()
        qr.has_more = True
        return qr

    def _handle_cc2cc(self, msg: M, ctx: TxContext) -> M:
        if not self.cc2cc_allowed:
            return self._error(msg, "chaincode-to-chaincode disabled")
        spec = chaincode_pb2.ChaincodeSpec.FromString(msg.payload)
        target = spec.chaincode_id.name.split("/", 1)[0]
        sub_txid = f"{msg.txid}-cc2cc-{target}"
        try:
            resp, _ = self.execute(
                target,
                ctx.channel_id,
                sub_txid,
                ctx.simulator,  # same simulator: one atomic rwset
                list(spec.input.args),
            )
        except ChaincodeExecuteError as exc:
            return self._error(msg, str(exc))
        return self._reply(msg, resp.SerializeToString())


# ---------------------------------------------------------------------------
# streams
# ---------------------------------------------------------------------------

class InProcStream:
    """Duplex queue pipe binding a shim-side handler to ChaincodeSupport in
    one process (system chaincodes; unit tests)."""

    def __init__(self, support: ChaincodeSupport, cc, name: str):
        from fabric_tpu.chaincode.shim import ShimHandler

        self._to_peer: queue.Queue = queue.Queue()
        self._to_cc: queue.Queue = queue.Queue()
        self._support = support
        peer_send = self._to_cc.put
        peer_recv = lambda: self._to_peer.get()
        self._shim = ShimHandler(
            cc, name, send=self._to_peer.put, recv=lambda: self._to_cc.get()
        )
        self._threads = [
            spawn_thread(
                target=self._serve_peer_side, args=(peer_send, peer_recv),
                name="cc-peer-side", kind="service",
            ),
            spawn_thread(
                target=self._shim.run, name="cc-shim", kind="service",
            ),
        ]

    def _serve_peer_side(self, send, recv) -> None:
        self._support.register_stream(send, recv)

    def start(self) -> None:
        for t in self._threads:
            t.start()

    def stop(self, timeout: float = 5.0) -> None:
        """Unblock both queue loops with a ``None`` sentinel and join
        the service threads.  Each queue has exactly one consumer
        (register_stream's recv on ``_to_peer``, the shim's recv on
        ``_to_cc``), so one sentinel per queue drains both sides; both
        loops treat ``None`` as EOF.  Idempotent — a second stop adds
        sentinels to queues nobody reads."""
        self._to_peer.put(None)
        self._to_cc.put(None)
        for t in self._threads:
            if t.ident is not None:
                t.join(timeout)

    def wait_registered(self, support: ChaincodeSupport, name: str, timeout=5.0):
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if support.registered(name):
                return
            time.sleep(0.01)
        raise TimeoutError(f"chaincode {name} did not register")


class TCPChaincodeListener:
    """Accepts external chaincode processes (peer's chaincode listener).

    Every connection must open with a handshake frame
    ``CCAUTH1\\0<name>\\0<token>`` carrying the launch credential the
    peer issued for that chaincode (ChaincodeSupport.issue_launch_token,
    delivered via chaincode.json); anything else closes the socket.
    Loopback binding is a mitigation, not an equivalent — the reference
    authenticates with per-launch TLS client certs
    (core/chaincode/accesscontrol/access_control.go), and this handshake
    is the framed-TCP analogue."""

    _HELLO = b"CCAUTH1"

    def __init__(self, support: ChaincodeSupport, listen_addr=("127.0.0.1", 0)):
        self._support = support
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind(listen_addr)
        self._server.listen(16)
        self.addr = self._server.getsockname()
        self._stop = threading.Event()
        # live (conn, serve-thread) pairs so close() can terminate and
        # join in-flight streams, not just stop accepting new ones;
        # _closing flips under the same lock so a conn accepted while
        # close() drains can never be registered-after-drain and leak
        self._conn_lock = threading.Lock()
        self._conns: list = []
        self._closing = False
        spawn_thread(
            target=self._accept, name="cc-accept", kind="service"
        ).start()

    def _accept(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            t = spawn_thread(
                target=self._serve, args=(conn,),
                name="cc-serve", kind="service",
            )
            with self._conn_lock:
                if self._closing:
                    # close() already drained the registry: this conn
                    # would never be shut down or joined — drop it
                    # instead of serving into a closed listener
                    try:
                        conn.close()
                    except OSError:
                        pass
                    return
                self._conns.append((conn, t))
            t.start()

    def _serve(self, conn: socket.socket) -> None:
        lock = threading.Lock()
        buf = bytearray()

        def send(data: bytes) -> None:
            with lock:
                conn.sendall(_LEN.pack(len(data)) + data)

        def recv() -> bytes | None:
            while len(buf) < _LEN.size:
                chunk = conn.recv(65536)
                if not chunk:
                    return None
                buf.extend(chunk)
            (ln,) = _LEN.unpack_from(bytes(buf[:4]))
            while len(buf) < _LEN.size + ln:
                chunk = conn.recv(65536)
                if not chunk:
                    return None
                buf.extend(chunk)
            frame = bytes(buf[4 : 4 + ln])
            del buf[: 4 + ln]
            return frame

        try:
            hello = recv()
            if hello is None:
                return
            parts = hello.split(b"\x00")
            if len(parts) != 3 or parts[0] != self._HELLO:
                return  # not an authenticated chaincode stream
            name = parts[1].decode("utf-8", "replace")
            token = parts[2].decode("utf-8", "replace")
            if not self._support.check_launch_token(name, token):
                return  # unknown/forged credential: drop silently
            self._support.register_stream(send, recv, authorized_name=name)
        except OSError:
            # abrupt peer disconnect (ECONNRESET from a client that
            # closed with frames in flight, EPIPE on send): the same
            # clean drop as an orderly close — surfaced by threadwatch
            # as a silent serve-thread death before this handler existed
            return
        finally:
            try:
                conn.close()
            except OSError:
                pass
            # self-prune: a connection that ended naturally must not
            # pin its socket + dead Thread in the registry for the
            # listener's lifetime (close() joins whatever remains)
            with self._conn_lock:
                self._conns[:] = [
                    (c, t) for c, t in self._conns if c is not conn
                ]

    def close(self) -> None:
        self._stop.set()
        # shutdown() BEFORE close(): close() alone does not wake a
        # thread already blocked in accept()/recv() on the same fd —
        # the accept loop and every serve thread would park until
        # their remote end disconnected on its own
        try:
            self._server.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._server.close()
        except OSError:
            pass
        with self._conn_lock:
            self._closing = True
            conns = list(self._conns)
            self._conns.clear()
        for conn, t in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
            if t.ident is not None:
                t.join(5.0)


__all__ = [
    "ChaincodeSupport",
    "InProcStream",
    "TCPChaincodeListener",
    "ChaincodeExecuteError",
    "TxContext",
]
