"""Config history store (reference core/ledger/confighistory): tracks
each chaincode's collection-config package by committing block number so
deliver-time private-data decisions and the reconciler can ask "what was
the collection config for namespace X as of block N".
"""

from __future__ import annotations

import struct

from fabric_tpu.ledger.kvstore import KVStore, NamedDB


def _key(ns: str, block_num: int) -> bytes:
    # descending block order under each namespace: the FIRST entry with
    # key >= (ns, ~block) is the most recent config at or below block
    return ns.encode() + b"\x00" + struct.pack(">Q", 0xFFFFFFFFFFFFFFFF - block_num)


class ConfigHistoryRetriever:
    def __init__(self, db: NamedDB):
        self._db = db

    def most_recent_below(
        self, ns: str, block_num: int
    ) -> tuple[int, bytes] | None:
        """Most recent collection config committed at a block STRICTLY
        below `block_num` (reference MostRecentCollectionConfigBelow).
        Returns (committing_block, serialized config) or None."""
        start = _key(ns, block_num - 1)
        end = ns.encode() + b"\x01"
        for k, v in self._db.iterate(start, end):
            inv = struct.unpack(">Q", k[len(ns) + 1:])[0]
            return (0xFFFFFFFFFFFFFFFF - inv, v)
        return None


class ConfigHistoryMgr:
    """Writer + retriever (reference confighistory.Mgr): call
    `handle_commit` with any namespaces whose collection config changed
    in the committed block."""

    def __init__(self, kv: KVStore, ledger_id: str):
        self._db = NamedDB(kv, f"confighistory/{ledger_id}")

    def handle_commit(
        self, block_num: int, configs: dict[str, bytes]
    ) -> None:
        """configs: {namespace: serialized CollectionConfigPackage}."""
        puts = {
            _key(ns, block_num): raw for ns, raw in configs.items()
        }
        if puts:
            self._db.write_batch(puts)

    def retriever(self) -> ConfigHistoryRetriever:
        return ConfigHistoryRetriever(self._db)

    # -- snapshot export / import (reference confighistory/db_helper
    # ExportConfigHistory / ImportConfigHistory) ---------------------------

    def export_entries(self):
        """All (key, value) entries in key order — the deterministic
        stream channel snapshots carry so a restored peer can still
        answer most_recent_below for pre-snapshot blocks."""
        return self._db.iterate(b"", None)

    def import_entries(self, entries) -> None:
        puts = dict(entries)
        if puts:
            self._db.write_batch(puts)


__all__ = ["ConfigHistoryMgr", "ConfigHistoryRetriever"]
