"""Seeded violation: a faultline injection point inside a broad except
handler does NOT launder the swallow — the seam call is transparent to
exception-discipline, and the handler still hides the failure from
every caller.  Expected: exception-discipline fires at the except."""

from fabric_tpu.devtools import faultline


def drop_errors(fetch):
    try:
        return fetch()
    except Exception:
        faultline.point("fixture.fetch")  # transparent to the rule
        return None
