"""Service discovery (reference discovery/): clients ask a peer for
channel config, peer membership, and endorsement descriptors (minimal
endorser sets satisfying a chaincode's endorsement policy)."""

from fabric_tpu.discovery.inquire import satisfaction_sets  # noqa: F401
from fabric_tpu.discovery.endorsement import (  # noqa: F401
    PeerInfo,
    compute_descriptor,
)
from fabric_tpu.discovery.service import DiscoveryService  # noqa: F401
from fabric_tpu.discovery.client import DiscoveryClient  # noqa: F401
