"""Fused Pallas TPU kernel for batched ECDSA-P256 verification.

The XLA graph in `ec.py` is correct but HBM-bound: each of the ~3800
field multiplications per ladder round-trips (B, ~600)-wide intermediates
through HBM (the matmul that sums limb products breaks XLA fusion).  This
kernel keeps the ENTIRE 64-window joint Shamir ladder resident in VMEM —
inputs stream in once, one bit streams out — so the arithmetic runs at
VPU rate instead of HBM rate.

Kernel-specific design (everything else mirrors `ec.py` exactly):

* **Layout** ``(limb, lane)``: a field element is ``(17, BLK)`` uint32 —
  limbs on the sublane axis, signatures on the 128-wide lane axis; every
  field op is a handful of full-tile VPU ops.  Grid = batch/BLK blocks.
* **Solinas reduction.** p = 2^256 − 2^224 + 2^192 + 2^96 − 1, so a
  product reduces by the FIPS-186 shifted-add recombination of its
  32-bit words (s1 + 2s2 + 2s3 + s4 + s5 − s6 − s7 − s8 − s9) instead of
  the generic fold-table multiplies of `limbs.Mod` — no multiplications
  in the reduction at all.  Negative terms are absorbed by a relaxed
  multiple-of-p bias constant whose every limb dominates the worst-case
  per-limb negative sum (the `sub_c` trick from limbs.py, scaled by 8
  so it still dominates for coarse — limbs <= 2^16 + 2^6 — input).
  Operands carry the lazy invariant value < 2^257, so the product has
  one word beyond the 512-bit Solinas range; its (tiny) top limb is
  folded with one extra multiply by 2^512 mod p.
* **No gathers.** Per-lane window-table selection is a one-hot masked
  sum over the 16 table entries; the Q table lives in VMEM scratch and
  is built in-kernel with 14 mixed adds.

Parity: tests/test_pallas_ec.py checks this kernel bit-for-bit against
ec.verify_kernel and the OpenSSL oracle on valid/tampered/edge batches.
Reference baseline being replaced: bccsp/sw/ecdsa.go:41-57 fanned out by
core/committer/txvalidator/v20/validator.go goroutines.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from fabric_tpu.csp.api import P256_GX, P256_GY, P256_P
from fabric_tpu.csp.tpu import ec
from fabric_tpu.csp.tpu.limbs import (
    LIMB_BITS,
    MASK,
    NLIMBS,
    WIDE,
    int_to_limbs,
)

BLK = 256  # lanes (signatures) per grid block (measured best vs 128/512/1024)
NWINDOWS = ec.NWINDOWS
TABLE = ec.TABLE

# ---------------------------------------------------------------------------
# Host-precomputed constants.
# ---------------------------------------------------------------------------

# Solinas term tables (FIPS 186-4 / HMV Alg 2.29 for P-256).  Each term is
# 8 32-bit words, most-significant first; entries index the 512-bit
# product's words c0..c15 (c0 least significant); None is a zero word.
_S_TERMS = [
    # (words ms-first, weight); positive terms first
    ([7, 6, 5, 4, 3, 2, 1, 0], 1),                     # s1 (low half)
    ([15, 14, 13, 12, 11, None, None, None], 2),       # s2
    ([None, 15, 14, 13, 12, None, None, None], 2),     # s3
    ([15, 14, None, None, None, 10, 9, 8], 1),         # s4
    ([8, 13, 15, 14, 13, 11, 10, 9], 1),               # s5
    ([10, 8, None, None, None, 13, 12, 11], -1),       # s6
    ([11, 9, None, None, 15, 14, 13, 12], -1),         # s7
    ([12, None, 10, 9, 8, 15, 14, 13], -1),            # s8
    ([13, None, 11, 10, 9, None, 15, 14], -1),         # s9
]


def _term_limb_indices(words_ms_first):
    """8 words (ms first) -> 16 limb indices into the 34-limb product
    (ls first); -1 marks a zero limb."""
    out = []
    for w in reversed(words_ms_first):
        if w is None:
            out += [-1, -1]
        else:
            out += [2 * w, 2 * w + 1]
    return out


@functools.lru_cache(maxsize=None)
def _solinas_runs():
    """Static (weight, out_pos, src_limb, length) runs: each Solinas term
    decomposes into 1-4 CONTIGUOUS limb slices of the product, so the
    recombination is ~21 pad+add VPU ops instead of an MXU contraction."""
    runs = []
    for words, w in _S_TERMS:
        li = _term_limb_indices(words)
        k = 0
        while k < NLIMBS:
            if li[k] < 0:
                k += 1
                continue
            start = k
            while (
                k + 1 < NLIMBS
                and li[k + 1] == li[k] + 1
            ):
                k += 1
            runs.append((w, start, li[start], k - start + 1))
            k += 1
    return runs


@functools.lru_cache(maxsize=None)
def _consts():
    """All numpy constants the kernel closes over."""
    p = P256_P
    # Signed Solinas matrix: output limb k accumulates product limb i
    # with net weight solmat[k, i].  Weights are small (|sum per row|
    # <= 11) and the product limbs are coarse (<= 2^16 + 2^6 after one
    # carry pass; the contraction is linear in the limb vector so
    # canonicality is not required), so the f32 contraction stays exact
    # (|sum| < 2^21 << 2^24).
    solmat = np.zeros((NLIMBS, 2 * WIDE), np.float32)
    for words, w in _S_TERMS:
        for k, i in enumerate(_term_limb_indices(words)):
            if i >= 0:
                solmat[k, i] += w

    # bias: 8 * (ceil(2^259/p) * p), in relaxed limbs every one of which
    # >= 8*2^16 - 8 (dominates the worst per-limb negative sum of the 4
    # subtracted terms even for coarse — limbs <= 2^16 + 2^6 — input:
    # 4*(2^16+2^6) < 8*MASK); value is a multiple of p so it vanishes
    # mod p.
    c = (1 << 259) // p + 1
    e = int_to_limbs(8 * c * p, WIDE).astype(np.int64)
    r = e.copy()
    r[0] += 8 << LIMB_BITS
    r[1:NLIMBS] += 8 * MASK
    r[NLIMBS] -= 8
    assert (r[:NLIMBS] >= 8 * MASK).all() and r[NLIMBS] >= 8
    bias = r.astype(np.uint32)[:, None]  # (17, 1)

    # fold rows: 2^256 mod p and 2^512 mod p (canonical 16 limbs)
    r256 = int_to_limbs((1 << 256) % p, NLIMBS)[:, None]  # (16, 1)
    r512 = int_to_limbs((1 << 512) % p, NLIMBS)[:, None]

    # relaxed-subtraction constant (limbs.Mod.sub_c)
    c1 = ((1 << 259) + p - 1) // p
    e1 = int_to_limbs(c1 * p, WIDE).astype(np.int64)
    s = e1.copy()
    s[0] += 1 << LIMB_BITS
    s[1:NLIMBS] += MASK
    s[NLIMBS] -= 1
    sub_c = s.astype(np.uint32)[:, None]  # (17, 1)

    p_limbs = int_to_limbs(p, WIDE)[:, None]  # (17, 1)
    from fabric_tpu.csp.api import P256_N
    n_limbs = int_to_limbs(P256_N, WIDE)[:, None]  # (17, 1)

    gx, gy, ginf = ec.g_table()  # (16, 17), (16, 17), (16,)
    return dict(
        solmat=solmat,
        bias=bias,
        r256=r256,
        r512=r512,
        sub_c=sub_c,
        p_limbs=p_limbs,
        n_limbs=n_limbs,
        gx=gx[:, :, None].astype(np.uint32),  # (16, 17, 1)
        gy=gy[:, :, None].astype(np.uint32),
        ginf=ginf.astype(np.uint32)[:, None],  # (16, 1)
    )


# ---------------------------------------------------------------------------
# In-kernel field arithmetic on (17, BLK) uint32, limbs on the sublane axis.
# ---------------------------------------------------------------------------


def _u2f(x):
    return x.astype(jnp.int32).astype(jnp.float32)


def _f2u(x):
    return x.astype(jnp.int32).astype(jnp.uint32)


def _shift_up(a, d: int):
    """result[i] = a[i-d] along the limb (first) axis, zero filled."""
    if d == 0:
        return a
    pad = [(d, 0)] + [(0, 0)] * (a.ndim - 1)
    return jnp.pad(a[: a.shape[0] - d] if d < a.shape[0] else a[:0], pad)


def _coarse(v, width: int):
    """One carry pass: limbs < 2**31 in, limbs <= 2**16 + (carry bound)
    out.  Value-preserving; does NOT canonicalize (use _resolve for that).
    Cheap replacement for _resolve wherever the consumer only needs
    bounded — not canonical — limbs (the Solinas contraction is linear in
    the limb vector, so bounded limbs suffice for exactness)."""
    if v.shape[0] < width:
        pad = [(0, width - v.shape[0])] + [(0, 0)] * (v.ndim - 1)
        v = jnp.pad(v, pad)
    one = jnp.uint32(LIMB_BITS)
    m = jnp.uint32(MASK)
    return (v & m) + _shift_up(v >> one, 1)


def _resolve(v, width: int):
    """Carry resolution (see limbs.resolve): limbs < 2**31 in, canonical
    16-bit limbs out; caller guarantees value < 2**(16*width)."""
    if v.shape[0] < width:
        pad = [(0, width - v.shape[0])] + [(0, 0)] * (v.ndim - 1)
        v = jnp.pad(v, pad)
    one = jnp.uint32(LIMB_BITS)
    m = jnp.uint32(MASK)
    c = v >> one
    v = (v & m) + _shift_up(c, 1)
    c = v >> one
    v = (v & m) + _shift_up(c, 1)
    g = (v >> one).astype(jnp.uint32)
    lo = v & m
    pprop = (lo == m).astype(jnp.uint32)
    d = 1
    while d < width:
        g = g | (pprop & _shift_up(g, d))
        pprop = pprop & _shift_up(pprop, d)
        d *= 2
    return (lo + _shift_up(g, 1)) & m


class FpP256:
    """Field ops mod P-256 on (17, BLK) uint32; drop-in for limbs.Mod in
    the point formulas (same method names, lazy invariant value < 2^257).
    Constants arrive as kernel inputs (Pallas kernels cannot capture
    array constants)."""

    def __init__(self, solmat, bias, r256, r512, sub_c, p_limbs):
        self.solmat = solmat
        self.bias = bias
        self.r256 = r256
        self.r512 = r512
        self.sub_c = sub_c
        self.p_limbs = p_limbs
        # 2p in canonical limbs (2*p_i is even, one coarse pass exact)
        self.p2_limbs = _coarse(p_limbs * jnp.uint32(2), WIDE)

    def _minifold(self, v):
        """17-limb value with small top limb -> invariant element."""
        acc = v[:NLIMBS] + v[NLIMBS:NLIMBS + 1] * self.r256
        return _resolve(acc, WIDE)

    def _fold_resolve(self, s):
        """Coarse 17-row value (limbs <= 2^16 + 2^8, top limb <= 2^9) ->
        canonical invariant element (17 rows, value < 2^257).

        Folds the top limb through r256 = 2^256 mod p, then resolves
        carries on 16 ALIGNED rows (two (8, lane) tiles, 4 Kogge-Stone
        steps) instead of 17 (three tiles, 5 steps) — this tail runs at
        the end of every field op, so the tile alignment matters more
        than anything inside the op.  Bound chain: r256's nonzero limbs
        sit at positions <= 13, so t[15] < 2^17 and the coarse carry out
        of limb 15 is {0,1}; t's value is < 2^257, so coarse-carry-out +
        KS-carry-out <= 1 and their sum IS the output's 17th limb."""
        t = s[:NLIMBS] + s[NLIMBS:NLIMBS + 1] * self.r256  # 16 rows, < 2^26
        one = jnp.uint32(LIMB_BITS)
        m = jnp.uint32(MASK)
        c = t >> one
        v = (t & m) + _shift_up(c, 1)  # limbs < 2^17
        cout = c[NLIMBS - 1:NLIMBS]  # {0,1} by the t[15] bound
        g = v >> one  # {0,1}
        lo = v & m
        pp = (lo == m).astype(jnp.uint32)
        d = 1
        while d < NLIMBS:
            g = g | (pp & _shift_up(g, d))
            pp = pp & _shift_up(pp, d)
            d *= 2
        res = (lo + _shift_up(g, 1)) & m
        return jnp.concatenate([res, cout + g[NLIMBS - 1:NLIMBS]], axis=0)

    def add(self, a, b):
        # a + b < 2^258: after one coarse pass limbs <= 2^16 and (value
        # argument: limb16 * 2^256 <= value) the top limb is <= 3, so the
        # r256 fold stays far below u32.
        return self._fold_resolve(_coarse(a + b, WIDE))

    def sub(self, a, b):
        # a + (C - b) with C = sub_c (relaxed multiple of p, limbwise
        # dominant): limbs < 2^18, value < 2^260 -> coarse top limb <= 15.
        return self._fold_resolve(_coarse(a + (self.sub_c - b), WIDE))

    def mul(self, a, b):
        # Schoolbook product with pure-VPU column accumulation: the
        # (i, j) limb products land in column i+j (lo half) and i+j+1
        # (hi half) via statically shifted adds — no dtype conversions,
        # no MXU round-trips (Mosaic's f32 dot at usable precision costs
        # 6 bf16 passes and dominated the kernel).
        prod = a[:, None, :] * b[None, :, :]  # (17, 17, BLK), exact u32
        plo = prod & jnp.uint32(MASK)
        phi = prod >> jnp.uint32(LIMB_BITS)
        blk = a.shape[-1]
        parts = []
        for i in range(WIDE):
            # row i contributes at columns i..i+17 (lo at +0, hi at +1)
            row = jnp.concatenate(
                [plo[i], jnp.zeros((1, blk), jnp.uint32)]
            ) + jnp.concatenate([jnp.zeros((1, blk), jnp.uint32), phi[i]])
            parts.append(
                jnp.pad(row, [(i, 2 * WIDE - (WIDE + 1) - i), (0, 0)])
            )
        # balanced tree sum keeps the column bound (< 34 * 2^17) tight
        while len(parts) > 1:
            parts = [
                parts[k] + parts[k + 1] if k + 1 < len(parts) else parts[k]
                for k in range(0, len(parts), 2)
            ]
        cols = _coarse(parts[0], 2 * WIDE)  # bounded 34-limb product
        return self._reduce_cols(cols)

    def _reduce_cols(self, cols):
        """Coarse 34-limb product (limbs <= 2^16 + 2^6) -> invariant
        element (< 2^257).

        Solinas recombination of the 512-bit range (limbs 0..31): one
        small signed f32 MXU contraction (measured faster than the
        equivalent pad+add chain on the VPU), negatives absorbed by the
        bias constant (a relaxed multiple of p dominating them).  The
        contraction is linear in the limb vector, so coarse — not
        canonical — limbs suffice: |sum| <= 12 * 2^16.1 + bias < 2^21,
        exact in f32 (< 2^24).  Limb 32 is <= 2^6.2 by the value bound
        (product < 2^514), so the 2^512-fold fits u32."""
        signed = jnp.dot(
            self.solmat,
            _u2f(cols),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        acc = _f2u(signed + _u2f(self.bias[:NLIMBS]))
        acc = acc + cols[32:33] * self.r512
        top = jnp.broadcast_to(self.bias[NLIMBS:], (1, acc.shape[-1]))
        acc = jnp.concatenate([acc, top], axis=0)
        # acc limbs < 2^23, value < 2^263 -> coarse top limb <= 2^7.
        return self._fold_resolve(_coarse(acc, WIDE))

    def sqr(self, a):
        return self.mul(a, a)

    def mul_const(self, a, k: int):
        # a*k limbs < 2^24; one coarse pass leaves the top limb <= 2^9
        # (a16 <= 1 so a16*k <= 256, plus a sub-2^8 carry) — no carry out
        # of limb 16, so width 17 suffices and the r256 fold fits u32.
        assert 0 < k <= 256
        return self._fold_resolve(_coarse(a * jnp.uint32(k), WIDE))

    def canon(self, a):
        v = self._minifold(a)
        for _ in range(3):
            v = _cond_sub(v, self.p_limbs)
        return v

    def is_zero(self, a):
        # An invariant element (canonical limbs, value < 2^257 < 3p) is
        # 0 mod p iff it equals 0, p, or 2p exactly — three limbwise
        # compares instead of canon's four carry networks.  int32 0/1
        # flag via mismatch counts, no i1 vectors (Mosaic reduces i1 via
        # i8 and cannot truncate back).

        def mism(c):
            return jnp.sum((a != c).astype(jnp.int32), axis=0, keepdims=True)

        n = mism(jnp.zeros_like(a)) * mism(self.p_limbs) * mism(self.p2_limbs)
        return (n == 0).astype(jnp.int32)


def _cond_sub(a, b_const):
    """a - b if a >= b else a; canonical limbs, (17, BLK)."""
    width = a.shape[0]
    notb = jnp.uint32(MASK) - b_const
    t = a + notb + _row_one(width, a.shape[-1])
    t = _resolve(t, width + 1)
    ge = (t[width:width + 1] > 0).astype(jnp.int32)
    return _sel(ge, t[:width], a)


def _row_one(rows: int, blk: int):
    """(rows, blk) uint32 with 1s in row 0, 0 elsewhere (scatter-free)."""
    return jnp.concatenate(
        [jnp.ones((1, blk), jnp.uint32), jnp.zeros((rows - 1, blk), jnp.uint32)]
    )


# ---------------------------------------------------------------------------
# Point formulas: identical structure to ec.py, (limb, lane) layout,
# infinity flags shaped (1, BLK).
# ---------------------------------------------------------------------------


# Flags are int32 0/1 vectors (1, BLK) throughout the point formulas:
# Mosaic handles i1 vectors poorly (broadcasts/loop carries round-trip
# through i8 and fail to truncate back), so selection is arithmetic.


def _sel(c, a, b):
    """c (1, BLK) int32 0/1 selects a (u32) else b via an XOR mask."""
    mask = (-c).astype(jnp.uint32)  # 0 or 0xffffffff
    return b ^ ((a ^ b) & mask)


def _fsel(c, a, b):
    """Flag select: all of c/a/b int32 0/1."""
    return b + (a - b) * c


def _pt_sel(c, p1, p2):
    return (
        _sel(c, p1[0], p2[0]),
        _sel(c, p1[1], p2[1]),
        _sel(c, p1[2], p2[2]),
        _fsel(c, p1[3], p2[3]),
    )


def _one(blk):
    return _row_one(WIDE, blk)


def _dbl(fp, p):
    x, y, z, inf = p
    delta = fp.sqr(z)
    gamma = fp.sqr(y)
    beta = fp.mul(x, gamma)
    alpha = fp.mul_const(fp.mul(fp.sub(x, delta), fp.add(x, delta)), 3)
    x3 = fp.sub(fp.sqr(alpha), fp.mul_const(beta, 8))
    z3 = fp.sub(fp.sub(fp.sqr(fp.add(y, z)), gamma), delta)
    y3 = fp.sub(
        fp.mul(alpha, fp.sub(fp.mul_const(beta, 4), x3)),
        fp.mul_const(fp.sqr(gamma), 8),
    )
    return (x3, y3, z3, inf)


def _add_full(fp, p1, p2):
    x1, y1, z1, inf1 = p1
    x2, y2, z2, inf2 = p2
    z1z1 = fp.sqr(z1)
    z2z2 = fp.sqr(z2)
    u1 = fp.mul(x1, z2z2)
    u2 = fp.mul(x2, z1z1)
    s1 = fp.mul(fp.mul(y1, z2), z2z2)
    s2 = fp.mul(fp.mul(y2, z1), z1z1)
    h = fp.sub(u2, u1)
    rr = fp.sub(s2, s1)
    h_zero = fp.is_zero(h)
    r_zero = fp.is_zero(rr)
    i = fp.sqr(fp.add(h, h))
    j = fp.mul(h, i)
    rr2 = fp.add(rr, rr)
    v = fp.mul(u1, i)
    x3 = fp.sub(fp.sub(fp.sqr(rr2), j), fp.add(v, v))
    t = fp.mul(s1, j)
    y3 = fp.sub(fp.mul(rr2, fp.sub(v, x3)), fp.add(t, t))
    z3 = fp.mul(fp.sub(fp.sub(fp.sqr(fp.add(z1, z2)), z1z1), z2z2), h)
    fin = jnp.zeros_like(inf1)
    out = (x3, y3, z3, fin)
    out = _pt_sel(h_zero * r_zero, _dbl(fp, p1), out)
    out = (out[0], out[1], out[2],
           jnp.maximum(out[3], h_zero * (1 - r_zero)))
    out = _pt_sel(inf2, p1, out)
    out = _pt_sel(inf1, p2, out)
    return out


def _add_mixed(fp, p1, a2):
    x1, y1, z1, inf1 = p1
    ax, ay, ainf = a2
    z1z1 = fp.sqr(z1)
    u2 = fp.mul(ax, z1z1)
    s2 = fp.mul(fp.mul(ay, z1), z1z1)
    h = fp.sub(u2, x1)
    rr = fp.sub(s2, y1)
    h_zero = fp.is_zero(h)
    r_zero = fp.is_zero(rr)
    hh = fp.sqr(h)
    i = fp.mul_const(hh, 4)
    j = fp.mul(h, i)
    rr2 = fp.add(rr, rr)
    v = fp.mul(x1, i)
    x3 = fp.sub(fp.sub(fp.sqr(rr2), j), fp.add(v, v))
    t = fp.mul(y1, j)
    y3 = fp.sub(fp.mul(rr2, fp.sub(v, x3)), fp.add(t, t))
    z3 = fp.sub(fp.sub(fp.sqr(fp.add(z1, h)), z1z1), hh)
    fin = jnp.zeros_like(inf1)
    out = (x3, y3, z3, fin)
    out = _pt_sel(h_zero * r_zero, _dbl(fp, p1), out)
    out = (out[0], out[1], out[2],
           jnp.maximum(out[3], h_zero * (1 - r_zero)))
    a2j = (ax, ay, _one(ax.shape[-1]), ainf)
    out = _pt_sel(ainf, p1, out)
    out = _pt_sel(inf1, a2j, out)
    return out


# ---------------------------------------------------------------------------
# The kernel.
# ---------------------------------------------------------------------------


def _onehot(digit, blk):
    """digit (1, BLK) int32 -> (16, BLK) int32 one-hot (signed: Mosaic
    has no unsigned reductions)."""
    t = jax.lax.broadcasted_iota(jnp.int32, (TABLE, blk), 0)
    return (t == digit).astype(jnp.int32)


def _isum(mask_i32, tab_u32):
    """One-hot select: sum(mask * table) over entries in int32 (Mosaic
    has no unsigned reductions; limbs < 2^16 so this is exact)."""
    return jnp.sum(mask_i32 * tab_u32.astype(jnp.int32), axis=0).astype(
        jnp.uint32
    )


def _unpack_words(wref):
    """(8, BLK) uint32 32-bit words -> (17, BLK) canonical 16-bit limbs.
    Inputs are canonical field elements (< 2^256), so the top limb is 0.
    Word inputs quarter the host->device transfer, which dominates
    end-to-end latency on tunneled devices."""
    w = wref[:]
    rows = []
    for i in range(8):
        rows.append(w[i:i + 1] & jnp.uint32(MASK))
        rows.append(w[i:i + 1] >> jnp.uint32(LIMB_BITS))
    rows.append(jnp.zeros_like(rows[0]))
    return jnp.concatenate(rows, axis=0)


KEYTAB = 256  # fixed unique-key table size for the dedup kernel variant


def _kernel(qx_ref, qy_ref, d1_ref, d2_ref, c0_ref, flags_ref,
            solmat_ref, bias_ref, r256_ref, r512_ref,
            subc_ref, plimbs_ref, nlimbs_ref, gx_ref, gy_ref,
            out_ref, tabx, taby, tabz, tabinf):
    fp = FpP256(
        solmat_ref[:], bias_ref[:], r256_ref[:],
        r512_ref[:], subc_ref[:], plimbs_ref[:],
    )
    qx = _unpack_words(qx_ref)
    qy = _unpack_words(qy_ref)
    _kernel_body(fp, qx, qy, d1_ref, d2_ref, c0_ref, flags_ref,
                 nlimbs_ref, gx_ref, gy_ref, out_ref,
                 tabx, taby, tabz, tabinf)


def _kernel_dedup(ktabx_ref, ktaby_ref, kidx_ref, d1_ref, d2_ref, c0_ref,
                  flags_ref, solmat_ref, bias_ref, r256_ref, r512_ref,
                  subc_ref, plimbs_ref, nlimbs_ref, gx_ref, gy_ref,
                  out_ref, tabx, taby, tabz, tabinf):
    """Variant with a shared unique-key table: real blocks carry few
    distinct endorser keys, so per-lane pubkeys (64B/sig of transfer)
    collapse to a (8, KEYTAB)-word table + one u32 index per lane.
    Per-lane coordinates materialize via an exact one-hot f32 MXU
    contraction (limbs < 2^16, one-hot sum -> < 2^24)."""
    fp = FpP256(
        solmat_ref[:], bias_ref[:], r256_ref[:],
        r512_ref[:], subc_ref[:], plimbs_ref[:],
    )
    blk = kidx_ref.shape[-1]
    tx = _unpack_words(ktabx_ref)  # (17, KEYTAB); shape-agnostic helper
    ty = _unpack_words(ktaby_ref)
    idx = kidx_ref[0:1].astype(jnp.int32)  # (1, blk)
    iota = jax.lax.broadcasted_iota(jnp.int32, (KEYTAB, blk), 0)
    oh = (iota == idx).astype(jnp.float32)  # (KEYTAB, blk)
    qx = _f2u(jnp.dot(_u2f(tx), oh, precision=jax.lax.Precision.HIGHEST))
    qy = _f2u(jnp.dot(_u2f(ty), oh, precision=jax.lax.Precision.HIGHEST))
    _kernel_body(fp, qx, qy, d1_ref, d2_ref, c0_ref, flags_ref,
                 nlimbs_ref, gx_ref, gy_ref, out_ref,
                 tabx, taby, tabz, tabinf)


def _kernel_body(fp, qx, qy, d1_ref, d2_ref, c0_ref, flags_ref,
                 nlimbs_ref, gx_ref, gy_ref, out_ref,
                 tabx, taby, tabz, tabinf):
    blk = qx.shape[-1]
    fin = jnp.zeros((1, blk), jnp.int32)  # flags are int32 0/1

    # -- Q window table (entries 0, 1 direct; 2..15 via mixed-add chain) --
    zero = jnp.zeros((1, WIDE, blk), jnp.uint32)
    tabx[0:1] = zero
    taby[0:1] = zero
    tabz[0:1] = zero
    tabinf[0:1] = jnp.ones((1, blk), jnp.uint32)
    tabx[1:2] = qx[None]
    taby[1:2] = qy[None]
    tabz[1:2] = _one(blk)[None]
    tabinf[1:2] = jnp.zeros((1, blk), jnp.uint32)
    q_aff = (qx, qy, fin)

    def build(i, _):
        prev = (
            tabx[pl.ds(i - 1, 1)][0],
            taby[pl.ds(i - 1, 1)][0],
            tabz[pl.ds(i - 1, 1)][0],
            tabinf[pl.ds(i - 1, 1)].astype(jnp.int32),
        )
        nxt = _add_mixed(fp, prev, q_aff)
        tabx[pl.ds(i, 1)] = nxt[0][None]
        taby[pl.ds(i, 1)] = nxt[1][None]
        tabz[pl.ds(i, 1)] = nxt[2][None]
        tabinf[pl.ds(i, 1)] = nxt[3].astype(jnp.uint32)
        return 0

    jax.lax.fori_loop(2, TABLE, build, 0)

    gx = gx_ref[:][:, :, None]  # (16, 17, 1)
    gy = gy_ref[:][:, :, None]

    # -- 64-window joint ladder, MSB first.  The infinity flag crosses
    # the fori_loop boundary as int32: an i1 loop carry round-trips
    # through i8 in Mosaic, which cannot truncate back to i1. --
    zeros = jnp.zeros((WIDE, blk), jnp.uint32)
    r0 = (zeros, zeros, zeros, jnp.ones((1, blk), jnp.int32))

    def window(w, r):
        for _ in range(4):
            r = _dbl(fp, r)
        # digits arrive packed 8-per-u32: word w//8, nibble w%8
        shift = (jnp.uint32(4) * (w % 8).astype(jnp.uint32))
        w1 = ((d1_ref[pl.ds(w // 8, 1)] >> shift) & jnp.uint32(0xF)).astype(
            jnp.int32
        )  # (1, BLK)
        w2 = ((d2_ref[pl.ds(w // 8, 1)] >> shift) & jnp.uint32(0xF)).astype(
            jnp.int32
        )
        oh1 = _onehot(w1, blk)  # (16, BLK) int32
        ga = (
            _isum(oh1[:, None, :], gx),
            _isum(oh1[:, None, :], gy),
            (w1 == 0).astype(jnp.int32),
        )
        r = _add_mixed(fp, r, ga)
        oh2 = _onehot(w2, blk)
        qj = (
            _isum(oh2[:, None, :], tabx[:]),
            _isum(oh2[:, None, :], taby[:]),
            _isum(oh2[:, None, :], tabz[:]),
            jnp.sum(oh2 * tabinf[:].astype(jnp.int32), axis=0,
                    keepdims=True),
        )
        r = _add_full(fp, r, qj)
        return r

    x, y, z, inf = jax.lax.fori_loop(0, NWINDOWS, window, r0)

    # -- final check: x(R) == r (mod n) without inversion --
    z2 = fp.sqr(z)
    x_can = fp.canon(x)

    def matches(cand):
        n = jnp.sum(
            (x_can != fp.canon(fp.mul(cand, z2))).astype(jnp.int32),
            axis=0,
            keepdims=True,
        )
        return (n == 0).astype(jnp.int32)

    cand0 = _unpack_words(c0_ref)
    m0 = matches(cand0)
    # cand1 = r + n, built on-device (saves a 32B/sig host transfer);
    # only consulted when the host flagged r + n < p, so the unreduced
    # value (< 2^257, canonicalized below) is safe to feed fp.mul.
    cand1 = fp._fold_resolve(_coarse(cand0 + nlimbs_ref[:], WIDE))
    m1 = matches(cand1)
    cand1_ok = flags_ref[0:1].astype(jnp.int32)
    valid = flags_ref[1:2].astype(jnp.int32)
    # z == 0 means the ladder degenerated (possible only for
    # out-of-group inputs, e.g. an off-curve or zero public key); the
    # x(R) check would then compare 0 == cand*0 and accept everything,
    # so such lanes are forced invalid (defense in depth — the host
    # stack never feeds off-curve keys).
    z_ok = 1 - fp.is_zero(z)
    ok = (
        jnp.minimum(m0 + m1 * cand1_ok, 1)
        * (1 - jnp.minimum(inf, 1)) * z_ok * valid
    )
    # (1, 8, BLK) block: row dim padded to the TPU sublane tile
    out_ref[:] = jnp.broadcast_to(
        ok.astype(jnp.uint32)[None], out_ref.shape
    )


def _specs(blk):
    lane_spec = lambda rows: pl.BlockSpec(  # noqa: E731
        (rows, blk), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    const_spec = lambda shape: pl.BlockSpec(  # noqa: E731
        shape, lambda i: tuple(0 for _ in shape), memory_space=pltpu.VMEM
    )
    return lane_spec, const_spec


def _common_specs(const_spec):
    return [
        const_spec((NLIMBS, 2 * WIDE)),           # solmat
        const_spec((WIDE, 1)),                    # bias
        const_spec((NLIMBS, 1)),                  # r256
        const_spec((NLIMBS, 1)),                  # r512
        const_spec((WIDE, 1)),                    # sub_c
        const_spec((WIDE, 1)),                    # p_limbs
        const_spec((WIDE, 1)),                    # n_limbs (group order)
        const_spec((TABLE, WIDE)),                # gx
        const_spec((TABLE, WIDE)),                # gy
    ]


def _pallas_opts(nblocks, blk, interpret):
    return dict(
        grid=(nblocks,),
        out_specs=pl.BlockSpec(
            (1, 8, blk), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((nblocks, 8, blk), jnp.uint32),
        scratch_shapes=[
            pltpu.VMEM((TABLE, WIDE, blk), jnp.uint32),  # tabx
            pltpu.VMEM((TABLE, WIDE, blk), jnp.uint32),  # taby
            pltpu.VMEM((TABLE, WIDE, blk), jnp.uint32),  # tabz
            pltpu.VMEM((TABLE, blk), jnp.uint32),        # tabinf
        ],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
        interpret=interpret,
    )


@functools.lru_cache(maxsize=None)
def _build_call(nblocks: int, blk: int, interpret: bool):
    lane_spec, const_spec = _specs(blk)
    fn = pl.pallas_call(
        _kernel,
        in_specs=[
            lane_spec(8),      # qx (packed 32-bit words)
            lane_spec(8),      # qy
            lane_spec(8),      # d1 (8 window digits per word)
            lane_spec(8),      # d2
            lane_spec(8),      # cand0
            lane_spec(2),      # flags: [cand1_ok; valid]
        ] + _common_specs(const_spec),
        **_pallas_opts(nblocks, blk, interpret),
    )
    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _build_call_dedup(nblocks: int, blk: int, interpret: bool):
    lane_spec, const_spec = _specs(blk)
    fn = pl.pallas_call(
        _kernel_dedup,
        in_specs=[
            const_spec((8, KEYTAB)),  # ktabx (unique-key words)
            const_spec((8, KEYTAB)),  # ktaby
            lane_spec(1),      # kidx (u32 per lane)
            lane_spec(8),      # d1
            lane_spec(8),      # d2
            lane_spec(8),      # cand0
            lane_spec(2),      # flags
        ] + _common_specs(const_spec),
        **_pallas_opts(nblocks, blk, interpret),
    )
    return jax.jit(fn)


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def prepare_packed(items) -> dict:
    """Host preprocessing straight to PACKED device inputs.

    Replaces ec.prepare_batch + prepack for the hot path: the scalar
    field work uses ONE modular inversion for the whole batch
    (Montgomery's trick over all s values) and the array packing is
    vectorized numpy over little-endian byte dumps — no per-limb Python
    loops.  ~10x faster than ec.prepare_batch on large batches.

    items: (x, y, digest32, r, s) tuples.  Returns the packed-array dict
    that verify_packed consumes.
    """
    from fabric_tpu.csp.api import P256_N

    n = len(items)
    half_n = P256_N >> 1
    valid = np.zeros(n, bool)
    c1_ok = np.zeros(n, bool)
    svals = []
    for i, it in enumerate(items):
        r, s = it[3], it[4]
        if (
            isinstance(r, int)
            and isinstance(s, int)
            and 0 < r < P256_N
            and 0 < s <= half_n
            and len(it[2]) == 32
        ):
            valid[i] = True
            svals.append(s)
        else:
            svals.append(1)

    # Montgomery batch inversion: one pow, 3(n-1) modular multiplies
    prefix = [1] * (n + 1)
    for i in range(n):
        prefix[i + 1] = prefix[i] * svals[i] % P256_N
    inv = pow(prefix[n], -1, P256_N)

    xb = bytearray(32 * n)
    yb = bytearray(32 * n)
    u1b = bytearray(32 * n)
    u2b = bytearray(32 * n)
    c0b = bytearray(32 * n)
    for i in range(n - 1, -1, -1):
        it = items[i]
        w = inv * prefix[i] % P256_N
        inv = inv * svals[i] % P256_N
        o = 32 * i
        if not valid[i]:
            x, y, u1, u2, c0 = P256_GX, P256_GY, 1, 1, 1
        else:
            x, y = it[0], it[1]
            r = it[3]
            e = int.from_bytes(it[2], "big") % P256_N
            u1 = e * w % P256_N
            u2 = r * w % P256_N
            c0 = r
            if r + P256_N < P256_P:
                c1_ok[i] = True
        xb[o:o + 32] = x.to_bytes(32, "little")
        yb[o:o + 32] = y.to_bytes(32, "little")
        u1b[o:o + 32] = u1.to_bytes(32, "little")
        u2b[o:o + 32] = u2.to_bytes(32, "little")
        c0b[o:o + 32] = c0.to_bytes(32, "little")

    def words(buf):  # (B, 32) LE bytes -> (8, B) u32 words
        return np.ascontiguousarray(
            np.frombuffer(bytes(buf), np.uint32).reshape(n, 8).T
        )

    def digits_packed(buf):  # LE bytes -> (8, B) u32, MSB-first nibbles
        u8 = np.frombuffer(bytes(buf), np.uint8).reshape(n, 32)
        nibbles = np.empty((n, 64), np.uint32)
        nibbles[:, 0::2] = u8 & 0xF        # nibble m even = low
        nibbles[:, 1::2] = u8 >> 4
        d = nibbles[:, ::-1]               # digit k = nibble 63-k
        shifts = (np.uint32(4) * np.arange(8, dtype=np.uint32))[None, None]
        return np.ascontiguousarray(
            (d.reshape(n, 8, 8) << shifts).sum(axis=2, dtype=np.uint32).T
        )

    return {
        "qx": words(xb),
        "qy": words(yb),
        "d1": digits_packed(u1b),
        "d2": digits_packed(u2b),
        "cand0": words(c0b),
        "cand1_ok": c1_ok,
        "valid": valid,
    }


def verify_packed(packed: dict, blk: int = BLK,
                  interpret: bool | None = None):
    """Run the kernel on prepare_packed / dedup_keys output; returns a
    lazy device array handle via a callable -> (B,) bool (so callers can
    dispatch several chunks before blocking on any result).

    When `packed` carries "kidx"/"ktabx"/"ktaby" (the deduplicated-key
    layout from `dedup_keys`), the key-table kernel variant runs: 64B of
    per-lane pubkey transfer collapses to one shared (8, 256)-word
    table + a u32 index per lane."""
    if interpret is None:
        interpret = _use_interpret()
    dedup = "kidx" in packed
    b = (packed["kidx"] if dedup else packed["qx"]).shape[-1]
    nb = -(-b // blk)
    pad = nb * blk - b

    def padlanes(a):
        if pad:
            a = np.concatenate(
                [a, np.zeros(a.shape[:-1] + (pad,), a.dtype)], axis=-1
            )
        return a

    flags = np.stack(
        [
            np.asarray(packed["cand1_ok"], np.uint32),
            np.asarray(packed["valid"], np.uint32),
        ]
    )
    c = _consts()
    if dedup:
        head = [
            packed["ktabx"],
            packed["ktaby"],
            padlanes(packed["kidx"].reshape(1, -1)),
        ]
    else:
        head = [padlanes(packed["qx"]), padlanes(packed["qy"])]
    inputs = head + [
        padlanes(packed["d1"]),
        padlanes(packed["d2"]),
        padlanes(packed["cand0"]),
        padlanes(flags),
        c["solmat"],
        c["bias"],
        c["r256"],
        c["r512"],
        c["sub_c"],
        c["p_limbs"],
        c["n_limbs"],
        c["gx"][:, :, 0],
        c["gy"][:, :, 0],
    ]
    build = _build_call_dedup if dedup else _build_call
    out = build(nb, blk, interpret)(*inputs)

    def collect():
        return np.asarray(out)[:, 0, :].reshape(-1)[:b].astype(bool)

    return collect


def dedup_keys(packed: dict) -> dict:
    """Rewrite a packed dict into the deduplicated-key layout when the
    batch uses at most KEYTAB distinct public keys (typical blocks carry
    a handful of endorser identities); otherwise return it unchanged.
    Saves 64B/signature of host->device transfer.

    The table shape is pinned to (8, KEYTAB): the kernel's one-hot is
    hard-wired to KEYTAB lanes, and an index outside it would select the
    zero point — which the kernel's z==0 guard rejects, but the layout
    never produces such an index in the first place."""
    qx, qy = packed["qx"], packed["qy"]
    cols = np.concatenate([qx, qy]).T  # (B, 16) words per key
    uniq, idx = np.unique(cols, axis=0, return_inverse=True)
    if uniq.shape[0] > KEYTAB:
        return packed
    ktab = np.zeros((KEYTAB, 16), np.uint32)
    ktab[: uniq.shape[0]] = uniq
    out = {k: v for k, v in packed.items() if k not in ("qx", "qy")}
    out["ktabx"] = np.ascontiguousarray(ktab[:, :8].T)
    out["ktaby"] = np.ascontiguousarray(ktab[:, 8:].T)
    out["kidx"] = idx.astype(np.uint32)
    return out


def _pack_words(limbs_bn: np.ndarray) -> np.ndarray:
    """(B, 17) canonical limbs -> (8, B) uint32 32-bit words (top limb
    must be 0, true for canonical < 2^256 field elements)."""
    a = np.asarray(limbs_bn, np.uint32)
    return np.ascontiguousarray(
        (a[:, 0:16:2] | (a[:, 1:17:2] << np.uint32(16))).T
    )


def _pack_digits(d_bn: np.ndarray) -> np.ndarray:
    """(B, 64) 4-bit window digits -> (8, B) uint32, 8 digits per word
    (digit k in bits 4*(k%8) of word k//8)."""
    d = np.asarray(d_bn, np.uint32).reshape(-1, 8, 8)
    shifts = (np.uint32(4) * np.arange(8, dtype=np.uint32))[None, None, :]
    return np.ascontiguousarray((d << shifts).sum(axis=2, dtype=np.uint32).T)


def prepack(prep: dict, blk: int = BLK) -> tuple[list, int]:
    """prepare_batch arrays -> padded, packed device inputs (~4x smaller
    transfers than raw limbs — the tunnel/PCIe hop is what dominates
    end-to-end batch-verify latency)."""
    b = prep["qx"].shape[0]
    nb = -(-b // blk)
    pad = nb * blk - b

    def padded(a):
        a = np.asarray(a)
        if pad:
            a = np.concatenate([a, np.zeros((pad,) + a.shape[1:], a.dtype)])
        return a

    flags = np.stack(
        [
            padded(np.asarray(prep["cand1_ok"], np.uint32)),
            padded(np.asarray(prep["valid"], np.uint32)),
        ]
    )
    c = _consts()
    inputs = [
        _pack_words(padded(prep["qx"])),
        _pack_words(padded(prep["qy"])),
        _pack_digits(padded(prep["d1"])),
        _pack_digits(padded(prep["d2"])),
        _pack_words(padded(prep["cand0"])),
        flags,
        c["solmat"],
        c["bias"],
        c["r256"],
        c["r512"],
        c["sub_c"],
        c["p_limbs"],
        c["n_limbs"],
        c["gx"][:, :, 0],
        c["gy"][:, :, 0],
    ]
    return inputs, b


def verify_prepared(qx, qy, d1, d2, cand0, cand1, cand1_ok, valid,
                    blk: int = BLK, interpret: bool | None = None):
    """Same contract as ec.verify_prepared (prepare_batch arrays in,
    (B,) bool out) via the fused Pallas kernel; pads to a lane multiple."""
    if interpret is None:
        interpret = _use_interpret()
    inputs, b = prepack(
        dict(qx=qx, qy=qy, d1=d1, d2=d2, cand0=cand0, cand1=cand1,
             cand1_ok=cand1_ok, valid=valid),
        blk,
    )
    nb = inputs[0].shape[1] // blk
    call = _build_call(nb, blk, interpret)
    out = call(*inputs)
    return np.asarray(out)[:, 0, :].reshape(-1)[:b].astype(bool)


__all__ = [
    "verify_prepared",
    "prepare_packed",
    "verify_packed",
    "FpP256",
    "BLK",
]
