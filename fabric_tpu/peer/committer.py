"""Commit orchestration: validate -> commit -> notify.

Reference: gossip/privdata/coordinator.go:149 StoreBlock (txvalidator ->
pvtdata assembly -> CommitLegacy) + core/committer/committer_impl.go.
Private-data fetching slots in between validate and commit when the
pvtdata subsystem lands.

`store_stream` is the TPU-first throughput path: the validator pipeline
overlaps host collect with device verify across blocks, and a dedicated
committer thread overlaps MVCC+persist of block k with collect of
k+1/k+2 (the reference serializes validate -> commit per block inside
StoreBlock; deliver clients therefore see commit latency on the
validation critical path)."""

from __future__ import annotations

import collections
import queue
import threading
import time

from fabric_tpu.common import tracing
from fabric_tpu.devtools.lockwatch import spawn_thread


class Committer:
    def __init__(self, validator, ledger, metrics=None):
        self._validator = validator
        self._ledger = ledger
        self._listeners: list = []
        self._lock = threading.Lock()
        self.metrics = metrics

    def add_commit_listener(self, fn) -> None:
        self._listeners.append(fn)

    def get_block_by_number(self, num: int):
        """Committed-block reader for gossip state transfer
        (gossip/state.py _read_committed serves state_requests from it
        once blocks age out of the gossip message store)."""
        return self._ledger.get_block_by_number(num)

    def store_block(self, block) -> list[int]:
        """The per-block pipeline; returns final validation flags."""
        t0 = time.perf_counter()
        self._validator.validate(block)  # sets sig/policy flags
        t_validate = time.perf_counter() - t0
        with self._lock:
            self._ledger.commit(block)  # MVCC + persist (updates flags again)
        if self.metrics is not None:
            self.metrics.observe(
                "validate_duration", t_validate, channel=self._validator.channel_id
            )
            self.metrics.observe(
                "commit_duration",
                time.perf_counter() - t0,
                channel=self._validator.channel_id,
            )
        from fabric_tpu import protoutil

        flags = list(protoutil.tx_filter(block))
        for fn in self._listeners:
            fn(block, flags)
        return flags

    def store_stream(self, blocks, depth: int = 3):
        """Pipelined validate+commit over a block stream; yields each
        block's final (post-MVCC) flags in order.

        Three overlapped stages: host collect (validator), device
        verify (CSP async), and MVCC+persist (this method's committer
        thread).  Same documented relaxation as validate_pipeline: SBE
        metadata reads for block k+1 may precede block k's commit;
        depth=1 restores strict adjacency.

        Group commit: the committer thread buffers up to `depth` blocks
        into one CommitGroup (one shared KV transaction + unsynced
        block-file appends) and flushes at the group boundary — one
        fsync + one KV txn for the whole group.  The boundary triggers
        when `depth` blocks are buffered OR the commit queue drains
        (so a validator-bound stream still goes durable block by block
        and adds no latency).  Listener callbacks, dedup-window
        releases, and yielded flags all wait for the flush: nothing is
        announced before it is durable."""
        from fabric_tpu import protoutil

        pending: collections.deque = collections.deque()
        releases: collections.deque = collections.deque()
        rwsets_q: collections.deque = collections.deque()

        def tee(it):
            for b in it:
                pending.append(b)
                yield b

        commit_q: queue.Queue = queue.Queue(maxsize=depth)
        done_q: queue.Queue = queue.Queue()

        def commit_loop():
            failed = False
            group = self._ledger.begin_commit_group()
            grouped: list = []  # (block, release_txids) awaiting flush

            def announce():
                # post-flush callbacks run OUTSIDE self._lock (as the
                # per-block path always did): a listener re-entering
                # the Committer must not deadlock, and slow listeners
                # must not serialize against other commit entrypoints
                for blk, release in grouped:
                    # the ledger index now durably holds these txids:
                    # safe to close the validator's in-flight dedup
                    # window
                    release()
                    flags = list(protoutil.tx_filter(blk))
                    for fn in self._listeners:
                        fn(blk, flags)
                    done_q.put(flags)
                grouped.clear()

            while True:
                item = commit_q.get()
                if item is None:
                    if not failed and grouped:
                        try:
                            with self._lock:
                                self._ledger.commit_group_flush(group)
                            announce()
                        except Exception as e:
                            done_q.put(e)
                    return
                if failed:
                    continue  # drain without committing past a failure
                blk, release_txids, assist = item
                try:
                    flushed = False
                    with self._lock, tracing.attached(
                        getattr(assist, "trace_ctx", None)
                    ):
                        self._ledger.commit(blk, assist=assist, group=group)
                        grouped.append((blk, release_txids))
                        # boundary_hint: a buffered block carries a
                        # pending snapshot request — flush HERE so the
                        # export height is exactly the requested one
                        if (
                            len(grouped) >= depth
                            or commit_q.empty()
                            or getattr(group, "boundary_hint", False)
                        ):
                            self._ledger.commit_group_flush(group)
                            flushed = True
                    if flushed:
                        announce()
                except Exception as e:  # surfaced to the consumer
                    # (a raising LISTENER counts too — the thread must
                    # post the error, never die leaving the consumer
                    # blocked on done_q); nothing further commits onto
                    # suspect state
                    failed = True
                    done_q.put(e)

        th = spawn_thread(
            target=commit_loop, name="committer-stream", kind="worker"
        )
        th.start()
        n_in = n_out = 0
        try:
            for _flags in self._validator.validate_pipeline(
                tee(blocks), depth=depth, release=releases.append,
                rwsets_out=rwsets_q.append,
            ):
                commit_q.put(
                    (pending.popleft(), releases.popleft(),
                     rwsets_q.popleft())
                )
                n_in += 1
                while not done_q.empty():
                    r = done_q.get()
                    if isinstance(r, Exception):
                        raise r
                    n_out += 1
                    yield r
            while n_out < n_in:
                r = done_q.get()
                if isinstance(r, Exception):
                    raise r
                n_out += 1
                yield r
        finally:
            commit_q.put(None)
            th.join()

    @property
    def height(self) -> int:
        """DURABLE chain height — gossip state transfer keys payload
        dedup and peer advertisement off this, and a buffered group's
        blocks are neither readable nor guaranteed to survive (a flush
        failure rolls them back), so they must not be advertised or
        used to drop incoming copies."""
        return getattr(self._ledger, "durable_height", self._ledger.height)


__all__ = ["Committer"]
