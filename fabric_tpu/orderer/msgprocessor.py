"""Broadcast-side message processing: classification + filter pipeline.

Reference: orderer/common/msgprocessor (standardchannel.go:100
ProcessNormalMsg runs the rule set; sigfilter.go evaluates the channel
Writers policy over the envelope signature; sizefilter.go enforces
absolute_max_bytes; expiration.go rejects expired creator certs).
"""

from __future__ import annotations

import datetime
import enum

from cryptography import x509

from fabric_tpu.protos.common import common_pb2
from fabric_tpu.protos.msp import identities_pb2
from fabric_tpu.protoutil import SignedData


class Classification(enum.Enum):
    NORMAL = 0
    CONFIG_UPDATE = 1
    CONFIG = 2


class MsgProcessorError(Exception):
    pass


class StandardChannelProcessor:
    def __init__(self, channel_id: str, bundle, csp):
        self.channel_id = channel_id
        self._bundle = bundle
        self._csp = csp

    def classify(self, env: common_pb2.Envelope) -> Classification:
        payload = common_pb2.Payload.FromString(env.payload)
        chdr = common_pb2.ChannelHeader.FromString(payload.header.channel_header)
        if chdr.type == common_pb2.CONFIG_UPDATE:
            return Classification.CONFIG_UPDATE
        if chdr.type == common_pb2.CONFIG:
            return Classification.CONFIG
        return Classification.NORMAL

    def process_normal_msg(self, env: common_pb2.Envelope) -> int:
        """Raises MsgProcessorError if rejected; returns the config sequence
        the message was validated against (for revalidation downstream)."""
        self._size_filter(env)
        payload = common_pb2.Payload.FromString(env.payload)
        chdr = common_pb2.ChannelHeader.FromString(payload.header.channel_header)
        if chdr.channel_id != self.channel_id:
            raise MsgProcessorError(
                f"message is for channel {chdr.channel_id!r}, this is {self.channel_id!r}"
            )
        shdr = common_pb2.SignatureHeader.FromString(payload.header.signature_header)
        self._expiration_filter(shdr.creator)
        self._sig_filter(env, shdr)
        return self._bundle.config.sequence

    def _size_filter(self, env: common_pb2.Envelope) -> None:
        oc = self._bundle.orderer_config
        size = len(env.SerializeToString())
        if oc and size > oc.absolute_max_bytes:
            raise MsgProcessorError(
                f"message size {size} exceeds absolute maximum {oc.absolute_max_bytes}"
            )

    def _expiration_filter(self, creator: bytes) -> None:
        try:
            sid = identities_pb2.SerializedIdentity.FromString(creator)
            certs = x509.load_pem_x509_certificates(sid.id_bytes)
        except Exception:
            return  # sig filter will reject undeserializable creators
        now = datetime.datetime.now(datetime.timezone.utc)
        if certs and certs[0].not_valid_after_utc < now:
            raise MsgProcessorError("creator certificate has expired")

    def _sig_filter(self, env: common_pb2.Envelope, shdr) -> None:
        policy = self._bundle.policy_manager.get_policy("/Channel/Writers")
        sd = [SignedData(env.payload, shdr.creator, env.signature)]
        if not policy.evaluate_signed_data(sd, self._csp):
            raise MsgProcessorError("message did not satisfy the channel Writers policy")


__all__ = ["StandardChannelProcessor", "MsgProcessorError", "Classification"]
