"""Test configuration.

Tests run on CPU with a virtual 8-device mesh so multi-chip sharding
(shard_map over jax.sharding.Mesh) is exercised without TPU hardware, per
the reference test strategy of simulating multi-node on one host
(integration/nwo).  Must run before jax initializes a backend.
"""

import os

# Force (not setdefault): the ambient environment pins JAX_PLATFORMS to the
# TPU platform, but unit tests must be hermetic and run on the virtual CPU
# mesh even when the TPU tunnel is down.
os.environ["JAX_PLATFORMS"] = "cpu"

# The whole tier-1 suite doubles as a lock-order soak test: coordination
# locks created through devtools.lockwatch.named_lock/named_rlock become
# instrumented wrappers that maintain the process-wide acquisition-order
# graph and raise LockOrderError on any acquisition that closes a cycle.
# setdefault so FABRIC_TPU_LOCKWATCH=0 can switch it off (or =record to
# log without raising) when bisecting a failure.
os.environ.setdefault("FABRIC_TPU_LOCKWATCH", "1")

# ...and as a thread-lifecycle soak test: every daemonized worker is
# created through devtools.lockwatch.spawn_thread (fabriclint's
# thread-hygiene rule enforces this statically), and under
# FABRIC_TPU_THREADWATCH each spawn registers in a process-wide live
# registry and records unhandled exceptions.  The session-end fixture
# below drains worker-kind threads and asserts the violation ledger is
# empty, so a worker leaked past its owner's drain/close fails the
# suite here instead of aborting interpreter teardown ("FATAL:
# exception not rethrown", the MULTICHIP rc=134 class).
os.environ.setdefault("FABRIC_TPU_THREADWATCH", "1")

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The env var alone is NOT enough here: the ambient TPU-tunnel harness
# installs a sitecustomize that calls jax.config.update("jax_platforms",
# "axon,cpu") at interpreter start, which takes priority over JAX_PLATFORMS.
# Without the explicit update below, "hermetic" tests silently run their
# kernels through the TPU tunnel (slow remote compiles, hangs when the
# tunnel misbehaves).  A later config.update wins as long as backends are
# not initialized yet.
import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")
if jax._src.xla_bridge.backends_are_initialized():  # pragma: no cover
    from jax.extend.backend import clear_backends

    clear_backends()


@pytest.fixture(scope="session", autouse=True)
def _lockwatch_soak_gate():
    """Fail the session if ANY lock-order inversion was recorded and not
    examined-and-cleared by a test.  Without this, a violation raised on
    a background thread (snapshot export) or inside a broad exception
    handler dies silently and tier-1 stays green — the suite-wide soak
    only has teeth if the violation ledger is asserted empty at the end.
    (tests/test_lockwatch.py injects inversions deliberately; its autouse
    fixture resets the ledger after each test.)"""
    yield
    from fabric_tpu.devtools import lockwatch

    assert not lockwatch.violations, (
        "lock-order inversions recorded during the test session "
        f"(likely on a background thread): {lockwatch.violations!r}"
    )


@pytest.fixture(scope="session", autouse=True)
def _threadwatch_drain_gate():
    """Fail the session if any watched WORKER thread outlives the tests
    or died with an unhandled exception.  Workers are bounded jobs
    (flush waiters, snapshot exports, stream committers) whose owners
    must drain them — a worker still alive here is precisely the daemon
    thread the interpreter would kill mid-kernel at teardown, and one
    that died silently is how green runs become rc=134 aborts.
    Service-kind threads (acceptors, gossip/consensus loops) are
    covered by their owners' stop()/close() paths and excluded from the
    sweep."""
    yield
    from fabric_tpu.devtools import lockwatch

    if not lockwatch.threads_enabled():
        return
    stragglers = lockwatch.drain_threads(timeout=15.0)
    assert not stragglers, (
        f"worker threads still alive at session end: {stragglers!r} — "
        "their owner never drained them; they would be killed "
        "mid-execution at interpreter exit"
    )
    assert not lockwatch.thread_violations, (
        "threadwatch violations recorded during the test session: "
        f"{lockwatch.thread_violations!r}"
    )


@pytest.fixture(autouse=True)
def _soak_residue_drain():
    """Under an ENV-ARMED session plan (``FABRIC_TPU_SOAK``, or a
    session-wide ``FABRIC_TPU_FAULTLINE``) the background plan fires
    across EVERY test — drain its trips between tests so tests
    asserting on the trip ledger see their own plans' trips, not
    accumulated background residue.  Keys off the plan faultline
    actually armed (which encodes the FAULTLINE-beats-SOAK precedence),
    never a re-parse of the environment.  A no-op in unarmed runs."""
    yield
    from fabric_tpu.devtools import faultline

    env_plan = faultline.session_env_plan()
    if env_plan is not None and faultline.current_plan() is env_plan:
        faultline.drain_trips(env_plan.label)


@pytest.fixture(scope="session", autouse=True)
def _workpool_shutdown():
    """Shut the shared host work pool down at session end.  The commit
    path's parallel collect/prepare stages lazily spin up one
    process-wide tracked executor (common/workpool.py, registered as a
    service whose stop path is this shutdown) — declared AFTER the
    gates above so its teardown runs FIRST (fixtures finalize in
    reverse instantiation order) and the pool is gone before the
    threadwatch sweep.  A pool nobody started makes this a no-op."""
    yield
    from fabric_tpu.common import workpool

    workpool.shutdown()


@pytest.fixture(scope="session", autouse=True)
def _faultline_drain_gate():
    """Fail the session if a fault plan is still armed or the trip
    ledger was left undrained.  Chaos tests arm plans through
    faultline.use_plan, which disarms and clears the ledger on exit —
    a plan leaking past its test would silently inject faults into
    every later test, and unexamined trips mean a test fired faults it
    never asserted on (the same teeth as the threadwatch drain gate).

    Exception: an ENV-ARMED session plan (``FABRIC_TPU_SOAK=<seed>``,
    or a session-wide ``FABRIC_TPU_FAULTLINE``) deliberately stays
    armed for the WHOLE session (tier-1 as a chaos soak) — exactly that
    plan is expected to still be armed here and its background trips
    are drained, not asserted on; test-local plans nested inside it
    still drain themselves via use_plan.  Identity is checked against
    ``faultline.session_env_plan()`` (the plan _init_from_env actually
    armed, encoding the FAULTLINE-beats-SOAK precedence), never a
    re-parse of the environment."""
    yield
    from fabric_tpu.devtools import faultline

    env_plan = faultline.session_env_plan()
    if env_plan is not None:
        plan = faultline.current_plan()
        assert plan is env_plan, (
            "an environment plan was armed for this session but the "
            f"plan at session end is {plan.label if plan else None!r} — "
            "a chaos test leaked a plan over it (use faultline.use_plan)"
        )
        stray = [
            t for t in faultline.trips() if t["plan"] != env_plan.label
        ]
        assert not stray, (
            f"undrained non-background faultline trips at session end: "
            f"{stray!r}"
        )
        faultline.deactivate()
        faultline.reset_trips()
        return
    assert not faultline.active(), (
        "a faultline plan is still armed at session end — a chaos test "
        "leaked its plan (use faultline.use_plan)"
    )
    assert not faultline.trips(), (
        "undrained faultline trips at session end: "
        f"{faultline.trips()!r} — the test that injected them never "
        "drained the ledger (use faultline.use_plan)"
    )
