#!/usr/bin/env python
"""CI wrapper around fabriclint: run the full-tree pass (fabric_tpu at
the strict profile, tests/ and scripts/ at the relaxed profile) and
emit one JSON summary line in the same shape the bench scripts use, so
the driver/CI can scrape `"experiment": "fabriclint"` next to the bench
lines.  Exit code mirrors the linter (non-zero on any unsuppressed
error-severity violation, after the optional baseline ratchet).

Usage: python scripts/lint.py [--show-suppressed] [--baseline FILE]
       [--write-baseline FILE] [--summaries-out P] [--guards-out P]
       [--lockgraph-out P] [--faultmap-out P] [--rpcmap-out P]
       [--knobs-out P] [--metricmap-out P] [--budget-s S]

The baseline ratchet lets a new rule land loud-but-not-fatal: a JSON
{"rule": count} file tolerates up to COUNT unsuppressed errors per rule.
Stale budgets (looser than reality) fail, so the carve-out dies the
moment the tree is cleaner than it claims — the ratchet only tightens.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fabric_tpu.devtools.lint import (  # noqa: E402
    apply_baseline,
    lint_tree,
    load_baseline,
)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--show-suppressed", action="store_true",
        help="also print suppressed violations (with their reasons)",
    )
    ap.add_argument(
        "--baseline", default=None, metavar="FILE",
        help="JSON {rule: count} ratchet of tolerated per-rule errors",
    )
    ap.add_argument(
        "--write-baseline", default=None, metavar="FILE",
        help="record current per-rule error counts and exit 0",
    )
    ap.add_argument(
        "--summaries-out", default=None, metavar="PATH",
        help="write the dataflow engine's per-function summaries as "
             "JSON lines next to the result line — a reviewable CI "
             "artifact (what the interprocedural rules believed about "
             "every function this run)",
    )
    ap.add_argument(
        "--guards-out", default=None, metavar="PATH",
        help="write racecheck's inferred guarded-by map (declared + "
             "majority-inferred, with per-field site counts) as a JSON "
             "artifact — reviewers diff guard inference across PRs",
    )
    ap.add_argument(
        "--lockgraph-out", default=None, metavar="PATH",
        help="write the static role-level lock acquisition-order graph "
             "(production sites, edges[src][dst] -> [[file, line], ...]) "
             "as a JSON artifact — the static twin of lockwatch's "
             "runtime graph; tier-1 asserts runtime ⊆ static",
    )
    ap.add_argument(
        "--faultmap-out", default=None, metavar="PATH",
        help="write the chaos-coverage faultmap (every statically "
             "enumerated faultline seam + every pinned plan rule, "
             "deterministic order) as a JSON artifact — what the "
             "chaos-coverage rule cross-checked against the pinned "
             "campaign registry this run",
    )
    ap.add_argument(
        "--rpcmap-out", default=None, metavar="PATH",
        help="write the rpc-conformance map (every RPC method with its "
             "register and call sites, component-classified, handler "
             "shapes inferred) as a JSON artifact — tier-1 asserts "
             "observed methods ⊆ this map",
    )
    ap.add_argument(
        "--knobs-out", default=None, metavar="PATH",
        help="write the knob-conformance map (the reviewed FABRIC_TPU_* "
             "registry joined with every statically enumerated read "
             "site) as a JSON artifact",
    )
    ap.add_argument(
        "--metricmap-out", default=None, metavar="PATH",
        help="write the metrics-conformance map (producer/derived/"
             "consumer planes + the exposable series set) as a JSON "
             "artifact — tier-1 asserts scraped series ⊆ exposed",
    )
    ap.add_argument(
        "--no-cache", action="store_true",
        help="bypass the .fabriclint_cache dataflow cache (escape "
             "hatch; the cache is keyed by file content hashes and "
             "invalidates per file)",
    )
    ap.add_argument(
        "--budget-s", type=float, default=None, metavar="SECONDS",
        help="fail (exit 1) when the lint pass exceeds this wall-time "
             "budget — CI asserts a warm-cache full-tree pass stays "
             "under 1.5s so the CFG pass cannot quietly double tier-1 "
             "setup cost",
    )
    args = ap.parse_args()

    t0 = time.perf_counter()
    report = lint_tree(cache=not args.no_cache)
    elapsed = time.perf_counter() - t0

    for v in report.unsuppressed:
        print(str(v), file=sys.stderr)
    for v in report.warnings:
        print(str(v), file=sys.stderr)
    if args.show_suppressed:
        for v in report.suppressed:
            print(str(v), file=sys.stderr)

    summary = report.summary()
    summaries_written = None
    if args.summaries_out:
        with open(args.summaries_out, "w", encoding="utf-8") as f:
            n = 0
            for s in report.function_summaries():
                f.write(json.dumps(s, sort_keys=True) + "\n")
                n += 1
        summaries_written = {"path": args.summaries_out, "functions": n}
    guards_written = None
    if args.guards_out:
        guards = report.guard_map()
        with open(args.guards_out, "w", encoding="utf-8") as f:
            json.dump(guards, f, indent=2, sort_keys=True)
            f.write("\n")
        guards_written = {"path": args.guards_out, "fields": len(guards)}
    lockgraph_written = None
    if args.lockgraph_out:
        graph = report.lock_graph()
        with open(args.lockgraph_out, "w", encoding="utf-8") as f:
            json.dump(graph, f, indent=2, sort_keys=True)
            f.write("\n")
        lockgraph_written = {
            "path": args.lockgraph_out,
            "roles": len(graph["roles"]),
            "edges": sum(len(d) for d in graph["edges"].values()),
        }
    faultmap_written = None
    if args.faultmap_out:
        fm = report.faultmap()
        with open(args.faultmap_out, "w", encoding="utf-8") as f:
            json.dump(fm, f, indent=2, sort_keys=True)
            f.write("\n")
        faultmap_written = {
            "path": args.faultmap_out,
            "seams": len(fm["seams"]),
            "plans": len(fm["plans"]),
        }
    rpcmap_written = None
    if args.rpcmap_out:
        rm = report.rpcmap()
        with open(args.rpcmap_out, "w", encoding="utf-8") as f:
            json.dump(rm, f, indent=2, sort_keys=True)
            f.write("\n")
        rpcmap_written = {
            "path": args.rpcmap_out,
            "methods": len(rm["methods"]),
        }
    knobs_written = None
    if args.knobs_out:
        km = report.knobmap()
        with open(args.knobs_out, "w", encoding="utf-8") as f:
            json.dump(km, f, indent=2, sort_keys=True)
            f.write("\n")
        knobs_written = {
            "path": args.knobs_out,
            "knobs": len(km["registry"]),
            "reads": len(km["reads"]),
        }
    metricmap_written = None
    if args.metricmap_out:
        mm = report.metricmap()
        with open(args.metricmap_out, "w", encoding="utf-8") as f:
            json.dump(mm, f, indent=2, sort_keys=True)
            f.write("\n")
        metricmap_written = {
            "path": args.metricmap_out,
            "producers": len(mm["producers"]),
            "exposed": len(mm["exposed"]),
        }
    out = {
        "experiment": "fabriclint",
        "files": summary["files"],
        "violations": summary["violations"],
        "warnings": summary["warnings"],
        "suppressed": summary["suppressed"],
        "by_rule": summary["by_rule"],
        "warn_by_rule": summary["warn_by_rule"],
        "clean": summary["clean"],
        "cache": summary["cache"],
        "seconds": round(elapsed, 4),
    }
    if summaries_written is not None:
        out["summaries"] = summaries_written
    if guards_written is not None:
        out["guards"] = guards_written
    if lockgraph_written is not None:
        out["lockgraph"] = lockgraph_written
    if faultmap_written is not None:
        out["faultmap"] = faultmap_written
    if rpcmap_written is not None:
        out["rpcmap"] = rpcmap_written
    if knobs_written is not None:
        out["knobs"] = knobs_written
    if metricmap_written is not None:
        out["metricmap"] = metricmap_written
    budget_ok = True
    if args.budget_s is not None:
        budget_ok = elapsed <= args.budget_s
        out["budget"] = {"budget_s": args.budget_s, "ok": budget_ok}
    if args.write_baseline:
        with open(args.write_baseline, "w", encoding="utf-8") as f:
            json.dump(summary["by_rule"], f, indent=2, sort_keys=True)
            f.write("\n")
        out["baseline_written"] = args.write_baseline
        print(json.dumps(out))
        return 0
    if args.baseline:
        ratchet = apply_baseline(report, load_baseline(args.baseline))
        out["baseline"] = ratchet
        print(json.dumps(out))
        return 0 if ratchet["ok"] and budget_ok else 1
    print(json.dumps(out))
    return 0 if summary["clean"] and budget_ok else 1


if __name__ == "__main__":
    sys.exit(main())
