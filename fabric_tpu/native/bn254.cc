// Native BN254 (alt-bn128) G1 arithmetic for the idemix data plane.
//
// The reference's idemix math runs on pure-Go AMCL (fabric-amcl,
// SURVEY.md §2.1); the TPU build's Python bn254.py is the portable
// fallback and THIS file is the hot path: Montgomery Fp (4x64 limbs,
// __int128 products), Jacobian G1 (a = 0, y^2 = x^3 + 3), 4-bit
// windowed scalar multiplication, and batch APIs with one shared
// Montgomery inversion for the affine outputs.  Used by the Schnorr
// commitment recomputation in idemix signature verification
// (signature.go:243-relations equivalent) and the RLC accumulation in
// batched verification — the per-item cost that dominates once the
// pairings amortize to two per batch.
//
// All point/scalar I/O is 32-byte big-endian affine coordinates.

#include <cstdint>
#include <cstring>

#include "fp254.h"

typedef uint8_t u8;
typedef uint64_t u64;

namespace {

using fp254::Fp;
using fp254::ONE_M;
using fp254::fp_add;
using fp254::fp_dbl;
using fp254::fp_inv;
using fp254::fp_is_zero;
using fp254::fp_mul;
using fp254::fp_sqr;
using fp254::from_mont;
using fp254::load_fp_be;
using fp254::store_fp_be;
using fp254::to_mont;

using fp254::fp_sub;

inline bool is_zero(const Fp& a) { return fp_is_zero(a); }

// ---------------------------------------------------------------------------
// G1 Jacobian (Montgomery-domain coordinates).
// ---------------------------------------------------------------------------

struct G1 {
  Fp x, y, z;
  bool inf;
};

void g1_dbl(const G1& p, G1* out) {
  if (p.inf || is_zero(p.y)) {
    out->inf = true;
    return;
  }
  // dbl-2009-l (a = 0): A=X^2 B=Y^2 C=B^2 D=2((X+B)^2-A-C) E=3A F=E^2
  Fp A, B, C, D, E, F, t;
  fp_sqr(p.x, &A);
  fp_sqr(p.y, &B);
  fp_sqr(B, &C);
  fp_add(p.x, B, &t);
  fp_sqr(t, &t);
  fp_sub(t, A, &t);
  fp_sub(t, C, &t);
  fp_dbl(t, &D);
  fp_dbl(A, &E);
  fp_add(E, A, &E);
  fp_sqr(E, &F);
  G1 r;
  r.inf = false;
  fp_sub(F, D, &r.x);
  fp_sub(r.x, D, &r.x);               // X3 = F - 2D
  Fp c8;
  fp_dbl(C, &c8);
  fp_dbl(c8, &c8);
  fp_dbl(c8, &c8);                    // 8C
  fp_sub(D, r.x, &t);
  fp_mul(E, t, &r.y);
  fp_sub(r.y, c8, &r.y);              // Y3 = E(D - X3) - 8C
  fp_mul(p.y, p.z, &t);
  fp_dbl(t, &r.z);                    // Z3 = 2YZ
  *out = r;
}

void g1_add(const G1& p, const G1& q, G1* out) {
  if (p.inf) {
    *out = q;
    return;
  }
  if (q.inf) {
    *out = p;
    return;
  }
  // add-2007-bl
  Fp z1z1, z2z2, u1, u2, s1, s2, h, i, j, rr, v, t;
  fp_sqr(p.z, &z1z1);
  fp_sqr(q.z, &z2z2);
  fp_mul(p.x, z2z2, &u1);
  fp_mul(q.x, z1z1, &u2);
  fp_mul(p.y, q.z, &t);
  fp_mul(t, z2z2, &s1);
  fp_mul(q.y, p.z, &t);
  fp_mul(t, z1z1, &s2);
  fp_sub(u2, u1, &h);
  fp_sub(s2, s1, &rr);
  if (is_zero(h)) {
    if (is_zero(rr)) {
      g1_dbl(p, out);
      return;
    }
    out->inf = true;
    return;
  }
  fp_dbl(h, &t);
  fp_sqr(t, &i);
  fp_mul(h, i, &j);
  fp_dbl(rr, &rr);
  fp_mul(u1, i, &v);
  G1 r;
  r.inf = false;
  fp_sqr(rr, &r.x);
  fp_sub(r.x, j, &r.x);
  fp_sub(r.x, v, &r.x);
  fp_sub(r.x, v, &r.x);               // X3 = r^2 - J - 2V
  fp_sub(v, r.x, &t);
  fp_mul(rr, t, &r.y);
  Fp s1j;
  fp_mul(s1, j, &s1j);
  fp_dbl(s1j, &s1j);
  fp_sub(r.y, s1j, &r.y);             // Y3 = r(V - X3) - 2 S1 J
  fp_add(p.z, q.z, &t);
  fp_sqr(t, &t);
  fp_sub(t, z1z1, &t);
  fp_sub(t, z2z2, &t);
  fp_mul(t, h, &r.z);                 // Z3 = ((Z1+Z2)^2 - Z1Z1 - Z2Z2) H
  *out = r;
}

// 4-bit windowed scalar multiplication, MSB first.
void g1_mul(const G1& p, const u8* scalar_be, G1* out) {
  G1 table[16];
  table[0].inf = true;
  table[1] = p;
  for (int k = 2; k < 16; ++k) g1_add(table[k - 1], p, &table[k]);
  G1 acc;
  acc.inf = true;
  bool any = false;
  for (int i = 0; i < 32; ++i) {
    for (int half = 0; half < 2; ++half) {
      int d = half ? (scalar_be[i] & 0xf) : (scalar_be[i] >> 4);
      if (any) {
        g1_dbl(acc, &acc);
        g1_dbl(acc, &acc);
        g1_dbl(acc, &acc);
        g1_dbl(acc, &acc);
      }
      if (d) {
        g1_add(acc, table[d], &acc);
        any = true;
      } else if (any) {
        // nothing
      }
    }
  }
  *out = acc;
}

void load_point(const u8* x_be, const u8* y_be, G1* out) {
  Fp x, y;
  load_fp_be(x_be, &x);
  load_fp_be(y_be, &y);
  out->inf = is_zero(x) && is_zero(y);
  to_mont(x, &out->x);
  to_mont(y, &out->y);
  memcpy(out->z.v, ONE_M, sizeof(ONE_M));
}

}  // namespace

extern "C" {

// out = sum_i scalar_i * (x_i, y_i).  Inputs/outputs 32-byte big-endian
// affine; (0, 0) encodes infinity.  Returns 1 when the sum is infinity.
int bn254_g1_msm(int n, const u8* xs, const u8* ys, const u8* scalars,
                 u8* out_x, u8* out_y) {
  G1 acc;
  acc.inf = true;
  for (int i = 0; i < n; ++i) {
    G1 p, t;
    load_point(xs + 32 * i, ys + 32 * i, &p);
    if (p.inf) continue;
    g1_mul(p, scalars + 32 * i, &t);
    g1_add(acc, t, &acc);
  }
  if (acc.inf) {
    memset(out_x, 0, 32);
    memset(out_y, 0, 32);
    return 1;
  }
  Fp zinv, zinv2, zinv3, ax, ay;
  fp_inv(acc.z, &zinv);
  fp_sqr(zinv, &zinv2);
  fp_mul(zinv2, zinv, &zinv3);
  fp_mul(acc.x, zinv2, &ax);
  fp_mul(acc.y, zinv3, &ay);
  from_mont(ax, &ax);
  from_mont(ay, &ay);
  store_fp_be(ax, out_x);
  store_fp_be(ay, out_y);
  return 0;
}

// out_i = scalar_i * (x_i, y_i), independent muls; shared Montgomery
// batch inversion for the affine conversions.  inf_flags[i] set when
// the result is infinity.
int bn254_g1_mul_many(int n, const u8* xs, const u8* ys, const u8* scalars,
                      u8* out_xs, u8* out_ys, u8* inf_flags) {
  G1* res = new G1[n];
  for (int i = 0; i < n; ++i) {
    G1 p;
    load_point(xs + 32 * i, ys + 32 * i, &p);
    if (p.inf) {
      res[i].inf = true;
      continue;
    }
    g1_mul(p, scalars + 32 * i, &res[i]);
  }
  // batch inversion of all finite Z's
  Fp* prefix = new Fp[n + 1];
  memcpy(prefix[0].v, ONE_M, sizeof(ONE_M));
  for (int i = 0; i < n; ++i) {
    if (res[i].inf) {
      prefix[i + 1] = prefix[i];
    } else {
      fp_mul(prefix[i], res[i].z, &prefix[i + 1]);
    }
  }
  Fp inv;
  fp_inv(prefix[n], &inv);
  for (int i = n - 1; i >= 0; --i) {
    if (res[i].inf) {
      inf_flags[i] = 1;
      memset(out_xs + 32 * i, 0, 32);
      memset(out_ys + 32 * i, 0, 32);
      continue;
    }
    inf_flags[i] = 0;
    Fp zinv, zinv2, zinv3, ax, ay;
    fp_mul(inv, prefix[i], &zinv);
    fp_mul(inv, res[i].z, &inv);
    fp_sqr(zinv, &zinv2);
    fp_mul(zinv2, zinv, &zinv3);
    fp_mul(res[i].x, zinv2, &ax);
    fp_mul(res[i].y, zinv3, &ay);
    from_mont(ax, &ax);
    from_mont(ay, &ay);
    store_fp_be(ax, out_xs + 32 * i);
    store_fp_be(ay, out_ys + 32 * i);
  }
  delete[] res;
  delete[] prefix;
  return 0;
}

}  // extern "C"
