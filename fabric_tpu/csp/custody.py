"""Process-isolated key custody: the HSM role, TPU-host-sane.

Reference: bccsp/pkcs11 (impl.go:189, pkcs11.go:321,354) — ECDSA keygen
and signing happen inside an HSM behind a PKCS#11 session pool, the
private keys never enter the peer process, and everything else (hash,
verify, non-EC ops) falls back to the sw provider.  A real PKCS#11
stack needs a vendor C library this image doesn't carry, so the custody
boundary here is an OS PROCESS instead of a hardware module — the same
security property the reference buys from the HSM seam (a compromised
peer process can ask for signatures but can never exfiltrate a private
key) with the same provider split:

  KeyCustodyServer  — owns the only copy of the private keys
                      (FileKeyStore under a 0700 dir), serves
                      keygen/sign/get over the framed RPC transport
                      (optionally mutual-TLS), gated by a shared token
                      (the PKCS#11 PIN analogue, checked in constant
                      time).
  CustodyCSP        — peer-side provider: key_gen/sign/get_key go to
                      the daemon; hash/verify/verify_batch delegate to
                      a local provider (sw by default, the TPU provider
                      for hardware-verify deployments) exactly like the
                      reference pkcs11 CSP delegates to sw
                      (bccsp/pkcs11/impl.go SoftVerify-style split).
  CustodyKeyHandle  — what the peer holds: SKI + PUBLIC key only.
                      There is deliberately no API that returns private
                      material across the boundary.

`fabric-custody` (cmd/custody.py) runs the daemon; `bccsp.default:
CUSTODY` in core.yaml selects the provider (csp/factory.py).
"""

from __future__ import annotations

import hmac
import os
import threading

from fabric_tpu.csp.api import (
    CSP,
    ECDSAP256PrivateKey,
    ECDSAP256PublicKey,
    Key,
    VerifyBatchItem,
)
from fabric_tpu.csp.sw import SWCSP


class CustodyError(Exception):
    pass


# Structured sentinel the daemon prefixes to its unknown-SKI answer.
# The peer's local-keystore fallback keys off THIS machine token, not
# the human prose after it — a rewording of the daemon's message (or a
# transport error that happens to mention keys) can no longer be
# confused with "the daemon does not hold this SKI".
ERR_UNKNOWN_SKI = "CUSTODY_ERR_UNKNOWN_SKI"


class CustodyKeyHandle(Key):
    """The peer-visible face of a custody-held private key: SKI plus
    the public half.  sign() must go through the owning CustodyCSP —
    the handle itself holds no secret material at all.

    CONTRACT DIVERGENCE, on purpose: `Key.raw()` documents "private
    keys: PKCS8 DER", which this handle cannot produce — the key is
    non-extractable, exactly like an HSM-resident key — so raw()
    RAISES rather than quietly serializing the public half under a
    private label.  It is likewise not storable in the local keystores
    (there is nothing local to store); use `public_key()` for the
    certifiable public material."""

    def __init__(self, ski: bytes, public: ECDSAP256PublicKey):
        self._ski = ski
        self._public = public

    def ski(self) -> bytes:
        return self._ski

    def raw(self) -> bytes:
        raise CustodyError(
            "custody-held private keys are not extractable; "
            "use public_key().raw() for the public half"
        )

    @property
    def is_private(self) -> bool:
        return True  # signs (via the daemon); never exportable

    def public_key(self) -> ECDSAP256PublicKey:
        return self._public


class KeyCustodyServer:
    """The daemon: sole owner of the private keys.  RPC surface:

      custody.KeyGen   token                      -> ski(32) || pub(65)
      custody.Sign     token || ski(32) || digest -> DER signature
      custody.GetKey   token || ski(32)           -> pub(65)

    Wrong token, unknown SKI, or malformed bodies answer an ERR frame;
    no method returns private key bytes (the keystore directory is the
    custody boundary, exactly like an HSM's token storage)."""

    def __init__(self, keystore_dir: str, token: bytes,
                 host: str = "127.0.0.1", port: int = 0, tls=None):
        from fabric_tpu.comm import RPCServer
        from fabric_tpu.csp.keystore import FileKeyStore

        if not token:
            raise ValueError("custody token must not be empty")
        self._token = token
        self._sw = SWCSP(keystore=FileKeyStore(keystore_dir))
        self._lock = threading.Lock()
        self.rpc = RPCServer(host, port, tls=tls)
        self.rpc.register("custody.KeyGen", self._key_gen)
        self.rpc.register("custody.Sign", self._sign)
        self.rpc.register("custody.GetKey", self._get_key)

    @property
    def addr(self):
        return self.rpc.addr

    def start(self) -> None:
        self.rpc.start()

    def stop(self) -> None:
        self.rpc.stop()

    def _auth(self, body: bytes) -> bytes:
        n = len(self._token)
        if len(body) < n or not hmac.compare_digest(body[:n], self._token):
            raise CustodyError("custody: bad token")
        return body[n:]

    def _key_gen(self, body: bytes, stream) -> bytes:
        self._auth(body)
        with self._lock:
            key = self._sw.key_gen()
        pub = key.public_key()
        return key.ski() + pub.raw()

    def _sign(self, body: bytes, stream) -> bytes:
        rest = self._auth(body)
        if len(rest) != 64:
            raise CustodyError("custody: want ski(32) || digest(32)")
        ski, digest = rest[:32], rest[32:]
        with self._lock:
            try:
                key = self._sw.get_key(ski)
            except KeyError:
                raise CustodyError(
                    f"{ERR_UNKNOWN_SKI}: daemon holds no key for "
                    f"SKI {ski.hex()}"
                ) from None
        if not isinstance(key, ECDSAP256PrivateKey):
            raise CustodyError("custody: no private key for ski")
        return self._sw.sign(key, digest)

    def _get_key(self, body: bytes, stream) -> bytes:
        rest = self._auth(body)
        if len(rest) != 32:
            raise CustodyError("custody: want ski(32)")
        with self._lock:
            try:
                key = self._sw.get_key(rest)
            except KeyError:
                raise CustodyError(
                    f"{ERR_UNKNOWN_SKI}: daemon holds no key for "
                    f"SKI {rest.hex()}"
                ) from None
        return key.public_key().raw() if key.is_private else key.raw()


class CustodyCSP(CSP):
    """Peer-side provider over a KeyCustodyServer.  The reference
    pkcs11 split: private-key operations remote, everything else on the
    local provider (`verify_csp`: sw by default; pass a TPUCSP for
    hardware-verify + custody-sign deployments)."""

    def __init__(self, endpoint: tuple[str, int], token: bytes,
                 verify_csp: CSP | None = None, tls=None,
                 timeout: float = 10.0):
        from fabric_tpu.comm import RPCClient

        self._token = token
        self._local = verify_csp or SWCSP()
        # one client for the provider's lifetime: RPCClient opens a
        # connection per call anyway, but constructing it per sign
        # would rebuild the TLS context (cert/CA parse) on the hot path
        self._client = RPCClient(*endpoint, timeout=timeout, tls=tls)
        # key cache: ski -> CustodyKeyHandle or locally-imported Key
        # (the session-pool analogue — one daemon round-trip per key,
        # not per use)
        self._handles: dict[bytes, Key] = {}
        self._lock = threading.Lock()

    def _call(self, method: str, body: bytes) -> bytes:
        return self._client.call(method, self._token + body)

    @staticmethod
    def _parse_pub(raw: bytes) -> ECDSAP256PublicKey:
        if len(raw) != 65 or raw[:1] != b"\x04":
            raise CustodyError("custody: malformed public point")
        return ECDSAP256PublicKey.from_point(
            int.from_bytes(raw[1:33], "big"),
            int.from_bytes(raw[33:65], "big"),
        )

    # -- key management: remote -------------------------------------------

    def key_gen(self) -> CustodyKeyHandle:
        out = self._call("custody.KeyGen", b"")
        if len(out) != 32 + 65:
            raise CustodyError("custody: malformed keygen reply")
        handle = CustodyKeyHandle(out[:32], self._parse_pub(out[32:]))
        with self._lock:
            self._handles[handle.ski()] = handle
        return handle

    def key_import(self, raw: bytes, private: bool = False) -> Key:
        if private:
            # importing private material would move a secret THROUGH
            # the peer process — the custody boundary forbids it, like
            # an HSM with non-extractable/non-importable keys
            raise CustodyError(
                "custody provider cannot import private keys"
            )
        return self._local.key_import(raw, private=False)

    def get_key(self, ski: bytes) -> Key:
        with self._lock:
            h = self._handles.get(ski)
        if h is not None:
            return h
        # custody FIRST: a custody-held SKI must come back as a
        # SIGNABLE handle even when its public half was also imported
        # locally (e.g. an MSP deriving the SKI from a certificate) —
        # the local keystore serves only SKIs the daemon doesn't hold.
        # Only the daemon's STRUCTURED unknown-SKI answer (the
        # ERR_UNKNOWN_SKI sentinel it prefixes) falls through;
        # transport failures and malformed replies PROPAGATE (a daemon
        # outage must not silently demote a signable key to a public
        # one, and no rewording of the daemon's prose can masquerade
        # as unknown-SKI).
        from fabric_tpu.comm.rpc import RPCError

        try:
            pub = self._parse_pub(self._call("custody.GetKey", ski))
            key: Key = CustodyKeyHandle(ski, pub)
        except RPCError as exc:
            if not str(exc).startswith(ERR_UNKNOWN_SKI):
                raise
            key = self._local.get_key(ski)  # KeyError if absent
        with self._lock:
            # positive AND local-fallback results cache: a locally
            # imported key must not pay a daemon round trip per lookup
            self._handles[ski] = key
        return key

    def sign(self, key: Key, digest: bytes) -> bytes:
        if isinstance(key, CustodyKeyHandle):
            return self._call("custody.Sign", key.ski() + digest)
        raise CustodyError(
            "custody provider signs only with custody-held keys"
        )

    # -- hash / verify: local (the pkcs11 'fall back to sw' split) ---------

    def hash(self, msg: bytes) -> bytes:
        return self._local.hash(msg)

    def hash_batch(self, msgs) -> list[bytes]:
        return self._local.hash_batch(msgs)

    def verify(self, key: Key, signature: bytes, digest: bytes) -> bool:
        if isinstance(key, CustodyKeyHandle):
            key = key.public_key()
        return self._local.verify(key, signature, digest)

    def verify_batch(self, items) -> list[bool]:
        return self._local.verify_batch(self._publicized(items))

    def verify_batch_async(self, items):
        return self._local.verify_batch_async(self._publicized(items))

    @staticmethod
    def _publicized(items):
        return [
            VerifyBatchItem(it.key.public_key(), it.digest, it.signature)
            if isinstance(it.key, CustodyKeyHandle)
            else it
            for it in items
        ]


def load_token(path: str) -> bytes:
    """Read the shared custody token (the PIN file analogue); trailing
    newlines are tolerated so `echo secret > file` provisioning works."""
    with open(path, "rb") as f:
        token = f.read().strip()
    if not token:
        raise CustodyError(f"custody token file {path!r} is empty")
    return token


__all__ = [
    "KeyCustodyServer",
    "CustodyCSP",
    "CustodyKeyHandle",
    "CustodyError",
    "ERR_UNKNOWN_SKI",
    "load_token",
]
