"""CLEAN TWIN of fix_thread_dirty: the same worker through the
threadwatch seam (registered, drainable)."""

from fabric_tpu.devtools.lockwatch import spawn_thread


def start_worker(job):
    t = spawn_thread(target=job, name="fixture-worker", kind="worker")
    t.start()
    return t
