"""CLEAN TWIN of fix_lock_dirty: the same helper, called after the
commit lock is released."""

from fabric_tpu.ledger.fix_lock_helper import persist


class Ledger:
    def __init__(self, lock, fd):
        self.commit_lock = lock
        self._fd = fd

    def commit(self):
        with self.commit_lock:
            pass
        persist(self._fd)
