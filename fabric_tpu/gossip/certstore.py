"""Certstore: identity dissemination over the pull protocol.

Reference gossip/gossip/certstore.go:30 — a pull engine
(hello/digest/request/response, PULL_IDENTITY_MSG) whose items are
SELF-SIGNED PeerIdentity messages: each peer signs its own identity
message once; receivers forward the original signed envelope intact, so
any peer can verify provenance without having met the owner.  Verified
identities land in the IdentityMapper (expiration-aware) and in the
comm layer's identity table so subsequent message signatures verify.
"""

from __future__ import annotations

import random
import threading

from fabric_tpu.protos.gossip import message_pb2 as gpb


class CertStore:
    def __init__(self, comm, mapper, membership, rng=None):
        self._comm = comm
        self._mapper = mapper
        self._membership = membership
        self._rng = rng or random.Random()
        self._nonce = 0
        self._pending: dict[int, str] = {}
        self._lock = threading.Lock()
        # pki-hex -> serialized SignedGossipMessage (owner-signed)
        self._signed: dict[str, bytes] = {}
        self._add_own_identity()
        if hasattr(mapper, "add_purge_listener"):
            # stop advertising/serving identities the mapper expired —
            # otherwise every pull round re-offers certs receivers can
            # only reject (reference certstore deletes purged ids from
            # the pull mediator)
            mapper.add_purge_listener(self._evict)
        comm.subscribe(self._handle)

    def _evict(self, pki: bytes) -> None:
        if pki == self._comm.pki_id:
            return  # never stop advertising our own identity
        with self._lock:
            self._signed.pop(pki.hex(), None)

    def _add_own_identity(self) -> None:
        m = gpb.GossipMessage(tag=gpb.GossipMessage.EMPTY)
        m.peer_identity.pki_id = self._comm.pki_id
        m.peer_identity.cert = self._comm.identity
        signed = self._comm.wrap(m)  # signed by our own MCS key
        self._signed[self._comm.pki_id.hex()] = signed.SerializeToString()

    # -- pull round --------------------------------------------------------

    def tick(self) -> None:
        peers = list(self._membership())
        if not peers:
            return
        target = self._rng.choice(peers)
        self._nonce += 1
        hello = gpb.GossipMessage()
        hello.hello.nonce = self._nonce
        hello.hello.msg_type = gpb.PULL_IDENTITY_MSG
        with self._lock:
            self._pending[self._nonce] = target
            while len(self._pending) > 32:
                del self._pending[min(self._pending)]
        self._comm.send(target, hello)

    def known_pkis(self) -> list[str]:
        with self._lock:
            return sorted(self._signed)

    # -- inbound -----------------------------------------------------------

    def _handle(self, rm) -> None:
        msg = rm.msg
        kind = msg.WhichOneof("content")
        if kind == "hello" and msg.hello.msg_type == gpb.PULL_IDENTITY_MSG:
            resp = gpb.GossipMessage()
            resp.data_dig.nonce = msg.hello.nonce
            resp.data_dig.msg_type = gpb.PULL_IDENTITY_MSG
            for h in self.known_pkis():
                resp.data_dig.digests.append(h.encode())
            self._respond(rm, resp)
        elif kind == "data_dig" and msg.data_dig.msg_type == gpb.PULL_IDENTITY_MSG:
            with self._lock:
                target = self._pending.pop(msg.data_dig.nonce, None)
                have = set(self._signed)
            if target is None:
                return
            want = [d for d in msg.data_dig.digests if d.decode() not in have]
            if not want:
                return
            req = gpb.GossipMessage()
            req.data_req.nonce = msg.data_dig.nonce
            req.data_req.msg_type = gpb.PULL_IDENTITY_MSG
            req.data_req.digests.extend(want)
            self._comm.send(target, req)
        elif kind == "data_req" and msg.data_req.msg_type == gpb.PULL_IDENTITY_MSG:
            resp = gpb.GossipMessage()
            resp.data_update.nonce = msg.data_req.nonce
            resp.data_update.msg_type = gpb.PULL_IDENTITY_MSG
            with self._lock:
                for d in msg.data_req.digests:
                    raw = self._signed.get(d.decode())
                    if raw is not None:
                        resp.data_update.data.append(
                            gpb.SignedGossipMessage.FromString(raw)
                        )
            self._respond(rm, resp)
        elif kind == "data_update" and msg.data_update.msg_type == gpb.PULL_IDENTITY_MSG:
            for signed in msg.data_update.data:
                self._learn(signed)

    def _learn(self, signed: gpb.SignedGossipMessage) -> None:
        """Admit a pulled identity: the inner PeerIdentity's pki must
        derive from its cert, and the envelope must verify under THAT
        identity (self-signed — certstore.go validateIdentityMsg)."""
        try:
            inner = gpb.GossipMessage.FromString(signed.payload)
            if inner.WhichOneof("content") != "peer_identity":
                return
            ident = bytes(inner.peer_identity.cert)
            pki = bytes(inner.peer_identity.pki_id)
            if self._comm.mcs.get_pki_id(ident) != pki:
                return  # forged pki binding
            if not self._comm.mcs.verify(
                ident, bytes(signed.signature), bytes(signed.payload)
            ):
                return  # not signed by the identity's owner
            self._mapper.put(ident)  # raises when expired
        except Exception:
            return
        with self._lock:
            self._signed.setdefault(pki.hex(), signed.SerializeToString())
        self._comm.learn_identity(ident)

    def _respond(self, rm, msg: gpb.GossipMessage) -> None:
        ep = self._endpoint_for(rm.sender_pki)
        if ep:
            self._comm.send(ep, msg)
        else:
            try:
                rm.respond(msg)
            except Exception:
                pass

    endpoint_lookup = None

    def _endpoint_for(self, pki_id: bytes):
        if self.endpoint_lookup is not None:
            return self.endpoint_lookup(pki_id)
        return None


__all__ = ["CertStore"]
