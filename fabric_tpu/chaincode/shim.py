"""Chaincode shim: the library a chaincode process links against.

Capability parity with the reference's shim side of the ChaincodeSupport
stream (the fabric-chaincode-go shim; peer-side counterpart in
core/chaincode/handler.go): REGISTER handshake, then for each inbound
TRANSACTION/INIT the shim builds a ChaincodeStub bound to the stream and
invokes the user chaincode; GetState/PutState/... block on RESPONSE
messages from the peer, matched by txid.

The stream abstraction is a pair of callables (send, recv) over
length-prefixed frames, so the same shim runs over an in-process duplex
queue (system chaincodes, tests) or a TCP socket from a separate OS
process (`shim_main`, the external-chaincode path — our environment has
no docker, mirroring the reference's externalbuilder mode).
"""

from __future__ import annotations

import queue
import socket
import struct
import threading

from fabric_tpu.devtools.lockwatch import spawn_thread
from fabric_tpu.protos.peer import chaincode_shim_pb2 as shim_pb
from fabric_tpu.protos.peer import chaincode_pb2, proposal_pb2

_LEN = struct.Struct(">I")
M = shim_pb.ChaincodeMessage


class ChaincodeError(Exception):
    pass


class Chaincode:
    """User chaincode interface: subclass and implement init/invoke."""

    def init(self, stub: "ChaincodeStub") -> proposal_pb2.Response:
        return success()

    def invoke(self, stub: "ChaincodeStub") -> proposal_pb2.Response:
        raise NotImplementedError


def success(payload: bytes = b"", message: str = "") -> proposal_pb2.Response:
    return proposal_pb2.Response(status=200, message=message, payload=payload)


def error(message: str, status: int = 500) -> proposal_pb2.Response:
    return proposal_pb2.Response(status=status, message=message)


class ChaincodeStub:
    def __init__(self, handler: "ShimHandler", msg: M):
        self._handler = handler
        self.txid = msg.txid
        self.channel_id = msg.channel_id
        inp = chaincode_pb2.ChaincodeInput.FromString(msg.payload)
        self.args = list(inp.args)
        self._proposal_bytes = bytes(msg.proposal)
        self._event: bytes = b""

    # -- args --------------------------------------------------------------

    def get_args(self) -> list[bytes]:
        return self.args

    def get_function_and_parameters(self) -> tuple[str, list[bytes]]:
        if not self.args:
            return "", []
        return self.args[0].decode(), self.args[1:]

    # -- identity ----------------------------------------------------------

    def get_creator(self) -> bytes:
        """Serialized identity of the proposal submitter (GetCreator)."""
        if not self._proposal_bytes:
            return b""
        from fabric_tpu.protos.common import common_pb2

        sp = proposal_pb2.SignedProposal.FromString(self._proposal_bytes)
        prop = proposal_pb2.Proposal.FromString(sp.proposal_bytes)
        hdr = common_pb2.Header.FromString(prop.header)
        shdr = common_pb2.SignatureHeader.FromString(hdr.signature_header)
        return bytes(shdr.creator)

    def creator_mspid(self) -> str:
        creator = self.get_creator()
        if not creator:
            return ""
        from fabric_tpu.protos.msp import identities_pb2

        return identities_pb2.SerializedIdentity.FromString(creator).mspid

    # -- state -------------------------------------------------------------

    def _call(self, mtype, payload: bytes) -> M:
        resp = self._handler.call_peer(
            M(type=mtype, payload=payload, txid=self.txid, channel_id=self.channel_id)
        )
        if resp.type == M.ERROR:
            raise ChaincodeError(resp.payload.decode("utf-8", "replace"))
        return resp

    def get_state(self, key: str, collection: str = "") -> bytes:
        g = shim_pb.GetState(key=key, collection=collection)
        return self._call(M.GET_STATE, g.SerializeToString()).payload

    def put_state(self, key: str, value: bytes, collection: str = "") -> None:
        p = shim_pb.PutState(key=key, value=value, collection=collection)
        self._call(M.PUT_STATE, p.SerializeToString())

    def del_state(self, key: str, collection: str = "") -> None:
        d = shim_pb.DelState(key=key, collection=collection)
        self._call(M.DEL_STATE, d.SerializeToString())

    def _paged_results(self, first_resp):
        """Drain a QueryResponse (+ QUERY_STATE_NEXT pages) into
        (key, value) pairs — shared by range and rich queries."""
        qr = shim_pb.QueryResponse.FromString(first_resp.payload)
        while True:
            for rb in qr.results:
                kv = shim_pb.KV.FromString(rb.result_bytes)
                yield kv.key, kv.value
            if not qr.has_more:
                return
            nxt = shim_pb.QueryStateNext(id=qr.id)
            resp = self._call(M.QUERY_STATE_NEXT, nxt.SerializeToString())
            qr = shim_pb.QueryResponse.FromString(resp.payload)

    def get_state_by_range(self, start: str, end: str, collection: str = ""):
        """Yields (key, value) pairs."""
        g = shim_pb.GetStateByRange(
            start_key=start, end_key=end, collection=collection
        )
        resp = self._call(M.GET_STATE_BY_RANGE, g.SerializeToString())
        yield from self._paged_results(resp)

    def get_query_result(self, query: str, collection: str = ""):
        """Rich JSON-selector query (reference shim GetQueryResult,
        CouchDB state backend).  Yields (key, value) pairs."""
        g = shim_pb.GetQueryResult(query=query, collection=collection)
        resp = self._call(M.GET_QUERY_RESULT, g.SerializeToString())
        yield from self._paged_results(resp)

    def get_private_data_hash(self, collection: str, key: str) -> bytes:
        g = shim_pb.GetState(key=key, collection=collection)
        return self._call(M.GET_PRIVATE_DATA_HASH, g.SerializeToString()).payload

    # -- state metadata / key-level endorsement ----------------------------

    def get_state_metadata(
        self, key: str, collection: str = ""
    ) -> dict[str, bytes]:
        g = shim_pb.GetStateMetadata(key=key, collection=collection)
        resp = self._call(M.GET_STATE_METADATA, g.SerializeToString())
        res = shim_pb.StateMetadataResult.FromString(resp.payload)
        return {e.metakey: bytes(e.value) for e in res.entries}

    def put_state_metadata(
        self, key: str, metakey: str, value: bytes, collection: str = ""
    ) -> None:
        p = shim_pb.PutStateMetadata(key=key, collection=collection)
        p.metadata.metakey = metakey
        p.metadata.value = value
        self._call(M.PUT_STATE_METADATA, p.SerializeToString())

    def set_state_validation_parameter(
        self, key: str, policy_bytes: bytes, collection: str = ""
    ) -> None:
        """Attach a key-level endorsement policy (reference shim
        SetStateValidationParameter; build policies with
        fabric_tpu.chaincode.statebased)."""
        self.put_state_metadata(
            key, "VALIDATION_PARAMETER", policy_bytes, collection
        )

    def get_state_validation_parameter(
        self, key: str, collection: str = ""
    ) -> bytes:
        return self.get_state_metadata(key, collection).get(
            "VALIDATION_PARAMETER", b""
        )

    def invoke_chaincode(self, name: str, args: list[bytes], channel: str = ""):
        spec = chaincode_pb2.ChaincodeSpec()
        spec.chaincode_id.name = name if not channel else f"{name}/{channel}"
        spec.input.args.extend(args)
        resp = self._call(M.INVOKE_CHAINCODE, spec.SerializeToString())
        return proposal_pb2.Response.FromString(resp.payload)

    def set_event(self, name: str, payload: bytes) -> None:
        from fabric_tpu.protos.peer import chaincode_event_pb2

        ev = chaincode_event_pb2.ChaincodeEvent(
            chaincode_id="", tx_id=self.txid, event_name=name, payload=payload
        )
        self._event = ev.SerializeToString()


class ShimHandler:
    """Drives one chaincode over one stream."""

    def __init__(self, cc: Chaincode, name: str, send, recv):
        self._cc = cc
        self.name = name
        self._send_raw = send
        self._recv = recv
        # Response routing keyed by (channel_id, txid): the peer allows the
        # same txid live on different channels concurrently.
        self._responses: dict[tuple[str, str], queue.Queue] = {}
        self._lock = threading.Lock()

    def _send(self, msg: M) -> None:
        self._send_raw(msg.SerializeToString())

    def call_peer(self, msg: M) -> M:
        q: queue.Queue = queue.Queue(maxsize=1)
        key = (msg.channel_id, msg.txid)
        with self._lock:
            if key in self._responses:
                raise ChaincodeError(
                    f"concurrent peer call for tx {key} on one stub"
                )
            self._responses[key] = q
        try:
            self._send(msg)
            return q.get(timeout=30)
        finally:
            with self._lock:
                self._responses.pop(key, None)

    def run(self) -> None:
        reg = chaincode_pb2.ChaincodeID(name=self.name)
        self._send(M(type=M.REGISTER, payload=reg.SerializeToString()))
        while True:
            raw = self._recv()
            if raw is None:
                return
            msg = M.FromString(raw)
            if msg.type in (M.REGISTERED, M.READY, M.KEEPALIVE):
                continue
            if msg.type in (M.RESPONSE, M.ERROR):
                with self._lock:
                    q = self._responses.get((msg.channel_id, msg.txid))
                if q is not None:
                    q.put(msg)
                continue
            if msg.type in (M.TRANSACTION, M.INIT):
                spawn_thread(
                    target=self._execute, args=(msg,),
                    name=f"cc-exec-{msg.txid[:8]}", kind="worker",
                ).start()

    def _execute(self, msg: M) -> None:
        try:
            stub = ChaincodeStub(self, msg)
            if msg.type == M.INIT:
                resp = self._cc.init(stub)
            else:
                resp = self._cc.invoke(stub)
            self._send(
                M(
                    type=M.COMPLETED,
                    payload=resp.SerializeToString(),
                    txid=msg.txid,
                    channel_id=msg.channel_id,
                    chaincode_event=stub._event,
                )
            )
        except Exception as exc:  # chaincode panic -> ERROR (handler.go)
            self._send(
                M(
                    type=M.ERROR,
                    payload=str(exc).encode(),
                    txid=msg.txid,
                    channel_id=msg.channel_id,
                )
            )


def shim_main(
    cc: Chaincode, name: str, peer_address: str,
    auth_token: str | None = None,
) -> None:
    """External chaincode entry: connect to the peer's chaincode listener
    (CORE_PEER_ADDRESS equivalent) and serve forever.  `auth_token` is
    the launch credential from chaincode.json; the listener's handshake
    requires it before any protocol message (the reference presents its
    launch-issued TLS client cert instead)."""
    host, port = peer_address.rsplit(":", 1)
    sock = socket.create_connection((host, int(port)))
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    lock = threading.Lock()

    def send(data: bytes) -> None:
        with lock:
            sock.sendall(_LEN.pack(len(data)) + data)

    if auth_token is not None:
        send(b"\x00".join(
            [b"CCAUTH1", name.encode(), auth_token.encode()]
        ))

    buf = bytearray()

    def recv() -> bytes | None:
        while len(buf) < _LEN.size:
            chunk = sock.recv(65536)
            if not chunk:
                return None
            buf.extend(chunk)
        (ln,) = _LEN.unpack_from(bytes(buf[:4]))
        while len(buf) < _LEN.size + ln:
            chunk = sock.recv(65536)
            if not chunk:
                return None
            buf.extend(chunk)
        frame = bytes(buf[4 : 4 + ln])
        del buf[: 4 + ln]
        return frame

    ShimHandler(cc, name, send, recv).run()


__all__ = [
    "Chaincode",
    "ChaincodeStub",
    "ChaincodeError",
    "ShimHandler",
    "shim_main",
    "success",
    "error",
]
