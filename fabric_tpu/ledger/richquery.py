"""Rich (JSON selector) state queries — the CouchDB-backend capability
(reference core/ledger/kvledger/txmgmt/statedb/statecouchdb with its
Mango selector queries, surfaced to chaincode as GetQueryResult).

Supported selector subset: implicit equality, $eq $ne $gt $gte $lt
$lte $in $nin $exists, dotted field paths, $and / $or combinators, and
an optional "limit".

Execution is index-assisted when the statedb defines an index on a
field the selector constrains conjunctively (statedb.VersionedDB
define_index; reference statecouchdb.go:53 index-backed queries): the
planner picks one indexed condition ($eq, then $in, then a range),
range-scans the order-preserving index for candidate keys, and rechecks
every candidate document with the full selector — so an imprecise index
can only over-select, never change results.  Results are key-ordered
and limit-truncated identically to the scan path, keeping endorsement
read/write sets deterministic whether or not an index exists.  Without
a usable index, selectors run as the full-namespace scan (semantically
the reference's behavior on an unindexed CouchDB field).

As in the reference, rich-query results are NOT protected by MVCC
phantom detection (statecouchdb documents this caveat); only range
queries get hash-based phantom checks.
"""

from __future__ import annotations

import json
from typing import Iterable


def _field(doc, path: str):
    cur = doc
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None, False
        cur = cur[part]
    return cur, True


def _cmp_ok(a, b, op: str) -> bool:
    try:
        if op == "$gt":
            return a > b
        if op == "$gte":
            return a >= b
        if op == "$lt":
            return a < b
        if op == "$lte":
            return a <= b
    except TypeError:
        return False
    return False


def _match_cond(value, present: bool, cond) -> bool:
    if not isinstance(cond, dict):
        return present and value == cond
    for op, operand in cond.items():
        if op == "$eq":
            if not (present and value == operand):
                return False
        elif op == "$ne":
            if present and value == operand:
                return False
        elif op in ("$gt", "$gte", "$lt", "$lte"):
            if not (present and _cmp_ok(value, operand, op)):
                return False
        elif op == "$in":
            if not (present and value in operand):
                return False
        elif op == "$nin":
            if present and value in operand:
                return False
        elif op == "$exists":
            if bool(operand) != present:
                return False
        else:
            raise ValueError(f"unsupported operator {op!r}")
    return True


def match_selector(doc, selector: dict) -> bool:
    for key, cond in selector.items():
        if key == "$and":
            if not all(match_selector(doc, s) for s in cond):
                return False
        elif key == "$or":
            if not any(match_selector(doc, s) for s in cond):
                return False
        else:
            value, present = _field(doc, key)
            if not _match_cond(value, present, cond):
                return False
    return True


def _parse_query(query: str) -> tuple[dict, int | None]:
    q = json.loads(query)
    selector = q.get("selector", {}) if isinstance(q, dict) else {}
    limit = q.get("limit") if isinstance(q, dict) else None
    if limit is not None:
        if not isinstance(limit, int) or isinstance(limit, bool) or limit < 0:
            raise ValueError(f"invalid limit {limit!r}")
    return selector, limit


def _conjunctive_conds(selector: dict) -> list[tuple[str, object]]:
    """(field, condition) pairs that must ALL hold — top-level fields
    plus $and arms; $or arms contribute nothing (any single-field
    prefilter would under-select a disjunction)."""
    out: list[tuple[str, object]] = []
    for key, cond in selector.items():
        if key == "$and":
            for sub in cond:
                if isinstance(sub, dict):
                    out.extend(_conjunctive_conds(sub))
        elif key != "$or":
            out.append((key, cond))
    return out


def plan_index(selector: dict, indexed: set) -> tuple | None:
    """Pick the best indexed prefilter: ("eq", field, value) |
    ("in", field, values) | ("range", field, lo|None, hi|None) | None.
    Range bounds are widened to inclusive (the recheck restores
    exactness)."""
    conds = [
        (f, c) for f, c in _conjunctive_conds(selector) if f in indexed
    ]
    for field, cond in conds:
        if not isinstance(cond, dict):
            return ("eq", field, cond)
        if "$eq" in cond:
            return ("eq", field, cond["$eq"])
    for field, cond in conds:
        if isinstance(cond, dict) and isinstance(cond.get("$in"), list):
            return ("in", field, cond["$in"])
    for field, cond in conds:
        if not isinstance(cond, dict):
            continue
        lo = cond.get("$gte", cond.get("$gt"))
        hi = cond.get("$lte", cond.get("$lt"))
        if lo is not None or hi is not None:
            return ("range", field, lo, hi)
    return None


def _eq_encodings(v) -> list[bytes] | None:
    """All index encodings an equality operand must probe, or None when
    the index cannot serve it (caller falls back to the full scan).

    Two invariants keep "index can only over-select" true: (a) docs with
    non-scalar values (arrays/objects) are never indexed, so an
    unencodable operand means the index would silently drop matches;
    (b) match_selector compares with Python ==, under which True == 1
    and False == 0, while bool and number encode under different type
    tags — so bool operands also probe the numeric entry and 0/1
    numeric operands also probe the bool entry."""
    from fabric_tpu.ledger.statedb import encode_scalar

    enc = encode_scalar(v)
    if enc is None:
        return None
    probes = [enc]
    if isinstance(v, bool):
        probes.append(encode_scalar(int(v)))
    elif isinstance(v, (int, float)) and v in (0, 1):
        probes.append(encode_scalar(bool(v)))
    return probes


def execute_query_indexed(db, ns: str, query: str):
    """Index-assisted execution against a statedb.VersionedDB; returns
    [(key, value, version)] in key order, or None when no defined index
    matches the selector (caller falls back to the scan path)."""
    from fabric_tpu.ledger.statedb import encode_scalar

    selector, limit = _parse_query(query)
    p = plan_index(selector, db.indexes_for(ns))
    if p is None:
        return None
    if p[0] in ("eq", "in"):
        operands = [p[2]] if p[0] == "eq" else list(p[2])
        keys = []
        for v in operands:
            probes = _eq_encodings(v)
            if probes is None:
                return None  # index can't serve this operand: full scan
            for enc in probes:
                keys.extend(db.index_scan(ns, p[1], enc, enc))
    else:
        _, field, lo, hi = p
        if isinstance(lo, bool) or isinstance(hi, bool):
            return None  # bool bounds cross-compare with numbers: scan
        lo_enc = encode_scalar(lo) if lo is not None else None
        hi_enc = encode_scalar(hi) if hi is not None else None
        if (lo is not None and lo_enc is None) or (
            hi is not None and hi_enc is None
        ):
            return None  # unencodable bound: fall back to the scan
        keys = list(db.index_scan(ns, field, lo_enc, hi_enc))
        lo_num = lo if isinstance(lo, (int, float)) else None
        hi_num = hi if isinstance(hi, (int, float)) else None
        if (lo_num is not None or hi_num is not None) and (
            lo_num is None or lo_num <= 1
        ) and (hi_num is None or hi_num >= 0):
            # bool doc values order-compare with numeric bounds under
            # Python (True >= 1), but live under a different type tag —
            # sweep the (two-value) bool region when the bounds overlap
            # [False, True] ≡ [0, 1]; the recheck is exact
            keys.extend(
                db.index_scan(ns, field, encode_scalar(False), encode_scalar(True))
            )
    out = []
    for key in sorted(set(keys)):
        vv = db.get_state(ns, key)
        if vv is None:
            continue
        try:
            doc = json.loads(vv.value.decode("utf-8"))
        except Exception:
            continue
        if isinstance(doc, dict) and match_selector(doc, selector):
            out.append((key, vv.value, vv.version))
            if limit is not None and len(out) >= limit:
                break
    return out


def execute_query(
    pairs: Iterable[tuple[str, bytes]], query: str
) -> list[tuple[str, bytes]]:
    """Filter (key, value) pairs by a JSON selector query string."""
    selector, limit = _parse_query(query)
    out = []
    for key, value in pairs:
        if limit is not None and len(out) >= limit:
            break
        try:
            doc = json.loads(value.decode("utf-8"))
        except Exception:
            continue  # non-JSON values never match (couchdb attachments)
        if not isinstance(doc, dict):
            continue
        if match_selector(doc, selector):
            out.append((key, value))
    return out


__all__ = [
    "match_selector",
    "execute_query",
    "execute_query_indexed",
    "plan_index",
]
