"""Chaos commit matrix (ISSUE 6 tentpole): an injected CRASH at every
commit-pipeline stage (mvcc / block_append / pvt / state / history /
fsync / kv_txn — plus the kvstore-txn boundary and a torn mid-record
file append), followed by a reopen, must recover to a consistent height
with no torn state.  PR 2's tests exercised exactly two hand-picked
torn points; faultline generalizes them into an any-stage matrix.

A faultline "crash" raises FaultCrash (a BaseException): the ledger's
rollback seams deliberately SKIP their unwind for it, so what is on
disk at the reopen is exactly what a killed process would have left —
the recovery scan, not the graceful rollback, is what these tests
exercise."""

import os
import struct

import pytest

from fabric_tpu.devtools import faultline
from fabric_tpu.ledger import LedgerProvider
from fabric_tpu.ledger.statedb import Height

from test_ledger import _endorsed_block
from test_group_commit import _write_block


def _crash_plan(point: str, ctx: dict | None = None, **extra) -> dict:
    fault = {"point": point, "action": "crash", **extra}
    if ctx:
        fault["ctx"] = ctx
    return {"seed": 1, "faults": [fault]}


def _assert_consistent(led, height: int, keys: dict) -> None:
    """The recovery invariants: advertised height matches the block
    store AND the state savepoint, every block below it is readable
    with its index entries, the block-file-first invariant holds (no
    index entry can point past file content — a readable block at
    every indexed height proves it), and expected state matches."""
    assert led.height == height
    assert led.durable_height == height
    sp = led.state_db.savepoint()
    if height > 0:
        assert sp is not None and sp.block_num == height - 1
        for num in range(height):
            blk = led.get_block_by_number(num)
            assert blk is not None and blk.header.number == num
        # the hash chain is intact through the recovered tail
        assert led.block_store.last_block_hash
    for (ns, key), want in keys.items():
        assert led.get_state(ns, key) == want, (ns, key)


STAGE_POINTS = [
    ("commit.stage", {"stage": "mvcc"}),
    ("commit.stage", {"stage": "block_append"}),
    ("commit.stage", {"stage": "pvt"}),
    ("commit.stage", {"stage": "state"}),
    ("commit.stage", {"stage": "history"}),
    ("commit.stage", {"stage": "fsync"}),
    ("commit.stage", {"stage": "kv_txn"}),
    ("kvstore.txn", None),
    ("blkstorage.fsync", None),
]


@pytest.mark.parametrize(
    "point,ctx", STAGE_POINTS,
    ids=[(ctx or {}).get("stage", p) for p, ctx in STAGE_POINTS],
)
def test_crash_at_every_commit_stage_recovers(tmp_path, point, ctx):
    """One ungrouped commit traverses every stage; a crash at stage X
    leaves block 2 either fully absent (crash before its record could
    reach the file) or replayable from the file scan — never a torn
    ledger.  The chain then continues cleanly from the recovered
    height."""
    provider = LedgerProvider(str(tmp_path))
    ledger = provider.open("chaos")
    ledger.commit(_write_block(ledger, 0, [("cc", "a", b"0")]))
    ledger.commit(_write_block(ledger, 1, [("cc", "b", b"1")]))

    blk2 = _write_block(ledger, 2, [("cc", "c", b"2")])
    with faultline.use_plan(_crash_plan(point, ctx)):
        with pytest.raises(faultline.FaultCrash):
            ledger.commit(blk2)
        assert faultline.trips(), "the plan never fired"
    provider.close()  # the "dead" process's fds

    # before the block_append stage point, block 2's record never
    # reached the file; from block_append on, the tail scan replays it
    survived = not (point == "commit.stage" and ctx["stage"] == "mvcc")
    expect_h = 3 if survived else 2
    keys = {("cc", "a"): b"0", ("cc", "b"): b"1",
            ("cc", "c"): b"2" if survived else None}

    provider2 = LedgerProvider(str(tmp_path))
    led2 = provider2.open("chaos")
    _assert_consistent(led2, expect_h, keys)
    # and the chain continues from wherever recovery landed
    led2.commit(_write_block(led2, expect_h, [("cc", "next", b"n")]))
    assert led2.get_state("cc", "next") == b"n"
    assert led2.state_db.savepoint() == Height(expect_h, 1)
    provider2.close()


@pytest.mark.parametrize(
    "stage", ["block_append", "fsync", "kv_txn"],
)
def test_group_crash_at_flush_stage_recovers_all_buffered(tmp_path, stage):
    """A multi-block group crashed at a flush-path stage: every
    appended record (durable or not — same filesystem view) replays on
    reopen; a crash after kv_txn changes nothing observable."""
    provider = LedgerProvider(str(tmp_path))
    ledger = provider.open("chaos")
    ledger.commit(_write_block(ledger, 0, [("cc", "a", b"0")]))
    group = ledger.begin_commit_group()
    blk1 = _write_block(ledger, 1, [("cc", "b", b"1")])
    blk2 = _write_block(ledger, 2, [("cc", "c", b"2")])
    plan = _crash_plan(
        "commit.stage", {"stage": stage} if stage != "block_append" else
        {"stage": stage, "block": 2},
    )
    with faultline.use_plan(plan):
        with pytest.raises(faultline.FaultCrash):
            ledger.commit(blk1, group=group)
            ledger.commit(blk2, group=group)
            ledger.commit_group_flush(group)
        assert faultline.trips()
    provider.close()

    provider2 = LedgerProvider(str(tmp_path))
    led2 = provider2.open("chaos")
    _assert_consistent(led2, 3, {
        ("cc", "a"): b"0", ("cc", "b"): b"1", ("cc", "c"): b"2",
    })
    assert led2.get_history_for_key("cc", "c") == [(2, 0)]
    provider2.close()


def test_torn_file_append_truncated_on_reopen(tmp_path):
    """torn-write-then-crash at the block-file append: a strict prefix
    of block 2's record lands on disk; the recovery scan must truncate
    it away and the same block must re-commit cleanly."""
    provider = LedgerProvider(str(tmp_path))
    ledger = provider.open("chaos")
    ledger.commit(_write_block(ledger, 0, [("cc", "a", b"0")]))
    ledger.commit(_write_block(ledger, 1, [("cc", "b", b"1")]))
    blk2 = _write_block(ledger, 2, [("cc", "c", b"2")])
    plan = {"seed": 3, "faults": [{
        "point": "blkstorage.file_append", "action": "torn",
        "cut": 0.5, "ctx": {"block": 2},
    }]}
    with faultline.use_plan(plan):
        with pytest.raises(faultline.FaultCrash, match="torn write"):
            ledger.commit(blk2)
        # label filter: under FABRIC_TPU_SOAK the pre-plan commits leave
        # background delay trips in the ledger
        [trip] = [t for t in faultline.trips() if t["plan"] != "soak"]
        assert trip["point"] == "blkstorage.file_append"
    provider.close()

    # the torn prefix is really on disk (strictly shorter than a full
    # record: length header promises more bytes than exist)
    path = os.path.join(str(tmp_path), "chaos", "chains",
                        "blocks_000000.dat")
    with open(path, "rb") as f:
        data = f.read()
    off = 0
    for _ in range(2):  # complete records of blocks 0 and 1
        (n,) = struct.unpack(">I", data[off:off + 4])
        off += 4 + n
    assert off < len(data), "no torn tail was written"

    provider2 = LedgerProvider(str(tmp_path))
    led2 = provider2.open("chaos")
    _assert_consistent(led2, 2, {
        ("cc", "a"): b"0", ("cc", "b"): b"1", ("cc", "c"): None,
    })
    led2.commit(_write_block(led2, 2, [("cc", "c", b"2")]))
    assert led2.get_state("cc", "c") == b"2"
    provider2.close()


def test_crash_before_any_write_loses_nothing(tmp_path):
    """A raise-style fault (graceful failure, NOT a crash) at the
    kvstore txn rolls the group back and the caller retries — the
    PR 2 rollback path still works with injected failures."""
    provider = LedgerProvider(str(tmp_path))
    ledger = provider.open("chaos")
    ledger.commit(_write_block(ledger, 0, [("cc", "a", b"0")]))
    blk1 = _write_block(ledger, 1, [("cc", "b", b"1")])
    with faultline.use_plan({"faults": [{
        "point": "kvstore.txn", "action": "raise", "error": "OSError",
        "message": "injected disk full",
    }]}):
        with pytest.raises(OSError, match="injected disk full"):
            ledger.commit(blk1)
        assert faultline.trips()
    # graceful rollback ran: live state matches durable storage
    assert ledger.height == ledger.durable_height == 1
    ledger.commit(_write_block(ledger, 1, [("cc", "b", b"1")]))
    assert ledger.get_state("cc", "b") == b"1"
    provider.close()


def test_pinned_parallel_prepare_crash_plan(tmp_path, monkeypatch):
    """Pinned seeded plan over the PR 9 parallel-stage seam: a crash
    inside the fanned-out MVCC namespace prepare (mvcc.ns_prepare,
    targeted at one namespace's group so the trip is deterministic even
    with pool workers racing) aborts the commit before anything reaches
    disk; reopen recovers cleanly and the same block re-commits.  Two
    runs yield identical trip ledgers."""
    monkeypatch.setenv("FABRIC_TPU_MVCC_POOL", "3")
    plan = {"seed": 9, "faults": [{
        "point": "mvcc.ns_prepare", "ctx": {"ns": "ns1"},
        "action": "crash",
    }]}

    def run(sub: str) -> list[dict]:
        provider = LedgerProvider(str(tmp_path / sub))
        ledger = provider.open("chaos")
        ledger.commit(_write_block(ledger, 0, [("ns0", "a", b"0")]))
        # 3 namespaces x 15 writes: past the prepare fan-out threshold
        items = [
            (f"ns{j}", f"k{i}", b"v")
            for j in range(3) for i in range(15)
        ]
        blk = _write_block(ledger, 1, items)
        with faultline.use_plan(plan):
            with pytest.raises(faultline.FaultCrash):
                ledger.commit(blk)
            observed = [
                t for t in faultline.trips() if t["plan"] != "soak"
            ]
        assert observed and all(
            t["point"] == "mvcc.ns_prepare" and t["ctx"]["ns"] == "ns1"
            for t in observed
        )
        provider.close()

        # the crash hit BEFORE the block-append stage: nothing reached
        # disk, recovery lands at height 1, the block re-commits
        provider2 = LedgerProvider(str(tmp_path / sub))
        led2 = provider2.open("chaos")
        _assert_consistent(led2, 1, {("ns0", "a"): b"0",
                                     ("ns1", "k0"): None})
        led2.commit(_write_block(led2, 1, items))
        assert led2.get_state("ns1", "k0") == b"v"
        assert led2.height == 2
        provider2.close()
        return observed

    first, second = run("r1"), run("r2")
    assert first == second


# -- storage engine v2: the two-phase group-flush torn points ----------------


@pytest.mark.parametrize("stage", ["prepare", "commit", "apply"])
def test_crash_at_every_shard_flush_stage_recovers(
    tmp_path, stage, monkeypatch
):
    """The sharded statedb's two-phase flush, crashed at each of its
    three torn points.  The block record is durable BEFORE the kv flush
    starts, so every arm must land at the same height 3 — what differs
    is the recovery arm: a crash at prepare or at the coordinator-commit
    point leaves a pending epoch AHEAD of the committed one (roll back
    ALL shards, replay block 2 from the file), while a crash at apply
    leaves pending == committed (roll the staged writes FORWARD — the
    coordinator savepoint already acknowledged them)."""
    monkeypatch.setenv("FABRIC_TPU_STORE_SHARDS", "2")
    monkeypatch.setenv("FABRIC_TPU_STORE_POOL", "0")
    provider = LedgerProvider(str(tmp_path))
    ledger = provider.open("chaos")
    ledger.commit(_write_block(ledger, 0, [("cc", "a", b"0")]))
    ledger.commit(_write_block(ledger, 1, [("qscc", "b", b"1")]))

    # two namespaces so both shards carry staged writes at the crash
    blk2 = _write_block(
        ledger, 2, [("cc", "c", b"2"), ("qscc", "d", b"3")]
    )
    with faultline.use_plan(
        _crash_plan("store.shard_flush", {"stage": stage})
    ):
        with pytest.raises(faultline.FaultCrash):
            ledger.commit(blk2)
        assert faultline.trips(), "the plan never fired"
    provider.close()

    # reopen under the observer: recovery's own seam tells the two arms
    # apart — only the apply crash leaves a committed-but-unapplied
    # epoch for the roll-forward guard to resolve
    faultline.reset_registry()
    with faultline.observe():
        provider2 = LedgerProvider(str(tmp_path))
        led2 = provider2.open("chaos")
    rolled_forward = "store.shard_recover" in faultline.registry()
    assert rolled_forward == (stage == "apply"), faultline.registry()

    _assert_consistent(led2, 3, {
        ("cc", "a"): b"0", ("qscc", "b"): b"1",
        ("cc", "c"): b"2", ("qscc", "d"): b"3",
    })
    led2.commit(_write_block(led2, 3, [("cc", "next", b"n")]))
    assert led2.get_state("cc", "next") == b"n"
    provider2.close()


def test_graceful_raise_at_coordinator_txn_rolls_back_shards(
    tmp_path, monkeypatch
):
    """A raise-style fault (graceful failure) at the coordinator txn
    AFTER both shards staged their pending writes: the ledger rolls the
    group back, the staged epochs stay invisible to reads, and the next
    commit's prepare sweeps them — no reopen required."""
    monkeypatch.setenv("FABRIC_TPU_STORE_SHARDS", "2")
    monkeypatch.setenv("FABRIC_TPU_STORE_POOL", "0")
    provider = LedgerProvider(str(tmp_path))
    ledger = provider.open("chaos")
    ledger.commit(_write_block(ledger, 0, [("cc", "a", b"0")]))
    blk1 = _write_block(
        ledger, 1, [("cc", "b", b"1"), ("qscc", "c", b"2")]
    )
    with faultline.use_plan({"faults": [{
        "point": "kvstore.txn", "action": "raise", "error": "OSError",
        "message": "injected disk full",
    }]}):
        with pytest.raises(OSError, match="injected disk full"):
            ledger.commit(blk1)
        assert faultline.trips()
    assert ledger.height == ledger.durable_height == 1
    assert ledger.get_state("cc", "b") is None
    assert ledger.get_state("qscc", "c") is None
    ledger.commit(_write_block(
        ledger, 1, [("cc", "b", b"1"), ("qscc", "c", b"2")]
    ))
    assert ledger.get_state("qscc", "c") == b"2"
    provider.close()


def test_pinned_shard_flush_crash_plan_deterministic(tmp_path, monkeypatch):
    """Pinned seeded plan over the storage-v2 seams: a crash inside the
    fanned-out shard prepare (store.shard_flush, targeted at one shard's
    prepare so the trip is deterministic even with pool workers racing)
    aborts the kv flush after the block record is durable; reopen rolls
    the staged epochs back and replays the block from the file.  Two
    runs yield identical trip ledgers — the chaos determinism contract
    extended to the new seams."""
    monkeypatch.setenv("FABRIC_TPU_STORE_SHARDS", "4")
    monkeypatch.setenv("FABRIC_TPU_STORE_POOL", "3")
    plan = {"seed": 17, "faults": [{
        "point": "store.shard_flush",
        "ctx": {"stage": "prepare", "shard": 2},
        "action": "crash",
    }]}
    # namespaces spread across all 4 shards so the fan-out is real
    items = [
        (f"ns{j}", f"k{i}", b"v") for j in range(8) for i in range(4)
    ]

    def run(sub: str) -> list[dict]:
        provider = LedgerProvider(str(tmp_path / sub))
        ledger = provider.open("chaos")
        ledger.commit(_write_block(ledger, 0, [("ns0", "a", b"0")]))
        blk = _write_block(ledger, 1, items)
        with faultline.use_plan(plan):
            with pytest.raises(faultline.FaultCrash):
                ledger.commit(blk)
            observed = [
                t for t in faultline.trips() if t["plan"] != "soak"
            ]
        assert observed and all(
            t["point"] == "store.shard_flush"
            and t["ctx"]["shard"] == 2
            for t in observed
        )
        provider.close()

        provider2 = LedgerProvider(str(tmp_path / sub))
        led2 = provider2.open("chaos")
        _assert_consistent(led2, 2, {
            ("ns0", "a"): b"0", ("ns1", "k0"): b"v",
        })
        led2.commit(_write_block(led2, 2, [("ns2", "z", b"z")]))
        assert led2.get_state("ns2", "z") == b"z"
        provider2.close()
        return observed

    first, second = run("r1"), run("r2")
    assert first == second


# literal plan rules (not a name parametrized through _crash_plan):
# these pins are what the chaos-coverage faultmap cross-check counts
# as arming the two segment-lifecycle seams
SEGMENT_LIFECYCLE_PLANS = [
    {"seed": 1, "faults": [
        {"point": "blkstorage.segment_prealloc", "action": "crash"},
    ]},
    {"seed": 1, "faults": [
        {"point": "blkstorage.segment_roll", "action": "crash"},
    ]},
]


@pytest.mark.parametrize(
    "plan", SEGMENT_LIFECYCLE_PLANS,
    ids=[p["faults"][0]["point"] for p in SEGMENT_LIFECYCLE_PLANS],
)
def test_crash_at_segment_lifecycle_points_recovers(
    tmp_path, plan, monkeypatch
):
    """The preallocated-segment writer's metadata seams: a crash while
    preallocating the next segment (before its rename publishes it) or
    while sealing a full one must leave the committed chain fully
    replayable — segment lifecycle is bookkeeping, never data loss.  A
    tiny segment floor forces a roll on the second block."""
    monkeypatch.setenv("FABRIC_TPU_STORE_SEGMENT", "4096")
    provider = LedgerProvider(str(tmp_path))
    ledger = provider.open("chaos")
    big = b"x" * 3000  # ~3KB payload: two records cannot share 4KB
    ledger.commit(_write_block(ledger, 0, [("cc", "a", big)]))

    blk1 = _write_block(ledger, 1, [("cc", "b", big)])
    with faultline.use_plan(plan):
        with pytest.raises(faultline.FaultCrash):
            ledger.commit(blk1)
        assert faultline.trips(), "the plan never fired"
    provider.close()

    provider2 = LedgerProvider(str(tmp_path))
    led2 = provider2.open("chaos")
    # block 1 never reached the (unpublished or mid-seal) segment —
    # recovery lands at height 1 and the same block re-commits into a
    # freshly preallocated segment
    _assert_consistent(led2, 1, {("cc", "a"): big, ("cc", "b"): None})
    led2.commit(_write_block(led2, 1, [("cc", "b", big)]))
    assert led2.get_state("cc", "b") == big
    assert led2.height == 2
    provider2.close()


def test_same_seed_same_trip_ledger_across_runs(tmp_path):
    """Determinism acceptance: the same plan over the same workload
    yields an IDENTICAL trip ledger across two runs — seeded
    probability triggers included."""
    plan = {"seed": 42, "faults": [
        {"point": "commit.stage", "ctx": {"stage": "history"},
         "action": "delay", "delay_s": 0.0, "prob": 0.5, "count": 100},
        {"point": "kvstore.txn", "action": "delay", "delay_s": 0.0,
         "every": 2, "count": 100},
    ]}

    def run(sub: str) -> list[dict]:
        provider = LedgerProvider(str(tmp_path / sub))
        ledger = provider.open("det")
        with faultline.use_plan(plan):
            for n in range(8):
                ledger.commit(
                    _write_block(ledger, n, [("cc", f"k{n}", b"v")])
                )
            observed = [
                t for t in faultline.trips() if t["plan"] != "soak"
            ]
        provider.close()
        return observed

    first, second = run("r1"), run("r2")
    assert first == second
    assert first, "the probabilistic rule never fired in 8 commits"
