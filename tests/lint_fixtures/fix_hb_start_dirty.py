"""Seeded violation: a write AFTER start() races with the spawned
thread's read of the same field — the write slipped past its
publication point (racecheck, v4 happens-before pass)."""

from fabric_tpu.devtools.lockwatch import spawn_thread


def handle(item):
    return item


class Pump:
    def __init__(self):
        self._batch = []

    def start(self):
        self._batch = ["seed"]  # before start(): published by the spawn
        t = spawn_thread(target=self._run, name="pump", kind="worker")
        t.start()
        self._batch = ["late"]  # <- racecheck fires HERE
        return t

    def _run(self):
        for item in self._batch:
            handle(item)
