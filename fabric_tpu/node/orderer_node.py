"""Orderer daemon: AtomicBroadcast over the framed RPC transport.

Reference: orderer/common/server/main.go Main() assembles localconfig,
the multichannel registrar, and the Broadcast/Deliver gRPC handlers
(server.go:159,177); channel participation (join/remove without a system
channel, channelparticipation/restapi.go) is exposed as admin RPCs.

RPC surface:
  ab.Broadcast        Envelope -> BroadcastResponse
  ab.Deliver          signed SeekInfo Envelope -> stream DeliverResponse
  participation.Join  genesis Block -> channel id (join without system
                      channel)
  participation.List  "" -> ChannelQueryResponse (channel ids)
"""

from __future__ import annotations

from fabric_tpu.comm import RPCServer
from fabric_tpu.common.deliver import BlockNotifier, DeliverService
from fabric_tpu.orderer.broadcast import BroadcastHandler
from fabric_tpu.orderer.multichannel import Registrar
from fabric_tpu.protos.common import common_pb2
from fabric_tpu.protos.orderer import ab_pb2
from fabric_tpu.protos.peer import configuration_pb2 as peer_cfg


class OrdererNode:
    def __init__(
        self,
        root_dir: str | None,
        csp,
        signer=None,
        host: str = "127.0.0.1",
        port: int = 0,
        genesis_blocks: list | None = None,
        consenter_overrides: dict | None = None,
        node_id: int = 1,
        transport=None,
        tls=None,
        keepalive=None,
        operations_port: int | None = None,
    ):
        self.tls = tls  # comm.tls.TLSCredentials | None
        # operations endpoint (reference orderer main.go serves the
        # same core/operations system): /metrics carries the raft
        # term/leader/committed-index gauges + WAL histograms netscope
        # scrapes, /healthz the registrar-halted checker
        self.operations = None
        raft_metrics = None
        if operations_port is not None:
            from fabric_tpu.common.operations import System

            self.operations = System(
                ("127.0.0.1", operations_port), process_metrics=True
            )
            raft_metrics = self.operations.raft_metrics()
            if transport is not None and hasattr(transport, "set_metrics"):
                transport.set_metrics(raft_metrics)
            self.operations.register_checker(
                "registrar",
                lambda: not getattr(self.registrar, "_halted", False),
            )
            from fabric_tpu.common import profile

            if profile.enabled():
                profile.set_lock_metrics(self.operations.lock_metrics())
        self.registrar = Registrar(
            root_dir,
            csp,
            signer=signer,
            node_id=node_id,
            transport=transport,
            consenter_overrides=consenter_overrides,
            raft_metrics=raft_metrics,
        )
        self._csp = csp
        notifier = BlockNotifier()
        self.deliver = DeliverService(
            self.registrar.get_chain,
            csp,
            policy_path="/Channel/Readers",
            notifier=notifier,
        )
        self.registrar.add_block_listener(
            lambda ch, blk: notifier.notify()
        )
        self.broadcast = BroadcastHandler(self.registrar)
        if genesis_blocks:
            self.registrar.startup(genesis_blocks)

        self._signer = signer
        self.rpc = RPCServer(host, port, tls=tls, keepalive=keepalive)
        self.rpc.register("ab.Broadcast", self._broadcast)
        self.rpc.register("ab.Deliver", self._deliver)
        self.rpc.register("participation.Join", self._join)
        self.rpc.register("participation.Onboard", self._onboard)
        self.rpc.register("participation.List", self._list)

    @property
    def addr(self):
        return self.rpc.addr

    def start(self) -> None:
        self._warn_expiring_certs()
        self.rpc.start()
        if self.operations is not None:
            self.operations.start()

    def _warn_expiring_certs(self) -> None:
        """Week-ahead warnings for the orderer's signing and TLS certs
        (reference expiration.go TrackExpiration, orderer main.go)."""
        from fabric_tpu.common.crypto import warn_node_cert_expirations
        from fabric_tpu.common.flogging import must_get_logger

        warn_node_cert_expirations(
            self._signer, self.tls, "signing",
            must_get_logger("orderer").warning,
        )

    def stop(self) -> None:
        # idempotent: subprocess drivers reach stop() from BOTH the
        # signal handler and their finally block — the second call must
        # be a no-op, not a crash on half-torn-down components
        if getattr(self, "_stopped", False):
            return
        self._stopped = True
        self.rpc.stop()
        self.deliver.stop()
        self.registrar.halt_all()
        if self.operations is not None:
            self.operations.stop()

    # -- handlers ----------------------------------------------------------

    def _broadcast(self, body: bytes, stream) -> bytes:
        env = common_pb2.Envelope.FromString(body)
        status = self.broadcast.process_message(env)
        return ab_pb2.BroadcastResponse(status=status).SerializeToString()

    def _deliver(self, body: bytes, stream):
        from fabric_tpu.common.deliver import deliver_response_frames

        return deliver_response_frames(self.deliver, body)

    def _join(self, body: bytes, stream) -> bytes:
        blk = common_pb2.Block.FromString(body)
        cs = self.registrar.create_chain(blk)
        return cs.channel_id.encode("utf-8")

    def _onboard(self, body: bytes, stream) -> bytes:
        """Cluster replication/onboarding (reference orderer/common/
        cluster/replication.go): pull an existing channel's chain from
        another orderer, verify it — hash chain, data hashes, and
        orderer signatures under the config in force at each height,
        anchored at a LOCALLY supplied genesis block — then join with
        the replicated ledger.  Request: JSON {"channel", "from",
        "genesis": hex(Block)}; the genesis is the caller's trust
        anchor, never taken from the remote."""
        import binascii
        import json

        from fabric_tpu import protoutil
        from fabric_tpu.comm import RPCClient
        from fabric_tpu.common.channelconfig import bundle_from_genesis
        from fabric_tpu.common.deliver import make_seek_info_envelope
        from fabric_tpu.orderer.blockwriter import verify_block_signature

        req = json.loads(body)
        channel_id = req["channel"]
        genesis = common_pb2.Block.FromString(
            binascii.unhexlify(req["genesis"])
        )
        if self.registrar.get_chain(channel_id) is not None:
            raise ValueError(f"channel {channel_id!r} already exists")
        host, _, port = req["from"].rpartition(":")
        client = RPCClient(
            host or "127.0.0.1", int(port), timeout=30.0, tls=self.tls
        )
        env = make_seek_info_envelope(
            channel_id, 0, "newest", signer=self._signer,
            behavior=ab_pb2.SeekInfo.FAIL_IF_NOT_READY,
        )
        blocks = []
        final_status = None
        for raw in client.stream("ab.Deliver", env.SerializeToString()):
            resp = ab_pb2.DeliverResponse.FromString(raw)
            if resp.WhichOneof("Type") == "block":
                blk = common_pb2.Block()
                blk.CopyFrom(resp.block)
                blocks.append(blk)
            else:
                final_status = resp.status
        if final_status != common_pb2.SUCCESS:
            raise ValueError(f"deliver ended with status {final_status}")
        if not blocks:
            raise ValueError(f"no blocks for channel {channel_id!r}")
        if blocks[0].SerializeToString() != genesis.SerializeToString():
            raise ValueError("remote genesis differs from the trust anchor")

        bundle = bundle_from_genesis(genesis, self._csp)
        policy = bundle.policy_manager.get_policy(
            "/Channel/Orderer/BlockValidation"
        )
        prev_hash = protoutil.block_header_hash(genesis.header)
        for i, blk in enumerate(blocks[1:], start=1):
            if blk.header.number != i:
                raise ValueError(
                    f"gap in pulled chain: got {blk.header.number}, want {i}"
                )
            if blk.header.previous_hash != prev_hash:
                raise ValueError(f"block {i} breaks the hash chain")
            if blk.header.data_hash != protoutil.block_data_hash(blk.data):
                raise ValueError(f"block {i} data hash mismatch")
            if policy is not None and not verify_block_signature(
                blk, policy, self._csp
            ):
                raise ValueError(
                    f"block {i} fails signature verification"
                )
            prev_hash = protoutil.block_header_hash(blk.header)
            # a config block changes the verifier for subsequent blocks
            # (reference replication re-derives per config)
            try:
                env0 = protoutil.extract_envelope(blk, 0)
                if protoutil.channel_header(env0).type == common_pb2.CONFIG:
                    bundle = bundle_from_genesis(blk, self._csp)
                    policy = bundle.policy_manager.get_policy(
                        "/Channel/Orderer/BlockValidation"
                    )
            except Exception:
                pass
        cs = self.registrar.create_chain(genesis, extra_blocks=blocks[1:])
        return json.dumps(
            {"channel": channel_id, "height": cs.store.height}
        ).encode()

    def _list(self, body: bytes, stream) -> bytes:
        resp = peer_cfg.ChannelQueryResponse()
        for ch in self.registrar.channel_list():
            resp.channels.add().channel_id = ch
        return resp.SerializeToString()


__all__ = ["OrdererNode"]
