"""Test configuration.

Tests run on CPU with a virtual 8-device mesh so multi-chip sharding
(shard_map over jax.sharding.Mesh) is exercised without TPU hardware, per
the reference test strategy of simulating multi-node on one host
(integration/nwo).  Must run before jax initializes a backend.
"""

import os

# Force (not setdefault): the ambient environment pins JAX_PLATFORMS to the
# TPU platform, but unit tests must be hermetic and run on the virtual CPU
# mesh even when the TPU tunnel is down.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
