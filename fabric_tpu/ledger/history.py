"""History database: per-key write history index.

Reference: core/ledger/kvledger/history (leveldb index keyed
(ns, key, blockNum, txNum) enabling GetHistoryForKey)."""

from __future__ import annotations

import struct

from fabric_tpu.ledger.kvstore import KVStore, NamedDB

_SEP = b"\x00"
_SAVEPOINT_KEY = b"\x01sp"


def _hkey(ns: str, key: str, block: int, tx: int) -> bytes:
    return b"\x02" + ns.encode() + _SEP + key.encode() + _SEP + struct.pack(">QQ", block, tx)


class HistoryDB:
    def __init__(self, store: KVStore, name: str = "historydb"):
        self._db = NamedDB(store, name)

    def commit(
        self,
        block_num: int,
        writes_per_tx: list[list[tuple[str, str]]],
        into=None,
    ) -> None:
        """writes_per_tx[tx_num] = [(ns, key), ...] for valid txs.
        `into` (a WriteBatchCollector over this DB's backing store)
        buffers the writes into the block's shared KV transaction."""
        db = self._db if into is None else self._db.rebase(into)
        puts = {_SAVEPOINT_KEY: struct.pack(">Q", block_num)}
        for tx_num, writes in enumerate(writes_per_tx):
            for ns, key in writes:
                puts[_hkey(ns, key, block_num, tx_num)] = b""
        db.write_batch(puts)

    def savepoint(self) -> int | None:
        raw = self._db.get(_SAVEPOINT_KEY)
        return None if raw is None else struct.unpack(">Q", raw)[0]

    def get_history_for_key(self, ns: str, key: str) -> list[tuple[int, int]]:
        """[(block_num, tx_num)] ascending."""
        prefix = b"\x02" + ns.encode() + _SEP + key.encode() + _SEP
        out = []
        for k, _ in self._db.iterate(prefix, prefix + b"\xff" * 16):
            block, tx = struct.unpack(">QQ", k[len(prefix):])
            out.append((block, tx))
        return out


__all__ = ["HistoryDB"]
