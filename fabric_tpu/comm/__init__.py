"""Process-level communication substrate (reference internal/pkg/comm):
framed TCP RPC with unary and server-streaming calls, used by the peer
and orderer daemons and their CLI clients."""

from fabric_tpu.comm.rpc import (  # noqa: F401
    RPCClient,
    RPCError,
    RPCServer,
    Stream,
)
