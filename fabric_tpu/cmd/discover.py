"""discover: CLI client for the peer discovery service (reference
cmd/discover + discovery/client).

    discover peers   --channel ch --peer :7051 --mspid Org1MSP --msp-dir d
    discover config  --channel ch --peer :7051 ...
    discover endorsers --channel ch --chaincode cc --peer :7051 ...
"""

from __future__ import annotations

import argparse
import json
import sys

from fabric_tpu.cmd.common import (
    load_signer,
    parse_endpoint,
    tls_from_args,
    tls_parent,
)
from fabric_tpu.comm import RPCClient
from fabric_tpu.discovery.client import DiscoveryClient, select_endorsers
from fabric_tpu.protos.discovery import protocol_pb2 as dpb


def _client(args) -> DiscoveryClient:
    signer = load_signer(args.msp_dir, args.mspid)
    rpc = RPCClient(*parse_endpoint(args.peer), tls=tls_from_args(args))

    def send(signed: dpb.SignedRequest) -> dpb.Response:
        raw = rpc.call("discovery.Process", signed.SerializeToString())
        return dpb.Response.FromString(raw)

    return DiscoveryClient(signer, send)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="discover")
    sub = ap.add_subparsers(dest="cmd", required=True)
    tlsp = tls_parent()
    for name in ("peers", "config", "endorsers"):
        p = sub.add_parser(name, parents=[tlsp])
        p.add_argument("--channel", required=True)
        p.add_argument("--peer", required=True)
        p.add_argument("--mspid", required=True)
        p.add_argument("--msp-dir", required=True)
        if name == "endorsers":
            p.add_argument("--chaincode", required=True)
    args = ap.parse_args(argv)
    client = _client(args)

    if args.cmd == "peers":
        out = [
            {
                "endpoint": p.endpoint,
                "ledger_height": p.ledger_height,
                "chaincodes": list(p.chaincodes),
            }
            for p in client.peers(args.channel)
        ]
        print(json.dumps(out, indent=2))
        return 0
    if args.cmd == "config":
        conf = client.config(args.channel)
        print(json.dumps({"msps": sorted(conf.msps)}, indent=2))
        return 0
    desc = client.endorsers(args.channel, args.chaincode)
    sel = select_endorsers(desc)
    print(json.dumps(sorted(s.endpoint for s in sel), indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
