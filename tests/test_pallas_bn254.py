"""Pallas BN254 Schnorr-ladder parity (interpret mode on the CPU mesh).

The fused Montgomery ladder (csp/tpu/pallas_bn254.py) must produce
bit-identical T1/T2/T3 commitments to the host Schnorr path for valid,
tampered, and malformed signatures — the same oracle discipline
tests/test_bn254_device.py applies to the XLA engine.  Batches stay
small: interpreted Pallas executes the grid in Python.
"""

from __future__ import annotations

import dataclasses

import pytest

from fabric_tpu.idemix import bn254 as bn
from fabric_tpu.idemix import schnorr, signature
from fabric_tpu.idemix.credential import new_cred_request, new_credential
from fabric_tpu.idemix.issuer import IssuerKey


@pytest.fixture(autouse=True)
def _force_pallas(monkeypatch):
    """On non-TPU backends the dispatcher prefers the XLA engine; this
    module exists to test the Pallas one (interpret mode on CPU)."""
    monkeypatch.setenv("FABRIC_BN254_FORCE_PALLAS", "1")


@pytest.fixture(scope="module")
def world():
    isk = IssuerKey.generate(["a0", "a1", "a2"])
    sk = bn.rand_zr()
    req = new_cred_request(sk, b"nonce", isk.ipk)
    attrs = [11, 22, 33]
    cred = new_credential(isk, req, attrs)
    return isk, sk, cred, attrs


def _sigs(world, n=5):
    isk, sk, cred, attrs = world
    out = []
    for i in range(n):
        disclosure = [
            [False, False, False],
            [True, False, True],
            [True, True, True],
        ][i % 3]
        msg = b"pallas-msg-%d" % i
        sig = signature.new_signature(
            cred, sk, isk.ipk, msg, disclosure=disclosure
        )
        out.append((sig, msg))
    return out


def _host_commitments(sig, ipk):
    rels = signature._relations(
        ipk, sig.a_prime, sig.a_bar, sig.b_prime, sig.nym,
        sig.disclosure, sig.disclosed_attrs,
    )
    return schnorr.recompute_commitments(rels, sig.challenge, sig.responses)


def test_pallas_matches_host_commitments(world, monkeypatch):
    from fabric_tpu.csp.tpu import bn254_batch

    # force the pallas engine: any fallback to XLA must fail the test
    def no_xla(*a, **k):
        raise AssertionError("pallas engine fell back to XLA")

    monkeypatch.setattr(bn254_batch, "_commitments_xla", no_xla)
    isk, *_ = world
    pairs = _sigs(world)
    got = bn254_batch.schnorr_commitments_batch(
        [s for s, _ in pairs], isk.ipk
    )
    assert len(got) == len(pairs)
    for j, (sig, _msg) in enumerate(pairs):
        want = _host_commitments(sig, isk.ipk)
        assert got[j] is not None
        assert list(got[j]) == list(want), f"sig {j} commitments diverge"


def test_pallas_handles_tampered_and_malformed(world, monkeypatch):
    from fabric_tpu.csp.tpu import bn254_batch

    def no_xla(*a, **k):
        raise AssertionError("pallas engine fell back to XLA")

    monkeypatch.setattr(bn254_batch, "_commitments_xla", no_xla)
    isk, *_ = world
    sigs = [s for s, _ in _sigs(world)]
    # tampered challenge: still computes (the commitments diverge from
    # the honest ones; the challenge re-hash catches it upstream)
    sigs[1] = dataclasses.replace(
        sigs[1], challenge=(sigs[1].challenge + 1) % bn.R
    )
    # malformed: off-curve point -> lane marked None
    sigs[3] = dataclasses.replace(
        sigs[3],
        a_prime=(sigs[3].a_prime[0], (sigs[3].a_prime[1] + 1) % bn.P),
    )
    got = bn254_batch.schnorr_commitments_batch(sigs, isk.ipk)
    assert got[3] is None
    for j in (0, 1, 2, 4):
        want = _host_commitments(sigs[j], isk.ipk)
        assert list(got[j]) == list(want), j


def test_device_verify_batch_mask_via_pallas(world, monkeypatch):
    from fabric_tpu.csp.tpu import bn254_batch

    # a broken Pallas kernel must not silently pass via the XLA fallback
    def no_xla(*a, **k):
        raise AssertionError("pallas engine fell back to XLA")

    monkeypatch.setattr(bn254_batch, "_commitments_xla", no_xla)
    isk, *_ = world
    pairs = _sigs(world)
    sigs = [s for s, _ in pairs]
    msgs = [m for _, m in pairs]
    sigs[2] = dataclasses.replace(
        sigs[2], challenge=(sigs[2].challenge + 1) % bn.R
    )
    want = signature.verify_batch(list(sigs), isk.ipk, list(msgs))
    # ... and neither may verify_batch_device's own host fallback:
    # compute `want` first, then make the host oracle unreachable
    monkeypatch.setattr(
        signature, "verify_batch",
        lambda *a, **k: (_ for _ in ()).throw(
            AssertionError("device path fell back to host verify")
        ),
    )
    got = signature.verify_batch_device(list(sigs), isk.ipk, list(msgs))
    assert got == want
    assert want == [True, True, False, True, True]
