"""Signature-policy compilation & evaluation (cauthdsl equivalent).

Reference: common/cauthdsl/cauthdsl.go:24-92 (compile to a closure with the
`used[]` de-duplication trick) and common/policies/policy.go:365-402
(SignatureSetToValidIdentities: verify each signature once, dedup
identities, then run the closure over *valid identities only*).

TPU-first split (SURVEY.md §7 step 3): the reference interleaves signature
verification with policy evaluation per transaction; here the two phases
are explicit so a whole block's signatures batch into one device call:

  1. `prepare(signed_data)` -> PendingEvaluation: deserializes/dedups
     identities and exposes `items` (VerifyBatchItems) WITHOUT verifying.
  2. the caller batches items from many policies into CSP.verify_batch.
  3. `PendingEvaluation.finish(mask)` runs the compiled combinatoric
     closure over the identities whose signatures verified.

`evaluate_signed_data` composes all three for single-policy callers (e.g.
the orderer's sig filter).
"""

from __future__ import annotations

import dataclasses

from fabric_tpu.csp.api import VerifyBatchItem
from fabric_tpu.protos.common import policies_pb2
from fabric_tpu.protoutil import SignedData


class PolicyError(Exception):
    pass


def _compile(policy: policies_pb2.SignaturePolicy, identities, deserializer):
    """SignaturePolicy tree -> closure(valid_identities, used) -> bool.

    `valid_identities` is a list of (identity, index) whose signatures
    verified; `used` is a parallel bool list implementing the reference's
    rule that one signature cannot satisfy two leaves (cauthdsl.go:40-60)."""
    which = policy.WhichOneof("Type")
    if which == "signed_by":
        idx = policy.signed_by
        if idx < 0 or idx >= len(identities):
            raise PolicyError(f"identity index {idx} out of range")
        principal = identities[idx]

        def signed_by(valid, used):
            for pos, ident in enumerate(valid):
                if used[pos] or ident is None:
                    continue
                try:
                    deserializer.satisfies_principal(ident, principal)
                except Exception:
                    # fabriclint: allow[exception-discipline] principal
                    # mismatch is the expected per-lane outcome, not an error
                    continue
                used[pos] = True
                return True
            return False

        return signed_by
    if which == "n_out_of":
        n = policy.n_out_of.n
        subs = [_compile(r, identities, deserializer) for r in policy.n_out_of.rules]

        def n_out_of(valid, used):
            verified = 0
            for sub in subs:
                # speculative evaluation against a copy of `used`; commit
                # only on success (the reference's buf/copy dance)
                trial = list(used)
                if sub(valid, trial):
                    verified += 1
                    used[:] = trial
            return verified >= n

        return n_out_of
    raise PolicyError(f"unknown signature policy type {which!r}")


@dataclasses.dataclass
class PendingEvaluation:
    """Deferred policy evaluation: feed `items` to verify_batch, then call
    `finish` with the per-item validity mask."""

    items: list  # VerifyBatchItem per *deduped* signed-data entry
    _closure: object
    _identities: list  # deserialized identity per item (None if bad)

    def finish(self, mask) -> bool:
        if len(mask) != len(self.items):
            raise PolicyError("mask length mismatch")
        valid = [
            ident if ok and ident is not None else None
            for ident, ok in zip(self._identities, mask)
        ]
        used = [False] * len(valid)
        return self._closure(valid, used)


class SignaturePolicy:
    """A compiled SignaturePolicyEnvelope bound to an identity deserializer
    (implements the `policies.Policy` protocol)."""

    def __init__(self, envelope: policies_pb2.SignaturePolicyEnvelope, deserializer):
        if envelope.version != 0:
            raise PolicyError(f"unsupported policy version {envelope.version}")
        self._envelope = envelope
        self._deserializer = deserializer
        self._closure = _compile(envelope.rule, list(envelope.identities), deserializer)

    def prepare(self, signed_data: list[SignedData]) -> PendingEvaluation:
        """Deserialize + dedup identities; no signature verification here.

        Dedup matches the reference (policy.go:381-388): repeated identity
        bytes contribute a single entry — and a single verify item."""
        seen: dict[bytes, int] = {}
        items, idents = [], []
        for sd in signed_data:
            if sd.identity in seen:
                continue
            seen[sd.identity] = len(items)
            ident = None
            try:
                ident = self._deserializer.deserialize_identity(sd.identity)
            except Exception:
                # fabriclint: allow[exception-discipline] lane stays None and
                # gets an unsatisfiable dummy item (alignment sentinel below)
                pass
            idents.append(ident)
            if ident is None:
                # keep lane alignment; a lane that cannot deserialize can
                # never verify.  Use an unsatisfiable dummy item.
                items.append(_dummy_item())
            elif sd.digest is not None:
                items.append(
                    VerifyBatchItem(ident.public_key, sd.digest, sd.signature)
                )
            else:
                items.append(ident.verification_item(sd.data, sd.signature))
        return PendingEvaluation(items, self._closure, idents)

    def evaluate_signed_data(self, signed_data: list[SignedData], csp) -> bool:
        """One-shot path (reference policy.EvaluateSignedData,
        common/cauthdsl/policy.go:87-95)."""
        pending = self.prepare(signed_data)
        mask = csp.verify_batch(pending.items)
        return pending.finish(mask)


_DUMMY = None


def _dummy_item():
    """A VerifyBatchItem that always fails verification (malformed DER)."""
    global _DUMMY
    if _DUMMY is None:
        from fabric_tpu.csp.api import ECDSAP256PrivateKey, VerifyBatchItem

        key = ECDSAP256PrivateKey.generate().public_key()
        _DUMMY = VerifyBatchItem(key, b"\x00" * 32, b"\x30\x00")
    return _DUMMY


# ---------------------------------------------------------------------------
# Convenience policy constructors (reference common/policydsl builders).
# ---------------------------------------------------------------------------


def signed_by(index: int) -> policies_pb2.SignaturePolicy:
    return policies_pb2.SignaturePolicy(signed_by=index)


def n_out_of(n: int, rules) -> policies_pb2.SignaturePolicy:
    return policies_pb2.SignaturePolicy(
        n_out_of=policies_pb2.SignaturePolicy.NOutOf(n=n, rules=list(rules))
    )


def signed_by_msp_role(mspid: str, role) -> "policies_pb2.SignaturePolicyEnvelope":
    from fabric_tpu.protos.msp import msp_principal_pb2 as mp

    principal = mp.MSPPrincipal(
        principal_classification=mp.MSPPrincipal.ROLE,
        principal=mp.MSPRole(msp_identifier=mspid, role=role).SerializeToString(),
    )
    return policies_pb2.SignaturePolicyEnvelope(
        version=0, rule=signed_by(0), identities=[principal]
    )


def signed_by_any_member(mspids) -> policies_pb2.SignaturePolicyEnvelope:
    """1-of-N member policy across the given MSPs (reference
    policydsl SignedByAnyMember)."""
    from fabric_tpu.protos.msp import msp_principal_pb2 as mp

    identities = []
    rules = []
    for i, mspid in enumerate(mspids):
        identities.append(
            mp.MSPPrincipal(
                principal_classification=mp.MSPPrincipal.ROLE,
                principal=mp.MSPRole(
                    msp_identifier=mspid, role=mp.MSPRole.MEMBER
                ).SerializeToString(),
            )
        )
        rules.append(signed_by(i))
    return policies_pb2.SignaturePolicyEnvelope(
        version=0, rule=n_out_of(1, rules), identities=identities
    )


__all__ = [
    "PolicyError",
    "SignaturePolicy",
    "PendingEvaluation",
    "signed_by",
    "n_out_of",
    "signed_by_msp_role",
    "signed_by_any_member",
]
