"""CSP factory: provider selection + process-wide default.

Reference: bccsp/factory/factory.go:42 GetDefault, nopkcs11.go:28
InitFactories.  Providers: "sw" (host) and "tpu" (JAX batched).  The tpu
provider is imported lazily so host-only users never pay JAX startup.
"""

from __future__ import annotations

import threading
from typing import Optional

from fabric_tpu.csp.api import CSP
from fabric_tpu.csp.sw import SWCSP

_lock = threading.Lock()
_default: Optional[CSP] = None


def _install_default(csp: CSP) -> CSP:
    """Record the process default AND hand it to the common.crypto hash
    seam, so seam-routed call sites (protoutil block hashing, snapshot
    digests, …) ride the same provider as block validation.  The seam's
    SHA-256 equivalence probe runs FIRST: a provider it rejects must not
    be left installed as the default, or direct get_default() users
    would hash through the very backend the probe refused."""
    global _default
    from fabric_tpu.common import hashing as _hashing

    _hashing.set_hash_backend(csp)
    _default = csp
    return csp


def init_factories(provider: str = "sw", force: bool = False, **kwargs) -> CSP:
    """Initialize the process default CSP.

    Like the reference's InitFactories (bccsp/factory/nopkcs11.go:28 via
    sync.Once), the first call wins and later calls return the existing
    default — replacing the default would orphan keys already stored in the
    previous provider's keystore. Pass force=True to replace anyway (tests).
    """
    with _lock:
        if _default is None or force:
            _install_default(_new_csp(provider, **kwargs))
        return _default


def get_default() -> CSP:
    """Reference bccsp/factory/factory.go:42-62: lazily bootstraps a sw
    provider when nothing was configured."""
    with _lock:
        if _default is None:
            _install_default(SWCSP())
        return _default


def _maybe_install(csp: CSP) -> CSP:
    """First configured CSP becomes the process default (and the hash
    seam backend) unless one was already installed — config-built nodes
    must not leave the seam on the hashlib fallback while validating
    through a batched provider."""
    with _lock:
        if _default is None:
            _install_default(csp)
    return csp


def _new_csp(provider: str, **kwargs) -> CSP:
    if provider == "sw":
        return SWCSP(**kwargs)
    if provider == "tpu":
        from fabric_tpu.csp.tpu.provider import TPUCSP

        return TPUCSP(**kwargs)
    if provider == "custody":
        from fabric_tpu.csp.custody import CustodyCSP

        return CustodyCSP(**kwargs)
    raise ValueError(f"unknown CSP provider {provider!r}")


def _tpu_kwargs(cfg, prefix: str) -> dict:
    """TPU provider tuning knobs from the config block — shared by the
    direct-TPU and custody-verify construction sites so a new knob
    cannot drift between them."""
    kwargs = {}
    mdb = cfg.get(f"{prefix}.tpu.minDeviceBatch")
    if mdb is not None:
        kwargs["min_device_batch"] = int(mdb)
    return kwargs


def csp_from_config(cfg, prefix: str = "bccsp") -> CSP:
    """Build a CSP from a core.yaml/orderer.yaml BCCSP block (reference
    bccsp/factory/opts.go + sampleconfig/core.yaml:290-315):

        bccsp:
          default: SW | TPU | CUSTODY
          sw:
            fileKeyStore:
              keyStorePath: <dir>     # empty/absent -> in-memory
          tpu:
            minDeviceBatch: <n>
          custody:                    # process-isolated key custody
            endpoint: host:port       # fabric-custody daemon
            tokenFile: <path>         # shared token (PIN analogue)
            verify: SW | TPU          # local hash/verify provider
            tls: {certFile, keyFile, caFiles: [..]}  # mutual TLS

    The file keystore is what makes node restarts reuse generated keys
    (reference fileks.go); it backs BOTH providers' key management (the
    tpu provider delegates keys/signing to its embedded sw provider)."""
    provider = str(cfg.get(f"{prefix}.default", "SW")).lower()
    ks_path = cfg.get(f"{prefix}.sw.fileKeyStore.keyStorePath")
    keystore = None
    if ks_path:
        from fabric_tpu.csp.keystore import FileKeyStore

        keystore = FileKeyStore(str(ks_path))
    sw = SWCSP(keystore=keystore)
    if provider == "tpu":
        from fabric_tpu.csp.tpu.provider import TPUCSP

        return _maybe_install(TPUCSP(sw=sw, **_tpu_kwargs(cfg, prefix)))
    if provider == "custody":
        # bccsp.custody: {endpoint: host:port, tokenFile: path,
        # verify: SW|TPU, tls: {certFile, keyFile, caFiles: [...]}} —
        # the pkcs11 config block's role (sampleconfig/core.yaml
        # BCCSP.PKCS11 library/pin/label)
        from fabric_tpu.cmd.common import parse_endpoint
        from fabric_tpu.csp.custody import CustodyCSP, load_token

        endpoint = cfg.get(f"{prefix}.custody.endpoint")
        token_file = cfg.get(f"{prefix}.custody.tokenFile")
        if not endpoint:
            raise ValueError(
                f"{prefix}.default is CUSTODY but "
                f"{prefix}.custody.endpoint is not set"
            )
        if not token_file:
            raise ValueError(
                f"{prefix}.default is CUSTODY but "
                f"{prefix}.custody.tokenFile is not set"
            )
        tls = None
        cert = cfg.get(f"{prefix}.custody.tls.certFile")
        key = cfg.get(f"{prefix}.custody.tls.keyFile")
        cas = cfg.get(f"{prefix}.custody.tls.caFiles")
        if cert or key or cas:
            if not (cert and key):
                raise ValueError(
                    f"{prefix}.custody.tls needs BOTH certFile and "
                    "keyFile (partial TLS config would silently send "
                    "the token in plaintext)"
                )
            from fabric_tpu.comm.tls import credentials_from_files

            tls = credentials_from_files(
                str(cert), str(key), [str(c) for c in (cas or [])]
            )
        verify: CSP = sw
        if str(cfg.get(f"{prefix}.custody.verify", "SW")).lower() == "tpu":
            from fabric_tpu.csp.tpu.provider import TPUCSP

            verify = TPUCSP(sw=sw, **_tpu_kwargs(cfg, prefix))
        return _maybe_install(CustodyCSP(
            parse_endpoint(str(endpoint)),
            load_token(str(token_file)),
            verify_csp=verify,
            tls=tls,
        ))
    return _maybe_install(sw)
