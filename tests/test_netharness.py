"""Netharness: the multi-process N-org × M-peer network with kill -9
chaos (ISSUE 11 tentpole).

Tier-1 pins:
- a real 1-org × 2-peer + 1-orderer multi-process topology survives a
  SIGKILL of one peer mid-stream: the killed peer catches up via gossip
  state transfer and the invariants oracle is green on every node;
- the deliver client fails over to another orderer endpoint when the
  orderer it streams from is SIGKILLed (real process death);
- ``LedgerProvider.open`` recovers after kill -9 mid-``_flush_group``
  in a CHILD process (a faultline delay holds the fsync window open so
  the SIGKILL lands inside the flush);
- the gossip TCP transport piggybacks the tracelens wire token, so a
  remote peer's dispatch nests under the disseminating peer's trace;
- ``GET /traces?since=<event-id>`` serves incremental flight-recorder
  dumps.

The slow soak scales to 3 orgs × 2 peers × 3 orderers with a seeded
kill schedule (including an orderer follower) and pins the
byte-determinism of the verdict JSON for a fixed seed.
"""

from __future__ import annotations

import json
import os
import re
import signal
import subprocess
import sys
import time

import pytest

from fabric_tpu.devtools import invariants, netharness as nh, netident

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FLUSH_WORKER = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "flush_worker.py"
)


def _wait(pred, timeout=30.0, msg="condition", interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timeout waiting for {msg}")


# ---------------------------------------------------------------------------
# tier-1 smoke: SIGKILL a peer mid-stream, catch up, oracle green
# ---------------------------------------------------------------------------


def test_smoke_kill9_peer_catches_up(tmp_path):
    topo = nh.Topology(orgs=1, peers_per_org=2, orderers=1, seed=7)
    schedule = [nh.KillRule(
        node="org1-peer1", at_height=4, sig="kill9",
        rejoin="restart", restart_after_s=0.4,
    )]
    with nh.Network(str(tmp_path / "net"), topo) as net:
        net.start()
        result = nh.run_stream(
            net, txs=80, kill_schedule=schedule, settle_timeout_s=120,
        )
    assert result["errors"] == []
    assert result["ok"], result
    # the killed peer was actually down and came back
    assert "org1-peer1" in result["catch_up_s"], result
    assert result["state_digests_agree"]
    assert result["violations"] == {}
    assert result["missing"] == []
    heights = set(result["heights"].values())
    assert len(heights) == 1 and heights.pop() >= 1 + 80 // topo.max_message_count
    # the verdict view carries NO timing fields — only seed-derived and
    # pass/fail data, the byte-determinism contract the soak pins
    verdict = nh.verdict_doc(result)
    assert set(verdict) == {
        "experiment", "rpcmap_sha256", "seed", "topology",
        "kill_schedule", "txs", "ok", "state_digests_agree",
        "stalled_nodes", "violations", "missing", "caught_up",
        "partition_schedule", "partition_checks", "healed_caught_up",
    }
    assert verdict["caught_up"] == ["org1-peer1"]
    assert verdict["stalled_nodes"] == []
    # the verdict pins the static RPC surface it certified (v6): the
    # embedded hash is the sha256 of the canonical --rpcmap artifact
    assert verdict["rpcmap_sha256"] == nh.rpcmap_hash()
    assert re.fullmatch(r"[0-9a-f]{64}", verdict["rpcmap_sha256"])


def test_kill_schedule_generation_deterministic():
    topo = nh.Topology(orgs=3, peers_per_org=2, orderers=3, seed=11)
    a = nh.generate_kill_schedule(11, topo, 30, kills=2)
    b = nh.generate_kill_schedule(11, topo, 30, kills=2)
    assert [r.as_dict() for r in a] == [r.as_dict() for r in b]
    # a 3-orderer cluster keeps quorum through one orderer kill, so the
    # generator includes one
    assert any(r.node.startswith("orderer") for r in a)
    assert all(r.node != r2.node or r is r2 for r in a for r2 in a)


# ---------------------------------------------------------------------------
# tier-1: deliver-client endpoint failover on orderer SIGKILL
# ---------------------------------------------------------------------------


def test_deliver_failover_on_orderer_kill9(tmp_path):
    from fabric_tpu.comm import RPCClient
    from fabric_tpu.common.deliver import make_seek_info_envelope
    from fabric_tpu.common.hashing import sha256
    from fabric_tpu.peer.deliverclient import DeliverClient
    from fabric_tpu.protos.orderer import ab_pb2

    topo = nh.Topology(orgs=1, peers_per_org=0, orderers=3, seed=5)
    with nh.Network(str(tmp_path / "net"), topo) as net:
        net.start()

        def send(n0, count):
            for i in range(n0, n0 + count):
                env = netident.make_tx(
                    topo.channel, f"fk{i}", b"v%d" % i, orgs=1
                )
                net.broadcast(env, prefer=i)

        send(0, 20)
        _wait(
            lambda: all(
                net.status(n)["height"] >= 4
                for n in topo.orderer_names() if net.nodes[n].alive()
            ),
            msg="orderers commit the first batches",
        )

        ident = b"cre:failover-client"

        class _Signer:
            def serialize(self):
                return ident

            def sign(self, msg):
                return netident.sign_as(ident, sha256(msg))

        def connect_fn(endpoint):
            def connect(start_num: int):
                client = RPCClient(endpoint[0], endpoint[1], timeout=5.0)
                env = make_seek_info_envelope(
                    topo.channel, start_num, 0x7FFFFFFFFFFFFFFF,
                    signer=_Signer(),
                )
                for raw in client.stream(
                    "ab.Deliver", env.SerializeToString()
                ):
                    resp = ab_pb2.DeliverResponse.FromString(raw)
                    if resp.WhichOneof("Type") == "block":
                        yield resp.block
                    else:
                        return

            return connect

        got: dict[int, bytes] = {}
        endpoints = [
            tuple(net.nodes[n].rpc_addr) for n in topo.orderer_names()
        ]
        dc = DeliverClient(
            topo.channel,
            [connect_fn(ep) for ep in endpoints],
            height_fn=lambda: (max(got) + 1) if got else 0,
            sink=lambda seq, raw: got.__setitem__(seq, raw),
            max_backoff_s=1.0,
        )
        dc.start()
        try:
            _wait(lambda: len(got) >= 4, msg="initial deliver stream")
            # SIGKILL the orderer this client is actually streaming from
            # — real process death, not a stream error
            victim_idx = dc.endpoint_log[-1]
            victim = topo.orderer_names()[victim_idx]
            net.kill(victim, signal.SIGKILL)
            before = max(got)
            # net.broadcast rotates off the dead orderer, but the
            # SURVIVORS may still believe the dead node is the raft
            # leader until their election timeout fires — envelopes
            # forwarded to it meanwhile are legitimately lost (the
            # reference broadcast contract is client resubmission, as
            # run_stream does).  Submit in waves of fresh keys until
            # deliveries progress, instead of racing one burst against
            # the election (the old form flaked when all 20 sends beat
            # the new leader).
            n0, deadline = 20, time.monotonic() + 30
            while max(got) < before + 3 and time.monotonic() < deadline:
                send(n0, 5)
                n0 += 5
                time.sleep(0.3)
            assert max(got) >= before + 3, (
                f"no blocks delivered after orderer SIGKILL "
                f"(delivered up to {max(got)}, started at {before})"
            )
            # the client rotated to a DIFFERENT endpoint after the kill
            post_kill = [
                idx for idx in list(dc.endpoint_log)
            ]
            assert any(
                idx != victim_idx
                for idx in post_kill[post_kill.index(victim_idx):]
            ), post_kill
        finally:
            dc.stop()


def test_deliver_client_restart_while_draining():
    """Leadership flap regression (netharness finding): stop() while
    the runner is blocked inside a stream, then start() again — the old
    re-used stop flag left the client permanently wedged (start saw a
    live thread and returned; the live thread saw the stop flag and
    exited).  Generations fix it: the new start() must pull blocks even
    though the old runner is still draining."""
    import threading

    from fabric_tpu.peer.deliverclient import DeliverClient
    from fabric_tpu.protos.common import common_pb2

    release = threading.Event()
    delivered = []

    def blocking_connect(start_num):
        blk = common_pb2.Block()
        blk.header.number = start_num
        yield blk
        release.wait(20)  # the runner is stuck mid-stream here

    dc = DeliverClient(
        "ch", [blocking_connect],
        height_fn=lambda: len(delivered),
        sink=lambda seq, raw: delivered.append(seq),
    )
    dc.start()
    _wait(lambda: len(delivered) >= 1, msg="first delivery")
    dc.stop()  # join times out: the runner is blocked in release.wait
    old_thread = dc._thread
    assert old_thread.is_alive()
    dc.start()  # must arm a NEW generation, not no-op against the old
    try:
        _wait(
            lambda: len(delivered) >= 2, timeout=10,
            msg="new generation delivers despite the draining old one",
        )
    finally:
        release.set()
        dc.stop()
        old_thread.join(timeout=5)


# ---------------------------------------------------------------------------
# tier-1: kill -9 mid-_flush_group in a child process, real recovery
# ---------------------------------------------------------------------------


def test_child_kill9_mid_flush_group_recovers(tmp_path):
    import flush_worker as fw

    from fabric_tpu.ledger import LedgerProvider

    root = str(tmp_path / "ledger-root")
    status = str(tmp_path / "status")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    # hold each group flush open: a 0.15s delay at every fsync makes
    # "mid-_flush_group" the overwhelmingly likely place for the
    # SIGKILL below to land
    env["FABRIC_TPU_FAULTLINE"] = json.dumps({
        "seed": 1,
        "faults": [{
            "point": "commit.stage", "ctx": {"stage": "fsync"},
            "action": "delay", "delay_s": 0.15, "every": 1,
            "count": 1000000,
        }],
    })
    proc = subprocess.Popen(
        [sys.executable, FLUSH_WORKER, root, status, "3", "200"],
        env=env,
        stdout=open(str(tmp_path / "worker.log"), "ab"),
        stderr=subprocess.STDOUT,
    )
    try:
        _wait(
            lambda: os.path.exists(status)
            and int(open(status).read() or 0) >= 4,
            timeout=60, msg="child reaches durable height 4",
        )
        # land inside the NEXT flush's widened fsync window
        time.sleep(0.08)
        proc.kill()
        proc.wait(timeout=10)
        assert proc.returncode == -signal.SIGKILL
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    # REAL recovery: reopen the kill -9'd stores in this process
    provider = LedgerProvider(root)
    ledger = provider.open(fw.CHANNEL)
    height = ledger.height
    assert height >= 4
    writes_by_block = [[]] + [
        fw.block_writes(n) for n in range(1, height + 8)
    ]
    violations = invariants.check_ledger(
        ledger, writes_by_block=writes_by_block
    )
    assert violations == [], [str(v) for v in violations]
    # continuation: the recovered ledger accepts the next block
    blk = fw.build_block(height, ledger.block_store.last_block_hash)
    ledger.commit(blk)
    assert ledger.height == height + 1
    assert invariants.check_chain(ledger) == []
    provider.close()


# ---------------------------------------------------------------------------
# tier-1: gossip TCP wire token (trace-merge satellite)
# ---------------------------------------------------------------------------


def test_gossip_tcp_trace_token():
    from fabric_tpu.common import tracing
    from fabric_tpu.gossip.comm import (
        TCPGossipComm,
        _frame_with_token,
        _split_frame_token,
    )
    from fabric_tpu.protos.gossip import message_pb2 as gpb

    # helper contract: untraced frames are byte-identical, tokens strip
    raw = b"\x0a\x05hello"
    assert _frame_with_token(raw, None) is raw
    ctx = tracing.SpanContext(0xABC, 0x1)
    framed = _frame_with_token(raw, ctx)
    payload, parsed = _split_frame_token(framed)
    assert payload == raw and parsed == ctx
    assert _split_frame_token(raw) == (raw, None)

    a = TCPGossipComm(("127.0.0.1", 0), b"nodeA")
    b = TCPGossipComm(("127.0.0.1", 0), b"nodeB")
    try:
        with tracing.scope() as rec:
            with tracing.span("disseminate") as root:
                root_trace = root.trace_id
                msg = gpb.GossipMessage(channel=b"tch")
                msg.data_msg.seq_num = 1
                msg.data_msg.block = b"blockbytes"
                a.send(b.endpoint, msg)
            _wait(
                lambda: any(
                    ev.get("name") == "gossip.deliver"
                    and ev["args"].get("trace") == f"{root_trace:x}"
                    for ev in rec.snapshot()
                ),
                timeout=10,
                msg="remote dispatch joins the sender's trace",
            )
            deliver = next(
                ev for ev in rec.snapshot()
                if ev.get("name") == "gossip.deliver"
                and ev["args"].get("trace") == f"{root_trace:x}"
            )
            # nested under the sender's span, not a fresh root
            assert "parent" in deliver["args"]
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# tier-1: GET /traces?since= incremental cursor
# ---------------------------------------------------------------------------


def test_traces_since_cursor():
    import http.client

    from fabric_tpu.common import tracing
    from fabric_tpu.common.operations import System

    sysm = System(("127.0.0.1", 0))
    sysm.start()
    try:
        with tracing.scope():
            with tracing.span("first"):
                pass

            def get(path):
                conn = http.client.HTTPConnection(*sysm.addr, timeout=5)
                conn.request("GET", path)
                resp = conn.getresponse()
                body = resp.read()
                conn.close()
                return resp.status, json.loads(body)

            status, doc = get("/traces")
            assert status == 200
            assert [e["name"] for e in doc["traceEvents"]] == ["first"]
            cursor = doc["otherData"]["last_event_id"]
            assert cursor == 1

            with tracing.span("second"):
                pass
            status, doc2 = get(f"/traces?since={cursor}")
            assert status == 200
            assert [e["name"] for e in doc2["traceEvents"]] == ["second"]
            assert doc2["otherData"]["last_event_id"] == 2
            # a fresh cursor poll with nothing new is empty
            status, doc3 = get("/traces?since=2")
            assert doc3["traceEvents"] == []
            # a cursor from BEFORE a recorder reset is stale: the
            # endpoint detects it (ahead of the fresh cursor) and
            # answers with the full buffer so the poller resyncs
            tracing.reset()
            with tracing.span("post-reset"):
                pass
            status, doc4 = get("/traces?since=2")
            assert [e["name"] for e in doc4["traceEvents"]] == [
                "post-reset"
            ]
            assert doc4["otherData"]["last_event_id"] == 1
            # malformed cursor: a clean 400, not a server error
            status, err = get("/traces?since=banana")
            assert status == 400 and "error" in err
    finally:
        sysm.stop()


# ---------------------------------------------------------------------------
# tier-1: runtime ⊆ static (v6 rpc-conformance cross-check)
# ---------------------------------------------------------------------------


def test_runtime_rpc_methods_subset_of_static_rpcmap(tmp_path):
    """v6 runtime ⊆ static contract, RPC plane: every method a live
    traced session actually exercised — client-side ``rpc.call``/
    ``rpc.stream``/``rpc.duplex`` spans in the harness process, plus
    ``rpc.serve`` spans pulled from every node's flight recorder —
    must appear in the static ``--rpcmap`` artifact.  An observed
    method missing from the map means the rpc-conformance scan lost a
    call or register site, which this pins with a real network run
    rather than a fixture."""
    from fabric_tpu.common import tracing
    from fabric_tpu.devtools.lint import lint_tree

    topo = nh.Topology(
        orgs=1, peers_per_org=1, orderers=1, seed=3, trace=4096,
    )
    rpc_span_names = {"rpc.call", "rpc.stream", "rpc.duplex", "rpc.serve"}
    observed: set[str] = set()

    def harvest(doc):
        for ev in doc.get("traceEvents", []):
            if ev.get("name") in rpc_span_names:
                m = ev.get("args", {}).get("method")
                if m:
                    observed.add(m)

    with tracing.scope(4096) as rec:
        with nh.Network(str(tmp_path / "net"), topo) as net:
            net.start()
            result = nh.run_stream(net, txs=10, settle_timeout_s=120)
            for name in topo.peer_names() + topo.orderer_names():
                harvest(net.trace_dump(name))
        harvest(tracing.export(rec))
    assert result["ok"], result

    # the ⊆ must not be vacuous: the session exercised both the
    # consensus path (broadcast) and the harness control plane
    assert "ab.Broadcast" in observed, sorted(observed)
    assert "net.TraceDump" in observed, sorted(observed)
    assert any(m.startswith("net.") for m in observed)

    static = set(lint_tree().rpcmap()["methods"])
    assert observed <= static, (
        "runtime RPC methods missing from static rpcmap: "
        f"{sorted(observed - static)}"
    )


# ---------------------------------------------------------------------------
# slow soak: 3 orgs × 2 peers × 3 orderers, seeded schedule, verdict
# byte-determinism
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_soak_multiorg_seeded_schedule(tmp_path):
    topo = nh.Topology(
        orgs=3, peers_per_org=2, orderers=3, seed=11,
        max_message_count=8,
    )
    txs = 240
    expected_height = 1 + -(-txs // topo.max_message_count)
    schedule = nh.generate_kill_schedule(
        11, topo, expected_height, kills=2
    )
    assert any(r.node.startswith("orderer") for r in schedule)
    with nh.Network(str(tmp_path / "net"), topo) as net:
        net.start(timeout=120)
        result = nh.run_stream(
            net, txs=txs, kill_schedule=schedule, settle_timeout_s=240,
        )
    assert result["errors"] == []
    assert result["ok"], result
    assert result["state_digests_agree"]
    assert len(set(
        h for n, h in result["heights"].items()
    )) == 1

    # byte-determinism of the verdict JSON for this fixed seed: the
    # verdict must be reconstructable from (seed, topology, schedule,
    # pass) alone — no timings, no throughput, no run-specific state
    verdict_bytes = json.dumps(
        nh.verdict_doc(result), sort_keys=True
    ).encode()
    expected = {
        "experiment": "netharness",
        "rpcmap_sha256": nh.rpcmap_hash(),
        "seed": 11,
        "topology": topo.as_dict(),
        "kill_schedule": [r.as_dict() for r in schedule],
        "txs": txs,
        "ok": True,
        "state_digests_agree": True,
        "stalled_nodes": [],
        "violations": {},
        "missing": [],
        "caught_up": sorted({r.node for r in schedule}),
        "partition_schedule": [],
        "partition_checks": [],
        "healed_caught_up": [],
    }
    assert verdict_bytes == json.dumps(expected, sort_keys=True).encode()


# ---------------------------------------------------------------------------
# network partitions (PR 20): schedule generation, split/heal judging,
# repro routing, and byte-deterministic verdicts
# ---------------------------------------------------------------------------


def test_partition_schedule_generation_deterministic():
    topo = nh.Topology(orgs=3, peers_per_org=2, orderers=3, seed=19)
    a = nh.generate_partition_schedule(19, topo, 40)
    b = nh.generate_partition_schedule(19, topo, 40)
    assert [r.as_dict() for r in a] == [r.as_dict() for r in b]
    (rule,) = a
    assert rule.mode in ("full", "oneway", "flaky")
    # the groups partition EVERY node: each appears in exactly one
    names = sorted(topo.orderer_names() + topo.peer_names())
    assert sorted(n for g in rule.groups for n in g) == names
    # the minority side breaks raft quorum but the majority keeps it
    minority = rule.groups[1]
    n_min_ord = sum(1 for n in minority if n.startswith("orderer"))
    assert 0 < n_min_ord <= (topo.orderers - 1) // 2
    assert nh.PartitionRule.from_dict(rule.as_dict()).as_dict() \
        == rule.as_dict()


def test_smoke_netsplit_majority_minority(tmp_path):
    topo = nh.Topology(
        orgs=2, peers_per_org=1, orderers=3, seed=13,
        max_message_count=5,
    )
    pschedule = [nh.PartitionRule(
        groups=[["orderer1", "orderer2", "org1-peer0"],
                ["orderer3", "org2-peer0"]],
        at_height=3, mode="full", heal_after_s=2.5,
    )]
    with nh.Network(str(tmp_path / "net"), topo) as net:
        net.start()
        result = nh.run_stream(
            net, txs=400, partition_schedule=pschedule,
            settle_timeout_s=180,
        )
    assert result["errors"] == []
    assert result["ok"], result
    (pc,) = result["partition_checks"]
    assert pc["violations"] == []
    # the minority (quorum-broken raft side) stalled WITHOUT forking
    assert pc["minority_stalled"]
    assert not pc["minority_forked"]
    assert pc["majority_progressed"]
    if not pc["quiesced"]:
        # a genuine mid-stream split: the majority orderers committed
        # past the split tip while the severed side stayed pinned
        heights = pc["pre_heal"]["heights"]
        assert max(
            heights[n] for n in pc["majority"]
            if n.startswith("orderer")
        ) > pc["split_tip"]
    # both severed nodes rejoined and caught up after the heal
    assert set(result["heal_catch_up_s"]) == {"orderer3", "org2-peer0"}
    # everyone converged on one chain after the heal
    assert result["state_digests_agree"]
    assert len(set(result["heights"].values())) == 1
    # byte-determinism: a passing verdict is reconstructable from
    # (seed, topology, schedules, pass) alone
    expected = {
        "experiment": "netharness",
        "rpcmap_sha256": nh.rpcmap_hash(),
        "seed": 13,
        "topology": topo.as_dict(),
        "kill_schedule": [],
        "txs": 400,
        "ok": True,
        "state_digests_agree": True,
        "stalled_nodes": [],
        "violations": {},
        "missing": [],
        "caught_up": [],
        "partition_schedule": [r.as_dict() for r in pschedule],
        "partition_checks": [{
            "rule": pschedule[0].as_dict(),
            "majority": ["orderer1", "orderer2", "org1-peer0"],
            "minority": ["orderer3", "org2-peer0"],
            "majority_progressed": True,
            "minority_stalled": True,
            "minority_forked": False,
            "violations": [],
        }],
        "healed_caught_up": ["orderer3", "org2-peer0"],
    }
    assert json.dumps(nh.verdict_doc(result), sort_keys=True) \
        == json.dumps(expected, sort_keys=True)


def test_write_repro_routes_netsplit_kind(tmp_path):
    base = {
        "seed": 5,
        "topology": nh.Topology(seed=5).as_dict(),
        "kill_schedule": [],
        "txs": 10,
        "ok": False,
        "state_digests_agree": True,
        "stalled_nodes": [],
        "violations": {},
        "missing": [],
        "catch_up_s": {},
        "partition_checks": [],
        "heal_catch_up_s": {},
    }
    rule = nh.PartitionRule(groups=[["a"], ["b"]], at_height=2)
    p1 = str(tmp_path / "ns.repro.json")
    nh.write_repro({**base, "partition_schedule": [rule.as_dict()]}, p1)
    with open(p1, encoding="utf-8") as f:
        doc = json.load(f)
    assert doc["kind"] == "netharness-netsplit"
    assert doc["partition_schedule"] == [rule.as_dict()]
    p2 = str(tmp_path / "k9.repro.json")
    nh.write_repro({**base, "partition_schedule": []}, p2)
    with open(p2, encoding="utf-8") as f:
        assert json.load(f)["kind"] == "netharness-kill9"


@pytest.mark.slow
def test_soak_netsplit_same_seed_byte_identical_verdict(tmp_path):
    topo = nh.Topology(
        orgs=2, peers_per_org=2, orderers=3, seed=23,
        max_message_count=8,
    )
    txs = 240
    expected_height = 1 + -(-txs // topo.max_message_count)
    verdicts = []
    for run in ("a", "b"):
        pschedule = nh.generate_partition_schedule(
            23, topo, expected_height
        )
        with nh.Network(str(tmp_path / f"net-{run}"), topo) as net:
            net.start(timeout=120)
            result = nh.run_stream(
                net, txs=txs, partition_schedule=pschedule,
                settle_timeout_s=240,
            )
        assert result["errors"] == []
        assert result["ok"], result
        verdicts.append(
            json.dumps(nh.verdict_doc(result), sort_keys=True).encode()
        )
    assert verdicts[0] == verdicts[1]
