"""Endorsement-descriptor computation.

Reference: discovery/endorsement/endorsement.go:164 (endorsementAnalyzer)
and :424-470 — build a bipartite principal<->peer mapping, enumerate the
policy's principal satisfaction sets (inquire), intersect with live
membership, and emit layouts: per satisfaction set, how many endorsements
are needed from each principal-group of peers.

Collection filtering: when the call touches collections, a peer must be a
member of EVERY named collection to endorse (reference
principalsFromCollectionConfig)."""

from __future__ import annotations

import dataclasses

from fabric_tpu.discovery.inquire import satisfaction_sets
from fabric_tpu.protos.discovery import protocol_pb2 as dpb


@dataclasses.dataclass
class PeerInfo:
    endpoint: str
    identity: bytes  # serialized identity
    mspid: str
    ledger_height: int = 0
    chaincodes: tuple[str, ...] = ()


def _peers_for_principal(principal, peers, deserializer):
    """Endpoints of peers whose identity satisfies the principal."""
    out = []
    for p in peers:
        try:
            ident = deserializer.deserialize_identity(p.identity)
            deserializer.satisfies_principal(ident, principal)
        except Exception:
            continue
        out.append(p)
    return out


def compute_descriptor(
    chaincode: str,
    policy_envelope,
    peers: list[PeerInfo],
    deserializer,
    collection_filter=None,  # callable(peer) -> bool, pre-filters peers
) -> dpb.EndorsementDescriptor:
    """Build the EndorsementDescriptor (groups + layouts) or raise
    ValueError when no layout is satisfiable by live peers."""
    if collection_filter is not None:
        peers = [p for p in peers if collection_filter(p)]
    principals = list(policy_envelope.identities)
    sets = satisfaction_sets(policy_envelope)
    if not sets:
        raise ValueError(f"policy of {chaincode} has no satisfaction sets")

    # group per principal index: Gk -> peers satisfying principal k
    group_peers: dict[int, list[PeerInfo]] = {
        k: _peers_for_principal(principals[k], peers, deserializer)
        for k in range(len(principals))
    }

    desc = dpb.EndorsementDescriptor(chaincode=chaincode)
    used_groups: set[int] = set()
    n_layouts = 0
    for s in sets:
        # quantity per principal in this satisfaction set
        quantities: dict[int, int] = {}
        for idx in s:
            quantities[idx] = quantities.get(idx, 0) + 1
        # feasible only if each group has enough live peers
        if any(
            len(group_peers.get(idx, [])) < q
            for idx, q in quantities.items()
        ):
            continue
        layout = desc.layouts.add()
        for idx, q in quantities.items():
            layout.quantities_by_group[f"G{idx}"] = q
            used_groups.add(idx)
        n_layouts += 1
    if n_layouts == 0:
        raise ValueError(
            f"no endorsement layout of {chaincode} is satisfiable by the "
            "current membership"
        )
    for idx in sorted(used_groups):
        grp = desc.endorsers_by_groups[f"G{idx}"]
        for p in group_peers[idx]:
            grp.peers.add(
                identity=p.identity,
                endpoint=p.endpoint,
                ledger_height=p.ledger_height,
                chaincodes=list(p.chaincodes),
            )
    return desc


__all__ = ["PeerInfo", "compute_descriptor"]
