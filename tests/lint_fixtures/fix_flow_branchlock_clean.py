"""Clean twin of fix_flow_branchlock_dirty: the explicit
acquire/try/finally-release straddles the write on EVERY path — no
``with`` statement, so only the flow-sensitive lockset (must-hold meet
over paths) can prove the critical section and stay quiet."""

import threading

from fabric_tpu.devtools.lockwatch import spawn_thread


class TallyBoard:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._stop = threading.Event()

    def serve(self):
        t = spawn_thread(
            target=self._run, name="tally", kind="service"
        )
        t.start()
        return t

    def stop(self):
        self._stop.set()

    def _run(self):
        while not self._stop.is_set():
            self.bump()

    def bump(self):
        self._lock.acquire()
        try:
            self._count += 1  # held on every path: proven quiet
        finally:
            self._lock.release()

    def read(self):
        with self._lock:
            return self._count

    def reset(self):
        with self._lock:
            self._count = 0
