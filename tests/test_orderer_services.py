"""Registrar + broadcast + deliver service tests (reference
orderer/common/multichannel, broadcast, common/deliver test strategy:
in-process fakes, real block stores)."""

import threading
import time

import pytest

from fabric_tpu.common.deliver import DeliverService, make_seek_info_envelope
from fabric_tpu.orderer.broadcast import BroadcastHandler
from fabric_tpu.orderer.multichannel import Registrar
from fabric_tpu.protos.common import common_pb2
from fabric_tpu.protos.orderer import ab_pb2
from fabric_tpu import protoutil

from fabric_tpu.common import configtx_builder as ctx
from fabric_tpu.msp import msp_config_from_ca

from orgfix import make_org


class _OrgSetup:
    def __init__(self):
        self.org1 = make_org("Org1MSP")
        oorg = make_org("OrdererMSP")
        app = ctx.application_group(
            {"Org1": ctx.org_group("Org1MSP", msp_config_from_ca(self.org1.ca, "Org1MSP"))}
        )
        ordg = ctx.orderer_group(
            {
                "OrdererOrg": ctx.org_group(
                    "OrdererMSP", msp_config_from_ca(oorg.ca, "OrdererMSP")
                )
            },
            consensus_type="solo",
            max_message_count=2,
            batch_timeout="250ms",
        )
        self.channel_id = "testchannel"
        self.genesis = ctx.genesis_block(
            self.channel_id, ctx.channel_group(app, ordg)
        )
        self.csp = self.org1.csp
        self.admin = self.org1.signer("admin", role_ou="admin")


@pytest.fixture(scope="module")
def org():
    return _OrgSetup()


@pytest.fixture
def registrar(org, tmp_path):
    reg = Registrar(str(tmp_path), org.csp)
    reg.startup([org.genesis])
    yield reg
    reg.halt_all()


def _tx_env(org, data: bytes) -> common_pb2.Envelope:
    chdr = protoutil.make_channel_header(
        common_pb2.ENDORSER_TRANSACTION, channel_id=org.channel_id
    )
    shdr = protoutil.make_signature_header(
        org.admin.serialize(), protoutil.random_nonce()
    )
    payload = common_pb2.Payload(data=data)
    payload.header.channel_header = chdr.SerializeToString()
    payload.header.signature_header = shdr.SerializeToString()
    raw = payload.SerializeToString()
    return common_pb2.Envelope(payload=raw, signature=org.admin.sign(raw))


def test_broadcast_orders_into_blocks(registrar, org):
    h = BroadcastHandler(registrar)
    cs = registrar.get_chain(org.channel_id)
    notifier_fired = threading.Event()
    registrar.add_block_listener(lambda ch, blk: notifier_fired.set())
    for i in range(3):
        assert h.process_message(_tx_env(org, b"d%d" % i)) == common_pb2.SUCCESS
    deadline = time.monotonic() + 10
    while cs.store.height < 2 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert cs.store.height >= 2
    assert notifier_fired.is_set()


def test_broadcast_unknown_channel(registrar, org):
    h = BroadcastHandler(registrar)
    chdr = protoutil.make_channel_header(
        common_pb2.ENDORSER_TRANSACTION, channel_id="no-such-channel"
    )
    payload = common_pb2.Payload(data=b"x")
    payload.header.channel_header = chdr.SerializeToString()
    env = common_pb2.Envelope(payload=payload.SerializeToString())
    assert h.process_message(env) == common_pb2.NOT_FOUND


def test_broadcast_rejects_unsigned(registrar, org):
    h = BroadcastHandler(registrar)
    chdr = protoutil.make_channel_header(
        common_pb2.ENDORSER_TRANSACTION, channel_id=org.channel_id
    )
    shdr = protoutil.make_signature_header(b"not-an-identity", b"nonce")
    payload = common_pb2.Payload(data=b"x")
    payload.header.channel_header = chdr.SerializeToString()
    payload.header.signature_header = shdr.SerializeToString()
    env = common_pb2.Envelope(payload=payload.SerializeToString())
    assert h.process_message(env) == common_pb2.FORBIDDEN


def test_deliver_streams_existing_and_new_blocks(registrar, org):
    h = BroadcastHandler(registrar)
    svc = DeliverService(registrar.get_chain, org.csp)
    registrar.add_block_listener(lambda ch, blk: svc.notifier.notify())
    for i in range(3):
        h.process_message(_tx_env(org, b"d%d" % i))
    cs = registrar.get_chain(org.channel_id)
    deadline = time.monotonic() + 10
    while cs.store.height < 2 and time.monotonic() < deadline:
        time.sleep(0.02)

    env = make_seek_info_envelope(
        org.channel_id, 0, cs.store.height - 1, signer=org.admin,
        behavior=ab_pb2.SeekInfo.FAIL_IF_NOT_READY,
    )
    events = list(svc.deliver(env))
    kinds = [k for k, _ in events]
    assert kinds[-1] == "status" and events[-1][1] == common_pb2.SUCCESS
    blocks = [b for k, b in events if k == "block"]
    assert [b.header.number for b in blocks] == list(range(cs.store.height))
    assert blocks[0].header.number == 0  # genesis


def test_deliver_block_until_ready_waits(registrar, org):
    svc = DeliverService(registrar.get_chain, org.csp)
    registrar.add_block_listener(lambda ch, blk: svc.notifier.notify())
    h = BroadcastHandler(registrar)
    got: list = []

    def consume():
        env = make_seek_info_envelope(org.channel_id, 1, 1, signer=org.admin)
        for kind, item in svc.deliver(env):
            got.append((kind, item))

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    time.sleep(0.2)
    assert not got  # waiting for block 1
    for i in range(3):
        h.process_message(_tx_env(org, b"w%d" % i))
    t.join(timeout=10)
    assert got and got[0][0] == "block" and got[0][1].header.number == 1


def test_deliver_forbidden_without_signature(registrar, org):
    svc = DeliverService(registrar.get_chain, org.csp)
    env = make_seek_info_envelope(org.channel_id, 0, 0, signer=None)
    events = list(svc.deliver(env))
    assert events == [("status", common_pb2.FORBIDDEN)]


def test_deliver_unknown_channel(registrar, org):
    svc = DeliverService(registrar.get_chain, org.csp)
    env = make_seek_info_envelope("ghost", 0, 0, signer=org.admin)
    assert list(svc.deliver(env)) == [("status", common_pb2.NOT_FOUND)]
