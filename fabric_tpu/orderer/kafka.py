"""Kafka consenter (legacy CFT path; reference orderer/consensus/kafka).

The reference orders a channel by publishing wrapped messages to one
Kafka topic partition and replaying the partition in offset order
(chain.go processMessagesToBlocks): REGULAR messages feed the block
cutter, a TIME-TO-CUT message (posted when the batch timer fires) cuts
the pending batch so every orderer cuts at the same offset, and CONNECT
probes establish liveness.  The partition is the ordering oracle — the
consenter itself is deterministic replay.

`Partition` is the broker seam: the in-process implementation stands in
for a Kafka topic partition exactly the way integration/nwo stands up
Kafka in a container; a real broker client can implement the same
append/consume surface.  Deprecated in the reference in favor of Raft —
kept for capability parity.
"""

from __future__ import annotations

import json
import threading

from fabric_tpu.devtools.lockwatch import (
    named_condition,
    spawn_thread,
    spawn_timer,
)

from fabric_tpu.orderer.blockcutter import BlockCutter
from fabric_tpu.orderer.blockwriter import BlockWriter


class Partition:
    """An append-only, offset-addressed message log (one topic
    partition).  Thread-safe; consumers poll from any offset."""

    def __init__(self):
        self._log: list[bytes] = []
        self._cond = named_condition("kafka.partition")

    def append(self, msg: bytes) -> int:
        with self._cond:
            self._log.append(msg)
            self._cond.notify_all()
            return len(self._log) - 1

    def get(self, offset: int, timeout: float = 0.25) -> bytes | None:
        with self._cond:
            if offset >= len(self._log):
                self._cond.wait(timeout)
            if offset < len(self._log):
                return self._log[offset]
            return None


class InProcBroker:
    """Partition registry keyed by channel (the dev/test 'cluster').
    Pass ONE broker instance to every replica of a network — there is
    deliberately no process-global default, so unrelated registrars in
    one process can never cross-consume each other's channels."""

    def __init__(self):
        self._parts: dict[str, Partition] = {}
        self._lock = threading.Lock()

    def partition(self, channel_id: str) -> Partition:
        with self._lock:
            return self._parts.setdefault(channel_id, Partition())


def _wrap(kind: str, payload: bytes = b"", block_number: int = 0) -> bytes:
    return json.dumps(
        {
            "type": kind,
            "payload": payload.hex(),
            "block_number": block_number,
        }
    ).encode()


_ORDERER_METADATA_INDEX = 3  # common.BlockMetadataIndex.ORDERER


def _persisted_offset(last_block) -> int:
    """Offset after the last consumed message, from block metadata."""
    if last_block is None:
        return 0
    md = last_block.metadata.metadata
    if len(md) > _ORDERER_METADATA_INDEX and md[_ORDERER_METADATA_INDEX]:
        try:
            return json.loads(md[_ORDERER_METADATA_INDEX])["next_offset"]
        except Exception:
            return 0
    return 0


class KafkaChain:
    """Consenter replaying a partition in offset order (reference
    kafka/chain.go).  Multiple orderers on the same partition write
    identical chains."""

    def __init__(
        self,
        channel_id: str,
        cutter: BlockCutter,
        writer: BlockWriter,
        broker: InProcBroker,
        batch_timeout_s: float = 2.0,
        on_block=None,
        start_offset: int | None = None,
    ):
        if broker is None:
            raise ValueError("kafka consenter requires a broker")
        self._partition = broker.partition(channel_id)
        self._cutter = cutter
        self._writer = writer
        self._timeout = batch_timeout_s
        self._on_block = on_block or (lambda blk: None)
        # resume from the offset persisted in the last block's ORDERER
        # metadata (reference: lastOffsetPersisted in Kafka metadata),
        # so a restart over an existing ledger does not replay txs
        if start_offset is None:
            start_offset = _persisted_offset(writer.last_block())
        self._offset = start_offset
        self._halted = threading.Event()
        self._timer: threading.Timer | None = None
        # the block number the next TIME-TO-CUT refers to; replicas on
        # the same partition starting from the same height agree
        self._pending_block = writer.height
        self._lock = threading.Lock()
        self._thread = spawn_thread(
            target=self._run, name="kafka-consenter", kind="service"
        )

    # -- consensus SPI -----------------------------------------------------

    def start(self) -> None:
        self._partition.append(_wrap("connect"))
        self._thread.start()

    def halt(self) -> None:
        self._halted.set()
        self._thread.join(timeout=5)
        self._cancel_timer()

    def wait_ready(self) -> None:
        return

    def set_batch_timeout(self, seconds: float) -> None:
        """Adopt a committed BatchTimeout config change."""
        self._timeout = seconds

    def order(self, env, config_seq: int = 0) -> None:
        if self._halted.is_set():
            raise RuntimeError("chain is halted")
        self._partition.append(_wrap("normal", env.SerializeToString()))

    def configure(self, env, config_seq: int = 0) -> None:
        if self._halted.is_set():
            raise RuntimeError("chain is halted")
        self._partition.append(_wrap("config", env.SerializeToString()))

    # -- partition replay --------------------------------------------------

    def _arm_timer(self) -> None:
        with self._lock:
            if self._timer is None:
                block_number = self._pending_block
                self._timer = spawn_timer(
                    self._timeout,
                    lambda: self._partition.append(
                        _wrap("timetocut", block_number=block_number)
                    ),
                    name="kafka-batch-timer",
                )
                self._timer.start()

    def _cancel_timer(self) -> None:
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None

    def _emit(self, batch: list[bytes], is_config: bool = False) -> None:
        if not batch:
            return
        blk = self._writer.create_next_block(batch)
        while len(blk.metadata.metadata) <= _ORDERER_METADATA_INDEX:
            blk.metadata.metadata.append(b"")
        blk.metadata.metadata[_ORDERER_METADATA_INDEX] = json.dumps(
            {"next_offset": self._offset}
        ).encode()
        self._writer.write_block(blk, is_config=is_config)
        self._pending_block += 1
        self._on_block(blk)

    def _run(self) -> None:
        while not self._halted.is_set():
            raw = self._partition.get(self._offset)
            if raw is None:
                continue
            self._offset += 1
            msg = json.loads(raw)
            kind = msg["type"]
            if kind == "connect":
                continue
            if kind == "timetocut":
                # every replica cuts at the same offset; stale TTCs (for
                # an already-cut block) are ignored (chain.go:TTC check)
                if msg["block_number"] == self._pending_block:
                    self._cancel_timer()
                    self._emit(self._cutter.cut())
                continue
            payload = bytes.fromhex(msg["payload"])
            if kind == "config":
                self._cancel_timer()
                self._emit(self._cutter.cut())
                self._emit([payload], is_config=True)
                continue
            batches, pending = self._cutter.ordered(payload)
            for batch in batches:
                self._cancel_timer()
                self._emit(batch)
            if pending:
                self._arm_timer()


__all__ = ["KafkaChain", "InProcBroker", "Partition"]
