"""Snapshot-serving RPC (ISSUE 11 satellite): ``admin.SnapshotFetch``
streams a completed snapshot directory from a remote peer so
join-by-snapshot works WITHOUT shared disk.  Integrity rides entirely
on verify-on-import: a torn stream (cut by the ``snapshot.fetch.chunk``
faultline seam) leaves a partial directory that verification — and
therefore ``create_from_snapshot`` — must refuse."""

from __future__ import annotations

import json
import os

import pytest

from fabric_tpu import protoutil
from fabric_tpu.comm import RPCClient, RPCError, RPCServer
from fabric_tpu.devtools import faultline, netident
from fabric_tpu.ledger import LedgerProvider, snapshot as snap
from fabric_tpu.protos.common import common_pb2

CHANNEL = "fetchch"


def _commit_blocks(ledger, n_blocks: int) -> None:
    prev = ledger.block_store.last_block_hash
    for n in range(ledger.height, n_blocks + 1):
        envs = [
            netident.make_tx(
                CHANNEL, f"b{n}k{i}", f"v{n}:{i}".encode(), orgs=1
            )
            for i in range(2)
        ]
        blk = common_pb2.Block()
        blk.header.number = n
        blk.header.previous_hash = prev
        blk.data.data.extend(envs)
        blk.header.data_hash = protoutil.block_data_hash(blk.data)
        protoutil.init_block_metadata(blk)
        protoutil.set_tx_filter(blk, bytearray(len(envs)))
        ledger.commit(blk)
        prev = protoutil.block_header_hash(blk.header)


@pytest.fixture
def served_snapshot(tmp_path):
    """A provider with a completed snapshot, served over a real RPC
    server speaking admin.SnapshotFetch."""
    provider = LedgerProvider(str(tmp_path / "donor"))
    ledger = provider.create(netident.make_genesis(CHANNEL))
    _commit_blocks(ledger, 6)
    res = ledger.snapshots.submit_request(0)
    sdir = res["snapshot_dir"]
    assert sdir and os.path.isdir(sdir)

    def fetch_handler(body: bytes, stream):
        req = json.loads(body.decode("utf-8"))
        return snap.stream_snapshot_dir(snap.completed_snapshot_dir(
            provider.snapshots_root, req["channel"],
            int(req["block_number"]),
        ))

    srv = RPCServer("127.0.0.1", 0)
    srv.register("admin.SnapshotFetch", fetch_handler)
    srv.start()
    height = res["block_number"]
    yield srv.addr, height, sdir
    srv.stop()
    provider.close()


def test_fetch_then_join(tmp_path, served_snapshot):
    addr, height, sdir = served_snapshot
    client = RPCClient(*addr, timeout=10.0)
    dest = snap.fetch_snapshot(
        client, CHANNEL, height, str(tmp_path / "fetched")
    )
    # the fetched copy is byte-faithful: same file set, verification
    # recomputes every digest
    assert sorted(os.listdir(dest)) == sorted(os.listdir(sdir))
    meta = snap.verify_snapshot(dest)
    assert meta["channel_id"] == CHANNEL
    # and a fresh provider joins from it, commit-ready at the height
    joiner = LedgerProvider(str(tmp_path / "joiner"))
    ledger = joiner.create_from_snapshot(dest)
    assert ledger.height == height + 1
    assert ledger.get_state("netcc", "b1k0") == b"v1:0"
    joiner.close()


def test_torn_stream_refused(tmp_path, served_snapshot):
    addr, height, _ = served_snapshot
    client = RPCClient(*addr, timeout=10.0)
    dest = str(tmp_path / "torn")
    # cut the transfer mid-way: the serving generator raises at its 3rd
    # chunk, the RPC stream surfaces ERR, the receiver is left partial
    with faultline.use_plan({"seed": 3, "faults": [{
        "point": "snapshot.fetch.chunk", "action": "raise", "nth": 3,
    }]}):
        with pytest.raises(RPCError):
            snap.fetch_snapshot(client, CHANNEL, height, dest)
    assert os.path.isdir(dest)  # partial files landed
    # verify-on-import is the integrity gate: the partial directory
    # must refuse verification AND join
    assert invariant_rejects(dest)
    joiner = LedgerProvider(str(tmp_path / "joiner"))
    with pytest.raises(snap.SnapshotError):
        joiner.create_from_snapshot(dest)
    joiner.close()


def invariant_rejects(snapshot_dir: str) -> bool:
    from fabric_tpu.devtools import invariants

    return invariants.check_snapshot_rejected(snapshot_dir) == []


def test_fetch_unknown_height_errors(served_snapshot):
    addr, height, _ = served_snapshot
    client = RPCClient(*addr, timeout=10.0)
    with pytest.raises(RPCError, match="no completed snapshot"):
        list(client.stream("admin.SnapshotFetch", json.dumps(
            {"channel": CHANNEL, "block_number": height + 100}
        ).encode()))
