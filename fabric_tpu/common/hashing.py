"""The host-side CSP hash seam — stdlib-only, importable everywhere.

fabriclint's csp-seam rule requires every SHA-256 call site outside
fabric_tpu/csp/ to route through here (or carry a reviewed pragma), so
new hashing stays VISIBLE to the batched providers.  The CSP factory
registers the process default provider via set_hash_backend at init;
until then (or on hosts without a configured CSP) hashlib produces the
identical digests.

This module deliberately imports NOTHING beyond hashlib: protoutil,
chaincode, and the ledger must stay importable on hosts without the
`cryptography` package (the cert/CA helpers that need it live in
common/crypto.py, which re-exports this seam).  The dependency points
csp -> common.hashing, never the reverse, so it stays import-cycle-free.
"""

from __future__ import annotations

import hashlib

_HASH_BACKEND = None


def set_hash_backend(csp) -> None:
    """Install the process CSP as the seam's backend (csp/factory.py
    calls this whenever the default provider is (re)initialized).

    The seam now feeds consensus-critical digests (tx ids, block header
    hashes, pvt key hashes), so a backend whose output is not
    byte-identical SHA-256 would silently fork this peer from the
    hashlib fallback — probe once at install time and fail fast.  The
    probes are tiny, so batched providers take their host fallback and
    no device compile is triggered here."""
    if csp is not None:
        probe = b"fabric-tpu hash seam probe"
        want = hashlib.sha256(probe).digest()
        if csp.hash(probe) != want or list(
            csp.hash_batch([probe, b""])
        ) != [want, hashlib.sha256(b"").digest()]:
            raise ValueError(
                f"refusing hash backend {type(csp).__name__}: its "
                "hash/hash_batch is not byte-identical SHA-256 — "
                "installing it would change tx ids and block hashes "
                "on this peer only"
            )
    global _HASH_BACKEND
    _HASH_BACKEND = csp


def sha256(data: bytes) -> bytes:
    """SHA-256 through the CSP seam: the configured provider's `hash`
    when one is installed, hashlib otherwise (identical digests)."""
    backend = _HASH_BACKEND
    if backend is not None:
        return backend.hash(data)
    return hashlib.sha256(data).digest()


def sha256_many(blobs) -> list[bytes]:
    """Batch SHA-256 through the CSP seam (`hash_batch` — ONE device
    call on the TPU provider); hashlib fallback host-side."""
    blobs = list(blobs)
    backend = _HASH_BACKEND
    if backend is not None:
        return list(backend.hash_batch(blobs))
    return [hashlib.sha256(b).digest() for b in blobs]


__all__ = ["set_hash_backend", "sha256", "sha256_many"]
