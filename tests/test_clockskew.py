"""Clock-skew seam tests (ISSUE 8 tentpole): the virtual clock drives
every timeout-bearing comm layer deterministically — backoff gates open
on clock jumps (including faultline ``skew`` rules), rpc idle windows
compress through io_timeout scaling, and the deliver client's whole
rotation/backoff cycle runs with no real sleeps."""

import socket
import threading
import time

import pytest

from fabric_tpu.comm.backoff import BackoffGate, DecorrelatedBackoff
from fabric_tpu.comm.rpc import KeepaliveOptions, RPCClient, RPCServer
from fabric_tpu.devtools import clockskew, faultline
from fabric_tpu.protos.common import common_pb2


# -- the provider contract ----------------------------------------------------


def test_system_clock_is_the_default():
    assert clockskew.installed() is None
    t0 = clockskew.monotonic()
    assert abs(t0 - time.monotonic()) < 1.0
    assert clockskew.io_timeout(30.0) == 30.0
    assert clockskew.io_timeout(None) is None


def test_virtual_clock_monotonic_never_regresses_wall_may():
    with clockskew.use_virtual(clockskew.VirtualClock(start=100.0,
                                                      wall=5000.0)) as clk:
        assert clockskew.monotonic() == 100.0
        clockskew.advance(-50.0)  # monotonic ignores the regression...
        assert clockskew.monotonic() == 100.0
        assert clockskew.wall() == 4950.0  # ...wall takes the NTP step
        clockskew.advance(10.0, wall_dt=-10.0)
        assert clockskew.monotonic() == 110.0
        assert clockskew.wall() == 4940.0
        # sleeps advance instead of blocking, and are recorded
        t0 = time.monotonic()
        clockskew.sleep(3600.0)
        assert time.monotonic() - t0 < 0.5
        assert clk.sleeps == [3600.0]
        assert clockskew.monotonic() == 3710.0
    assert clockskew.installed() is None  # restored on exit


def test_virtual_wait_advances_and_yields():
    ev = threading.Event()
    with clockskew.use_virtual() as clk:
        t0 = time.monotonic()
        assert clockskew.wait(ev, 30.0) is False
        assert time.monotonic() - t0 < 0.5
        assert clk.sleeps == [30.0]
        ev.set()
        assert clockskew.wait(ev, 30.0) is True
        assert clk.sleeps == [30.0]  # a set event consumes no time


def test_io_timeout_scaling_floors_at_10ms():
    with clockskew.use_virtual(
        clockskew.VirtualClock(timeout_scale=0.005)
    ):
        assert clockskew.io_timeout(30.0) == pytest.approx(0.15)
        assert clockskew.io_timeout(0.5) == pytest.approx(0.01)
        assert clockskew.io_timeout(None) is None


# -- backoff gate -------------------------------------------------------------


def test_backoff_gate_opens_on_clock_jump_not_real_time():
    with clockskew.use_virtual():
        gate = BackoffGate.for_key("node-a->peer:7050", base=0.5, cap=2.0)
        assert gate.ready()  # never armed
        wait = gate.arm()
        assert 0.5 <= wait <= 2.0
        assert not gate.ready()  # window armed, clock frozen
        clockskew.advance(wait / 2)
        assert not gate.ready()
        clockskew.advance(wait)  # past the window
        assert gate.ready()
        gate.arm()
        gate.clear()  # successful dial: window closes, jitter keeps going
        assert gate.ready()


def test_backoff_gate_reset_replays_jitter_sequence():
    b = DecorrelatedBackoff(base=0.05, cap=1.0, seed=9)
    gate = BackoffGate(b)
    with clockskew.use_virtual():
        first = [gate.arm() for _ in range(5)]
        gate.reset()
        assert [gate.arm() for _ in range(5)] == first
        assert gate.ready() is False  # the last arm left a window
        gate.reset()
        assert gate.ready()


def test_faultline_skew_rule_opens_backoff_gate():
    """A plan-injected clock jump at a fault point deterministically
    ends a backoff window — no sleeps, no monkeypatching."""
    with clockskew.use_virtual():
        gate = BackoffGate.for_key("x->y", base=0.5, cap=2.0)
        gate.arm()
        assert not gate.ready()
        with faultline.use_plan({"faults": [
            {"point": "test.skew", "action": "skew", "skew_s": 60.0},
        ]}):
            faultline.point("test.skew")
            [trip] = faultline.trips()
            assert trip["action"] == "skew"
            assert gate.ready()  # the 60s jump swallowed the window


# -- rpc idle reaping under a compressed clock --------------------------------


def test_rpc_idle_timeout_reaps_in_compressed_time():
    """A connected-but-silent client is reaped after the idle window —
    30 virtual seconds, ~150ms real under timeout_scale=0.005."""
    ka = KeepaliveOptions(idle_timeout=30.0)
    srv = RPCServer(keepalive=ka)
    srv.register("echo", lambda body, stream: body)
    srv.start()
    try:
        with clockskew.use_virtual(
            clockskew.VirtualClock(timeout_scale=0.005)
        ):
            sock = socket.create_connection(srv.addr, timeout=5.0)
            try:
                deadline = time.monotonic() + 5.0
                while time.monotonic() < deadline and \
                        srv.connection_count == 0:
                    time.sleep(0.01)
                assert srv.connection_count == 1
                # send NOTHING: the scaled 150ms idle window reaps us
                deadline = time.monotonic() + 5.0
                while time.monotonic() < deadline and \
                        srv.connection_count > 0:
                    time.sleep(0.02)
                assert srv.connection_count == 0
            finally:
                sock.close()
        # and a real request still works at full speed afterwards
        assert RPCClient(*srv.addr).call("echo", b"ok") == b"ok"
    finally:
        srv.stop()


# -- deliver client: the whole backoff cycle with no real sleeps --------------


def _block(num: int) -> common_pb2.Block:
    blk = common_pb2.Block()
    blk.header.number = num
    return blk


def test_deliver_backoff_cycle_without_real_sleeps():
    """Under a virtual clock the reconnect waits become clock advances:
    injected stream failures walk the backoff to its cap and back to
    the floor after a delivered block, in a fraction of the >1.5
    virtual seconds the waits add up to."""
    from fabric_tpu.peer.deliverclient import DeliverClient

    committed = []

    def endpoint(start):
        for n in range(start, 3):
            yield _block(n)

    dc = DeliverClient(
        "ch", [endpoint], height_fn=lambda: len(committed),
        sink=lambda seq, raw: committed.append(seq), max_backoff_s=0.8,
    )
    t0 = time.monotonic()
    with clockskew.use_virtual() as clk:
        with faultline.use_plan({"faults": [
            {"point": "deliver.read", "action": "raise",
             "error": "OSError", "every": 1, "count": 5},
        ]}):
            dc.start()
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline and len(committed) < 3:
                time.sleep(0.01)
            dc.stop()
            assert len([t for t in faultline.trips()
                        if t["point"] == "deliver.read"]) == 5
    elapsed = time.monotonic() - t0
    assert committed == [0, 1, 2]
    # the virtual clock recorded EVERY reconnect wait in order (the
    # client's own backoff_log is a bounded deque the caught-up polling
    # laps churn through): five consecutive failures walk 0.1 -> 0.2 ->
    # 0.4 -> cap 0.8 -> 0.8, then delivery resets to the 0.1 floor
    assert clk.sleeps[:5] == [0.1, 0.2, 0.4, 0.8, 0.8]
    assert 0.1 in clk.sleeps[5:]
    assert sum(clk.sleeps) >= 2.0  # >2 virtual seconds of waiting...
    assert elapsed < 8.0           # ...in well under that real time
