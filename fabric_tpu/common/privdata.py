"""Collection access policies (reference core/common/privdata/).

Parses `CollectionConfigPackage` (simplecollection.go SimpleCollection)
and answers the two questions the private-data flows ask:

- `is_member(serialized_identity)` — does this identity's org belong to
  the collection (member_orgs_policy satisfied)?  Gates read access
  (member_only_read) and distribution eligibility.
- accessors for required/maximum peer counts and BTL, consumed by the
  distributor and the pvtdata store's expiry policy.

The reference evaluates membership by running the signature policy over a
self-signed SignedData probe (simplecollection.go Setup/AccessFilter); we
evaluate the policy's principal tree against the deserialized identity
directly — same outcome, no fake signature round-trip.
"""

from __future__ import annotations

from fabric_tpu.protos.peer import collection_pb2
from fabric_tpu.protos.common import policies_pb2


class NoSuchCollectionError(Exception):
    pass


class SimpleCollection:
    def __init__(
        self, conf: collection_pb2.StaticCollectionConfig, deserializer
    ):
        self._conf = conf
        self._deserializer = deserializer
        pol = conf.member_orgs_policy
        if pol.WhichOneof("payload") != "signature_policy":
            raise ValueError(
                f"collection {conf.name!r}: missing member_orgs_policy"
            )
        self._envelope = pol.signature_policy

    @property
    def name(self) -> str:
        return self._conf.name

    @property
    def required_peer_count(self) -> int:
        return self._conf.required_peer_count

    @property
    def maximum_peer_count(self) -> int:
        return self._conf.maximum_peer_count

    @property
    def block_to_live(self) -> int:
        return self._conf.block_to_live

    @property
    def member_only_read(self) -> bool:
        return self._conf.member_only_read

    @property
    def member_only_write(self) -> bool:
        return self._conf.member_only_write

    def member_orgs(self) -> list[str]:
        """MSP IDs named by the member policy's principals."""
        from fabric_tpu.protos.msp import msp_principal_pb2

        out = []
        for p in self._envelope.identities:
            if (
                p.principal_classification
                == msp_principal_pb2.MSPPrincipal.ROLE
            ):
                role = msp_principal_pb2.MSPRole.FromString(p.principal)
                out.append(role.msp_identifier)
        return out

    def is_member(self, serialized_identity: bytes) -> bool:
        """Whether the identity satisfies any principal of the member-orgs
        policy (reference AccessFilter)."""
        try:
            ident = self._deserializer.deserialize_identity(
                serialized_identity
            )
        except Exception:
            return False
        for principal in self._envelope.identities:
            try:
                self._deserializer.satisfies_principal(ident, principal)
                return True
            except Exception:
                continue
        return False


class CollectionStore:
    """Per-channel collection registry fed from committed chaincode
    definitions (reference core/common/privdata/store.go retrieving from
    the lifecycle metadata)."""

    def __init__(self, deserializer):
        self._deserializer = deserializer
        self._packages: dict[str, collection_pb2.CollectionConfigPackage] = {}

    def set_collections(self, chaincode: str, package_bytes: bytes) -> None:
        """Install/refresh a chaincode's CollectionConfigPackage (called on
        lifecycle commit)."""
        if not package_bytes:
            self._packages.pop(chaincode, None)
            return
        self._packages[chaincode] = (
            collection_pb2.CollectionConfigPackage.FromString(package_bytes)
        )

    def collection(self, chaincode: str, name: str) -> SimpleCollection:
        pkg = self._packages.get(chaincode)
        if pkg is not None:
            for conf in pkg.config:
                if (
                    conf.WhichOneof("payload") == "static_collection_config"
                    and conf.static_collection_config.name == name
                ):
                    return SimpleCollection(
                        conf.static_collection_config, self._deserializer
                    )
        raise NoSuchCollectionError(f"{chaincode}/{name}")

    def collections_of(self, chaincode: str) -> list[SimpleCollection]:
        pkg = self._packages.get(chaincode)
        if pkg is None:
            return []
        return [
            SimpleCollection(
                c.static_collection_config, self._deserializer
            )
            for c in pkg.config
            if c.WhichOneof("payload") == "static_collection_config"
        ]

    def btl_policy(self):
        """(ns, coll) -> blocks-to-live callback for PvtDataStore."""

        def btl(ns: str, coll: str) -> int:
            try:
                return self.collection(ns, coll).block_to_live
            except NoSuchCollectionError:
                return 0

        return btl

    def is_eligible(
        self, chaincode: str, coll: str, serialized_identity: bytes
    ) -> bool:
        try:
            return self.collection(chaincode, coll).is_member(
                serialized_identity
            )
        except NoSuchCollectionError:
            return False


class LedgerBackedCollectionStore(CollectionStore):
    """Collection registry answering from COMMITTED lifecycle definitions
    (reference core/common/privdata/store.go pulling from the deployed
    chaincode info provider) — no explicit set_collections calls; a
    definition upgrade is visible at the next lookup."""

    def __init__(self, definition_provider, deserializer):
        """definition_provider: object with
        collection_config(name, collection) -> StaticCollectionConfig|None
        (chaincode.lifecycle.DefinitionProvider or a test fake)."""
        super().__init__(deserializer)
        self._definitions = definition_provider

    def collection(self, chaincode: str, name: str) -> SimpleCollection:
        sc = (
            self._definitions.collection_config(chaincode, name)
            if self._definitions is not None
            else None
        )
        if sc is None:
            raise NoSuchCollectionError(f"{chaincode}/{name}")
        return SimpleCollection(sc, self._deserializer)

    def collections_of(self, chaincode: str) -> list[SimpleCollection]:
        getter = getattr(self._definitions, "definition", None)
        d = getter(chaincode) if getter is not None else None
        if d is None or not d.collections:
            return []
        self.set_collections(chaincode, bytes(d.collections))
        return super().collections_of(chaincode)


def static_collection(
    name: str,
    member_mspids: list[str],
    required_peer_count: int = 0,
    maximum_peer_count: int = 1,
    block_to_live: int = 0,
    member_only_read: bool = True,
    member_only_write: bool = True,
    endorsement_policy=None,
) -> collection_pb2.CollectionConfig:
    """Convenience builder (tests + configtxgen-style tooling);
    `endorsement_policy` is an optional SignaturePolicyEnvelope gating
    writes to the collection's keys (StaticCollectionConfig field 8)."""
    from fabric_tpu.policies.signature_policy import signed_by_any_member

    conf = collection_pb2.CollectionConfig()
    sc = conf.static_collection_config
    sc.name = name
    sc.member_orgs_policy.signature_policy.CopyFrom(
        signed_by_any_member(member_mspids)
    )
    if endorsement_policy is not None:
        sc.endorsement_policy.signature_policy.CopyFrom(endorsement_policy)
    sc.required_peer_count = required_peer_count
    sc.maximum_peer_count = maximum_peer_count
    sc.block_to_live = block_to_live
    sc.member_only_read = member_only_read
    sc.member_only_write = member_only_write
    return conf


def collection_package(
    *configs: collection_pb2.CollectionConfig,
) -> collection_pb2.CollectionConfigPackage:
    pkg = collection_pb2.CollectionConfigPackage()
    pkg.config.extend(configs)
    return pkg


__all__ = [
    "CollectionStore",
    "LedgerBackedCollectionStore",
    "SimpleCollection",
    "NoSuchCollectionError",
    "static_collection",
    "collection_package",
]
