"""Capability feature-gating (reference common/capabilities/).

Channels declare required capabilities in their config (Capabilities
config values at channel/orderer/application level); a node that does not
implement a required capability must refuse to process the channel
(reference registry.go Supported).  This build implements the V2_0
semantics throughout (new lifecycle, v20 validation), and accepts the
V1_x names for config compatibility.
"""

from __future__ import annotations

from fabric_tpu.protos.common import configuration_pb2

CHANNEL_V2_0 = "V2_0"
CHANNEL_V1_4_3 = "V1_4_3"
CHANNEL_V1_4_2 = "V1_4_2"
CHANNEL_V1_3 = "V1_3"
CHANNEL_V1_1 = "V1_1"

APPLICATION_V2_0 = "V2_0"
APPLICATION_V1_4_2 = "V1_4_2"
APPLICATION_V1_3 = "V1_3"
APPLICATION_V1_2 = "V1_2"
APPLICATION_V1_1 = "V1_1"

ORDERER_V2_0 = "V2_0"
ORDERER_V1_4_2 = "V1_4_2"
ORDERER_V1_1 = "V1_1"


class UnsupportedCapabilityError(Exception):
    pass


class _Registry:
    def __init__(self, kind: str, known: set[str], caps: dict[str, bool]):
        self._kind = kind
        self._known = known
        self._required = {c for c, req in caps.items() if req}

    def supported(self) -> None:
        """Raise if the channel requires a capability this node lacks
        (reference registry.go Supported)."""
        unknown = self._required - self._known
        if unknown:
            raise UnsupportedCapabilityError(
                f"{self._kind} capabilities not supported: {sorted(unknown)}"
            )

    def required(self) -> set[str]:
        return set(self._required)

    def _has(self, cap: str) -> bool:
        return cap in self._required


class ChannelCapabilities(_Registry):
    def __init__(self, caps: dict[str, bool]):
        super().__init__(
            "channel",
            {CHANNEL_V1_1, CHANNEL_V1_3, CHANNEL_V1_4_2, CHANNEL_V1_4_3,
             CHANNEL_V2_0},
            caps,
        )

    @property
    def consensus_type_migration(self) -> bool:
        return self._has(CHANNEL_V1_4_2) or self._has(CHANNEL_V2_0)


class ApplicationCapabilities(_Registry):
    def __init__(self, caps: dict[str, bool]):
        super().__init__(
            "application",
            {APPLICATION_V1_1, APPLICATION_V1_2, APPLICATION_V1_3,
             APPLICATION_V1_4_2, APPLICATION_V2_0},
            caps,
        )

    @property
    def lifecycle_v20(self) -> bool:
        """New chaincode lifecycle (_lifecycle SCC) in force."""
        return self._has(APPLICATION_V2_0)

    @property
    def key_level_endorsement(self) -> bool:
        return self._has(APPLICATION_V1_3) or self._has(APPLICATION_V2_0)

    @property
    def private_channel_data(self) -> bool:
        return True  # always on in this build (reference gates on V1_1)

    @property
    def storage_pvt_data_experimental(self) -> bool:
        return self._has(APPLICATION_V2_0)


class OrdererCapabilities(_Registry):
    def __init__(self, caps: dict[str, bool]):
        super().__init__(
            "orderer",
            {ORDERER_V1_1, ORDERER_V1_4_2, ORDERER_V2_0},
            caps,
        )

    @property
    def use_channel_creation_policy_as_admins(self) -> bool:
        return self._has(ORDERER_V2_0)


def capabilities_value(names: list[str]) -> configuration_pb2.Capabilities:
    caps = configuration_pb2.Capabilities()
    for n in names:
        caps.capabilities[n].SetInParent()
    return caps


def parse_capabilities(raw: bytes) -> dict[str, bool]:
    caps = configuration_pb2.Capabilities.FromString(raw)
    return {name: True for name in caps.capabilities}


__all__ = [
    "ChannelCapabilities",
    "ApplicationCapabilities",
    "OrdererCapabilities",
    "UnsupportedCapabilityError",
    "capabilities_value",
    "parse_capabilities",
    "CHANNEL_V2_0",
    "APPLICATION_V2_0",
    "ORDERER_V2_0",
]
