"""Generalized multi-base Schnorr proofs over G1.

Every zero-knowledge proof in idemix (issuer well-formedness,
credential-request PoK, presentation proof, nym signature — reference
idemix/{issuerkey,credrequest,signature,nymsignature}.go) is an AND
composition of discrete-log representations Y = prod_j G_j^{x_j}.  Rather
than hand-rolling each commitment/response pair as the reference does, the
relations are expressed declaratively and this module runs the sigma
protocol: commitments T = prod G^rho, challenge c = H(...), responses
z_j = rho_j + c x_j, and the verifier identity prod G^z == T * Y^c.

Secrets shared between relations (e.g. the user secret key appearing in
both the credential relation and the pseudonym relation) reuse one rho and
one response, which is exactly what binds them.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Sequence

from fabric_tpu.idemix import bn254 as bn


@dataclasses.dataclass(frozen=True)
class Relation:
    """Y = prod_j bases[j] ^ secrets[names[j]] over G1."""

    target: tuple  # Y, a G1 point
    bases: Sequence[tuple]  # G_j
    names: Sequence[str]  # secret name per base (shared names share rho/z)


def _commitment(rel: Relation, rho: dict[str, int]):
    return bn.g1_msm(
        [(base, rho[name]) for base, name in zip(rel.bases, rel.names)]
    )


def prove(
    relations: Sequence[Relation],
    secrets: dict[str, int],
    challenge_fn: Callable[[Sequence[tuple]], int],
    rng=None,
) -> tuple[int, dict[str, int]]:
    """Run the prover; returns (challenge, responses-by-name).

    challenge_fn receives the list of commitment points T_i (same order as
    relations) and must hash them together with the statement and message.
    """
    rho = {name: bn.rand_zr(rng) for name in secrets}
    commitments = [_commitment(rel, rho) for rel in relations]
    c = challenge_fn(commitments)
    responses = {
        name: (rho[name] + c * x) % bn.R for name, x in secrets.items()
    }
    return c, responses


def recompute_commitments(
    relations: Sequence[Relation],
    challenge: int,
    responses: dict[str, int],
) -> list[tuple]:
    """Verifier side: T_i = prod G^z * Y^{-c}; feed into the same
    challenge_fn and compare challenges."""
    out = []
    for rel in relations:
        for name in rel.names:
            if name not in responses:
                raise ValueError(f"missing response for secret {name!r}")
        out.append(bn.g1_msm(
            [(rel.target, (-challenge) % bn.R)]
            + [
                (base, responses[name])
                for base, name in zip(rel.bases, rel.names)
            ]
        ))
    return out
