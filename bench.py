"""fabric-tpu benchmark entry point.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

North-star metric (BASELINE.json / BASELINE.md): **committed tx/s** for
1000-tx blocks under a 3-of-5 (MAJORITY over 5 orgs) endorsement policy
through the pipelined txvalidator with the TPU batch-verify backend.
Baseline is the *faithful* reference-shaped host path: sequential
per-signature `ecdsa.Verify` with every sub-policy re-verifying its
signatures per tx and no verify-item interning or endorsement-plan
caching (bccsp/sw/ecdsa.go:41 + common/policies/policy.go:365-402 +
core/committer/txvalidator/v20/validator.go:180-265 semantics).
"""

from __future__ import annotations

import json
import os
import sys
import time

_ROOT = os.path.dirname(os.path.abspath(__file__))


def _setup_path() -> None:
    for p in (_ROOT, os.path.join(_ROOT, "scripts"), os.path.join(_ROOT, "tests")):
        if p not in sys.path:
            sys.path.insert(0, p)


def main() -> None:
    _setup_path()
    from bench_pipeline import _build_world, _make_blocks

    from fabric_tpu.csp import SWCSP
    from fabric_tpu.peer.txvalidator import TxValidator
    from fabric_tpu.protos.common import common_pb2

    n_txs, n_blocks = 1000, 4
    sw = SWCSP()
    orgs, genesis = _build_world(5)
    ledger, bundle, blocks = _make_blocks(orgs, genesis, sw, n_txs, 3, n_blocks)

    def copies(k):
        out = []
        for j in range(k):
            b = common_pb2.Block()
            b.CopyFrom(blocks[j % n_blocks])
            out.append(b)
        return out

    # Faithful reference-shaped host baseline (slow by design — that is
    # the point of the comparison).  Warmed + best-of-2 so process
    # warm-up (EC backend init, native lib load, proto class setup) is
    # not charged to the baseline.
    vf = TxValidator("benchch", ledger, bundle, sw, faithful=True)
    vf.validate(copies(1)[0])  # warm-up
    base_best = float("inf")
    for _ in range(2):
        (b,) = copies(1)
        t0 = time.perf_counter()
        flags = vf.validate(b)
        base_best = min(base_best, time.perf_counter() - t0)
        assert all(f == 0 for f in flags)
    baseline = n_txs / base_best

    # Measured: pipelined committed tx/s with the TPU backend (falls
    # back to the optimized host path when no device is reachable).
    try:
        from fabric_tpu.csp.tpu.provider import TPUCSP

        csp = TPUCSP(min_device_batch=1)
        warm = TxValidator("benchch", ledger, bundle, csp)
        warm.validate(copies(1)[0])  # compile + first transfer
    except Exception:
        csp = sw

    best = float("inf")
    for _ in range(3):
        v = TxValidator("benchch", ledger, bundle, csp)
        bs = copies(n_blocks)
        t0 = time.perf_counter()
        for flags in v.validate_pipeline(iter(bs), depth=3):
            assert all(f == 0 for f in flags)
        best = min(best, time.perf_counter() - t0)
    value = n_blocks * n_txs / best

    print(
        json.dumps(
            {
                "metric": "committed_tx_per_s_1000tx_3of5_pipelined",
                "value": round(value, 2),
                "unit": "tx/s",
                "vs_baseline": round(value / baseline, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
