"""SEEDED VIOLATION (racecheck, type-informed call resolution): the
worker thread reaches the ledger's unguarded write ONLY through an
attribute call on an annotated parameter — without typed resolution
the call falls off the graph and the race is invisible."""

from fabric_tpu.devtools.lockwatch import spawn_thread

from .fix_race_typed_ledger import FixLedger


class HeightPump:
    def __init__(self, ledger: FixLedger):
        self._ledger = ledger

    def start(self):
        t = spawn_thread(
            target=self._run, name="fixture-height-pump", kind="worker"
        )
        t.start()
        return t

    def _run(self):
        self._ledger.bump()  # resolves via the FixLedger annotation
