"""Identity mapper: pki-id -> serialized identity with expiration.

Reference gossip/identity/identity.go:38 (NewIdentityMapper) — the
store behind gossip message verification and the certstore.  Identities
expire at their X.509 certificate's notAfter (when the identity parses
as an msp.SerializedIdentity carrying a PEM cert); opaque identities
fall back to a default TTL.  Expired identities are purged on access
and by `sweep()`, and an `on_purge` hook lets the comm layer drop its
own caches (the reference deletes the peer's connections too).
"""

from __future__ import annotations

import threading
import time


def identity_expiration(identity: bytes) -> float | None:
    """Seconds-since-epoch expiration for an identity, or None when it
    carries no parseable certificate (caller applies its default TTL).
    Mirrors msgCryptoService.Expiration feeding the mapper."""
    try:
        from cryptography import x509

        from fabric_tpu.protos.msp import identities_pb2

        sid = identities_pb2.SerializedIdentity.FromString(identity)
        cert = x509.load_pem_x509_certificate(sid.id_bytes)
        return cert.not_valid_after_utc.timestamp()
    except Exception:
        return None


class IdentityMapper:
    def __init__(
        self,
        mcs,
        self_identity: bytes,
        default_ttl_s: float = 3600.0,
        clock=time.time,
        on_purge=None,
    ):
        self._mcs = mcs
        self._default_ttl = default_ttl_s
        self._clock = clock
        self._purge_listeners: list = [on_purge] if on_purge else []
        self._lock = threading.Lock()
        # pki -> (identity bytes, expiration epoch-seconds)
        self._store: dict[bytes, tuple[bytes, float]] = {}
        self.self_pki = self.put(self_identity)

    def put(self, identity: bytes) -> bytes:
        """Store (or refresh) an identity; returns its pki-id.  Raises
        ValueError when the identity is already expired."""
        pki = self._mcs.get_pki_id(identity)
        exp = identity_expiration(identity)
        if exp is None:
            exp = self._clock() + self._default_ttl
        if exp <= self._clock():
            raise ValueError("identity is expired")
        with self._lock:
            self._store[pki] = (identity, exp)
        return pki

    def get(self, pki: bytes) -> bytes | None:
        with self._lock:
            entry = self._store.get(pki)
            if entry is None:
                return None
            identity, exp = entry
            if exp <= self._clock():
                del self._store[pki]
            else:
                return identity
        self._notify_purge(pki)
        return None

    def add_purge_listener(self, fn) -> None:
        """Register an extra purge hook (certstore eviction, comm cache
        drop — the reference certstore deletes purged identities from
        its pull mediator, gossip/gossip/certstore.go)."""
        self._purge_listeners.append(fn)

    def _notify_purge(self, pki: bytes) -> None:
        for fn in self._purge_listeners:
            fn(pki)

    def known(self) -> list[tuple[bytes, bytes]]:
        """[(pki, identity)] of unexpired entries."""
        self.sweep()
        with self._lock:
            return [(pki, ident) for pki, (ident, _) in self._store.items()]

    def sweep(self) -> list[bytes]:
        """Purge expired identities; returns the purged pki-ids
        (reference identity.go periodic purge + SuspectPeers)."""
        now = self._clock()
        with self._lock:
            dead = [p for p, (_, exp) in self._store.items() if exp <= now]
            for p in dead:
                del self._store[p]
        for p in dead:
            self._notify_purge(p)
        return dead


__all__ = ["IdentityMapper", "identity_expiration"]
