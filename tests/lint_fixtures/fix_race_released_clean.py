"""CLEAN TWIN of fix_race_released_dirty: the access stays inside the
critical section."""

from fabric_tpu.devtools.lockwatch import named_lock, spawn_thread


class DrainQueue:
    def __init__(self):
        self._lock = named_lock("fixture.drain")
        self._jobs = []
        self._last = None

    def start(self):
        t = spawn_thread(
            target=self._drain, name="fixture-drain", kind="worker"
        )
        t.start()
        return t

    def _drain(self):
        with self._lock:
            job = self._jobs.pop() if self._jobs else None
            self._last = job

    def submit(self, job):
        with self._lock:
            self._jobs.append(job)
            self._last = job

    def peek_last(self):
        with self._lock:
            return self._last
