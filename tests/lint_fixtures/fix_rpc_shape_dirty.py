"""Seeded violation (rpc-conformance): the only register site for
``fix.Feed`` binds a GENERATOR handler (stream-shaped), but the client
unary-``call``s it — the framing can never line up.  Expected: the
shape mismatch fires at the call site."""


class FixServer:
    def __init__(self, rpc):
        self.rpc = rpc
        self.rpc.register("fix.Feed", self._feed)

    def _feed(self, body, stream):
        for chunk in (b"a", b"b"):
            yield chunk


def drain(conn):
    return conn.call("fix.Feed", b"")  # <- verb/shape mismatch: HERE
