"""Idemix MSP provider tests (reference msp/idemixmsp.go coverage:
config setup, serialize/deserialize roundtrip, signing, principals)."""

import random

import pytest

from fabric_tpu.msp.idemixmsp import (
    ROLE_ADMIN,
    ROLE_MEMBER,
    IdemixMSP,
    IdemixMSPError,
    generate_issuer,
    idemix_msp_config,
    issue_signer_config,
)
from fabric_tpu.protos.msp import msp_principal_pb2

RNG = random.Random(7)


@pytest.fixture(scope="module")
def msp():
    issuer = generate_issuer(rng=RNG)
    signer = issue_signer_config(
        issuer, "IdemixOrg", ou="ou1", role=ROLE_MEMBER,
        enrollment_id="alice", rng=RNG,
    )
    conf = idemix_msp_config(issuer, "IdemixOrg", signer)
    return IdemixMSP.from_config(conf)


def test_sign_verify(msp):
    ident = msp.get_default_signing_identity()
    sig = ident.sign(b"tx-payload")
    assert msp.verify(ident, b"tx-payload", sig)
    assert not msp.verify(ident, b"other", sig)
    assert not msp.verify(ident, b"tx-payload", b"garbage")


def test_deserialize_roundtrip_is_anonymous(msp):
    ident = msp.get_default_signing_identity()
    back = msp.deserialize_identity(ident.serialize())
    assert back.ou == "ou1"
    assert back.role == ROLE_MEMBER
    assert back.nym == ident.nym
    msp.validate(back)
    # Anonymity surface: the serialized identity reveals OU/role only —
    # no enrollment id anywhere in the bytes.
    assert b"alice" not in ident.serialize()


def test_deserialize_rejects_claimed_ou_lie(msp):
    from fabric_tpu.protos.msp import identities_pb2

    sid = identities_pb2.SerializedIdentity.FromString(
        msp.get_default_signing_identity().serialize()
    )
    sii = identities_pb2.SerializedIdemixIdentity.FromString(sid.id_bytes)
    sii.ou = b"ou-forged"
    sid.id_bytes = sii.SerializeToString()
    with pytest.raises(IdemixMSPError):
        msp.deserialize_identity(sid.SerializeToString())


def test_satisfies_principal(msp):
    ident = msp.get_default_signing_identity()
    member = msp_principal_pb2.MSPPrincipal(
        principal_classification=msp_principal_pb2.MSPPrincipal.ROLE,
        principal=msp_principal_pb2.MSPRole(
            msp_identifier="IdemixOrg", role=msp_principal_pb2.MSPRole.MEMBER
        ).SerializeToString(),
    )
    msp.satisfies_principal(ident, member)

    admin = msp_principal_pb2.MSPPrincipal(
        principal_classification=msp_principal_pb2.MSPPrincipal.ROLE,
        principal=msp_principal_pb2.MSPRole(
            msp_identifier="IdemixOrg", role=msp_principal_pb2.MSPRole.ADMIN
        ).SerializeToString(),
    )
    with pytest.raises(IdemixMSPError):
        msp.satisfies_principal(ident, admin)

    ou_ok = msp_principal_pb2.MSPPrincipal(
        principal_classification=msp_principal_pb2.MSPPrincipal.ORGANIZATION_UNIT,
        principal=msp_principal_pb2.OrganizationUnit(
            msp_identifier="IdemixOrg", organizational_unit_identifier="ou1"
        ).SerializeToString(),
    )
    msp.satisfies_principal(ident, ou_ok)


def test_admin_identity():
    issuer = generate_issuer(rng=RNG)
    signer = issue_signer_config(
        issuer, "Org", ou="ou1", role=ROLE_ADMIN, enrollment_id="boss",
        rng=RNG,
    )
    msp = IdemixMSP.from_config(idemix_msp_config(issuer, "Org", signer))
    ident = msp.get_default_signing_identity()
    assert ident.is_admin
    admin = msp_principal_pb2.MSPPrincipal(
        principal_classification=msp_principal_pb2.MSPPrincipal.ROLE,
        principal=msp_principal_pb2.MSPRole(
            msp_identifier="Org", role=msp_principal_pb2.MSPRole.ADMIN
        ).SerializeToString(),
    )
    msp.satisfies_principal(ident, admin)
