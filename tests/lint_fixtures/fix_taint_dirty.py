"""SEEDED VIOLATION (taint): wall-clock smuggled through two
assignments and an attribute fill into a protobuf marshal."""

import time

from fabric_tpu.protos.common import common_pb2


def build_header(number: int) -> bytes:
    now = time.time()  # the source
    stamp = int(now)  # hop 1
    seconds = stamp + 0  # hop 2
    hdr = common_pb2.BlockHeader(number=number)
    hdr.timestamp = seconds  # attribute fill taints `hdr`
    return hdr.SerializeToString()  # <- taint must fire HERE
