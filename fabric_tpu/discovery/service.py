"""Discovery service (reference discovery/service.go:67-135).

Processes a SignedRequest: authenticates the caller (valid channel
identity + channel Readers ACL), then answers each query:

- ConfigQuery: channel MSP configs + orderer endpoints
- PeerMembershipQuery: live peers by org
- ChaincodeQuery: endorsement descriptors per interest
- LocalPeerQuery: channel-less membership

Results are memoized per (identity, request-shape) through a small auth
cache like the reference's (discovery/authcache.go).
"""

from __future__ import annotations

import threading

from fabric_tpu.common.hashing import sha256 as _sha256
from fabric_tpu.discovery.endorsement import PeerInfo, compute_descriptor
from fabric_tpu.protos.discovery import protocol_pb2 as dpb
from fabric_tpu.protoutil.common import SignedData


class DiscoveryError(Exception):
    pass


class DiscoverySupport:
    """Everything the service needs from the peer, injected (reference
    discovery/support/).  Callables keep the service decoupled:

    - channels() -> list[str]
    - bundle(channel) -> channelconfig Bundle (msp_manager, policy_manager)
    - peers(channel) -> list[PeerInfo]
    - msp_configs(channel) -> {mspid: serialized MSPConfig}
    - orderer_endpoints(channel) -> {mspid: [(host, port)]}
    - chaincode_policy(channel, cc_name) -> SignaturePolicyEnvelope | None
    - collection_filter(channel, cc, collections) -> callable(PeerInfo)->bool
    - acl_check(channel, signed_data) raises on denial
    """

    def __init__(self, **kw):
        self.__dict__.update(kw)


class DiscoveryService:
    def __init__(self, support: DiscoverySupport, csp,
                 auth_cache_size: int = 1000):
        self._support = support
        self._csp = csp
        self._auth_cache: dict[bytes, bool] = {}
        self._lock = threading.Lock()
        self._cache_size = auth_cache_size

    # -- authentication ------------------------------------------------------

    def _authenticate(self, signed: dpb.SignedRequest,
                      req: dpb.Request, channel: str) -> None:
        ident_bytes = bytes(req.authentication.client_identity)
        if not ident_bytes:
            raise DiscoveryError("access denied: no client identity")
        key = _sha256(
            channel.encode() + b"\x00" + ident_bytes + b"\x00"
            + bytes(signed.signature) + bytes(signed.payload)
        )
        with self._lock:
            cached = self._auth_cache.get(key)
        if cached is True:
            return
        if cached is False:
            raise DiscoveryError("access denied")
        ok = False
        try:
            bundle = self._support.bundle(channel)
            ident = bundle.msp_manager.deserialize_identity(ident_bytes)
            bundle.msp_manager.validate(ident)
            sd = SignedData(
                data=bytes(signed.payload),
                identity=ident_bytes,
                signature=bytes(signed.signature),
            )
            self._support.acl_check(channel, sd)
            ok = True
        except Exception as exc:
            raise DiscoveryError(f"access denied: {exc}") from exc
        finally:
            with self._lock:
                if len(self._auth_cache) >= self._cache_size:
                    self._auth_cache.clear()
                self._auth_cache[key] = ok

    # -- processing ----------------------------------------------------------

    def process(self, signed: dpb.SignedRequest) -> dpb.Response:
        res = dpb.Response()
        try:
            req = dpb.Request.FromString(signed.payload)
        except Exception:
            r = res.results.add()
            r.error.content = "malformed request"
            return res
        for q in req.queries:
            out = res.results.add()
            try:
                which = q.WhichOneof("query")
                if which in ("config_query", "peer_query", "cc_query"):
                    if q.channel not in self._support.channels():
                        raise DiscoveryError(
                            f"access denied: unknown channel {q.channel!r}"
                        )
                    self._authenticate(signed, req, q.channel)
                if which == "config_query":
                    self._config(q.channel, out)
                elif which == "peer_query":
                    self._members(q.channel, out)
                elif which == "cc_query":
                    self._endorsers(q.channel, q.cc_query, out)
                elif which == "local_peers":
                    self._members("", out)
                else:
                    raise DiscoveryError("unknown query type")
            except Exception as exc:
                out.error.content = str(exc)
        return res

    def _config(self, channel: str, out) -> None:
        for mspid, conf in self._support.msp_configs(channel).items():
            out.config_result.msps[mspid] = conf
        for mspid, eps in self._support.orderer_endpoints(channel).items():
            entry = out.config_result.orderers[mspid]
            for host, port in eps:
                entry.endpoint.add(host=host, port=port)

    def _members(self, channel: str, out) -> None:
        for p in self._support.peers(channel):
            out.members.peers_by_org[p.mspid].peers.add(
                identity=p.identity,
                endpoint=p.endpoint,
                ledger_height=p.ledger_height,
                chaincodes=list(p.chaincodes),
            )

    def _endorsers(self, channel: str, cc_query, out) -> None:
        bundle = self._support.bundle(channel)
        peers = self._support.peers(channel)
        for interest in cc_query.interests:
            if not interest.chaincodes:
                raise DiscoveryError("empty chaincode interest")
            # Multi-chaincode interests (cc2cc) require satisfying every
            # called chaincode's policy; descriptor per call like the
            # reference.
            for call in interest.chaincodes:
                pol = self._support.chaincode_policy(channel, call.name)
                if pol is None:
                    raise DiscoveryError(
                        f"no endorsement policy for {call.name!r}"
                    )
                cfilter = None
                if call.collection_names:
                    cfilter = self._support.collection_filter(
                        channel, call.name, list(call.collection_names)
                    )
                desc = compute_descriptor(
                    call.name, pol, peers, bundle.msp_manager,
                    collection_filter=cfilter,
                )
                out.cc_query_res.content.append(desc)


__all__ = ["DiscoveryService", "DiscoverySupport", "DiscoveryError", "PeerInfo"]
