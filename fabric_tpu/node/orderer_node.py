"""Orderer daemon: AtomicBroadcast over the framed RPC transport.

Reference: orderer/common/server/main.go Main() assembles localconfig,
the multichannel registrar, and the Broadcast/Deliver gRPC handlers
(server.go:159,177); channel participation (join/remove without a system
channel, channelparticipation/restapi.go) is exposed as admin RPCs.

RPC surface:
  ab.Broadcast        Envelope -> BroadcastResponse
  ab.Deliver          signed SeekInfo Envelope -> stream DeliverResponse
  participation.Join  genesis Block -> channel id (join without system
                      channel)
  participation.List  "" -> ChannelQueryResponse (channel ids)
"""

from __future__ import annotations

from fabric_tpu.comm import RPCServer
from fabric_tpu.common.deliver import BlockNotifier, DeliverService
from fabric_tpu.orderer.broadcast import BroadcastHandler
from fabric_tpu.orderer.multichannel import Registrar
from fabric_tpu.protos.common import common_pb2
from fabric_tpu.protos.orderer import ab_pb2
from fabric_tpu.protos.peer import configuration_pb2 as peer_cfg


class OrdererNode:
    def __init__(
        self,
        root_dir: str | None,
        csp,
        signer=None,
        host: str = "127.0.0.1",
        port: int = 0,
        genesis_blocks: list | None = None,
        consenter_overrides: dict | None = None,
        node_id: int = 1,
        transport=None,
    ):
        self.registrar = Registrar(
            root_dir,
            csp,
            signer=signer,
            node_id=node_id,
            transport=transport,
            consenter_overrides=consenter_overrides,
        )
        self._csp = csp
        notifier = BlockNotifier()
        self.deliver = DeliverService(
            self.registrar.get_chain,
            csp,
            policy_path="/Channel/Readers",
            notifier=notifier,
        )
        self.registrar.add_block_listener(
            lambda ch, blk: notifier.notify()
        )
        self.broadcast = BroadcastHandler(self.registrar)
        if genesis_blocks:
            self.registrar.startup(genesis_blocks)

        self.rpc = RPCServer(host, port)
        self.rpc.register("ab.Broadcast", self._broadcast)
        self.rpc.register("ab.Deliver", self._deliver)
        self.rpc.register("participation.Join", self._join)
        self.rpc.register("participation.List", self._list)

    @property
    def addr(self):
        return self.rpc.addr

    def start(self) -> None:
        self.rpc.start()

    def stop(self) -> None:
        self.rpc.stop()
        self.deliver.stop()
        self.registrar.halt_all()

    # -- handlers ----------------------------------------------------------

    def _broadcast(self, body: bytes, stream) -> bytes:
        env = common_pb2.Envelope.FromString(body)
        status = self.broadcast.process_message(env)
        return ab_pb2.BroadcastResponse(status=status).SerializeToString()

    def _deliver(self, body: bytes, stream):
        from fabric_tpu.common.deliver import deliver_response_frames

        return deliver_response_frames(self.deliver, body)

    def _join(self, body: bytes, stream) -> bytes:
        blk = common_pb2.Block.FromString(body)
        cs = self.registrar.create_chain(blk)
        return cs.channel_id.encode("utf-8")

    def _list(self, body: bytes, stream) -> bytes:
        resp = peer_cfg.ChannelQueryResponse()
        for ch in self.registrar.channel_list():
            resp.channels.add().channel_id = ch
        return resp.SerializeToString()


__all__ = ["OrdererNode"]
