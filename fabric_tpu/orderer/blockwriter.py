"""Block assembly + signing on the ordering node.

Reference: orderer/common/multichannel/blockwriter.go (CreateNextBlock,
WriteBlock: SIGNATURES metadata carrying OrdererBlockMetadata with the
last-config index, signed by the orderer's identity).
"""

from __future__ import annotations

from fabric_tpu.ledger.blkstorage import BlockStore
from fabric_tpu.protos.common import common_pb2
from fabric_tpu import protoutil


class BlockWriter:
    def __init__(self, store: BlockStore, signer=None, last_config_index: int = 0):
        self._store = store
        self._signer = signer  # SigningIdentity or None (dev)
        self._last_config_index = last_config_index

    @property
    def height(self) -> int:
        return self._store.height

    def last_block(self) -> common_pb2.Block | None:
        h = self._store.height
        return self._store.get_block_by_number(h - 1) if h else None

    def create_next_block(self, env_bytes_batch: list[bytes]) -> common_pb2.Block:
        if self._store.height == 0:
            prev_hash = b""
            number = 0
        else:
            prev = self._store.get_block_by_number(self._store.height - 1)
            prev_hash = protoutil.block_header_hash(prev.header)
            number = prev.header.number + 1
        blk = protoutil.new_block(number, prev_hash)
        for raw in env_bytes_batch:
            blk.data.data.append(raw)
        blk.header.data_hash = protoutil.block_data_hash(blk.data)
        return blk

    def write_block(self, blk: common_pb2.Block, is_config: bool = False) -> None:
        if is_config:
            self._last_config_index = blk.header.number
        obm = common_pb2.OrdererBlockMetadata()
        obm.last_config.index = self._last_config_index
        meta = common_pb2.Metadata(value=obm.SerializeToString())
        if self._signer is not None:
            shdr = protoutil.make_signature_header(
                self._signer.serialize(), protoutil.random_nonce()
            ).SerializeToString()
            # signature covers metadata value || sig header || block header
            msg = (
                meta.value + shdr + protoutil.block_header_bytes(blk.header)
            )
            meta.signatures.append(
                common_pb2.MetadataSignature(
                    signature_header=shdr, signature=self._signer.sign(msg)
                )
            )
        protoutil.init_block_metadata(blk)
        blk.metadata.metadata[common_pb2.SIGNATURES] = meta.SerializeToString()
        protoutil.set_tx_filter(blk, bytes(len(blk.data.data)))
        self._store.add_block(blk)


def verify_block_signature(blk: common_pb2.Block, policy, csp) -> bool:
    """Deliver-client side check of the orderer block signature against the
    channel's BlockValidation policy (reference
    internal/pkg/peer/blocksprovider + orderer/common/cluster/util.go)."""
    from fabric_tpu.protoutil import SignedData

    try:
        meta = common_pb2.Metadata.FromString(
            blk.metadata.metadata[common_pb2.SIGNATURES]
        )
    except Exception:
        return False
    if not meta.signatures:
        return False
    signed = []
    for ms in meta.signatures:
        shdr = common_pb2.SignatureHeader.FromString(ms.signature_header)
        msg = meta.value + ms.signature_header + protoutil.block_header_bytes(blk.header)
        signed.append(SignedData(msg, shdr.creator, ms.signature))
    return policy.evaluate_signed_data(signed, csp)


__all__ = ["BlockWriter", "verify_block_signature"]
