"""CLEAN TWIN of fix_gossip_taint_dirty: the digest covers only the
payload bytes and the sequence number is threaded in as an explicit
argument — deterministic on every peer."""

from fabric_tpu.common.hashing import sha256
from fabric_tpu.protos.gossip import message_pb2 as gpb


def payload_digest(payload: bytes) -> bytes:
    return sha256(payload)


def marshal_data_msg(payload: bytes, seq_num: int) -> bytes:
    msg = gpb.GossipMessage(tag=gpb.GossipMessage.EMPTY)
    msg.data_msg.payload.data = payload
    msg.data_msg.payload.seq_num = seq_num
    return msg.SerializeToString()
