"""Pseudonym signatures (reference idemix/nymsignature.go).

A nym signature proves knowledge of (sk, r_nym) with
Nym = HSk^sk * HRand^r_nym over a message — no credential, no pairing
(the reference's NymSignature.Ver at nymsignature.go:74 is three scalar
multiplications).  Used by the idemix MSP for per-transaction signing once
the session pseudonym is established.
"""

from __future__ import annotations

import dataclasses

from fabric_tpu.idemix import bn254 as bn
from fabric_tpu.idemix.issuer import IssuerPublicKey


@dataclasses.dataclass
class NymSignature:
    challenge: int
    z_sk: int
    z_rnym: int


def new_nym_signature(
    sk: int,
    nym: tuple,
    r_nym: int,
    ipk: IssuerPublicKey,
    msg: bytes,
    rng=None,
) -> NymSignature:
    rho_sk = bn.rand_zr(rng)
    rho_r = bn.rand_zr(rng)
    t = bn.g1_add(bn.g1_mul(ipk.h_sk, rho_sk), bn.g1_mul(ipk.h_rand, rho_r))
    c = bn.hash_to_zr(
        b"idemix-nym-signature",
        bn.g1_to_bytes(t),
        bn.g1_to_bytes(nym),
        ipk.hash(),
        msg,
    )
    return NymSignature(
        challenge=c,
        z_sk=(rho_sk + c * sk) % bn.R,
        z_rnym=(rho_r + c * r_nym) % bn.R,
    )


def verify_nym(
    sig: NymSignature, nym: tuple, ipk: IssuerPublicKey, msg: bytes
) -> bool:
    if nym is None or not bn.g1_is_on_curve(nym):
        return False
    t = bn.g1_add(
        bn.g1_add(
            bn.g1_mul(ipk.h_sk, sig.z_sk),
            bn.g1_mul(ipk.h_rand, sig.z_rnym),
        ),
        bn.g1_mul(nym, (-sig.challenge) % bn.R),
    )
    c = bn.hash_to_zr(
        b"idemix-nym-signature",
        bn.g1_to_bytes(t),
        bn.g1_to_bytes(nym),
        ipk.hash(),
        msg,
    )
    return c == sig.challenge
