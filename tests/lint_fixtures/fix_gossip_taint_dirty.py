"""SEEDED VIOLATION (taint, gossip sinks): wall-clock mixed into a
gossip payload digest, and into a gossip message marshaled for the
wire — peers compare/pull by exactly these bytes, so both fork the
gossip view."""

import time

from fabric_tpu.common.hashing import sha256
from fabric_tpu.protos.gossip import message_pb2 as gpb


def payload_digest(payload: bytes) -> bytes:
    stamp = time.time()  # the source
    tag = f"{stamp}:{len(payload)}"
    return sha256(tag.encode() + payload)  # <- gossip-digest: fires HERE


def marshal_data_msg(payload: bytes) -> bytes:
    msg = gpb.GossipMessage(tag=gpb.GossipMessage.EMPTY)
    msg.data_msg.payload.data = payload
    msg.data_msg.payload.seq_num = int(time.time())  # attribute fill
    return msg.SerializeToString()  # <- serialize sink: fires HERE
