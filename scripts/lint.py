#!/usr/bin/env python
"""CI wrapper around fabriclint: run the full-tree pass and emit one
JSON summary line in the same shape the bench scripts use, so the
driver/CI can scrape `"experiment": "fabriclint"` next to the bench
lines.  Exit code mirrors the linter (non-zero on any unsuppressed
violation).

Usage: python scripts/lint.py [--show-suppressed]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fabric_tpu.devtools.lint import lint_tree  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--show-suppressed", action="store_true",
        help="also print suppressed violations (with their reasons)",
    )
    args = ap.parse_args()

    t0 = time.perf_counter()
    report = lint_tree()
    elapsed = time.perf_counter() - t0

    for v in report.unsuppressed:
        print(str(v), file=sys.stderr)
    if args.show_suppressed:
        for v in report.suppressed:
            print(str(v), file=sys.stderr)

    summary = report.summary()
    print(json.dumps({
        "experiment": "fabriclint",
        "files": summary["files"],
        "violations": summary["violations"],
        "suppressed": summary["suppressed"],
        "by_rule": summary["by_rule"],
        "clean": summary["clean"],
        "seconds": round(elapsed, 4),
    }))
    return 0 if summary["clean"] else 1


if __name__ == "__main__":
    sys.exit(main())
