"""Key-level endorsement policy builder for chaincode authors.

Reference: the chaincode-shim `pkg/statebased` KeyEndorsementPolicy —
build/modify a SignaturePolicyEnvelope listing org principals, serialize
it, and attach it to a key via
`stub.set_state_validation_parameter(key, policy_bytes)`.
"""

from __future__ import annotations

from fabric_tpu.protos.common import policies_pb2
from fabric_tpu.protos.msp import msp_principal_pb2

ROLE_MEMBER = msp_principal_pb2.MSPRole.MEMBER
ROLE_PEER = msp_principal_pb2.MSPRole.PEER


class KeyEndorsementPolicy:
    """N-of-N over a set of org principals (reference statebased
    policy.go: AddOrgs/DelOrgs/ListOrgs/Policy)."""

    def __init__(self, policy_bytes: bytes = b""):
        self._orgs: dict[str, int] = {}
        if policy_bytes:
            env = policies_pb2.SignaturePolicyEnvelope.FromString(
                policy_bytes
            )
            for p in env.identities:
                role = msp_principal_pb2.MSPRole.FromString(p.principal)
                self._orgs[role.msp_identifier] = role.role

    def add_orgs(self, role: int, *mspids: str) -> None:
        for mspid in mspids:
            self._orgs[mspid] = role

    def del_orgs(self, *mspids: str) -> None:
        for mspid in mspids:
            self._orgs.pop(mspid, None)

    def list_orgs(self) -> list[str]:
        return sorted(self._orgs)

    def policy(self) -> bytes:
        """Serialized SignaturePolicyEnvelope requiring a signature from
        EVERY listed org."""
        env = policies_pb2.SignaturePolicyEnvelope(version=0)
        rules = []
        for i, mspid in enumerate(sorted(self._orgs)):
            p = env.identities.add()
            p.principal_classification = (
                msp_principal_pb2.MSPPrincipal.ROLE
            )
            p.principal = msp_principal_pb2.MSPRole(
                msp_identifier=mspid, role=self._orgs[mspid]
            ).SerializeToString()
            rule = policies_pb2.SignaturePolicy()
            rule.signed_by = i
            rules.append(rule)
        env.rule.n_out_of.n = len(rules)
        env.rule.n_out_of.rules.extend(rules)
        return env.SerializeToString()


__all__ = ["KeyEndorsementPolicy", "ROLE_MEMBER", "ROLE_PEER"]
