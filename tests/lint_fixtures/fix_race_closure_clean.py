"""CLEAN TWIN of fix_race_closure_dirty: the closure thread target
takes the guard lock around its write, so every access site agrees."""

from fabric_tpu.devtools.lockwatch import named_lock, spawn_thread


class StreamPump:
    def __init__(self):
        self._lock = named_lock("fixture.pump")
        self._done = {}

    def start(self):
        def pump_loop():
            with self._lock:
                self._done["n"] = 1

        t = spawn_thread(
            target=pump_loop, name="fixture-pump", kind="worker"
        )
        t.start()
        return t

    def mark(self):
        with self._lock:
            self._done["m"] = 2

    def poll(self):
        with self._lock:
            return self._done.get("n")
