// Native block-collect pass for the txvalidator (SURVEY.md §7 native
// components policy; the "move the collect phase into the C++
// marshaller" step recorded in BASELINE.md).
//
// Walks the protobuf wire format of every envelope in a block —
// Envelope / Payload / Header / ChannelHeader / SignatureHeader /
// Transaction / ChaincodeActionPayload / ChaincodeEndorsedAction /
// ProposalResponsePayload / ChaincodeAction — performing the syntactic
// checks of core/common/validation/msgvalidation.go:26-330 (reference
// file:line) and emitting, per tx, the offsets and SHA-256 digests the
// Python control plane needs to finish validation without touching a
// single protobuf object on the hot path.
//
// Field numbers mirror fabric-protos-go (verified against the generated
// *_pb2 descriptors): Envelope{payload=1,signature=2},
// Payload{header=1,data=2}, Header{channel_header=1,signature_header=2},
// ChannelHeader{type=1,channel_id=4,tx_id=5,epoch=6,extension=7},
// SignatureHeader{creator=1,nonce=2}, Transaction{actions=1},
// TransactionAction{payload=2}, ChaincodeActionPayload{ccpp=1,action=2},
// ChaincodeEndorsedAction{prp=1,endorsements=2},
// Endorsement{endorser=1,signature=2},
// ProposalResponsePayload{proposal_hash=1,extension=2},
// ChaincodeAction{results=1,events=2,chaincode_id=4},
// ChaincodeHeaderExtension{chaincode_id=2}, ChaincodeID{name=2},
// ChaincodeEvent{chaincode_id=1}.

#include <cstdint>
#include <cstring>
#include <string>

#include <dlfcn.h>

#include <new>

typedef uint8_t u8;
typedef uint32_t u32;
typedef uint64_t u64;
typedef int32_t i32;
typedef int64_t i64;

namespace {

// ---------------------------------------------------------------------------
// SHA-256.  The host libcrypto (when present) provides SHA-NI/AVX
// dispatch — ~10x the scalar loop on this block-digest-heavy pass — so
// it is resolved at runtime via dlopen; the scalar FIPS 180-4
// implementation below is the always-available fallback.
// ---------------------------------------------------------------------------

struct OsslSha {
  int (*init)(void*) = nullptr;
  int (*update)(void*, const void*, size_t) = nullptr;
  int (*fin)(u8*, void*) = nullptr;
  bool ok = false;
};

const OsslSha& ossl() {
  static const OsslSha s = [] {
    OsslSha o;
    for (const char* name :
         {"libcrypto.so.3", "libcrypto.so.1.1", "libcrypto.so"}) {
      void* h = dlopen(name, RTLD_NOW | RTLD_LOCAL);
      if (!h) continue;
      o.init = reinterpret_cast<int (*)(void*)>(dlsym(h, "SHA256_Init"));
      o.update = reinterpret_cast<int (*)(void*, const void*, size_t)>(
          dlsym(h, "SHA256_Update"));
      o.fin = reinterpret_cast<int (*)(u8*, void*)>(dlsym(h, "SHA256_Final"));
      if (o.init && o.update && o.fin) {
        o.ok = true;
        break;
      }
      dlclose(h);
    }
    return o;
  }();
  return s;
}

struct ScalarSha256 {
  u32 h[8];
  u8 buf[64];
  u64 len = 0;
  int fill = 0;
  ScalarSha256() {
    static const u32 init[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372,
                                0xa54ff53a, 0x510e527f, 0x9b05688c,
                                0x1f83d9ab, 0x5be0cd19};
    memcpy(h, init, sizeof(h));
  }
  static u32 rotr(u32 x, int n) { return (x >> n) | (x << (32 - n)); }
  void block(const u8* p) {
    static const u32 K[64] = {
        0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b,
        0x59f111f1, 0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01,
        0x243185be, 0x550c7dc3, 0x72be5d74, 0x80deb1fe, 0x9bdc06a7,
        0xc19bf174, 0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
        0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da, 0x983e5152,
        0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
        0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc,
        0x53380d13, 0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
        0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3, 0xd192e819,
        0xd6990624, 0xf40e3585, 0x106aa070, 0x19a4c116, 0x1e376c08,
        0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f,
        0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
        0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};
    u32 w[64];
    for (int i = 0; i < 16; ++i)
      w[i] = (u32(p[4 * i]) << 24) | (u32(p[4 * i + 1]) << 16) |
             (u32(p[4 * i + 2]) << 8) | u32(p[4 * i + 3]);
    for (int i = 16; i < 64; ++i) {
      u32 s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
      u32 s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    u32 a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
        g = h[6], hh = h[7];
    for (int i = 0; i < 64; ++i) {
      u32 S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
      u32 ch = (e & f) ^ (~e & g);
      u32 t1 = hh + S1 + ch + K[i] + w[i];
      u32 S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
      u32 mj = (a & b) ^ (a & c) ^ (b & c);
      u32 t2 = S0 + mj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }
  void update(const u8* p, size_t n) {
    len += n;
    if (fill) {
      while (n && fill < 64) { buf[fill++] = *p++; --n; }
      if (fill == 64) { block(buf); fill = 0; }
    }
    while (n >= 64) { block(p); p += 64; n -= 64; }
    while (n) { buf[fill++] = *p++; --n; }
  }
  void final(u8* out) {
    u64 bits = len * 8;
    u8 pad = 0x80;
    update(&pad, 1);
    u8 z = 0;
    while (fill != 56) update(&z, 1);
    u8 lb[8];
    for (int i = 0; i < 8; ++i) lb[i] = u8(bits >> (56 - 8 * i));
    update(lb, 8);
    for (int i = 0; i < 8; ++i) {
      out[4 * i] = u8(h[i] >> 24);
      out[4 * i + 1] = u8(h[i] >> 16);
      out[4 * i + 2] = u8(h[i] >> 8);
      out[4 * i + 3] = u8(h[i]);
    }
  }
};

// Incremental SHA-256 front dispatching to libcrypto when available.
// SHA256_CTX is 112 bytes (public, ABI-stable layout: h[8], Nl, Nh,
// data[16], num, md_len); 128 leaves slack.  The two states share
// storage — only the active one is ever constructed.
struct Sha256 {
  union {
    alignas(8) u8 octx[128];
    ScalarSha256 scalar;
  };
  bool fast;
  Sha256() {
    fast = ossl().ok;
    if (fast) ossl().init(octx);
    else new (&scalar) ScalarSha256();
  }
  void update(const u8* p, size_t n) {
    if (fast) ossl().update(octx, p, n);
    else scalar.update(p, n);
  }
  void final(u8* out) {
    if (fast) ossl().fin(out, octx);
    else scalar.final(out);
  }
};

void sha256(const u8* p, size_t n, u8* out) {
  Sha256 s;
  s.update(p, n);
  s.final(out);
}

// ---------------------------------------------------------------------------
// Protobuf wire walker.
// ---------------------------------------------------------------------------

struct Slice {
  const u8* p = nullptr;
  size_t n = 0;
  bool set = false;
};

bool read_varint(const u8*& p, const u8* end, u64* v) {
  u64 out = 0;
  int shift = 0;
  while (p < end && shift < 64) {
    u8 b = *p++;
    out |= u64(b & 0x7f) << shift;
    if (!(b & 0x80)) { *v = out; return true; }
    shift += 7;
  }
  return false;
}

// Scan a message, filling `fields[num] = last occurrence` for
// length-delimited fields and `varints[num]` for varint fields
// (numbers above `maxf` are skipped).  Returns false on malformed wire.
// Largest legal protobuf field number (2^29 - 1); python's decoder
// rejects tags beyond it and field number 0, so the walker must too —
// and the bound is what keeps `num` a safe array index below (a huge
// tag varint truncated through int() would otherwise go NEGATIVE and
// index out of bounds: found by the envelope fuzzer).
const u64 MAX_FIELD = 536870911u;

bool scan(const u8* p, size_t n, int maxf, Slice* fields, u64* varints) {
  const u8* end = p + n;
  while (p < end) {
    u64 tag;
    if (!read_varint(p, end, &tag)) return false;
    u64 fnum = tag >> 3;
    if (fnum == 0 || fnum > MAX_FIELD) return false;
    int num = int(fnum);
    int wt = int(tag & 7);
    if (wt == 0) {
      u64 v;
      if (!read_varint(p, end, &v)) return false;
      if (num <= maxf && varints) varints[num] = v;
    } else if (wt == 2) {
      u64 l;
      if (!read_varint(p, end, &l)) return false;
      if (l > size_t(end - p)) return false;
      if (num <= maxf && fields) {
        fields[num].p = p;
        fields[num].n = size_t(l);
        fields[num].set = true;
      }
      p += l;
    } else if (wt == 5) {
      if (end - p < 4) return false;
      p += 4;
    } else if (wt == 1) {
      if (end - p < 8) return false;
      p += 8;
    } else {
      return false;
    }
  }
  return true;
}

const char HEX[] = "0123456789abcdef";

// Strict UTF-8 validation (rejects overlongs, surrogates, > U+10FFFF)
// — the same acceptance set as python's protobuf string decoding.
// Proto3 `string` fields python PARSES must be checked here: a field
// the walker treats as raw bytes but python rejects as invalid UTF-8
// would otherwise flag differently across the two engines (or, worse,
// crash the glue's .decode()).
bool utf8_valid(const u8* p, size_t n) {
  size_t i = 0;
  while (i < n) {
    u8 c = p[i];
    if (c < 0x80) {
      i++;
      continue;
    }
    int len;
    u32 cp, min;
    if ((c & 0xe0) == 0xc0) {
      len = 2; cp = c & 0x1f; min = 0x80;
    } else if ((c & 0xf0) == 0xe0) {
      len = 3; cp = c & 0x0f; min = 0x800;
    } else if ((c & 0xf8) == 0xf0) {
      len = 4; cp = c & 0x07; min = 0x10000;
    } else {
      return false;
    }
    if (i + size_t(len) > n) return false;
    for (int k = 1; k < len; ++k) {
      if ((p[i + k] & 0xc0) != 0x80) return false;
      cp = (cp << 6) | (p[i + k] & 0x3f);
    }
    if (cp < min || cp > 0x10FFFF) return false;
    if (cp >= 0xD800 && cp <= 0xDFFF) return false;
    i += size_t(len);
  }
  return true;
}

// Fields 1..3 of a submessage are all proto strings (ChaincodeID
// path/name/version; ChaincodeEvent chaincode_id/tx_id/event_name).
bool strings_1to3_valid(const Slice* f) {
  for (int k = 1; k <= 3; ++k) {
    if (f[k].set && !utf8_valid(f[k].p, f[k].n)) return false;
  }
  return true;
}

// Status codes.  The glue treats EVERY negative status identically —
// the lane re-runs the canonical pure-python collector, which picks
// the TxValidationCode (engine parity by construction; see
// txvalidator._collect_native).  The distinct negative codes exist for
// debugging and the fuzzer's known-set assertion only; 0/1 are the
// codes that matter (fully-validated endorser/config tx).
enum {
  OK_ENDORSER = 0,
  OK_CONFIG = 1,
  E_NIL_ENVELOPE = -1,
  E_BAD_PAYLOAD = -2,
  E_BAD_COMMON_HEADER = -3,
  E_BAD_CHANNEL_HEADER = -4,
  E_BAD_PROPOSAL_TXID = -5,
  E_BAD_RESPONSE_PAYLOAD = -6,
  E_NO_ENDORSEMENTS = -7,
  E_UNKNOWN_TX_TYPE = -8,
  E_BAD_HEADER_EXTENSION = -9,
  E_INVALID_CHAINCODE = -10,
  E_INVALID_OTHER = -11,
  E_PY_FALLBACK = -12,
  E_NIL_TXACTION = -13,
};

}  // namespace

extern "C" {

// Per-tx arrays sized n; endorsement arrays sized max_endos.  All
// offsets are relative to `envs`.  Returns the total endorsement count,
// or -1 when max_endos was exceeded (caller re-runs with more room).
int fabric_collect_block(
    int n, const u8* envs, const i64* env_off, const u8* channel_id,
    int channel_id_len, i32* status, i32* type_out, i64* creator_off,
    i32* creator_len, i64* sig_off, i32* sig_len, u8* payload_digest,
    i64* txid_off, i32* txid_len, i64* prp_off, i32* prp_len,
    i64* rwset_off, i32* rwset_len, i64* ccid_off, i32* ccid_len,
    i32* endo_start, i32* endo_count, int max_endos, i64* e_endorser_off,
    i32* e_endorser_len, i64* e_sig_off, i32* e_sig_len, u8* e_digest) {
  int ne = 0;
  for (int i = 0; i < n; ++i) {
    status[i] = E_BAD_PAYLOAD;
    type_out[i] = -1;
    creator_len[i] = sig_len[i] = txid_len[i] = 0;
    prp_len[i] = rwset_len[i] = ccid_len[i] = 0;
    endo_start[i] = ne;
    endo_count[i] = 0;
    const u8* env = envs + env_off[i];
    size_t env_n = size_t(env_off[i + 1] - env_off[i]);

    Slice ef[3];
    if (!scan(env, env_n, 2, ef, nullptr)) continue;
    if (!ef[1].set || ef[1].n == 0) { status[i] = E_NIL_ENVELOPE; continue; }
    const Slice payload = ef[1];
    // creator signature over the payload bytes
    sig_off[i] = ef[2].set ? (ef[2].p - envs) : 0;
    sig_len[i] = ef[2].set ? i32(ef[2].n) : 0;
    sha256(payload.p, payload.n, payload_digest + 32 * i);

    Slice pf[3];
    if (!scan(payload.p, payload.n, 2, pf, nullptr)) continue;
    if (!pf[1].set) continue;
    Slice hf[3];
    if (!scan(pf[1].p, pf[1].n, 2, hf, nullptr)) continue;
    if (!hf[1].set || !hf[2].set) continue;
    const Slice chdr = hf[1], shdr = hf[2];
    Slice cf[8];
    u64 cv[8] = {0};
    if (!scan(chdr.p, chdr.n, 7, cf, cv)) continue;
    // timestamp (field 3) is a Timestamp SUBMESSAGE python parses
    // recursively; an opaque-blob pass here would accept garbage
    // python rejects (accept-side engine divergence)
    if (cf[3].set && !scan(cf[3].p, cf[3].n, 0, nullptr, nullptr)) continue;
    Slice sf[3];
    if (!scan(shdr.p, shdr.n, 2, sf, nullptr)) continue;

    const Slice creator = sf[1], nonce = sf[2];
    if (!creator.set || creator.n == 0 || !nonce.set || nonce.n == 0) {
      status[i] = E_BAD_COMMON_HEADER;
      continue;
    }
    // channel id match + epoch == 0
    if (!cf[4].set || cf[4].n != size_t(channel_id_len) ||
        memcmp(cf[4].p, channel_id, channel_id_len) != 0 || cv[6] != 0) {
      status[i] = E_BAD_CHANNEL_HEADER;
      continue;
    }
    creator_off[i] = creator.p - envs;
    creator_len[i] = i32(creator.n);
    type_out[i] = i32(cv[1]);

    if (cv[1] == 1 /* CONFIG */) { status[i] = OK_CONFIG; continue; }
    if (cv[1] != 3 /* ENDORSER_TRANSACTION */) {
      status[i] = E_UNKNOWN_TX_TYPE;
      continue;
    }

    // tx-id binding: hex(sha256(nonce || creator)) == chdr.tx_id
    {
      if (!cf[5].set || cf[5].n != 64) { status[i] = E_BAD_PROPOSAL_TXID; continue; }
      Sha256 s;
      s.update(nonce.p, nonce.n);
      s.update(creator.p, creator.n);
      u8 d[32];
      s.final(d);
      char hex[64];
      for (int k = 0; k < 32; ++k) {
        hex[2 * k] = HEX[d[k] >> 4];
        hex[2 * k + 1] = HEX[d[k] & 0xf];
      }
      if (memcmp(hex, cf[5].p, 64) != 0) { status[i] = E_BAD_PROPOSAL_TXID; continue; }
      txid_off[i] = cf[5].p - envs;
      txid_len[i] = 64;
    }

    // Transaction -> FIRST action (python validates tx.actions[0];
    // scan() keeps the last occurrence, so walk manually).  The walk
    // continues to the END of the message even after actions[0] is
    // found: python's Transaction.FromString wire-validates every
    // trailing action (and any other field), so stopping early would
    // accept envelopes python rejects.
    if (!pf[2].set) { status[i] = E_NIL_TXACTION; continue; }
    Slice action0;
    {
      const u8* p = pf[2].p;
      const u8* end = p + pf[2].n;
      bool bad = false;
      while (p < end) {
        u64 tag;
        if (!read_varint(p, end, &tag)) { bad = true; break; }
        if ((tag >> 3) == 0 || (tag >> 3) > MAX_FIELD) { bad = true; break; }
        int wt = int(tag & 7);
        if (wt == 2) {
          u64 l;
          if (!read_varint(p, end, &l) || l > size_t(end - p)) { bad = true; break; }
          if ((tag >> 3) == 1) {
            // every TransactionAction submessage must be wire-valid
            // (python parses them all, even past actions[0])
            if (!scan(p, size_t(l), 0, nullptr, nullptr)) { bad = true; break; }
            if (!action0.set) { action0.p = p; action0.n = size_t(l); action0.set = true; }
          }
          p += l;
        } else if (wt == 0) {
          u64 v;
          if (!read_varint(p, end, &v)) { bad = true; break; }
        } else if (wt == 5) { if (end - p < 4) { bad = true; break; } p += 4; }
        else if (wt == 1) { if (end - p < 8) { bad = true; break; } p += 8; }
        else { bad = true; break; }
      }
      if (bad) continue;
      if (!action0.set) { status[i] = E_NIL_TXACTION; continue; }
    }
    Slice taf[3];
    if (!scan(action0.p, action0.n, 2, taf, nullptr)) continue;
    if (!taf[2].set) continue;
    Slice capf[3];
    if (!scan(taf[2].p, taf[2].n, 2, capf, nullptr)) continue;
    if (!capf[2].set) continue;
    const Slice ccpp = capf[1];
    Slice eaf[3];
    if (!scan(capf[2].p, capf[2].n, 2, eaf, nullptr)) continue;
    if (!eaf[1].set) continue;
    const Slice prp = eaf[1];
    Slice prpf[3];
    if (!scan(prp.p, prp.n, 2, prpf, nullptr)) continue;
    if (!prpf[1].set || !prpf[2].set) continue;

    // proposal-hash binding: sha256(chdr || shdr || committed ccpp
    // bytes AS-IS) — the reference's GetProposalHash2 semantics
    // (protoutil/txutils.go:431, msgvalidation.go:233).  The committed
    // ccpp is never parsed by either engine, so no canonicalization and
    // no content validation are needed: any byte difference from the
    // endorsed preimage (including a smuggled TransientMap) hashes
    // differently and the lane flags BAD_RESPONSE_PAYLOAD.
    {
      Sha256 s;
      s.update(chdr.p, chdr.n);
      s.update(shdr.p, shdr.n);
      if (ccpp.set && ccpp.n) s.update(ccpp.p, ccpp.n);
      u8 want[32];
      s.final(want);
      if (prpf[1].n != 32 || memcmp(prpf[1].p, want, 32) != 0) {
        status[i] = E_BAD_RESPONSE_PAYLOAD;
        continue;
      }
    }

    // endorsements FIRST (python checks cap.action.endorsements right
    // after the proposal-hash binding, before any chaincode-id checks):
    // every occurrence of field 2 in ChaincodeEndorsedAction.  A missing
    // endorser field stays in the batch (empty identity -> python's
    // dummy-item lane -> policy failure at finish), matching the python
    // path's per-endorsement tolerance.
    {
      const u8* p = capf[2].p;
      const u8* end = p + capf[2].n;
      int count = 0;
      bool ok = true;
      while (p < end) {
        u64 tag;
        if (!read_varint(p, end, &tag)) { ok = false; break; }
        if ((tag >> 3) == 0 || (tag >> 3) > MAX_FIELD) { ok = false; break; }
        int num = int(tag >> 3);
        int wt = int(tag & 7);
        if (wt != 2) { ok = false; break; }
        u64 l;
        if (!read_varint(p, end, &l) || l > size_t(end - p)) { ok = false; break; }
        const u8* body = p;
        p += l;
        if (num != 2) continue;
        if (ne >= max_endos) return -1;
        Slice endo[3];
        if (!scan(body, size_t(l), 2, endo, nullptr)) { ok = false; break; }
        e_endorser_off[ne] = endo[1].set ? (endo[1].p - envs) : 0;
        e_endorser_len[ne] = endo[1].set ? i32(endo[1].n) : 0;
        e_sig_off[ne] = endo[2].set ? (endo[2].p - envs) : 0;
        e_sig_len[ne] = endo[2].set ? i32(endo[2].n) : 0;
        // digest of (prp_bytes || endorser): what each endorsement signs
        Sha256 es;
        es.update(prp.p, prp.n);
        if (endo[1].set) es.update(endo[1].p, endo[1].n);
        es.final(e_digest + 32 * size_t(ne));
        ++ne;
        ++count;
      }
      if (!ok) { status[i] = E_BAD_PAYLOAD; endo_count[i] = 0; continue; }
      if (count == 0) { status[i] = E_NO_ENDORSEMENTS; continue; }
      endo_count[i] = count;
    }

    // ChaincodeAction: results, events, chaincode_id
    Slice af[5];
    if (!scan(prpf[2].p, prpf[2].n, 4, af, nullptr)) { endo_count[i] = 0; continue; }
    // header-extension chaincode id.  A MISSING extension parses as an
    // empty message in python (cc_id == "" -> INVALID_CHAINCODE);
    // BAD_HEADER_EXTENSION is only for extension bytes that fail to
    // parse.
    Slice hef[3];
    if (cf[7].set && !scan(cf[7].p, cf[7].n, 2, hef, nullptr)) {
      status[i] = E_BAD_HEADER_EXTENSION;
      endo_count[i] = 0;
      continue;
    }
    Slice hccf[4];
    if (hef[2].set && !scan(hef[2].p, hef[2].n, 3, hccf, nullptr)) {
      status[i] = E_BAD_HEADER_EXTENSION;
      endo_count[i] = 0;
      continue;
    }
    if (!strings_1to3_valid(hccf)) {
      // python rejects the whole hdr_ext parse on invalid UTF-8; let
      // the python collector pick the exact flag
      status[i] = E_PY_FALLBACK;
      endo_count[i] = 0;
      continue;
    }
    if (!hccf[2].set || hccf[2].n == 0) {
      status[i] = E_INVALID_CHAINCODE;
      endo_count[i] = 0;
      continue;
    }
    const Slice ccid = hccf[2];  // UTF-8 already vetted just above
    {
      Slice accf[4];
      if (!af[4].set || !scan(af[4].p, af[4].n, 3, accf, nullptr) ||
          !strings_1to3_valid(accf)) {
        status[i] = af[4].set ? E_PY_FALLBACK : E_INVALID_CHAINCODE;
        endo_count[i] = 0;
        continue;
      }
      if (!accf[2].set || accf[2].n != ccid.n ||
          memcmp(accf[2].p, ccid.p, ccid.n) != 0) {
        status[i] = E_INVALID_CHAINCODE;
        endo_count[i] = 0;
        continue;
      }
    }
    // ChaincodeAction.response (field 3) is a Response{status=1,
    // message=2(string), payload=3}: python's ChaincodeAction parse
    // validates message's UTF-8
    if (af[3].set && af[3].n) {
      Slice rf[3];
      if (!scan(af[3].p, af[3].n, 2, rf, nullptr) ||
          (rf[2].set && !utf8_valid(rf[2].p, rf[2].n))) {
        status[i] = E_PY_FALLBACK;
        endo_count[i] = 0;
        continue;
      }
    }
    if (af[2].set && af[2].n) {  // chaincode event must name the chaincode
      // ChaincodeEvent{chaincode_id=1, tx_id=2, event_name=3, payload=4}
      // — three proto strings python's parse validates
      Slice evf[4];
      if (!scan(af[2].p, af[2].n, 3, evf, nullptr)) {
        status[i] = E_INVALID_OTHER;
        endo_count[i] = 0;
        continue;
      }
      if (!strings_1to3_valid(evf)) {  // fields 1..3 are all strings
        status[i] = E_PY_FALLBACK;
        endo_count[i] = 0;
        continue;
      }
      if (!evf[1].set || evf[1].n != ccid.n ||
          memcmp(evf[1].p, ccid.p, ccid.n) != 0) {
        status[i] = E_INVALID_OTHER;
        endo_count[i] = 0;
        continue;
      }
    }
    ccid_off[i] = ccid.p - envs;
    ccid_len[i] = i32(ccid.n);
    if (af[1].set) {
      rwset_off[i] = af[1].p - envs;
      rwset_len[i] = i32(af[1].n);
    }
    prp_off[i] = prp.p - envs;
    prp_len[i] = i32(prp.n);
    status[i] = OK_ENDORSER;
  }
  return ne;
}

}  // extern "C"
