"""TPU provider parity vs the sw oracle (hash + verify batch APIs)."""

import hashlib
import random

from fabric_tpu.csp import SWCSP, VerifyBatchItem, api, init_factories
from fabric_tpu.csp.tpu.provider import TPUCSP


def test_factory_selects_tpu():
    csp = init_factories("tpu", force=True)
    assert isinstance(csp, TPUCSP)
    init_factories("sw", force=True)


def test_hash_batch_parity():
    rng = random.Random(3)
    csp = TPUCSP(min_device_batch=1)
    msgs = [bytes(rng.randrange(256) for _ in range(rng.randrange(0, 200))) for _ in range(37)]
    msgs += [b"", b"a" * 55, b"a" * 56, b"a" * 64, b"a" * 119, b"a" * 120]
    got = csp.hash_batch(msgs)
    want = [hashlib.sha256(m).digest() for m in msgs]
    assert got == want


def test_verify_batch_parity_with_tampering():
    rng = random.Random(11)
    sw = SWCSP()
    tpu = TPUCSP(sw=sw, min_device_batch=1)
    items = []
    for i in range(40):
        key = sw.key_gen()
        digest = sw.hash(b"payload-%d" % i)
        sig = sw.sign(key, digest)
        roll = rng.random()
        if roll < 0.15:
            sig = sig[:-2] + bytes([sig[-2] ^ 1, sig[-1]])
        elif roll < 0.25:
            digest = sw.hash(b"evil-%d" % i)
        elif roll < 0.3:
            sig = b"\x30\x02\x01\x01"  # malformed DER
        elif roll < 0.35:
            r, s = api.unmarshal_ecdsa_signature(sig)
            sig = api.marshal_ecdsa_signature(r, api.P256_N - s)  # high-S
        items.append(VerifyBatchItem(key.public_key(), digest, sig))
    got = tpu.verify_batch(items)
    want = sw.verify_batch(items)
    assert got == want
    assert any(got) and not all(got)


def test_verify_batch_small_falls_back_to_host():
    sw = SWCSP()
    tpu = TPUCSP(sw=sw, min_device_batch=64)
    key = sw.key_gen()
    d = sw.hash(b"x")
    items = [VerifyBatchItem(key.public_key(), d, sw.sign(key, d))]
    assert tpu.verify_batch(items) == [True]
