#!/usr/bin/env python
"""CI wrapper around faultfuzz: run an N-plan chaos campaign (seeded
plan generation over the live fault-point registry, the invariant
oracle as the judge, shrinking + replayable repro artifacts for every
failure) and emit one JSON summary line in the same shape the bench and
lint scripts use, so the driver/CI can scrape `"experiment":
"faultfuzz"` next to those lines.

Usage: python scripts/chaos.py [--plans N] [--seed S] [--blocks B]
       [--out DIR] [--no-shrink] [--no-comm] [--mutants K]
       [--replay FILE] [--kill9] [--netsplit]

`--netsplit` is the PARTITION campaign mode: each plan stands up a
2-org/3-orderer topology, splits it into a seeded majority/minority
partition mid-stream (netsplit plans pushed per node over the
net.Netsplit control RPC), heals it, and judges with the
partition-aware oracle (majority keeps committing, minority stalls
without forking, every node rejoins after heal).  It composes with
`--kill9` (the seeded kill schedule runs INSIDE the same plans) and
arms a seeded per-node faultline delay plan; failing plans write a
`netharness-netsplit` repro JSON that `--replay` routes kind-aware.

`--mutants K` derives K seeded single-edit mutants (trigger tweak,
action swap within the point's pool, or dropped rule) from every
FAILING plan and runs them through the same judge/shrink/repro path —
probing how brittle the failure is to exactly one variable.  Mutants
are fully seed-derived, so same-seed campaigns stay byte-identical.

Exit code: nonzero when ANY plan's oracle verdict is a failure (each
one has been shrunk and written as a replayable repro JSON under --out,
default .faultfuzz/, which is gitignored).  `--replay FILE` re-arms a
repro artifact over a fresh workload directory instead of running a
campaign: exit 0 when the failure REPRODUCES (the artifact is good),
nonzero when it does not.

`--kill9` is the MULTI-PROCESS campaign mode (the faultfuzz follow-on
PR 8 filed): each plan stands up a real multi-process topology via
devtools/netharness, drives a tx stream through broadcast -> raft ->
gossip -> commit, SIGKILLs nodes on a seeded kill schedule, and judges
with the network-wide oracle.  Failing campaigns write a kill9 repro
JSON; `--replay` detects the artifact kind and routes to the right
replayer, so one CLI replays both in-process fault plans and kill -9
schedules.

A fixed (--seed, --plans) campaign is deterministic: two runs produce
identical verdicts and canonical trip ledgers (the printed line carries
a sha256 over the canonical trip ledger so CI can diff determinism
cheaply across runs).
"""

import argparse
import hashlib
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from fabric_tpu.devtools import faultfuzz  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--plans", type=int, default=25,
                    help="number of generated plans (default 25)")
    ap.add_argument("--seed", type=int, default=7,
                    help="campaign seed (default 7)")
    ap.add_argument("--blocks", type=int, default=faultfuzz.DEFAULT_BLOCKS,
                    help="single-block commits in the canned workload")
    ap.add_argument("--out", default=".faultfuzz", metavar="DIR",
                    help="repro-artifact directory (default .faultfuzz)")
    ap.add_argument("--no-shrink", action="store_true",
                    help="skip plan minimization on failures")
    ap.add_argument("--mutants", type=int, default=0, metavar="K",
                    help="per FAILING plan, derive K seeded single-"
                         "edit mutants (trigger tweak / action swap / "
                         "dropped rule) and run them through the same "
                         "judge/shrink/repro path (default 0)")
    ap.add_argument("--no-comm", action="store_true",
                    help="skip the rpc traffic phase of the workload")
    ap.add_argument("--replay", default=None, metavar="FILE",
                    help="re-arm a repro artifact instead of fuzzing; "
                         "exit 0 iff the failure reproduces (kill9 "
                         "artifacts are auto-detected and re-run "
                         "through the multi-process harness)")
    ap.add_argument("--kill9", action="store_true",
                    help="multi-process campaign: per plan, a real "
                         "topology with a seeded kill -9 schedule "
                         "(with --netsplit: composed INTO each "
                         "partition plan instead of a separate "
                         "campaign)")
    ap.add_argument("--netsplit", action="store_true",
                    help="multi-process partition campaign: per plan, "
                         "a 2-org/3-orderer topology with a seeded "
                         "majority/minority netsplit schedule (split "
                         "at height, heal on a timer), judged by the "
                         "partition-aware oracle; composes with "
                         "--kill9 schedules and a seeded per-node "
                         "faultline delay plan")
    ap.add_argument("--export-registry", nargs="?", default=None,
                    const="", metavar="PATH",
                    help="refresh the pinned chaos-coverage registry "
                         "(observer-plan discovery on the canned "
                         "workload unioned with every seam a pinned "
                         "plan rule can arm, restricted to statically "
                         "enumerated seams) and exit; PATH defaults to "
                         "the in-tree fabric_tpu/devtools/"
                         "faultmap_registry.json that fabriclint's "
                         "chaos-coverage rule cross-checks")
    ap.add_argument("--txs", type=int, default=80,
                    help="txs per kill9 campaign plan (default 80)")
    ap.add_argument("--metrics-out", default=None, metavar="DIR",
                    help="kill9 mode: arm profscope in every node and "
                         "run each plan under the netscope collector; "
                         "FAILING plans ship their netscope_seed<S>"
                         ".jsonl/.html telemetry artifacts plus "
                         "per-node CPU/lock profiles into DIR beside "
                         "the repro JSON (--replay of a kill9 artifact "
                         "honors the flag too)")
    ap.add_argument("--trace-dir", default=None, metavar="DIR",
                    help="arm tracelens for the campaign and write each "
                         "failing plan's flight-recorder dump (Chrome "
                         "trace JSON) into DIR beside the repro paths "
                         "(FABRIC_TPU_TRACE also arms it; dumps then "
                         "default beside the repro JSON in --out)")
    ap.add_argument("--profile-dir", default=None, metavar="DIR",
                    help="arm profscope for the campaign and write each "
                         "failing plan's CPU/lock profile (speedscope "
                         "JSON) into DIR beside the repro paths "
                         "(FABRIC_TPU_PROFILE also arms it; docs then "
                         "default beside the repro JSON in --out)")
    args = ap.parse_args()

    from fabric_tpu.common import profile, tracing  # noqa: E402

    if args.trace_dir and not tracing.enabled():
        # don't clobber an env-armed recorder: FABRIC_TPU_TRACE=N may
        # have sized the ring larger than the default
        tracing.arm()
    if args.profile_dir and not profile.enabled():
        # same contract as --trace-dir: FABRIC_TPU_PROFILE may already
        # have armed the sampler with a tuned cadence
        profile.arm()

    if args.export_registry is not None:
        from fabric_tpu.devtools import lint as lintmod  # noqa: E402

        path = args.export_registry or lintmod.FAULTMAP_REGISTRY_PATH
        reg = faultfuzz.export_registry(
            blocks=args.blocks, comm=not args.no_comm
        )
        with open(path, "w", encoding="utf-8") as f:
            f.write(json.dumps(reg, indent=2, sort_keys=True) + "\n")
        print(json.dumps({
            "experiment": "faultmap-registry",
            "path": path,
            "points": len(reg["points"]),
        }, sort_keys=True))
        return 0

    t0 = time.perf_counter()
    if args.replay:
        import shutil
        import tempfile

        with open(args.replay, "r", encoding="utf-8") as f:
            try:
                artifact_kind = json.load(f).get("kind", "")
            except ValueError:
                artifact_kind = ""
        if artifact_kind in ("netharness-kill9", "netharness-netsplit"):
            from fabric_tpu.devtools import netharness as nh

            flavor = artifact_kind.split("-", 1)[1]
            workdir = tempfile.mkdtemp(prefix=f"{flavor}-replay-")
            result = None
            try:
                result = nh.replay_repro(
                    args.replay, workdir,
                    metrics_out=args.metrics_out,
                )
            finally:
                # keep the workdir (node logs) for any non-clean run
                if result is not None and result["ok"]:
                    shutil.rmtree(workdir, ignore_errors=True)
            out = {
                "experiment": f"{flavor}-replay",
                "artifact": args.replay,
                "reproduced": not result["ok"],
                "verdict": nh.verdict_doc(result),
                "workdir": None if result["ok"] else workdir,
                "seconds": round(time.perf_counter() - t0, 4),
            }
            print(json.dumps(out, sort_keys=True))
            return 0 if not result["ok"] else 1

        workdir = tempfile.mkdtemp(prefix="faultfuzz-replay-")
        try:
            res = faultfuzz.replay(args.replay, workdir)
        finally:
            shutil.rmtree(workdir, ignore_errors=True)
        out = {
            "experiment": "faultfuzz-replay",
            "artifact": args.replay,
            "reproduced": bool(res["violations"]),
            "violations": res["violations"],
            "trips": len(res["trips"]),
            "seconds": round(time.perf_counter() - t0, 4),
        }
        if res.get("trace") is not None:
            # same fallback as the campaign path: --trace-dir when
            # given, else beside the repro artifacts in --out
            out["trace"] = faultfuzz.write_trace_doc(
                os.path.join(
                    args.trace_dir or args.out,
                    os.path.basename(args.replay) + ".trace.json",
                ),
                res["trace"],
            )
        if res.get("profile") is not None:
            out["profile"] = faultfuzz.write_profile_doc(
                os.path.join(
                    args.profile_dir or args.out,
                    os.path.basename(args.replay) + ".profile.json",
                ),
                res["profile"],
            )
        print(json.dumps(out))
        return 0 if res["violations"] else 1

    if args.netsplit:
        import random as _random
        import shutil
        import tempfile

        from fabric_tpu.devtools import netharness as nh

        failures = 0
        verdicts = []
        repro_paths = []
        netscope_paths = []
        trace_paths = []
        for i in range(args.plans):
            seed = args.seed + i
            topo = nh.Topology(
                orgs=2, peers_per_org=2, orderers=3, seed=seed,
                ops=args.metrics_out is not None,
                profile=args.metrics_out is not None,
                trace=args.metrics_out is not None,
            )
            expected = 1 + -(-args.txs // topo.max_message_count)
            pschedule = nh.generate_partition_schedule(
                seed, topo, expected
            )
            schedule = (
                nh.generate_kill_schedule(seed, topo, expected, kills=1)
                if args.kill9 else []
            )
            # composed per-node faultline plan: a seeded, benign
            # gossip-dial delay on one victim peer, so every netsplit
            # campaign also exercises partitions UNDER injected faults
            fl_rng = _random.Random(f"chaos-netsplit-fl:{seed}")
            victim = fl_rng.choice(topo.peer_names())
            topo.faultline = {victim: {
                "seed": seed,
                "label": f"chaos-netsplit-fl:{seed}",
                "faults": [{
                    "point": "gossip.dial", "action": "delay",
                    "delay_s": 0.02, "prob": 0.2, "count": 10,
                }],
            }}
            workdir = tempfile.mkdtemp(prefix=f"netsplit-s{seed}-")
            with nh.Network(workdir, topo) as net:
                net.start()
                scope = (
                    nh.attach_netscope(net)
                    if args.metrics_out is not None else None
                )
                result = nh.run_stream(
                    net, args.txs, schedule, scope=scope,
                    partition_schedule=pschedule,
                )
                profiles = None
                if scope is not None:
                    scope.stop()
                    if not result["ok"]:
                        profiles = scope.fetch_profiles(
                            args.metrics_out,
                            prefix=f"netscope_seed{seed}",
                        )
                        # the merged cross-process trace must also be
                        # pulled while the failing plan's nodes still
                        # answer net.TraceDump
                        trace_path = os.path.join(
                            args.metrics_out,
                            f"netscope_seed{seed}.trace.json",
                        )
                        nh.merge_traces(net, trace_path)
                        trace_paths.append(trace_path)
            verdicts.append("ok" if result["ok"] else "FAIL")
            if result["ok"]:
                shutil.rmtree(workdir, ignore_errors=True)
            else:
                failures += 1
                repro_paths.append(nh.write_repro(result, os.path.join(
                    args.out, f"netsplit_seed{seed}.repro.json"
                )))
                if scope is not None:
                    from fabric_tpu.devtools.netscope import (
                        write_artifacts,
                    )

                    netscope_paths.append(write_artifacts(
                        scope, args.metrics_out,
                        prefix=f"netscope_seed{seed}",
                        profiles=profiles,
                    ))
        out = {
            "experiment": "chaos-netsplit",
            "seed": args.seed,
            "plans": args.plans,
            "txs": args.txs,
            "kill9": bool(args.kill9),
            "failures": failures,
            "verdicts": verdicts,
            "repro": repro_paths,
            "netscope": netscope_paths,
            "trace": trace_paths,
            "seconds": round(time.perf_counter() - t0, 4),
        }
        print(json.dumps(out, sort_keys=True))
        for path in repro_paths:
            print(f"netsplit: repro artifact written: {path}",
                  file=sys.stderr)
        return 1 if failures else 0

    if args.kill9:
        import shutil
        import tempfile

        from fabric_tpu.devtools import netharness as nh

        failures = 0
        verdicts = []
        repro_paths = []
        netscope_paths = []
        for i in range(args.plans):
            seed = args.seed + i
            topo = nh.Topology(
                orgs=1, peers_per_org=2, orderers=1, seed=seed,
                ops=args.metrics_out is not None,
                profile=args.metrics_out is not None,
            )
            expected = 1 + -(-args.txs // topo.max_message_count)
            schedule = nh.generate_kill_schedule(
                seed, topo, expected, kills=1
            )
            workdir = tempfile.mkdtemp(prefix=f"kill9-s{seed}-")
            with nh.Network(workdir, topo) as net:
                net.start()
                scope = (
                    nh.attach_netscope(net)
                    if args.metrics_out is not None else None
                )
                result = nh.run_stream(
                    net, args.txs, schedule, scope=scope
                )
                profiles = None
                if scope is not None:
                    scope.stop()
                    if not result["ok"]:
                        # per-node profscope docs must be pulled HERE,
                        # while the failing plan's nodes still answer
                        # GET /profile — outside this block they are
                        # already dead
                        profiles = scope.fetch_profiles(
                            args.metrics_out,
                            prefix=f"netscope_seed{seed}",
                        )
            verdicts.append("ok" if result["ok"] else "FAIL")
            if result["ok"]:
                shutil.rmtree(workdir, ignore_errors=True)
            else:
                failures += 1
                repro_paths.append(nh.write_repro(result, os.path.join(
                    args.out, f"kill9_seed{seed}.repro.json"
                )))
                if scope is not None:
                    # evidence rides WITH the repro: the jsonl series
                    # + HTML timeline + per-node CPU/lock profiles of
                    # the exact failing run
                    from fabric_tpu.devtools.netscope import (
                        write_artifacts,
                    )

                    paths = write_artifacts(
                        scope, args.metrics_out,
                        prefix=f"netscope_seed{seed}",
                        profiles=profiles,
                    )
                    netscope_paths.append(paths)
        out = {
            "experiment": "chaos-kill9",
            "seed": args.seed,
            "plans": args.plans,
            "txs": args.txs,
            "failures": failures,
            "verdicts": verdicts,
            "repro": repro_paths,
            "netscope": netscope_paths,
            "seconds": round(time.perf_counter() - t0, 4),
        }
        print(json.dumps(out, sort_keys=True))
        for path in repro_paths:
            print(f"kill9: repro artifact written: {path}",
                  file=sys.stderr)
        return 1 if failures else 0

    campaign = faultfuzz.Campaign(
        seed=args.seed, plans=args.plans, blocks=args.blocks,
        out_dir=args.out, shrink=not args.no_shrink,
        comm=not args.no_comm, trace_dir=args.trace_dir,
        profile_dir=args.profile_dir, mutants=args.mutants,
    )
    summary = campaign.run()
    ledger_digest = hashlib.sha256(
        json.dumps(summary["trip_ledger"], sort_keys=True).encode()
    ).hexdigest()
    out = {
        "experiment": "faultfuzz",
        "seed": summary["seed"],
        "plans": summary["plans"],
        "blocks": summary["blocks"],
        "registry_points": summary["registry_points"],
        "failures": summary["failures"],
        "mutants_per_failure": summary["mutants_per_failure"],
        "mutant_failures": summary["mutant_failures"],
        "verdicts": summary["verdicts"],
        "trips_total": summary["trips_total"],
        "trip_ledger_sha256": ledger_digest,
        "repro": summary["repro"],
        "trace": summary.get("trace", []),
        "profile": summary.get("profile", []),
        "seconds": round(time.perf_counter() - t0, 4),
    }
    print(json.dumps(out))
    for path in summary["repro"]:
        print(f"faultfuzz: repro artifact written: {path}",
              file=sys.stderr)
    return 1 if summary["failures"] else 0


if __name__ == "__main__":
    sys.exit(main())
