"""Sweep device chunk size for the batched-verify e2e path."""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from fabric_tpu.csp import SWCSP
from fabric_tpu.csp import api
from fabric_tpu.csp.tpu import pallas_ec


def main():
    n = 32768
    csp = SWCSP()
    keys = [csp.key_gen() for _ in range(64)]
    tuples = []
    for i in range(n):
        key = keys[i % 64]
        d = csp.hash(b"sweep-%d" % i)
        r, s = api.unmarshal_ecdsa_signature(csp.sign(key, d))
        pub = key.public_key()
        tuples.append((pub.x, pub.y, d, r, s))
    packed = pallas_ec.prepare_packed(tuples)

    for chunk in (32768, 16384, 8192, 4096):
        def run():
            pending = []
            for off in range(0, n, chunk):
                sl = {
                    k: (v[:, off:off + chunk] if v.ndim == 2 else v[off:off + chunk])
                    for k, v in packed.items()
                }
                pending.append(pallas_ec.verify_packed(sl))
            out = []
            for c in pending:
                out.append(c())
            return np.concatenate(out)

        ok = run()  # warm-up/compile
        assert ok.all()
        best = min(
            (lambda t0: (run(), time.perf_counter() - t0)[1])(time.perf_counter())
            for _ in range(3)
        )
        print(f"chunk={chunk:6d}: {best*1e3:7.1f} ms  ({n/best:8.0f}/s)")


if __name__ == "__main__":
    main()
