"""BN254 pairing arithmetic (host reference implementation).

The reference performs its idemix pairing math on FP256BN through the
fabric-amcl library (/root/reference/idemix/util.go:20-60 GenG1/GenG2/
RandModOrder; /root/reference/idemix/signature.go:290-291 FP256BN.Ate).
This module implements the same primitive set — G1/G2 group ops, scalar
multiplication, and the optimal-ate pairing e: G1 x G2 -> GT — on the
standard BN254 curve (aka alt_bn128), entirely from the curve equations:

    Fp:   y^2 = x^3 + 3,              p = 36u^4 + 36u^3 + 24u^2 + 6u + 1
    Fp2:  y^2 = x^3 + 3/(9+i)         (D-type sextic twist)
    u = 4965661367192848881

Tower: Fp2 = Fp[i]/(i^2+1), Fp6 = Fp2[v]/(v^3-xi) with xi = 9+i,
Fp12 = Fp6[w]/(w^2-v).  The Miller loop runs in affine coordinates over
Fp12 (clarity over speed: this is the host parity oracle; the batched TPU
kernel lives in fabric_tpu/csp/tpu/).

Elements of Fp2/Fp6/Fp12 are nested tuples of ints; points are affine
(x, y) tuples with None for the identity.
"""

from __future__ import annotations

import hashlib
import secrets

# --- BN254 parameters -------------------------------------------------------

U = 4965661367192848881  # BN parameter
P = 36 * U**4 + 36 * U**3 + 24 * U**2 + 6 * U + 1
R = 36 * U**4 + 36 * U**3 + 18 * U**2 + 6 * U + 1  # group order
GROUP_ORDER = R
ATE_LOOP = 6 * U + 2

B = 3  # curve coefficient: y^2 = x^3 + 3

# G1 generator.
G1_GEN = (1, 2)

# G2 generator on the twist (canonical alt_bn128 generator), coords in Fp2
# as (c0, c1) meaning c0 + c1*i.
G2_GEN = (
    (
        10857046999023057135944570762232829481370756359578518086990519993285655852781,
        11559732032986387107991004021392285783925812861821192530917403151452391805634,
    ),
    (
        8495653923123431417604973247489272438418190587263600148770280649306958101930,
        4082367875863433681332203403145435568316851327593401208105741076214120093531,
    ),
)

# --- Fp ---------------------------------------------------------------------


def _inv(a: int) -> int:
    return pow(a, -1, P)


# --- Fp2 = Fp[i]/(i^2 + 1) --------------------------------------------------

FP2_ZERO = (0, 0)
FP2_ONE = (1, 0)
XI = (9, 1)  # nonresidue for the Fp6 tower and the twist divisor


def fp2_add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def fp2_sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def fp2_neg(a):
    return (-a[0] % P, -a[1] % P)


def fp2_mul(a, b):
    # (a0 + a1 i)(b0 + b1 i) = a0b0 - a1b1 + (a0b1 + a1b0) i
    t0 = a[0] * b[0]
    t1 = a[1] * b[1]
    t2 = (a[0] + a[1]) * (b[0] + b[1])
    return ((t0 - t1) % P, (t2 - t0 - t1) % P)


def fp2_sq(a):
    # (a0 + a1 i)^2 = (a0+a1)(a0-a1) + 2 a0 a1 i
    t = a[0] * a[1]
    return ((a[0] + a[1]) * (a[0] - a[1]) % P, (t + t) % P)


def fp2_scalar(a, k: int):
    return (a[0] * k % P, a[1] * k % P)


def fp2_inv(a):
    # 1/(a0 + a1 i) = (a0 - a1 i)/(a0^2 + a1^2)
    d = _inv((a[0] * a[0] + a[1] * a[1]) % P)
    return (a[0] * d % P, -a[1] * d % P)


def fp2_conj(a):
    return (a[0], -a[1] % P)


def fp2_pow(a, e: int):
    out = FP2_ONE
    base = a
    while e:
        if e & 1:
            out = fp2_mul(out, base)
        base = fp2_sq(base)
        e >>= 1
    return out


# --- Fp6 = Fp2[v]/(v^3 - xi) ------------------------------------------------

FP6_ZERO = (FP2_ZERO, FP2_ZERO, FP2_ZERO)
FP6_ONE = (FP2_ONE, FP2_ZERO, FP2_ZERO)


def _mul_xi(a):
    # a * (9 + i)
    return ((9 * a[0] - a[1]) % P, (9 * a[1] + a[0]) % P)


def fp6_add(a, b):
    return (fp2_add(a[0], b[0]), fp2_add(a[1], b[1]), fp2_add(a[2], b[2]))


def fp6_sub(a, b):
    return (fp2_sub(a[0], b[0]), fp2_sub(a[1], b[1]), fp2_sub(a[2], b[2]))


def fp6_neg(a):
    return (fp2_neg(a[0]), fp2_neg(a[1]), fp2_neg(a[2]))


def fp6_mul(a, b):
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = fp2_mul(a0, b0)
    t1 = fp2_mul(a1, b1)
    t2 = fp2_mul(a2, b2)
    c0 = fp2_add(
        t0,
        _mul_xi(
            fp2_sub(
                fp2_mul(fp2_add(a1, a2), fp2_add(b1, b2)), fp2_add(t1, t2)
            )
        ),
    )
    c1 = fp2_add(
        fp2_sub(fp2_mul(fp2_add(a0, a1), fp2_add(b0, b1)), fp2_add(t0, t1)),
        _mul_xi(t2),
    )
    c2 = fp2_add(
        fp2_sub(fp2_mul(fp2_add(a0, a2), fp2_add(b0, b2)), fp2_add(t0, t2)),
        t1,
    )
    return (c0, c1, c2)


def fp6_sq(a):
    return fp6_mul(a, a)


def fp6_mul_fp2(a, k):
    return (fp2_mul(a[0], k), fp2_mul(a[1], k), fp2_mul(a[2], k))


def fp6_mul_v(a):
    # a * v: (a0 + a1 v + a2 v^2) v = a2 xi + a0 v + a1 v^2
    return (_mul_xi(a[2]), a[0], a[1])


def fp6_inv(a):
    a0, a1, a2 = a
    c0 = fp2_sub(fp2_sq(a0), _mul_xi(fp2_mul(a1, a2)))
    c1 = fp2_sub(_mul_xi(fp2_sq(a2)), fp2_mul(a0, a1))
    c2 = fp2_sub(fp2_sq(a1), fp2_mul(a0, a2))
    t = fp2_inv(
        fp2_add(
            fp2_mul(a0, c0),
            _mul_xi(fp2_add(fp2_mul(a2, c1), fp2_mul(a1, c2))),
        )
    )
    return (fp2_mul(c0, t), fp2_mul(c1, t), fp2_mul(c2, t))


# --- Fp12 = Fp6[w]/(w^2 - v) ------------------------------------------------

FP12_ONE = (FP6_ONE, FP6_ZERO)
FP12_ZERO = (FP6_ZERO, FP6_ZERO)


def fp12_add(a, b):
    return (fp6_add(a[0], b[0]), fp6_add(a[1], b[1]))


def fp12_sub(a, b):
    return (fp6_sub(a[0], b[0]), fp6_sub(a[1], b[1]))


def fp12_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = fp6_mul(a0, b0)
    t1 = fp6_mul(a1, b1)
    c0 = fp6_add(t0, fp6_mul_v(t1))
    c1 = fp6_sub(
        fp6_mul(fp6_add(a0, a1), fp6_add(b0, b1)), fp6_add(t0, t1)
    )
    return (c0, c1)


def fp12_sq(a):
    return fp12_mul(a, a)


def fp12_inv(a):
    a0, a1 = a
    t = fp6_inv(fp6_sub(fp6_sq(a0), fp6_mul_v(fp6_sq(a1))))
    return (fp6_mul(a0, t), fp6_neg(fp6_mul(a1, t)))


def fp12_conj(a):
    return (a[0], fp6_neg(a[1]))


def fp12_pow(a, e: int):
    if e < 0:
        a = fp12_inv(a)
        e = -e
    out = FP12_ONE
    base = a
    while e:
        if e & 1:
            out = fp12_mul(out, base)
        base = fp12_sq(base)
        e >>= 1
    return out


# Frobenius on Fp12: x -> x^p, computed componentwise via conjugation in Fp2
# and multiplication by precomputed constants gamma_i = xi^{i(p-1)/6}.
_GAMMA = [fp2_pow(XI, i * (P - 1) // 6) for i in range(6)]


def fp12_frobenius(a):
    (a0, a1, a2), (b0, b1, b2) = a
    c0 = (
        fp2_conj(a0),
        fp2_mul(fp2_conj(a1), _GAMMA[2]),
        fp2_mul(fp2_conj(a2), _GAMMA[4]),
    )
    c1 = (
        fp2_mul(fp2_conj(b0), _GAMMA[1]),
        fp2_mul(fp2_conj(b1), _GAMMA[3]),
        fp2_mul(fp2_conj(b2), _GAMMA[5]),
    )
    return (c0, c1)


def fp12_frobenius_n(a, n: int):
    for _ in range(n):
        a = fp12_frobenius(a)
    return a


# --- G1 (affine over Fp) ----------------------------------------------------


def g1_is_on_curve(pt) -> bool:
    if pt is None:
        return True
    x, y = pt
    return (y * y - x * x * x - B) % P == 0


def g1_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        lam = (3 * x1 * x1) * _inv(2 * y1) % P
    else:
        lam = (y2 - y1) * _inv(x2 - x1) % P
    x3 = (lam * lam - x1 - x2) % P
    y3 = (lam * (x1 - x3) - y1) % P
    return (x3, y3)


def g1_neg(p1):
    if p1 is None:
        return None
    return (p1[0], -p1[1] % P)


def _native():
    """The native BN254 module, or None (memoized availability gate).
    Real native-layer errors propagate — only absence falls back."""
    global _NATIVE
    if _NATIVE is _UNSET:
        from fabric_tpu import native

        _NATIVE = native if native.available() else None
    return _NATIVE


_UNSET = object()
_NATIVE = _UNSET


def _g1_mul_py(p1, k: int):
    """Pure-Python double-and-add — the parity oracle for the native
    backend (tests/test_bn254_native.py) and the no-compiler fallback."""
    k %= R
    out = None
    add = p1
    while k:
        if k & 1:
            out = g1_add(out, add)
        add = g1_add(add, add)
        k >>= 1
    return out


def g1_mul(p1, k: int):
    if p1 is None:
        return None
    nat = _native()
    if nat is not None:
        return nat.bn254_mul_many([p1], [k])[0]
    return _g1_mul_py(p1, k)


def g1_mul_many(points, scalars):
    """Independent scalars[i]*points[i] with one shared field inversion
    (native batch path; issuance/setup fan-out)."""
    nat = _native()
    if nat is not None:
        return nat.bn254_mul_many(points, scalars)
    return [
        _g1_mul_py(p, k) if p is not None else None
        for p, k in zip(points, scalars)
    ]


def g1_msm(terms):
    """sum of scalar*point over G1: [(point|None, scalar)] -> point|None.

    The verification hot path (Schnorr commitment recomputation, RLC
    accumulation in batched verify) — served by the native Montgomery
    implementation (native/bn254.cc) when available, else the affine
    Python ladder.  The reference does the same per-base loop in AMCL
    (fabric-amcl G1mul + add)."""
    nat = _native()
    if nat is not None:
        return nat.bn254_msm([t[0] for t in terms], [t[1] for t in terms])
    out = None
    for pt, k in terms:
        if pt is None:
            continue
        out = g1_add(out, _g1_mul_py(pt, k))
    return out


# --- G2 (affine over Fp2, on the twist) -------------------------------------

_TWIST_B = fp2_mul((B, 0), fp2_inv(XI))  # b' = 3/(9+i)


def g2_is_on_curve(pt) -> bool:
    if pt is None:
        return True
    x, y = pt
    lhs = fp2_sq(y)
    rhs = fp2_add(fp2_mul(fp2_sq(x), x), _TWIST_B)
    return lhs == rhs


def g2_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if fp2_add(y1, y2) == FP2_ZERO:
            return None
        lam = fp2_mul(
            fp2_scalar(fp2_sq(x1), 3), fp2_inv(fp2_scalar(y1, 2))
        )
    else:
        lam = fp2_mul(fp2_sub(y2, y1), fp2_inv(fp2_sub(x2, x1)))
    x3 = fp2_sub(fp2_sub(fp2_sq(lam), x1), x2)
    y3 = fp2_sub(fp2_mul(lam, fp2_sub(x1, x3)), y1)
    return (x3, y3)


def g2_neg(p1):
    if p1 is None:
        return None
    return (p1[0], fp2_neg(p1[1]))


def g2_mul(p1, k: int):
    k %= R
    out = None
    add = p1
    while k:
        if k & 1:
            out = g2_add(out, add)
        add = g2_add(add, add)
        k >>= 1
    return out


# --- Pairing ----------------------------------------------------------------
#
# Optimal ate: e(P, Q) = f_{6u+2, Q'}(P) * l_{T,pi(Q')}(P) * l_{T',-pi^2(Q')}(P)
# raised to (p^12-1)/r, with Q' the image of Q in Fp12 via the twist
# embedding psi(x, y) = (x w^2, y w^3) where w^6 = xi.


def _embed_g2(pt):
    """Map a twist point into Fp12 affine coordinates."""
    x, y = pt
    # x * w^2 = x * v  -> Fp6 coeff vector (0, x, 0), Fp12 c0 part.
    ex = ((FP2_ZERO, x, FP2_ZERO), FP6_ZERO)
    # y * w^3 = y * v * w -> Fp12 c1 part with Fp6 coeff (0, y, 0).
    ey = (FP6_ZERO, (FP2_ZERO, y, FP2_ZERO))
    return (ex, ey)


def _fp12_from_fp(a: int):
    return (((a % P, 0), FP2_ZERO, FP2_ZERO), FP6_ZERO)


def _e12_add(p1, p2):
    """Affine addition over the Fp12 curve y^2 = x^3 + 3 (no twist)."""
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if fp12_add(y1, y2) == FP12_ZERO:
            return None
        lam = fp12_mul(
            fp12_mul(fp12_sq(x1), _fp12_from_fp(3)),
            fp12_inv(fp12_mul(y1, _fp12_from_fp(2))),
        )
    else:
        lam = fp12_mul(fp12_sub(y2, y1), fp12_inv(fp12_sub(x2, x1)))
    x3 = fp12_sub(fp12_sub(fp12_sq(lam), x1), x2)
    y3 = fp12_sub(fp12_mul(lam, fp12_sub(x1, x3)), y1)
    return (x3, y3)


def _line(t, q, p_xy):
    """Evaluate the line through t and q (tangent if t == q) at P in Fp.

    Returns (line_value, t + q).
    """
    xp, yp = p_xy
    xp12 = _fp12_from_fp(xp)
    yp12 = _fp12_from_fp(yp)
    if t is None or q is None:
        nonzero = t if t is not None else q
        if nonzero is None:
            return FP12_ONE, None
        return fp12_sub(xp12, nonzero[0]), nonzero
    x1, y1 = t
    if x1 == q[0] and y1 != q[1]:
        # Vertical line x - x1 = 0.
        return fp12_sub(xp12, x1), None
    if t == q:
        lam = fp12_mul(
            fp12_mul(fp12_sq(x1), _fp12_from_fp(3)),
            fp12_inv(fp12_mul(y1, _fp12_from_fp(2))),
        )
    else:
        lam = fp12_mul(
            fp12_sub(q[1], y1), fp12_inv(fp12_sub(q[0], x1))
        )
    # l(P) = yP - y1 - lam (xP - x1)
    val = fp12_sub(
        fp12_sub(yp12, y1), fp12_mul(lam, fp12_sub(xp12, x1))
    )
    return val, _e12_add(t, q)


def miller_loop(p_xy, q_twist):
    """f_{6u+2, Q}(P) with the two frobenius correction lines (unreduced)."""
    if p_xy is None or q_twist is None:
        return FP12_ONE
    q12 = _embed_g2(q_twist)
    qx, qy = q12
    t = q12
    f = FP12_ONE
    bits = bin(ATE_LOOP)[3:]  # skip leading 1
    for bit in bits:
        line, t = _line(t, t, p_xy)
        f = fp12_mul(fp12_sq(f), line)
        if bit == "1":
            line, t = _line(t, q12, p_xy)
            f = fp12_mul(f, line)
    # Frobenius corrections: Q1 = pi(Q), Q2 = -pi^2(Q).
    q1 = (fp12_frobenius(qx), fp12_frobenius(qy))
    q2 = (fp12_frobenius_n(qx, 2), fp12_frobenius_n(qy, 2))
    q2 = (q2[0], fp12_sub(FP12_ZERO, q2[1]))
    line, t = _line(t, q1, p_xy)
    f = fp12_mul(f, line)
    line, t = _line(t, q2, p_xy)
    f = fp12_mul(f, line)
    return f


_HARD_EXP = (P**4 - P**2 + 1) // R


def final_exponentiation(f):
    # Easy part: f^((p^6-1)(p^2+1)).
    f = fp12_mul(fp12_conj(f), fp12_inv(f))  # f^(p^6 - 1)
    f = fp12_mul(fp12_frobenius_n(f, 2), f)  # ^(p^2 + 1)
    # Hard part: ^((p^4 - p^2 + 1)/r) by plain square-and-multiply (host
    # oracle favors obviousness; the TPU kernel uses the decomposed form).
    return fp12_pow(f, _HARD_EXP)


def pairing(p_g1, q_g2):
    """Reduced optimal-ate pairing e(P, Q) in GT (an Fp12 element)."""
    return final_exponentiation(miller_loop(p_g1, q_g2))


def multi_pairing(pairs):
    """prod_i e(P_i, Q_i): shares one final exponentiation across the
    product — the algebraic identity behind batched idemix verification
    (reference calls FP256BN.Ate twice per signature,
    idemix/signature.go:290-291; a batch shares the expensive tail)."""
    f = FP12_ONE
    for p_g1, q_g2 in pairs:
        f = fp12_mul(f, miller_loop(p_g1, q_g2))
    return final_exponentiation(f)


def pairing_check(pairs) -> bool:
    """prod_i e(P_i, Q_i) == 1 — the only form idemix consumes
    (credential ver, weak-BB, signature checks).  Native Miller loop +
    shared final exponentiation when available (native/pairing.cc),
    else the Python towers."""
    nat = _native()
    if nat is not None:
        return nat.bn254_pairing_check(pairs)
    return multi_pairing(pairs) == FP12_ONE


# --- Group element serialization & hashing ----------------------------------


def g1_to_bytes(pt) -> bytes:
    if pt is None:
        return b"\x00" * 64
    return pt[0].to_bytes(32, "big") + pt[1].to_bytes(32, "big")


def g1_from_bytes(raw: bytes):
    if len(raw) != 64:
        raise ValueError("bad G1 encoding length")
    if raw == b"\x00" * 64:
        return None
    pt = (int.from_bytes(raw[:32], "big"), int.from_bytes(raw[32:], "big"))
    # Canonical coordinates only: a coordinate >= P would give a second
    # byte-encoding of the same point and break Fiat-Shamir hash bindings.
    if pt[0] >= P or pt[1] >= P:
        raise ValueError("G1 coordinate out of range")
    if not g1_is_on_curve(pt):
        raise ValueError("G1 point not on curve")
    # BN254 G1 has cofactor 1: on-curve implies the order-r subgroup.
    return pt


def g2_to_bytes(pt) -> bytes:
    if pt is None:
        return b"\x00" * 128
    (x0, x1), (y0, y1) = pt
    return b"".join(c.to_bytes(32, "big") for c in (x0, x1, y0, y1))


def g2_from_bytes(raw: bytes):
    if len(raw) != 128:
        raise ValueError("bad G2 encoding length")
    if raw == b"\x00" * 128:
        return None
    c = [int.from_bytes(raw[i : i + 32], "big") for i in range(0, 128, 32)]
    if any(x >= P for x in c):
        raise ValueError("G2 coordinate out of range")
    pt = ((c[0], c[1]), (c[2], c[3]))
    if not g2_is_on_curve(pt):
        raise ValueError("G2 point not on curve")
    # The twist has a large cofactor: reject points outside the order-r
    # subgroup (small-subgroup / invalid-W attacks on issuer keys).
    if g2_mul(pt, R) is not None:
        raise ValueError("G2 point not in the r-torsion subgroup")
    return pt


def gt_to_bytes(f) -> bytes:
    out = []
    for c6 in f:
        for c2 in c6:
            for c in c2:
                out.append(c.to_bytes(32, "big"))
    return b"".join(out)


def g1_gen():
    return G1_GEN


def g2_gen():
    return G2_GEN


def rand_zr(rng=None) -> int:
    """Uniform scalar in [1, r) (reference idemix/util.go RandModOrder)."""
    if rng is not None:
        return rng.randrange(1, R)
    return secrets.randbelow(R - 1) + 1


def hash_to_zr(*chunks: bytes) -> int:
    """Fiat-Shamir hash to a scalar (reference idemix/util.go HashModOrder)."""
    # fabriclint: allow[csp-seam] BN254 hash-to-field is idemix's own
    # crypto domain (dedicated Pallas kernels), outside the P-256 seam
    h = hashlib.sha256()
    for c in chunks:
        h.update(len(c).to_bytes(8, "big"))
        h.update(c)
    return int.from_bytes(h.digest(), "big") % R


class G1:
    """Namespace handle for G1 ops (functional style preferred internally)."""

    add = staticmethod(g1_add)
    mul = staticmethod(g1_mul)
    neg = staticmethod(g1_neg)
    gen = staticmethod(g1_gen)
    to_bytes = staticmethod(g1_to_bytes)
    from_bytes = staticmethod(g1_from_bytes)


class G2:
    add = staticmethod(g2_add)
    mul = staticmethod(g2_mul)
    neg = staticmethod(g2_neg)
    gen = staticmethod(g2_gen)
    to_bytes = staticmethod(g2_to_bytes)
    from_bytes = staticmethod(g2_from_bytes)
