"""fabriclint self-gate (ISSUE 3 tentpole).

Two halves:

1. The GATE: the linter runs over the whole fabric_tpu tree and must
   report zero unsuppressed violations — so a future PR that hashes
   outside the CSP seam, swallows an exception on the validation path,
   or inverts a lock order fails tier-1 here, not in review.  Every
   allowlist entry must carry a reason and match live code (unused
   entries are violations, so the allowlist only shrinks).

2. Per-rule unit tests: each rule fires on a crafted violation AND
   stays quiet on conforming code, pragmas suppress with a reason and
   are themselves checked (reason-less / unknown-rule / unused pragmas
   are meta violations), and string-embedded pragma-shaped text is
   ignored (only real comments count).
"""

import json
import subprocess
import sys

from fabric_tpu.devtools.allowlist import ALLOWLIST
from fabric_tpu.devtools.lint import (
    RULES,
    AllowEntry,
    lint_source,
    lint_tree,
)

# crafted snippets lint as if they lived at these repo-relative paths
LEDGER = "fabric_tpu/ledger/example.py"
PEER = "fabric_tpu/peer/example.py"
CSP = "fabric_tpu/csp/example.py"
GOSSIP = "fabric_tpu/gossip/example.py"  # outside exc/det scopes


def _rules(violations, suppressed=False):
    return sorted(
        v.rule for v in violations if v.suppressed == suppressed
    )


# -- the gate ----------------------------------------------------------------


def test_full_tree_is_clean():
    report = lint_tree()
    assert report.files > 150  # fabric_tpu + tests + scripts
    pretty = "\n".join(str(v) for v in report.unsuppressed)
    assert not report.unsuppressed, f"fabriclint violations:\n{pretty}"
    assert report.summary()["clean"] is True
    # advisory findings may exist, but only from relaxed-profile scopes
    assert all(
        v.path.startswith(("tests/", "scripts/")) for v in report.warnings
    )


def test_every_allowlist_entry_has_a_reviewed_reason():
    for e in ALLOWLIST:
        assert e.rule in RULES, e
        assert e.path.startswith("fabric_tpu/"), e
        assert len(e.reason.strip()) >= 20, (
            f"allowlist entry for {e.path} needs a real reason, "
            f"not {e.reason!r}"
        )


def test_cli_json_summary_and_exit_codes(tmp_path):
    proc = subprocess.run(
        [sys.executable, "-m", "fabric_tpu.devtools.lint", "--json"],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    summary = json.loads(proc.stdout.strip().splitlines()[-1])
    assert summary["tool"] == "fabriclint"
    assert summary["clean"] is True
    assert summary["violations"] == 0

    # a deliberately dirty file makes the CLI exit non-zero
    bad = tmp_path / "bad.py"
    bad.write_text("import hashlib\nD = hashlib.sha256(b'x').digest()\n")
    proc = subprocess.run(
        [sys.executable, "-m", "fabric_tpu.devtools.lint", "--json",
         "--root", str(tmp_path), "bad.py"],
        capture_output=True, text=True,
    )
    assert proc.returncode == 1
    summary = json.loads(proc.stdout.strip().splitlines()[-1])
    assert summary["clean"] is False
    assert summary["by_rule"] == {"csp-seam": 1}


# -- csp-seam ----------------------------------------------------------------


def test_csp_seam_fires_outside_the_seam():
    src = "import hashlib\nH = hashlib.sha256(b'x').digest()\n"
    assert _rules(lint_source(src, PEER)) == ["csp-seam"]
    # from-import counts too
    src = "from hashlib import sha256\n"
    assert _rules(lint_source(src, LEDGER)) == ["csp-seam"]


def test_csp_seam_quiet_inside_seam_and_through_it():
    src = "import hashlib\nH = hashlib.sha256(b'x').digest()\n"
    assert lint_source(src, CSP) == []
    assert lint_source(src, "fabric_tpu/common/hashing.py") == []
    routed = (
        "from fabric_tpu.common.hashing import sha256\n"
        "H = sha256(b'x')\n"
    )
    assert lint_source(routed, PEER) == []


# -- exception-discipline ----------------------------------------------------


def test_exception_discipline_fires_on_silent_swallow():
    src = (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        pass\n"
    )
    assert _rules(lint_source(src, PEER)) == ["exception-discipline"]
    bare = src.replace("except Exception:", "except:")
    assert _rules(lint_source(bare, LEDGER)) == ["exception-discipline"]
    trivial_return = src.replace("pass", "return None")
    assert _rules(lint_source(trivial_return, PEER)) == [
        "exception-discipline"
    ]


def test_exception_discipline_quiet_when_structured():
    logged = (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception as exc:\n"
        "        log.warning('boom: %s', exc)\n"
    )
    assert lint_source(logged, PEER) == []
    reraise = logged.replace("log.warning('boom: %s', exc)", "raise")
    assert lint_source(reraise, PEER) == []
    sentinel = logged.replace(
        "log.warning('boom: %s', exc)", "return ERR_UNKNOWN_SKI"
    )
    assert lint_source(sentinel, PEER) == []
    narrow = logged.replace("Exception as exc", "ValueError")
    assert lint_source(narrow, PEER) == []
    # out of scope: gossip may use its own error style
    swallow = logged.replace("log.warning('boom: %s', exc)", "pass")
    assert lint_source(swallow, GOSSIP) == []


# -- determinism -------------------------------------------------------------


def test_determinism_fires_on_consensus_paths():
    assert _rules(
        lint_source("import time\nT = time.time()\n",
                    "fabric_tpu/protoutil/example.py")
    ) == ["determinism"]
    assert _rules(
        lint_source("from time import time\nT = time()\n", LEDGER)
    ) == ["determinism"]
    assert _rules(
        lint_source("import random\nX = random.random()\n", PEER)
    ) == ["determinism"]
    assert _rules(
        lint_source("import json\nB = json.dumps({'a': 1})\n", LEDGER)
    ) == ["determinism"]
    # qualified and from-import spellings must not slip past the gate
    assert _rules(
        lint_source("import datetime\nN = datetime.datetime.now()\n",
                    LEDGER)
    ) == ["determinism"]
    assert _rules(
        lint_source("from datetime import datetime as dt\nN = dt.now()\n",
                    PEER)
    ) == ["determinism"]
    assert _rules(
        lint_source("from random import shuffle\nshuffle([1])\n", PEER)
    ) == ["determinism"]


def test_determinism_quiet_on_conforming_code():
    ok = (
        "import json, random, time, datetime\n"
        "B = json.dumps({'a': 1}, sort_keys=True)\n"
        "R = random.Random(7)\n"
        "from random import Random\n"
        "R2 = Random(11)\n"
        "T = time.monotonic()\n"
        "P = time.perf_counter()\n"
        "TZ = datetime.timezone.utc\n"
        "D = datetime.datetime(2020, 1, 1)\n"
    )
    assert lint_source(ok, LEDGER) == []
    # gossip's anti-entropy jitter is outside the consensus scopes
    assert lint_source("import time\nT = time.time()\n", GOSSIP) == []


# -- lock-discipline ---------------------------------------------------------


def test_lock_discipline_fires_on_bare_acquire():
    src = (
        "def f(lock):\n"
        "    lock.acquire()\n"
        "    work()\n"
        "    lock.release()\n"
    )
    assert _rules(lint_source(src, LEDGER)) == ["lock-discipline"]


def test_lock_discipline_quiet_with_try_finally_or_enter():
    # the canonical safe idiom: acquire OUTSIDE the try, immediately
    # followed by a try whose finally releases (a failed acquire never
    # reaches the finally) — quiet
    src = (
        "def f(lock):\n"
        "    lock.acquire()\n"
        "    try:\n"
        "        work()\n"
        "    finally:\n"
        "        lock.release()\n"
    )
    assert lint_source(src, LEDGER) == []
    # acquire inside the try body is also accepted (release is in a
    # finally either way)
    src = (
        "def f(lock):\n"
        "    try:\n"
        "        lock.acquire()\n"
        "        work()\n"
        "    finally:\n"
        "        lock.release()\n"
    )
    assert lint_source(src, LEDGER) == []
    enter = (
        "class L:\n"
        "    def __enter__(self):\n"
        "        self._lock.acquire()\n"
        "        return self\n"
    )
    assert lint_source(enter, LEDGER) == []


def test_lock_discipline_fires_on_with_order_inversion():
    src = (
        "def f(self):\n"
        "    with self._lock:\n"
        "        with self.commit_lock:\n"
        "            pass\n"
    )
    assert _rules(lint_source(src, LEDGER)) == ["lock-discipline"]
    ok = src.replace("self._lock", "X").replace("self.commit_lock", "Y")
    canonical = (
        "def f(self):\n"
        "    with self.commit_lock:\n"
        "        with self._lock:\n"
        "            pass\n"
    )
    assert lint_source(canonical, LEDGER) == []


def test_lock_discipline_fires_on_blocking_io_under_commit_lock():
    src = (
        "import os\n"
        "def f(self, fd):\n"
        "    with self.commit_lock:\n"
        "        os.fsync(fd)\n"
    )
    assert _rules(lint_source(src, LEDGER)) == ["lock-discipline"]
    # ...including transitively through a same-class helper
    helper = (
        "import os\n"
        "class Ledger:\n"
        "    def _flush(self):\n"
        "        os.fsync(self.fd)\n"
        "    def commit(self):\n"
        "        with self.commit_lock:\n"
        "            self._flush()\n"
    )
    assert _rules(lint_source(helper, LEDGER)) == ["lock-discipline"]
    outside = (
        "import os\n"
        "def f(self, fd):\n"
        "    with self._lock:\n"
        "        pass\n"
        "    os.fsync(fd)\n"
    )
    assert lint_source(outside, LEDGER) == []


# -- jax-hygiene -------------------------------------------------------------


def test_jax_hygiene_fires_on_per_item_host_sync():
    src = (
        "def f(xs):\n"
        "    for x in xs:\n"
        "        x.block_until_ready()\n"
    )
    assert _rules(lint_source(src, "fabric_tpu/csp/tpu/example.py")) == [
        "jax-hygiene"
    ]
    batched = (
        "def f(out):\n"
        "    out.block_until_ready()\n"
    )
    assert lint_source(batched, "fabric_tpu/csp/tpu/example.py") == []


# -- suppression machinery ---------------------------------------------------


def test_pragma_suppresses_with_reason():
    src = (
        "import hashlib\n"
        "# fabriclint: allow[csp-seam] reviewed: legacy fingerprint\n"
        "H = hashlib.sha256(b'x').digest()\n"
    )
    vs = lint_source(src, PEER)
    assert _rules(vs) == []  # nothing unsuppressed
    assert _rules(vs, suppressed=True) == ["csp-seam"]
    assert all("legacy fingerprint" in v.suppression
               for v in vs if v.suppressed)


def test_pragma_reaches_through_wrapped_comment_blocks():
    # pragma two comment lines above the flagged line (wrapped reason)
    above = (
        "import hashlib\n"
        "# fabriclint: allow[csp-seam] reviewed: a reason that wraps\n"
        "# onto a second comment line before the code\n"
        "H = hashlib.sha256(b'x').digest()\n"
    )
    assert _rules(lint_source(above, PEER)) == []
    # pragma inside the handler body of a flagged `except` opener
    below = (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        # fabriclint: allow[exception-discipline] reviewed ok\n"
        "        pass\n"
    )
    assert _rules(lint_source(below, PEER)) == []


def test_pragma_does_not_leak_to_the_statement_above():
    # a pragma written for the NEXT statement must not also grant the
    # statement ABOVE it — each suppression covers exactly one reviewed
    # site, so the audit surface never widens by adjacency
    src = (
        "import hashlib\n"
        "A = hashlib.sha256(b'a').digest()\n"
        "# fabriclint: allow[csp-seam] reviewed: only B\n"
        "B = hashlib.sha256(b'b').digest()\n"
    )
    vs = lint_source(src, PEER)
    assert [v.line for v in vs if not v.suppressed] == [2]
    assert [v.line for v in vs if v.suppressed] == [4]


def test_pragma_without_reason_is_a_violation():
    src = (
        "import hashlib\n"
        "# fabriclint: allow[csp-seam]\n"
        "H = hashlib.sha256(b'x').digest()\n"
    )
    assert "pragma" in _rules(lint_source(src, PEER))


def test_unused_and_unknown_pragmas_are_violations():
    unused = "# fabriclint: allow[csp-seam] nothing here to suppress\nX = 1\n"
    assert _rules(lint_source(unused, PEER)) == ["pragma"]
    unknown = (
        "# fabriclint: allow[no-such-rule] typo'd rule name\nX = 1\n"
    )
    rules = _rules(lint_source(unknown, PEER))
    assert rules.count("pragma") == 2  # unknown rule AND unused


def test_pragma_shaped_text_in_strings_is_ignored():
    src = (
        'DOC = "*# fabriclint: allow[csp-seam] example in docs*"\n'
        "import hashlib\n"
        "H = hashlib.sha256(b'x').digest()\n"
    )
    # the string pragma neither suppresses nor registers as unused
    assert _rules(lint_source(src, PEER)) == ["csp-seam"]


def test_allowlist_entry_suppresses_and_unused_entry_flags():
    src = "import time\nT = time.time()\n"
    entry = AllowEntry(
        rule="determinism", path=LEDGER, match="time.time()",
        reason="test entry",
    )
    used = set()
    vs = lint_source(src, LEDGER, allowlist=[entry], used_entries=used)
    assert _rules(vs) == []
    assert used == {0}
    # an entry matching nothing is reported by lint_tree as a violation
    report = lint_tree(allowlist=list(ALLOWLIST) + [AllowEntry(
        rule="determinism", path="fabric_tpu/peer/nope.py",
        match="never-matches", reason="dead entry",
    )])
    dead = [v for v in report.unsuppressed if v.rule == "allowlist"]
    assert len(dead) == 1 and "never-matches" in dead[0].message



# -- taint (unit; the fixture corpus in test_lint_fixtures.py covers the
# cross-function and clean-twin cases) ---------------------------------------


def test_taint_fires_at_the_sink_not_the_source():
    src = (
        "import time\n"
        "from fabric_tpu.protos.common import common_pb2\n"
        "def f():\n"
        "    t = time.time()\n"
        "    hdr = common_pb2.BlockHeader(number=int(t))\n"
    )
    vs = [v for v in lint_source(src, "fabric_tpu/orderer/x.py")
          if v.rule == "taint" and not v.suppressed]
    assert [v.line for v in vs] == [5]  # the constructor, not line 4


def test_taint_ignores_monotonic_and_seeded_random():
    src = (
        "import time, random\n"
        "from fabric_tpu.protos.common import common_pb2\n"
        "def f(rng: random.Random):\n"
        "    t = time.monotonic()\n"
        "    r = random.Random(7)\n"
        "    hdr = common_pb2.BlockHeader(number=int(t))\n"
        "    return hdr.SerializeToString()\n"
    )
    assert lint_source(src, "fabric_tpu/orderer/x.py") == []


def test_taint_follows_fstrings():
    src = (
        "import time\n"
        "from fabric_tpu.protos.common import common_pb2\n"
        "def f():\n"
        "    label = f'at-{time.time()}'\n"
        "    return common_pb2.ChannelHeader(channel_id=label)\n"
    )
    vs = [v for v in lint_source(src, "fabric_tpu/orderer/x.py")
          if v.rule == "taint"]
    assert [v.line for v in vs] == [5]


# -- profiles ----------------------------------------------------------------


def test_relaxed_profile_disables_determinism_and_advisories_seam():
    # tests/ fabricate timestamps by design: determinism/taint off
    src = "import time\nT = time.time()\n"
    assert lint_source(src, "tests/test_example.py") == []
    # ...and hashing expectations directly is advisory, not an error
    hsrc = "import hashlib\nH = hashlib.sha256(b'x').digest()\n"
    vs = lint_source(hsrc, "tests/test_example.py")
    assert [v.severity for v in vs] == ["warning"]
    assert [v.rule for v in vs] == ["csp-seam"]
    # thread-hygiene stays at error even under the relaxed profile
    tsrc = (
        "import threading\n"
        "t = threading.Thread(target=print, daemon=True)\n"
    )
    vs = lint_source(tsrc, "scripts/example.py")
    assert [(v.rule, v.severity) for v in vs] == [
        ("thread-hygiene", "error")
    ]


# -- baseline ratchet --------------------------------------------------------


def test_baseline_ratchet_tolerates_exactly_the_budget(tmp_path):
    from fabric_tpu.devtools.lint import apply_baseline, lint_sources

    dirty = (
        "import threading\n"
        "a = threading.Thread(target=print, daemon=True)\n"
        "b = threading.Thread(target=print, daemon=True)\n"
    )
    report = lint_sources({"fabric_tpu/gossip/x.py": dirty})
    assert report.summary()["by_rule"] == {"thread-hygiene": 2}
    assert apply_baseline(report, {"thread-hygiene": 2})["ok"]
    under = apply_baseline(report, {"thread-hygiene": 1})
    assert not under["ok"] and under["over_budget"] == {"thread-hygiene": 1}
    # a budget looser than reality is itself a failure: the ratchet
    # only tightens, so stale carve-outs die with the violations
    stale = apply_baseline(report, {"thread-hygiene": 3})
    assert not stale["ok"] and stale["stale_budget"] == {"thread-hygiene": 3}


def test_baseline_cli_roundtrip(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import threading\n"
        "t = threading.Thread(target=print, daemon=True)\n"
    )
    base = tmp_path / "baseline.json"
    # write the baseline from the dirty state...
    proc = subprocess.run(
        [sys.executable, "-m", "fabric_tpu.devtools.lint", "--json",
         "--root", str(tmp_path), "--write-baseline", str(base), "bad.py"],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(base.read_text()) == {"thread-hygiene": 1}
    # ...under which the same tree passes (ratcheted, not clean)
    proc = subprocess.run(
        [sys.executable, "-m", "fabric_tpu.devtools.lint", "--json",
         "--root", str(tmp_path), "--baseline", str(base), "bad.py"],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    summary = json.loads(proc.stdout.strip().splitlines()[-1])
    assert summary["baseline"]["ok"] is True
    assert summary["baseline"]["ratcheted"] == 1
    # fixing the tree makes the stale budget fail until it is deleted
    bad.write_text("x = 1\n")
    proc = subprocess.run(
        [sys.executable, "-m", "fabric_tpu.devtools.lint", "--json",
         "--root", str(tmp_path), "--baseline", str(base), "bad.py"],
        capture_output=True, text=True,
    )
    assert proc.returncode == 1
    summary = json.loads(proc.stdout.strip().splitlines()[-1])
    assert summary["baseline"]["stale_budget"] == {"thread-hygiene": 1}


def test_hash_seam_rejects_non_sha256_backend():
    # the seam feeds consensus bytes: a backend that is not literal
    # SHA-256 must be refused at install time, not fork the peer later
    import hashlib

    from fabric_tpu.common import hashing

    class Bad:
        def hash(self, b):
            return hashlib.sha1(b).digest()

        def hash_batch(self, bs):
            return [hashlib.sha1(b).digest() for b in bs]

    class Good:
        def hash(self, b):
            return hashlib.sha256(b).digest()

        def hash_batch(self, bs):
            return [hashlib.sha256(b).digest() for b in bs]

    try:
        import pytest

        with pytest.raises(ValueError, match="byte-identical"):
            hashing.set_hash_backend(Bad())
        hashing.set_hash_backend(Good())
        assert hashing.sha256(b"x") == hashlib.sha256(b"x").digest()
    finally:
        hashing.set_hash_backend(None)


def test_rejected_backend_is_not_installed_as_default():
    # a provider the seam probe refuses must not be left as the process
    # default — get_default() users would hash through the rejected
    # backend while the seam stays on hashlib (split-brain digests)
    import hashlib
    import importlib.util

    import pytest

    if importlib.util.find_spec("cryptography") is None:
        pytest.skip("csp.factory needs cryptography; minimal host")
    from fabric_tpu.csp import factory

    class Sha1CSP:
        def hash(self, b):
            return hashlib.sha1(b).digest()

        def hash_batch(self, bs):
            return [hashlib.sha1(b).digest() for b in bs]

    before = factory._default
    with pytest.raises(ValueError, match="byte-identical"):
        factory._install_default(Sha1CSP())
    assert factory._default is before


def test_racecheck_is_enforced_at_error_with_no_baseline():
    """ISSUE 7 acceptance: racecheck is a first-class rule, on at error
    severity in the strict profile, and the tree gate above runs with
    no baseline file — so any unsuppressed racecheck finding fails
    tier-1."""
    from fabric_tpu.devtools.lint import RELAXED_PROFILE, STRICT_PROFILE

    assert "racecheck" in RULES
    assert "racecheck" not in STRICT_PROFILE.disabled
    assert "racecheck" not in STRICT_PROFILE.advisory
    assert "racecheck" in RELAXED_PROFILE.disabled
    import glob
    import os

    from fabric_tpu.devtools.lint import repo_root

    assert not glob.glob(os.path.join(repo_root(), "*baseline*.json")), (
        "the tree must stay clean with NO baseline ratchet file"
    )


# -- dataflow cache (ISSUE 7 satellite) --------------------------------------


def _report_json(report) -> str:
    """Everything observable about a lint run, as canonical JSON —
    cache hits must be indistinguishable from cold runs.  Since v4 the
    observable surface includes the lock-order graph (and the HB facts
    folded into the guard map), so the identity pin covers them too."""
    summary = {k: v for k, v in report.summary().items() if k != "cache"}
    return json.dumps({
        "violations": [v.to_dict() for v in report.violations],
        "summary": summary,
        "summaries": report.function_summaries(),
        "guards": report.guard_map(),
        "lockgraph": report.lock_graph(),
        # v5: the chaos-coverage faultmap and the CFG facts riding the
        # function summaries are cached artifacts too
        "faultmap": report.faultmap(),
        # v6: the three surface-conformance artifacts are cached too —
        # a cache hit must serve them byte-identical to the cold run
        "rpcmap": report.rpcmap(),
        "knobs": report.knobmap(),
        "metricmap": report.metricmap(),
    }, sort_keys=True)


def _write_cache_tree(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir(exist_ok=True)
    (pkg / "mod.py").write_text(
        "import threading\n"
        "def go():\n"
        "    t = threading.Thread(target=print, daemon=True)\n"
        "    t.start()\n"
        "    t.join()\n"  # lifecycle-quiet: only thread-hygiene fires
    )
    (pkg / "helper.py").write_text(
        "def double(x):\n"
        "    return 2 * x\n"
    )
    # a nested named-lock acquisition so the cached lock-order graph is
    # non-empty — the identity pin must cover real lockgraph content
    (pkg / "locks.py").write_text(
        "from fabric_tpu.devtools.lockwatch import named_lock\n"
        "class P:\n"
        "    def __init__(self):\n"
        "        self._a = named_lock('cachefix.a')\n"
        "        self._b = named_lock('cachefix.b')\n"
        "    def go(self):\n"
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n"
    )
    # a branchy function so the cached summaries carry a real CFG-facts
    # block (v5) — the identity pin must cover it
    (pkg / "branchy.py").write_text(
        "def walk(items):\n"
        "    total = 0\n"
        "    for it in items:\n"
        "        if it:\n"
        "            total += 1\n"
        "    return total\n"
    )


def test_dataflow_cache_hit_matches_cold_run_exactly(tmp_path):
    from fabric_tpu.devtools.lint import lint_tree

    _write_cache_tree(tmp_path)
    cold = lint_tree(root=str(tmp_path), targets=("pkg",))
    assert cold.cache_state == "miss"
    assert cold.summary()["by_rule"] == {"thread-hygiene": 1}
    # the cold summaries carry real CFG facts for the identity pin
    assert any(
        s.get("cfg", {}).get("back_edges") for s in cold.function_summaries()
    )
    hit = lint_tree(root=str(tmp_path), targets=("pkg",))
    assert hit.cache_state == "hit"
    assert hit.project is None  # served without re-analysis
    assert _report_json(hit) == _report_json(cold)
    # the lockgraph served from cache is the real graph, not a stub
    assert hit.lock_graph()["edges"]["cachefix.a"]["cachefix.b"]
    # the escape hatch bypasses the cache entirely
    off = lint_tree(root=str(tmp_path), targets=("pkg",), cache=False)
    assert off.cache_state == "off"
    assert _report_json(off) == _report_json(cold)


def test_dataflow_cache_invalidates_on_any_file_edit(tmp_path):
    from fabric_tpu.devtools.lint import lint_tree

    _write_cache_tree(tmp_path)
    first = lint_tree(root=str(tmp_path), targets=("pkg",))
    assert first.cache_state == "miss"
    # editing ONE file must invalidate (content-hash keyed)
    (tmp_path / "pkg" / "helper.py").write_text(
        "def double(x):\n"
        "    return x + x\n"
    )
    second = lint_tree(root=str(tmp_path), targets=("pkg",))
    assert second.cache_state == "miss"
    # unchanged tree -> hit again
    third = lint_tree(root=str(tmp_path), targets=("pkg",))
    assert third.cache_state == "hit"


def test_ci_wrapper_guards_out_writes_artifact(tmp_path):
    """scripts/lint.py --guards-out PATH (ISSUE 7 satellite): the
    inferred guarded-by map lands as a JSON artifact next to the
    result line, declared entries included, so reviewers can diff
    guard inference across PRs."""
    import os

    from fabric_tpu.devtools.lint import repo_root

    root = repo_root()
    out_path = tmp_path / "guards.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "scripts", "lint.py"),
         "--guards-out", str(out_path)],
        capture_output=True, text=True, cwd=root,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert result["experiment"] == "fabriclint"
    assert result["guards"]["path"] == str(out_path)
    guards = json.loads(out_path.read_text())
    assert len(guards) == result["guards"]["fields"] > 20
    active = guards["fabric_tpu.ledger.kvledger.KVLedger._active_group"]
    assert active["guard"] == "kvledger.commit_lock"
    assert active["source"] == "declared"
    assert active["sites"] > 0
    # majority inference is represented too
    assert any(g["source"] == "inferred" for g in guards.values())


def test_v4_rules_enforced_at_error_with_no_baseline():
    """ISSUE 13 acceptance: lock-order and thread-lifecycle are
    first-class rules, on at error severity in the strict profile, off
    under the relaxed profile like racecheck, and the tree gate runs
    with no baseline file."""
    from fabric_tpu.devtools.lint import RELAXED_PROFILE, STRICT_PROFILE

    for rule in ("lock-order", "thread-lifecycle"):
        assert rule in RULES
        assert rule not in STRICT_PROFILE.disabled
        assert rule not in STRICT_PROFILE.advisory
        assert rule in RELAXED_PROFILE.disabled
    import glob
    import os

    from fabric_tpu.devtools.lint import repo_root

    assert not glob.glob(os.path.join(repo_root(), "*baseline*.json")), (
        "the tree must stay clean with NO baseline ratchet file"
    )


def test_static_lock_graph_is_cycle_free_and_covers_commit_path():
    """The whole-tree acquisition-order graph has no cycles (the gate
    would fail otherwise — this pins the property by name) and contains
    the canonical commit-path ordering the runtime watchdog enforces:
    commit_lock before the snapshot manager/idle locks."""
    from fabric_tpu.devtools.lint import _lock_order_cycles

    report = lint_tree()
    graph = report.lock_graph()
    assert list(_lock_order_cycles(graph)) == []
    commit_succ = graph["edges"]["kvledger.commit_lock"]
    assert "snapshot.manager" in commit_succ
    assert "snapshot.idle" in commit_succ
    # every recorded site is a production site (tests/scripts excluded)
    for _src, dsts in graph["edges"].items():
        for _dst, sites in dsts.items():
            for rel, _line in sites:
                assert not rel.startswith(("tests/", "scripts/")), rel


def test_hb_edges_prove_production_sites_safe():
    """ISSUE 13 acceptance pin: accesses that v3 could only cover with
    a guards.py declaration (or leave in the no-guard/UNKNOWN hole) are
    now positively proven by happens-before edges.

    * ``SnapshotManager._inflight`` is guards.py-DECLARED, and the
      background-export write is additionally HB-proven (``hb_safe``
      rides the declared entry).
    * ``RaftChain._probe_inflight`` (consensus loop vs eviction
      confirm) and ``RPCServer._thread`` (start/join lifecycle) carry
      NO lock anywhere — v4 resolves them as ``hb-publish``: every
      access publication-ordered, no guard needed, racecheck can still
      fire if a future edit adds an unordered access."""
    guards = lint_tree().guard_map()
    inflight = guards["fabric_tpu.ledger.snapshot.SnapshotManager._inflight"]
    assert inflight["source"] == "declared"
    assert inflight.get("hb_safe", 0) >= 1
    for field in (
        "fabric_tpu.orderer.raft.chain.RaftChain._probe_inflight",
        "fabric_tpu.comm.rpc.RPCServer._thread",
    ):
        g = guards[field]
        assert g["source"] == "hb-publish"
        assert g["guard"] is None
        assert g["hb_safe"] == g["sites"] > 0


def test_ci_wrapper_lockgraph_out_writes_artifact(tmp_path):
    """scripts/lint.py --lockgraph-out PATH (ISSUE 13 satellite): the
    static acquisition-order graph lands as a JSON artifact next to the
    result line, in the exact shape the runtime-⊆-static cross-check
    consumes."""
    import os

    from fabric_tpu.devtools.lint import repo_root

    root = repo_root()
    out_path = tmp_path / "lockgraph.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "scripts", "lint.py"),
         "--lockgraph-out", str(out_path)],
        capture_output=True, text=True, cwd=root,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert result["experiment"] == "fabriclint"
    assert result["lockgraph"]["path"] == str(out_path)
    graph = json.loads(out_path.read_text())
    assert result["lockgraph"]["roles"] == len(graph["roles"])
    assert result["lockgraph"]["edges"] == sum(
        len(d) for d in graph["edges"].values()
    ) > 10
    sites = graph["edges"]["kvledger.commit_lock"]["snapshot.manager"]
    assert all(
        isinstance(rel, str) and isinstance(line, int)
        for rel, line in sites
    )


def test_ci_wrapper_summaries_out_writes_artifact(tmp_path):
    """scripts/lint.py --summaries-out PATH (ISSUE 6 satellite): the
    per-function dataflow summaries land as a JSON-lines artifact next
    to the bench-style result line."""
    import os

    from fabric_tpu.devtools.lint import repo_root

    root = repo_root()
    out_path = tmp_path / "summaries.jsonl"
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "scripts", "lint.py"),
         "--summaries-out", str(out_path)],
        capture_output=True, text=True, cwd=root,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert result["experiment"] == "fabriclint"
    assert result["summaries"]["path"] == str(out_path)
    lines = out_path.read_text().strip().splitlines()
    assert len(lines) == result["summaries"]["functions"] > 100
    sample = json.loads(lines[0])
    assert "function" in sample and "file" in sample


# -- v5 "flowcheck": CFG facts, hb-publish floor, chaos-coverage -------------


def test_v5_chaos_coverage_enforced_at_error_in_both_profiles():
    """ISSUE 18 acceptance: chaos-coverage is a first-class rule, on at
    error severity in BOTH profiles (a test plan is coverage, so tests
    must lint it), and the tree gate still runs with no baseline."""
    from fabric_tpu.devtools.lint import RELAXED_PROFILE, STRICT_PROFILE

    assert "chaos-coverage" in RULES
    for prof in (STRICT_PROFILE, RELAXED_PROFILE):
        assert "chaos-coverage" not in prof.disabled
        assert "chaos-coverage" not in prof.advisory
    import glob
    import os

    from fabric_tpu.devtools.lint import repo_root

    assert not glob.glob(os.path.join(repo_root(), "*baseline*.json")), (
        "the tree must stay clean with NO baseline ratchet file"
    )


def test_hb_publish_count_does_not_decrease_vs_v4():
    """ISSUE 18 acceptance: the CFG-ordered happens-before pass must
    convert conservative silences into proofs, never lose them — the
    v4 guard map carried 171 hb-publish resolutions; v5 holds the
    floor (and production sites gained flow-sensitive CFG facts)."""
    report = lint_tree()
    guards = report.guard_map()
    hb = [g for g in guards.values() if g["source"] == "hb-publish"]
    assert len(hb) >= 171
    # per-function CFG facts are live on the production tree: loops
    # produce back edges, branches produce multi-block functions
    summaries = report.function_summaries()
    cfgs = [s["cfg"] for s in summaries if "cfg" in s]
    assert len(cfgs) > 200
    assert any(c["back_edges"] for c in cfgs)
    # no production function uses a bare acquire/release pair (all
    # critical sections are `with`-scoped), so flow_locks stays empty
    # tree-wide — the explicit-pair half of the flow lockset is pinned
    # by the fix_flow_branchlock / fix_flow_earlyret fixtures
    assert not any(c.get("flow_locks") for c in cfgs)


def test_faultmap_matches_pinned_registry_and_is_deterministic():
    """ISSUE 18 acceptance: the tree's chaos-coverage cross-check is
    green — every statically enumerated seam is armable (exact pin,
    prefix wildcard, or pinned campaign-registry entry) — and the
    pinned registry never names a seam the static scan cannot see
    (registry ⊆ faultmap, the same containment direction tier-1 pins
    for runtime-lockgraph ⊆ static)."""
    from fabric_tpu.devtools.lint import load_faultmap_registry

    report = lint_tree()
    fm = report.faultmap()
    assert not [v for v in report.unsuppressed
                if v.rule == "chaos-coverage"]
    seam_names = {s["name"] for s in fm["seams"]}
    assert len(seam_names) > 30
    assert not fm["dynamic"], "every production seam name is a literal"
    registry = load_faultmap_registry()
    assert len(registry) > 30
    for name, ent in registry.items():
        assert name in seam_names, (
            f"pinned registry names unknown seam {name!r} — stale "
            "export; refresh with scripts/chaos.py --export-registry"
        )
        kinds = {s["kind"] for s in fm["seams"] if s["name"] == name}
        assert set(ent["kinds"]) <= kinds, name
    # the faultmap artifact is byte-deterministic across runs
    a = json.dumps(fm, sort_keys=True)
    b = json.dumps(lint_tree(cache=False).faultmap(), sort_keys=True)
    assert a == b


def test_ci_wrapper_faultmap_out_and_warm_cache_budget(tmp_path):
    """scripts/lint.py --faultmap-out PATH + --budget-s S (ISSUE 18
    satellite): the faultmap lands as a JSON artifact beside the
    result line, and a warm-cache full-tree pass fits the 1.5 s budget
    the CI gate asserts — the CFG pass cannot quietly double tier-1
    setup cost."""
    import os

    from fabric_tpu.devtools.lint import repo_root

    root = repo_root()
    out_path = tmp_path / "faultmap.json"
    # first run warms the cache (no budget: it may be a cold miss)
    warm = subprocess.run(
        [sys.executable, os.path.join(root, "scripts", "lint.py")],
        capture_output=True, text=True, cwd=root,
    )
    assert warm.returncode == 0, warm.stdout + warm.stderr
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "scripts", "lint.py"),
         "--faultmap-out", str(out_path), "--budget-s", "1.5"],
        capture_output=True, text=True, cwd=root,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert result["experiment"] == "fabriclint"
    assert result["cache"] == "hit"
    assert result["budget"] == {"budget_s": 1.5, "ok": True}
    assert result["faultmap"]["path"] == str(out_path)
    fm = json.loads(out_path.read_text())
    assert result["faultmap"]["seams"] == len(fm["seams"]) > 50
    assert result["faultmap"]["plans"] == len(fm["plans"]) > 50
    sample = fm["seams"][0]
    assert {"name", "kind", "module", "line"} <= set(sample)


# -- v6 "surfcheck": rpc/knob/metrics conformance ----------------------------


def test_v6_surface_trio_enforced_at_error_with_no_baseline():
    """ISSUE 19 acceptance: rpc-conformance, knob-conformance, and
    metrics-conformance bring the rule count to 14, all on at error
    severity in the strict profile with no baseline — and off under
    the relaxed profile (they anchor at production sites only)."""
    from fabric_tpu.devtools.lint import RELAXED_PROFILE, STRICT_PROFILE

    assert len(RULES) == 14
    for rule in ("rpc-conformance", "knob-conformance",
                 "metrics-conformance"):
        assert rule in RULES
        assert rule not in STRICT_PROFILE.disabled
        assert rule not in STRICT_PROFILE.advisory
        assert rule in RELAXED_PROFILE.disabled
    import glob
    import os

    from fabric_tpu.devtools.lint import repo_root

    assert not glob.glob(os.path.join(repo_root(), "*baseline*.json")), (
        "the tree must stay clean with NO baseline ratchet file"
    )


def test_v6_tree_artifacts_cover_the_real_surfaces():
    """The whole-tree artifacts are non-degenerate: every gateway/
    deliver/participation method is mapped with both register and call
    sites, every registry knob has a read site, and the metric planes
    carry the production series netscope consumes."""
    from fabric_tpu.devtools import knob_registry

    report = lint_tree()
    rpc = report.rpcmap()["methods"]
    assert len(rpc) >= 25
    for method in ("ab.Broadcast", "deliver.DeliverFiltered",
                   "participation.List", "endorser.ProcessProposal",
                   "net.TraceDump"):
        assert rpc[method]["registers"], method
        assert rpc[method]["calls"], method
    knobs = report.knobmap()
    assert set(knobs["registry"]) == set(knob_registry.KNOBS)
    read_names = {r["name"] for r in knobs["reads"]}
    assert read_names == set(knob_registry.KNOBS)
    assert knobs["dynamic"] == []
    mm = report.metricmap()
    assert all(p["registered"] for p in mm["producers"])
    assert len(mm["exposed"]) >= 60
    consumed = {c["name"] for c in mm["consumers"]}
    assert "ledger_height" in consumed
    assert consumed <= set(mm["exposed"])


def test_ci_wrapper_v6_artifacts_byte_identical_cold_vs_hit(tmp_path):
    """scripts/lint.py --rpcmap-out/--knobs-out/--metricmap-out (ISSUE
    19 satellite): all three artifacts land beside the result line,
    and a --no-cache cold pass writes byte-identical files to a
    warm-cache hit — determinism of the cached artifact plane."""
    import os

    from fabric_tpu.devtools.lint import repo_root

    root = repo_root()

    def run(tag, *extra):
        paths = {
            kind: str(tmp_path / f"{kind}_{tag}.json")
            for kind in ("rpcmap", "knobs", "metricmap")
        }
        proc = subprocess.run(
            [sys.executable, os.path.join(root, "scripts", "lint.py"),
             "--rpcmap-out", paths["rpcmap"],
             "--knobs-out", paths["knobs"],
             "--metricmap-out", paths["metricmap"], *extra],
            capture_output=True, text=True, cwd=root,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        result = json.loads(proc.stdout.strip().splitlines()[-1])
        return result, paths

    cold, cold_paths = run("cold", "--no-cache")
    assert cold["cache"] == "off"
    hit, hit_paths = run("hit")
    assert hit["cache"] == "hit"
    for kind in ("rpcmap", "knobs", "metricmap"):
        a = open(cold_paths[kind], "rb").read()
        b = open(hit_paths[kind], "rb").read()
        assert a == b, f"{kind} artifact differs cold vs hit"
    assert hit["rpcmap"]["methods"] >= 25
    assert hit["knobs"]["knobs"] == 18
    assert hit["knobs"]["reads"] >= 16
    assert hit["metricmap"]["producers"] >= 40
    assert hit["metricmap"]["exposed"] >= 60
