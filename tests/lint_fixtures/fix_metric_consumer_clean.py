"""Clean twin of fix_metric_consumer_dirty: every consumed series name
has a registered producer — metrics-conformance stays quiet."""

from fabric_tpu.common.metrics import CounterOpts


def wire(provider):
    return provider.new_counter(
        CounterOpts(namespace="fix", name="events_total")
    )


def watch(scope, node):
    return scope.series(node, "fix_events_total")


def boot(provider, scope, node):
    wire(provider)
    return watch(scope, node)
