"""netsplit — deterministic network-partition injection (ISSUE 20
tentpole).

Tier-1 pins:
- plan validation rejects malformed partition specs loudly;
- the seam is ZERO-overhead while no plan is armed (lookup counter);
- ``full`` denies cross-group links both ways, ``oneway`` only from an
  earlier-listed group toward a later one, ``flaky`` draws per-link
  deterministic drop streams (two same-seed plans replay identically);
- :class:`NetsplitDenied` is an ``OSError`` — the transports' existing
  connect-failure paths route it fast, no connect-timeout stall;
- arming a full/oneway plan CUTS tracked established connections on
  severed links (and a heal does NOT: reconnects ride the seam);
- ``netsplit.deny`` / ``netsplit.cut`` are real faultline seams: a
  pinned plan rule arms each and the injected fault demonstrably fires
  (chaos-coverage rule 11's arming-test contract);
- a flaky-link plan drives the deliver client's whole rotation/backoff
  cycle under the virtual clock with ZERO real sleeps;
- the env knob (``FABRIC_TPU_NETSPLIT``) arms inline/@file plans and
  falsy values disarm;
- the gossip dial timeout routes through ``FABRIC_TPU_DIAL_TIMEOUT_S``.
"""

from __future__ import annotations

import json
import socket
import time

import pytest

from fabric_tpu.devtools import clockskew, faultline, netsplit
from fabric_tpu.protos.common import common_pb2


def _plan(mode="full", groups=None, **kw):
    d = {"seed": 7, "mode": mode,
         "groups": groups or [["a", "b"], ["c"]]}
    d.update(kw)
    return d


# -- plan validation ----------------------------------------------------------


def test_plan_validation_errors():
    with pytest.raises(netsplit.PlanError):
        netsplit.Plan("not json{")
    with pytest.raises(netsplit.PlanError):
        netsplit.Plan(_plan(mode="half"))
    with pytest.raises(netsplit.PlanError):
        netsplit.Plan(_plan(groups=[["a"]]))  # < 2 groups
    with pytest.raises(netsplit.PlanError):
        netsplit.Plan(_plan(groups=[["a"], []]))  # empty group
    with pytest.raises(netsplit.PlanError):
        netsplit.Plan(_plan(groups=[["a"], ["a"]]))  # overlap
    with pytest.raises(netsplit.PlanError):
        netsplit.Plan(_plan(p=1.5))
    with pytest.raises(netsplit.PlanError):
        netsplit.Plan(_plan(node=""))
    with pytest.raises(netsplit.PlanError):
        netsplit.Plan(_plan(addrs={"x": 3}))
    # a valid plan round-trips through as_dict
    p = netsplit.Plan(_plan(addrs={"127.0.0.1:9001": "c"}))
    assert netsplit.Plan(p.as_dict()).as_dict() == p.as_dict()


def test_zero_overhead_when_unarmed():
    assert not netsplit.active()
    before = netsplit.lookup_count()
    for _ in range(100):
        netsplit.connect("c")
        netsplit.accept("a", addr="127.0.0.1:1")
    # no plan armed: the fast path is a global load + None test — the
    # policy machinery is provably never consulted
    assert netsplit.lookup_count() == before


# -- modes --------------------------------------------------------------------


def test_full_mode_denies_cross_group_both_ways():
    netsplit.reset_log()
    with netsplit.use_plan(_plan(node="a")):
        with pytest.raises(netsplit.NetsplitDenied):
            netsplit.connect("c")
        with pytest.raises(netsplit.NetsplitDenied):
            netsplit.accept("c")
        # NetsplitDenied is an OSError: transports' except-OSError
        # connect paths route it like ECONNREFUSED
        with pytest.raises(OSError):
            netsplit.connect("c")
        netsplit.connect("b")              # same group
        netsplit.connect("nobody")         # ungrouped: always allowed
        netsplit.connect(addr="10.0.0.9:1")  # unresolvable: allowed
        # an addr that IS a group-member name resolves to that node
        with pytest.raises(netsplit.NetsplitDenied):
            netsplit.connect(addr="c")
    denials = netsplit.denial_log()
    assert denials and all(d["mode"] == "full" for d in denials)
    assert {(d["src"], d["dst"]) for d in denials} == {
        ("a", "c"), ("c", "a")
    }


def test_full_mode_addrs_map_resolution():
    plan = _plan(node="a", addrs={"127.0.0.1:9001": "c",
                                  "127.0.0.1:9002": "b"})
    with netsplit.use_plan(plan):
        with pytest.raises(netsplit.NetsplitDenied):
            netsplit.connect(addr="127.0.0.1:9001")
        with pytest.raises(netsplit.NetsplitDenied):
            netsplit.connect(addr=("127.0.0.1", 9001))  # tuple form
        netsplit.connect(addr="127.0.0.1:9002")  # same group


def test_oneway_mode_is_asymmetric():
    groups = [["a"], ["c"]]
    with netsplit.use_plan(_plan(mode="oneway", groups=groups,
                                 node="a")):
        with pytest.raises(netsplit.NetsplitDenied):
            netsplit.connect("c")          # earlier -> later: denied
        netsplit.accept("c")               # c -> a: allowed
    with netsplit.use_plan(_plan(mode="oneway", groups=groups,
                                 node="c")):
        netsplit.connect("a")              # later -> earlier: allowed
        with pytest.raises(netsplit.NetsplitDenied):
            netsplit.accept("a")           # a -> c still denied


def test_flaky_per_link_streams_are_deterministic():
    a = netsplit.Plan(_plan(mode="flaky", p=0.5))
    b = netsplit.Plan(_plan(mode="flaky", p=0.5))
    seq_a = [a.denies("a", "c") for _ in range(40)]
    seq_b = [b.denies("a", "c") for _ in range(40)]
    assert seq_a == seq_b                  # same seed: same stream
    assert True in seq_a and False in seq_a
    # each direction of a link draws its OWN stream
    rev = [b.denies("c", "a") for _ in range(40)]
    assert rev != seq_b or rev == seq_b  # deterministic either way...
    c = netsplit.Plan(_plan(mode="flaky", p=0.5, seed=8))
    assert [c.denies("a", "c") for _ in range(40)] != seq_a
    # flaky never SEVERS (no mid-stream cut, only per-attempt drops)
    assert not a.severed("a", "c")


# -- mid-stream cut -----------------------------------------------------------


def test_activate_cuts_tracked_severed_connections():
    netsplit.reset_log()
    sa, sb = socket.socketpair()
    keep_a, keep_b = socket.socketpair()
    try:
        tok = netsplit.track(sa, addr="c")
        keep_tok = netsplit.track(keep_a, addr="b")
        netsplit.activate(_plan(node="a"))
        try:
            assert sa.fileno() == -1       # severed link: closed
            assert keep_a.fileno() != -1   # same-group link: alive
            cuts = netsplit.cut_log()
            assert {"plan": "netsplit:7", "src": "a", "dst": "c"} in cuts
            # heal disarms but does NOT close anything else
            netsplit.deactivate()
            assert keep_a.fileno() != -1
        finally:
            netsplit.deactivate()
        netsplit.untrack(tok)
        netsplit.untrack(keep_tok)
    finally:
        for s in (sa, sb, keep_a, keep_b):
            try:
                s.close()
            except OSError:
                pass


def test_flaky_plans_never_cut():
    sa, sb = socket.socketpair()
    try:
        tok = netsplit.track(sa, addr="c")
        netsplit.activate(_plan(mode="flaky", node="a", p=1.0))
        try:
            assert sa.fileno() != -1
        finally:
            netsplit.deactivate()
        netsplit.untrack(tok)
    finally:
        sa.close()
        sb.close()


def test_use_plan_nesting_restores_outer():
    outer = _plan(node="a")
    inner = _plan(mode="oneway", node="a", groups=[["c"], ["a"]])
    with netsplit.use_plan(outer):
        with pytest.raises(netsplit.NetsplitDenied):
            netsplit.connect("c")
        with netsplit.use_plan(inner):
            # inner wins: a is in the LATER group, a -> c allowed
            netsplit.connect("c")
        with pytest.raises(netsplit.NetsplitDenied):
            netsplit.connect("c")          # outer restored
    assert not netsplit.active()


# -- the faultline seams (chaos-coverage arming tests) ------------------------


def test_deny_seam_armed_by_pinned_faultline_rule():
    fault_plan = {
        "seed": 3, "label": "netsplit-deny-arm",
        "faults": [
            {"point": "netsplit.deny", "action": "raise", "count": 1},
        ],
    }
    with faultline.use_plan(fault_plan):
        with netsplit.use_plan(_plan(node="a")):
            # the injected fault fires INSIDE the denial path — the
            # seam is armable, not just named
            with pytest.raises(faultline.FaultInjected):
                netsplit.connect("c")
        trips = faultline.trips()
        assert [t["point"] for t in trips] == ["netsplit.deny"]
        assert trips[0]["ctx"] == {"src": "a", "dst": "c",
                                   "mode": "full"}


def test_cut_seam_armed_fault_does_not_save_the_connection():
    fault_plan = {
        "seed": 3, "label": "netsplit-cut-arm",
        "faults": [
            {"point": "netsplit.cut", "action": "raise", "count": 1},
        ],
    }
    netsplit.reset_log()
    sa, sb = socket.socketpair()
    try:
        tok = netsplit.track(sa, addr="c")
        with faultline.use_plan(fault_plan):
            netsplit.activate(_plan(node="a"))
            try:
                # the injected OSError on the cut seam is swallowed —
                # the connection still dies and the trip still lands
                assert sa.fileno() == -1
                assert netsplit.cut_log()
                assert [t["point"] for t in faultline.trips()] == [
                    "netsplit.cut"
                ]
            finally:
                netsplit.deactivate()
        netsplit.untrack(tok)
    finally:
        for s in (sa, sb):
            try:
                s.close()
            except OSError:
                pass


# -- flaky link under the virtual clock (zero real sleeps) --------------------


def test_flaky_link_deliver_rotation_zero_real_sleeps():
    from fabric_tpu.peer.deliverclient import DeliverClient

    netsplit.reset_log()
    got: list[int] = []

    def endpoint(start_num: int):
        for n in range(start_num, 3):
            blk = common_pb2.Block()
            blk.header.number = n
            yield blk

    client = DeliverClient(
        "ch", [endpoint], height_fn=lambda: len(got),
        sink=lambda seq, raw: got.append(seq),
        endpoint_addrs=["nodeB"],
    )
    plan = _plan(mode="flaky", p=0.5, node="nodeA",
                 groups=[["nodeA"], ["nodeB"]])

    def denied() -> bool:
        return any(
            d["src"] == "nodeA" and d["dst"] == "nodeB"
            for d in netsplit.denial_log()
        )

    t0 = time.monotonic()
    with clockskew.use_virtual() as clk:
        with netsplit.use_plan(plan):
            client.start()
            deadline = time.monotonic() + 20.0
            while (
                (len(got) < 3 or not denied())
                and time.monotonic() < deadline
            ):
                time.sleep(0.005)
            client.stop()
    assert {0, 1, 2} <= set(got)           # delivery completed
    assert denied()                        # the link really dropped
    # the whole rotation/backoff cycle ran on the virtual clock: the
    # recorded waits dwarf the real wall time spent
    assert clk.sleeps and sum(clk.sleeps) > 0
    assert time.monotonic() - t0 < 15.0


# -- env knob arming ----------------------------------------------------------


def test_env_knob_arms_at_file_plan(tmp_path, monkeypatch):
    path = tmp_path / "plan.json"
    path.write_text(json.dumps(_plan(node="a")), encoding="utf-8")
    monkeypatch.setenv("FABRIC_TPU_NETSPLIT", "@" + str(path))
    saved = netsplit._env_plan
    try:
        netsplit._init_from_env()
        assert netsplit.active()
        assert netsplit.session_env_plan() is not None
        assert netsplit.session_env_plan().mode == "full"
        with pytest.raises(netsplit.NetsplitDenied):
            netsplit.connect("c")
    finally:
        netsplit.deactivate()
        netsplit._env_plan = saved


def test_env_knob_falsy_values_disarm(monkeypatch):
    saved = netsplit._env_plan
    for raw in ("", "0", "false", "off"):
        monkeypatch.setenv("FABRIC_TPU_NETSPLIT", raw)
        try:
            netsplit._init_from_env()
            assert not netsplit.active()
        finally:
            netsplit.deactivate()
            netsplit._env_plan = saved


# -- gossip dial-timeout knob -------------------------------------------------


def test_gossip_dial_timeout_knob(monkeypatch):
    from fabric_tpu.gossip import comm as gcomm

    monkeypatch.delenv("FABRIC_TPU_DIAL_TIMEOUT_S", raising=False)
    assert gcomm._dial_timeout() == 2.0
    monkeypatch.setenv("FABRIC_TPU_DIAL_TIMEOUT_S", "0.25")
    assert gcomm._dial_timeout() == 0.25
    monkeypatch.setenv("FABRIC_TPU_DIAL_TIMEOUT_S", "junk")
    with pytest.raises(ValueError):
        gcomm._dial_timeout()
    monkeypatch.setenv("FABRIC_TPU_DIAL_TIMEOUT_S", "-2")
    with pytest.raises(ValueError):
        gcomm._dial_timeout()


# -- the partition judge (pure function) --------------------------------------


def test_partition_violations_judgment():
    from fabric_tpu.devtools import invariants as inv

    kw = dict(
        majority=["o1", "o2", "p1"], minority=["o3", "p2"],
        orderer_names=["o1", "o2", "o3"], peer_names=["p1", "p2"],
    )
    # green episode: majority past the tip, minority pinned, one digest
    ok = inv.partition_violations(
        mode="full", split_tip=8, stall_tip=12,
        pre_heal_heights={"o1": 40, "o2": 40, "o3": 12,
                          "p1": 40, "p2": 12},
        minority_digests={"p2": [12, "d" * 64]}, **kw,
    )
    assert ok == []
    # no sample at all: the episode cannot be judged green
    assert [v.check for v in inv.partition_violations(
        mode="full", split_tip=8, pre_heal_heights=None,
        minority_digests=None, **kw,
    )] == ["partition.sample"]
    # majority never committed past the split tip
    assert "partition.majority_stalled" in [
        v.check for v in inv.partition_violations(
            mode="full", split_tip=8, stall_tip=8,
            pre_heal_heights={"o1": 8, "o2": 8, "o3": 8,
                              "p1": 8, "p2": 8},
            minority_digests={"p2": [8, "d" * 64]}, **kw,
        )
    ]
    # a quiesced episode waives ONLY the progress expectation
    assert inv.partition_violations(
        mode="full", split_tip=8, stall_tip=8, expect_progress=False,
        pre_heal_heights={"o1": 8, "o2": 8, "o3": 8, "p1": 8, "p2": 8},
        minority_digests={"p2": [8, "d" * 64]}, **kw,
    ) == []
    # the quorum-less side kept ordering past its post-cut baseline
    assert "partition.minority_progressed" in [
        v.check for v in inv.partition_violations(
            mode="full", split_tip=8, stall_tip=9,
            pre_heal_heights={"o1": 40, "o2": 40, "o3": 20,
                              "p1": 40, "p2": 20},
            minority_digests={"p2": [20, "d" * 64]}, **kw,
        )
    ]
    # minority peers at the SAME height disagreeing on digest = fork
    forked = inv.partition_violations(
        mode="flaky", split_tip=8,
        pre_heal_heights={"o1": 40, "o2": 40, "o3": 12,
                          "p1": 40, "p2": 12},
        minority_digests={"p2": [12, "a" * 64], "p3": [12, "b" * 64]},
        majority=["o1", "o2", "p1"], minority=["o3", "p2", "p3"],
        orderer_names=["o1", "o2", "o3"],
        peer_names=["p1", "p2", "p3"],
    )
    assert [v.check for v in forked] == ["partition.minority_forked"]
