"""peer CLI (reference cmd/peer + internal/peer/**): node daemon, channel
ops, chaincode invoke/query, lifecycle commands.

    peer node start --listen :7051 --root /var/peer --mspid Org1MSP \
        --msp-dir .../peers/peer0.org1/msp --orderer 127.0.0.1:7050 \
        --chaincode mycc=my_pkg.chaincodes:MyCC
    peer channel join --block ch.block --peer :7051
    peer channel list --peer :7051
    peer channel fetch newest out.block -c ch --peer :7051 --mspid ... \
        --msp-dir ...
    peer chaincode invoke -C ch -n mycc -a put -a k -a v --peer :7051 \
        --orderer :7050 --mspid ... --msp-dir ...
    peer chaincode query  -C ch -n mycc -a get -a k --peer :7051 ...
    peer lifecycle queryinstalled/querycommitted/...
    peer snapshot submitrequest -c ch -b 500 --peer :7051
    peer snapshot listpending -c ch --peer :7051
    peer snapshot joinbysnapshot --snapshotpath .../completed/ch/499 \
        --peer :7051
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading

from fabric_tpu.cmd.common import (
    endorse,
    load_signer,
    parse_endpoint,
    submit,
    tls_from_args,
    tls_parent,
)
from fabric_tpu.comm import RPCClient
from fabric_tpu.comm.rpc import KeepaliveOptions
from fabric_tpu.protos.common import common_pb2
from fabric_tpu.protos.orderer import ab_pb2
from fabric_tpu.protos.peer import configuration_pb2 as peer_cfg


def _signer(args):
    return load_signer(args.msp_dir, args.mspid)


def cmd_node_start(args) -> int:
    from fabric_tpu.common.config import Config
    from fabric_tpu.common.diag import install_signal_handler
    from fabric_tpu.csp import csp_from_config
    from fabric_tpu.node.peer_node import PeerNode

    install_signal_handler()  # SIGUSR1 -> thread dump (common/diag)
    # core.yaml (FABRIC_CFG_PATH) + CORE_* env supply defaults the flags
    # can override (viper precedence)
    cfg = Config.load("core", "CORE")
    host, port = parse_endpoint(args.listen)
    node = PeerNode(
        args.root,
        # bccsp block selects SW/TPU and the SKI-keyed file keystore
        csp_from_config(cfg),
        load_signer(args.msp_dir, args.mspid),
        host=host,
        port=port,
        chaincode_specs=args.chaincode,
        orderer_endpoints=[parse_endpoint(o) for o in args.orderer],
        operations_port=args.operations_port,
        endorser_concurrency=cfg.get_int(
            "peer.limits.concurrency.endorserService", 2500
        ),
        deliver_concurrency=cfg.get_int(
            "peer.limits.concurrency.deliverService", 2500
        ),
        tls=tls_from_args(args),
        keepalive=KeepaliveOptions.from_config(cfg),
    )
    if cfg.get_bool("peer.profile.enabled", False):
        # continuous profscope sampling (reference cmd/peer/main.go:10 +
        # core/peer/config.go:83-85 ProfileEnabled gates pprof the same
        # way).  The speedscope document is served from the operations
        # endpoint (GET /profile, /profile/heap) — the old standalone
        # ProfileServer listener is retired
        from fabric_tpu.common import profile

        if not profile.enabled():
            # FABRIC_TPU_PROFILE may already have armed a tuned cadence
            profile.arm()
        if node.operations is not None:
            profile.set_lock_metrics(node.operations.lock_metrics())
            print(
                f"profiling armed: GET /profile on operations port "
                f"{args.operations_port}",
                flush=True,
            )
        else:
            print("profiling armed (no operations port: export via "
                  "fabric_tpu.common.profile.dump_to)", flush=True)
    gossip_bootstrap = list(args.gossip_bootstrap) or [
        str(b) for b in (cfg.get("peer.gossip.bootstrap") or [])
    ]
    if args.gossip_listen:
        node.enable_gossip(
            parse_endpoint(args.gossip_listen),
            gossip_bootstrap,
            fanout=cfg.get_int("peer.gossip.fanout", 3),
            store_capacity=cfg.get_int(
                "peer.gossip.maxBlockCountToStore", 200
            ),
            tick_interval_s=cfg.get_duration(
                "peer.gossip.pullInterval", 4.0
            ),
            identity_ttl_s=cfg.get_duration(
                "peer.gossip.identityExpiration", 3600.0
            ),
            reconcile_interval_s=cfg.get_duration(
                "peer.gossip.pvtData.reconcileSleepInterval", 60.0
            ),
        )
    node.start()
    print(f"peer listening on {node.addr[0]}:{node.addr[1]}", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    node.stop()
    from fabric_tpu.common import profile as _profile

    _profile.disarm()  # joins the sampler thread; no-op when disarmed
    return 0


def cmd_node_rebuild_dbs(args) -> int:
    from fabric_tpu.ledger import admin

    ids = admin.rebuild_dbs(args.root, args.channel)
    for lid in ids:
        h = admin.verify_rebuild(args.root, lid)
        print(f"rebuilt state/history DBs for {lid} (height {h})")
    return 0


def cmd_node_rollback(args) -> int:
    from fabric_tpu.ledger import admin

    h = admin.rollback(args.root, args.channel, args.block_number)
    print(f"rolled back {args.channel} to height {h}")
    return 0


def cmd_node_reset(args) -> int:
    from fabric_tpu.ledger import admin

    for lid, h in admin.reset(args.root).items():
        print(f"reset {lid} to height {h}")
    return 0


def cmd_channel_join(args) -> int:
    with open(args.block, "rb") as f:
        raw = f.read()
    out = RPCClient(*parse_endpoint(args.peer), tls=tls_from_args(args)).call(
        "admin.JoinChannel", raw
    )
    print(f"joined channel {out.decode()}")
    return 0


def cmd_channel_list(args) -> int:
    """List channels from a peer (admin.Channels) or, with --orderer,
    from the orderer's channel-participation API (reference osnadmin
    channel list / channelparticipation restapi.go)."""
    if bool(args.peer) == bool(args.orderer):
        print("channel list requires exactly one of --peer/--orderer",
              file=sys.stderr)
        return 2
    if args.peer:
        raw = RPCClient(
            *parse_endpoint(args.peer), tls=tls_from_args(args)
        ).call("admin.Channels")
    else:
        raw = RPCClient(
            *parse_endpoint(args.orderer), tls=tls_from_args(args)
        ).call("participation.List")
    resp = peer_cfg.ChannelQueryResponse.FromString(raw)
    for ch in resp.channels:
        print(ch.channel_id)
    return 0


def cmd_channel_getinfo(args) -> int:
    raw = RPCClient(*parse_endpoint(args.peer), tls=tls_from_args(args)).call(
        "admin.Height", args.channel.encode()
    )
    print(f"height: {raw.decode()}")
    return 0


def cmd_channel_fetch(args) -> int:
    from fabric_tpu.common.deliver import make_seek_info_envelope

    if not args.peer and not args.orderer:
        print("channel fetch requires --peer or --orderer", file=sys.stderr)
        return 2
    if args.filtered and not args.peer:
        print("channel fetch --filtered requires --peer (the filtered "
              "deliver service is peer-side)", file=sys.stderr)
        return 2
    signer = _signer(args) if args.msp_dir else None
    pos = args.position
    start = stop = pos if pos in ("newest", "oldest") else int(pos)
    env = make_seek_info_envelope(args.channel, start, stop, signer=signer)
    target = args.peer or args.orderer
    if args.filtered:
        return _fetch_filtered(args, env)
    method = "deliver.Deliver" if args.peer else "ab.Deliver"
    blk = None
    for raw in RPCClient(*parse_endpoint(target), tls=tls_from_args(args)).stream(
        method, env.SerializeToString()
    ):
        resp = ab_pb2.DeliverResponse.FromString(raw)
        if resp.WhichOneof("Type") == "block":
            blk = resp.block
    if blk is None:
        print("no block received", file=sys.stderr)
        return 1
    with open(args.out, "wb") as f:
        f.write(blk.SerializeToString())
    print(f"wrote block {blk.header.number} to {args.out}")
    return 0


def _fetch_filtered(args, env) -> int:
    """`channel fetch --filtered`: pull through the peer's filtered
    deliver service (reference peer/deliverevents.go DeliverFiltered) —
    txids + validation codes, no payloads."""
    from fabric_tpu.protos.peer import events_pb2

    fblk = None
    for raw in RPCClient(
        *parse_endpoint(args.peer), tls=tls_from_args(args)
    ).stream("deliver.DeliverFiltered", env.SerializeToString()):
        resp = events_pb2.DeliverResponse.FromString(raw)
        if resp.WhichOneof("Type") == "filtered_block":
            fblk = resp.filtered_block
    if fblk is None:
        print("no filtered block received", file=sys.stderr)
        return 1
    with open(args.out, "wb") as f:
        f.write(fblk.SerializeToString())
    for ftx in fblk.filtered_transactions:
        print(f"{ftx.txid or '-'} {ftx.tx_validation_code}")
    print(f"wrote filtered block {fblk.number} to {args.out}")
    return 0


def _cc_args(args) -> list[bytes]:
    return [a.encode("utf-8") for a in args.arg or []]


def cmd_chaincode_invoke(args) -> int:
    signer = _signer(args)
    peers = [parse_endpoint(p) for p in args.peer]
    prop, responses = endorse(
        peers, signer, args.channel, args.name, _cc_args(args),
        tls=tls_from_args(args),
    )
    for r in responses:
        # same success range create_signed_tx enforces (2xx/3xx)
        if not (200 <= r.response.status < 400):
            print(f"endorsement failed: {r.response.message}",
                  file=sys.stderr)
            return 1
    status = submit(
        parse_endpoint(args.orderer), signer, prop, responses,
        tls=tls_from_args(args),
    )
    ok = status == common_pb2.SUCCESS
    print("committed" if ok else f"broadcast status {status}")
    return 0 if ok else 1


def cmd_chaincode_query(args) -> int:
    signer = _signer(args)
    _, responses = endorse(
        [parse_endpoint(args.peer[0])], signer, args.channel, args.name,
        _cc_args(args), tls=tls_from_args(args),
    )
    r = responses[0]
    if not (200 <= r.response.status < 400):
        print(f"query failed: {r.response.message}", file=sys.stderr)
        return 1
    sys.stdout.buffer.write(r.response.payload)
    sys.stdout.write("\n")
    return 0


def _lifecycle_call(args, fn_name: str, payload: bytes, channel: str = ""):
    """Endorse a _lifecycle invocation on the given peers; raises on a
    non-2xx endorsement (same guard as chaincode invoke/query)."""
    peers = [parse_endpoint(p) for p in args.peer]
    prop, resps = endorse(
        peers, _signer(args), channel or getattr(args, "channel", ""),
        "_lifecycle", [fn_name.encode(), payload], tls=tls_from_args(args),
    )
    for r in resps:
        if not (200 <= r.response.status < 400):
            raise SystemExit(
                f"{fn_name} failed ({r.response.status}): {r.response.message}"
            )
    return prop, resps


def cmd_lifecycle_package(args) -> int:
    from fabric_tpu.chaincode.platforms import package_chaincode

    pkg = package_chaincode(args.path, args.label, args.lang)
    with open(args.output, "wb") as f:
        f.write(pkg)
    print(f"wrote {args.output} ({len(pkg)} bytes, label {args.label})")
    return 0


def cmd_lifecycle_install(args) -> int:
    from fabric_tpu.protos.peer import lifecycle_pb2 as lcpb

    with open(args.package, "rb") as f:
        pkg = f.read()
    req = lcpb.InstallChaincodeArgs(chaincode_install_package=pkg)
    _, resps = _lifecycle_call(args, "InstallChaincode", req.SerializeToString())
    res = lcpb.InstallChaincodeResult.FromString(resps[0].response.payload)
    print(f"installed {res.package_id} (label {res.label})")
    return 0


def cmd_lifecycle_queryinstalled(args) -> int:
    from fabric_tpu.protos.peer import lifecycle_pb2 as lcpb

    _, resps = _lifecycle_call(args, "QueryInstalledChaincodes", b"")
    res = lcpb.QueryInstalledChaincodesResult.FromString(
        resps[0].response.payload
    )
    for ic in res.installed_chaincodes:
        print(f"{ic.package_id}\t{ic.label}")
    return 0


def _definition_from(args):
    from fabric_tpu.protos.peer import lifecycle_pb2 as lcpb

    return lcpb.ChaincodeDefinition(
        sequence=args.sequence, name=args.name, version=args.version,
    )


def cmd_lifecycle_approve(args) -> int:
    from fabric_tpu.protos.peer import lifecycle_pb2 as lcpb

    req = lcpb.ApproveChaincodeDefinitionForMyOrgArgs(
        definition=_definition_from(args)
    )
    if args.package_id:
        req.source.local_package.package_id = args.package_id
    prop, resps = _lifecycle_call(
        args, "ApproveChaincodeDefinitionForMyOrg", req.SerializeToString()
    )
    status = submit(parse_endpoint(args.orderer), _signer(args), prop, resps,
                    tls=tls_from_args(args))
    print(f"approval submitted: {status}")
    return 0 if status == 200 else 1


def cmd_lifecycle_checkreadiness(args) -> int:
    from fabric_tpu.protos.peer import lifecycle_pb2 as lcpb

    req = lcpb.CheckCommitReadinessArgs(definition=_definition_from(args))
    _, resps = _lifecycle_call(
        args, "CheckCommitReadiness", req.SerializeToString()
    )
    res = lcpb.CheckCommitReadinessResult.FromString(resps[0].response.payload)
    for org, approved in sorted(res.approvals.items()):
        print(f"{org}: {approved}")
    return 0


def cmd_lifecycle_commit(args) -> int:
    from fabric_tpu.protos.peer import lifecycle_pb2 as lcpb

    req = lcpb.CommitChaincodeDefinitionArgs(definition=_definition_from(args))
    prop, resps = _lifecycle_call(
        args, "CommitChaincodeDefinition", req.SerializeToString()
    )
    status = submit(parse_endpoint(args.orderer), _signer(args), prop, resps,
                    tls=tls_from_args(args))
    print(f"commit submitted: {status}")
    return 0 if status == 200 else 1


def cmd_lifecycle_querycommitted(args) -> int:
    from fabric_tpu.protos.peer import lifecycle_pb2 as lcpb

    if args.name:
        req = lcpb.QueryChaincodeDefinitionArgs(name=args.name)
        _, resps = _lifecycle_call(
            args, "QueryChaincodeDefinition", req.SerializeToString()
        )
        res = lcpb.QueryChaincodeDefinitionResult.FromString(
            resps[0].response.payload
        )
        d = res.definition
        print(f"{d.name} v{d.version} seq {d.sequence}")
    else:
        req = lcpb.QueryChaincodeDefinitionsArgs()
        _, resps = _lifecycle_call(
            args, "QueryChaincodeDefinitions", req.SerializeToString()
        )
        res = lcpb.QueryChaincodeDefinitionsResult.FromString(
            resps[0].response.payload
        )
        for info in res.chaincode_definitions:
            d = info.definition
            print(f"{info.name} v{d.version} seq {d.sequence}")
    return 0


def cmd_node_pause(args) -> int:
    from fabric_tpu.ledger import admin

    admin.pause(args.root, args.channel)
    print(f"channel {args.channel} paused")
    return 0


def cmd_node_resume(args) -> int:
    from fabric_tpu.ledger import admin

    admin.resume(args.root, args.channel)
    print(f"channel {args.channel} resumed")
    return 0


def cmd_node_upgrade_dbs(args) -> int:
    from fabric_tpu.ledger import admin

    rebuilt = admin.upgrade_dbs(args.root)
    print("up to date" if not rebuilt else f"rebuilt: {', '.join(rebuilt)}")
    return 0


def cmd_snapshot_submitrequest(args) -> int:
    """Request a channel snapshot at a block number (0 = the last
    committed block, generated immediately); future blocks auto-trigger
    at commit (reference peer snapshot submitrequest)."""
    import json

    payload = json.dumps(
        {"channel": args.channel, "block_number": args.block_number}
    ).encode()
    raw = RPCClient(*parse_endpoint(args.peer), tls=tls_from_args(args)).call(
        "admin.SnapshotSubmit", payload
    )
    res = json.loads(raw.decode())
    if res.get("snapshot_dir"):
        print(f"snapshot generated at {res['snapshot_dir']}")
    else:
        print(
            f"snapshot request submitted for block {res['block_number']}"
        )
    return 0


def cmd_snapshot_cancelrequest(args) -> int:
    import json

    payload = json.dumps(
        {"channel": args.channel, "block_number": args.block_number}
    ).encode()
    RPCClient(*parse_endpoint(args.peer), tls=tls_from_args(args)).call(
        "admin.SnapshotCancel", payload
    )
    print(f"cancelled snapshot request for block {args.block_number}")
    return 0


def cmd_snapshot_listpending(args) -> int:
    import json

    raw = RPCClient(*parse_endpoint(args.peer), tls=tls_from_args(args)).call(
        "admin.SnapshotList", args.channel.encode()
    )
    pending = json.loads(raw.decode())
    print(
        "pending: " + (", ".join(str(n) for n in pending) if pending else "none")
    )
    return 0


def cmd_snapshot_fetch(args) -> int:
    """Stream a COMPLETED snapshot from a REMOTE peer into a local
    directory (no shared disk required), then optionally join from it.
    The fetched directory is verified the same way a local one is:
    verify-on-import recomputes every file digest, so a torn or
    tampered stream is refused at join time."""
    from fabric_tpu.ledger import snapshot as snap

    client = RPCClient(*parse_endpoint(args.frompeer),
                       tls=tls_from_args(args))
    dest = snap.fetch_snapshot(
        client, args.channel, args.block_number, args.out
    )
    print(f"fetched snapshot for {args.channel}@{args.block_number} "
          f"into {dest}")
    if args.join_via:
        raw = RPCClient(
            *parse_endpoint(args.join_via), tls=tls_from_args(args)
        ).call("admin.JoinBySnapshot", dest.encode())
        print(f"joined channel {raw.decode()} from fetched snapshot")
    return 0


def cmd_snapshot_joinbysnapshot(args) -> int:
    """Join a channel from a snapshot directory: the peer bootstraps a
    blockless ledger at the snapshot height and catches up from the
    orderer from there (reference peer channel joinbysnapshot)."""
    raw = RPCClient(*parse_endpoint(args.peer), tls=tls_from_args(args)).call(
        "admin.JoinBySnapshot", args.snapshotpath.encode()
    )
    print(f"joined channel {raw.decode()} from snapshot")
    return 0


def cmd_channel_create(args) -> int:
    """Create a channel: submit its genesis block to the orderer's
    channel-participation API (the reference's post-system-channel flow:
    osnadmin channel join / channelparticipation restapi.go)."""
    with open(args.file, "rb") as f:
        raw = f.read()
    out = RPCClient(
        *parse_endpoint(args.orderer), tls=tls_from_args(args)
    ).call("participation.Join", raw)
    print(f"channel {out.decode()} created")
    return 0


def cmd_channel_update(args) -> int:
    """Submit a signed CONFIG_UPDATE envelope (reference peer channel
    update)."""
    from fabric_tpu.protos.orderer import ab_pb2

    with open(args.file, "rb") as f:
        raw = f.read()
    resp = ab_pb2.BroadcastResponse.FromString(
        RPCClient(
            *parse_endpoint(args.orderer), tls=tls_from_args(args)
        ).call("ab.Broadcast", raw)
    )
    print(f"update status: {resp.status}")
    return 0 if resp.status == 200 else 1


def cmd_channel_signconfigtx(args) -> int:
    """Add this identity's signature to a config-update envelope in
    place (reference peer channel signconfigtx)."""
    from fabric_tpu import protoutil
    from fabric_tpu.protos.common import configtx_pb2

    signer = load_signer(args.msp_dir, args.mspid)
    with open(args.file, "rb") as f:
        env = common_pb2.Envelope.FromString(f.read())
    payload = common_pb2.Payload.FromString(env.payload)
    cue = configtx_pb2.ConfigUpdateEnvelope.FromString(payload.data)
    shdr = protoutil.make_signature_header(
        signer.serialize(), protoutil.random_nonce()
    ).SerializeToString()
    sig = cue.signatures.add()
    sig.signature_header = shdr
    sig.signature = signer.sign(shdr + cue.config_update)
    payload.data = cue.SerializeToString()
    env = common_pb2.Envelope(
        payload=payload.SerializeToString(),
        signature=signer.sign(payload.SerializeToString()),
    )
    with open(args.file, "wb") as f:
        f.write(env.SerializeToString())
    print(f"signed config update as {args.mspid}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="peer")
    sub = ap.add_subparsers(dest="cmd", required=True)
    tlsp = tls_parent()

    node = sub.add_parser("node").add_subparsers(dest="sub", required=True)
    start = node.add_parser("start", parents=[tlsp])
    start.add_argument("--listen", default="127.0.0.1:0")
    start.add_argument("--root", default=None)
    start.add_argument("--mspid", required=True)
    start.add_argument("--msp-dir", required=True)
    start.add_argument("--orderer", action="append", default=[])
    start.add_argument("--chaincode", action="append", default=[])
    start.add_argument("--operations-port", type=int, default=None)
    start.add_argument("--gossip-listen", default=None,
                       help="host:port for the gossip transport")
    start.add_argument("--gossip-bootstrap", action="append", default=[],
                       help="bootstrap gossip endpoint (repeatable)")
    start.set_defaults(fn=cmd_node_start)
    # offline repair ops (reference internal/peer/node/{reset,rollback,
    # rebuild_dbs}.go) — run against a STOPPED peer's storage root
    rb = node.add_parser("rebuild-dbs")
    rb.add_argument("--root", required=True)
    rb.add_argument("-c", "--channel", default=None)
    rb.set_defaults(fn=cmd_node_rebuild_dbs)
    ro = node.add_parser("rollback")
    ro.add_argument("--root", required=True)
    ro.add_argument("-c", "--channel", required=True)
    ro.add_argument("-b", "--block-number", type=int, required=True)
    ro.set_defaults(fn=cmd_node_rollback)
    for opname, fn in (("pause", cmd_node_pause), ("resume", cmd_node_resume)):
        op = node.add_parser(opname)
        op.add_argument("--root", required=True)
        op.add_argument("-c", "--channel", required=True)
        op.set_defaults(fn=fn)
    ud = node.add_parser("upgrade-dbs")
    ud.add_argument("--root", required=True)
    ud.set_defaults(fn=cmd_node_upgrade_dbs)
    rs = node.add_parser("reset")
    rs.add_argument("--root", required=True)
    rs.set_defaults(fn=cmd_node_reset)

    chan = sub.add_parser("channel").add_subparsers(dest="sub", required=True)
    create = chan.add_parser("create", parents=[tlsp])
    create.add_argument("-f", "--file", required=True,
                        help="genesis block for the new channel")
    create.add_argument("--orderer", required=True)
    create.set_defaults(fn=cmd_channel_create)
    upd = chan.add_parser("update", parents=[tlsp])
    upd.add_argument("-f", "--file", required=True,
                     help="signed CONFIG_UPDATE envelope")
    upd.add_argument("--orderer", required=True)
    upd.set_defaults(fn=cmd_channel_update)
    sct = chan.add_parser("signconfigtx")
    sct.add_argument("-f", "--file", required=True)
    sct.add_argument("--mspid", required=True)
    sct.add_argument("--msp-dir", required=True)
    sct.set_defaults(fn=cmd_channel_signconfigtx)
    join = chan.add_parser("join", parents=[tlsp])
    join.add_argument("--block", required=True)
    join.add_argument("--peer", required=True)
    join.set_defaults(fn=cmd_channel_join)
    lst = chan.add_parser("list", parents=[tlsp])
    lst.add_argument("--peer")
    lst.add_argument("--orderer")
    lst.set_defaults(fn=cmd_channel_list)
    info = chan.add_parser("getinfo", parents=[tlsp])
    info.add_argument("-c", "--channel", required=True)
    info.add_argument("--peer", required=True)
    info.set_defaults(fn=cmd_channel_getinfo)
    fetch = chan.add_parser("fetch", parents=[tlsp])
    fetch.add_argument("position")  # newest | oldest | block number
    fetch.add_argument("out")
    fetch.add_argument("-c", "--channel", required=True)
    fetch.add_argument("--peer")
    fetch.add_argument("--orderer")
    fetch.add_argument("--mspid")
    fetch.add_argument("--msp-dir")
    fetch.add_argument("--filtered", action="store_true",
                       help="use the peer's filtered deliver service")
    fetch.set_defaults(fn=cmd_channel_fetch)

    snap = sub.add_parser("snapshot").add_subparsers(dest="sub", required=True)
    for name, fn, needs_block in (
        ("submitrequest", cmd_snapshot_submitrequest, False),
        ("cancelrequest", cmd_snapshot_cancelrequest, True),
        ("listpending", cmd_snapshot_listpending, False),
    ):
        p = snap.add_parser(name, parents=[tlsp])
        p.add_argument("-c", "--channel", required=True)
        p.add_argument("--peer", required=True)
        if name != "listpending":
            p.add_argument(
                "-b", "--block-number", type=int,
                required=needs_block, default=0,
                help="0 = snapshot the last committed block now",
            )
        p.set_defaults(fn=fn)
    jbs = snap.add_parser("joinbysnapshot", parents=[tlsp])
    jbs.add_argument("--snapshotpath", required=True,
                     help="completed snapshot directory on the peer host")
    jbs.add_argument("--peer", required=True)
    jbs.set_defaults(fn=cmd_snapshot_joinbysnapshot)
    sf = snap.add_parser("fetch", parents=[tlsp])
    sf.add_argument("-c", "--channel", required=True)
    sf.add_argument("-b", "--block-number", type=int, required=True)
    sf.add_argument("--frompeer", required=True,
                    help="remote peer serving admin.SnapshotFetch")
    sf.add_argument("--out", required=True,
                    help="local directory to receive the snapshot")
    sf.add_argument("--join-via", default=None,
                    help="optionally join a LOCAL peer from the fetched "
                         "snapshot (its admin endpoint)")
    sf.set_defaults(fn=cmd_snapshot_fetch)

    cc = sub.add_parser("chaincode").add_subparsers(dest="sub", required=True)
    for name, fn, needs_orderer in (
        ("invoke", cmd_chaincode_invoke, True),
        ("query", cmd_chaincode_query, False),
    ):
        p = cc.add_parser(name, parents=[tlsp])
        p.add_argument("-C", "--channel", required=True)
        p.add_argument("-n", "--name", required=True)
        p.add_argument("-a", "--arg", action="append", default=[])
        p.add_argument("--peer", action="append", required=True)
        if needs_orderer:
            p.add_argument("--orderer", required=True)
        p.add_argument("--mspid", required=True)
        p.add_argument("--msp-dir", required=True)
        p.set_defaults(fn=fn)

    lc = sub.add_parser("lifecycle").add_subparsers(dest="sub", required=True)
    lcc = lc.add_parser("chaincode").add_subparsers(dest="op", required=True)
    pkg = lcc.add_parser("package")
    pkg.add_argument("output")
    pkg.add_argument("--path", required=True)
    pkg.add_argument("--label", required=True)
    pkg.add_argument("--lang", default="python")
    pkg.set_defaults(fn=cmd_lifecycle_package)
    for name, fn in (
        ("install", cmd_lifecycle_install),
        ("queryinstalled", cmd_lifecycle_queryinstalled),
        ("approveformyorg", cmd_lifecycle_approve),
        ("checkcommitreadiness", cmd_lifecycle_checkreadiness),
        ("commit", cmd_lifecycle_commit),
        ("querycommitted", cmd_lifecycle_querycommitted),
    ):
        p = lcc.add_parser(name, parents=[tlsp])
        p.add_argument("--peer", action="append", required=True)
        p.add_argument("--mspid", required=True)
        p.add_argument("--msp-dir", required=True)
        if name == "install":
            p.add_argument("package")
        if name in ("approveformyorg", "checkcommitreadiness", "commit",
                    "querycommitted", "queryinstalled", "install"):
            p.add_argument("-C", "--channel", default="")
        if name in ("approveformyorg", "checkcommitreadiness", "commit"):
            p.add_argument("-n", "--name", required=True)
            p.add_argument("-v", "--version", required=True)
            p.add_argument("--sequence", type=int, required=True)
            p.add_argument("--package-id", default="")
        if name == "querycommitted":
            p.add_argument("-n", "--name", default="")
        if name in ("approveformyorg", "commit"):
            p.add_argument("--orderer", required=True)
        p.set_defaults(fn=fn)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
