"""Gossip layer tests: discovery membership, push/pull dissemination,
leader election, state transfer, deliver-client failover.  All on the
in-process net with synchronous ticks (the reference unit-tests gossip
the same way: mocked comm, deterministic rounds)."""

import threading

from fabric_tpu.gossip import (
    GossipService,
    InProcGossipComm,
    InProcGossipNet,
)
from fabric_tpu.peer.deliverclient import DeliverClient
from fabric_tpu.protos.common import common_pb2
from fabric_tpu import protoutil


def make_node(net, name: str) -> GossipService:
    comm = InProcGossipComm(name, net, identity_bytes(name))
    return GossipService(comm, bootstrap=["n0"])


def identity_bytes(name: str) -> bytes:
    return b"identity-" + name.encode()


class FakeCommitter:
    """Stands in for the commit pipeline (store_block/height/reader)."""

    def __init__(self):
        self.blocks: dict[int, common_pb2.Block] = {}
        self.lock = threading.Lock()

    @property
    def height(self) -> int:
        with self.lock:
            return max(self.blocks) + 1 if self.blocks else 0

    def store_block(self, blk: common_pb2.Block) -> None:
        with self.lock:
            self.blocks[blk.header.number] = blk

    def get_block_by_number(self, n: int):
        with self.lock:
            return self.blocks.get(n)


def _block(num: int) -> bytes:
    blk = protoutil.new_block(num, b"prev")
    blk.data.data.append(b"tx-%d" % num)
    return blk.SerializeToString()


def _mesh(n: int):
    net = InProcGossipNet()
    nodes = [make_node(net, f"n{i}") for i in range(n)]
    for _ in range(4):  # converge membership
        for node in nodes:
            node.tick()
    return net, nodes


def test_discovery_membership_converges():
    _, nodes = _mesh(4)
    for node in nodes:
        assert len(node.discovery.alive_peers()) == 3


def test_discovery_detects_death():
    net, nodes = _mesh(3)
    dead = nodes[2]
    net.unregister(dead.endpoint)
    for _ in range(10):
        nodes[0].tick()
        nodes[1].tick()
    alive0 = {p.endpoint for p in nodes[0].discovery.alive_peers()}
    assert dead.endpoint not in alive0
    assert dead.endpoint in {p.endpoint for p in nodes[0].discovery.dead_peers()}


def test_push_dissemination_reaches_all_members():
    _, nodes = _mesh(4)
    committers = [FakeCommitter() for _ in nodes]
    handles = [
        node.join_channel("ch", c) for node, c in zip(nodes, committers)
    ]
    # seed committed genesis so sequencing starts at block 0
    for c in committers:
        pass
    handles[0].state.add_payload(0, _block(0), from_orderer=True)
    # push fanout is 3 on a 3-peer membership: direct flood
    for c in committers:
        assert c.height == 1, "push should reach every peer"


def test_pull_repairs_partitioned_peer():
    net, nodes = _mesh(3)
    committers = [FakeCommitter() for _ in nodes]
    handles = [node.join_channel("ch", c) for node, c in zip(nodes, committers)]
    # cut n2 off from n0 and n1
    net.partition("n0", "n2")
    net.partition("n1", "n2")
    handles[0].state.add_payload(0, _block(0), from_orderer=True)
    assert committers[2].height == 0
    net.heal()
    for _ in range(6):
        for node in nodes:
            node.tick()
    assert committers[2].height == 1, "pull anti-entropy should repair the gap"


def test_election_converges_to_single_leader_and_fails_over():
    net, nodes = _mesh(3)
    committers = [FakeCommitter() for _ in nodes]
    handles = [node.join_channel("ch", c) for node, c in zip(nodes, committers)]
    for _ in range(6):
        for node in nodes:
            node.tick()
    leaders = [i for i, h in enumerate(handles) if h.election.is_leader]
    assert len(leaders) == 1, f"want one leader, got {leaders}"
    # kill the leader; remaining nodes elect a new one
    dead = leaders[0]
    net.unregister(nodes[dead].endpoint)
    survivors = [i for i in range(3) if i != dead]
    for _ in range(14):
        for i in survivors:
            nodes[i].tick()
    new_leaders = [i for i in survivors if handles[i].election.is_leader]
    assert len(new_leaders) == 1
    assert new_leaders[0] != dead


def test_state_provider_orders_out_of_order_payloads():
    net = InProcGossipNet()
    node = make_node(net, "n0")
    committer = FakeCommitter()
    h = node.join_channel("ch", committer)
    h.state.add_payload(2, _block(2))
    h.state.add_payload(1, _block(1))
    assert committer.height == 0  # waiting for 0
    h.state.add_payload(0, _block(0))
    assert committer.height == 3  # drained in order


def test_state_anti_entropy_catches_up_lagging_peer():
    net, nodes = _mesh(2)
    committers = [FakeCommitter() for _ in nodes]
    handles = [node.join_channel("ch", c) for node, c in zip(nodes, committers)]
    net.partition("n0", "n1")
    for i in range(5):
        handles[0].state.add_payload(i, _block(i), from_orderer=True)
    assert committers[0].height == 5 and committers[1].height == 0
    net.heal()
    for _ in range(10):
        for node in nodes:
            node.tick()
    assert committers[1].height == 5


def test_deliver_client_failover_and_sink():
    got = []
    height = lambda: len(got)

    def bad_endpoint(start):
        raise ConnectionError("orderer down")

    def good_endpoint(start):
        for i in range(start, 3):
            blk = common_pb2.Block.FromString(_block(i))
            yield blk

    done = threading.Event()

    def sink(seq, raw):
        got.append(seq)
        if len(got) == 3:
            done.set()

    dc = DeliverClient("ch", [bad_endpoint, good_endpoint], height, sink)
    dc.start()
    assert done.wait(5), f"expected 3 blocks, got {got}"
    dc.stop()
    assert got == [0, 1, 2]


def test_concurrent_pull_converges_at_scale():
    """14 peers, one seeded with 30 blocks the others never saw: the
    multi-peer pull rounds (3 hellos per tick, per-digest in-flight
    filters) must converge everyone within a bounded number of rounds —
    the reference's algo/pull.go engages several peers per round for
    exactly this reason (advisor round-2 weak #8: single-flight pull was
    only proven at 3 processes)."""
    n = 14
    _, nodes = _mesh(n)
    committers = [FakeCommitter() for _ in nodes]
    handles = [
        node.join_channel("ch", c) for node, c in zip(nodes, committers)
    ]
    # seed node 0 only, without pushes (pure anti-entropy repair)
    for seq in range(30):
        handles[0].gossip.add_block(seq, _block(seq), push=False)
    rounds = 0
    while rounds < 40 and not all(c.height == 30 for c in committers):
        for node in nodes:
            node.tick()
        rounds += 1
    assert all(c.height == 30 for c in committers), [
        c.height for c in committers
    ]


def test_pull_inflight_digests_not_double_requested():
    """Two digests arriving from two concurrent pulls in the same round
    are requested once: the second dig response for an in-flight digest
    yields no data_req."""
    from fabric_tpu.gossip.core import ChannelGossip

    sent = []

    class SpyComm:
        pki_id = b"spy"

        def subscribe(self, fn):
            self.handler = fn

        def send(self, ep, msg):
            sent.append((ep, msg))

        def wrap(self, m):
            import fabric_tpu.protos.gossip.message_pb2 as gpb

            return gpb.SignedGossipMessage(payload=m.SerializeToString())

    comm = SpyComm()
    cg = ChannelGossip("ch", comm, lambda: ["a", "b"])
    cg.tick()  # sends hellos to both peers
    hellos = [m for _, m in sent if m.WhichOneof("content") == "hello"]
    assert len(hellos) == 2
    sent.clear()

    import fabric_tpu.protos.gossip.message_pb2 as gpb

    class FakeRM:
        def __init__(self, msg):
            self.msg = msg
            self.sender_pki = b"x"

    def dig(nonce):
        m = gpb.GossipMessage(channel=b"ch")
        m.data_dig.nonce = nonce
        m.data_dig.msg_type = gpb.PULL_BLOCK_MSG
        m.data_dig.digests.append(b"7")
        return m

    cg._endpoint_for = lambda pki: "a"
    cg._handle(FakeRM(dig(hellos[0].hello.nonce)))
    cg._handle(FakeRM(dig(hellos[1].hello.nonce)))
    reqs = [m for _, m in sent if m.WhichOneof("content") == "data_req"]
    assert len(reqs) == 1, "digest 7 must be requested exactly once"


def test_msgstore_ttl_expires_blocks_from_digests():
    """TTL semantics (reference gossip/gossip/msgstore/msgs.go): a block
    older than the TTL leaves the store — its digest is no longer
    advertised to pulls, the expiration callback fires exactly once, and
    younger blocks survive.  The count bound still caps bursts."""
    from fabric_tpu.gossip.core import ChannelGossip

    class SpyComm:
        pki_id = b"spy"

        def subscribe(self, fn):
            self.handler = fn

        def send(self, ep, msg):
            pass

    expired = []
    cg = ChannelGossip(
        "ch", SpyComm(), lambda: [], store_ttl_ticks=3,
        on_expire=lambda seq, blk: expired.append((seq, blk)),
    )
    cg.add_block(1, b"b1", push=False)
    cg.tick()
    cg.add_block(2, b"b2", push=False)
    cg.tick()  # tick 2: block 1 is 2 ticks old — still there
    assert cg.store.digests() == [1, 2]
    cg.tick()  # tick 3: block 1 (stamped tick 0) hits ttl=3
    assert cg.store.digests() == [2]
    assert cg.store.get(1) is None
    assert expired == [(1, b"b1")]
    cg.tick()  # tick 4: block 2 (stamped tick 1) expires too
    assert cg.store.digests() == []
    assert expired == [(1, b"b1"), (2, b"b2")]

    # without a TTL the count bound alone evicts (oldest first, no cb)
    cg2 = ChannelGossip("ch", SpyComm(), lambda: [], store_capacity=2)
    for s in (1, 2, 3):
        cg2.add_block(s, b"x", push=False)
    for _ in range(10):
        cg2.tick()
    assert cg2.store.digests() == [2, 3]
