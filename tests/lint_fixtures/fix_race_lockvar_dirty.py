"""SEEDED VIOLATION (racecheck): the worker takes the WRONG lock
through a bare local alias (``lock = self._aux; with lock:``).  Before
PR 8 a lock-shaped local degraded to the UNKNOWN lockset, which
suppressed this finding; resolving the alias through its binding shows
the held lock is not the field's guard."""

from fabric_tpu.devtools.lockwatch import named_lock, spawn_thread


class SessionTable:
    def __init__(self):
        self._lock = named_lock("fixture.sessions")
        self._aux = named_lock("fixture.sessions.aux")
        self._sessions = {}

    def start(self):
        t = spawn_thread(
            target=self._expire, name="fixture-expire", kind="worker"
        )
        t.start()
        return t

    def _expire(self):
        lock = self._aux
        with lock:
            self._sessions["expired"] = True  # <- racecheck fires HERE

    def put(self, key, value):
        with self._lock:
            self._sessions[key] = value

    def get(self, key):
        with self._lock:
            return self._sessions.get(key)
