"""profscope — the profiling plane (third observability pillar).

Tracelens spans say WHICH stage is slow and netscope time series say
WHEN a node degraded; profscope says WHY: where the interpreter
actually spends its time, which lock roles threads wait behind, and
how long workpool chunks sit queued before they run.  It follows the
tracelens/faultline seam discipline exactly:

* ``FABRIC_TPU_PROFILE`` unset (the default, and tier-1's default):
  ``_profiler`` is None and every entry point is a shared no-op whose
  fast path is one module-global load plus an ``is None`` test.  The
  armed-path counter (:func:`lookup_count`) stays 0 across a live
  commit+RPC workload — pinned by tests/test_profile.py.
* armed (env knob, :func:`arm`, or :func:`scope`): a sampler service
  thread walks ``sys._current_frames()`` on a cadence routed through
  clockskew (so virtual-clock sessions replay), folding each thread's
  stack into a BOUNDED in-process aggregate of collapsed stacks.  A
  frame that moved since the previous sample (``(id(frame), f_lasti)``
  changed) counts as on-CPU; one that did not is treated as waiting —
  a GIL-friendly approximation of per-thread CPU vs wall time.  (On
  3.12+ ``sys.monitoring`` could drive exact attribution; the sampling
  form is kept because it is version-portable and has no per-bytecode
  cost.)  Samples landing inside a live tracelens span are attributed
  to it, so ``critical_path_ms`` gains a per-stage ``self_cpu_ms``
  breakdown.  Lock acquire-wait/hold (fed by lockwatch) and workpool
  queue-wait/run-time (fed by run_chunked) aggregate here too, and
  mirror into ``lock_wait_seconds{role=...}`` histograms on /metrics
  when a :class:`~fabric_tpu.common.metrics.LockMetrics` bundle is
  attached via :func:`set_lock_metrics`.

Export surfaces: :func:`export` returns a speedscope-format document
(loadable at speedscope.app) whose ``otherData`` carries the collapsed
stacks, ``self_cpu_ms`` map, lock-role and workpool aggregates; the
operations System serves it at ``GET /profile`` (and an on-demand
session at ``/profile?seconds=N`` via :func:`sample_for`), with heap
attribution at ``/profile/heap`` (:func:`heap_doc`).  The reference's
side pprof listener (``peer.profile.*`` / ``General.Profile.Address``)
— our old ``ProfileServer`` — is retired into those endpoints.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import threading

from fabric_tpu.common import tracing
from fabric_tpu.devtools import clockskew, knob_registry
from fabric_tpu.devtools.lockwatch import spawn_thread

_ENV = "FABRIC_TPU_PROFILE"
_FALSY = ("", "0", "false", "off", "no")

DEFAULT_INTERVAL_S = 0.01  # 100 Hz
DEFAULT_MAX_STACKS = 4096  # distinct collapsed stacks kept per session
_MAX_DEPTH = 64            # frames kept per stack walk

SPEEDSCOPE_SCHEMA = "https://www.speedscope.app/file-format-schema.json"

# idents of threads currently running a sampling loop: every session
# (background or inline) skips them, so the profiler never profiles
# itself or a concurrent session's loop
_sampler_idents: set[int] = set()


class Profiler:
    """One profiling session: a bounded aggregate plus (optionally) a
    background sampler service thread.  All shared aggregate state
    moves under ``_lock`` (declared in devtools/guards.py); ``_last``
    is confined to whichever single thread drives sample_once."""

    def __init__(self, interval_s: float = DEFAULT_INTERVAL_S,
                 max_stacks: int = DEFAULT_MAX_STACKS,
                 name: str = "profscope"):
        self.interval_s = max(1e-4, float(interval_s))
        self.max_stacks = int(max_stacks)
        self.name = name
        self._lock = threading.Lock()
        # collapsed "f (file:line);..." -> [wall_samples, cpu_samples]
        self._stacks: dict[str, list] = {}
        # (span name, span cat) -> [wall_samples, cpu_samples]
        self._spans: dict[tuple, list] = {}
        # lock role -> wait/hold aggregate dict
        self._locks: dict[str, dict] = {}
        self._chunks = {"chunks": 0, "queue_wait_s": 0.0, "run_s": 0.0}
        self._samples = 0
        self._dropped = 0
        self._t0 = clockskew.monotonic()
        # sampler-thread-confined: last seen (frame id, f_lasti) per tid
        self._last: dict[int, tuple] = {}
        self._stop_evt = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        """Start the background sampler (idempotent)."""
        if self._thread is not None:
            return
        self._stop_evt.clear()
        t = spawn_thread(
            self._run, name="profscope-sampler", kind="service",
        )
        self._thread = t
        t.start()

    def stop(self) -> None:
        """Stop the sampler and JOIN it — the deterministic teardown
        the thread-lifecycle lint demands of every spawn site."""
        t = self._thread
        if t is None:
            return
        self._stop_evt.set()
        t.join(timeout=10.0)
        self._thread = None

    @property
    def running(self) -> bool:
        return self._thread is not None

    def _run(self) -> None:
        ident = threading.get_ident()
        _sampler_idents.add(ident)
        try:
            while not self._stop_evt.is_set():
                self.sample_once()
                if clockskew.wait(self._stop_evt, self.interval_s):
                    break
        finally:
            _sampler_idents.discard(ident)

    # -- sampling -----------------------------------------------------------

    def sample_once(self) -> None:
        """Fold one ``sys._current_frames()`` sweep into the aggregate.
        Must be driven from ONE thread per profiler (the background
        sampler, or the caller of sample_rounds/sample_for)."""
        me = threading.get_ident()
        trace_on = tracing.enabled()
        frames = sys._current_frames()
        rows = []
        try:
            for tid, frame in frames.items():
                if tid == me or tid in _sampler_idents:
                    continue
                top = (id(frame), frame.f_lasti)
                on_cpu = self._last.get(tid) != top
                self._last[tid] = top
                parts = []
                f = frame
                depth = 0
                while f is not None and depth < _MAX_DEPTH:
                    code = f.f_code
                    parts.append(
                        f"{code.co_name} "
                        f"({code.co_filename.rsplit(os.sep, 1)[-1]}"
                        f":{f.f_lineno})"
                    )
                    f = f.f_back
                    depth += 1
                parts.reverse()
                span = tracing.active_span_of(tid) if trace_on else None
                rows.append((";".join(parts), on_cpu, span))
            if len(self._last) > 2 * len(frames) + 8:
                self._last = {
                    t: v for t, v in self._last.items() if t in frames
                }
        finally:
            del frames  # frames hold other threads' locals; drop fast
        with self._lock:
            self._samples += 1
            for key, on_cpu, span in rows:
                cell = self._stacks.get(key)
                if cell is None:
                    if len(self._stacks) >= self.max_stacks:
                        self._dropped += 1
                        continue
                    cell = self._stacks[key] = [0, 0]
                cell[0] += 1
                if on_cpu:
                    cell[1] += 1
                if span is not None:
                    skey = (span.name, span.cat)
                    scell = self._spans.get(skey)
                    if scell is None and len(self._spans) < self.max_stacks:
                        scell = self._spans[skey] = [0, 0]
                    if scell is not None:
                        scell[0] += 1
                        if on_cpu:
                            scell[1] += 1

    def sample_rounds(self, n: int) -> None:
        """n synchronous sweeps with no cadence wait — deterministic
        test hook for an un-started profiler."""
        for _ in range(n):
            self.sample_once()

    # -- feed points (called via the module-level no-op seam) ---------------

    def _note_lock(self, role: str, wait_s: float | None = None,
                   hold_s: float | None = None) -> None:
        with self._lock:
            cell = self._locks.get(role)
            if cell is None:
                cell = self._locks[role] = {
                    "wait_s": 0.0, "wait_count": 0, "max_wait_s": 0.0,
                    "hold_s": 0.0, "hold_count": 0,
                }
            if wait_s is not None:
                cell["wait_s"] += wait_s
                cell["wait_count"] += 1
                if wait_s > cell["max_wait_s"]:
                    cell["max_wait_s"] = wait_s
            if hold_s is not None:
                cell["hold_s"] += hold_s
                cell["hold_count"] += 1

    def _note_chunk(self, queue_wait_s: float, run_s: float) -> None:
        with self._lock:
            c = self._chunks
            c["chunks"] += 1
            c["queue_wait_s"] += queue_wait_s
            c["run_s"] += run_s

    # -- export -------------------------------------------------------------

    def reset(self) -> None:
        """Clear the aggregates (bench resets per measured pass, like
        tracing.reset)."""
        with self._lock:
            self._stacks.clear()
            self._spans.clear()
            self._locks.clear()
            self._chunks = {
                "chunks": 0, "queue_wait_s": 0.0, "run_s": 0.0,
            }
            self._samples = 0
            self._dropped = 0
            self._t0 = clockskew.monotonic()

    def export(self, name: str | None = None) -> dict:
        """Snapshot the aggregate as one speedscope-format document.
        ``shared.frames``/``profiles[0]`` load directly in the
        speedscope app; everything fabric-specific (collapsed stacks,
        per-stage ``self_cpu_ms``, lock-role waits, workpool chunk
        attribution) rides in ``otherData``."""
        with self._lock:
            stacks = {k: list(v) for k, v in self._stacks.items()}
            spans = {k: list(v) for k, v in self._spans.items()}
            locks = {
                r: {k: round(v, 6) if isinstance(v, float) else v
                    for k, v in c.items()}
                for r, c in self._locks.items()
            }
            chunks = {
                k: round(v, 6) if isinstance(v, float) else v
                for k, v in self._chunks.items()
            }
            samples = self._samples
            dropped = self._dropped
            duration = max(0.0, clockskew.monotonic() - self._t0)
        frames: list[str] = []
        index: dict[str, int] = {}
        sample_rows: list[list[int]] = []
        weights: list[float] = []
        collapsed: list[str] = []
        for key in sorted(stacks):
            wall, _cpu = stacks[key]
            idxs = []
            for fr in key.split(";"):
                i = index.get(fr)
                if i is None:
                    i = index[fr] = len(frames)
                    frames.append(fr)
                idxs.append(i)
            sample_rows.append(idxs)
            weights.append(round(wall * self.interval_s, 6))
            collapsed.append(f"{key} {wall}")
        total = round(sum(weights), 6)
        span_rows = []
        self_cpu: dict[str, float] = {}
        for skey in sorted(spans):
            sname, cat = skey
            wall, cpu = spans[skey]
            cpu_ms = round(cpu * self.interval_s * 1e3, 3)
            span_rows.append({
                "name": sname, "cat": cat,
                "wall_samples": wall, "cpu_samples": cpu,
                "self_wall_ms": round(wall * self.interval_s * 1e3, 3),
                "self_cpu_ms": cpu_ms,
            })
            self_cpu[sname] = round(self_cpu.get(sname, 0.0) + cpu_ms, 3)
        return {
            "$schema": SPEEDSCOPE_SCHEMA,
            "exporter": "fabric-tpu profscope",
            "name": name or self.name,
            "activeProfileIndex": 0,
            "shared": {"frames": [{"name": fr} for fr in frames]},
            "profiles": [{
                "type": "sampled",
                "name": name or self.name,
                "unit": "seconds",
                "startValue": 0,
                "endValue": total,
                "samples": sample_rows,
                "weights": weights,
            }],
            "otherData": {
                "armed": _profiler is self,
                "interval_s": self.interval_s,
                "samples": samples,
                "duration_s": round(duration, 6),
                "dropped_stacks": dropped,
                "collapsed": collapsed,
                "self_cpu_ms": self_cpu,
                "span_cpu": span_rows,
                "locks": locks,
                "workpool": chunks,
            },
        }


# the armed profiler; None = profiling disarmed.  EVERY entry point's
# fast path tests only this global (the tracing `_recorder` pattern).
_profiler: Profiler | None = None
_state_lock = threading.Lock()

# armed-path consultations — stays 0 while profiling has never been
# armed, which is the zero-overhead acceptance probe
_lookups = [0]

# optional live LockMetrics bundle (operations.System.lock_metrics()):
# armed lock waits/holds mirror into its histograms for /metrics
_lock_metrics = None


def enabled() -> bool:
    return _profiler is not None


def profiler() -> Profiler | None:
    return _profiler


def lookup_count() -> int:
    return _lookups[0]


def arm(interval_s: float | None = None,
        max_stacks: int | None = None) -> Profiler:
    """Arm profiling process-wide and start the sampler; replaces (and
    stops) any previous profiler."""
    global _profiler
    prof = Profiler(
        interval_s=DEFAULT_INTERVAL_S if interval_s is None else interval_s,
        max_stacks=DEFAULT_MAX_STACKS if max_stacks is None else max_stacks,
    )
    with _state_lock:
        prev = _profiler
        _profiler = prof
    if prev is not None:
        prev.stop()
    prof.start()
    return prof


def disarm() -> None:
    global _profiler
    with _state_lock:
        prof = _profiler
        _profiler = None
    if prof is not None:
        prof.stop()


@contextlib.contextmanager
def scope(interval_s: float | None = None,
          max_stacks: int | None = None, sampler: bool = True):
    """Temporarily armed profiler for tests/benches; restores the
    previous armed state (without stopping it) on exit and always
    joins its own sampler.  ``sampler=False`` arms the seam without a
    background thread — feed points and sample_rounds still work,
    deterministically."""
    global _profiler
    prof = Profiler(
        interval_s=DEFAULT_INTERVAL_S if interval_s is None else interval_s,
        max_stacks=DEFAULT_MAX_STACKS if max_stacks is None else max_stacks,
    )
    with _state_lock:
        prev = _profiler
        _profiler = prof
    if sampler:
        prof.start()
    try:
        yield prof
    finally:
        with _state_lock:
            _profiler = prev
        prof.stop()


def reset() -> None:
    p = _profiler
    if p is None:
        return
    _lookups[0] += 1
    p.reset()


def set_lock_metrics(bundle) -> None:
    """Attach a LockMetrics bundle: armed lock waits/holds observe
    into its ``lock_wait_seconds{role}`` / ``lock_hold_seconds{role}``
    histograms (node wiring calls this with the operations System's
    bundle)."""
    global _lock_metrics
    _lock_metrics = bundle


def note_lock_wait(role: str, seconds: float) -> None:
    """Feed point for lockwatch: time a thread spent blocked acquiring
    the lock with this role.  No-op disarmed; the profiler's own lock
    roles are excluded so metric observation can never recurse."""
    p = _profiler
    if p is None:
        return
    if role.startswith("profile."):
        return
    _lookups[0] += 1
    p._note_lock(role, wait_s=seconds)
    m = _lock_metrics
    if m is not None:
        try:
            m.wait.With("role", role).observe(seconds)
        except Exception:
            pass


def note_lock_hold(role: str, seconds: float) -> None:
    """Feed point for lockwatch: how long the lock was held once
    acquired (outermost acquire to final release)."""
    p = _profiler
    if p is None:
        return
    if role.startswith("profile."):
        return
    _lookups[0] += 1
    p._note_lock(role, hold_s=seconds)
    m = _lock_metrics
    if m is not None:
        try:
            m.hold.With("role", role).observe(seconds)
        except Exception:
            pass


def note_chunk(queue_wait_s: float, run_s: float) -> None:
    """Feed point for workpool.run_chunked: per-chunk queue-wait vs
    run-time attribution."""
    p = _profiler
    if p is None:
        return
    _lookups[0] += 1
    p._note_chunk(queue_wait_s, run_s)


def export(name: str | None = None) -> dict:
    """The armed profiler's accumulated document, or a valid (empty)
    disarmed speedscope doc — the /traces 'armed: false' convention."""
    p = _profiler
    if p is None:
        return {
            "$schema": SPEEDSCOPE_SCHEMA,
            "exporter": "fabric-tpu profscope",
            "name": "profscope (disarmed)",
            "activeProfileIndex": 0,
            "shared": {"frames": []},
            "profiles": [],
            "otherData": {"armed": False},
        }
    _lookups[0] += 1
    return p.export(name)


def sample_for(seconds: float, interval_s: float | None = None,
               name: str = "profscope.session") -> dict:
    """Synchronous sampling session in the CALLING thread (no spawn):
    backs ``GET /profile?seconds=N``, works armed or disarmed, and
    under a virtual clock completes instantly with the same number of
    rounds.  Always takes at least one sample."""
    prof = Profiler(
        interval_s=DEFAULT_INTERVAL_S if interval_s is None else interval_s,
        name=name,
    )
    ident = threading.get_ident()
    _sampler_idents.add(ident)
    try:
        deadline = clockskew.monotonic() + max(0.0, float(seconds))
        while True:
            prof.sample_once()
            if clockskew.monotonic() >= deadline:
                break
            clockskew.sleep(prof.interval_s)
    finally:
        _sampler_idents.discard(ident)
    return prof.export()


def heap_doc(limit: int = 50) -> dict:
    """Allocation attribution via tracemalloc (``GET /profile/heap``).
    Starts tracemalloc on first call if nobody else did — that first
    document only covers allocations from this point on, flagged by
    ``tracemalloc_started_now``."""
    import tracemalloc

    started_now = False
    if not tracemalloc.is_tracing():
        tracemalloc.start()
        started_now = True
    snapshot = tracemalloc.take_snapshot()
    current, peak = tracemalloc.get_traced_memory()
    stats = snapshot.statistics("lineno")[: max(0, int(limit))]
    top = [
        {
            "site": (
                f"{s.traceback[0].filename.rsplit(os.sep, 1)[-1]}"
                f":{s.traceback[0].lineno}"
            ),
            "size_bytes": s.size,
            "count": s.count,
        }
        for s in stats
    ]
    return {
        "source": "fabric_tpu.profscope.heap",
        "tracemalloc_started_now": started_now,
        "current_bytes": current,
        "peak_bytes": peak,
        "top": top,
    }


def dump_to(path: str, doc: dict | None = None) -> str:
    """Write a profile document (default: :func:`export`) as JSON."""
    doc = export() if doc is None else doc
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, sort_keys=True)
    return path


def _init_from_env() -> None:
    """FABRIC_TPU_PROFILE: unset/falsy = disarmed; truthy = armed at
    the default 100 Hz; a number > 1 = that sampling rate in Hz (the
    FABRIC_TPU_TRACE sizing convention)."""
    raw = knob_registry.raw(_ENV)
    if raw.strip().lower() in _FALSY:
        if _profiler is not None:
            disarm()
        return
    try:
        hz = float(raw)
    except ValueError:
        hz = 0.0
    arm(interval_s=(1.0 / hz) if hz > 1.0 else DEFAULT_INTERVAL_S)


_init_from_env()


__all__ = [
    "Profiler",
    "enabled",
    "profiler",
    "lookup_count",
    "arm",
    "disarm",
    "scope",
    "reset",
    "export",
    "sample_for",
    "heap_doc",
    "dump_to",
    "set_lock_metrics",
    "note_lock_wait",
    "note_lock_hold",
    "note_chunk",
    "DEFAULT_INTERVAL_S",
    "DEFAULT_MAX_STACKS",
    "SPEEDSCOPE_SCHEMA",
]
