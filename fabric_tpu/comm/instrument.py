"""RPC interceptors: per-method metrics + payload logging.

The reference wraps every gRPC server with duration/count metric
interceptors (common/grpcmetrics/interceptor.go: grpc_server_unary_
requests_completed, _request_duration) and optional zap payload logging
(common/grpclogging).  `instrument` installs the equivalent around an
RPCServer's method table; it applies to methods registered before AND
after the call.
"""

from __future__ import annotations

import logging
import time

from fabric_tpu.common.metrics import CounterOpts, HistogramOpts


def instrument(server, provider, payload_logger: str | None = None):
    """Wrap all (current and future) methods of `server` with metrics
    from `provider` (common.metrics Provider) and, when payload_logger
    names a logger, DEBUG-level payload logging."""
    completed = provider.new_counter(CounterOpts(
        namespace="rpc", subsystem="server",
        name="requests_completed",
        help="Completed RPCs, labeled by method and result code.",
        label_names=["method", "code"],
    ))
    duration = provider.new_histogram(HistogramOpts(
        namespace="rpc", subsystem="server",
        name="request_duration",
        help="RPC handling time in seconds, labeled by method.",
        label_names=["method"],
    ))
    log = logging.getLogger(payload_logger) if payload_logger else None

    def wrap(method: str, fn):
        def handler(body, stream):
            t0 = time.perf_counter()
            if log is not None:
                log.debug("rpc recv %s (%d bytes)", method, len(body))
            try:
                out = fn(body, stream)
            except Exception:
                completed.with_labels("method", method, "code", "error").add()
                duration.with_labels("method", method).observe(
                    time.perf_counter() - t0
                )
                raise
            completed.with_labels("method", method, "code", "ok").add()
            duration.with_labels("method", method).observe(
                time.perf_counter() - t0
            )
            return out

        return handler

    # wrap what exists; hook register for what comes later
    for m, fn in list(server.methods.items()):
        server.methods[m] = wrap(m, fn)
    orig_register = server.register

    def register(method, fn, limiter=None):
        orig_register(method, fn, limiter=limiter)
        server.methods[method] = wrap(method, server.methods[method])

    server.register = register
    return server


__all__ = ["instrument"]
