"""configtxgen: build genesis blocks from configtx.yaml profiles
(reference internal/configtxgen/{genesisconfig,encoder} + cmd/configtxgen).

Supported schema (subset):

    Organizations:
      - Name: Org1
        ID: Org1MSP
        MSPDir: crypto-config/peerOrganizations/org1.example.com/msp
    Profiles:
      TwoOrgsApplicationGenesis:
        Orderer:
          OrdererType: solo            # or raft/etcdraft
          BatchTimeout: 250ms
          BatchSize: {MaxMessageCount: 10}
          Organizations: [Orderer]
          Addresses: [127.0.0.1:7050]
        Application:
          Organizations: [Org1, Org2]

Flags mirror the reference: -profile, -channelID, -outputBlock,
-inspectBlock, -configPath.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import yaml

from fabric_tpu.common import configtx_builder as ctx
from fabric_tpu.msp.config import load_msp_dir
from fabric_tpu.protos.common import common_pb2


def _org_groups(org_names, org_index, config_dir):
    out = {}
    for name in org_names or []:
        org = org_index[name]
        msp_dir = org["MSPDir"]
        if not os.path.isabs(msp_dir):
            msp_dir = os.path.join(config_dir, msp_dir)
        conf = load_msp_dir(msp_dir, org["ID"])
        from fabric_tpu.protos.msp import msp_config_pb2

        fconf = msp_config_pb2.FabricMSPConfig.FromString(conf.config)
        if not fconf.root_certs:
            raise SystemExit(
                f"MSPDir {msp_dir!r} for org {org['Name']!r} has no CA "
                "certs (run cryptogen first?)"
            )
        out[org["Name"]] = ctx.org_group(org["ID"], conf)
    return out


def build_genesis(doc: dict, profile_name: str, channel_id: str,
                  config_dir: str) -> common_pb2.Block:
    profile = (doc.get("Profiles") or {})[profile_name]
    org_index = {o["Name"]: o for o in doc.get("Organizations") or []}

    app = None
    if profile.get("Application"):
        app = ctx.application_group(
            _org_groups(
                profile["Application"].get("Organizations"), org_index,
                config_dir,
            )
        )
    ordg = None
    addresses = None
    if profile.get("Orderer"):
        oconf = profile["Orderer"]
        batch = oconf.get("BatchSize") or {}
        ordg = ctx.orderer_group(
            _org_groups(oconf.get("Organizations"), org_index, config_dir),
            consensus_type=oconf.get("OrdererType", "solo"),
            max_message_count=batch.get("MaxMessageCount", 500),
            absolute_max_bytes=batch.get(
                "AbsoluteMaxBytes", 10 * 1024 * 1024
            ),
            preferred_max_bytes=batch.get(
                "PreferredMaxBytes", 2 * 1024 * 1024
            ),
            batch_timeout=oconf.get("BatchTimeout", "2s"),
        )
        addresses = oconf.get("Addresses")
    group = ctx.channel_group(app, ordg, orderer_addresses=addresses)
    return ctx.genesis_block(channel_id, group)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="configtxgen")
    ap.add_argument("-profile")
    ap.add_argument("-channelID", default="testchannel")
    ap.add_argument("-outputBlock")
    ap.add_argument("-inspectBlock")
    ap.add_argument("-configPath", default=".")
    args = ap.parse_args(argv)

    if args.inspectBlock:
        with open(args.inspectBlock, "rb") as f:
            blk = common_pb2.Block.FromString(f.read())
        print(json.dumps({
            "number": blk.header.number,
            "previous_hash": blk.header.previous_hash.hex(),
            "data_hash": blk.header.data_hash.hex(),
            "tx_count": len(blk.data.data),
        }, indent=2))
        return 0

    if not args.profile or not args.outputBlock:
        ap.error("-profile and -outputBlock are required")
    cfg = os.path.join(args.configPath, "configtx.yaml")
    with open(cfg) as f:
        doc = yaml.safe_load(f) or {}
    blk = build_genesis(doc, args.profile, args.channelID, args.configPath)
    with open(args.outputBlock, "wb") as f:
        f.write(blk.SerializeToString())
    print(f"wrote genesis block for {args.channelID!r} to {args.outputBlock}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
