"""Chaincode lifecycle: the `_lifecycle` system chaincode.

Capability parity with the reference's core/chaincode/lifecycle
(lifecycle.go InstallChaincode/ApproveChaincodeDefinitionForOrg/
CheckCommitReadiness/CommitChaincodeDefinition; scc.go argument
dispatch; persistence/ package store).  Model:

- Install: store the package (.tar.gz bytes) on disk keyed by
  package-id = "<label>:<sha256>" (persistence/chaincode_package.go).
- Approve: org-scoped approval recorded in the org's implicit namespace —
  state key "approvals/<name>/<sequence>/<mspid>" holding the hash of the
  marshaled definition, the same agreement-by-hash scheme the reference
  implements with implicit private collections.
- CheckCommitReadiness: compare each org's stored approval hash against
  the proposed definition.
- Commit: requires approvals satisfying the channel's
  LifecycleEndorsement rule (MAJORITY of application orgs here, the
  reference default); writes "chaincodes/<name>" -> ChaincodeDefinition.

The committed definition (with its validation_parameter endorsement
policy) is what the txvalidator's VSCC reads via DefinitionProvider
(reference deployedcc_infoprovider.go).
"""

from __future__ import annotations

import json
import os

from fabric_tpu.chaincode.shim import Chaincode, ChaincodeStub, error, success
from fabric_tpu.common.hashing import sha256 as _sha256
from fabric_tpu.protos.peer import lifecycle_pb2 as lc

NAMESPACE = "_lifecycle"


class PackageStore:
    """On-disk chaincode package store (core/chaincode/persistence)."""

    def __init__(self, dir_path: str):
        self.dir = dir_path
        os.makedirs(dir_path, exist_ok=True)

    @staticmethod
    def package_id(label: str, package_bytes: bytes) -> str:
        return f"{label}:{_sha256(package_bytes).hex()}"

    def _path(self, package_id: str) -> str:
        # content hash names the file; labels live in the index
        return os.path.join(self.dir, package_id.rsplit(":", 1)[1] + ".tar.gz")

    def _index_path(self) -> str:
        return os.path.join(self.dir, "index.json")

    def _read_index(self) -> dict:
        try:
            with open(self._index_path()) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    def save(self, label: str, package_bytes: bytes) -> str:
        pid = self.package_id(label, package_bytes)
        path = self._path(pid)
        if not os.path.exists(path):
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(package_bytes)
            os.replace(tmp, path)
        idx = self._read_index()
        if pid not in idx:
            idx[pid] = label
            tmp = self._index_path() + ".tmp"
            with open(tmp, "w") as f:
                json.dump(idx, f)
            os.replace(tmp, self._index_path())
        return pid

    def load(self, package_id: str) -> bytes | None:
        if package_id not in self._read_index():
            return None
        try:
            with open(self._path(package_id), "rb") as f:
                return f.read()
        except OSError:
            return None

    def list(self) -> list[tuple[str, str]]:
        """[(package_id, label)]"""
        return sorted(self._read_index().items())


def _definition_hash(d: lc.ChaincodeDefinition) -> bytes:
    return _sha256(d.SerializeToString())


def _approval_key(name: str, sequence: int, mspid: str) -> str:
    return f"approvals/{name}/{sequence}/{mspid}"


def _definition_key(name: str) -> str:
    return f"chaincodes/{name}"


class LifecycleSCC(Chaincode):
    def __init__(self, package_store: PackageStore, org_lister=None):
        """org_lister() -> list of application-org MSP IDs on the channel
        (for MAJORITY commit readiness)."""
        self._store = package_store
        self._org_lister = org_lister or (lambda: [])

    # -- dispatch ----------------------------------------------------------

    def invoke(self, stub: ChaincodeStub):
        fn, params = stub.get_function_and_parameters()
        handler = {
            "InstallChaincode": self._install,
            "QueryInstalledChaincodes": self._query_installed,
            "GetInstalledChaincodePackage": self._get_package,
            "ApproveChaincodeDefinitionForMyOrg": self._approve,
            "CheckCommitReadiness": self._check_readiness,
            "CommitChaincodeDefinition": self._commit,
            "QueryChaincodeDefinition": self._query_definition,
            "QueryChaincodeDefinitions": self._query_definitions,
        }.get(fn)
        if handler is None:
            return error(f"unknown lifecycle function {fn!r}")
        try:
            return handler(stub, params[0] if params else b"")
        except Exception as exc:
            return error(str(exc))

    # -- install (node-local, no channel state) ----------------------------

    def _install(self, stub, raw):
        args = lc.InstallChaincodeArgs.FromString(raw)
        pkg = bytes(args.chaincode_install_package)
        label = self._package_label(pkg)
        pid = self._store.save(label, pkg)
        res = lc.InstallChaincodeResult(package_id=pid, label=label)
        return success(res.SerializeToString())

    @staticmethod
    def _package_label(pkg: bytes) -> str:
        """Packages are tar.gz with a metadata.json holding the label
        (persistence/chaincode_package.go ParseChaincodePackage); fall back
        to a content hash prefix for opaque blobs."""
        import gzip
        import io
        import tarfile

        try:
            with tarfile.open(fileobj=io.BytesIO(pkg), mode="r:gz") as tf:
                for m in tf.getmembers():
                    if os.path.basename(m.name) == "metadata.json":
                        meta = json.loads(tf.extractfile(m).read())
                        return meta.get("label", "unlabeled")
        except (tarfile.TarError, gzip.BadGzipFile, OSError, ValueError):
            pass
        return "pkg-" + _sha256(pkg).hex()[:12]

    def _query_installed(self, stub, raw):
        res = lc.QueryInstalledChaincodesResult()
        for pid, label in self._store.list():
            if label.startswith("cds:"):
                continue  # legacy lscc package (CDS bytes, not .tar.gz)
            ic = res.installed_chaincodes.add()
            ic.package_id = pid
            ic.label = label
        return success(res.SerializeToString())

    def _get_package(self, stub, raw):
        pid = raw.decode()
        pkg = self._store.load(pid)
        if pkg is None:
            return error(f"package {pid!r} not installed", status=404)
        return success(pkg)

    # -- approvals / commit (channel state) --------------------------------

    def _approve(self, stub, raw):
        args = lc.ApproveChaincodeDefinitionForMyOrgArgs.FromString(raw)
        d = args.definition
        mspid = stub.creator_mspid()
        if not mspid:
            return error("cannot determine approving org")
        committed = self._load_definition(stub, d.name)
        expected_seq = (committed.sequence + 1) if committed else 1
        if d.sequence > expected_seq:
            return error(
                f"requested sequence {d.sequence}, next committable is {expected_seq}"
            )
        stub.put_state(
            _approval_key(d.name, d.sequence, mspid), _definition_hash(d)
        )
        return success(
            lc.ApproveChaincodeDefinitionForMyOrgResult().SerializeToString()
        )

    def _approvals_for(self, stub, d: lc.ChaincodeDefinition) -> dict[str, bool]:
        want = _definition_hash(d)
        out = {}
        for mspid in self._org_lister():
            got = stub.get_state(_approval_key(d.name, d.sequence, mspid))
            out[mspid] = bool(got) and got == want
        return out

    def _check_readiness(self, stub, raw):
        args = lc.CheckCommitReadinessArgs.FromString(raw)
        res = lc.CheckCommitReadinessResult()
        for mspid, ok in sorted(self._approvals_for(stub, args.definition).items()):
            res.approvals[mspid] = ok
        return success(res.SerializeToString())

    def _commit(self, stub, raw):
        args = lc.CommitChaincodeDefinitionArgs.FromString(raw)
        d = args.definition
        committed = self._load_definition(stub, d.name)
        expected_seq = (committed.sequence + 1) if committed else 1
        if d.sequence != expected_seq:
            return error(
                f"requested sequence {d.sequence}, next committable is {expected_seq}"
            )
        approvals = self._approvals_for(stub, d)
        yes = sum(approvals.values())
        if not approvals or yes * 2 <= len(approvals):
            return error(
                f"chaincode definition not agreed to by majority: {approvals}"
            )
        stub.put_state(_definition_key(d.name), d.SerializeToString())
        stub.set_event("CommitChaincodeDefinition", d.name.encode())
        return success(lc.CommitChaincodeDefinitionResult().SerializeToString())

    def _load_definition(self, stub, name: str) -> lc.ChaincodeDefinition | None:
        raw = stub.get_state(_definition_key(name))
        if not raw:
            return None
        return lc.ChaincodeDefinition.FromString(raw)

    def _query_definition(self, stub, raw):
        args = lc.QueryChaincodeDefinitionArgs.FromString(raw)
        d = self._load_definition(stub, args.name)
        if d is None:
            return error(f"namespace {args.name} is not defined", status=404)
        res = lc.QueryChaincodeDefinitionResult()
        res.definition.CopyFrom(d)
        for mspid, ok in sorted(self._approvals_for(stub, d).items()):
            res.approvals[mspid] = ok
        return success(res.SerializeToString())

    def _query_definitions(self, stub, raw):
        res = lc.QueryChaincodeDefinitionsResult()
        for key, value in stub.get_state_by_range("chaincodes/", "chaincodes0"):
            info = res.chaincode_definitions.add()
            info.name = key.split("/", 1)[1]
            info.definition.ParseFromString(value)
        return success(res.SerializeToString())


class DefinitionProvider:
    """Reads committed chaincode definitions straight from the state DB —
    the validator-side seam (reference lifecycle/deployedcc_infoprovider.go
    ValidationInfo): returns the endorsement policy for a namespace."""

    def __init__(self, ledger):
        self._ledger = ledger

    def definition(self, name: str) -> lc.ChaincodeDefinition | None:
        sim = self._ledger.new_query_executor()
        raw = sim.get_state(NAMESPACE, _definition_key(name))
        if not raw:
            return None
        return lc.ChaincodeDefinition.FromString(raw)

    def validation_info(self, name: str) -> tuple[str, bytes] | None:
        d = self.definition(name)
        if d is None:
            return None
        return (d.validation_plugin or "vscc", bytes(d.validation_parameter))

    def collection_config(self, name: str, collection: str):
        """The StaticCollectionConfig of one collection, or None
        (reference deployedcc_infoprovider.go AllCollectionsConfigPkg +
        v20.go CollectionValidationInfo)."""
        from fabric_tpu.protos.peer import collection_pb2

        d = self.definition(name)
        if d is None or not d.collections:
            return None
        pkg = collection_pb2.CollectionConfigPackage.FromString(d.collections)
        for c in pkg.config:
            sc = c.static_collection_config
            if c.HasField("static_collection_config") and sc.name == collection:
                return sc
        return None


__all__ = ["LifecycleSCC", "PackageStore", "DefinitionProvider", "NAMESPACE"]
