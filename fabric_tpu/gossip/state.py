"""Gossip state transfer: ordered block delivery into the commit pipeline.

Capability parity with the reference's gossip/state
(state.go:189 NewGossipStateProvider, :547 deliverPayloads, :591
antiAntropy, :750 AddPayload, :781 commitBlock): blocks arrive out of
order from gossip push/pull or in order from the deliver client; a
payload buffer holds them; a delivery loop commits strictly sequentially;
anti-entropy asks peers that advertise greater height for the missing
range (RemoteStateRequest/Response).
"""

from __future__ import annotations

import threading

from fabric_tpu.devtools import faultline
from fabric_tpu.devtools.lockwatch import named_lock

from fabric_tpu.protos.common import common_pb2
from fabric_tpu.protos.gossip import message_pb2 as gpb


class PayloadBuffer:
    def __init__(self):
        self._by_seq: dict[int, bytes] = {}
        self._lock = named_lock("gossip.state.buffer")

    def push(self, seq: int, block_bytes: bytes) -> None:
        with self._lock:
            self._by_seq.setdefault(seq, block_bytes)

    def pop(self, seq: int) -> bytes | None:
        with self._lock:
            return self._by_seq.pop(seq, None)

    def __contains__(self, seq: int) -> bool:
        with self._lock:
            return seq in self._by_seq


class StateProvider:
    def __init__(
        self,
        channel_id: str,
        channel_gossip,  # ChannelGossip
        committer,       # object with .store_block(Block) and .height
        comm,
        max_batch: int = 10,
    ):
        self.channel_id = channel_id
        self._chan = channel_id.encode()
        self._gossip = channel_gossip
        self._committer = committer
        self._comm = comm
        self._buffer = PayloadBuffer()
        self._max_batch = max_batch
        # watched under FABRIC_TPU_LOCKWATCH: ordered BEFORE the
        # ledger commit lock (store_block enters the committer/ledger
        # while holding it); nothing may take it while holding those
        self._commit_lock = named_lock("gossip.state.commit")
        # re-entrancy guard for continuous catch-up: the in-process
        # gossip transport dispatches synchronously on the sender's
        # stack, so an unguarded request->response->request chain would
        # RECURSE once per batch and overflow the stack on a peer far
        # behind; one level of chaining per thread keeps TCP at
        # transfer rate while in-proc degrades safely to tick rate
        self._chaining = threading.local()
        # optional common.metrics.GossipMetrics (state-transfer
        # counters), published by GossipService.set_metrics
        self._metrics = None
        channel_gossip.ledger_height = lambda: self._committer.height
        # blocks arriving via gossip land here
        self._gossip._on_block = self._on_gossip_block
        comm.subscribe(self._handle)

    def set_metrics(self, metrics) -> None:
        self._metrics = metrics

    # -- ingestion ---------------------------------------------------------

    def add_payload(self, seq: int, block_bytes: bytes, from_orderer: bool = False) -> None:
        """AddPayload: deliver-client (ordered) or gossip (unordered)."""
        if seq < self._committer.height:
            return  # already committed
        # EVERY path a block takes into this peer funnels through here
        # or _on_gossip_block — an armed raise at this point wedges
        # exactly this node's height while its process stays alive and
        # chatty (the silent-wedge class netscope's stall detector
        # exists for; tests/test_netscope.py drives it per-node)
        faultline.point("gossip.state.payload", seq=seq)
        self._buffer.push(seq, block_bytes)
        if from_orderer:
            # teach the gossip layer so it disseminates to org peers
            self._gossip.add_block(seq, block_bytes)
        self._drain()

    def _on_gossip_block(self, seq: int, block_bytes: bytes) -> None:
        if seq < self._committer.height:
            return
        faultline.point("gossip.state.payload", seq=seq)
        self._buffer.push(seq, block_bytes)
        self._drain()

    # -- ordered commit ----------------------------------------------------

    def _drain(self) -> None:
        with self._commit_lock:
            while True:
                nxt = self._committer.height
                raw = self._buffer.pop(nxt)
                if raw is None:
                    return
                # contiguous run: a backlog (fast deliver stream,
                # post-restart catch-up) goes through the group-commit
                # pipeline — one fsync + one KV txn per group instead
                # of per block (the sink half of the ROADMAP #2
                # bottleneck).  A lone block keeps the per-block path:
                # no pipeline threads, no added latency.  hasattr
                # guard: toy committers in tests only do store_block.
                run = [raw]
                if hasattr(self._committer, "store_stream"):
                    while True:
                        more = self._buffer.pop(nxt + len(run))
                        if more is None:
                            break
                        run.append(more)
                if len(run) == 1:
                    self._committer.store_block(
                        common_pb2.Block.FromString(raw)
                    )
                else:
                    blocks = (
                        common_pb2.Block.FromString(r) for r in run
                    )
                    for _flags in self._committer.store_stream(blocks):
                        pass

    # -- anti-entropy ------------------------------------------------------

    def tick(self) -> None:
        """Request the missing range from the best-known peer if we lag."""
        self._request_missing()

    def _request_missing(self) -> bool:
        """One state-transfer request for the first missing range; True
        when a request went out.  Catch-up-under-churn fixes the
        netharness surfaced: blocks the payload buffer ALREADY holds
        are skipped (a restarted peer's push/pull traffic pre-fills the
        buffer — re-requesting those wastes the batch budget exactly
        when the peer is furthest behind), and the request anchors at
        the first actual gap."""
        ep, their_height = self._gossip.best_peer_height()
        my_height = self._committer.height
        if ep is None or their_height <= my_height:
            return False
        start = my_height
        while start < their_height and start in self._buffer:
            start += 1
        if start >= their_height:
            return False  # every missing block is already buffered
        m = self._metrics
        if m is not None:
            m.state_requests_sent.add()
        req = gpb.GossipMessage(channel=self._chan)
        req.state_request.start_seq_num = start
        req.state_request.end_seq_num = min(
            their_height - 1, start + self._max_batch - 1
        )
        self._comm.send(ep, req)
        return True

    def _handle(self, rm) -> None:
        msg = rm.msg
        if bytes(msg.channel) != self._chan:
            return
        kind = msg.WhichOneof("content")
        if kind == "state_request":
            resp = gpb.GossipMessage(channel=self._chan)
            lo = msg.state_request.start_seq_num
            hi = msg.state_request.end_seq_num
            for seq in range(lo, hi + 1):
                raw = self._gossip.store.get(seq) or self._read_committed(seq)
                if raw is None:
                    break
                dm = resp.state_response.payloads.add()
                dm.seq_num = seq
                dm.block = raw
            ep = self._gossip._endpoint_for(rm.sender_pki)
            if ep and resp.state_response.payloads:
                m = self._metrics
                if m is not None:
                    m.state_requests_served.add()
                    m.state_blocks_served.add(
                        len(resp.state_response.payloads)
                    )
                self._comm.send(ep, resp)
        elif kind == "state_response":
            before = self._committer.height
            for dm in msg.state_response.payloads:
                self.add_payload(dm.seq_num, bytes(dm.block))
            # continuous catch-up: this batch made real progress and we
            # are still behind — chain the next request NOW instead of
            # waiting for the next anti-entropy tick, so a kill -9'd
            # peer catches up at transfer rate, not tick rate (the
            # progress guard makes the chain terminate: a batch that
            # advances nothing stops it; the thread-local depth guard
            # keeps a synchronous in-proc transport from recursing)
            if (
                msg.state_response.payloads
                and self._committer.height > before
                and not getattr(self._chaining, "active", False)
            ):
                self._chaining.active = True
                try:
                    self._request_missing()
                finally:
                    self._chaining.active = False

    def _read_committed(self, seq: int) -> bytes | None:
        reader = getattr(self._committer, "get_block_by_number", None)
        if reader is None:
            return None
        blk = reader(seq)
        return blk.SerializeToString() if blk is not None else None


__all__ = ["StateProvider", "PayloadBuffer"]
