"""Channel-snapshot subsystem tests: request bookkeeping with commit-time
auto-trigger, the generate -> join-by-snapshot round trip (state / height /
pvt-hash parity, commit resumption at the snapshot height, reopen
recovery), tampered-snapshot rejection, repair-op guards, and the metrics
wiring (reference test model: core/ledger/kvledger snapshot tests +
internal/peer/snapshot)."""

import json
import os

import pytest

from fabric_tpu.ledger import LedgerProvider
from fabric_tpu.ledger import admin
from fabric_tpu.ledger.snapshot import (
    METADATA_FILE,
    PUBLIC_STATE_FILE,
    TXIDS_FILE,
    SnapshotError,
    load_metadata,
    verify_snapshot,
)

from test_ledger import _endorsed_block


CHANNEL = "snapch"


def _commit_blocks(ledger, start, count, channel=CHANNEL):
    """Commit `count` single-tx endorser blocks; every 5th tx also
    writes a private collection key (hashed write into the public rwset,
    cleartext into the pvt store)."""
    prev = ledger.block_store.last_block_hash
    for i in range(start, start + count):
        sim = ledger.new_tx_simulator()
        sim.set_state("cc", f"k{i:03d}", b"v%d" % i)
        pvt = None
        if i % 5 == 0:
            sim.set_private_data("cc", "coll", f"p{i}", b"secret%d" % i)
            pvt = sim.get_pvt_simulation_results()
        blk = _endorsed_block(
            i, prev, [sim.get_tx_simulation_results()], channel=channel
        )
        ledger.commit(blk, pvt_data={0: pvt} if pvt else None)
        prev = ledger.block_store.last_block_hash
    return ledger


def _source_ledger(tmp_path, n_blocks):
    provider = LedgerProvider(str(tmp_path / "src"))
    ledger = provider.open(CHANNEL)
    _commit_blocks(ledger, 0, n_blocks)
    return provider, ledger


# -- request lifecycle -----------------------------------------------------


def test_request_bookkeeping_and_auto_trigger(tmp_path):
    provider, ledger = _source_ledger(tmp_path, 5)
    mgr = ledger.snapshots
    # future request: recorded, pending, durable
    res = mgr.submit_request(8)
    assert res == {"block_number": 8, "snapshot_dir": None}
    with pytest.raises(SnapshotError):
        mgr.submit_request(8)  # duplicate
    with pytest.raises(SnapshotError):
        mgr.submit_request(2)  # already committed
    with pytest.raises(SnapshotError):
        mgr.cancel_request(9)  # never submitted
    mgr.submit_request(7)
    mgr.cancel_request(7)
    assert mgr.list_pending() == [8]
    # commits below the requested height change nothing
    _commit_blocks(ledger, 5, 3)
    assert mgr.list_pending() == [8]
    snap_dir = os.path.join(
        str(tmp_path / "src"), "snapshots", "completed", CHANNEL, "8"
    )
    assert not os.path.isdir(snap_dir)
    # committing block 8 auto-generates (in the background, off the
    # commit thread) and clears the request
    _commit_blocks(ledger, 8, 1)
    assert mgr.list_pending() == []
    assert mgr.wait_idle()
    assert os.path.isdir(snap_dir)
    meta = load_metadata(snap_dir)
    assert meta["last_block_number"] == 8
    assert meta["channel_id"] == CHANNEL
    # re-requesting a height that already has a snapshot on disk fails
    with pytest.raises(SnapshotError):
        mgr.submit_request(0)
    # block_number=0 snapshots the last committed block immediately
    _commit_blocks(ledger, 9, 1)
    res = mgr.submit_request(0)
    assert res["block_number"] == 9
    assert os.path.isdir(res["snapshot_dir"])
    provider.close()


def test_request_survives_reopen(tmp_path):
    provider, ledger = _source_ledger(tmp_path, 3)
    ledger.snapshots.submit_request(10)
    provider.close()
    provider2 = LedgerProvider(str(tmp_path / "src"))
    ledger2 = provider2.open(CHANNEL)
    assert ledger2.snapshots.list_pending() == [10]
    _commit_blocks(ledger2, 3, 8)
    assert ledger2.snapshots.list_pending() == []
    assert ledger2.snapshots.wait_idle()
    assert os.path.isdir(
        os.path.join(
            str(tmp_path / "src"), "snapshots", "completed", CHANNEL, "10"
        )
    )
    provider2.close()


# -- round trip ------------------------------------------------------------


def test_snapshot_round_trip_50_blocks(tmp_path):
    """Acceptance: a >=50-block channel snapshot restores to an
    identical-state, commit-ready ledger at the snapshot height, with
    metadata digests verified on import."""
    provider, ledger = _source_ledger(tmp_path, 55)
    path = ledger.snapshots.generate()
    meta = verify_snapshot(path)
    assert meta["last_block_number"] == 54

    dst = LedgerProvider(str(tmp_path / "dst"))
    restored = dst.create_from_snapshot(path)
    assert restored.height == ledger.height == 55
    assert (
        restored.block_store.last_block_hash
        == ledger.block_store.last_block_hash
    )
    # public state parity across every committed key
    for i in range(55):
        assert restored.get_state("cc", f"k{i:03d}") == b"v%d" % i
    # private data: hashes restored, cleartext intentionally absent
    for i in range(0, 55, 5):
        assert (
            restored.get_private_data_hash("cc", "coll", f"p{i}")
            == ledger.get_private_data_hash("cc", "coll", f"p{i}")
            is not None
        )
        assert restored.get_private_data("cc", "coll", f"p{i}") is None
    assert restored.pvt_store.bootstrap_height == 55
    # txid duplicate guard spans the snapshot; locations don't
    assert restored.tx_id_exists("tx-10-0")
    assert not restored.tx_id_exists("nope")
    assert restored.tx_ids_exist(["tx-3-0", "zzz"]) == {"tx-3-0"}
    assert restored.get_tx_by_id("tx-10-0") is None
    # no blocks below the bootstrap height
    assert restored.get_block_by_number(3) is None
    assert restored.block_store.bootstrap_height == 55

    # commit-ready: the next block lands at the snapshot height
    sim = restored.new_tx_simulator()
    sim.set_state("cc", "after", b"snapshot")
    blk = _endorsed_block(
        55,
        restored.block_store.last_block_hash,
        [sim.get_tx_simulation_results()],
        channel=CHANNEL,
    )
    restored.commit(blk)
    assert restored.height == 56
    assert restored.get_state("cc", "after") == b"snapshot"
    assert restored.get_block_by_number(55) is not None
    dst.close()

    # reopen from disk: recovery respects the bootstrap (no replay of
    # nonexistent blocks) and keeps post-snapshot commits
    dst2 = LedgerProvider(str(tmp_path / "dst"))
    reopened = dst2.open(CHANNEL)
    assert reopened.height == 56
    assert reopened.get_state("cc", "k012") == b"v12"
    assert reopened.get_state("cc", "after") == b"snapshot"
    assert reopened.block_store.bootstrap_height == 55
    dst2.close()
    provider.close()


def test_chained_snapshot_from_restored_ledger(tmp_path):
    """A snapshot generated BY a snapshot-bootstrapped ledger must stay
    complete (cumulative state + txids + carried config block)."""
    provider, ledger = _source_ledger(tmp_path, 52)
    path = ledger.snapshots.generate()
    mid = LedgerProvider(str(tmp_path / "mid"))
    restored = mid.create_from_snapshot(path)
    _commit_blocks(restored, 52, 3)
    path2 = restored.snapshots.generate()
    assert load_metadata(path2)["last_block_number"] == 54

    dst = LedgerProvider(str(tmp_path / "dst"))
    second = dst.create_from_snapshot(path2)
    assert second.height == 55
    assert second.get_state("cc", "k001") == b"v1"   # pre-first-snapshot
    assert second.get_state("cc", "k053") == b"v53"  # between snapshots
    assert second.tx_id_exists("tx-10-0") and second.tx_id_exists("tx-53-0")
    dst.close()
    mid.close()
    provider.close()


def test_rich_query_indexes_rebuilt_on_import(tmp_path):
    provider = LedgerProvider(str(tmp_path / "src"))
    ledger = provider.open(CHANNEL)
    prev = b""
    for i in range(3):
        sim = ledger.new_tx_simulator()
        sim.set_state(
            "cc", f"doc{i}", json.dumps({"size": i}).encode()
        )
        blk = _endorsed_block(
            i, prev, [sim.get_tx_simulation_results()], channel=CHANNEL
        )
        ledger.commit(blk)
        prev = ledger.block_store.last_block_hash
    ledger.define_index("cc", "size")
    path = ledger.snapshots.generate()

    dst = LedgerProvider(str(tmp_path / "dst"))
    restored = dst.create_from_snapshot(path)
    assert restored.state_db.indexes_for("cc") == {"size"}
    keys = list(restored.state_db.index_scan("cc", "size", None, None))
    assert sorted(keys) == ["doc0", "doc1", "doc2"]
    dst.close()
    provider.close()


def test_public_key_that_looks_like_collection_namespace(tmp_path):
    """A PUBLIC key embedding '\\x00pvt\\x00'/'\\x00hash\\x00' bytes parses
    like a derived collection namespace; export must still carry it (only
    confirmed cleartext private data — hashed counterpart present — is
    dropped)."""
    provider = LedgerProvider(str(tmp_path / "src"))
    ledger = provider.open(CHANNEL)
    tricky = ["pvt\x00a\x00b", "hash\x00c\x00d", "\x00composite\x00pvt\x00"]
    sim = ledger.new_tx_simulator()
    for k in tricky:
        sim.set_state("cc", k, b"public!")
    sim.set_private_data("cc", "coll", "realpvt", b"secret")
    blk = _endorsed_block(
        0, b"", [sim.get_tx_simulation_results()], channel=CHANNEL
    )
    ledger.commit(blk, pvt_data={0: sim.get_pvt_simulation_results()})
    path = ledger.snapshots.generate()

    dst = LedgerProvider(str(tmp_path / "dst"))
    restored = dst.create_from_snapshot(path)
    for k in tricky:
        assert restored.get_state("cc", k) == b"public!", k
    # the genuinely-private cleartext stays out of the snapshot
    assert restored.get_private_data("cc", "coll", "realpvt") is None
    assert (
        restored.get_private_data_hash("cc", "coll", "realpvt") is not None
    )
    dst.close()
    provider.close()


def test_reset_validates_all_channels_before_truncating(tmp_path):
    """reset() over a root holding a normal AND a bootstrapped channel
    must refuse upfront, leaving the normal channel untouched."""
    provider, ledger = _source_ledger(tmp_path, 5)
    path = ledger.snapshots.generate()
    provider.close()

    root = str(tmp_path / "mixed")
    prov = LedgerProvider(root)
    normal = prov.open("aaa_normal")  # sorts BEFORE snapch in the loop
    _commit_blocks(normal, 0, 3, channel="aaa_normal")
    prov.create_from_snapshot(path)
    prov.close()

    with pytest.raises(ValueError, match="bootstrapped from a snapshot"):
        admin.reset(root)
    check = LedgerProvider(root)
    assert check.open("aaa_normal").height == 3  # NOT half-reset
    check.close()


# -- tamper / error paths --------------------------------------------------


def test_tampered_snapshot_rejected(tmp_path):
    provider, ledger = _source_ledger(tmp_path, 5)
    path = ledger.snapshots.generate()

    def corrupt(name, mutate):
        p = os.path.join(path, name)
        raw = bytearray(open(p, "rb").read())
        orig = bytes(raw)
        mutate(raw)
        with open(p, "wb") as f:
            f.write(bytes(raw))
        dst = LedgerProvider(str(tmp_path / "dst"))
        with pytest.raises(SnapshotError):
            dst.create_from_snapshot(path)
        dst.close()
        with open(p, "wb") as f:
            f.write(orig)

    def flip(raw):
        raw[len(raw) // 2] ^= 0xFF

    corrupt(PUBLIC_STATE_FILE, flip)
    corrupt(TXIDS_FILE, lambda raw: raw.extend(b"\x00\x00\x00\x01x"))
    # a deleted data file is also refused
    os.rename(
        os.path.join(path, PUBLIC_STATE_FILE),
        os.path.join(path, PUBLIC_STATE_FILE + ".bak"),
    )
    with pytest.raises(SnapshotError):
        verify_snapshot(path)
    os.rename(
        os.path.join(path, PUBLIC_STATE_FILE + ".bak"),
        os.path.join(path, PUBLIC_STATE_FILE),
    )
    # pristine again: restore succeeds
    dst = LedgerProvider(str(tmp_path / "dst2"))
    assert dst.create_from_snapshot(path).height == 5
    dst.close()
    provider.close()


def test_metadata_required(tmp_path):
    with pytest.raises(SnapshotError):
        load_metadata(str(tmp_path))
    assert not os.path.exists(os.path.join(str(tmp_path), METADATA_FILE))


def test_cannot_restore_over_existing_channel(tmp_path):
    provider, ledger = _source_ledger(tmp_path, 5)
    path = ledger.snapshots.generate()
    with pytest.raises(SnapshotError):
        provider.create_from_snapshot(path)  # same provider, same channel
    provider.close()


# -- repair-op guards ------------------------------------------------------


def test_admin_ops_refuse_snapshot_bootstrapped_channel(tmp_path):
    provider, ledger = _source_ledger(tmp_path, 55)
    path = ledger.snapshots.generate()
    provider.close()

    dst_root = str(tmp_path / "dst")
    dst = LedgerProvider(dst_root)
    restored = dst.create_from_snapshot(path)
    _commit_blocks(restored, 55, 2)
    dst.close()

    with pytest.raises(ValueError, match="bootstrapped from a snapshot"):
        admin.rollback(dst_root, CHANNEL, 55)
    with pytest.raises(ValueError, match="bootstrapped from a snapshot"):
        admin.reset(dst_root)
    with pytest.raises(ValueError, match="bootstrapped from a snapshot"):
        admin.rebuild_dbs(dst_root)
    # the guards must not have damaged the channel
    dst2 = LedgerProvider(dst_root)
    assert dst2.open(CHANNEL).height == 57
    dst2.close()
    # an ordinary (non-bootstrapped) channel still rolls back fine
    with pytest.raises(ValueError):  # sanity: src guard does NOT trip
        admin.rollback(str(tmp_path / "src"), CHANNEL, 99)  # target too high


# -- metrics ---------------------------------------------------------------


def test_snapshot_metrics_wiring(tmp_path):
    from fabric_tpu.common.metrics import PrometheusProvider, SnapshotMetrics

    prov = PrometheusProvider()
    metrics = SnapshotMetrics(prov)
    provider = LedgerProvider(str(tmp_path / "src"), metrics=metrics)
    ledger = provider.open(CHANNEL)
    _commit_blocks(ledger, 0, 5)
    ledger.snapshots.submit_request(9)
    exposed = prov.registry.expose()
    assert 'snapshot_pending_requests{channel="snapch"} 1' in exposed
    _commit_blocks(ledger, 5, 5)  # auto-trigger at block 9
    assert ledger.snapshots.wait_idle()
    exposed = prov.registry.expose()
    assert 'snapshot_pending_requests{channel="snapch"} 0' in exposed
    assert "snapshot_generation_duration_count" in exposed
    assert "snapshot_bytes_hashed" in exposed
    assert "snapshot_hash_batch_mb_per_s" in exposed
    provider.close()


# -- peer-node surface (needs the crypto stack) ----------------------------


def test_peer_join_by_snapshot(tmp_path):
    """End-to-end over the node layer: snapshot a channel built through
    the devnode, then a fresh PeerNode joins it by snapshot and serves
    height/config/pending-request admin queries."""
    pytest.importorskip("cryptography")
    from orgfix import make_org

    from fabric_tpu.node.peer_node import PeerNode
    from test_ledger_admin import _make_chain

    lid = _make_chain(tmp_path / "src", 3)
    src = LedgerProvider(str(tmp_path / "src"))
    source_ledger = src.open(lid)
    height = source_ledger.height
    path = source_ledger.snapshots.generate()
    src.close()

    org = make_org("Org1MSP")
    node = PeerNode(
        str(tmp_path / "peer2"),
        org.csp,
        org.signer("peer1", role_ou="peer"),
    )
    try:
        assert node.join_by_snapshot(path) == lid
        assert lid in node.channel_list()
        ch = node.channels[lid]
        assert ch.ledger.height == height
        assert ch.ledger.get_state("kv", "k1") == b"v1"
        assert node._config_block(lid) is not None
        # snapshot admin handlers over the node surface
        body = json.dumps({"channel": lid, "block_number": height + 5})
        assert (
            json.loads(node._admin_snapshot_submit(body.encode(), None))[
                "snapshot_dir"
            ]
            is None
        )
        assert json.loads(
            node._admin_snapshot_list(lid.encode(), None)
        ) == [height + 5]
        node._admin_snapshot_cancel(body.encode(), None)
        assert json.loads(
            node._admin_snapshot_list(lid.encode(), None)
        ) == []
        # duplicate join refused
        with pytest.raises(SnapshotError):
            node.join_by_snapshot(path)
    finally:
        node.stop()

    # restart recovery re-joins the snapshot-bootstrapped channel from
    # its carried config block (no chain block 0 exists)
    node2 = PeerNode(
        str(tmp_path / "peer2"),
        org.csp,
        org.signer("peer1", role_ou="peer"),
    )
    try:
        assert lid in node2.channel_list()
        assert node2.channels[lid].ledger.height == height
    finally:
        node2.stop()
