"""Crypto service provider (CSP) -- the pluggable crypto SPI.

Equivalent of the reference's BCCSP (bccsp/bccsp.go:90-134) with one
deliberate extension the reference lacks: a first-class *batch* API
(`verify_batch`, `hash_batch`) so a whole block's signatures become a single
device call. Providers:

- sw:  host reference implementation (OpenSSL via `cryptography`, hashlib)
- tpu: JAX/XLA batched implementation (csp/tpu/)
"""

from fabric_tpu.csp.api import (
    CSP,
    Key,
    ECDSAP256PublicKey,
    ECDSAP256PrivateKey,
    VerifyBatchItem,
)
from fabric_tpu.csp.sw import SWCSP
from fabric_tpu.csp.idemix_provider import IdemixCSP, IdemixVerifyItem
from fabric_tpu.csp.factory import csp_from_config, get_default, init_factories
from fabric_tpu.csp.keystore import (
    DummyKeyStore,
    FileKeyStore,
    InMemoryKeyStore,
)

__all__ = [
    "CSP",
    "Key",
    "ECDSAP256PublicKey",
    "ECDSAP256PrivateKey",
    "VerifyBatchItem",
    "SWCSP",
    "IdemixCSP",
    "IdemixVerifyItem",
    "get_default",
    "init_factories",
    "csp_from_config",
    "InMemoryKeyStore",
    "FileKeyStore",
    "DummyKeyStore",
]
