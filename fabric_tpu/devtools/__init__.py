"""Developer-facing correctness tooling.

Two parts, both self-gated in tier-1 (tests/test_lint_clean.py):

- fabriclint (devtools/lint.py): an ast-based static pass enforcing the
  domain invariants reviewer memory cannot — crypto routed through the
  CSP seam, no silent exception swallows on validation paths, no
  nondeterminism where peers must agree, lock discipline on the commit
  path, no host syncs inside per-item device loops.

- lock-order watchdog (devtools/lockwatch.py): an instrumented lock
  wrapper recording the runtime acquisition-order graph across the
  commit lock, snapshot manager, and gossip locks; cycles raise under
  tests (FABRIC_TPU_LOCKWATCH=1, set by tests/conftest.py).
"""
