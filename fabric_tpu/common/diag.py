"""Diagnostics: thread dump on SIGUSR1 (reference common/diag/
goroutine.go:19-28 dumps goroutines; Python daemons dump thread stacks
to the log stream)."""

from __future__ import annotations

import signal
import sys
import threading
import traceback


def dump_threads(out=None) -> str:
    out = out or sys.stderr
    frames = sys._current_frames()
    lines = []
    for t in threading.enumerate():
        lines.append(f"--- thread {t.name} (daemon={t.daemon}) ---")
        frame = frames.get(t.ident)
        if frame is not None:
            lines.extend(
                line.rstrip()
                for line in traceback.format_stack(frame)
            )
    text = "\n".join(lines) + "\n"
    out.write(text)
    out.flush()
    return text


def install_signal_handler(sig=signal.SIGUSR1) -> None:
    """Register the dump on SIGUSR1 (reference internal/peer/node/
    signals.go wires the same signal)."""
    signal.signal(sig, lambda *_: dump_threads())


__all__ = ["dump_threads", "install_signal_handler"]
