"""Transaction management: simulation (rwset building) + MVCC validation.

Reference surface: core/ledger/kvledger/txmgmt —
  * rwsetutil: TxReadWriteSet build/parse (rwsetutil/rwset_builder.go)
  * validation: validateAndPrepareBatch / validateKVRead / validateRangeQuery
    (validation/validator.go:82-260)
  * lockbased_txmgr: the simulator handed to the endorser.

The MVCC pass itself is host work (string keys, variable shapes — not
device-friendly); the TPU win upstream is that by the time blocks reach
MVCC, all signature checks already ran as one batch.
"""

from __future__ import annotations

import dataclasses
import time

from fabric_tpu.common import workpool
from fabric_tpu.common.hashing import sha256 as _sha256
from fabric_tpu.devtools import faultline
from fabric_tpu.ledger.kvstore import shard_of_namespace, store_shards
from fabric_tpu.ledger.statedb import Height, VersionedDB, VersionedValue
from fabric_tpu.protos.ledger.rwset import rwset_pb2
from fabric_tpu.protos.ledger.rwset.kvrwset import kv_rwset_pb2
from fabric_tpu.protos.peer import transaction_pb2

VALID = transaction_pb2.VALID
MVCC_READ_CONFLICT = transaction_pb2.MVCC_READ_CONFLICT
PHANTOM_READ_CONFLICT = transaction_pb2.PHANTOM_READ_CONFLICT
BAD_RWSET = transaction_pb2.BAD_RWSET


# Private collections live in the same VersionedDB under derived namespaces
# (the reference keeps a composite public/hashed/private DB,
# core/ledger/kvledger/txmgmt/privacyenabledstate/db.go; we derive
# sub-namespaces instead — '\x00' can't appear in chaincode names).
def pvt_ns(ns: str, coll: str) -> str:
    return f"{ns}\x00pvt\x00{coll}"


def hash_ns(ns: str, coll: str) -> str:
    return f"{ns}\x00hash\x00{coll}"


def key_hash(key: str) -> bytes:
    return _sha256(key.encode())


def value_hash(value: bytes) -> bytes:
    return _sha256(value)


# State metadata is a named-entry map; the key-level endorsement policy
# lives under the entry name VALIDATION_PARAMETER (reference
# core/ledger/kvledger/txmgmt/statemetadata + pkg/statebased).
VALIDATION_PARAMETER = "VALIDATION_PARAMETER"


def encode_metadata(entries: dict[str, bytes]) -> bytes:
    from fabric_tpu.protos.peer import chaincode_shim_pb2 as _shim

    res = _shim.StateMetadataResult()
    for name in sorted(entries):
        res.entries.add(metakey=name, value=entries[name])
    return res.SerializeToString()


def decode_metadata(raw: bytes) -> dict[str, bytes]:
    from fabric_tpu.protos.peer import chaincode_shim_pb2 as _shim

    if not raw:
        return {}
    res = _shim.StateMetadataResult.FromString(raw)
    return {e.metakey: bytes(e.value) for e in res.entries}


def _version_proto(h: Height | None):
    if h is None:
        return None
    return kv_rwset_pb2.Version(block_num=h.block_num, tx_num=h.tx_num)


def _height_of(v: kv_rwset_pb2.Version | None) -> Height | None:
    if v is None:
        return None
    return Height(v.block_num, v.tx_num)


class TxSimulator:
    """Collects a read-write set while chaincode reads/writes state
    (reference TxSimulator, core/ledger/ledger_interface.go:270)."""

    def __init__(self, db: VersionedDB):
        self._db = db
        self._reads: dict[tuple[str, str], Height | None] = {}
        self._writes: dict[tuple[str, str], bytes | None] = {}
        self._range_queries: list[kv_rwset_pb2.RangeQueryInfo] = []
        # Private data (reference TxSimulator Get/Set/DeletePrivateData,
        # ledger_interface.go:270): reads are recorded against the *hashed*
        # key-space (what committers without the collection validate), and
        # writes split into a hashed write (public) + the cleartext write
        # (distributed separately via the transient store / gossip).
        self._pvt_reads: dict[tuple[str, str, str], Height | None] = {}
        self._pvt_writes: dict[tuple[str, str, str], bytes | None] = {}
        # Metadata writes: full-entry-map replacement per key (reference
        # SetStateMetadata semantics are per-entry; we merge at write time
        # against the committed map so the rwset carries the final map).
        self._meta_writes: dict[tuple[str, str], dict[str, bytes]] = {}
        self._pvt_meta_writes: dict[
            tuple[str, str, str], dict[str, bytes]
        ] = {}
        self._done = False

    def get_state(self, ns: str, key: str) -> bytes | None:
        if (ns, key) in self._writes:
            return self._writes[(ns, key)]
        vv = self._db.get_state(ns, key)
        self._reads.setdefault((ns, key), vv.version if vv else None)
        return vv.value if vv else None

    def set_state(self, ns: str, key: str, value: bytes) -> None:
        self._writes[(ns, key)] = value

    def delete_state(self, ns: str, key: str) -> None:
        self._writes[(ns, key)] = None

    def get_state_metadata(self, ns: str, key: str) -> dict[str, bytes]:
        """Committed metadata entries of a key (reference
        GetStateMetadata); records NO read — metadata is validated by the
        key-level validator, not MVCC."""
        if (ns, key) in self._meta_writes:
            return dict(self._meta_writes[(ns, key)])
        vv = self._db.get_state(ns, key)
        return decode_metadata(vv.metadata) if vv else {}

    def set_state_metadata(
        self, ns: str, key: str, entries: dict[str, bytes]
    ) -> None:
        """Merge entries into the key's metadata (reference
        SetStateMetadata is per-entry upsert)."""
        cur = self.get_state_metadata(ns, key)
        cur.update(entries)
        self._meta_writes[(ns, key)] = cur

    def delete_state_metadata(self, ns: str, key: str, name: str) -> None:
        cur = self.get_state_metadata(ns, key)
        cur.pop(name, None)
        self._meta_writes[(ns, key)] = cur

    def get_private_data_metadata(
        self, ns: str, coll: str, key: str
    ) -> dict[str, bytes]:
        if (ns, coll, key) in self._pvt_meta_writes:
            return dict(self._pvt_meta_writes[(ns, coll, key)])
        vv = self._db.get_state(hash_ns(ns, coll), key_hash(key).hex())
        return decode_metadata(vv.metadata) if vv else {}

    def set_private_data_metadata(
        self, ns: str, coll: str, key: str, entries: dict[str, bytes]
    ) -> None:
        cur = self.get_private_data_metadata(ns, coll, key)
        cur.update(entries)
        self._pvt_meta_writes[(ns, coll, key)] = cur

    def get_private_data(self, ns: str, coll: str, key: str) -> bytes | None:
        if (ns, coll, key) in self._pvt_writes:
            return self._pvt_writes[(ns, coll, key)]
        # The hashed key-space is keyed by hex(sha256(key)) — the version
        # recorded here is what committers outside the collection validate.
        hv = self._db.get_state(hash_ns(ns, coll), key_hash(key).hex())
        self._pvt_reads.setdefault(
            (ns, coll, key), hv.version if hv else None
        )
        vv = self._db.get_state(pvt_ns(ns, coll), key)
        return vv.value if vv else None

    def set_private_data(self, ns: str, coll: str, key: str, value: bytes):
        self._pvt_writes[(ns, coll, key)] = value

    def delete_private_data(self, ns: str, coll: str, key: str) -> None:
        self._pvt_writes[(ns, coll, key)] = None

    def get_private_data_hash(self, ns: str, coll: str, key: str):
        """Hash-only read: allowed even for peers outside the collection
        (reference GetPrivateDataHash); does NOT record a read."""
        vv = self._db.get_state(hash_ns(ns, coll), key_hash(key).hex())
        return vv.value if vv else None

    def get_private_data_range(self, ns: str, coll: str, start: str, end: str):
        """[(key, value)] over the private key-space.  Like the reference,
        private range queries record no phantom-protection info."""
        return [
            (key, vv.value)
            for key, vv in self._db.get_state_range(pvt_ns(ns, coll), start, end)
        ]

    def get_query_result(self, ns: str, query: str):
        """Rich JSON-selector query (reference GetQueryResult via the
        CouchDB backend).  Every RETURNED key is recorded in the read set
        for MVCC version checks (reference queryHelper adds each result
        to the rwset); only phantoms go unprotected, matching the
        reference's couchdb caveat."""
        from fabric_tpu.ledger.richquery import (
            execute_query,
            execute_query_indexed,
        )

        if hasattr(self._db, "indexes_for"):
            got = execute_query_indexed(self._db, ns, query)
            if got is not None:
                out = []
                for key, value, version in got:
                    self._reads.setdefault((ns, key), version)
                    out.append((key, value))
                return out

        versions = {}

        def pairs():
            for key, vv in self._db.get_state_range(ns, "", ""):
                versions[key] = vv.version
                yield key, vv.value

        out = execute_query(pairs(), query)
        for key, _ in out:
            self._reads.setdefault((ns, key), versions[key])
        return out

    def get_private_data_query_result(self, ns: str, coll: str, query: str):
        from fabric_tpu.ledger.richquery import execute_query

        pairs = (
            (key, vv.value)
            for key, vv in self._db.get_state_range(pvt_ns(ns, coll), "", "")
        )
        return execute_query(pairs, query)

    def get_state_range(self, ns: str, start: str, end: str):
        """Returns [(key, value)] and records the range query for phantom
        detection at validation time."""
        rqi = kv_rwset_pb2.RangeQueryInfo(start_key=start, end_key=end, itr_exhausted=True)
        out = []
        for key, vv in self._db.get_state_range(ns, start, end):
            rqi.raw_reads.kv_reads.append(
                kv_rwset_pb2.KVRead(key=key, version=_version_proto(vv.version))
            )
            out.append((key, vv.value))
        self._range_queries.append((ns, rqi))
        return out

    def _pvt_collection_rwsets(self) -> dict[str, dict[str, bytes]]:
        """{ns: {coll: serialized private KVRWSet}} for namespaces with
        private writes."""
        per_coll: dict[tuple[str, str], kv_rwset_pb2.KVRWSet] = {}
        for (ns, coll, key), value in sorted(self._pvt_writes.items()):
            per_coll.setdefault((ns, coll), kv_rwset_pb2.KVRWSet()).writes.append(
                kv_rwset_pb2.KVWrite(
                    key=key, is_delete=value is None, value=value or b""
                )
            )
        out: dict[str, dict[str, bytes]] = {}
        for (ns, coll), kvrw in per_coll.items():
            out.setdefault(ns, {})[coll] = kvrw.SerializeToString()
        return out

    def get_tx_simulation_results(self) -> bytes:
        """Marshaled rwset.TxReadWriteSet: public reads/writes plus, per
        collection touched, the hashed rwset + hash of the private rwset
        (reference rwsetutil/rwset_builder.go GetTxSimulationResults)."""
        self._done = True
        by_ns: dict[str, kv_rwset_pb2.KVRWSet] = {}

        def ns_set(ns: str) -> kv_rwset_pb2.KVRWSet:
            return by_ns.setdefault(ns, kv_rwset_pb2.KVRWSet())

        for (ns, key), ver in sorted(self._reads.items()):
            ns_set(ns).reads.append(
                kv_rwset_pb2.KVRead(key=key, version=_version_proto(ver))
            )
        for item in self._range_queries:
            ns, rqi = item
            ns_set(ns).range_queries_info.append(rqi)
        for (ns, key), value in sorted(self._writes.items()):
            ns_set(ns).writes.append(
                kv_rwset_pb2.KVWrite(
                    key=key, is_delete=value is None, value=value or b""
                )
            )
        for (ns, key), entries in sorted(self._meta_writes.items()):
            mw = kv_rwset_pb2.KVMetadataWrite(key=key)
            for name in sorted(entries):
                mw.entries.add(name=name, value=entries[name])
            ns_set(ns).metadata_writes.append(mw)

        # Hashed r/w sets per (ns, collection).
        hashed: dict[tuple[str, str], kv_rwset_pb2.HashedRWSet] = {}

        def coll_set(ns: str, coll: str) -> kv_rwset_pb2.HashedRWSet:
            return hashed.setdefault((ns, coll), kv_rwset_pb2.HashedRWSet())

        for (ns, coll, key), ver in sorted(self._pvt_reads.items()):
            coll_set(ns, coll).hashed_reads.append(
                kv_rwset_pb2.KVReadHash(
                    key_hash=key_hash(key), version=_version_proto(ver)
                )
            )
        for (ns, coll, key), value in sorted(self._pvt_writes.items()):
            coll_set(ns, coll).hashed_writes.append(
                kv_rwset_pb2.KVWriteHash(
                    key_hash=key_hash(key),
                    is_delete=value is None,
                    value_hash=value_hash(value) if value is not None else b"",
                )
            )
        for (ns, coll, key), entries in sorted(
            self._pvt_meta_writes.items()
        ):
            mw = kv_rwset_pb2.KVMetadataWriteHash(key_hash=key_hash(key))
            for name in sorted(entries):
                mw.entries.add(name=name, value=entries[name])
            coll_set(ns, coll).metadata_writes.append(mw)

        pvt = self._pvt_collection_rwsets()
        namespaces = sorted(
            set(by_ns) | {ns for ns, _ in hashed}
        )
        txrw = rwset_pb2.TxReadWriteSet(data_model=rwset_pb2.TxReadWriteSet.KV)
        for ns in namespaces:
            nsrw = rwset_pb2.NsReadWriteSet(
                namespace=ns,
                rwset=by_ns.get(ns, kv_rwset_pb2.KVRWSet()).SerializeToString(),
            )
            for (hns, coll), hrw in sorted(hashed.items()):
                if hns != ns:
                    continue
                pvt_bytes = pvt.get(ns, {}).get(coll)
                nsrw.collection_hashed_rwset.append(
                    rwset_pb2.CollectionHashedReadWriteSet(
                        collection_name=coll,
                        hashed_rwset=hrw.SerializeToString(),
                        pvt_rwset_hash=(
                            _sha256(pvt_bytes)
                            if pvt_bytes is not None
                            else b""
                        ),
                    )
                )
            txrw.ns_rwset.append(nsrw)
        return txrw.SerializeToString()

    def get_pvt_simulation_results(self) -> bytes | None:
        """Marshaled rwset.TxPvtReadWriteSet with the cleartext private
        writes, or None if the tx touched no collections.  Never embedded
        in the transaction — distributed via transient store + gossip."""
        pvt = self._pvt_collection_rwsets()
        if not pvt:
            return None
        txpvt = rwset_pb2.TxPvtReadWriteSet(
            data_model=rwset_pb2.TxReadWriteSet.KV
        )
        for ns in sorted(pvt):
            nsp = rwset_pb2.NsPvtReadWriteSet(namespace=ns)
            for coll in sorted(pvt[ns]):
                nsp.collection_pvt_rwset.append(
                    rwset_pb2.CollectionPvtReadWriteSet(
                        collection_name=coll, rwset=pvt[ns][coll]
                    )
                )
            txpvt.ns_pvt_rwset.append(nsp)
        return txpvt.SerializeToString()


@dataclasses.dataclass
class _TxUpdates:
    writes: dict[tuple[str, str], bytes | None]


# a block below this many write operations prepares serially even when
# a fan-out width is configured — chunking overhead would dominate
_PARALLEL_MIN_WRITES = 32


class MVCCValidator:
    """Block-level MVCC validation building the state update batch
    (reference validation/validator.go:82 validateAndPrepareBatch).

    Structured as two passes so the write-set prepare can fan out:

    1. **check** (always serial, commit order): read/range/hashed-read
       conflict detection and the in-block version bookkeeping
       (``updated_versions``) — the pass whose outputs feed later txs'
       conflict checks, so it is inherently ordered.
    2. **prepare** (parallelizable per top-level namespace): building
       the ``{ns: {key: VersionedValue|None}}`` batch, including
       metadata retention and cleartext-private application.  Namespaces
       are disjoint batch keys (derived hash/pvt namespaces embed their
       parent), so per-namespace workers never share output, and the
       merge re-assembles the batch in the exact first-encounter
       namespace order the serial loop would have produced — flags and
       batch contents are byte-identical to serial at every fan-out
       width (pinned by tests/test_parallel_commit.py).

    `fanout` chunks the namespace groups across `pool` (default: the
    process workpool); None reads FABRIC_TPU_MVCC_POOL, 0 keeps prepare
    serial.  The bulk version preload fans out per namespace under the
    same width."""

    def __init__(self, db: VersionedDB, pool=None, fanout: int | None = None):
        self._db = db
        self._pool = pool
        if fanout is None:
            fanout = workpool.stage_width("FABRIC_TPU_MVCC_POOL")
        self._fanout = max(0, fanout)
        # per-call stage wall seconds {preload, check, prepare} — the
        # ledger folds these into commit_stage_seconds/os /metrics as
        # mvcc_preload/mvcc_check/mvcc_prepare
        self.last_stage_seconds: dict[str, float] = {}
        # blocks whose prepare actually fanned out (smoke-test probe)
        self.parallel_prepare_blocks = 0

    def _committed_version(
        self, ns: str, key: str, updates: dict, cache: dict | None = None
    ) -> Height | None:
        if (ns, key) in updates:
            return updates[(ns, key)]
        if cache is not None and (ns, key) in cache:
            vv = cache[(ns, key)]
            return None if vv is None else vv.version
        return self._db.get_version(ns, key)

    def _preload(self, parsed_per_tx: list) -> dict:
        """Bulk-load the block's whole point read/version set — every
        read key, hashed read, and (only in namespaces that may carry
        metadata) every write key, whose committed metadata a value-only
        write must retain — in ONE get_state_many round-trip instead of
        a store probe per key (the reference pays a leveldb get per
        read, validator.go validateKVRead).  Range queries are not
        preloaded; they fall back to scans.  The result maps every
        harvested (ns, key) to VersionedValue | None, so a cache entry
        of None means known-absent, not not-probed."""
        keys: list[tuple[str, str]] = []
        may_meta: dict[str, bool] = {}

        def meta(ns: str) -> bool:
            # _existing_metadata short-circuits on may_have_metadata,
            # so metadata-free namespaces (the common case) need no
            # write-key preload at all
            got = may_meta.get(ns)
            if got is None:
                got = may_meta[ns] = self._db.may_have_metadata(ns)
            return got

        for parsed in parsed_per_tx:
            if not parsed:
                continue
            for ns, kvrw, colls in parsed:
                keys.extend((ns, r.key) for r in kvrw.reads)
                if meta(ns):
                    keys.extend((ns, w.key) for w in kvrw.writes)
                    keys.extend(
                        (ns, mw.key) for mw in kvrw.metadata_writes
                    )
                for coll, hrw, _ in colls:
                    hns = hash_ns(ns, coll)
                    keys.extend(
                        (hns, bytes(hr.key_hash).hex())
                        for hr in hrw.hashed_reads
                    )
                    if meta(hns):
                        keys.extend(
                            (hns, bytes(hw.key_hash).hex())
                            for hw in hrw.hashed_writes
                        )
                        keys.extend(
                            (hns, bytes(mw.key_hash).hex())
                            for mw in hrw.metadata_writes
                        )
        if not keys:
            return {}
        width = self._fanout
        if width > 1 and len(keys) >= 2 * _PARALLEL_MIN_WRITES:
            by_ns: dict[str, list] = {}
            for pair in keys:
                by_ns.setdefault(pair[0], []).append(pair)
            if len(by_ns) >= 2:
                # per-namespace version preload: each group is one
                # get_state_many round-trip; the merged cache is the
                # same mapping the single round-trip would produce
                # (namespace is part of every key, so groups are
                # disjoint)
                def _load(off, chunk):
                    out = []
                    for pairs in chunk:
                        faultline.point(
                            "mvcc.ns_prepare", stage="preload",
                            ns=pairs[0][0],
                        )
                        out.append(self._db.get_state_many(pairs))
                    return out

                maps = workpool.run_chunked(
                    self._pool or workpool.default_pool(),
                    _load, list(by_ns.values()),
                    min(width, len(by_ns)),
                )
                merged: dict = {}
                for m in maps:
                    merged.update(m)
                return merged
        return self._db.get_state_many(keys)

    def validate_and_prepare(
        self,
        block_num: int,
        rwsets: list[bytes | None],
        flags: list[int],
        pvt_data: dict[int, bytes] | None = None,
        footprints: list | None = None,
    ) -> dict:
        """rwsets[i]: marshaled TxReadWriteSet of tx i (None = not an
        endorser tx or already invalid).  Mutates `flags` with MVCC codes;
        returns the state update batch {ns: {key: VersionedValue|None}}.

        pvt_data maps tx_num -> marshaled TxPvtReadWriteSet for txs whose
        cleartext private writes this peer holds; cleartext writes apply
        only when their hash matches the endorsed pvt_rwset_hash (reference
        coordinator verifies hashes before commit,
        gossip/privdata/coordinator.go).

        footprints[i], when given, is the validator's RwsetFootprint for
        tx i: its `.parsed` [(ns, KVRWSet, [(coll, HashedRWSet, hash)])]
        is exactly this method's decode, so the rwset wire format is
        walked once per tx per lifecycle instead of once per stage (the
        reference re-unmarshals in validateAndPrepareBatch,
        validation/validator.go:82).

        Matches the reference's serial-in-commit-order semantics: a tx sees
        conflicts against committed state AND the writes of earlier valid
        txs in the same block."""
        pvt_data = pvt_data or {}
        # decode pass: adopt the validator's footprints or unmarshal
        # once per tx, so the whole block's read set can be harvested
        # for ONE bulk version preload before any validation runs
        parsed_per_tx: list = [None] * len(rwsets)
        for tx_num, raw in enumerate(rwsets):
            if flags[tx_num] != VALID or raw is None:
                continue
            fp = footprints[tx_num] if footprints is not None else None
            if fp is not None:
                parsed_per_tx[tx_num] = fp.parsed
                continue
            try:
                txrw = rwset_pb2.TxReadWriteSet.FromString(raw)
                parsed_per_tx[tx_num] = [
                    (
                        nsrw.namespace,
                        kv_rwset_pb2.KVRWSet.FromString(nsrw.rwset),
                        [
                            (
                                ch.collection_name,
                                kv_rwset_pb2.HashedRWSet.FromString(
                                    ch.hashed_rwset
                                ),
                                bytes(ch.pvt_rwset_hash),
                            )
                            for ch in nsrw.collection_hashed_rwset
                        ],
                    )
                    for nsrw in txrw.ns_rwset
                ]
            except Exception:
                flags[tx_num] = BAD_RWSET
        t = time.perf_counter
        t0 = t()
        cache = self._preload(parsed_per_tx)
        t1 = t()

        # -- pass 1: serial conflict checks + version bookkeeping -------
        # updated_versions carries every in-block write's version (None
        # for deletes) — the state later txs' conflict checks read —
        # and doubles as the "was this key written earlier in the
        # block" oracle the metadata-write bookkeeping needs.  Work for
        # pass 2 is grouped by TOP-LEVEL namespace (derived hash/pvt
        # namespaces ride with their parent), and ns_order records the
        # exact batch-key first-encounter order of the serial loop.
        updated_versions: dict[tuple[str, str], Height] = {}
        ns_order: list[str] = []
        ns_owner: dict[str, str] = {}
        groupwork: dict[str, list] = {}
        all_items: list = []  # every group item in global (tx, entry)
        # order — the collision fallback's single serial group
        collided = [False]
        n_writes = 0

        def order(ns: str, owner: str) -> None:
            # `owner` is the TOP-LEVEL group key (the parsed entry's
            # namespace) recorded explicitly — never re-derived from the
            # namespace string, because an adversarial rwset may name a
            # top-level namespace that itself contains the \x00 the
            # derived hash/pvt encodings use.  If two different groups
            # ever claim one output namespace (a literal namespace
            # colliding with another namespace's derived hash/pvt
            # encoding — only constructible by an adversarial rwset),
            # the groups are NOT disjoint and pass 2 falls back to one
            # serial group over all items, which reproduces the old
            # single-batch-dict semantics exactly.
            if ns not in ns_owner:
                ns_owner[ns] = owner
                ns_order.append(ns)
            elif ns_owner[ns] != owner:
                collided[0] = True

        for tx_num, parsed in enumerate(parsed_per_tx):
            if parsed is None or flags[tx_num] != VALID:
                continue
            code = VALID
            for ns, kvrw, colls in parsed:
                for read in kvrw.reads:
                    want = _height_of(read.version) if read.HasField("version") else None
                    have = self._committed_version(
                        ns, read.key, updated_versions, cache
                    )
                    if want != have:
                        code = MVCC_READ_CONFLICT
                        break
                if code != VALID:
                    break
                for rqi in kvrw.range_queries_info:
                    if not self._validate_range_query(ns, rqi, updated_versions):
                        code = PHANTOM_READ_CONFLICT
                        break
                if code != VALID:
                    break
                for coll, hrw, _ in colls:
                    hns = hash_ns(ns, coll)
                    for hread in hrw.hashed_reads:
                        want = (
                            _height_of(hread.version)
                            if hread.HasField("version")
                            else None
                        )
                        have = self._committed_version(
                            hns, bytes(hread.key_hash).hex(),
                            updated_versions, cache,
                        )
                        if want != have:
                            code = MVCC_READ_CONFLICT
                            break
                    if code != VALID:
                        break
                if code != VALID:
                    break
            flags[tx_num] = code
            if code != VALID:
                continue
            h = Height(block_num, tx_num)
            pvt_by_coll = self._parse_pvt(pvt_data.get(tx_num))
            # cleartext authenticity is decided HERE, once: only
            # collections whose supplied cleartext hashes to the
            # endorsed pvt_rwset_hash survive into pvt_ok — pass 2
            # applies them without re-hashing, forged/absent supplies
            # are treated as missing (an empty endorsed hash means NO
            # cleartext was endorsed, so any supply is forged)
            pvt_ok: dict = {}
            for ns, kvrw, colls in parsed:
                order(ns, ns)
                item = (h, ns, kvrw, colls, pvt_ok)
                groupwork.setdefault(ns, []).append(item)
                all_items.append(item)
                for w in kvrw.writes:
                    n_writes += 1
                    updated_versions[(ns, w.key)] = (
                        None if w.is_delete else h  # type: ignore[assignment]
                    )
                for mw in kvrw.metadata_writes:
                    n_writes += 1
                    self._meta_write_version(
                        ns, mw.key, h, updated_versions, cache
                    )
                for coll, hrw, expected_hash in colls:
                    hns = hash_ns(ns, coll)
                    order(hns, ns)
                    for hw in hrw.hashed_writes:
                        n_writes += 1
                        updated_versions[(hns, bytes(hw.key_hash).hex())] = (
                            None if hw.is_delete else h  # type: ignore[assignment]
                        )
                    for mw in hrw.metadata_writes:
                        n_writes += 1
                        self._meta_write_version(
                            hns, bytes(mw.key_hash).hex(), h,
                            updated_versions, cache,
                        )
                    clear = pvt_by_coll.get((ns, coll))
                    if clear is not None and expected_hash and \
                            _sha256(clear[0]) == expected_hash:
                        pvt_ok[(ns, coll)] = clear
                        order(pvt_ns(ns, coll), ns)
        t2 = t()

        # -- pass 2: write-set prepare, fanned out per namespace --------
        if collided[0]:
            # non-disjoint groups (see order()): one serial group over
            # all items in global order — the old single-dict semantics
            groups = [("", all_items)]
        else:
            groups = [(ns, items) for ns, items in groupwork.items()]
        width = self._fanout
        if (
            width > 1 and len(groups) >= 2
            and n_writes >= _PARALLEL_MIN_WRITES
        ):
            # warm the metadata-namespace cache once on this thread so
            # pool workers only ever read it
            self._db.may_have_metadata("")
            width = min(width, len(groups))
            self.parallel_prepare_blocks += 1
        else:
            width = 0
        pool = None
        if width:
            pool = self._pool or workpool.default_pool()

        def _prep(off, chunk, _cache=cache):
            return self._prepare_groups(chunk, _cache)

        maps = workpool.run_chunked(pool, _prep, groups, width or 1)
        batch: dict[str, dict[str, VersionedValue | None]] = {}
        if collided[0]:
            single = maps[0]
            for ns in ns_order:
                batch[ns] = single.get(ns, {})
        else:
            # each namespace (top-level or derived) resolves to the
            # group pass 1 recorded as its owner
            by_group = {
                gns: m for (gns, _items), m in zip(groups, maps)
            }
            for ns in ns_order:
                batch[ns] = by_group[ns_owner[ns]].get(ns, {})
        self.last_stage_seconds = {
            "preload": t1 - t0, "check": t2 - t1, "prepare": t() - t2,
        }
        return batch

    def _prepare_groups(self, groups: list, cache: dict) -> list[dict]:
        """Pass-2 worker: build the batch dicts for a chunk of namespace
        groups.  Each group's items arrive in commit order, outputs are
        keyed by exact namespace strings (parent + derived), and no two
        groups share an output key — so any interleaving of workers
        merges to the same batch."""
        out = []
        for ns_top, items in groups:
            faultline.point(
                "mvcc.ns_prepare", stage="prepare", ns=ns_top,
                txs=len(items),
                # the statedb shard this namespace group's writes will
                # route to under the current FABRIC_TPU_STORE_SHARDS —
                # lets chaos plans and profiles line the MVCC partition
                # up with the storage partition it feeds
                shard=shard_of_namespace(ns_top, store_shards()),
            )
            m: dict[str, dict] = {}
            for h, ns, kvrw, colls, pvt_by_coll in items:
                self._build_ns_writes(
                    ns, kvrw, colls, h, pvt_by_coll, m, cache
                )
            out.append(m)
        return out

    def _build_ns_writes(self, ns, kvrw, colls, h, pvt_by_coll, out,
                         cache) -> None:
        """Apply one tx's writes for one parsed namespace entry into the
        per-group batch maps — the exact write-application the serial
        loop performed, minus the version bookkeeping pass 1 already
        did."""
        ns_batch = out.setdefault(ns, {})
        for w in kvrw.writes:
            if w.is_delete:
                ns_batch[w.key] = None
            else:
                # A value-only write RETAINS existing metadata
                # (key-level endorsement policies survive plain
                # puts — reference tx_ops metadata merge).
                ns_batch[w.key] = VersionedValue(
                    w.value, h,
                    self._existing_metadata(ns, w.key, ns_batch, cache),
                )
        for mw in kvrw.metadata_writes:
            self._apply_metadata_write(
                ns, mw.key,
                {e.name: bytes(e.value) for e in mw.entries},
                ns_batch, h, cache,
            )
        for coll, hrw, expected_hash in colls:
            hns = hash_ns(ns, coll)
            h_batch = out.setdefault(hns, {})
            for hw in hrw.hashed_writes:
                hkey = bytes(hw.key_hash).hex()
                if hw.is_delete:
                    h_batch[hkey] = None
                else:
                    h_batch[hkey] = VersionedValue(
                        bytes(hw.value_hash), h,
                        self._existing_metadata(hns, hkey, h_batch, cache),
                    )
            for mw in hrw.metadata_writes:
                self._apply_metadata_write(
                    hns, bytes(mw.key_hash).hex(),
                    {e.name: bytes(e.value) for e in mw.entries},
                    h_batch, h, cache,
                )
            # Cleartext private writes: pvt_by_coll is pass 1's
            # ALREADY-AUTHENTICATED map (only entries whose cleartext
            # hashed to the endorsed pvt_rwset_hash survive), so the
            # worker applies without re-hashing; forged/absent supplies
            # were dropped there.
            clear = pvt_by_coll.get((ns, coll))
            if clear is None:
                continue
            _raw_kvrw, clear_kvrw = clear
            p_batch = out.setdefault(pvt_ns(ns, coll), {})
            for w in clear_kvrw.writes:
                if w.is_delete:
                    p_batch[w.key] = None
                else:
                    p_batch[w.key] = VersionedValue(w.value, h)

    def _meta_write_version(self, ns, key, h, updated_versions, cache) -> None:
        """Pass-1 version bookkeeping of a metadata write: it bumps the
        key's version only when the key EXISTS (earlier in-block write
        that was not a delete, else committed state) — mirroring
        _apply_metadata_write's early returns."""
        if (ns, key) in updated_versions:
            if updated_versions[(ns, key)] is None:
                return  # deleted earlier in the block: metadata no-op
        else:
            if cache is not None and (ns, key) in cache:
                vv = cache[(ns, key)]
            else:
                vv = self._db.get_state(ns, key)
                if cache is not None:
                    # stash so the pass-2 worker's _apply_metadata_write
                    # hits the cache instead of re-probing the store
                    cache[(ns, key)] = vv
            if vv is None:
                return  # key absent: metadata write is a no-op
        updated_versions[(ns, key)] = h

    def _existing_metadata(
        self, ns: str, key: str, ns_batch: dict, cache: dict | None = None
    ) -> bytes:
        """Current metadata of a key: in-block overlay first, then
        committed state (preload cache before a point probe); empty for
        new/deleted keys."""
        if key in ns_batch:
            base = ns_batch[key]
            return base.metadata if base is not None else b""
        if not self._db.may_have_metadata(ns):
            return b""  # namespace never stored metadata: skip the store
        if cache is not None and (ns, key) in cache:
            vv = cache[(ns, key)]
        else:
            vv = self._db.get_state(ns, key)
        return vv.metadata if vv is not None else b""

    def _apply_metadata_write(
        self, ns: str, key: str, entries: dict[str, bytes],
        ns_batch: dict, h: Height, cache: dict | None = None,
    ) -> None:
        """Replace a key's metadata map, keeping its value; a metadata
        write on a non-existent/deleted key is a no-op (reference
        statemetadata semantics).  Version bookkeeping lives in pass 1
        (_meta_write_version) — this is pure batch construction."""
        if key in ns_batch:
            base = ns_batch[key]
            if base is None:
                return
            ns_batch[key] = VersionedValue(base.value, h, encode_metadata(entries))
        else:
            if cache is not None and (ns, key) in cache:
                vv = cache[(ns, key)]
            else:
                vv = self._db.get_state(ns, key)
            if vv is None:
                return
            ns_batch[key] = VersionedValue(vv.value, h, encode_metadata(entries))

    @staticmethod
    def _parse_pvt(raw: bytes | None):
        """{(ns, coll): (raw_kvrwset_bytes, parsed KVRWSet)}"""
        out: dict[tuple[str, str], tuple[bytes, kv_rwset_pb2.KVRWSet]] = {}
        if not raw:
            return out
        try:
            txpvt = rwset_pb2.TxPvtReadWriteSet.FromString(raw)
            for nsp in txpvt.ns_pvt_rwset:
                for cp in nsp.collection_pvt_rwset:
                    out[(nsp.namespace, cp.collection_name)] = (
                        bytes(cp.rwset),
                        kv_rwset_pb2.KVRWSet.FromString(cp.rwset),
                    )
        except Exception:
            # fabriclint: allow[exception-discipline] unparsable supplied pvt
            # cleartext contributes no writes; the hashed-namespace comparison
            # independently flags the gap as missing data
            return {}
        return out

    def _validate_range_query(self, ns: str, rqi, updated_versions) -> bool:
        """Re-scan and compare against recorded raw reads (reference
        validateRangeQuery; the Merkle-summary variant is not implemented —
        simulators here always record raw reads)."""
        if rqi.WhichOneof("reads_info") == "reads_merkle_hashes":
            return False
        current: list[tuple[str, Height | None]] = []
        seen = set()
        for key, vv in self._db.get_state_range(ns, rqi.start_key, rqi.end_key):
            ver = updated_versions.get((ns, key), vv.version)
            if ver is not None:
                current.append((key, ver))
                seen.add(key)
        # keys created by earlier txs in this block inside the range are
        # phantoms too
        for (uns, ukey), uver in updated_versions.items():
            if uns != ns or ukey in seen or uver is None:
                continue
            if rqi.start_key <= ukey and (not rqi.end_key or ukey < rqi.end_key):
                current.append((ukey, uver))
        current.sort()
        recorded = [
            (r.key, _height_of(r.version) if r.HasField("version") else None)
            for r in rqi.raw_reads.kv_reads
        ]
        return current == recorded


__all__ = [
    "TxSimulator",
    "MVCCValidator",
    "VALID",
    "MVCC_READ_CONFLICT",
    "PHANTOM_READ_CONFLICT",
    "BAD_RWSET",
    "pvt_ns",
    "hash_ns",
    "key_hash",
    "value_hash",
    "VALIDATION_PARAMETER",
    "encode_metadata",
    "decode_metadata",
]
