"""E2E ACL enforcement at the peer's API entries (reference
core/aclmgmt wired per core/endorser/endorser.go:286,
core/scc/qscc/query.go:112, core/peer/deliverevents.go:258-281,
internal/peer/node/start.go:945): a VALIDLY-SIGNED client whose
identity does not satisfy a resource's policy must be rejected at that
resource — and only there.  The channel config's ACLs value overrides
the default resource policies per channel."""

import pytest

from fabric_tpu.common import configtx_builder as ctx
from fabric_tpu.common.deliver import make_seek_info_envelope
from fabric_tpu.msp import msp_config_from_ca
from fabric_tpu.node.peer_node import PeerNode
from fabric_tpu.peer import aclmgmt
from fabric_tpu.peer.endorser import ACLDeniedError
from fabric_tpu.protos.common import common_pb2
from fabric_tpu.protos.orderer import ab_pb2
from fabric_tpu.protos.peer import proposal_pb2
from fabric_tpu import protoutil

from orgfix import make_org

ADMINS = "/Channel/Application/Admins"


def kvcc(sim, args):
    if args[0] == b"put":
        sim.set_state("kvcc", args[1].decode(), args[2])
        return 200, "", b""
    return 500, "bad op", b""


@pytest.fixture(scope="module")
def net(tmp_path_factory):
    org = make_org("Org1MSP")
    oorg = make_org("OrdererMSP")
    app = ctx.application_group(
        {"Org1": ctx.org_group("Org1MSP", msp_config_from_ca(org.ca, "Org1MSP"))},
        acls={
            aclmgmt.PEER_PROPOSE: ADMINS,
            aclmgmt.QSCC_GET_CHAIN_INFO: ADMINS,
            aclmgmt.EVENT_BLOCK: ADMINS,
            # event/FilteredBlock left at its default (Readers): the
            # same client must be allowed there and denied on the two
            # overridden resources
        },
    )
    ordg = ctx.orderer_group(
        {"OrdererOrg": ctx.org_group("OrdererMSP", msp_config_from_ca(oorg.ca, "OrdererMSP"))},
        consensus_type="solo",
    )
    genesis = ctx.genesis_block("aclch", ctx.channel_group(app, ordg))
    node = PeerNode(None, org.csp, org.signer("peer0", role_ou="peer"),
                    chaincodes={"kvcc": kvcc})
    node.join_channel(genesis)
    node.start()
    yield org, node
    node.stop()


def _signed_proposal(client, channel, cc, args):
    prop, _ = protoutil.create_chaincode_proposal(
        client.serialize(), channel, cc, args
    )
    return proposal_pb2.SignedProposal(
        proposal_bytes=prop.SerializeToString(),
        signature=client.sign(prop.SerializeToString()),
    )


def test_propose_acl_denies_non_admin(net):
    org, node = net
    member = org.signer("client-member", role_ou="client")
    admin = org.signer("client-admin", role_ou="admin")
    ch = node.channels["aclch"]
    sp = _signed_proposal(member, "aclch", "kvcc", [b"put", b"k", b"v"])
    with pytest.raises(ACLDeniedError, match="peer/Propose"):
        ch.endorser.process_proposal(sp)
    sp = _signed_proposal(admin, "aclch", "kvcc", [b"put", b"k", b"v"])
    resp = ch.endorser.process_proposal(sp)
    assert resp.response.status == 200


def test_qscc_function_acl(net):
    org, node = net
    member = org.signer("q-member", role_ou="client")
    admin = org.signer("q-admin", role_ou="admin")
    ch = node.channels["aclch"]
    sp = _signed_proposal(member, "aclch", "qscc", [b"GetChainInfo", b"aclch"])
    with pytest.raises(ACLDeniedError, match="qscc/GetChainInfo"):
        ch.endorser.process_proposal(sp)
    sp = _signed_proposal(admin, "aclch", "qscc", [b"GetChainInfo", b"aclch"])
    assert ch.endorser.process_proposal(sp).response.status == 200
    # an UN-overridden qscc resource keeps its default (Readers): the
    # member passes there — denial was per-resource, not per-identity
    sp = _signed_proposal(
        member, "aclch", "qscc", [b"GetBlockByNumber", b"aclch", b"0"]
    )
    assert ch.endorser.process_proposal(sp).response.status == 200


def test_uncataloged_scc_function_fails_closed(net):
    """ADVICE r5 regression: a system-chaincode function with no ACL
    catalog entry is DENIED at the endorser — even for an admin — not
    silently exempted from the check."""
    org, node = net
    admin = org.signer("fc-admin", role_ou="admin")
    ch = node.channels["aclch"]
    sp = _signed_proposal(admin, "aclch", "qscc", [b"NotInTheCatalog"])
    with pytest.raises(ACLDeniedError, match="no ACL catalog entry"):
        ch.endorser.process_proposal(sp)


def test_lscc_deploy_covered_by_propose(net):
    """lscc deploy/upgrade ride the peer/Propose gate (reference
    defaultaclprovider.go:69-70 'ACL check covered by PROPOSAL'), so
    the Admins override denies a member there too — while an lscc
    query with its default Readers policy still admits the member
    (the ACL fires before simulation, so a 404-ish chaincode result
    is fine; a DENIAL would raise instead)."""
    org, node = net
    member = org.signer("l-member", role_ou="client")
    ch = node.channels["aclch"]
    sp = _signed_proposal(member, "aclch", "lscc", [b"deploy", b"aclch", b"x"])
    with pytest.raises(ACLDeniedError, match="peer/Propose"):
        ch.endorser.process_proposal(sp)
    sp = _signed_proposal(member, "aclch", "lscc", [b"getccdata", b"aclch", b"x"])
    resp = ch.endorser.process_proposal(sp)
    assert resp.response.status != 200  # served (not found), not denied


def test_deliver_block_vs_filtered_acl(net):
    org, node = net
    member = org.signer("d-member", role_ou="client")
    env = make_seek_info_envelope(
        "aclch", 0, 0, signer=member,
        behavior=ab_pb2.SeekInfo.FAIL_IF_NOT_READY,
    )
    events = list(node.deliver.deliver(env))
    assert events == [("status", common_pb2.FORBIDDEN)]
    # the filtered stream's default (Readers) still admits the member
    events = list(node.deliver_filtered_svc.deliver(env))
    kinds = [k for k, _ in events]
    assert kinds == ["block", "status"]
    assert events[-1] == ("status", common_pb2.SUCCESS)
    # an admin satisfies the override on the full-block stream
    admin = org.signer("d-admin", role_ou="admin")
    env = make_seek_info_envelope(
        "aclch", 0, 0, signer=admin,
        behavior=ab_pb2.SeekInfo.FAIL_IF_NOT_READY,
    )
    events = list(node.deliver.deliver(env))
    assert [k for k, _ in events] == ["block", "status"]


def test_propose_acl_denied_over_rpc(net):
    """The denial must surface over the REAL wire too: a member's
    proposal through the peer's RPC endpoint gets an error naming the
    resource; the admin's succeeds — same transport, same channel."""
    from fabric_tpu.cmd.common import endorse

    org, node = net
    member = org.signer("rpc-member", role_ou="client")
    admin = org.signer("rpc-admin", role_ou="admin")
    with pytest.raises(Exception, match="peer/Propose"):
        endorse([node.addr], member, "aclch", "kvcc", [b"put", b"k", b"v"])
    _, resps = endorse([node.addr], admin, "aclch", "kvcc", [b"put", b"k", b"v"])
    assert resps[0].response.status == 200


def test_discovery_acl_rejects_foreign_identity(net):
    org, node = net
    from fabric_tpu.discovery import DiscoveryClient
    from fabric_tpu.protos.discovery import protocol_pb2 as dpb

    def send(signed: dpb.SignedRequest) -> dpb.Response:
        return dpb.Response.FromString(
            node._discovery(signed.SerializeToString(), None)
        )

    member = org.signer("disc-member", role_ou="client")
    resp = DiscoveryClient(member, send).peers("aclch")
    assert resp  # membership query served

    outsider = make_org("EvilMSP").signer("mallory", role_ou="client")
    with pytest.raises(Exception, match="access denied"):
        DiscoveryClient(outsider, send).peers("aclch")


def test_default_acls_admit_members():
    """Without overrides every defaulted resource behaves as before:
    a plain member can propose (Writers) and read blocks (Readers)."""
    org = make_org("Org1MSP")
    oorg = make_org("OrdererMSP")
    app = ctx.application_group(
        {"Org1": ctx.org_group("Org1MSP", msp_config_from_ca(org.ca, "Org1MSP"))}
    )
    ordg = ctx.orderer_group(
        {"OrdererOrg": ctx.org_group("OrdererMSP", msp_config_from_ca(oorg.ca, "OrdererMSP"))},
        consensus_type="solo",
    )
    genesis = ctx.genesis_block("defch", ctx.channel_group(app, ordg))
    node = PeerNode(None, org.csp, org.signer("peer0", role_ou="peer"),
                    chaincodes={"kvcc": kvcc})
    node.join_channel(genesis)
    node.start()
    try:
        member = org.signer("m", role_ou="client")
        ch = node.channels["defch"]
        sp = _signed_proposal(member, "defch", "kvcc", [b"put", b"k", b"v"])
        assert ch.endorser.process_proposal(sp).response.status == 200
        env = make_seek_info_envelope(
            "defch", 0, 0, signer=member,
            behavior=ab_pb2.SeekInfo.FAIL_IF_NOT_READY,
        )
        assert [k for k, _ in node.deliver.deliver(env)] == ["block", "status"]
    finally:
        node.stop()
