"""Discovery service tests (reference discovery/service_test.go +
discovery/endorsement/endorsement_test.go coverage model): config and
membership queries, endorsement layouts against live membership,
collection filtering, auth denial."""

import pytest

from fabric_tpu.common import configtx_builder as ctx
from fabric_tpu.discovery import (
    DiscoveryClient,
    DiscoveryService,
    PeerInfo,
    satisfaction_sets,
)
from fabric_tpu.discovery.client import select_endorsers
from fabric_tpu.discovery.service import DiscoverySupport
from fabric_tpu.common.channelconfig import bundle_from_genesis
from fabric_tpu.msp import msp_config_from_ca
from fabric_tpu.policies.signature_policy import (
    n_out_of,
    signed_by,
    signed_by_any_member,
    signed_by_msp_role,
)
from fabric_tpu.protos.common import policies_pb2
from fabric_tpu.protos.msp import msp_principal_pb2

from orgfix import make_org


class TestInquire:
    def test_satisfaction_sets(self):
        # OutOf(2, A, B, C) -> {A,B} {A,C} {B,C}
        env = policies_pb2.SignaturePolicyEnvelope(version=0)
        env.rule.CopyFrom(
            n_out_of(2, [signed_by(0), signed_by(1), signed_by(2)])
        )
        for i in range(3):
            env.identities.add()
        assert satisfaction_sets(env) == [(0, 1), (0, 2), (1, 2)]

    def test_nested(self):
        # AND(A, OR(B, C)) -> {A,B} {A,C}
        env = policies_pb2.SignaturePolicyEnvelope(version=0)
        env.rule.CopyFrom(
            n_out_of(
                2,
                [signed_by(0), n_out_of(1, [signed_by(1), signed_by(2)])],
            )
        )
        for i in range(3):
            env.identities.add()
        assert satisfaction_sets(env) == [(0, 1), (0, 2)]


@pytest.fixture(scope="module")
def world():
    org1 = make_org("Org1MSP")
    org2 = make_org("Org2MSP")
    oorg = make_org("OrdererMSP")
    conf1 = msp_config_from_ca(org1.ca, "Org1MSP")
    conf2 = msp_config_from_ca(org2.ca, "Org2MSP")
    app = ctx.application_group(
        {
            "Org1": ctx.org_group("Org1MSP", conf1),
            "Org2": ctx.org_group("Org2MSP", conf2),
        }
    )
    ordg = ctx.orderer_group(
        {"OrdererOrg": ctx.org_group("OrdererMSP", msp_config_from_ca(oorg.ca, "OrdererMSP"))},
        consensus_type="solo",
    )
    genesis = ctx.genesis_block("dchannel", ctx.channel_group(app, ordg))
    bundle = bundle_from_genesis(genesis, org1.csp)

    p1 = org1.signer("peer0.org1", role_ou="peer")
    p2 = org1.signer("peer1.org1", role_ou="peer")
    p3 = org2.signer("peer0.org2", role_ou="peer")
    peers = [
        PeerInfo("p1:7051", p1.serialize(), "Org1MSP", 10, ("mycc",)),
        PeerInfo("p2:7051", p2.serialize(), "Org1MSP", 12, ("mycc",)),
        PeerInfo("p3:7051", p3.serialize(), "Org2MSP", 11, ("mycc",)),
    ]

    policies = {
        "mycc": signed_by_msp_role(
            "Org1MSP", msp_principal_pb2.MSPRole.MEMBER
        ),  # Org1 only
        "andcc": _and_policy(),
    }

    def collection_filter(channel, cc, colls):
        # collA restricted to Org2
        if "collA" in colls:
            return lambda p: p.mspid == "Org2MSP"
        return lambda p: True

    support = DiscoverySupport(
        channels=lambda: ["dchannel"],
        bundle=lambda ch: bundle,
        peers=lambda ch: peers,
        msp_configs=lambda ch: {
            "Org1MSP": conf1.SerializeToString(),
            "Org2MSP": conf2.SerializeToString(),
        },
        orderer_endpoints=lambda ch: {"OrdererMSP": [("orderer0", 7050)]},
        chaincode_policy=lambda ch, cc: policies.get(cc),
        collection_filter=collection_filter,
        acl_check=lambda ch, sd: None,
    )
    service = DiscoveryService(support, org1.csp)
    client_signer = org1.signer("user1", role_ou="client")
    client = DiscoveryClient(client_signer, service.process)
    return service, client, org1


def _and_policy():
    env = policies_pb2.SignaturePolicyEnvelope(version=0)
    e1 = signed_by_msp_role("Org1MSP", msp_principal_pb2.MSPRole.MEMBER)
    e2 = signed_by_msp_role("Org2MSP", msp_principal_pb2.MSPRole.MEMBER)
    env.identities.extend([e1.identities[0], e2.identities[0]])
    env.rule.CopyFrom(n_out_of(2, [signed_by(0), signed_by(1)]))
    return env


def test_config_query(world):
    _, client, _ = world
    conf = client.config("dchannel")
    assert set(conf.msps) == {"Org1MSP", "Org2MSP"}
    assert conf.orderers["OrdererMSP"].endpoint[0].host == "orderer0"


def test_membership_query(world):
    _, client, _ = world
    peers = client.peers("dchannel")
    assert len(peers) == 3
    assert {p.endpoint for p in peers} == {"p1:7051", "p2:7051", "p3:7051"}


def test_endorsement_descriptor_single_org(world):
    _, client, _ = world
    desc = client.endorsers("dchannel", "mycc")
    assert len(desc.layouts) == 1
    (group, qty), = desc.layouts[0].quantities_by_group.items()
    assert qty == 1
    eps = {p.endpoint for p in desc.endorsers_by_groups[group].peers}
    assert eps == {"p1:7051", "p2:7051"}  # only Org1 peers
    chosen = select_endorsers(desc)
    assert len(chosen) == 1
    assert chosen[0].endpoint == "p2:7051"  # highest ledger height


def test_endorsement_descriptor_and_policy(world):
    _, client, _ = world
    desc = client.endorsers("dchannel", "andcc")
    assert len(desc.layouts) == 1
    assert sorted(desc.layouts[0].quantities_by_group.values()) == [1, 1]
    chosen = select_endorsers(desc)
    assert len(chosen) == 2
    assert {p.endpoint for p in chosen} & {"p1:7051", "p2:7051"}
    assert "p3:7051" in {p.endpoint for p in chosen}


def test_collection_filtering(world):
    _, client, _ = world
    # collA restricts to Org2 peers; mycc's policy needs Org1 -> no layout
    with pytest.raises(RuntimeError, match="no endorsement layout"):
        client.endorsers("dchannel", "mycc", collections=["collA"])


def test_unknown_chaincode(world):
    _, client, _ = world
    with pytest.raises(RuntimeError, match="no endorsement policy"):
        client.endorsers("dchannel", "nope")


def test_unknown_channel_denied(world):
    _, client, _ = world
    from fabric_tpu.protos.discovery import protocol_pb2 as dpb

    q = dpb.Query(channel="nochannel")
    q.config_query.SetInParent()
    with pytest.raises(RuntimeError, match="access denied"):
        client._one(q)


def test_foreign_identity_denied(world):
    service, _, _ = world
    evil = make_org("EvilMSP").signer("mallory", role_ou="client")
    client = DiscoveryClient(evil, service.process)
    with pytest.raises(RuntimeError, match="access denied"):
        client.config("dchannel")
