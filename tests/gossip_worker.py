"""Worker process for the multi-process gossip convergence test
(test_gossip_mp.py): one gossip node over the real TCP transport.

argv: name listen_port bootstrap(-|host:port) have_lo have_hi
      want_blocks want_idents out_json
Adds blocks [have_lo, have_hi] with push DISABLED, then ticks until it
holds want_blocks blocks and want_idents identities — i.e. convergence
happens purely through the pull engines (block pull + state
anti-entropy + certstore identity pull)."""

from __future__ import annotations

import hashlib
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fabric_tpu.gossip import GossipService
from fabric_tpu.gossip.comm import MessageCryptoService, TCPGossipComm
from fabric_tpu.protos.common import common_pb2


class ToyMCS(MessageCryptoService):
    def sign(self, payload: bytes) -> bytes:
        return hashlib.sha256(b"mp-secret" + payload).digest()

    def verify(self, identity: bytes, signature: bytes, payload: bytes) -> bool:
        return signature == hashlib.sha256(b"mp-secret" + payload).digest()


class Committer:
    def __init__(self):
        self.blocks: dict[int, common_pb2.Block] = {}

    @property
    def height(self) -> int:
        return (max(self.blocks) + 1) if self.blocks else 1

    def store_block(self, blk: common_pb2.Block) -> None:
        self.blocks[blk.header.number] = blk

    def get_block_by_number(self, seq: int):
        return self.blocks.get(seq)


def _block(seq: int) -> bytes:
    b = common_pb2.Block()
    b.header.number = seq
    b.data.data.append(b"tx-%d" % seq)
    return b.SerializeToString()


def main(argv) -> int:
    name, port, bootstrap, lo, hi, want_blocks, want_idents, out = argv
    comm = TCPGossipComm(("127.0.0.1", int(port)), name.encode(), mcs=ToyMCS())
    svc = GossipService(
        comm, bootstrap=[] if bootstrap == "-" else [bootstrap]
    )
    committer = Committer()
    handle = svc.join_channel("mpch", committer)
    for seq in range(int(lo), int(hi) + 1):
        handle.gossip.add_block(seq, _block(seq), push=False)

    deadline = time.time() + 60
    converged = False
    grace_until = None  # keep serving pulls so LATER joiners converge too
    while time.time() < deadline:
        svc.tick()
        idents = {i for _, i in svc.identities.known()}
        if (
            len(committer.blocks) >= int(want_blocks)
            and len(idents) >= int(want_idents)
        ):
            converged = True
            if grace_until is None:
                grace_until = time.time() + 12
            elif time.time() >= grace_until:
                break
        time.sleep(0.2)
    with open(out, "w") as f:
        json.dump(
            {
                "blocks": sorted(committer.blocks),
                "identities": sorted(i.decode() for i in idents),
            },
            f,
        )
    comm.close()
    return 0 if converged else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
